"""Tests for image KernelSHAP (superpixel masking) and the CNN predictor."""

import numpy as np
import pytest

from distributedkernelshap_tpu import KernelShap
from distributedkernelshap_tpu.ops.image import _box_blur, image_background, superpixel_groups


def test_superpixel_groups_partition():
    groups, names = superpixel_groups(28, 28, patch=4)
    assert len(groups) == 49 and len(names) == 49
    cols = sorted(c for g in groups for c in g)
    assert cols == list(range(28 * 28))  # exact partition
    assert all(len(g) == 16 for g in groups)


def test_superpixel_groups_ragged_and_channels():
    groups, _ = superpixel_groups(5, 5, patch=2)
    assert len(groups) == 9
    assert sorted(c for g in groups for c in g) == list(range(25))
    groups3, _ = superpixel_groups(4, 4, patch=2, channels=3)
    assert sorted(c for g in groups3 for c in g) == list(range(48))


def test_superpixel_ragged_edge_patch_membership():
    """Ragged edges (patch does not divide H/W): the edge patches are
    exactly the leftover rows/columns, named by their patch grid cell."""

    groups, names = superpixel_groups(5, 5, patch=2)
    by_name = dict(zip(names, groups))
    # interior patch: full 2x2 block, row-major pixel order
    assert by_name["patch_0_0"] == [0, 1, 5, 6]
    # right edge: 2 rows x 1 leftover column (x = 4)
    assert by_name["patch_0_2"] == [4, 9]
    # bottom edge: 1 leftover row (y = 4) x 2 columns
    assert by_name["patch_2_0"] == [20, 21]
    # corner: the single leftover pixel
    assert by_name["patch_2_2"] == [24]
    assert [len(g) for g in groups] == [4, 4, 2, 4, 4, 2, 2, 2, 1]


def test_superpixel_multichannel_column_order_matches_flatten():
    """Multi-channel groups list columns in the SAME (y, x, c) row-major
    interleave that ``images.reshape(n, -1)`` (and ``image_background``)
    produce — each patch owns every channel of its pixels, adjacent in
    memory."""

    groups, names = superpixel_groups(4, 4, patch=2, channels=3)
    by_name = dict(zip(names, groups))
    # pixel (y, x) channel c flattens to (y*4 + x)*3 + c
    assert by_name["patch_0_1"] == [
        (y * 4 + x) * 3 + c
        for y in (0, 1) for x in (2, 3) for c in (0, 1, 2)]
    # cross-check against an actual image: each patch's columns pick out
    # exactly its pixels' channel values from the flattened row
    img = np.arange(4 * 4 * 3, dtype=np.float32).reshape(1, 4, 4, 3)
    img[0, :, :, 1] += 100.0  # make channels distinguishable
    flat = img.reshape(1, -1)
    got = flat[0, by_name["patch_1_0"]].reshape(2, 2, 3)
    np.testing.assert_array_equal(got, img[0, 2:4, 0:2, :])


def test_image_background_modes():
    rng = np.random.default_rng(0)
    imgs = rng.random((10, 8, 8)).astype(np.float32)
    assert image_background(imgs, "mean").shape == (1, 64)
    fill = image_background(imgs, "fill", fill_value=0.5)
    assert np.all(fill == 0.5)
    assert image_background(imgs, "sample", n_rows=3).shape == (3, 64)
    blur = image_background(imgs, "blur", blur_radius=1, n_rows=2)
    assert blur.shape == (2, 64)
    with pytest.raises(ValueError):
        image_background(imgs.reshape(10, -1), "blur")


def test_box_blur_constant_invariant():
    imgs = np.full((1, 6, 6, 1), 3.0, dtype=np.float32)
    np.testing.assert_allclose(_box_blur(imgs, 2), imgs, atol=1e-6)


def test_cnn_train_and_image_explain():
    from distributedkernelshap_tpu.models.cnn import train_mnist_cnn
    from scripts.process_mnist_data import _class_templates, _synthetic_digits

    rng = np.random.default_rng(0)
    templates = _class_templates(rng)
    images, labels = _synthetic_digits(2000, rng, templates)
    pred = train_mnist_cnn(images, labels, epochs=1, batch_size=128)

    test_imgs, test_labels = _synthetic_digits(200, rng, templates)
    acc = float((np.asarray(pred(test_imgs.reshape(200, -1))).argmax(1) == test_labels).mean())
    assert acc > 0.5  # 1 epoch on 2k samples; real training does much better

    groups, names = superpixel_groups(28, 28, patch=7)  # 16 superpixels
    bg = image_background(images, mode="mean")
    ex = KernelShap(pred, link="logit", feature_names=names, seed=0)
    ex.fit(bg, group_names=groups and names, groups=groups)
    explanation = ex.explain(test_imgs[:2].reshape(2, -1), nsamples=200,
                             l1_reg=False, silent=True)
    sv = explanation.shap_values
    assert len(sv) == 10 and sv[0].shape == (2, 16)
    total = np.stack(sv, 1).sum(-1) + np.asarray(explanation.expected_value)[None]
    np.testing.assert_allclose(total, explanation.data["raw"]["raw_prediction"], atol=1e-3)


def test_covertype_schema():
    from scripts.process_covertype_data import covertype_groups, load_covertype

    data = load_covertype(n_rows=5000)
    # cached full file may exist from bench runs; check schema not size
    assert data["X"].shape[1] == 54
    assert len(data["feature_names"]) == 54
    groups, names = covertype_groups()
    assert len(groups) == 12
    assert sorted(c for g in groups for c in g) == list(range(54))


def test_covertype_cache_guard(tmp_path, monkeypatch):
    """Undersized caches: marked-synthetic ones are regenerated in place;
    unmarked ones (possibly a real dataset copy) are never overwritten —
    the requested size is generated in memory only."""

    import pickle

    import scripts.process_covertype_data as cov

    cache = tmp_path / "covertype.pkl"
    monkeypatch.setattr(cov, "COVERTYPE_LOCAL", str(cache))

    # no cache: generates at requested size, writes marked cache
    d = cov.load_covertype(n_rows=300)
    assert d["X"].shape == (300, 54) and d["synthetic"]
    assert pickle.load(open(cache, "rb"))["X"].shape[0] == 300

    # marked cache smaller than requested: regenerated and rewritten
    d = cov.load_covertype(n_rows=500)
    assert d["X"].shape[0] == 500
    assert pickle.load(open(cache, "rb"))["X"].shape[0] == 500

    # larger cache sliced (and copied, not a view of the cached array)
    d = cov.load_covertype(n_rows=200)
    assert d["X"].shape[0] == 200 and d["X"].base is None

    # unmarked cache (real copy): file untouched, full size served in memory
    with open(cache, "wb") as f:
        unmarked = {"X": d["X"][:100], "y": d["y"][:100],
                    "feature_names": d["feature_names"]}
        pickle.dump(unmarked, f)
    d = cov.load_covertype(n_rows=400)
    assert d["X"].shape[0] == 400
    assert pickle.load(open(cache, "rb"))["X"].shape[0] == 100


def test_image_explain_chunked_matches_unchunked():
    """The MNIST benchmark config explains through instance_chunk + the
    shared dispatch pipeline (round 3); chunked and unchunked image
    explains must agree exactly."""

    from distributedkernelshap_tpu.kernel_shap import EngineConfig
    from distributedkernelshap_tpu.models.cnn import train_mnist_cnn
    from scripts.process_mnist_data import _class_templates, _synthetic_digits

    rng = np.random.default_rng(1)
    templates = _class_templates(rng)
    images, labels = _synthetic_digits(1200, rng, templates)
    pred = train_mnist_cnn(images, labels, epochs=1, batch_size=128)
    groups, names = superpixel_groups(28, 28, patch=7)  # 16 superpixels
    bg = image_background(images, mode="mean")
    X = _synthetic_digits(10, rng, templates)[0].reshape(10, -1)

    base = KernelShap(pred, link="logit", feature_names=names, seed=0)
    base.fit(bg, group_names=names, groups=groups)
    ref = base.explain(X, nsamples=128, l1_reg=False, silent=True)

    chunked = KernelShap(pred, link="logit", feature_names=names, seed=0,
                         engine_config=EngineConfig(instance_chunk=4,
                                                    dispatch_window=2))
    chunked.fit(bg, group_names=names, groups=groups)
    got = chunked.explain(X, nsamples=128, l1_reg=False, silent=True)
    for a, b in zip(ref.shap_values, got.shap_values):
        np.testing.assert_allclose(a, b, atol=1e-5)
