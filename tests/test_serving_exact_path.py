"""Exact TreeSHAP on the serving hot path (ISSUE 7 serving promotion):
auto-selection for lifted tree predictors, staged-rows + donated-entry
integration, warmup-ladder coverage and per-request path attribution.
"""

import json
import time

import numpy as np
import pytest


@pytest.fixture(scope="module")
def tree_setup():
    from sklearn.ensemble import HistGradientBoostingRegressor

    rng = np.random.default_rng(8)
    X = rng.normal(size=(200, 5)).astype(np.float64)
    y = X[:, 0] - np.where(X[:, 2] > 0, 1.0, -1.0) * X[:, 3]
    gbr = HistGradientBoostingRegressor(max_iter=8, random_state=0).fit(X, y)
    return dict(gbr=gbr, bg=X[:15].astype(np.float32),
                Xe=X[100:106].astype(np.float32))


@pytest.fixture(scope="module")
def linear_setup():
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(9)
    X = rng.normal(size=(120, 5)).astype(np.float64)
    y = (X[:, 0] > 0).astype(int)
    clf = LogisticRegression(max_iter=200).fit(X, y)
    return dict(clf=clf, bg=X[:10].astype(np.float32),
                Xe=X[50:54].astype(np.float32))


def test_auto_selects_exact_for_lifted_tree_regressor(tree_setup):
    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.serving.wrappers import KernelShapModel

    s = tree_setup
    model = KernelShapModel(s["gbr"].predict, s["bg"], {"seed": 0}, {})
    assert model.explain_path == "exact"
    assert model.explain_path_reason == "auto"
    assert model.explain_kwargs == {"nsamples": "exact"}
    # responses match a direct exact explain bit-for-bit (the served
    # engine runs the same packed/dense exact program)
    payloads = model.explain_batch(s["Xe"], split_sizes=[3, 3])
    direct = KernelShap(s["gbr"].predict, seed=0)
    direct.fit(s["bg"])
    want = np.asarray(direct.explain(s["Xe"], silent=True,
                                     nsamples="exact").shap_values)
    want = want[0] if want.ndim == 3 else want
    got = np.asarray(json.loads(payloads[0])["data"]["shap_values"])
    np.testing.assert_array_equal(np.squeeze(got), want[:3])


def test_auto_selection_opt_outs(tree_setup, linear_setup, monkeypatch):
    from distributedkernelshap_tpu.serving.wrappers import KernelShapModel

    s = tree_setup
    # pinned nsamples always wins — including None as an explicit opt-out
    pinned = KernelShapModel(s["gbr"].predict, s["bg"], {"seed": 0}, {},
                             explain_kwargs={"nsamples": 100})
    assert pinned.explain_path == "sampled"
    assert pinned.explain_path_reason == "pinned"
    opted = KernelShapModel(s["gbr"].predict, s["bg"], {"seed": 0}, {},
                            explain_kwargs={"nsamples": None})
    assert opted.explain_path == "sampled"
    # env kill switch
    monkeypatch.setenv("DKS_EXACT_AUTO", "0")
    off = KernelShapModel(s["gbr"].predict, s["bg"], {"seed": 0}, {})
    assert off.explain_path == "sampled"
    assert off.explain_path_reason == "auto_disabled"
    assert "nsamples" not in off.explain_kwargs
    monkeypatch.delenv("DKS_EXACT_AUTO")
    # non-tree predictors keep the sampled path AND their staging
    li = linear_setup
    lin = KernelShapModel(li["clf"], li["bg"],
                          {"link": "logit", "seed": 0}, {},
                          explain_kwargs={"l1_reg": False})
    assert lin.explain_path == "sampled"
    assert lin.stage_rows(li["Xe"]) is not None


def test_exact_staged_async_matches_sync_payloads(tree_setup):
    from distributedkernelshap_tpu.kernel_shap import StagedRows
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    s = tree_setup
    model = BatchKernelShapModel(s["gbr"].predict, s["bg"], {"seed": 0}, {})
    staged = model.stage_rows(s["Xe"])
    assert isinstance(staged, StagedRows)
    sync = model.explain_batch(s["Xe"], split_sizes=[2, 2, 2])
    got = model.explain_batch_async(staged, split_sizes=[2, 2, 2])()
    assert got == sync
    # binary wire slots work on the exact path too
    staged2 = model.stage_rows(s["Xe"])
    binary = model.explain_batch_async(
        staged2, split_sizes=[2, 2, 2],
        formats=["binary", "json", "binary"])()
    assert isinstance(binary[0], (bytes, bytearray))
    assert binary[1] == sync[1]


def test_explain_path_metric_counts(tree_setup):
    from distributedkernelshap_tpu.serving import wrappers

    s = tree_setup
    model = wrappers.BatchKernelShapModel(s["gbr"].predict, s["bg"],
                                          {"seed": 0}, {})
    before = wrappers.explain_path_counts().get(("exact",), 0.0)
    model.explain_batch(s["Xe"], split_sizes=[3, 3])
    after = wrappers.explain_path_counts()[("exact",)]
    assert after == before + 2  # one per request slot, not per row


def test_warmup_ladder_covers_exact_path(tree_setup):
    """A warmup-enabled server over an auto-exact deployment compiles the
    exact entry per bucket (signatures carry the path), serves requests
    warm, and renders the path/fallback metrics."""

    from distributedkernelshap_tpu.runtime.compile_cache import (
        compile_events,
    )
    from distributedkernelshap_tpu.serving.server import ExplainerServer
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    s = tree_setup
    model = BatchKernelShapModel(s["gbr"].predict, s["bg"], {"seed": 0}, {})
    assert model.explain_path == "exact"
    ce = compile_events()
    before = ce.snapshot()
    srv = ExplainerServer(model, host="127.0.0.1", port=0,
                          max_batch_size=4, warmup=True,
                          health_interval_s=0).start()
    try:
        deadline = time.monotonic() + 60
        while srv.warmup_status()["state"] in ("pending", "running"):
            assert time.monotonic() < deadline, "warmup never finished"
            time.sleep(0.05)
        st = srv.warmup_status()
        assert st["state"] == "done"
        assert st["completed_buckets"] == st["buckets"] != []
        # the ladder's compile signatures name the exact path — the
        # accounting can attribute each rung to the executable it warmed
        delta = ce.delta(before, ce.snapshot())
        sigs = {sig for (_, sig) in delta["counts"]}
        assert any(sig.endswith(",path=exact") for sig in sigs), sigs
        # the metrics page carries the path attribution + fallback series
        page = srv.metrics.render()
        assert 'dks_serve_explain_path_total{path="exact"}' in page
        assert "dks_treeshap_fallback_total" in page
    finally:
        srv.stop()
