"""Known-good twin: one global acquisition order (a before b)."""

import threading


class TwoLocks:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.x = 0

    def ab(self):
        with self._a:
            with self._b:
                self.x += 1

    def ab2(self):
        with self._a:
            with self._b:
                self.x -= 1
