"""Known-bad: DKS-C001 — bare counter bumped from the worker thread,
read by a panel method, no common lock."""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.ticks = 0

    def _loop(self):
        while not self._stop.wait(0.1):
            try:
                self.ticks += 1
            except Exception:
                pass

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def panel(self):
        return {"ticks": self.ticks}
