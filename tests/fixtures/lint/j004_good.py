"""Known-good twin: hashable tuple default."""

import jax


def fn(x, sizes=(1, 2, 3)):
    return x


entry = jax.jit(fn, static_argnums=(1,))
