"""Known-good twin: the blocking get happens outside the lock (and the
in-lock variant is bounded by a timeout)."""

import queue
import threading


class Consumer:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self.seen = 0

    def take(self):
        item = self._q.get(timeout=1.0)
        with self._lock:
            self.seen += 1
        return item
