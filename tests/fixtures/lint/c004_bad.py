"""Known-bad: DKS-C004 — untimed queue.get() while holding the lock."""

import queue
import threading


class Consumer:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self.seen = 0

    def take(self):
        with self._lock:
            item = self._q.get()
            self.seen += 1
        return item
