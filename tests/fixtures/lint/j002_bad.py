"""Known-bad: DKS-J002 — a cached consts buffer fed to the donated
argnum of a known donated entry."""


class Engine:
    def _exact_fn(self, consts):
        raise NotImplementedError

    def _exact_consts(self):
        raise NotImplementedError

    def dispatch(self, Xp):
        consts = self._exact_consts()
        fn = self._exact_fn(consts)
        return fn(consts["reach"], Xp)

    def dispatch_shadowed(self, Xp, key):
        # the cache read reaches the donated call even though a per-call
        # upload shadows the name afterwards — a last-assignment-wins
        # (flow-insensitive) model misses this one
        fn = self._exact_fn(self._exact_consts())
        batch = self._dev_cache[key]
        out = fn(batch)
        batch = upload(Xp)
        return out


def upload(x):
    raise NotImplementedError
