"""Known-bad: DKS-C002 — dict iterated outside the lock while another
method mutates it in place."""

import threading


class Draining:
    def __init__(self):
        self._lock = threading.Lock()
        self._draining = {}

    def add(self, index):
        with self._lock:
            self._draining[index] = {"since": 0.0}

    def poll(self):
        ages = []
        for index in list(self._draining):
            ages.append(index)
        return ages
