"""Known-bad: DKS-J001 — a donate_argnums site off the audited list."""

import jax


def make_entry(fn):
    return jax.jit(fn, donate_argnums=(0,))
