"""Mini engine: one dispatch entry + fingerprint-keyed consts builder
per structured path."""


class Engine:
    _DEV_CACHE_MAX_ENTRIES = 8

    def content_fingerprint(self):
        return "fp"

    def _plan_consts(self, plan, chunk):
        key = (self.content_fingerprint(), chunk)
        if key in self._plan_consts_cache:
            return self._plan_consts_cache[key]
        consts = {"plan": plan}
        self._plan_consts_cache[key] = consts
        return consts

    def _exact_consts(self):
        key = ("exact_consts", self.content_fingerprint())
        if key in self._plan_consts_cache:
            return self._plan_consts_cache[key]
        consts = {"reach": None}
        self._plan_consts_cache[key] = consts
        return consts

    def _dispatch_array(self, X, plan):
        consts = self._plan_consts(plan, 1)
        return consts

    def _dispatch_exact(self, X):
        consts = self._exact_consts()
        return consts
