"""Mini exact-tree ops: the fallback counter family."""


def attach_fallback_metrics(registry):
    registry.counter("dks_treeshap_fallback_total",
                     "Exact-path fallbacks by reason.",
                     labelnames=("reason",))
