"""Mini classifier: the path universe the ladder lint checks."""

ENGINE_PATHS = ("linear", "exact_tree", "sampled")
