"""Mini serving wrappers: the path-label site."""

_path_counts = {"exact": 0.0, "sampled": 0.0}


def record_explain_path(path, n=1):
    _path_counts[path] = _path_counts.get(path, 0.0) + n


class Model:
    def resolve(self, decision):
        self.explain_path = "sampled"
        if decision == "exact_tree":
            self.explain_path = "exact"
