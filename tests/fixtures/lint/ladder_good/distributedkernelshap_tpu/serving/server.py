"""Mini server: the warmup rung passes the model's explain_path."""

from distributedkernelshap_tpu.runtime.compile_cache import shape_signature


def warm_rung(model, b):
    return shape_signature(b, getattr(model, "explain_path", None))
