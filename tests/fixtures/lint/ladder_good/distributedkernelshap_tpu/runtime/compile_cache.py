"""Mini compile cache: the one signature spelling."""


def shape_signature(rows, path=None):
    sig = f"rows={int(rows)}"
    if path:
        sig = f"{sig},path={path}"
    return sig
