"""Known-good twin: jit without donation needs no audit entry."""

import jax


def make_entry(fn):
    return jax.jit(fn)
