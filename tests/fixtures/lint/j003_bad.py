"""Known-bad: DKS-J003 — host RNG, clock and np-on-traced-arg inside a
jitted function."""

import time

import numpy as np

from distributedkernelshap_tpu.ops.explain import jit_batch_entry


def build(pred):
    def fn(Xp, consts):
        noise = np.random.normal(size=3)
        t0 = time.time()
        mean = np.mean(Xp)
        return pred(Xp) + noise[0] + t0 + mean

    return jit_batch_entry(fn, donate_argnums=(0,))
