"""Known-bad: DKS-C005 — thread loop body with no exception guard."""

import threading


class Sampler:
    def __init__(self):
        self._stop = threading.Event()

    def _loop(self):
        while not self._stop.wait(1.0):
            self.sample_once()

    def sample_once(self):
        pass

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()
