"""Known-good twin: jnp math only; constants computed outside."""

import jax.numpy as jnp

from distributedkernelshap_tpu.ops.explain import jit_batch_entry


def build(pred, noise0, t0):
    def fn(Xp, consts):
        mean = jnp.mean(Xp)
        return pred(Xp) + noise0 + t0 + mean

    return jit_batch_entry(fn, donate_argnums=(0,))
