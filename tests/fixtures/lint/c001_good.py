"""Known-good twin: the counter is mutated and read under the lock."""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.ticks = 0

    def _loop(self):
        while not self._stop.wait(0.1):
            try:
                with self._lock:
                    self.ticks += 1
            except Exception:
                pass

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def panel(self):
        with self._lock:
            return {"ticks": self.ticks}
