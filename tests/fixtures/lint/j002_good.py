"""Known-good twin: only the per-call batch buffer is donated."""


class Engine:
    def _exact_fn(self, consts):
        raise NotImplementedError

    def _exact_consts(self):
        raise NotImplementedError

    def dispatch(self, Xp):
        consts = self._exact_consts()
        fn = self._exact_fn(consts)
        return fn(Xp, consts["reach"])

    def dispatch_name_reuse(self, Xp, key):
        # a cache read assigned AFTER the donated call reuses the name:
        # flow-sensitive J002 must judge the call against the per-call
        # upload that actually reaches it, not the later assignment
        fn = self._exact_fn(self._exact_consts())
        batch = upload(Xp)
        out = fn(batch)
        batch = self._dev_cache[key]
        return out, batch


def upload(x):
    raise NotImplementedError
