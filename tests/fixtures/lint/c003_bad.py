"""Known-bad: DKS-C003 — two locks acquired in both orders."""

import threading


class TwoLocks:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.x = 0

    def ab(self):
        with self._a:
            with self._b:
                self.x += 1

    def ba(self):
        with self._b:
            with self._a:
                self.x -= 1
