"""Known-good twin: the loop body survives a bad tick."""

import logging
import threading

logger = logging.getLogger(__name__)


class Sampler:
    def __init__(self):
        self._stop = threading.Event()

    def _loop(self):
        while not self._stop.wait(1.0):
            try:
                self.sample_once()
            except Exception:
                logger.exception("tick failed")

    def sample_once(self):
        pass

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()
