"""Known-good twin: the iteration snapshots under the lock."""

import threading


class Draining:
    def __init__(self):
        self._lock = threading.Lock()
        self._draining = {}

    def add(self, index):
        with self._lock:
            self._draining[index] = {"since": 0.0}

    def poll(self):
        with self._lock:
            pending = list(self._draining)
        ages = []
        for index in pending:
            ages.append(index)
        return ages
