"""Elastic SLO-driven autoscaling (``serving/autoscaler.py``): decision
logic against a real ``FanInProxy`` (fake targets, patched signals),
the replica lifecycle states and drain semantics, the admission
estimator's ``capacity_hint``, supervisor retirement, the ``scaler.tick``
chaos site, and one full in-process spawn→warm→admit→drain→retire cycle
against real ``ExplainerServer`` replicas."""

import time

import numpy as np
import pytest

from distributedkernelshap_tpu.resilience.faults import (
    FaultInjector,
    parse_faults,
)
from distributedkernelshap_tpu.resilience.supervisor import ReplicaSupervisor
from distributedkernelshap_tpu.scheduling.admission import (
    AdmissionController,
    ServiceRateEstimator,
)
from distributedkernelshap_tpu.serving.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    _ScalerCrashed,
)
from distributedkernelshap_tpu.serving.replicas import FanInProxy


# --------------------------------------------------------------------- #
# capacity_hint (scheduling/admission.py satellite)
# --------------------------------------------------------------------- #


def test_capacity_hint_rescales_the_ewma():
    est = ServiceRateEstimator()
    est.observe(100, 1.0)
    est.capacity_hint(2)          # first call: baseline only
    assert est.rows_per_s() == pytest.approx(100.0)
    est.capacity_hint(4)          # capacity doubled: rate doubles NOW
    assert est.rows_per_s() == pytest.approx(200.0)
    est.capacity_hint(1)          # drained to a quarter
    assert est.rows_per_s() == pytest.approx(50.0)


def test_capacity_hint_without_observation_is_baseline_only():
    est = ServiceRateEstimator()
    est.capacity_hint(2)
    est.capacity_hint(8)          # no observations yet: nothing to scale
    assert est.rows_per_s() is None
    est.observe(40, 1.0)          # later observations land unscaled
    assert est.rows_per_s() == pytest.approx(40.0)


def test_capacity_hint_rejects_nonpositive():
    with pytest.raises(ValueError):
        ServiceRateEstimator().capacity_hint(0)


def test_admission_controller_delegates_capacity_hint():
    est = ServiceRateEstimator()
    est.observe(10, 1.0)
    ctl = AdmissionController(estimator=est)
    ctl.capacity_hint(1)
    ctl.capacity_hint(3)
    assert est.rows_per_s() == pytest.approx(30.0)
    # and a controller without an estimator shrugs it off
    AdmissionController(estimator=None).capacity_hint(5)


# --------------------------------------------------------------------- #
# replica lifecycle states on the proxy
# --------------------------------------------------------------------- #


def _proxy(n=1, **kwargs):
    kwargs.setdefault("probe_interval_s", 3600)
    kwargs.setdefault("health_interval_s", 0)
    return FanInProxy([("127.0.0.1", 1 + i) for i in range(n)], **kwargs)


def test_add_target_starts_warming_and_unroutable():
    proxy = _proxy(1)
    index = proxy.add_target("127.0.0.1", 99)
    r = proxy.replicas[index]
    assert r.state() == "warming" and not r.routable()
    assert proxy.replica_state_counts()["warming"] == 1
    # only the prober may declare it live; _pick must never return it
    assert proxy._pick(set()).index == 0
    assert proxy._pick({0}) is None


def test_draining_replica_is_unroutable_but_alive():
    proxy = _proxy(2)
    proxy.start_drain(0)
    r = proxy.replicas[0]
    assert r.alive and r.draining and not r.routable()
    assert r.state() == "draining"
    # every pick lands on the survivor
    for _ in range(4):
        assert proxy._pick(set()).index == 1
    proxy.finish_drain(0)
    assert r.retired and not r.alive and r.state() == "retired"
    assert proxy.replica_state_counts()["retired"] == 1


def test_standby_held_out_until_activation():
    proxy = _proxy(1)
    index = proxy.add_target("127.0.0.1", 99, standby=True)
    r = proxy.replicas[index]
    assert r.state() in ("standby",) and not r.routable()
    # not yet probed ready: activation clears the flag but cannot admit
    assert proxy.activate_standby(index) is False
    r.standby = True              # back in the pool for the real case
    r.warm_ready = True           # the prober's 200 verdict
    r.warming = False
    assert proxy.activate_standby(index) is True
    assert r.routable() and r.state() == "ready"


# --------------------------------------------------------------------- #
# supervisor retirement (resilience/supervisor.py satellite)
# --------------------------------------------------------------------- #


class _FakeProc:
    def __init__(self, returncode=None):
        self.returncode = returncode

    def poll(self):
        return self.returncode


def test_supervisor_never_respawns_a_retired_replica():
    from distributedkernelshap_tpu.resilience.supervisor import (
        RestartPolicy,
    )

    spawned = []
    procs = [_FakeProc(returncode=0)]  # already exited
    sup = ReplicaSupervisor(
        procs, lambda i: spawned.append(i) or _FakeProc(),
        policy=RestartPolicy(base_backoff_s=0.001, max_backoff_s=0.001),
        poll_interval_s=3600)
    sup.retire(0)
    for _ in range(3):
        sup._tick()
        time.sleep(0.01)
    assert spawned == []          # the exit was the goal
    assert sup.is_retired(0)
    assert sup.stats()["retired"] == 1
    # track() reuses the slot for a scaler-spawned replacement
    sup.track(0)
    assert not sup.is_retired(0)
    sup._tick()                   # schedules the respawn (backoff)...
    time.sleep(0.05)
    sup._tick()                   # ...and performs it
    assert spawned == [0]         # supervision resumed


# --------------------------------------------------------------------- #
# scaler.tick fault site (resilience/faults.py satellite)
# --------------------------------------------------------------------- #


def test_fire_crash_thread_scope_returns_instead_of_exiting():
    injector = FaultInjector(parse_faults("crash:site=scaler.tick"))
    # process scope would os._exit(42) and kill the test runner; thread
    # scope must RETURN the kind so the caller's loop can die alone
    assert injector.fire("scaler.tick", crash_scope="thread") == "crash"


def test_scaler_tick_crash_kills_only_the_loop():
    proxy = _proxy(1)
    scaler = Autoscaler(_FakeFleet(proxy), proxy,
                        config=AutoscalerConfig(max_replicas=2),
                        fault_injector=FaultInjector(
                            parse_faults("crash:site=scaler.tick")))
    with pytest.raises(_ScalerCrashed):
        scaler.tick()
    # the fleet is untouched: still one ready replica, nothing draining
    counts = proxy.replica_state_counts()
    assert counts["ready"] == 1 and counts["draining"] == 0


# --------------------------------------------------------------------- #
# decision logic (real proxy, fake fleet, patched signals)
# --------------------------------------------------------------------- #


class _FakeFleet:
    def __init__(self, proxy):
        self.proxy = proxy
        self.spawned = []
        self.retired = []

    def spawn_replica(self, standby=False):
        index = self.proxy.add_target("127.0.0.1",
                                      90 + len(self.proxy.replicas),
                                      standby=standby)
        self.spawned.append((index, standby))
        return index

    def retire_replica(self, index):
        self.retired.append(index)
        self.proxy.finish_drain(index)


_IDLE_DETAIL = {"queue_depths": {}, "in_flight_batches": 0,
                "service_rate_rows_per_s": 10.0,
                "rows_served_total": 0,
                "projected_wait_s": {"interactive": 0.0}}


def _scaler(proxy, **cfg_kwargs):
    cfg_kwargs.setdefault("min_replicas", 1)
    cfg_kwargs.setdefault("max_replicas", 4)
    cfg_kwargs.setdefault("up_ticks", 1)
    cfg_kwargs.setdefault("interval_s", 0.05)
    fleet = _FakeFleet(proxy)
    scaler = Autoscaler(fleet, proxy,
                        config=AutoscalerConfig(**cfg_kwargs))
    scaler._replica_detail = lambda r: dict(_IDLE_DETAIL)
    return scaler, fleet


def _feed_rate(proxy, slope_recent, slope_old=None, span_s=12.0):
    """Write a dks_fanin_forwarded_total counter history into the
    proxy's health store: ``slope_old`` req/s until 2 s ago, then
    ``slope_recent`` req/s (defaults to a flat rate)."""

    store = proxy.health.store
    slope_old = slope_recent if slope_old is None else slope_old
    now = time.time()
    value = 0.0
    t = now - span_s
    while t <= now:
        value += (slope_recent if t > now - 2.0 else slope_old) * 0.5
        store.add("dks_fanin_forwarded_total", t, value, kind="counter")
        t += 0.5


def test_scale_up_on_breached_slo():
    proxy = _proxy(1)
    scaler, fleet = _scaler(proxy)
    proxy.health.slo_statuses = lambda now=None: [
        {"name": "interactive_latency", "breached": True}]
    sig = scaler.tick()
    assert sig["breached_slos"] == ["interactive_latency"]
    assert [s for s, standby in fleet.spawned if not standby]
    assert proxy.replica_state_counts()["warming"] == 1


def test_scale_up_on_queue_wait_projection():
    proxy = _proxy(1)
    scaler, fleet = _scaler(proxy)
    scaler.estimator.observe(10, 1.0)  # fleet serves ~10 rows/s
    busy = dict(_IDLE_DETAIL, queue_depths={"interactive": 20})
    scaler._replica_detail = lambda r: dict(busy)
    scaler.tick()                      # projected wait 20/10 = 2 s
    assert fleet.spawned


def test_predictive_prewarm_on_rate_trend():
    proxy = _proxy(1)
    scaler, fleet = _scaler(proxy, trend_factor=1.5,
                            trend_window_short_s=2.0,
                            trend_window_long_s=10.0,
                            trend_min_utilization=0.4)
    scaler.estimator.observe(12, 1.0)
    _feed_rate(proxy, slope_recent=10.0, slope_old=1.0)
    # rows_served_total must actually move: utilization is served ROWS
    # over rows/s capacity, so a ramp in request counts alone (cache
    # hits, errors) cannot pre-warm.  First tick primes the demand
    # differentiator, second sees the rising counter and fires.
    rows = {"n": 0.0}

    def _busy_detail(r):
        rows["n"] += 5.0
        return dict(_IDLE_DETAIL, rows_served_total=rows["n"])

    scaler._replica_detail = _busy_detail
    scaler.tick()
    sig = scaler.tick()
    assert sig["rate_short_rps"] > 1.5 * sig["rate_long_rps"]
    assert sig["utilization"] is not None and sig["utilization"] >= 0.4
    assert fleet.spawned
    # and the decision is attributed to the trend signal
    decisions = proxy.metrics.get("dks_autoscale_decisions_total")
    assert decisions.value(action="scale_up", reason="rate_trend") == 1


def test_flat_traffic_never_triggers_the_trend():
    proxy = _proxy(1)
    scaler, fleet = _scaler(proxy)
    scaler.estimator.observe(12, 1.0)
    _feed_rate(proxy, slope_recent=5.0)
    scaler.tick()
    assert not fleet.spawned


def test_scale_up_holds_at_max_replicas():
    proxy = _proxy(1)
    scaler, fleet = _scaler(proxy, max_replicas=1)
    proxy.health.slo_statuses = lambda now=None: [
        {"name": "x", "breached": True}]
    scaler.tick()
    assert not fleet.spawned
    decisions = proxy.metrics.get("dks_autoscale_decisions_total")
    assert decisions.value(action="hold", reason="max_replicas") == 1


def test_crashed_replica_counts_against_max():
    """A "down" replica is about to be respawned by the supervisor — the
    scaler must not spawn a replacement the restart then overshoots."""

    proxy = _proxy(2)
    scaler, fleet = _scaler(proxy, max_replicas=2)
    dead = proxy.replicas[1]
    dead.alive, dead.warming = False, False   # crashed, not warming
    assert dead.state() == "down"
    proxy.health.slo_statuses = lambda now=None: [
        {"name": "x", "breached": True}]
    scaler.tick()
    assert not fleet.spawned
    decisions = proxy.metrics.get("dks_autoscale_decisions_total")
    assert decisions.value(action="hold", reason="max_replicas") == 1


def test_up_cooldown_blocks_back_to_back_spawns():
    proxy = _proxy(1)
    scaler, fleet = _scaler(proxy, max_replicas=4, up_cooldown_s=60.0)
    proxy.health.slo_statuses = lambda now=None: [
        {"name": "x", "breached": True}]
    scaler.tick()
    assert len(fleet.spawned) == 1
    scaler.tick()                      # still breached, but cooling down
    assert len(fleet.spawned) == 1
    decisions = proxy.metrics.get("dks_autoscale_decisions_total")
    assert decisions.value(action="hold", reason="cooldown") >= 1


def test_hysteresis_requires_consecutive_up_ticks():
    proxy = _proxy(1)
    scaler, fleet = _scaler(proxy, up_ticks=3)
    proxy.health.slo_statuses = lambda now=None: [
        {"name": "x", "breached": True}]
    scaler.tick()
    scaler.tick()
    assert not fleet.spawned           # 2 of 3
    scaler.tick()
    assert fleet.spawned


def test_scale_down_drains_then_retires():
    proxy = _proxy(2)
    scaler, fleet = _scaler(proxy, down_ticks=2, down_cooldown_s=0.0,
                            drain_settle_polls=2)
    _feed_rate(proxy, slope_recent=1.0)    # flat traffic, ~20 capacity
    scaler.tick()                          # primes the demand snapshot
    scaler.tick()                          # demand 0 rows/s: streak 1
    assert not proxy.replicas[1].draining
    scaler.tick()                          # streak 2: drain starts
    assert proxy.replicas[1].draining      # LIFO victim
    assert fleet.retired == []
    scaler.tick()                          # idle poll 1
    scaler.tick()                          # idle poll 2: retire
    assert fleet.retired == [1]
    assert proxy.replicas[1].retired
    # the survivor is at min_replicas: no further drain ever
    for _ in range(6):
        scaler.tick()
    assert proxy.replica_state_counts()["ready"] == 1


def test_scale_down_holds_at_min_replicas():
    proxy = _proxy(1)
    scaler, fleet = _scaler(proxy, down_ticks=1, down_cooldown_s=0.0)
    _feed_rate(proxy, slope_recent=0.5)
    scaler.estimator.observe(10, 1.0)
    for _ in range(4):
        scaler.tick()
    assert not proxy.replicas[0].draining and not fleet.retired


def test_scale_down_held_while_warming():
    proxy = _proxy(2)
    scaler, fleet = _scaler(proxy, down_ticks=1, down_cooldown_s=0.0)
    proxy.add_target("127.0.0.1", 99)      # a warming scale-up in flight
    _feed_rate(proxy, slope_recent=0.5)
    scaler.estimator.observe(20, 1.0)
    scaler.tick()
    assert not any(r.draining for r in proxy.replicas)


def test_queue_pressure_blocks_scale_down():
    proxy = _proxy(2)
    scaler, fleet = _scaler(proxy, down_ticks=1, down_cooldown_s=0.0)
    _feed_rate(proxy, slope_recent=0.5)
    busy = dict(_IDLE_DETAIL, queue_depths={"batch": 3})
    scaler._replica_detail = lambda r: dict(busy)
    scaler.tick()
    assert not any(r.draining for r in proxy.replicas)


def test_drain_tolerates_transient_statusz_misses():
    """One failed /statusz poll on a draining victim must NOT force the
    SIGTERM — only a replica dark for 3 consecutive polls (crashed
    mid-drain) is forced early; drain_timeout_s backstops the rest."""

    proxy = _proxy(2)
    scaler, fleet = _scaler(proxy, down_ticks=1, down_cooldown_s=0.0,
                            drain_settle_polls=2, drain_timeout_s=3600)
    scaler._scale_down(time.monotonic())
    assert proxy.replicas[1].draining
    answers = iter([None, dict(_IDLE_DETAIL), None, None, None])
    scaler._replica_detail = lambda r: next(answers)
    scaler._poll_draining(time.monotonic())   # miss 1: keep draining
    assert not proxy.replicas[1].retired and fleet.retired == []
    scaler._poll_draining(time.monotonic())   # reachable: miss reset
    scaler._poll_draining(time.monotonic())   # miss 1
    scaler._poll_draining(time.monotonic())   # miss 2
    assert not proxy.replicas[1].retired
    scaler._poll_draining(time.monotonic())   # miss 3: forced
    assert proxy.replicas[1].retired and fleet.retired == [1]


def test_retired_slot_is_reused_by_add_target():
    """Scale cycles must not grow the roster forever: a retired slot's
    index is recycled for the next dynamically added address."""

    proxy = _proxy(2)
    proxy.start_drain(1)
    proxy.finish_drain(1)
    assert proxy.replicas[1].retired
    index = proxy.add_target("127.0.0.1", 777)
    assert index == 1                      # recycled, not appended
    assert len(proxy.replicas) == 2
    r = proxy.replicas[1]
    assert r.port == 777 and not r.retired and r.state() == "warming"
    # pinning a non-retired slot is refused
    with pytest.raises(ValueError):
        proxy.add_target("127.0.0.1", 778, index=0)


def test_warm_standby_pool_fills_and_activates_first():
    proxy = _proxy(1)
    fleet = _FakeFleet(proxy)
    scaler = Autoscaler(fleet, proxy, config=AutoscalerConfig(
        min_replicas=1, max_replicas=3, warm_standby=1, up_ticks=1,
        interval_s=0.05))
    scaler._replica_detail = lambda r: dict(_IDLE_DETAIL)
    scaler._replenish_standby()
    assert fleet.spawned == [(1, True)]
    # the prober declares it warm; a scale-up then ACTIVATES instead of
    # spawning serving capacity (the replenish spawn is a standby again)
    standby = proxy.replicas[1]
    standby.warm_ready, standby.warming = True, False
    proxy.health.slo_statuses = lambda now=None: [
        {"name": "x", "breached": True}]
    scaler.tick()
    assert standby.routable() and standby.state() == "ready"
    assert [s for _, s in fleet.spawned] == [True, True]


def test_capacity_hint_applied_when_capacity_actually_moves():
    proxy = _proxy(1)
    scaler, fleet = _scaler(proxy)
    scaler.estimator.observe(10, 1.0)
    scaler.capacity_hint(1)
    proxy.health.slo_statuses = lambda now=None: [
        {"name": "x", "breached": True}]
    scaler.tick()                          # spawn: replica 1 warming
    # a warming replica serves nothing — the projection must NOT be
    # credited before the prober admits it
    assert scaler.estimator.rows_per_s() == pytest.approx(10.0)
    # prober admits it: ready 1 -> 2; the next gather reconciles the
    # hint BEFORE folding in the new capacity observation
    added = proxy.replicas[1]
    added.alive, added.warming = True, False
    proxy.health.slo_statuses = lambda now=None: []
    scaler.tick()
    assert scaler.estimator.rows_per_s() == pytest.approx(20.0)


def test_capacity_hint_on_standby_activation_is_immediate():
    proxy = _proxy(1)
    fleet = _FakeFleet(proxy)
    scaler = Autoscaler(fleet, proxy, config=AutoscalerConfig(
        min_replicas=1, max_replicas=3, warm_standby=1, up_ticks=1,
        interval_s=0.05))
    scaler._replica_detail = lambda r: dict(_IDLE_DETAIL)
    scaler.estimator.observe(10, 1.0)
    scaler.capacity_hint(1)
    scaler._replenish_standby()
    standby = proxy.replicas[1]
    standby.warm_ready, standby.warming = True, False
    proxy.health.slo_statuses = lambda now=None: [
        {"name": "x", "breached": True}]
    scaler.tick()                          # activates: ready 1 -> 2 NOW
    assert standby.state() == "ready"
    assert scaler.estimator.rows_per_s() == pytest.approx(20.0)


def test_statusz_panel_shape():
    proxy = _proxy(1)
    scaler, _ = _scaler(proxy)
    panel = proxy._statusz_detail()["autoscaler"]
    assert panel["bounds"] == [1, 4]
    assert {"states", "last_decision", "signals", "ticks_total",
            "cooldown_up_remaining_s", "draining_age_s"} <= set(panel)


# --------------------------------------------------------------------- #
# server /statusz: the scaler's queue-pressure inputs
# --------------------------------------------------------------------- #


def test_server_statusz_reports_rate_and_projected_wait():
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    class _StubModel:
        pass

    server = ExplainerServer(_StubModel())
    detail = server._statusz_detail()
    assert detail["service_rate_rows_per_s"] is None
    assert detail["projected_wait_s"] is None   # no observations yet
    server._service_rate.observe(10, 1.0)

    class _Item:
        klass, deadline, t_enqueued, rows, done = \
            "interactive", None, time.monotonic(), 5, False

    server._sched.put(_Item())
    detail = server._statusz_detail()
    assert detail["service_rate_rows_per_s"] == pytest.approx(10.0)
    # the cumulative served-rows counter the autoscaler differentiates
    # into a rows/s demand (unit-compatible with the capacity EWMA)
    assert detail["rows_served_total"] == 10
    # 5 rows ahead of a fresh interactive request at 10 rows/s
    assert detail["projected_wait_s"]["interactive"] == pytest.approx(
        0.5, abs=0.05)


# --------------------------------------------------------------------- #
# full in-process cycle: spawn -> warm -> admit -> drain -> retire
# --------------------------------------------------------------------- #


def test_full_scale_cycle_with_real_replicas():
    from benchmarks.autoscale_bench import (
        DIM,
        LocalFleet,
        SyntheticServedModel,
        _post_with_retry,
    )

    fleet = LocalFleet(lambda: SyntheticServedModel(base_s=0.005,
                                                    per_row_s=0.005),
                       max_batch_size=4).start(1)
    scaler = None
    try:
        assert fleet.wait_ready(30)
        scaler = Autoscaler(fleet, fleet.proxy, config=AutoscalerConfig(
            min_replicas=1, max_replicas=2, interval_s=0.1,
            drain_settle_polls=1, drain_timeout_s=10.0))
        t0 = time.monotonic()
        scaler._scale_up("queue_wait", t0)
        index = max(fleet.servers)
        assert fleet.proxy.replicas[index].state() == "warming"
        # the prober admits it the moment its warmup ladder finishes
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and \
                not fleet.proxy.replicas[index].routable():
            time.sleep(0.05)
        assert fleet.proxy.replicas[index].routable()
        assert fleet.servers[index].warmup_status()["state"] == "done"
        status, payload, _ = _post_with_retry(
            fleet.proxy.host, fleet.proxy.port,
            np.ones((1, DIM), np.float32), {})
        assert status == 200 and "echo" in payload
        # drain it back down: unroutable immediately, retired once idle
        scaler._scale_down(time.monotonic())
        assert fleet.proxy.replicas[index].draining
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and \
                not fleet.proxy.replicas[index].retired:
            scaler._poll_draining(time.monotonic())
            time.sleep(0.1)
        assert fleet.proxy.replicas[index].retired
        assert fleet.servers[index]._stop.is_set()
        # the survivor still serves
        status, _, _ = _post_with_retry(
            fleet.proxy.host, fleet.proxy.port,
            np.ones((1, DIM), np.float32), {})
        assert status == 200
    finally:
        if scaler is not None:
            scaler.stop()
        fleet.stop()
