"""Tests for the native runtime kernels and the host-eval black-box path."""

from dataclasses import replace

import numpy as np
import pytest

from distributedkernelshap_tpu.kernel_shap import EngineConfig, KernelExplainerEngine
from distributedkernelshap_tpu.models import CallbackPredictor, LinearPredictor
from distributedkernelshap_tpu.runtime import native


@pytest.fixture(scope="module")
def shapes():
    rng = np.random.default_rng(0)
    B, S, N, D = 3, 5, 4, 6
    X = rng.normal(size=(B, D)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    zc = (rng.random((S, D)) > 0.5).astype(np.float32)
    return X, bg, zc


def numpy_masked(X, bg, zc):
    return (X[:, None, None, :] * zc[None, :, None, :]
            + bg[None, None, :, :] * (1 - zc[None, :, None, :])).reshape(-1, X.shape[1])


def test_native_build_and_masked_fill(shapes):
    X, bg, zc = shapes
    out = native.masked_fill(X, bg, zc)
    np.testing.assert_allclose(out, numpy_masked(X, bg, zc), atol=1e-7)


def test_native_weighted_mean(shapes):
    rng = np.random.default_rng(1)
    R, N, K = 7, 4, 3
    pred = rng.normal(size=(R * N, K)).astype(np.float32)
    w = rng.random(N).astype(np.float32)
    w /= w.sum()
    out = native.weighted_mean(pred, w, R)
    expected = np.einsum("rnk,n->rk", pred.reshape(R, N, K), w)
    np.testing.assert_allclose(out, expected, atol=1e-5)


def test_native_lib_loaded():
    # g++ is baked into the image; the OpenMP library should actually build
    assert native.get_lib() is not None


def test_backend_callback_probe_and_auto_routing():
    """`host_eval=None` must auto-route CallbackPredictors by *structurally*
    detecting callback support (active client vs registered tunnel plugins),
    not by backend name — tunnelled TPU backends report 'tpu' but hang on
    callbacks, and executing a probe callback could wedge the device."""

    from distributedkernelshap_tpu.models import predictors as P

    supported = P.backend_supports_callbacks()
    assert isinstance(supported, bool)
    assert P.backend_supports_callbacks() is supported  # cached

    rng = np.random.default_rng(5)
    bg = rng.normal(size=(8, 4)).astype(np.float32)
    W = rng.normal(size=(4, 2)).astype(np.float32)

    def opaque(x):
        z = np.asarray(x) @ W
        e = np.exp(z - z.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    eng = KernelExplainerEngine(CallbackPredictor(opaque, example_dim=4),
                                bg, link="logit", seed=0)
    assert eng.config.host_eval is (not supported)
    phi = eng.get_explanation(rng.normal(size=(3, 4)).astype(np.float32))
    assert phi[0].shape == (3, 4)


def test_hosteval_matches_device_path():
    """Forced host-eval (black-box route) must agree with the fully on-device
    pipeline for the same model."""

    rng = np.random.default_rng(2)
    D, K, N, B = 9, 2, 12, 6
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)

    def host_model(x):
        z = x @ W + b
        e = np.exp(z - z.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    cb = CallbackPredictor(host_model, example_dim=D)
    host_engine = KernelExplainerEngine(
        cb, bg, link="logit", seed=0, config=EngineConfig(host_eval=True))
    device_engine = KernelExplainerEngine(
        LinearPredictor(W, b, activation="softmax"), bg, link="logit", seed=0)

    sv_host = host_engine.get_explanation(X, nsamples=100)
    sv_dev = device_engine.get_explanation(X, nsamples=100)
    np.testing.assert_allclose(sv_host[0], sv_dev[0], atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(host_engine.expected_value),
        np.asarray(device_engine.expected_value), atol=1e-5)


def test_hosteval_threaded_workers_match_sequential():
    """The host-eval chunk fan-out (`host_eval_workers`) must be bitwise
    identical to the sequential loop — chunks write disjoint output slices."""

    rng = np.random.default_rng(7)
    D, K, N, B = 11, 3, 10, 5
    W = rng.normal(size=(D, K)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)

    def host_model(x):
        z = x @ W
        e = np.exp(z - z.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    def engine(workers):
        cb = CallbackPredictor(host_model, example_dim=D)
        # tiny chunk target forces many coalition chunks so the pool is used
        cfg = EngineConfig(host_eval=True, host_eval_workers=workers)
        cfg = replace(cfg, shap=replace(cfg.shap, coalition_chunk=16))
        return KernelExplainerEngine(cb, bg, link="logit", seed=0, config=cfg)

    sv_seq = engine(1).get_explanation(X, nsamples=200)
    sv_par = engine(4).get_explanation(X, nsamples=200)
    for a, b_ in zip(sv_seq, sv_par):
        np.testing.assert_array_equal(a, b_)

    # the public API reaches the same knob via `engine_config`
    from distributedkernelshap_tpu import KernelShap

    ks = KernelShap(host_model, link="logit", seed=0,
                    engine_config=EngineConfig(host_eval=True,
                                               host_eval_workers=4))
    ks.fit(bg)
    sv_api = ks.explain(X, nsamples=200).shap_values
    for a, b_ in zip(sv_seq, sv_api):
        np.testing.assert_allclose(a, b_, atol=1e-6)


def test_hosteval_l1_reg():
    rng = np.random.default_rng(3)
    D = 16
    W = rng.normal(size=(D, 1)).astype(np.float32)
    bg = rng.normal(size=(8, D)).astype(np.float32)
    X = rng.normal(size=(2, D)).astype(np.float32)

    cb = CallbackPredictor(lambda x: x @ W, example_dim=D)
    engine = KernelExplainerEngine(cb, bg, link="identity", seed=0,
                                   config=EngineConfig(host_eval=True))
    sv = engine.get_explanation(X, nsamples=64, l1_reg="num_features(5)")
    nz = (np.abs(sv[0]) > 1e-9).sum(1)
    assert (nz <= 6).all()


def test_get_explanation_async_fallback_paths():
    """The async API's synchronous fallbacks (host_eval engines, batches
    over instance_chunk, active l1) must return exactly what the sync call
    returns — they run on the dispatcher thread and close over the result."""

    rng = np.random.default_rng(5)
    D, K, N, B = 6, 2, 10, 12
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = np.zeros(K, np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)

    def host_model(x):
        z = x @ W + b
        e = np.exp(z - z.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    # host_eval fallback
    eng_host = KernelExplainerEngine(
        CallbackPredictor(host_model, example_dim=D), bg, link="logit",
        seed=0, config=EngineConfig(host_eval=True))
    want = eng_host.get_explanation(X, nsamples=40)
    got, info = eng_host.get_explanation_async(X, nsamples=40)()
    np.testing.assert_allclose(got[0], want[0], atol=1e-6)
    assert info["raw_prediction"].shape == (B, K)

    # instance_chunk fallback
    eng_chunk = KernelExplainerEngine(
        LinearPredictor(W, b, activation="softmax"), bg, link="logit",
        seed=0, config=EngineConfig(instance_chunk=4))
    want = eng_chunk.get_explanation(X, nsamples=40)
    got, _ = eng_chunk.get_explanation_async(X, nsamples=40)()
    np.testing.assert_allclose(got[0], want[0], atol=1e-6)

    # active-l1 fallback (explicit num_features selection)
    eng_l1 = KernelExplainerEngine(
        LinearPredictor(W, b, activation="softmax"), bg, link="logit", seed=0)
    want = eng_l1.get_explanation(X, nsamples=40, l1_reg="num_features(4)")
    got, _ = eng_l1.get_explanation_async(X, nsamples=40,
                                          l1_reg="num_features(4)")()
    np.testing.assert_allclose(got[0], want[0], atol=1e-6)


def test_hosteval_workers_scale_with_gil_releasing_predictor():
    """VERDICT r3 #6: `host_eval_workers` must deliver measured SPEEDUP,
    not just correctness, when the predictor releases the GIL (sklearn /
    XGBoost release it inside their numeric cores; here a sleep stands in
    so the test is deterministic even on a 1-core host).  Eight coalition
    chunks at ~60 ms each: sequential ≈ 480 ms, four workers ≈ 2 waves,
    so ≥0.36 s of guaranteed sleep overlap — asserted as an ABSOLUTE
    margin (see the inline comment: a ratio flaked on a loaded core)."""

    import time as _time

    rng = np.random.default_rng(11)
    D, K, N, B = 8, 2, 8, 4
    W = rng.normal(size=(D, K)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)

    def slow_host_model(x):
        _time.sleep(0.06)  # GIL released, like a BLAS/XGBoost core
        z = x @ W
        e = np.exp(z - z.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    def run(workers):
        cb = CallbackPredictor(slow_host_model, example_dim=D)
        cfg = EngineConfig(host_eval=True, host_eval_workers=workers)
        # nsamples=128 / chunk=16 -> 8 coalition chunks
        cfg = replace(cfg, shap=replace(cfg.shap, coalition_chunk=16))
        eng = KernelExplainerEngine(cb, bg, link="logit", seed=0, config=cfg)
        t0 = _time.perf_counter()
        sv = eng.get_explanation(X, nsamples=128)
        return _time.perf_counter() - t0, sv

    run(1)  # untimed warm-up: backend init + lazy imports out of the timing
    t_seq, sv_seq = run(1)
    t_par, sv_par = run(4)
    for a, b_ in zip(sv_seq, sv_par):
        np.testing.assert_array_equal(a, b_)
    # ABSOLUTE sleep-overlap margin, not a ratio: sleeps overlap regardless
    # of CPU contention (they hold no core), while a loaded CI host
    # inflates the non-sleep overhead of BOTH runs — a ratio assertion
    # flaked under a 3x-oversubscribed core.  8 chunks x 60 ms sequential
    # vs 2 waves at 4 workers leaves >=0.36 s of guaranteed saving.
    assert t_par < t_seq - 0.2, (
        f"host_eval_workers=4 took {t_par:.2f}s vs sequential {t_seq:.2f}s "
        f"— the chunk fan-out is not overlapping GIL-releasing predictor "
        f"calls")
