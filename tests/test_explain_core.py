"""Correctness oracles for the core explain kernel (SURVEY.md §4):

1. additivity: Σφ + E[f] == link(f(x)) per instance/class;
2. exact Shapley values for linear models with identity link:
   φ_j = Σ_{d∈group j} W_dk · (x_d - E_bg[x_d]) under full enumeration;
3. linear fast path ≡ generic path;
4. sequential == batched (order invariance).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributedkernelshap_tpu.models.predictors import (
    CallbackPredictor,
    JaxPredictor,
    LinearPredictor,
    as_predictor,
)
from distributedkernelshap_tpu.ops.coalitions import coalition_plan
from distributedkernelshap_tpu.ops.explain import (
    ShapConfig,
    build_explainer_fn,
    groups_to_matrix,
    split_shap_values,
)


def run_explain(predictor, X, bg, groups=None, nsamples=None, link="identity",
                bgw=None, seed=0, **cfg):
    D = X.shape[1]
    G = groups_to_matrix(groups, D)
    M = G.shape[0]
    plan = coalition_plan(M, nsamples=nsamples, seed=seed)
    if bgw is None:
        bgw = np.ones(bg.shape[0], dtype=np.float32)
    fn = jax.jit(build_explainer_fn(predictor, ShapConfig(link=link, **cfg)))
    return fn(jnp.asarray(X), jnp.asarray(bg), jnp.asarray(bgw),
              jnp.asarray(plan.mask), jnp.asarray(plan.weights), jnp.asarray(G))


@pytest.fixture(scope="module")
def linear_setup():
    rng = np.random.default_rng(0)
    D, K, N, B = 7, 3, 12, 5
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    return W, b, X, bg


def test_exact_shapley_linear_identity(linear_setup):
    W, b, X, bg = linear_setup
    pred = LinearPredictor(W, b, activation="identity")
    out = run_explain(pred, X, bg, nsamples=2 ** 7)  # full enumeration, M=D=7
    phi = np.asarray(out["shap_values"])  # (B, K, M)
    expected = (X - bg.mean(0))[:, None, :] * W.T[None, :, :]  # (B, K, D)
    np.testing.assert_allclose(phi, expected, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(out["expected_value"]), bg.mean(0) @ W + b, atol=1e-4
    )


def test_exact_shapley_linear_grouped(linear_setup):
    W, b, X, bg = linear_setup
    groups = [[0], [1, 2], [3, 4, 5], [6]]
    pred = LinearPredictor(W, b, activation="identity")
    out = run_explain(pred, X, bg, groups=groups, nsamples=64)  # 2^4-2=14 → exact
    phi = np.asarray(out["shap_values"])  # (B, K, 4)
    diff = (X - bg.mean(0))  # (B, D)
    for j, cols in enumerate(groups):
        expected_j = diff[:, cols] @ W[cols, :]  # (B, K)
        np.testing.assert_allclose(phi[:, :, j], expected_j, atol=2e-4)


@pytest.mark.parametrize("link,activation", [("identity", "identity"),
                                             ("logit", "softmax")])
def test_additivity(linear_setup, link, activation):
    W, b, X, bg = linear_setup
    pred = LinearPredictor(W, b, activation=activation)
    out = run_explain(pred, X, bg, nsamples=200, link=link)
    phi = np.asarray(out["shap_values"])
    total = phi.sum(-1) + np.asarray(out["expected_value"])[None, :]
    np.testing.assert_allclose(total, np.asarray(out["raw_prediction"]), atol=1e-4)


def test_additivity_sampled_many_features():
    rng = np.random.default_rng(3)
    D, K, N, B = 25, 2, 10, 4
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = np.zeros(K, dtype=np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    pred = LinearPredictor(W, b, activation="softmax")
    out = run_explain(pred, X, bg, nsamples=500, link="logit")
    phi = np.asarray(out["shap_values"])
    total = phi.sum(-1) + np.asarray(out["expected_value"])[None, :]
    np.testing.assert_allclose(total, np.asarray(out["raw_prediction"]), atol=1e-3)


def test_linear_fast_path_matches_generic(linear_setup):
    W, b, X, bg = linear_setup
    fast = LinearPredictor(W, b, activation="softmax")
    generic = JaxPredictor(lambda x: jax.nn.softmax(x @ W + b, axis=-1), n_outputs=3)
    out_fast = run_explain(fast, X, bg, nsamples=150, link="logit")
    out_gen = run_explain(generic, X, bg, nsamples=150, link="logit")
    np.testing.assert_allclose(np.asarray(out_fast["shap_values"]),
                               np.asarray(out_gen["shap_values"]), atol=1e-4)


def test_callback_predictor_matches_native(linear_setup):
    W, b, X, bg = linear_setup

    def host_model(x):
        z = x @ np.asarray(W) + np.asarray(b)
        e = np.exp(z - z.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    cb = CallbackPredictor(host_model, example_dim=X.shape[1])
    native = LinearPredictor(W, b, activation="softmax")
    out_cb = run_explain(cb, X, bg, nsamples=100, link="logit")
    out_na = run_explain(native, X, bg, nsamples=100, link="logit")
    np.testing.assert_allclose(np.asarray(out_cb["shap_values"]),
                               np.asarray(out_na["shap_values"]), atol=1e-4)


def test_batch_order_invariance(linear_setup):
    W, b, X, bg = linear_setup
    pred = LinearPredictor(W, b, activation="identity")
    out_all = np.asarray(run_explain(pred, X, bg, nsamples=128)["shap_values"])
    out_rows = np.concatenate(
        [np.asarray(run_explain(pred, X[i:i + 1], bg, nsamples=128)["shap_values"])
         for i in range(X.shape[0])], 0)
    np.testing.assert_allclose(out_all, out_rows, atol=1e-4)


def test_background_weights(linear_setup):
    W, b, X, bg = linear_setup
    pred = LinearPredictor(W, b, activation="identity")
    bgw = np.zeros(bg.shape[0], dtype=np.float32)
    bgw[0] = 5.0  # only background row 0 matters
    out = run_explain(pred, X, bg, nsamples=128, bgw=bgw)
    expected = (X - bg[0]) [:, None, :] * W.T[None, :, :]
    np.testing.assert_allclose(np.asarray(out["shap_values"]), expected, atol=2e-4)


def test_chunking_invariance(linear_setup):
    W, b, X, bg = linear_setup
    pred = LinearPredictor(W, b, activation="softmax")
    out_small = run_explain(pred, X, bg, nsamples=100, link="logit", coalition_chunk=7)
    out_large = run_explain(pred, X, bg, nsamples=100, link="logit", coalition_chunk=1000)
    np.testing.assert_allclose(np.asarray(out_small["shap_values"]),
                               np.asarray(out_large["shap_values"]), atol=1e-5)


def test_single_group():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(3, 4)).astype(np.float32)
    bg = rng.normal(size=(6, 4)).astype(np.float32)
    W = rng.normal(size=(4, 2)).astype(np.float32)
    pred = LinearPredictor(W, np.zeros(2, np.float32), activation="identity")
    out = run_explain(pred, X, bg, groups=[[0, 1, 2, 3]])
    phi = np.asarray(out["shap_values"])  # (3, 2, 1)
    expected = (X @ W - (bg.mean(0) @ W)[None])[:, :, None]
    np.testing.assert_allclose(phi, expected, atol=1e-4)


def test_split_shap_values_layout():
    phi = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    out = split_shap_values(phi)
    assert isinstance(out, list) and len(out) == 3
    np.testing.assert_array_equal(out[1], phi[:, 1, :])
    single = split_shap_values(phi[:, :1, :], vector_out=False)
    assert isinstance(single, np.ndarray) and single.shape == (2, 4)


def test_as_predictor_sklearn_lift():
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(7)
    Xtr = rng.normal(size=(200, 5))
    ytr = (Xtr @ rng.normal(size=5) > 0).astype(int)
    clf = LogisticRegression(max_iter=200).fit(Xtr, ytr)
    pred = as_predictor(clf.predict_proba, example_dim=5)
    assert isinstance(pred, LinearPredictor)
    probe = np.asarray(Xtr[:10], dtype=np.float32)
    np.testing.assert_allclose(np.asarray(pred(jnp.asarray(probe))),
                               clf.predict_proba(probe), atol=1e-5)


def test_exact_shapley_nonlinear_brute_force():
    """Independent oracle for a NONLINEAR model: with full enumeration the
    WLS solve must reproduce the classic Shapley formula
    phi_i = sum_S |S|!(M-|S|-1)!/M! (v(S+i) - v(S)) with the interventional
    value function v(S) = E_bg[f(x_S, bg_notS)] — computed here by brute
    force over all subsets, no regression involved."""

    import math as pymath
    from itertools import combinations as combos

    rng = np.random.default_rng(7)
    D, K, N, B = 6, 2, 8, 3
    W1 = rng.normal(size=(D, 5)).astype(np.float32)
    W2 = rng.normal(size=(5, K)).astype(np.float32)

    def f_np(x):  # tiny MLP: genuinely nonlinear
        return np.tanh(x @ W1) @ W2

    predictor = JaxPredictor(
        lambda x: jnp.tanh(x @ jnp.asarray(W1)) @ jnp.asarray(W2), n_outputs=K)

    X = rng.normal(size=(B, D)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)

    out = run_explain(predictor, X, bg, nsamples=2 ** D)  # exact plan
    phi = np.asarray(out["shap_values"])  # (B, K, D)

    def v(b_idx, subset):
        rows = bg.copy()
        rows[:, list(subset)] = X[b_idx, list(subset)]
        return f_np(rows).mean(0)  # (K,)

    M = D
    for b_idx in range(B):
        phi_bf = np.zeros((K, M))
        for i in range(M):
            others = [j for j in range(M) if j != i]
            for r in range(M):
                coef = pymath.factorial(r) * pymath.factorial(M - r - 1) / pymath.factorial(M)
                for S in combos(others, r):
                    phi_bf[:, i] += coef * (v(b_idx, S + (i,)) - v(b_idx, S))
        np.testing.assert_allclose(phi[b_idx], phi_bf, atol=5e-4)
