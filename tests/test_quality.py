"""Continuous correctness observability (``observability/quality.py``):
the invariant screen's per-path tolerances, the auditor's bounded repro
ring, the shadow-oracle sampler's budget/oracle-selection/inert modes,
the canary sentinel across gated hot swaps, the ``/qualityz`` document
and its federated fold, and tenant label retirement on unregister."""

import json
import time
from types import SimpleNamespace

import numpy as np
import pytest

from distributedkernelshap_tpu.models import LinearPredictor
from distributedkernelshap_tpu.observability.flightrec import flightrec
from distributedkernelshap_tpu.observability.metrics import MetricsRegistry
from distributedkernelshap_tpu.observability.quality import (
    DRIFT_TOLERANCE,
    CanarySentinel,
    QualityAuditor,
    QualityMonitor,
    ShadowSampler,
    cacheable_payload,
    merge_quality_pages,
    screen_arrays,
    screen_payload,
    stub_doc,
)
from distributedkernelshap_tpu.registry import ModelRegistry
from distributedkernelshap_tpu.resilience.faults import corrupt_phi_payload
from distributedkernelshap_tpu.scheduling.result_cache import ResultCache
from distributedkernelshap_tpu.serving import wire
from distributedkernelshap_tpu.serving.server import ExplainerServer
from distributedkernelshap_tpu.serving.wrappers import BatchKernelShapModel

D = 6


def _flight_count(kind):
    return sum(1 for e in flightrec().to_payload()["events"]
               if e.get("kind") == kind)


# --------------------------------------------------------------------- #
# invariant screen
# --------------------------------------------------------------------- #


def _clean_answer(b=2, k=2, m=4, seed=0):
    """Arrays satisfying additivity exactly: raw := sum(phi) + E[f]."""

    rng = np.random.default_rng(seed)
    sv = [rng.normal(size=(b, m)) for _ in range(k)]
    ev = rng.normal(size=(k,))
    raw = np.stack([v.sum(axis=-1) for v in sv], axis=-1) + ev
    return sv, ev, raw


def test_screen_clean_answer_passes():
    sv, ev, raw = _clean_answer()
    assert screen_arrays(sv, ev, raw, path="sampled") == []
    assert screen_arrays(sv, ev, raw, path="exact") == []


def test_screen_flags_additivity_break():
    sv, ev, raw = _clean_answer()
    sv[0][0, 1] += 0.5
    checks = [c for c, _ in screen_arrays(sv, ev, raw, path="sampled")]
    assert checks == ["additivity"]


def test_screen_path_tolerances_tight_vs_loose():
    # a 5e-3 residual on |raw| <= 1: inside the sampled path's bound
    # (1e-3 + 1e-2), outside the exact path's (1e-4 + 1e-3)
    sv, ev, raw = _clean_answer(b=1, k=1, m=3)
    raw = np.clip(raw, -0.9, 0.9)
    ev = raw[0] - sv[0].sum(axis=-1)  # re-solve additivity after the clip
    sv[0][0, 0] += 5e-3
    assert screen_arrays(sv, ev, raw, path="sampled") == []
    checks = [c for c, _ in screen_arrays(sv, ev, raw, path="exact")]
    assert checks == ["additivity"]
    # unknown paths screen at the loose default, not the tight bound
    assert screen_arrays(sv, ev, raw, path="no-such-path") == []


def test_screen_final_err_widens_bound():
    # anytime answers declare their residual; the screen honours it
    sv, ev, raw = _clean_answer(b=1, k=1, m=3)
    sv[0][0, 0] += 0.05
    assert [c for c, _ in screen_arrays(sv, ev, raw, path="sampled")] \
        == ["additivity"]
    assert screen_arrays(sv, ev, raw, path="sampled", final_err=0.06) == []


def test_screen_flags_nonfinite():
    for poison in (np.nan, np.inf, -np.inf):
        sv, ev, raw = _clean_answer()
        sv[1][0, 2] = poison
        checks = [c for c, _ in screen_arrays(sv, ev, raw)]
        assert checks == ["finite"], poison
    sv, ev, raw = _clean_answer()
    raw = raw.copy()
    raw[0, 0] = np.nan
    assert [c for c, _ in screen_arrays(sv, ev, raw)] == ["finite"]


def test_screen_flags_insane_error_bound():
    sv, ev, raw = _clean_answer()
    for bad in (-0.5, 1e9, float("nan")):
        checks = [c for c, _ in screen_arrays(sv, ev, raw, path="sampled",
                                              final_err=bad)]
        assert "error_bound" in checks, bad


def test_screen_payload_decode_violation():
    violations, arrays = screen_payload(b"\x00garbage-not-an-explanation")
    assert arrays is None
    assert [c for c, _ in violations] == ["decode"]


def _wire_payload(b=1, k=1, m=4, seed=3):
    sv, ev, raw = _clean_answer(b=b, k=k, m=m, seed=seed)
    return wire.encode_explanation(
        [v.astype(np.float32) for v in sv], ev.astype(np.float32),
        raw.astype(np.float32))


def test_screen_payload_roundtrips_binary_wire():
    violations, arrays = screen_payload(_wire_payload(), path="sampled")
    assert violations == []
    assert arrays is not None and len(arrays["shap_values"]) == 1


def test_cacheable_payload_semantics(monkeypatch):
    good = _wire_payload()
    bad = corrupt_phi_payload(good, seed=5)
    assert cacheable_payload(good, path="sampled")
    assert not cacheable_payload(bad, path="sampled")
    # non-explanation strings pass through: the result cache is generic
    # keyed storage and its historical contract accepts arbitrary values
    assert cacheable_payload("xxxx")
    # screen disabled => pre-quality behaviour, everything passes
    monkeypatch.setenv("DKS_QUALITY_AUDIT", "0")
    assert cacheable_payload(bad, path="sampled")


def test_result_cache_rejects_poisoned_insert_on_unscreened_put():
    cache = ResultCache(max_bytes=1 << 16)
    bad = corrupt_phi_payload(_wire_payload(), seed=7).hex()  # str payload?
    # decodable-but-wrong phi must be refused at insert; arbitrary
    # strings (the cache's historical contract) must still store
    cache.put("k-bad", corrupt_phi_payload(
        _wire_payload(), seed=7), screened=False)
    assert cache.get("k-bad") is None
    assert cache.stats()["audit_rejects"] == 1
    cache.put("k-str", "not-an-explanation", screened=False)
    assert cache.get("k-str") == "not-an-explanation"
    del bad


def test_result_cache_invalidate_is_audit_hook():
    cache = ResultCache(max_bytes=1 << 16)
    cache.put("k", "payload", screened=True)
    assert cache.invalidate("k", audit=True)
    assert cache.get("k") is None
    assert not cache.invalidate("k", audit=True)  # idempotent
    assert cache.stats()["audit_rejects"] == 1


# --------------------------------------------------------------------- #
# auditor: repro ring + flight events
# --------------------------------------------------------------------- #


def test_auditor_ring_bounded_and_counts():
    auditor = QualityAuditor(ring_size=4)
    before = _flight_count("quality_violation")
    good = _wire_payload()
    for i in range(10):
        ok, _ = auditor.audit(corrupt_phi_payload(good, seed=i),
                              model_id="t", path="sampled",
                              trace=f"tr-{i}")
        assert not ok
    ok, _ = auditor.audit(good, model_id="t", path="sampled")
    assert ok
    snap = auditor.snapshot()
    assert snap["audited_total"] == 11
    assert snap["violation_answers_total"] == 10
    assert len(snap["ring"]) == 4  # bounded, newest kept
    assert snap["ring"][-1]["trace"] == "tr-9"
    assert snap["ring"][-1]["checks"] == ["additivity"]
    assert _flight_count("quality_violation") == before + 10


def test_auditor_disabled_is_inert():
    auditor = QualityAuditor(enabled=False)
    ok, arrays = auditor.audit(b"garbage", model_id="t")
    assert ok and arrays is None
    assert auditor.snapshot()["audited_total"] == 0


# --------------------------------------------------------------------- #
# shadow sampler
# --------------------------------------------------------------------- #


class _FakeOracle:
    """Duck-typed serving model: records oracle kwargs, returns phi of
    ``scale * rows`` after an optional sleep (budget tests)."""

    def __init__(self, scale=1.0, sleep_s=0.0,
                 explain_kwargs=None):
        self.scale = scale
        self.sleep_s = sleep_s
        self.explain_kwargs = {"nsamples": 64, "l1_reg": 0.0,
                               "interactions": False} \
            if explain_kwargs is None else explain_kwargs
        self.calls = []
        outer = self

        class _Explainer:
            def explain(self, rows, silent=True, **kwargs):
                outer.calls.append(kwargs)
                if outer.sleep_s:
                    time.sleep(outer.sleep_s)
                return SimpleNamespace(
                    shap_values=[np.asarray(rows) * outer.scale])

        self.explainer = _Explainer()


def test_sampler_disabled_is_inert():
    sampler = ShadowSampler(fraction=0.0)
    assert not sampler.offer("t", "sampled", _FakeOracle(),
                             np.ones((1, D)), [np.ones((1, D))])
    assert sampler.drain_once() is None
    snap = sampler.snapshot()
    assert snap["sampled"] == 0 and snap["offered"] == 0


def test_sampler_oracle_selection_by_path():
    model = _FakeOracle()
    sampler = ShadowSampler(fraction=1.0, oracle_nsamples=2048)
    # sampled-path tenants get a high-nsamples re-run; the pinned
    # interactions flag must NOT leak into the oracle call
    kw = sampler._oracle_kwargs("sampled", model)
    assert kw == {"nsamples": 2048, "l1_reg": 0.0}
    kw = sampler._oracle_kwargs("linear", model)
    assert kw["nsamples"] == 2048  # linear is still the sampled estimator
    # exact paths are their own oracle: pinned kwargs pass unchanged
    for path in ("exact", "exact_tree", "exact_tn", "deepshap"):
        kw = sampler._oracle_kwargs(path, model)
        assert kw == {"nsamples": 64, "l1_reg": 0.0}, path


def test_sampler_budget_trips_and_gates_offers():
    model = _FakeOracle(sleep_s=0.01)
    sampler = ShadowSampler(fraction=1.0, budget_s=0.015)
    rows = np.ones((1, D), dtype=np.float32)
    for _ in range(5):
        sampler.offer("t", "sampled", model, rows, [rows])
    assert sampler.drain_once() is not None  # first run always allowed
    assert sampler.drain_once() is None      # projected over budget
    snap = sampler.snapshot()
    assert snap["exhausted"]
    assert snap["tenants"]["t"]["runs"] == 1
    assert snap["spent_s"] <= 0.015 + snap["max_run_s"]
    # exhausted sampler stops admitting new samples at offer time
    assert not sampler.offer("t", "sampled", model, rows, [rows])


def test_sampler_error_series_bounded():
    model = _FakeOracle(scale=1.0)
    sampler = ShadowSampler(fraction=1.0, budget_s=1e9, series_size=3)
    rows = np.ones((1, D), dtype=np.float32)
    served = [np.asarray(rows) + 0.25]  # served phi off by 0.25
    for _ in range(5):
        assert sampler.offer("t", "sampled", model, rows, served)
        result = sampler.drain_once()
        assert result is not None
    snap = sampler.snapshot()["tenants"]["t"]
    assert snap["runs"] == 5
    assert snap["last_err"] == pytest.approx(0.25)
    assert len(snap["series"]) == 3  # bounded time-series
    sampler.retire("t")
    assert "t" not in sampler.snapshot()["tenants"]


# --------------------------------------------------------------------- #
# canary sentinel
# --------------------------------------------------------------------- #


class _FakeCanaryModel(_FakeOracle):
    """Fake with an inspectable engine background (canary row source)."""

    def __init__(self, scale=1.0):
        super().__init__(scale=scale)
        background = np.arange(5 * D, dtype=np.float64).reshape(5, D)
        self.explainer._explainer = SimpleNamespace(background=background)


def test_canary_capture_replay_and_drift():
    sentinel = CanarySentinel(n_rows=3)
    model = _FakeCanaryModel(scale=1.0)
    assert sentinel.capture("t", model, fingerprint="t@v1:abc")
    assert sentinel.tenants() == ["t"]
    verdict = sentinel.replay("t", model)
    assert verdict["verdict"] == "ok"
    assert verdict["drift"] <= DRIFT_TOLERANCE
    assert verdict["rows"] == 3
    before = _flight_count("swap_drift")
    drifted = sentinel.replay("t", _FakeCanaryModel(scale=2.0))
    assert drifted["verdict"] == "drift" and drifted["drift"] > 0
    assert _flight_count("swap_drift") == before + 1
    snap = sentinel.snapshot()["tenants"]["t"]
    assert snap["verdict"] == "drift"
    sentinel.retire("t")
    assert sentinel.tenants() == []


def test_canary_swap_check_recaptures_baseline():
    sentinel = CanarySentinel(n_rows=2)
    assert sentinel.swap_check("t", _FakeCanaryModel(1.0)) is None  # first
    # the flip to scale=2 drifts against the v1 baseline...
    verdict = sentinel.swap_check("t", _FakeCanaryModel(2.0))
    assert verdict["verdict"] == "drift"
    # ...and re-captures: the SAME content now replays clean
    verdict = sentinel.swap_check("t", _FakeCanaryModel(2.0))
    assert verdict["verdict"] == "ok"


def test_canary_inert_for_stub_models():
    sentinel = CanarySentinel()
    assert not sentinel.capture("t", SimpleNamespace())  # no engine
    assert sentinel.replay("t", SimpleNamespace()) is None


# --------------------------------------------------------------------- #
# monitor: deferred queue, metrics, /qualityz, label retirement
# --------------------------------------------------------------------- #


def _monitor(**kwargs):
    kwargs.setdefault("audit", True)
    kwargs.setdefault("sample", 0.0)
    return QualityMonitor(server=None, costmeter=None, **kwargs)


def test_monitor_deferred_queue_flush_drains_inline():
    monitor = _monitor()
    good = _wire_payload()
    for _ in range(3):
        monitor.enqueue_answer(good, model_id="t", path="sampled")
    assert monitor.audit_backlog() == 3
    assert monitor.flush()  # no thread running: drains inline
    assert monitor.audit_backlog() == 0
    assert monitor.auditor.snapshot()["audited_total"] == 3


def test_monitor_deferred_audit_invalidates_poisoned_cache_entry():
    monitor = _monitor()
    cache = ResultCache(max_bytes=1 << 16)
    bad = corrupt_phi_payload(_wire_payload(), seed=9)
    cache.put("k", bad, screened=True)  # finalizer inserts optimistically
    monitor.enqueue_answer(bad, model_id="t", path="sampled",
                           cache=cache, cache_key="k")
    assert monitor.flush()
    assert cache.get("k") is None  # poison pulled back out
    assert cache.stats()["audit_rejects"] == 1


def test_monitor_metrics_and_label_retirement():
    registry = MetricsRegistry()
    monitor = _monitor()
    monitor.attach_metrics(registry)
    bad = corrupt_phi_payload(_wire_payload(), seed=11)
    monitor.enqueue_answer(bad, model_id="tenant-x", path="sampled")
    monitor.flush()
    page = registry.render()
    assert 'dks_quality_violations_total{model="tenant-x",' \
        'path="sampled",check="additivity"}' in page
    monitor.retire_tenant("tenant-x", registry=registry)
    assert 'model="tenant-x"' not in registry.render()


def test_qualityz_document_schema():
    monitor = _monitor()
    ctype, body = monitor.qualityz_payload()
    assert ctype == "application/json"
    doc = json.loads(body)
    assert doc["component"] == "server"
    for key in ("enabled", "audited_total", "violation_answers_total",
                "backlog", "backlog_dropped", "ring_size", "ring"):
        assert key in doc["audit"], key
    for key in ("fraction", "budget_s", "spent_s", "max_run_s",
                "exhausted", "offered", "sampled", "dropped", "queued",
                "tenants"):
        assert key in doc["shadow"], key
    assert doc["canary"]["threshold"] == DRIFT_TOLERANCE
    # the stub (proxy without ?federate=1) shares the schema
    stub = stub_doc()
    assert set(stub["audit"]) == set(doc["audit"])
    assert set(stub["shadow"]) == set(doc["shadow"])


def test_merge_quality_pages_folds_replicas():
    page_a = stub_doc("server")
    page_a["audit"].update(enabled=True, audited_total=10,
                           violation_answers_total=2, ring_size=4,
                           ring=[{"ts": 1.0, "checks": ["additivity"]},
                                 {"ts": 3.0, "checks": ["finite"]}])
    page_a["shadow"].update(spent_s=1.0, max_run_s=0.5,
                            tenants={"t": {"runs": 2, "last_err": 0.1,
                                           "series": [[1.0, 0.1]]}})
    page_a["canary"]["tenants"]["t"] = {"drift": 0.0, "verdict": "ok"}
    page_b = stub_doc("server")
    page_b["audit"].update(audited_total=5, violation_answers_total=1,
                           ring_size=4, ring=[{"ts": 2.0,
                                               "checks": ["additivity"]}])
    page_b["shadow"].update(spent_s=0.5, max_run_s=0.7, exhausted=True,
                            tenants={"t": {"runs": 3, "last_err": 0.4,
                                           "series": [[2.0, 0.4]]}})
    page_b["canary"]["tenants"]["t"] = {"drift": 0.02, "verdict": "drift"}
    merged = json.loads(merge_quality_pages(
        [json.dumps(page_a), json.dumps(page_b), "not-json"]))
    assert merged["component"] == "fleet"
    assert merged["replicas"] == 2  # the garbage page is skipped
    assert merged["audit"]["enabled"]
    assert merged["audit"]["audited_total"] == 15
    assert merged["audit"]["violation_answers_total"] == 3
    # ring folds newest-first under the bound
    assert [e["ts"] for e in merged["audit"]["ring"]] == [3.0, 2.0, 1.0]
    shadow = merged["shadow"]
    assert shadow["spent_s"] == pytest.approx(1.5)
    assert shadow["max_run_s"] == pytest.approx(0.7)
    assert shadow["exhausted"]
    assert shadow["tenants"]["t"]["runs"] == 5
    assert shadow["tenants"]["t"]["last_err"] == pytest.approx(0.4)
    assert len(shadow["tenants"]["t"]["series"]) == 2
    # canary keeps the worst replica's verdict per tenant
    assert merged["canary"]["tenants"]["t"]["verdict"] == "drift"


# --------------------------------------------------------------------- #
# gated hot swap on the real registry + server (integration)
# --------------------------------------------------------------------- #


def _linear_model(seed):
    rng = np.random.default_rng(seed)
    W = rng.normal(size=(D, 2)).astype(np.float32)
    b = rng.normal(size=(2,)).astype(np.float32)
    bg = np.random.default_rng(99).normal(size=(8, D)).astype(np.float32)
    return BatchKernelShapModel(LinearPredictor(W, b, activation="softmax"),
                                bg, {"link": "logit", "seed": 0},
                                {"nsamples": 32})


def test_registry_swap_check_gates_hot_swap():
    registry = ModelRegistry()
    server = ExplainerServer(registry=registry)  # attach only, no start
    try:
        events = [e for e in flightrec().to_payload()["events"]
                  if e.get("kind") == "model_swap"]
        seen = len(events)
        registry.register("tenant-q", _linear_model(seed=1), warm=False)
        registry.register("tenant-q", _linear_model(seed=1), warm=False)
        registry.register("tenant-q", _linear_model(seed=5), warm=False)
        swaps = [e for e in flightrec().to_payload()["events"]
                 if e.get("kind") == "model_swap"
                 and e.get("model") == "tenant-q"][seen - len(events) or None:]
        by_version = {e["to_version"]: e for e in swaps}
        # v1: no baseline yet (first registration) — verdict absent
        assert by_version[1].get("canary_verdict") is None
        # v2: identical content replays ~zero drift against v1's baseline
        assert by_version[2]["canary_verdict"] == "ok"
        assert by_version[2]["canary_drift"] <= DRIFT_TOLERANCE
        # v3: different weights drift loudly BEFORE traffic moved
        assert by_version[3]["canary_verdict"] == "drift"
        assert by_version[3]["canary_drift"] > DRIFT_TOLERANCE
        # the sentinel's state shows through /qualityz
        _, body = server._quality.qualityz_payload()
        canary = json.loads(body)["canary"]["tenants"]["tenant-q"]
        assert canary["verdict"] == "drift"
        # unregister retires the tenant's quality state and labels
        registry.unregister("tenant-q")
        _, body = server._quality.qualityz_payload()
        assert "tenant-q" not in json.loads(body)["canary"]["tenants"]
        assert 'model="tenant-q"' not in server.metrics.render()
    finally:
        server._quality.stop()
