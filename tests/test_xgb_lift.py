"""XGBoost JSON-model lifting (models/xgb.py).

xgboost is not installed in CI, so these tests validate the parser against
hand-constructed ``save_model`` JSON (per the documented schema) and an
*independent* pure-Python tree walker written here — not against the parser
itself.  On user machines with xgboost installed, every lift is additionally
probe-verified against the real ``predict_proba`` in ``as_predictor``.
"""

import numpy as np
import pytest

from distributedkernelshap_tpu.models import predictor_from_xgboost_json


def _tree(split_indices, split_conditions, left, right, default_left):
    return {
        "split_indices": split_indices,
        "split_conditions": split_conditions,
        "left_children": left,
        "right_children": right,
        "default_left": default_left,
        "split_type": [0] * len(split_indices),
        "categories": [],
    }


def _model(trees, objective, base_score, num_class=0, tree_info=None):
    return {"learner": {
        "objective": {"name": objective},
        "learner_model_param": {"base_score": str(base_score),
                                "num_class": str(num_class)},
        "gradient_booster": {"model": {
            "trees": trees,
            "tree_info": tree_info or [0] * len(trees),
        }},
    }}


def _walk(tree, x):
    """Independent reference evaluator: xgboost semantics (strict x < t,
    default_left for NaN)."""

    j = 0
    while tree["left_children"][j] != -1:
        v = x[tree["split_indices"][j]]
        if np.isnan(v):
            go_left = bool(tree["default_left"][j])
        else:
            go_left = v < tree["split_conditions"][j]
        j = tree["left_children"][j] if go_left else tree["right_children"][j]
    return tree["split_conditions"][j]


@pytest.fixture
def binary_model():
    # two depth-2 trees over 3 features
    t0 = _tree([0, 1, 2, 0, 0, 0, 0],
               [0.5, -1.0, 2.0, 0.3, -0.7, 1.1, -0.2],
               [1, 3, 5, -1, -1, -1, -1],
               [2, 4, 6, -1, -1, -1, -1],
               [1, 0, 1, 0, 0, 0, 0])
    t1 = _tree([2, 0, 0],
               [1.5, 0.25, -0.4],
               [1, -1, -1],
               [2, -1, -1],
               [0, 0, 0])
    return _model([t0, t1], "binary:logistic", 0.5), [t0, t1]


def test_binary_logistic(binary_model):
    model, trees = binary_model
    pred = predictor_from_xgboost_json(model)
    assert pred is not None and pred.n_outputs == 2
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 3)).astype(np.float32)
    margin = np.array([sum(_walk(t, x) for t in trees) for x in X])
    expected = 1.0 / (1.0 + np.exp(-margin))        # base_score 0.5 -> bias 0
    got = np.asarray(pred(X))
    np.testing.assert_allclose(got[:, 1], expected, atol=1e-5)
    np.testing.assert_allclose(got.sum(1), 1.0, atol=1e-6)


def test_base_score_bias():
    t = _tree([0], [0.0], [-1], [-1], [0])          # single leaf, value 0
    pred = predictor_from_xgboost_json(_model([t], "binary:logistic", 0.8))
    p = np.asarray(pred(np.zeros((1, 1), np.float32)))
    np.testing.assert_allclose(p[0, 1], 0.8, atol=1e-5)  # sigmoid(logit(0.8))


def test_strict_less_than_boundary(binary_model):
    """xgboost routes x < t left; a probe exactly AT a threshold must go
    right (the one-ulp threshold shift)."""

    model, trees = binary_model
    pred = predictor_from_xgboost_json(model)
    x = np.array([[0.5, 0.0, 0.0]], np.float32)     # x[0] == t0 root threshold
    margin = sum(_walk(t, x[0]) for t in trees)
    got = np.asarray(pred(x))
    np.testing.assert_allclose(got[0, 1], 1 / (1 + np.exp(-margin)), atol=1e-5)


def test_threshold_cast_rounds_strictly_below():
    """The x < t conversion must yield the largest f32 strictly below t even
    when the nearest f32 cast lands BELOW t already (no double-step) or
    ABOVE t (step down)."""

    for t, probe, expect_left in [
        (1.0 - 1e-12, 1.0, False),        # cast overshoots up; 1.0 !< t
        (1.0 + 1e-12, 1.0, True),         # cast undershoots; 1.0 < t
        (1.0, np.float32(np.nextafter(np.float32(1.0), np.float32(-np.inf))), True),
        (1.0, 1.0, False),                # boundary: 1.0 !< 1.0
    ]:
        tree = _tree([0, 0, 0], [t, 10.0, -10.0], [1, -1, -1], [2, -1, -1],
                     [0, 0, 0])
        pred = predictor_from_xgboost_json(_model([tree], "reg:squarederror", 0.0))
        got = float(np.asarray(pred(np.array([[probe]], np.float32)))[0, 0])
        assert got == (10.0 if expect_left else -10.0), (t, probe, got)


def test_missing_value_routing(binary_model):
    model, trees = binary_model
    pred = predictor_from_xgboost_json(model)
    X = np.array([[np.nan, 2.0, 0.0],
                  [0.1, np.nan, 5.0],
                  [np.nan, np.nan, np.nan]], np.float32)
    margin = np.array([sum(_walk(t, x) for t in trees) for x in X])
    got = np.asarray(pred(X))
    np.testing.assert_allclose(got[:, 1], 1 / (1 + np.exp(-margin)), atol=1e-5)


def test_multiclass_softprob():
    # 3 classes, one round: tree i contributes to class i (tree_info)
    trees = [_tree([0, 0, 0], [0.5, 0.3 * (k + 1), -0.1 * (k + 1)],
                   [1, -1, -1], [2, -1, -1], [0, 0, 0]) for k in range(3)]
    model = _model(trees, "multi:softprob", 0.5, num_class=3, tree_info=[0, 1, 2])
    pred = predictor_from_xgboost_json(model)
    assert pred.n_outputs == 3
    rng = np.random.default_rng(1)
    X = rng.normal(size=(32, 1)).astype(np.float32)
    margins = np.stack([[ _walk(t, x) for t in trees] for x in X])  # (n, 3)
    got = np.asarray(pred(X))
    np.testing.assert_allclose(got.sum(1), 1.0, atol=1e-6)
    # softmax over per-class margins + shared bias (cancels in softmax)
    e = np.exp(margins - margins.max(1, keepdims=True))
    np.testing.assert_allclose(got, e / e.sum(1, keepdims=True), atol=1e-5)


def test_regression_identity():
    t = _tree([0, 0, 0], [1.0, 2.5, -3.5], [1, -1, -1], [2, -1, -1], [0, 0, 0])
    model = _model([t], "reg:squarederror", 0.7)
    pred = predictor_from_xgboost_json(model)
    assert not pred.vector_out
    got = np.asarray(pred(np.array([[0.0], [2.0]], np.float32)))
    np.testing.assert_allclose(got[:, 0], [2.5 + 0.7, -3.5 + 0.7], atol=1e-5)


def test_categorical_split_declines():
    t = _tree([0, 0, 0], [0.5, 1.0, -1.0], [1, -1, -1], [2, -1, -1], [0, 0, 0])
    t["split_type"] = [1, 0, 0]                      # categorical root
    assert predictor_from_xgboost_json(_model([t], "binary:logistic", 0.5)) is None


def test_malformed_json_declines():
    assert predictor_from_xgboost_json({"learner": {}}) is None
    assert predictor_from_xgboost_json({}) is None


def test_malformed_tree_declines(binary_model):
    """A schema-drifted tree dict (missing fields) must decline, not raise."""

    model, _ = binary_model
    del model["learner"]["gradient_booster"]["model"]["trees"][0]["default_left"]
    assert predictor_from_xgboost_json(model) is None


def test_short_tree_info_declines():
    trees = [_tree([0, 0, 0], [0.5, 1.0, -1.0], [1, -1, -1], [2, -1, -1],
                   [0, 0, 0]) for _ in range(3)]
    model = _model(trees, "multi:softprob", 0.5, num_class=3, tree_info=[0])
    assert predictor_from_xgboost_json(model) is None


def test_unreproducible_objective_declines():
    t = _tree([0], [1.5], [-1], [-1], [0])
    for obj in ("reg:logistic", "count:poisson", "reg:gamma", "reg:tweedie"):
        assert predictor_from_xgboost_json(_model([t], obj, 0.5)) is None


def test_logitraw_base_score_is_logit_transformed():
    t = _tree([0], [0.0], [-1], [-1], [0])          # single leaf, value 0
    pred = predictor_from_xgboost_json(_model([t], "binary:logitraw", 0.8))
    got = np.asarray(pred(np.zeros((1, 1), np.float32)))
    np.testing.assert_allclose(got[0, 0], np.log(0.8 / 0.2), atol=1e-5)


def test_early_stopping_slices_trees(binary_model):
    """With best_iteration recorded, only the first best_iteration+1 rounds
    contribute — matching what booster.predict() does after early stopping."""

    model, trees = binary_model
    bm = model["learner"]["gradient_booster"]["model"]
    model["learner"]["attributes"] = {"best_iteration": "0"}
    bm["iteration_indptr"] = [0, 1, 2]               # one tree per round
    pred = predictor_from_xgboost_json(model)
    rng = np.random.default_rng(4)
    X = rng.normal(size=(32, 3)).astype(np.float32)
    margin = np.array([_walk(trees[0], x) for x in X])  # tree 1 dropped
    np.testing.assert_allclose(np.asarray(pred(X))[:, 1],
                               1 / (1 + np.exp(-margin)), atol=1e-5)


def test_early_stopping_without_indptr(binary_model):
    """Older JSON without iteration_indptr: rounds estimated from num_class
    and num_parallel_tree."""

    model, trees = binary_model
    model["learner"]["attributes"] = {"best_iteration": "0"}
    bm = model["learner"]["gradient_booster"]["model"]
    bm["gbtree_model_param"] = {"num_parallel_tree": "1"}
    pred = predictor_from_xgboost_json(model)
    X = np.zeros((4, 3), np.float32)
    margin = np.array([_walk(trees[0], x) for x in X])
    np.testing.assert_allclose(np.asarray(pred(X))[:, 1],
                               1 / (1 + np.exp(-margin)), atol=1e-5)


def test_explain_end_to_end_from_json(binary_model):
    """The parsed predictor drives the full KernelShap pipeline."""

    from distributedkernelshap_tpu import KernelShap

    model, _ = binary_model
    pred = predictor_from_xgboost_json(model)
    rng = np.random.default_rng(2)
    bg = rng.normal(size=(30, 3)).astype(np.float32)
    Xe = rng.normal(size=(12, 3)).astype(np.float32)
    ex = KernelShap(pred, link="logit", seed=0)
    ex.fit(bg)
    res = ex.explain(Xe, silent=True)
    proba = np.clip(np.asarray(pred(Xe)), 1e-7, 1 - 1e-7)
    for k, phi in enumerate(res.shap_values):
        lhs = phi.sum(axis=1) + res.expected_value[k]
        rhs = np.log(proba[:, k] / (1 - proba[:, k]))
        np.testing.assert_allclose(lhs, rhs, atol=5e-3)
