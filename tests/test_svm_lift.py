"""Native lifting of sklearn SVMs (models/svm.py).

Binary SVC decision_function and SVR predict are exact kernel expansions
over the support vectors — lifted as one Gram matmul + elementwise kernel
map.  Platt-scaled predict_proba and multiclass one-vs-one aggregation are
NOT deterministic functions of the lifted surface and must fall back.
"""

import numpy as np
import pytest

from distributedkernelshap_tpu.models import (
    CallbackPredictor,
    LinearPredictor,
    SVMPredictor,
    as_predictor,
)
from distributedkernelshap_tpu.models.svm import lift_svm


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    X = rng.normal(size=(250, 5))
    y = (X[:, 0] + 0.4 * X[:, 1] ** 2 > 0.2).astype(int)
    yr = np.sin(X[:, 0]) + 0.1 * X[:, 1]
    return X, y, yr


def _check(method, X, atol=2e-5):
    lifted = lift_svm(method)
    assert lifted is not None
    expected = np.asarray(method(X), dtype=np.float64)
    if expected.ndim == 1:
        expected = expected[:, None]
    got = np.asarray(lifted(X.astype(np.float32)), dtype=np.float64)
    scale = max(1.0, np.abs(expected).max())
    np.testing.assert_allclose(got, expected, atol=atol * scale)
    return lifted


@pytest.mark.parametrize("kernel", ["rbf", "poly", "sigmoid"])
def test_svc_decision_function(data, kernel):
    from sklearn.svm import SVC

    X, y, _ = data
    clf = SVC(kernel=kernel, random_state=0).fit(X, y)
    lifted = _check(clf.decision_function, X[:64])
    assert lifted.kernel == kernel and not lifted.vector_out


@pytest.mark.parametrize("kernel", ["rbf", "poly", "sigmoid"])
def test_svr_predict(data, kernel):
    from sklearn.svm import SVR

    X, _, yr = data
    reg = SVR(kernel=kernel).fit(X, yr)
    _check(reg.predict, X[:64])


def test_nusvr_predict(data):
    from sklearn.svm import NuSVR

    X, _, yr = data
    reg = NuSVR(kernel="rbf").fit(X, yr)
    _check(reg.predict, X[:64])


def test_linear_kernel_svc_uses_linear_lift(data):
    """Linear-kernel SVC exposes coef_ and hits the (exact, simpler)
    LinearPredictor lift before the SVM lift."""

    from sklearn.svm import SVC

    X, y, _ = data
    clf = SVC(kernel="linear", random_state=0).fit(X, y)
    pred = as_predictor(clf.decision_function, example_dim=X.shape[1])
    assert isinstance(pred, LinearPredictor)


def test_multiclass_svc_not_lifted(data):
    from sklearn.svm import SVC

    X, y, _ = data
    y3 = y + (X[:, 2] > 1).astype(int)
    clf = SVC(kernel="rbf", random_state=0).fit(X, y3)
    assert lift_svm(clf.decision_function) is None


def test_svc_label_predict_not_lifted(data):
    from sklearn.svm import SVC

    X, y, _ = data
    clf = SVC(kernel="rbf", random_state=0).fit(X, y)
    assert lift_svm(clf.predict) is None


def test_platt_proba_falls_back_to_host(data):
    """predict_proba (libsvm internal-CV Platt scaling) is not liftable; it
    must land on the host-callback path, not a wrong device lift."""

    import warnings

    from sklearn.svm import SVC

    X, y, _ = data
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", FutureWarning)
        clf = SVC(kernel="rbf", probability=True, random_state=0).fit(X, y)
        pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, CallbackPredictor)


def test_sparse_fitted_svm_falls_back(data):
    """SVMs fit on sparse input store sparse internals; the lift must fall
    back (or densify), not crash as_predictor."""

    import scipy.sparse as sp
    from sklearn.svm import SVC

    X, y, _ = data
    clf = SVC(kernel="rbf", random_state=0).fit(sp.csr_matrix(X), y)
    pred = as_predictor(clf.decision_function, example_dim=X.shape[1])
    expected = clf.decision_function(X[:16])
    got = np.asarray(pred(X[:16].astype(np.float32))).ravel()
    np.testing.assert_allclose(got, expected, atol=1e-4)


def test_unfitted_svm_returns_none(data):
    from sklearn.svm import SVC

    assert lift_svm(SVC(kernel="rbf").decision_function) is None


def test_as_predictor_routes_svm(data):
    from sklearn.svm import SVC

    X, y, _ = data
    clf = SVC(kernel="rbf", random_state=0).fit(X, y)
    pred = as_predictor(clf.decision_function, example_dim=X.shape[1])
    assert isinstance(pred, SVMPredictor)


@pytest.mark.parametrize("kernel", ["rbf", "linear", "poly", "sigmoid"])
def test_masked_ey_matches_row_eval(data, kernel):
    """The separable masked evaluation equals materialising every synthetic
    row, for every kernel, with and without grouping."""

    from sklearn.svm import SVC

    from distributedkernelshap_tpu.ops.coalitions import coalition_plan
    from distributedkernelshap_tpu.ops.explain import _ey_generic, groups_to_matrix

    X, y, _ = data
    clf = SVC(kernel=kernel, random_state=0).fit(X, y)
    pred = lift_svm(clf.decision_function)
    assert pred.supports_masked_ey
    for groups in (None, [[0, 1], [2], [3, 4]]):
        G = groups_to_matrix(groups, X.shape[1])
        plan = coalition_plan(G.shape[0], nsamples=30, seed=0)
        Xe = X[:9].astype(np.float32)
        bg = X[100:117].astype(np.float32)
        bgw = np.full(bg.shape[0], 1.0 / bg.shape[0], np.float32)
        mask = np.asarray(plan.mask, np.float32)
        ey_rows = np.asarray(_ey_generic(pred, Xe, bg, bgw, mask @ G, chunk=8))
        ey_fast = np.asarray(pred.masked_ey(Xe, bg, bgw, mask, G))
        scale = max(1.0, np.abs(ey_rows).max())
        np.testing.assert_allclose(ey_fast, ey_rows, atol=2e-4 * scale)


def test_masked_ey_tiny_chunks(data):
    from sklearn.svm import SVC

    from distributedkernelshap_tpu.ops.coalitions import coalition_plan
    from distributedkernelshap_tpu.ops.explain import groups_to_matrix

    X, y, _ = data
    clf = SVC(kernel="rbf", random_state=0).fit(X, y)
    pred = lift_svm(clf.decision_function)
    G = groups_to_matrix(None, X.shape[1])
    plan = coalition_plan(G.shape[0], nsamples=22, seed=0)
    Xe = X[:7].astype(np.float32)
    bg = X[100:113].astype(np.float32)
    bgw = np.full(bg.shape[0], 1.0 / bg.shape[0], np.float32)
    mask = np.asarray(plan.mask, np.float32)
    big = np.asarray(pred.masked_ey(Xe, bg, bgw, mask, G))
    tiny = np.asarray(pred.masked_ey(Xe, bg, bgw, mask, G,
                                     target_chunk_elems=1 << 9))
    np.testing.assert_allclose(tiny, big, atol=1e-5)


def test_kernel_shap_end_to_end_svm(data):
    """Full explain over a lifted RBF SVM: additivity in identity link
    (decision_function is a margin, not a probability)."""

    from sklearn.svm import SVC

    from distributedkernelshap_tpu import KernelShap

    X, y, _ = data
    clf = SVC(kernel="rbf", random_state=0).fit(X, y)
    ex = KernelShap(clf.decision_function, seed=0)
    ex.fit(X[:40])
    assert isinstance(ex._explainer.predictor, SVMPredictor)
    Xe = X[40:56]
    res = ex.explain(Xe, silent=True)
    phi = np.asarray(res.shap_values[0] if isinstance(res.shap_values, list)
                     else res.shap_values)
    lhs = phi.sum(axis=1) + np.ravel(res.expected_value)[0]
    np.testing.assert_allclose(lhs, clf.decision_function(Xe), atol=5e-3)
