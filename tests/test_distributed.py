"""Tests for the mesh-sharded distributed layer (8 virtual CPU devices —
the TPU-native analog of the reference's Ray local mode, SURVEY.md §4)."""

import numpy as np
import pytest

import jax

from distributedkernelshap_tpu import DenseData, KernelShap
from distributedkernelshap_tpu.kernel_shap import KernelExplainerEngine
from distributedkernelshap_tpu.models import LinearPredictor
from distributedkernelshap_tpu.parallel.distributed import (
    DistributedExplainer,
    invert_permutation,
    kernel_shap_postprocess_fn,
    kernel_shap_target_fn,
)
from distributedkernelshap_tpu.parallel.mesh import device_mesh, pad_to_multiple


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(0)
    D, K, N, B = 11, 2, 20, 24
    groups = [[0], [1], [2, 3, 4], [5, 6], [7, 8, 9, 10]]
    group_names = ["a", "b", "c", "d", "e"]
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)
    pred = LinearPredictor(W, b, activation="softmax")
    data = DenseData(bg, group_names, groups)
    return dict(pred=pred, data=data, X=X, groups=groups, group_names=group_names, bg=bg)


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_invert_permutation():
    p = [3, 0, 2, 1]
    s = invert_permutation(p)
    np.testing.assert_array_equal(s, [1, 3, 2, 0])
    np.testing.assert_array_equal(np.asarray(p)[s], np.arange(4))


def test_postprocess_single_output():
    parts = [np.ones((2, 3)), 2 * np.ones((3, 3))]
    out = kernel_shap_postprocess_fn(parts)
    assert out.shape == (5, 3) and out[2:].mean() == 2.0


def test_postprocess_multi_output():
    parts = [[np.ones((2, 3)), np.zeros((2, 3))], [2 * np.ones((1, 3)), np.zeros((1, 3))]]
    out = kernel_shap_postprocess_fn(parts)
    assert len(out) == 2 and out[0].shape == (3, 3)
    assert out[0][-1, 0] == 2.0


def test_target_fn_dispatch(setup):
    engine = KernelExplainerEngine(setup["pred"], setup["data"], link="logit", seed=0)
    idx, sv = kernel_shap_target_fn(engine, (3, setup["X"][:2]), {"nsamples": 32})
    assert idx == 3 and sv[0].shape == (2, 5)


def test_mesh_shapes():
    mesh = device_mesh(8)
    assert mesh.shape == {"data": 8, "coalition": 1}
    mesh2 = device_mesh(8, coalition_parallel=2)
    assert mesh2.shape == {"data": 4, "coalition": 2}
    with pytest.raises(ValueError):
        device_mesh(6, coalition_parallel=4)
    assert pad_to_multiple(10, 8) == (16, 6)
    assert pad_to_multiple(16, 8) == (16, 0)


def test_distributed_matches_sequential(setup):
    seq = KernelExplainerEngine(setup["pred"], setup["data"], link="logit", seed=0)
    sv_seq = seq.get_explanation(setup["X"], nsamples=64)

    dist = DistributedExplainer(
        {"n_devices": 8, "batch_size": None, "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    sv_dist = dist.get_explanation(setup["X"], nsamples=64)
    assert len(sv_dist) == 2
    np.testing.assert_allclose(sv_dist[0], sv_seq[0], atol=1e-5)
    np.testing.assert_allclose(sv_dist[1], sv_seq[1], atol=1e-5)


def test_distributed_batch_size_slabs(setup):
    dist = DistributedExplainer(
        {"n_devices": 8, "batch_size": 2, "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    # 24 rows -> slabs of 16, padded to 32
    sv = dist.get_explanation(setup["X"], nsamples=64)
    seq = KernelExplainerEngine(setup["pred"], setup["data"], link="logit", seed=0)
    sv_seq = seq.get_explanation(setup["X"], nsamples=64)
    np.testing.assert_allclose(sv[0], sv_seq[0], atol=1e-5)


def test_distributed_f16_transfer_and_window(setup):
    """The sharded slab pipeline honours dispatch_window and the opt-in
    f16 result transfer; results stay float32 on the host and match the
    f32 path to f16 rounding."""

    from distributedkernelshap_tpu.kernel_shap import EngineConfig
    from distributedkernelshap_tpu.ops.explain import ShapConfig

    seq = KernelExplainerEngine(setup["pred"], setup["data"], link="logit", seed=0)
    sv_seq = seq.get_explanation(setup["X"], nsamples=64)

    dist = DistributedExplainer(
        {"n_devices": 8, "batch_size": 1, "dispatch_window": 2,
         "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0,
         "config": EngineConfig(shap=ShapConfig(transfer_dtype="float16"))},
    )
    assert dist.dispatch_window == 2
    sv = dist.get_explanation(setup["X"], nsamples=64)
    for a, b in zip(sv_seq, sv):
        assert np.asarray(b).dtype == np.float32
        # f16 rounding is relative (~5e-4 of |phi|): pair rtol with atol
        np.testing.assert_allclose(a, b, atol=1e-3, rtol=2e-3)
    assert dist.last_raw_prediction.dtype == np.float32


def test_distributed_batch_fits_one_slab(setup):
    """batch_size >= B must not pad the batch up to batch_size * n_devices
    (that multiplied the work by up to n_devices): it runs as one sharded
    call and still matches the sequential result."""

    dist = DistributedExplainer(
        {"n_devices": 8, "batch_size": 64, "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    # B=24 < slab=64*8: single call, no slab padding
    sv = dist.get_explanation(setup["X"], nsamples=64)
    seq = KernelExplainerEngine(setup["pred"], setup["data"], link="logit", seed=0)
    sv_seq = seq.get_explanation(setup["X"], nsamples=64)
    np.testing.assert_allclose(sv[0], sv_seq[0], atol=1e-5)
    assert sv[0].shape == sv_seq[0].shape


def test_distributed_ragged_batch(setup):
    dist = DistributedExplainer(
        {"n_devices": 8, "batch_size": None, "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    # 13 rows is not divisible by 8: exercises padding
    sv = dist.get_explanation(setup["X"][:13], nsamples=64)
    assert sv[0].shape == (13, 5)


def test_coalition_parallel_matches(setup):
    seq = KernelExplainerEngine(setup["pred"], setup["data"], link="logit", seed=0)
    sv_seq = seq.get_explanation(setup["X"], nsamples=64)

    dist = DistributedExplainer(
        {"n_devices": 8, "batch_size": None, "coalition_parallel": 2,
         "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    assert dist.mesh.shape == {"data": 4, "coalition": 2}
    sv = dist.get_explanation(setup["X"], nsamples=64)
    np.testing.assert_allclose(sv[0], sv_seq[0], atol=1e-5)
    np.testing.assert_allclose(sv[1], sv_seq[1], atol=1e-5)


def test_shardmap_pallas_matches_gspmd(setup):
    """The default multi-chip path (shard_map carrying the pallas fast path,
    interpret mode on this CPU mesh) must agree with the GSPMD
    jit-with-shardings path — i.e. the sharded production path runs the same
    kernel the single-chip benchmark measured (VERDICT r1 #3)."""

    from distributedkernelshap_tpu.kernel_shap import EngineConfig
    from distributedkernelshap_tpu.ops.explain import ShapConfig

    pallas_cfg = EngineConfig(link="logit",
                              shap=ShapConfig(link="logit", use_pallas=True))
    dist = DistributedExplainer(
        {"n_devices": 8, "batch_size": None, "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"seed": 0, "config": pallas_cfg},
    )
    assert dist.partitioning == "shard_map"
    sv = dist.get_explanation(setup["X"], nsamples=64)

    gspmd = DistributedExplainer(
        {"n_devices": 8, "batch_size": None, "algorithm": "kernel_shap",
         "partitioning": "gspmd"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    sv_g = gspmd.get_explanation(setup["X"], nsamples=64)
    np.testing.assert_allclose(sv[0], sv_g[0], atol=1e-5)
    np.testing.assert_allclose(sv[1], sv_g[1], atol=1e-5)


def test_actor_cpu_fraction_maps_to_coalition_parallel(setup):
    """The reference's packing knob (one actor spanning f CPUs) maps onto f
    devices co-operating per batch; results still match sequential."""

    dist = DistributedExplainer(
        {"n_devices": 8, "batch_size": None, "actor_cpu_fraction": 2.0,
         "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    assert dist.coalition_parallel == 2
    assert dist.mesh.shape == {"data": 4, "coalition": 2}
    sv = dist.get_explanation(setup["X"], nsamples=64)
    seq = KernelExplainerEngine(setup["pred"], setup["data"], link="logit", seed=0)
    sv_seq = seq.get_explanation(setup["X"], nsamples=64)
    np.testing.assert_allclose(sv[0], sv_seq[0], atol=1e-5)


def test_actor_cpu_fraction_subunit_warns_and_ignores(setup, caplog):
    import logging

    with caplog.at_level(logging.WARNING,
                         logger="distributedkernelshap_tpu.parallel.distributed"):
        dist = DistributedExplainer(
            {"n_devices": 8, "batch_size": None, "actor_cpu_fraction": 0.25,
             "algorithm": "kernel_shap"},
            KernelExplainerEngine,
            (setup["pred"], setup["data"]),
            {"link": "logit", "seed": 0},
        )
    assert dist.coalition_parallel == 1
    assert any("actor_cpu_fraction" in rec.message for rec in caplog.records)
    # a whole fraction that does not divide the device count degrades with a
    # warning (the reference's knob floors n_actors = n_cpus // frac — it
    # never hard-fails); an explicit coalition_parallel still raises
    with caplog.at_level(logging.WARNING,
                         logger="distributedkernelshap_tpu.parallel.distributed"):
        d3 = DistributedExplainer(
            {"n_devices": 8, "actor_cpu_fraction": 3.0, "algorithm": "kernel_shap"},
            KernelExplainerEngine,
            (setup["pred"], setup["data"]),
            {"link": "logit", "seed": 0},
        )
    assert d3.coalition_parallel == 1
    with pytest.raises(ValueError):
        DistributedExplainer(
            {"n_devices": 8, "coalition_parallel": 3, "algorithm": "kernel_shap"},
            KernelExplainerEngine, (setup["pred"], setup["data"]),
            {"link": "logit", "seed": 0})
    with pytest.raises(ValueError):
        DistributedExplainer(
            {"n_devices": 8, "partitioning": "gpsmd", "algorithm": "kernel_shap"},
            KernelExplainerEngine, (setup["pred"], setup["data"]),
            {"link": "logit", "seed": 0})
    # an explicit coalition_parallel always wins over the alias
    dist2 = DistributedExplainer(
        {"n_devices": 8, "coalition_parallel": 4, "actor_cpu_fraction": 2.0,
         "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    assert dist2.coalition_parallel == 4


def test_attribute_proxy(setup):
    dist = DistributedExplainer(
        {"n_devices": 4, "batch_size": None, "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    assert dist.vector_out is True
    assert np.asarray(dist.expected_value).shape == (2,)
    assert dist.return_attribute("M") == 5


def test_kernel_shap_distributed_end_to_end(setup):
    # the reference call shape: distributed_opts with the n_cpus spelling
    explainer = KernelShap(setup["pred"], link="logit",
                           feature_names=setup["group_names"],
                           distributed_opts={"n_cpus": 8, "batch_size": None}, seed=0)
    explainer.fit(setup["bg"], group_names=setup["group_names"], groups=setup["groups"])
    explanation = explainer.explain(setup["X"], silent=True, nsamples=64)
    sv = explanation.shap_values
    assert sv[0].shape == (24, 5)
    total = np.stack(sv, 1).sum(-1) + np.asarray(explanation.expected_value)[None]
    np.testing.assert_allclose(total, explanation.data["raw"]["raw_prediction"], atol=1e-4)

    seq = KernelShap(setup["pred"], link="logit", seed=0)
    seq.fit(setup["bg"], group_names=setup["group_names"], groups=setup["groups"])
    sv_seq = seq.explain(setup["X"], silent=True, nsamples=64).shap_values
    np.testing.assert_allclose(sv[0], sv_seq[0], atol=1e-5)


def test_graft_entry_single_and_multichip():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert np.asarray(out["shap_values"]).shape == (8, 2, 6)
    ge.dryrun_multichip(8)
    ge.dryrun_multichip(4)
    ge.dryrun_multichip(1)
    # awkward counts: data axis 3 (coalition 2) must still divide the batch
    ge.dryrun_multichip(6)
    ge.dryrun_multichip(3)


def test_mesh_async_dispatch_matches_sync():
    """DistributedExplainer.get_explanation_async (round 4: true pipelining
    on single-process meshes) must match the synchronous sharded path, and
    the fallback matrix (slab-split, l1-active, exact) must close over the
    sync results."""

    import numpy as np

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models import LinearPredictor

    rng = np.random.default_rng(4)
    D, K, N, B = 7, 2, 12, 16
    W = rng.normal(size=(D, K)).astype(np.float32)
    pred = LinearPredictor(W, np.zeros(K, np.float32), activation="softmax")
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)

    ex = KernelShap(pred, link="identity", seed=0,
                    distributed_opts={"n_devices": 4})
    ex.fit(bg)
    dist = ex._explainer
    want = dist.get_explanation(X, nsamples=64, l1_reg=False)
    values, info = dist.get_explanation_async(X, nsamples=64,
                                              l1_reg=False)()
    for a, b in zip(want, values):
        np.testing.assert_allclose(a, b, atol=1e-6)
    assert info["raw_prediction"].shape == (B, K)
    assert info["expected_value"].shape == (K,)

    # slab-split fallback (batch_size forces multiple slabs): same contract
    ex2 = KernelShap(pred, link="identity", seed=0,
                     distributed_opts={"n_devices": 4, "batch_size": 2})
    ex2.fit(bg)
    dist2 = ex2._explainer
    want2 = dist2.get_explanation(X, nsamples=64, l1_reg=False)
    values2, _ = dist2.get_explanation_async(X, nsamples=64, l1_reg=False)()
    for a, b in zip(want2, values2):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_mesh_serving_pipelines_and_aligns():
    """Serving over a single-process mesh now pipelines (the model exposes
    explain_batch_async through DistributedExplainer): concurrent single-row
    requests must come back aligned with their instances and match direct
    explains."""

    import json as _json

    import numpy as np

    from distributedkernelshap_tpu.models import LinearPredictor
    from distributedkernelshap_tpu.serving import (
        KernelShapModel,
        distribute_requests,
        serve_explainer,
    )

    rng = np.random.default_rng(6)
    D, K, N = 6, 2, 10
    W = rng.normal(size=(D, K)).astype(np.float32)
    pred = LinearPredictor(W, np.zeros(K, np.float32), activation="softmax")
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(12, D)).astype(np.float32)
    ctor = {"link": "logit", "seed": 0,
            "distributed_opts": {"n_devices": 4}}

    srv = serve_explainer(pred, bg, ctor, {}, host="127.0.0.1", port=0,
                          max_batch_size=1, pipeline_depth=4)
    try:
        # PROVE the pipelined path engages (not the old synchronous
        # degrade, which fetched before returning): after dispatch the
        # fetch must not have happened yet; calling finalize performs it
        from distributedkernelshap_tpu.parallel.distributed import (
            DistributedExplainer,
        )

        fetches = {"n": 0}
        real_fetch = DistributedExplainer._fetch_sharded

        def counting_fetch(self, dispatched):
            fetches["n"] += 1
            return real_fetch(self, dispatched)

        DistributedExplainer._fetch_sharded = counting_fetch
        try:
            fin = srv.model.explain_batch_async(X[:1], split_sizes=[1])
            assert fetches["n"] == 0, "async dispatch must not fetch eagerly"
            payload = fin()[0]
            assert fetches["n"] == 1
            import json as _json2

            assert _json2.loads(payload)["data"]["shap_values"]
        finally:
            DistributedExplainer._fetch_sharded = real_fetch
        payloads = distribute_requests(
            f"http://127.0.0.1:{srv.port}/explain", X, max_workers=8)
        ref = KernelShapModel(pred, bg, ctor, {})
        for i, p in enumerate(payloads):
            got = np.asarray(_json.loads(p)["data"]["shap_values"])[:, 0, :]
            want = ref.explainer.explain(X[i:i + 1], silent=True).shap_values
            np.testing.assert_allclose(
                got, np.stack([v[0] for v in want]), atol=1e-5)
    finally:
        srv.stop()


def test_replicate_results_matches_sharded_output():
    """distributed_opts['replicate_results']: the in-program all-gather
    variant must produce identical phi/f(x) to the default data-sharded
    output, on both partitioning paths, and enable async dispatch."""

    import numpy as np

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models import LinearPredictor

    rng = np.random.default_rng(8)
    D, K, N, B = 6, 2, 10, 12
    W = rng.normal(size=(D, K)).astype(np.float32)
    pred = LinearPredictor(W, np.zeros(K, np.float32), activation="softmax")
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)

    def run(**extra):
        ex = KernelShap(pred, link="identity", seed=0,
                        distributed_opts={"n_devices": 4, **extra})
        ex.fit(bg)
        return ex, ex.explain(X, silent=True, nsamples=64,
                              l1_reg=False).shap_values

    _, want = run()
    for opts in ({"replicate_results": True},
                 {"replicate_results": True, "partitioning": "gspmd"}):
        ex, got = run(**opts)
        for a, b in zip(want, got):
            np.testing.assert_allclose(a, b, atol=1e-6)
        assert ex._explainer.replicate_results
        values, _ = ex._explainer.get_explanation_async(
            X, nsamples=64, l1_reg=False)()
        for a, b in zip(want, values):
            np.testing.assert_allclose(a, b, atol=1e-6)


def test_kernel_path_recorded_on_sharded_paths(setup):
    """VERDICT r4 #2 on the DISTRIBUTED paths: every trace-bearing dispatch
    (the sharded explain AND get_importance's direct fn loop) must record
    which evaluation kernel engaged, surfaced via the engine proxy."""

    dist = DistributedExplainer(
        {"n_devices": 8, "batch_size": None, "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    dist.get_explanation(setup["X"], nsamples=64)
    kp = dist.kernel_path  # proxies to the engine via __getattr__
    assert kp.get("ey") in ("pallas", "einsum"), kp  # linear predictor path
    assert kp["pallas_degrades"] == 0

    # a fresh explainer exercising ONLY get_importance must record too
    # (it traces fn directly, outside _dispatch_call)
    dist2 = DistributedExplainer(
        {"n_devices": 8, "batch_size": None, "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (setup["pred"], setup["data"]),
        {"link": "logit", "seed": 0},
    )
    dist2.get_importance(setup["X"], nsamples=64)
    assert dist2.kernel_path.get("ey") in ("pallas", "einsum"), \
        dist2.kernel_path
