"""Composite-estimator lifting (models/compose.py): Pipelines, soft voting,
and CalibratedClassifierCV, each verified against sklearn's own outputs on
f32-representable inputs and through the full explain pipeline."""

import warnings

import numpy as np
import pytest

from distributedkernelshap_tpu.models import (
    CalibratedBinaryPredictor,
    CallbackPredictor,
    MeanEnsemblePredictor,
    PipelinePredictor,
    as_predictor,
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(21)
    X = (rng.normal(size=(300, 6)) * np.array([1, 5, 0.2, 3, 1, 10])
         + np.array([0, 2, -1, 0, 4, -3]))
    y = (X[:, 0] + 0.3 * X[:, 1] - 0.05 * X[:, 5] > 1).astype(int)
    yr = X[:, 0] * 2.0 - X[:, 3] + rng.normal(size=300)
    return X, y, yr


def _quant(X):
    return X.astype(np.float32).astype(np.float64)


def _check(pred, method, X, atol=5e-5):
    Xq = _quant(X)
    expected = np.asarray(method(Xq), dtype=np.float64)
    if expected.ndim == 1:
        expected = expected[:, None]
    got = np.asarray(pred(Xq.astype(np.float32)), dtype=np.float64)
    scale = max(1.0, np.abs(expected).max())
    np.testing.assert_allclose(got, expected, atol=atol * scale)


@pytest.mark.parametrize("scaler_name", ["standard", "minmax", "maxabs", "robust"])
def test_pipeline_scaler_plus_lr(data, scaler_name):
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import (
        MaxAbsScaler,
        MinMaxScaler,
        RobustScaler,
        StandardScaler,
    )

    from distributedkernelshap_tpu.models import LinearPredictor

    scaler = {"standard": StandardScaler(), "minmax": MinMaxScaler(),
              "maxabs": MaxAbsScaler(), "robust": RobustScaler()}[scaler_name]
    X, y, _ = data
    pipe = Pipeline([("sc", scaler), ("lr", LogisticRegression())]).fit(X, y)
    pred = as_predictor(pipe.predict_proba, example_dim=X.shape[1])
    # affine + linear folds into ONE LinearPredictor -> MXU einsum fast path
    assert isinstance(pred, LinearPredictor)
    _check(pred, pipe.predict_proba, X[:64])


def test_pipeline_pca_then_svm(data):
    from sklearn.decomposition import PCA
    from sklearn.pipeline import Pipeline
    from sklearn.svm import SVC

    X, y, _ = data
    pipe = Pipeline([("sc", __import__("sklearn.preprocessing", fromlist=["StandardScaler"]).StandardScaler()),
                     ("pca", PCA(n_components=4)),
                     ("svc", SVC(kernel="rbf"))]).fit(X, y)
    pred = as_predictor(pipe.decision_function, example_dim=X.shape[1])
    assert isinstance(pred, PipelinePredictor)
    _check(pred, pipe.decision_function, X[:64])


def test_pipeline_imputer(data):
    from sklearn.impute import SimpleImputer
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import Pipeline

    X, y, _ = data
    Xm = X.copy()
    Xm[::5, 1] = np.nan
    pipe = Pipeline([("imp", SimpleImputer(strategy="median")),
                     ("lr", LogisticRegression())]).fit(Xm, y)
    pred = as_predictor(pipe.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, PipelinePredictor)
    _check(pred, pipe.predict_proba, Xm[:64])


def test_pipeline_whitened_pca_regressor(data):
    from sklearn.decomposition import PCA
    from sklearn.linear_model import LinearRegression
    from sklearn.pipeline import Pipeline

    X, _, yr = data
    from distributedkernelshap_tpu.models import LinearPredictor

    pipe = Pipeline([("pca", PCA(n_components=5, whiten=True)),
                     ("lin", LinearRegression())]).fit(X, yr)
    pred = as_predictor(pipe.predict, example_dim=X.shape[1])
    assert isinstance(pred, LinearPredictor)   # linear ∘ linear folds
    _check(pred, pipe.predict, X[:64])


def test_minmax_clip_is_reproduced(data):
    """MinMaxScaler(clip=True) must clip out-of-range inputs like sklearn —
    including values beyond the fitted range, which the probe never sees."""

    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import MinMaxScaler

    X, y, _ = data
    pipe = Pipeline([("sc", MinMaxScaler(clip=True)),
                     ("lr", LogisticRegression())]).fit(X, y)
    pred = as_predictor(pipe.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, PipelinePredictor)  # clip stage blocks folding
    X_ood = X[:16] * 25.0 + 40.0                 # far outside the fitted range
    _check(pred, pipe.predict_proba, X_ood)


def test_voting_with_dropped_member(data):
    """weights pair with NON-dropped members (sklearn _weights_not_none)."""

    from sklearn.ensemble import VotingClassifier
    from sklearn.linear_model import LogisticRegression
    from sklearn.tree import DecisionTreeClassifier

    X, y, _ = data
    clf = VotingClassifier(
        [("lr", LogisticRegression()), ("drop_me", "drop"),
         ("dt", DecisionTreeClassifier(max_depth=3, random_state=0))],
        voting="soft", weights=[2.0, 5.0, 1.0]).fit(X, y)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, MeanEnsemblePredictor)
    assert len(pred.members) == 2
    _check(pred, clf.predict_proba, X[:64])


def test_pipeline_unknown_step_falls_back(data):
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import Normalizer

    X, y, _ = data
    pipe = Pipeline([("norm", Normalizer()),        # row-dependent: not lifted
                     ("lr", LogisticRegression())]).fit(X, y)
    pred = as_predictor(pipe.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, CallbackPredictor)


def test_voting_soft(data):
    from sklearn.ensemble import GradientBoostingClassifier, VotingClassifier
    from sklearn.linear_model import LogisticRegression

    X, y, _ = data
    clf = VotingClassifier(
        [("lr", LogisticRegression()),
         ("gb", GradientBoostingClassifier(n_estimators=10, random_state=0))],
        voting="soft", weights=[2.0, 1.0]).fit(X, y)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, MeanEnsemblePredictor)
    _check(pred, clf.predict_proba, X[:64])


def test_voting_hard_falls_back(data):
    from sklearn.ensemble import VotingClassifier
    from sklearn.linear_model import LogisticRegression
    from sklearn.tree import DecisionTreeClassifier

    X, y, _ = data
    clf = VotingClassifier([("lr", LogisticRegression()),
                            ("dt", DecisionTreeClassifier(max_depth=3))],
                           voting="hard").fit(X, y)
    pred = as_predictor(clf.predict, example_dim=X.shape[1])
    assert isinstance(pred, CallbackPredictor)


def test_voting_regressor(data):
    from sklearn.ensemble import VotingRegressor
    from sklearn.linear_model import LinearRegression
    from sklearn.tree import DecisionTreeRegressor

    X, _, yr = data
    reg = VotingRegressor([("lin", LinearRegression()),
                           ("dt", DecisionTreeRegressor(max_depth=4))]).fit(X, yr)
    pred = as_predictor(reg.predict, example_dim=X.shape[1])
    assert isinstance(pred, MeanEnsemblePredictor)
    _check(pred, reg.predict, X[:64])


def test_bagging_classifier_with_feature_subsets(data):
    """Bagged trees on bootstrap feature subsets lift: each member gets a
    'select' stage; the mean matches sklearn."""

    from sklearn.ensemble import BaggingClassifier

    X, y, _ = data
    clf = BaggingClassifier(n_estimators=7, max_features=0.5,
                            bootstrap_features=True, random_state=0).fit(X, y)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, MeanEnsemblePredictor)
    assert any(isinstance(m, PipelinePredictor) for m in pred.members)
    _check(pred, clf.predict_proba, X[:64])


def test_bagging_regressor(data):
    from sklearn.ensemble import BaggingRegressor

    X, _, yr = data
    reg = BaggingRegressor(n_estimators=5, max_features=4,
                           random_state=0).fit(X, yr)
    pred = as_predictor(reg.predict, example_dim=X.shape[1])
    assert isinstance(pred, MeanEnsemblePredictor)
    _check(pred, reg.predict, X[:64])


def test_bagging_forwards_masked_ey(data):
    """Feature-subset members still ride the masked fast path (the select
    stage re-indexes the group matrix); phi matches row evaluation."""

    from sklearn.ensemble import BaggingClassifier

    from distributedkernelshap_tpu import KernelShap

    X, y, _ = data
    clf = BaggingClassifier(n_estimators=5, max_features=0.7,
                            bootstrap_features=True, random_state=0).fit(X, y)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert pred.supports_masked_ey

    Xq = _quant(X)
    ex_fast = KernelShap(clf.predict_proba, link="logit", seed=0)
    ex_fast.fit(Xq[:30])
    phi_fast = ex_fast.explain(Xq[200:210], silent=True).shap_values

    slow = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    for m in slow.members:
        inner = m.inner if isinstance(m, PipelinePredictor) else m
        inner.path_sign = None
    assert not slow.supports_masked_ey
    ex_slow = KernelShap(slow, link="logit", seed=0)
    ex_slow.fit(Xq[:30])
    phi_slow = ex_slow.explain(Xq[200:210], silent=True).shap_values
    for a, b in zip(phi_fast, phi_slow):
        np.testing.assert_allclose(a, b, atol=5e-4)


def test_ovr_multiclass(data):
    from sklearn.linear_model import LogisticRegression
    from sklearn.multiclass import OneVsRestClassifier

    from distributedkernelshap_tpu.models import OneVsRestPredictor

    X, y, _ = data
    y3 = y + (X[:, 3] > 2).astype(int)
    clf = OneVsRestClassifier(LogisticRegression()).fit(X, y3)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, OneVsRestPredictor) and pred.n_outputs == 3
    _check(pred, clf.predict_proba, X[:64], atol=1e-4)


def test_ovr_multilabel_unnormalised(data):
    """Multilabel OvR: per-label sigmoids, no row normalisation — and the
    memberwise-linear composition forwards the masked fast path."""

    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.multiclass import OneVsRestClassifier

    from distributedkernelshap_tpu.models import OneVsRestPredictor
    from distributedkernelshap_tpu.ops.coalitions import coalition_plan
    from distributedkernelshap_tpu.ops.explain import _ey_generic, groups_to_matrix

    X, y, _ = data
    Y = np.stack([(y > 0).astype(int), (X[:, 3] > 2).astype(int)], axis=1)
    clf = OneVsRestClassifier(GradientBoostingClassifier(
        n_estimators=5, random_state=0)).fit(X, Y)
    assert clf.multilabel_
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, OneVsRestPredictor) and not pred.normalise
    _check(pred, clf.predict_proba, X[:64], atol=1e-4)

    assert pred.supports_masked_ey
    G = groups_to_matrix(None, X.shape[1])
    plan = coalition_plan(G.shape[0], nsamples=24, seed=0)
    Xe = _quant(X[:6]).astype(np.float32)
    bgm = _quant(X[100:112]).astype(np.float32)
    bgw = np.full(12, 1.0 / 12, np.float32)
    mask = np.asarray(plan.mask, np.float32)
    ey_rows = np.asarray(_ey_generic(pred, Xe, bgm, bgw, mask @ G, chunk=8))
    ey_fast = np.asarray(pred.masked_ey(Xe, bgm, bgw, mask, G))
    np.testing.assert_allclose(ey_fast, ey_rows, atol=1e-5)


def test_ovr_with_unliftable_members_falls_back(data):
    """OvR whose members expose predict_proba but cannot lift (Platt-scaled
    SVCs) declines to the host path."""

    import warnings as _w

    from sklearn.multiclass import OneVsRestClassifier
    from sklearn.svm import SVC

    X, y, _ = data
    y3 = y + (X[:, 3] > 2).astype(int)
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        clf = OneVsRestClassifier(SVC(kernel="rbf", probability=True,
                                      random_state=0)).fit(X, y3)
        pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, CallbackPredictor)


def test_ovr_explain_additivity(data):
    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.multiclass import OneVsRestClassifier

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models import OneVsRestPredictor

    X, y, _ = data
    y3 = y + (X[:, 3] > 2).astype(int)
    clf = OneVsRestClassifier(GradientBoostingClassifier(
        n_estimators=6, random_state=0)).fit(X, y3)
    Xq = _quant(X)
    ex = KernelShap(clf.predict_proba, link="logit", seed=0)
    ex.fit(Xq[:30])
    assert isinstance(ex._explainer.predictor, OneVsRestPredictor)
    res = ex.explain(Xq[200:210], silent=True)
    proba = np.clip(clf.predict_proba(Xq[200:210]), 1e-7, 1 - 1e-7)
    for k, phi in enumerate(res.shap_values):
        lhs = phi.sum(axis=1) + res.expected_value[k]
        rhs = np.log(proba[:, k] / (1 - proba[:, k]))
        np.testing.assert_allclose(lhs, rhs, rtol=1e-3, atol=5e-3)


@pytest.mark.parametrize("passthrough", [False, True])
def test_stacking_classifier(data, passthrough):
    from sklearn.ensemble import GradientBoostingClassifier, StackingClassifier
    from sklearn.linear_model import LogisticRegression

    from distributedkernelshap_tpu.models import StackingPredictor

    X, y, _ = data
    clf = StackingClassifier(
        [("lr", LogisticRegression()),
         ("gb", GradientBoostingClassifier(n_estimators=8, random_state=0))],
        final_estimator=LogisticRegression(), cv=3,
        passthrough=passthrough).fit(X, y)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, StackingPredictor)
    _check(pred, clf.predict_proba, X[:64], atol=1e-4)


def test_stacking_multiclass(data):
    from sklearn.ensemble import StackingClassifier
    from sklearn.linear_model import LogisticRegression
    from sklearn.tree import DecisionTreeClassifier

    from distributedkernelshap_tpu.models import StackingPredictor

    X, y, _ = data
    y3 = y + (X[:, 3] > 2).astype(int)
    clf = StackingClassifier(
        [("lr", LogisticRegression()),
         ("dt", DecisionTreeClassifier(max_depth=4, random_state=0))],
        final_estimator=LogisticRegression(), cv=3).fit(X, y3)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, StackingPredictor) and pred.n_outputs == 3
    _check(pred, clf.predict_proba, X[:64], atol=1e-4)


def test_stacking_regressor(data):
    from sklearn.ensemble import StackingRegressor
    from sklearn.linear_model import LinearRegression
    from sklearn.tree import DecisionTreeRegressor

    from distributedkernelshap_tpu.models import StackingPredictor

    X, _, yr = data
    reg = StackingRegressor(
        [("lin", LinearRegression()),
         ("dt", DecisionTreeRegressor(max_depth=4, random_state=0))],
        final_estimator=LinearRegression(), cv=3).fit(X, yr)
    pred = as_predictor(reg.predict, example_dim=X.shape[1])
    assert isinstance(pred, StackingPredictor)
    _check(pred, reg.predict, X[:64], atol=1e-4)


def test_stacking_explain_additivity(data):
    from sklearn.ensemble import GradientBoostingClassifier, StackingClassifier
    from sklearn.linear_model import LogisticRegression

    from distributedkernelshap_tpu import KernelShap

    X, y, _ = data
    clf = StackingClassifier(
        [("lr", LogisticRegression()),
         ("gb", GradientBoostingClassifier(n_estimators=6, random_state=0))],
        final_estimator=LogisticRegression(), cv=3).fit(X, y)
    Xq = _quant(X)
    ex = KernelShap(clf.predict_proba, link="logit", seed=0)
    ex.fit(Xq[:30])
    res = ex.explain(Xq[200:210], silent=True)
    proba = np.clip(clf.predict_proba(Xq[200:210]), 1e-7, 1 - 1e-7)
    for k, phi in enumerate(res.shap_values):
        lhs = phi.sum(axis=1) + res.expected_value[k]
        rhs = np.log(proba[:, k] / (1 - proba[:, k]))
        np.testing.assert_allclose(lhs, rhs, rtol=1e-3, atol=5e-3)


@pytest.mark.parametrize("method", ["sigmoid", "isotonic"])
def test_calibrated_svc(data, method):
    """CalibratedClassifierCV(SVC) — the recommended replacement for the
    deprecated SVC(probability=True) — lifts end to end."""

    from sklearn.calibration import CalibratedClassifierCV
    from sklearn.svm import SVC

    X, y, _ = data
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = CalibratedClassifierCV(SVC(kernel="rbf"), method=method,
                                     cv=3).fit(X, y)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, (CalibratedBinaryPredictor, MeanEnsemblePredictor))
    _check(pred, clf.predict_proba, X[:64], atol=1e-4)


def test_calibrated_ensemble_false(data):
    from sklearn.calibration import CalibratedClassifierCV
    from sklearn.svm import SVC

    X, y, _ = data
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = CalibratedClassifierCV(SVC(kernel="rbf"), method="sigmoid",
                                     ensemble=False, cv=3).fit(X, y)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, CalibratedBinaryPredictor)
    _check(pred, clf.predict_proba, X[:64], atol=1e-4)


def test_pipeline_forwards_masked_ey(data):
    """Columnwise-stage pipelines forward the tree masked-ey fast path with
    transformed sources; phi matches the row-evaluating path."""

    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import StandardScaler

    from distributedkernelshap_tpu import KernelShap

    X, y, _ = data
    pipe = Pipeline([("sc", StandardScaler()),
                     ("gb", GradientBoostingClassifier(n_estimators=8,
                                                       max_depth=3,
                                                       random_state=0))]).fit(X, y)
    pred = as_predictor(pipe.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, PipelinePredictor) and pred.supports_masked_ey

    Xq = _quant(X)
    ex_fast = KernelShap(pipe.predict_proba, link="logit", seed=0)
    ex_fast.fit(Xq[:30])
    phi_fast = ex_fast.explain(Xq[200:212], silent=True).shap_values

    slow = as_predictor(pipe.predict_proba, example_dim=X.shape[1])
    slow.inner.path_sign = None          # force row evaluation
    ex_slow = KernelShap(slow, link="logit", seed=0)
    ex_slow.fit(Xq[:30])
    phi_slow = ex_slow.explain(Xq[200:212], silent=True).shap_values
    for a, b in zip(phi_fast, phi_slow):
        np.testing.assert_allclose(a, b, atol=5e-4)


def test_pca_pipeline_does_not_forward_masked_ey(data):
    """Column-mixing stages must NOT forward (masking in original space is
    not masking in projected space)."""

    from sklearn.decomposition import PCA
    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.pipeline import Pipeline

    X, y, _ = data
    pipe = Pipeline([("pca", PCA(n_components=4)),
                     ("gb", GradientBoostingClassifier(n_estimators=5,
                                                       random_state=0))]).fit(X, y)
    pred = as_predictor(pipe.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, PipelinePredictor)
    assert not pred.supports_masked_ey


def test_voting_forwards_masked_ey(data):
    """A soft-voting LR+GBT ensemble rides the masked fast path (expectation
    is linear over members) and matches the row-evaluating path."""

    from sklearn.ensemble import GradientBoostingClassifier, VotingClassifier
    from sklearn.linear_model import LogisticRegression

    from distributedkernelshap_tpu import KernelShap

    X, y, _ = data
    clf = VotingClassifier(
        [("lr", LogisticRegression()),
         ("gb", GradientBoostingClassifier(n_estimators=8, max_depth=3,
                                           random_state=0))],
        voting="soft", weights=[2.0, 1.0]).fit(X, y)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, MeanEnsemblePredictor) and pred.supports_masked_ey

    Xq = _quant(X)
    ex_fast = KernelShap(clf.predict_proba, link="logit", seed=0)
    ex_fast.fit(Xq[:30])
    phi_fast = ex_fast.explain(Xq[200:212], silent=True).shap_values

    slow = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    slow.members[1].path_sign = None     # tree member loses its fast path
    assert not slow.supports_masked_ey
    ex_slow = KernelShap(slow, link="logit", seed=0)
    ex_slow.fit(Xq[:30])
    phi_slow = ex_slow.explain(Xq[200:212], silent=True).shap_values
    for a, b in zip(phi_fast, phi_slow):
        np.testing.assert_allclose(a, b, atol=5e-4)


def test_explain_end_to_end_pipeline(data):
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import StandardScaler

    from distributedkernelshap_tpu import KernelShap

    X, y, _ = data
    from distributedkernelshap_tpu.models import LinearPredictor

    pipe = Pipeline([("sc", StandardScaler()),
                     ("lr", LogisticRegression())]).fit(X, y)
    ex = KernelShap(pipe.predict_proba, link="logit", seed=0)
    ex.fit(X[:40])
    assert isinstance(ex._explainer.predictor, LinearPredictor)
    Xe = _quant(X[40:56])
    res = ex.explain(Xe, silent=True)
    proba = np.clip(pipe.predict_proba(Xe), 1e-7, 1 - 1e-7)
    for k, phi in enumerate(res.shap_values):
        lhs = phi.sum(axis=1) + res.expected_value[k]
        rhs = np.log(proba[:, k] / (1 - proba[:, k]))
        # rtol absorbs the f32 blow-up of near-saturated probabilities
        # (|logit| ~ 12 means p within 1e-5 of 1)
        np.testing.assert_allclose(lhs, rhs, rtol=1e-3, atol=5e-3)


def test_search_cv_delegates_to_best_estimator(data):
    """GridSearchCV/RandomizedSearchCV route predict* to the refit winner;
    the lift must be the winner's lift (here a pipeline that folds into one
    LinearPredictor) and reproduce the search object's own outputs."""

    from sklearn.linear_model import LogisticRegression
    from sklearn.model_selection import GridSearchCV, RandomizedSearchCV
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import StandardScaler

    from distributedkernelshap_tpu.models import LinearPredictor

    X, y, _ = data
    pipe = Pipeline([("sc", StandardScaler()), ("lr", LogisticRegression())])
    gs = GridSearchCV(pipe, {"lr__C": [0.1, 1.0]}, cv=3).fit(X, y)
    pred = as_predictor(gs.predict_proba, example_dim=X.shape[1],
                        probe_data=X[:32])
    assert isinstance(pred, LinearPredictor)
    _check(pred, gs.predict_proba, X[:64])

    rs = RandomizedSearchCV(LogisticRegression(), {"C": [0.5, 2.0]},
                            n_iter=2, cv=3, random_state=0).fit(X, y)
    pred_r = as_predictor(rs.predict_proba, example_dim=X.shape[1],
                          probe_data=X[:32])
    assert isinstance(pred_r, LinearPredictor)
    _check(pred_r, rs.predict_proba, X[:64])


def test_search_cv_without_refit_declines(data):
    from sklearn.linear_model import LogisticRegression
    from sklearn.model_selection import GridSearchCV

    from distributedkernelshap_tpu.models.compose import lift_search_cv

    X, y, _ = data
    gs = GridSearchCV(LogisticRegression(), {"C": [0.1, 1.0]}, cv=3,
                      refit=False).fit(X, y)
    # refit=False leaves no best_estimator_ and sklearn raises on predict*;
    # the lifter must decline rather than crash (score is the only method)
    assert lift_search_cv(getattr(gs, "predict_proba", None) or gs.score) is None


def test_adaboost_classifier_lifts(data):
    """SAMME AdaBoost: one-hot argmax votes of lifted tree members must
    reproduce sklearn's decision_function and predict_proba exactly."""

    from sklearn.ensemble import AdaBoostClassifier

    from distributedkernelshap_tpu.models.compose import AdaBoostPredictor

    X, y, _ = data
    clf = AdaBoostClassifier(n_estimators=12, random_state=0).fit(X, y)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1],
                        probe_data=X[:32])
    assert isinstance(pred, AdaBoostPredictor)
    _check(pred, clf.predict_proba, X[:64])

    pred_d = as_predictor(clf.decision_function, example_dim=X.shape[1],
                          probe_data=X[:32])
    assert isinstance(pred_d, AdaBoostPredictor)
    _check(pred_d, clf.decision_function, X[:64])


def test_adaboost_multiclass_lifts():
    from sklearn.ensemble import AdaBoostClassifier

    from distributedkernelshap_tpu.models.compose import AdaBoostPredictor

    rng = np.random.default_rng(9)
    X = rng.normal(size=(300, 5))
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)  # 3 classes
    clf = AdaBoostClassifier(n_estimators=10, random_state=0).fit(X, y)
    pred = as_predictor(clf.predict_proba, example_dim=5, probe_data=X[:32])
    assert isinstance(pred, AdaBoostPredictor)
    _check(pred, clf.predict_proba, X[:64])
    pred_d = as_predictor(clf.decision_function, example_dim=5, probe_data=X[:32])
    assert isinstance(pred_d, AdaBoostPredictor)
    _check(pred_d, clf.decision_function, X[:64])


def test_adaboost_explain_end_to_end(data):
    from sklearn.ensemble import AdaBoostClassifier

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models.compose import AdaBoostPredictor

    X, y, _ = data
    clf = AdaBoostClassifier(n_estimators=8, random_state=0).fit(X, y)
    ex = KernelShap(clf.predict_proba, link="logit", seed=0)
    ex.fit(X[:40].astype(np.float32))
    assert isinstance(ex._explainer.predictor, AdaBoostPredictor)
    Xe = _quant(X[40:52]).astype(np.float32)
    res = ex.explain(Xe, silent=True)
    # external oracle: Σφ + E matches the ORIGINAL sklearn outputs
    proba = np.clip(clf.predict_proba(Xe), 1e-7, 1 - 1e-7)
    for k, phi in enumerate(res.shap_values):
        lhs = phi.sum(axis=1) + res.expected_value[k]
        rhs = np.log(proba[:, k] / (1 - proba[:, k]))
        np.testing.assert_allclose(lhs, rhs, rtol=1e-3, atol=5e-3)


def test_adaboost_regressor_declines(data):
    from sklearn.ensemble import AdaBoostRegressor

    from distributedkernelshap_tpu.models.compose import lift_adaboost

    X, _, yr = data
    reg = AdaBoostRegressor(n_estimators=5, random_state=0).fit(X, yr)
    assert lift_adaboost(reg.predict) is None


def test_transformed_target_regressor_lifts(data):
    """TTR.predict = inverse(regressor.predict): an affine target scaler
    folds into the linear inner model, keeping the MXU fast path; a GBT
    inner keeps its masked fast path through the affine head."""

    from sklearn.compose import TransformedTargetRegressor
    from sklearn.ensemble import HistGradientBoostingRegressor
    from sklearn.linear_model import LinearRegression
    from sklearn.preprocessing import MinMaxScaler, StandardScaler

    from distributedkernelshap_tpu.models import LinearPredictor
    from distributedkernelshap_tpu.models.compose import AffineOutputPredictor

    X, _, yr = data
    ttr = TransformedTargetRegressor(
        regressor=LinearRegression(), transformer=StandardScaler()).fit(X, yr)
    pred = as_predictor(ttr.predict, example_dim=X.shape[1], probe_data=X[:32])
    assert isinstance(pred, LinearPredictor)  # head folded into the weights
    _check(pred, ttr.predict, X[:64])

    ttr2 = TransformedTargetRegressor(
        regressor=HistGradientBoostingRegressor(max_iter=8, random_state=0),
        transformer=MinMaxScaler()).fit(X, yr)
    pred2 = as_predictor(ttr2.predict, example_dim=X.shape[1], probe_data=X[:32])
    assert isinstance(pred2, AffineOutputPredictor)
    assert pred2.supports_masked_ey  # forwards the tree fast path
    _check(pred2, ttr2.predict, X[:64])


def test_transformed_target_nonaffine_declines(data):
    from sklearn.compose import TransformedTargetRegressor
    from sklearn.linear_model import LinearRegression

    from distributedkernelshap_tpu.models.compose import lift_transformed_target

    X, _, yr = data
    yr_pos = np.abs(yr) + 1.0
    ttr = TransformedTargetRegressor(
        regressor=LinearRegression(), func=np.log,
        inverse_func=np.exp).fit(X, yr_pos)
    assert lift_transformed_target(ttr.predict) is None
