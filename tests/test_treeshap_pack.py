"""Path-parallel packed exact TreeSHAP (ops/treeshap_pack.py + the packed
routes in ops/treeshap.py and their engine/mesh integration).

Oracles: the planner's invariants are checked structurally (every live
path scheduled exactly once, tile alignment, bucket dmax bounds, shard
balance); the packed einsum route is pinned BIT-IDENTICAL to the dense
chunked-einsum reference (its engineered property — same Beta-weight
route, same chunk layout, scatter-to-dense final contraction); the
packed Pallas route (interpret mode on CPU) is pinned to the same
tolerance class as the existing dense kernel tests, including ensembles
whose deep buckets straddle the old global ``_exact_dmax <= 64`` kernel
cap that used to disqualify the WHOLE ensemble.
"""

import numpy as np
import pytest

from benchmarks.exact_ab import build_unbalanced_ensemble
from distributedkernelshap_tpu.kernel_shap import (
    EngineConfig,
    KernelExplainerEngine,
    StagedRows,
)
from distributedkernelshap_tpu.ops import groups_to_matrix
from distributedkernelshap_tpu.ops import treeshap as ts
from distributedkernelshap_tpu.ops.explain import ShapConfig
from distributedkernelshap_tpu.ops.treeshap_pack import plan_packed_paths


@pytest.fixture(scope="module")
def unbalanced():
    """Mostly-shallow bushy trees + a deep caterpillar minority over a
    wide feature space: deep paths touch > 64 DISTINCT features, so their
    bucket straddles the old global kernel dmax cap."""

    rng = np.random.default_rng(4)
    D = 80
    pred = build_unbalanced_ensemble(
        n_bushy=18, bushy_depth=3, n_deep=2, deep_depth=70, D=D, seed=4)
    G = groups_to_matrix(None, D)
    X = rng.normal(size=(6, D)).astype(np.float32)
    bg = rng.normal(size=(21, D)).astype(np.float32)
    bgw = (rng.random(21) + 0.1).astype(np.float32)
    return dict(pred=pred, G=G, X=X, bg=bg, bgw=bgw, D=D)


# --------------------------------------------------------------------- #
# planner units
# --------------------------------------------------------------------- #


def test_planner_covers_each_live_path_exactly_once():
    rng = np.random.default_rng(0)
    counts = rng.integers(-1, 10, size=(7, 13))
    plan = plan_packed_paths(counts, tile=32)
    flat = counts.ravel()
    want = np.sort(np.nonzero(flat > 0)[0])
    got = np.sort(plan.perm[plan.live])
    np.testing.assert_array_equal(got, want)
    assert plan.n_live == want.shape[0]
    # tile-aligned local bucket slices tiling [0, n_packed) exactly
    pos = 0
    for start, stop, dmax in plan.buckets:
        assert start == pos and (stop - start) % plan.tile == 0
        members = flat[plan.perm[start:stop][plan.live[start:stop]]]
        assert members.size == 0 or members.max() <= dmax
        pos = stop
    assert pos == plan.n_packed
    # pad slots are masked and zero-group paths are dropped (their phi
    # contribution is identically zero)
    assert int(plan.live.sum()) == plan.n_live
    assert (flat[plan.perm[plan.live]] > 0).all()


def test_planner_shard_striping_and_balance():
    rng = np.random.default_rng(1)
    counts = rng.integers(1, 13, size=(40, 50))
    shards, tile = 4, 16
    plan = plan_packed_paths(counts, tile=tile, shards=shards)
    assert plan.n_packed == shards * plan.local_len
    assert plan.local_len % tile == 0
    # every shard carries the SAME static bucket structure (shard_map is
    # SPMD) and the strided deal keeps live work balanced
    assert plan.buckets[-1][1] == plan.local_len
    assert plan.shard_balance <= 1.35
    # per-shard coverage: the union of shard slices is the live set
    flat = counts.ravel()
    got = np.sort(plan.perm[plan.live])
    np.testing.assert_array_equal(got, np.sort(np.nonzero(flat > 0)[0]))


def test_planner_gain_models_unbalance(unbalanced):
    plan = ts.build_packed_plan(unbalanced["pred"], unbalanced["G"])
    assert plan.gain > 1.2          # unbalanced ensembles pack profitably
    assert plan.dmax_global > 64    # the deep bucket straddles the old cap
    assert any(d > 64 for _, _, d in plan.buckets)
    assert any(d <= 64 for _, _, d in plan.buckets)
    # uniform ensemble: packing models ~no saving, the auto rule keeps
    # the tuned dense layout
    uniform = build_unbalanced_ensemble(
        n_bushy=16, bushy_depth=3, n_deep=0, deep_depth=0, D=12, seed=2)
    plan_u = ts.build_packed_plan(uniform, groups_to_matrix(None, 12))
    assert plan_u.gain <= 1.05
    assert not ts.resolve_pack_paths(None, plan_u)
    assert ts.resolve_pack_paths(True, plan_u)      # explicit force wins
    assert not ts.resolve_pack_paths(False, plan)


# --------------------------------------------------------------------- #
# packed routes vs the dense einsum reference
# --------------------------------------------------------------------- #


def test_packed_einsum_bit_identical_to_dense_reference(unbalanced):
    """The packed einsum route must reproduce the dense chunked-einsum
    exact path BIT-identically (np.array_equal) — the property that makes
    enabling packing safe for served answers and result caches."""

    s = unbalanced
    pred = s["pred"]
    for groups in (None, [[i, i + 1] for i in range(0, 40, 2)]):
        G = groups_to_matrix(groups, s["D"])
        reach = ts.background_reach(pred, s["bg"], G)
        ref = np.asarray(ts.exact_shap_from_reach(
            pred, s["X"], reach, s["bgw"], G, use_pallas=False))
        plan = ts.build_packed_plan(pred, G)
        packed = ts.pack_reach(pred, reach, plan)
        got = np.asarray(ts.exact_shap_packed(
            pred, s["X"], reach["onpath_g"], packed, s["bgw"], G,
            plan.buckets, use_pallas=False))
        assert np.array_equal(got, ref)


def test_packed_pallas_matches_dense_straddling_dmax_cap(unbalanced,
                                                         monkeypatch):
    """The packed Pallas route (interpret mode on CPU) at depths
    straddling the old ``_exact_dmax <= 64`` cap: shallow buckets run the
    fused kernel with their TIGHT dmax, the deep bucket falls back to the
    packed einsum for just its slice (counted), and phi matches the dense
    einsum reference to the established kernel tolerance."""

    from distributedkernelshap_tpu.ops import pallas_kernels as pk

    s = unbalanced
    pred, G = s["pred"], s["G"]
    reach = ts.background_reach(pred, s["bg"], G)
    plan = ts.build_packed_plan(pred, G)
    packed = ts.pack_reach(pred, reach, plan)

    kernel_dmaxes = []
    real = pk.exact_tree_phi

    def spy(*a, **k):
        kernel_dmaxes.append(k.get("dmax"))
        return real(*a, **k)

    monkeypatch.setattr(pk, "exact_tree_phi", spy)
    before = ts.exact_fallback_counts().get(("dmax_cap",), 0)
    ref = np.asarray(ts.exact_shap_from_reach(
        pred, s["X"], reach, s["bgw"], G, use_pallas=False))
    got = np.asarray(ts.exact_shap_packed(
        pred, s["X"], reach["onpath_g"], packed, s["bgw"], G,
        plan.buckets, use_pallas=True))
    scale = np.abs(ref).max()
    np.testing.assert_allclose(got, ref, atol=2e-5 * max(scale, 1.0),
                               rtol=2e-5)
    # shallow buckets engaged the kernel with their tight per-bucket dmax
    shallow = [d for _, _, d in plan.buckets if d <= 64]
    deep = [d for _, _, d in plan.buckets if d > 64]
    assert deep and shallow
    assert sorted(set(kernel_dmaxes)) == sorted(set(shallow))
    assert ts.exact_fallback_counts().get(("dmax_cap",), 0) > before


def test_dmax_static_bound_fallback_counted(unbalanced):
    """Tracing over the predictor itself loses the tight per-fit dmax —
    that demotion must be counted, not silent (the satellite's 10x
    slowdown observability)."""

    import types

    import jax
    import jax.numpy as jnp

    before = ts.exact_fallback_counts().get(("dmax_static_bound",), 0)

    def f(ps):
        fake = types.SimpleNamespace(path_sign=ps)
        return jnp.zeros((ts._exact_dmax(fake, 6),))

    jax.jit(f)(jnp.abs(unbalanced["pred"].path_sign))
    assert ts.exact_fallback_counts()[("dmax_static_bound",)] == before + 1


# --------------------------------------------------------------------- #
# engine integration
# --------------------------------------------------------------------- #


def test_engine_packed_matches_dense_bitwise_and_caches(unbalanced):
    s = unbalanced
    bg = s["bg"][:16]
    e_dense = KernelExplainerEngine(
        s["pred"], bg, link="identity", seed=0,
        config=EngineConfig(shap=ShapConfig(pack_paths=False)))
    e_packed = KernelExplainerEngine(
        s["pred"], bg, link="identity", seed=0,
        config=EngineConfig(shap=ShapConfig(pack_paths=True)))
    want = np.asarray(e_dense.get_explanation(s["X"], nsamples="exact"))
    got = np.asarray(e_packed.get_explanation(s["X"], nsamples="exact"))
    assert np.array_equal(got, want)
    assert e_packed.kernel_path["exact_phi"] == "einsum_packed"
    assert e_dense.kernel_path["exact_phi"] == "einsum"
    # consts are device-cached by content fingerprint and dropped by the
    # wedge-recovery hook
    key = ('exact_consts', e_packed.content_fingerprint(), True)
    assert key in e_packed._plan_consts_cache
    e_packed.reset_device_state()
    assert key not in e_packed._plan_consts_cache
    got2 = np.asarray(e_packed.get_explanation(s["X"], nsamples="exact"))
    assert np.array_equal(got2, want)


def test_engine_staged_async_exact_matches_sync(unbalanced):
    """nsamples='exact' rides the pipelined hot path: stage_rows accepts
    it, the staged buffer feeds the donated entry, and the async result is
    bit-identical to the sync explain."""

    s = unbalanced
    engine = KernelExplainerEngine(s["pred"], s["bg"][:12], link="identity",
                                   seed=0)
    want = engine.get_explanation(s["X"], nsamples="exact")
    staged = engine.stage_rows(s["X"], nsamples="exact")
    assert isinstance(staged, StagedRows)
    fin = engine.get_explanation_async(staged, nsamples="exact")
    values, info = fin()
    np.testing.assert_array_equal(np.asarray(values), np.asarray(want))
    np.testing.assert_array_equal(
        info["raw_prediction"],
        np.asarray(engine.last_raw_prediction))
    assert info["expected_value"].shape == (1,)
    # interactions stay on the sync path (and decline staging)
    assert engine.stage_rows(s["X"], nsamples="exact",
                             interactions=True) is None
    # non-tree explain options keep their historical staging behaviour
    assert engine.stage_rows(s["X"], nsamples=64, l1_reg=False) is not None


def test_engine_async_exact_unstaged(unbalanced):
    """The async exact path without pre-staged rows (the server's
    staging-off deployments) pads/buckets identically to sync."""

    s = unbalanced
    engine = KernelExplainerEngine(s["pred"], s["bg"][:12], link="identity",
                                   seed=0)
    want = engine.get_explanation(s["X"][:5], nsamples="exact")
    values, _ = engine.get_explanation_async(s["X"][:5],
                                             nsamples="exact")()
    np.testing.assert_array_equal(np.asarray(values), np.asarray(want))


# --------------------------------------------------------------------- #
# mesh sharding of packed work items
# --------------------------------------------------------------------- #


def test_sharded_packed_matches_single_device(unbalanced):
    """Packed work items striped over the coalition axis (each rank owns
    a balanced slice of path tiles, partial phi psum'd) must match the
    single-device engine."""

    from distributedkernelshap_tpu.parallel.distributed import (
        DistributedExplainer,
    )

    s = unbalanced
    bg = s["bg"][:16]
    cfg = EngineConfig(shap=ShapConfig(pack_paths=True))
    seq = KernelExplainerEngine(s["pred"], bg, link="identity", seed=0,
                                config=cfg)
    want = seq.get_explanation(s["X"], nsamples="exact")

    dist = DistributedExplainer(
        {"n_devices": 8, "coalition_parallel": 2,
         "algorithm": "kernel_shap"},
        KernelExplainerEngine, (s["pred"], bg),
        {"link": "identity", "seed": 0, "config": cfg})
    got = dist.get_explanation(s["X"], nsamples="exact")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)
    # staging declines for sharded explainers (mesh padding differs from
    # the single-engine bucketing) instead of proxying the inner engine's
    assert dist.stage_rows(s["X"], nsamples="exact") is None

    dist4 = DistributedExplainer(
        {"n_devices": 8, "coalition_parallel": 4,
         "algorithm": "kernel_shap"},
        KernelExplainerEngine, (s["pred"], bg),
        {"link": "identity", "seed": 0, "config": cfg})
    got4 = dist4.get_explanation(s["X"], nsamples="exact")
    np.testing.assert_allclose(np.asarray(got4), np.asarray(want),
                               atol=1e-5)
