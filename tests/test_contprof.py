"""Continuous sampling profiler: env resolution, sampler lifecycle and
refcounting, auto-disable under the overhead budget, fold-table bounds,
role/tenant tagging, collapsed/Perfetto export round-trips, and the
/profilez endpoint on the server and proxy (incl. federation)."""

import json
import http.client
import threading
import time

import numpy as np
import pytest

from distributedkernelshap_tpu.observability.contprof import (
    ContProf,
    contprof,
    from_perfetto,
    merge_collapsed,
    parse_collapsed,
    resolve_contprof_env,
)


# --------------------------------------------------------------------- #
# env resolution
# --------------------------------------------------------------------- #


def test_env_resolution(monkeypatch):
    monkeypatch.delenv("DKS_CONTPROF", raising=False)
    assert resolve_contprof_env(default_hz=19.0) == 19.0
    monkeypatch.setenv("DKS_CONTPROF", "0")
    assert resolve_contprof_env() == 0.0
    monkeypatch.setenv("DKS_CONTPROF", "off")
    assert resolve_contprof_env() == 0.0
    monkeypatch.setenv("DKS_CONTPROF", "1")
    assert resolve_contprof_env(default_hz=19.0) == 19.0
    monkeypatch.setenv("DKS_CONTPROF", "97")
    assert resolve_contprof_env() == 97.0
    monkeypatch.setenv("DKS_CONTPROF", "100000")
    assert resolve_contprof_env() == 250.0  # clamped
    monkeypatch.setenv("DKS_CONTPROF", "garbage")
    assert resolve_contprof_env(default_hz=19.0) == 19.0


# --------------------------------------------------------------------- #
# helpers: a parked worker thread with a recognisable stack
# --------------------------------------------------------------------- #


def _parked_worker(prof, role, tenant=None, trace=None):
    """Spawn a thread parked inside a distinct function frame; returns
    (thread, release_event)."""

    release = threading.Event()
    ready = threading.Event()

    def _worker_frame_for_contprof():
        prof.register_current_thread(role)
        if tenant or trace:
            prof.tag_current_thread(trace_id=trace, tenant=tenant)
        ready.set()
        release.wait(30)

    t = threading.Thread(target=_worker_frame_for_contprof, daemon=True)
    t.start()
    ready.wait(5)
    return t, release


# --------------------------------------------------------------------- #
# lifecycle
# --------------------------------------------------------------------- #


def test_start_stop_and_refcounted_acquire():
    p = ContProf(hz=200.0)
    assert not p.running
    p.acquire()
    assert p.running
    p.acquire()
    p.release()
    assert p.running      # second holder keeps it alive
    p.release()
    assert not p.running


def test_sampler_collects_role_tagged_stacks():
    p = ContProf(hz=200.0)
    t, release = _parked_worker(p, "handler", tenant="alpha",
                                trace="t-123")
    try:
        p.start()
        deadline = time.monotonic() + 5.0
        while p.samples_total() == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        p.stop()
        release.set()
    assert p.samples_total() > 0
    text = p.collapsed()
    assert "thread:handler" in text
    assert "tenant:alpha" in text
    assert "_worker_frame_for_contprof" in text


def test_hz_zero_never_starts():
    p = ContProf(hz=0.0)
    p.start()
    assert not p.running


def test_pause_resume_skips_sweeps():
    p = ContProf(hz=100.0)
    t, release = _parked_worker(p, "other")
    try:
        p.pause()
        p._sweep()
        assert p.samples_total() == 0
        p.resume()
        p._sweep()
        assert p.samples_total() > 0
    finally:
        release.set()


def test_auto_disable_over_overhead_budget():
    p = ContProf(hz=100.0, overhead_budget=1e-12)
    t, release = _parked_worker(p, "other")
    try:
        p._started_mono = time.monotonic() - 10.0  # well past the 1s grace
        p._sweep()
        assert p.auto_disabled
        before = p.samples_total()
        p._sweep()                   # disabled: sweeps now no-op
        assert p.samples_total() == before
    finally:
        release.set()
    assert p.stats()["auto_disabled"] is True


def test_fold_table_bound_drops_and_counts():
    p = ContProf(hz=100.0, max_stacks=1)
    t1, r1 = _parked_worker(p, "role-a")
    t2, r2 = _parked_worker(p, "role-b")
    try:
        p._sweep()
    finally:
        r1.set()
        r2.set()
    stats = p.stats()
    assert stats["distinct_stacks"] <= 1
    assert stats["dropped_stacks"] > 0


# --------------------------------------------------------------------- #
# export round-trips
# --------------------------------------------------------------------- #


def test_parse_and_merge_collapsed():
    page_a = "thread:handler;mod:f;mod:g 3\nthread:tick;mod:h 1\n"
    page_b = "thread:handler;mod:f;mod:g 2\n"
    assert parse_collapsed(page_a) == {
        "thread:handler;mod:f;mod:g": 3, "thread:tick;mod:h": 1}
    merged = merge_collapsed([page_a, page_b])
    assert parse_collapsed(merged) == {
        "thread:handler;mod:f;mod:g": 5, "thread:tick;mod:h": 1}


def test_perfetto_roundtrip_matches_collapsed():
    p = ContProf(hz=100.0)
    t, release = _parked_worker(p, "handler", tenant="alpha")
    try:
        for _ in range(3):
            p._sweep()
    finally:
        release.set()
    collapsed = parse_collapsed(p.collapsed())
    assert collapsed
    doc = p.perfetto()
    assert doc["traceEvents"]
    assert from_perfetto(doc) == collapsed


def test_windowed_view_bounded_by_ring():
    p = ContProf(hz=100.0)
    t, release = _parked_worker(p, "other")
    try:
        p._sweep()
    finally:
        release.set()
    # the 60s window holds everything just sampled; a 0-second window
    # may only drop counts, never invent them
    full = sum(parse_collapsed(p.collapsed()).values())
    windowed = sum(parse_collapsed(p.collapsed(window_s=60)).values())
    assert 0 < windowed <= full


def test_profilez_payload_formats():
    p = ContProf(hz=100.0)
    t, release = _parked_worker(p, "handler")
    try:
        p._sweep()
    finally:
        release.set()
    ctype, body = p.profilez_payload({})
    assert ctype.startswith("application/json")
    doc = json.loads(body)
    assert "samples_total" in doc and "top_stacks" in doc
    ctype, body = p.profilez_payload({"format": ["collapsed"]})
    assert ctype.startswith("text/plain")
    assert parse_collapsed(body.decode())
    ctype, body = p.profilez_payload({"format": ["perfetto"]})
    assert "traceEvents" in json.loads(body)


def test_reset_zeroes_everything():
    p = ContProf(hz=100.0)
    t, release = _parked_worker(p, "other")
    try:
        p._sweep()
    finally:
        release.set()
    assert p.samples_total() > 0
    p.reset()
    assert p.samples_total() == 0
    assert p.collapsed() == ""


# --------------------------------------------------------------------- #
# serving integration: /profilez on server and proxy
# --------------------------------------------------------------------- #


def _get(host, port, path):
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class _Stub:
    max_rows = None

    def explain_batch(self, instances, split_sizes=None):
        return [json.dumps({"data": {}})] * len(split_sizes or [1])


@pytest.fixture()
def profiled_server():
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    server = ExplainerServer(_Stub(), host="127.0.0.1", port=0,
                             max_batch_size=2, batch_timeout_s=0.002,
                             health_interval_s=0).start()
    try:
        yield server
    finally:
        server.stop()


def test_server_profilez_routes(profiled_server):
    server = profiled_server
    status, body = _get(server.host, server.port, "/profilez")
    assert status == 200
    doc = json.loads(body)
    assert "samples_total" in doc and "hz" in doc
    status, body = _get(server.host, server.port,
                        "/profilez?format=collapsed")
    assert status == 200
    parse_collapsed(body.decode())  # well-formed (possibly empty early)
    status, body = _get(server.host, server.port,
                        "/profilez?format=perfetto")
    assert status == 200
    assert "traceEvents" in json.loads(body)
    # self-metering rides the ordinary exposition
    assert "dks_prof_samples_total" in server._render_metrics()


def test_proxy_profilez_and_federation(profiled_server):
    from distributedkernelshap_tpu.serving.replicas import FanInProxy

    server = profiled_server
    proxy = FanInProxy([(server.host, server.port)],
                       probe_interval_s=3600).start()
    try:
        status, body = _get(proxy.host, proxy.port, "/profilez")
        assert status == 200
        assert "samples_total" in json.loads(body)
        # give the shared sampler a beat so the merge carries content
        prof = contprof()
        deadline = time.monotonic() + 5.0
        while prof.samples_total() == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        prof.pause()   # freeze counts so federated == replica scrape
        try:
            status, fed = _get(proxy.host, proxy.port,
                               "/profilez?federate=1")
            assert status == 200
            status, solo = _get(server.host, server.port,
                                "/profilez?format=collapsed")
            assert status == 200
            # one replica: the federated merge IS that replica's page
            assert parse_collapsed(fed.decode()) == \
                parse_collapsed(solo.decode())
        finally:
            prof.resume()
    finally:
        proxy.stop()
