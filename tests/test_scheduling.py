"""Unit tests for the scheduling subsystem
(``distributedkernelshap_tpu/scheduling/``): EDF scheduler + row-budget
packing, admission control (bounded queues, token buckets, projected-wait
shedding) and the content-addressed result cache — plus their integration
into ``ExplainerServer`` (priority/deadline headers, 429 semantics, cache
hit paths, the carried-request lifecycle).  All CPU, no device needed.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributedkernelshap_tpu.scheduling import (
    AdmissionController,
    FIFOScheduler,
    ResultCache,
    ServiceRateEstimator,
    SLOScheduler,
    TokenBucket,
    array_fingerprint,
    model_fingerprint,
    request_cache_key,
)
from distributedkernelshap_tpu.models import LinearPredictor
from distributedkernelshap_tpu.serving import (
    ExplainerServer,
    KernelShapModel,
    distribute_requests,
    explain_request,
)


class Item:
    """Minimal scheduler item (the server's _Pending protocol)."""

    def __init__(self, name, klass="batch", deadline=None, rows=1,
                 t_enqueued=None):
        self.name = name
        self.klass = klass
        self.deadline = deadline
        self.rows = rows
        self.t_enqueued = time.monotonic() if t_enqueued is None else t_enqueued
        self.done = False

    def __repr__(self):
        return f"Item({self.name})"


# --------------------------------------------------------------------- #
# scheduler
# --------------------------------------------------------------------- #


def test_edf_deadline_ordering():
    """Explicit deadlines dominate arrival order: the latest-enqueued but
    earliest-deadline request leads the batch."""

    sched = SLOScheduler()
    now = time.monotonic()
    sched.put(Item("late", deadline=now + 30))
    sched.put(Item("mid", deadline=now + 10))
    sched.put(Item("urgent", deadline=now + 5))
    batch, expired = sched.next_batch(max_batch_size=3)
    assert [i.name for i in batch] == ["urgent", "mid", "late"]
    assert expired == []


def test_class_budgets_order_implicit_deadlines():
    """Without explicit deadlines, interactive sorts ahead of batch ahead
    of best_effort even when enqueued last (class ordering budgets)."""

    sched = SLOScheduler()
    t0 = time.monotonic()
    sched.put(Item("bg", klass="best_effort", t_enqueued=t0))
    sched.put(Item("bulk", klass="batch", t_enqueued=t0))
    sched.put(Item("ui", klass="interactive", t_enqueued=t0))
    batch, _ = sched.next_batch(max_batch_size=3)
    assert [i.name for i in batch] == ["ui", "bulk", "bg"]


def test_fifo_scheduler_is_arrival_order():
    sched = FIFOScheduler()
    now = time.monotonic()
    sched.put(Item("first", deadline=now + 30))
    sched.put(Item("second", deadline=now + 1))
    batch, _ = sched.next_batch(max_batch_size=2)
    assert [i.name for i in batch] == ["first", "second"]
    # FIFO never expires: a long-dead deadline still gets dispatched
    sched.put(Item("stale", deadline=now - 10))
    batch, expired = sched.next_batch(max_batch_size=1)
    assert [i.name for i in batch] == ["stale"] and expired == []


def test_row_budget_packing_and_no_starvation():
    """Packing stops at max_rows; the overflow item is NOT dropped and NOT
    double-dispatched — it leads the next batch even while smaller items
    keep arriving (the EDF key is its original enqueue time)."""

    sched = SLOScheduler()
    t0 = time.monotonic()
    big = Item("big", rows=6, t_enqueued=t0 + 0.001)
    sched.put(Item("a", rows=3, t_enqueued=t0))
    sched.put(big)
    sched.put(Item("b", rows=4, t_enqueued=t0 + 0.002))
    batch1, _ = sched.next_batch(max_batch_size=8, max_rows=8)
    # 'big' (6 rows) would overflow 3+6 > 8, so 'b' (4 rows) packs instead
    assert [i.name for i in batch1] == ["a", "b"]
    assert sum(i.rows for i in batch1) <= 8
    # queue stays hot: smaller, LATER items keep arriving — the carried
    # item keeps its original EDF key, so it must lead the next batch
    # (no starvation) and appear exactly once (no double dispatch)
    sched.put(Item("c", rows=2, t_enqueued=t0 + 0.01))
    batch2, _ = sched.next_batch(max_batch_size=8, max_rows=8)
    assert batch2[0].name == "big"
    names = [i.name for i in batch1 + batch2]
    assert names.count("big") == 1  # never double-dispatched


def test_rows_ahead_is_edf_aware():
    """The projected-wait input counts only rows that would sort AHEAD of
    the request under EDF — a deep batch backlog must not inflate an
    interactive request's projection (the scheduler dispatches it first).
    On the FIFO baseline everything queued really is ahead."""

    sched = SLOScheduler()
    now = time.monotonic()
    for i in range(10):
        sched.put(Item(f"bulk{i}", klass="batch", rows=5))  # eff ~now+30
    sched.put(Item("soon", deadline=now + 0.2, rows=2))
    # an interactive request due now+1: only 'soon' (eff now+0.2) is ahead
    assert sched.rows_ahead("interactive", now + 1.0) == 2
    # a request due after the batch budget window sees everything
    assert sched.rows_ahead("batch", now + 60.0) == 52
    fifo = FIFOScheduler()
    for i in range(3):
        fifo.put(Item(f"f{i}", klass="batch", rows=4))
    assert fifo.rows_ahead("interactive", now + 0.01) == 12


def test_expired_items_are_separated():
    sched = SLOScheduler()
    now = time.monotonic()
    sched.put(Item("dead", deadline=now - 1))
    sched.put(Item("alive", deadline=now + 60))
    batch, expired = sched.next_batch(max_batch_size=4)
    assert [i.name for i in batch] == ["alive"]
    assert [i.name for i in expired] == ["dead"]


def test_put_wakes_blocked_next_batch():
    """Condition-variable wakeup: a dispatcher blocked on an empty queue
    returns promptly once a request arrives (no 0.1 s poll tick)."""

    sched = SLOScheduler()
    out = {}

    def consume():
        t0 = time.monotonic()
        out["batch"], _ = sched.next_batch(max_batch_size=1)
        out["waited"] = time.monotonic() - t0

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)
    sched.put(Item("x"))
    t.join(timeout=5)
    assert not t.is_alive()
    assert [i.name for i in out["batch"]] == ["x"]


def test_drain_returns_everything_and_resets_depths():
    sched = SLOScheduler()
    for i in range(3):
        sched.put(Item(f"i{i}", klass="interactive"))
    sched.put(Item("b", klass="batch", rows=5))
    assert sched.depths()["interactive"] == 3
    assert sched.queued_rows() == 8
    drained = sched.drain()
    assert len(drained) == 4
    assert sched.qsize() == 0
    assert sched.depths() == {"interactive": 0, "batch": 0, "best_effort": 0}
    assert sched.queued_rows() == 0


# --------------------------------------------------------------------- #
# admission
# --------------------------------------------------------------------- #


def test_token_bucket_refill():
    clock = {"t": 0.0}
    bucket = TokenBucket(rate=2.0, burst=4.0, now=lambda: clock["t"])
    for _ in range(4):
        ok, _ = bucket.try_acquire()
        assert ok
    ok, retry = bucket.try_acquire()
    assert not ok and retry == pytest.approx(0.5)
    clock["t"] += 0.5  # refills exactly one token at 2/s
    ok, _ = bucket.try_acquire()
    assert ok
    # burst cap: a long idle period must not accumulate unbounded tokens
    clock["t"] += 1000.0
    assert bucket.tokens == pytest.approx(4.0)


def test_admission_queue_bound_per_class():
    ctl = AdmissionController(max_queued_per_class={"interactive": 2,
                                                    "batch": 100})
    dec = ctl.admit("interactive", 1, "c", queue_depth=2)
    assert not dec and dec.reason == "queue_full" and dec.retry_after_s > 0
    # the other class has its own bound: unaffected
    assert ctl.admit("batch", 1, "c", queue_depth=2)
    # a class MISSING from the dict keeps the default bound (1024) rather
    # than silently becoming unbounded
    assert ctl.admit("best_effort", 1, "c", queue_depth=1023)
    dec = ctl.admit("best_effort", 1, "c", queue_depth=1024)
    assert not dec and dec.reason == "queue_full"
    # an explicit 0 entry disables the gate for that class only
    ctl0 = AdmissionController(max_queued_per_class={"batch": 0})
    assert ctl0.admit("batch", 1, "c", queue_depth=10**6)


def test_admission_rate_limit_is_per_client():
    clock = {"t": 0.0}
    ctl = AdmissionController(max_queued_per_class=0,
                              rate_limit_per_client=(1.0, 2.0),
                              now=lambda: clock["t"])
    assert ctl.admit("batch", 1, "alice")
    assert ctl.admit("batch", 1, "alice")
    dec = ctl.admit("batch", 1, "alice")
    assert not dec and dec.reason == "rate_limited"
    assert ctl.admit("batch", 1, "bob")  # separate bucket
    clock["t"] += 1.0
    assert ctl.admit("batch", 1, "alice")  # refilled


def test_admission_projected_wait_shed():
    clock = {"t": 100.0}
    est = ServiceRateEstimator()
    est.observe(rows=10, seconds=1.0)  # 10 rows/s
    ctl = AdmissionController(max_queued_per_class=0, estimator=est,
                              now=lambda: clock["t"])
    # 50 rows queued ahead -> ~5s wait; a 1s deadline is unservable
    dec = ctl.admit("interactive", 1, "c", deadline=clock["t"] + 1.0,
                    queued_rows=50)
    assert not dec and dec.reason == "projected_wait"
    assert dec.retry_after_s == pytest.approx(5.1, rel=0.2)
    # a 10s deadline fits; and with no deadline the gate never sheds
    assert ctl.admit("interactive", 1, "c", deadline=clock["t"] + 10.0,
                     queued_rows=50)
    assert ctl.admit("interactive", 1, "c", deadline=None, queued_rows=50)


def test_estimator_ewma():
    est = ServiceRateEstimator(alpha=0.5)
    assert est.rows_per_s() is None
    est.observe(10, 1.0)
    assert est.rows_per_s() == pytest.approx(10.0)
    est.observe(20, 1.0)
    assert est.rows_per_s() == pytest.approx(15.0)
    est.observe(0, 1.0)  # ignored
    est.observe(10, 0.0)  # ignored
    assert est.rows_per_s() == pytest.approx(15.0)


# --------------------------------------------------------------------- #
# result cache
# --------------------------------------------------------------------- #


def test_cache_lru_eviction_by_byte_budget():
    cache = ResultCache(max_bytes=10)
    cache.put("a", "xxxx")  # 4 bytes
    cache.put("b", "yyyy")  # 8 total
    assert cache.get("a") == "xxxx"  # refreshes a's recency
    cache.put("c", "zzzz")  # 12 > 10: evicts LRU, which is now b
    assert cache.get("b") is None
    assert cache.get("a") == "xxxx" and cache.get("c") == "zzzz"
    assert cache.current_bytes <= 10
    stats = cache.stats()
    assert stats["evictions"] == 1 and stats["entries"] == 2


def test_cache_oversized_payload_not_cached():
    cache = ResultCache(max_bytes=4)
    cache.put("k", "way too big")
    assert len(cache) == 0 and cache.get("k") is None


def test_cache_replacing_key_adjusts_bytes():
    cache = ResultCache(max_bytes=100)
    cache.put("k", "aaaa")
    cache.put("k", "bb")
    assert cache.current_bytes == 2 and len(cache) == 1


def test_fingerprints_change_with_content():
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    assert array_fingerprint(a) == array_fingerprint(a.copy())
    assert array_fingerprint(a) != array_fingerprint(a + 1)
    assert array_fingerprint(a) != array_fingerprint(a.reshape(3, 2))
    assert array_fingerprint(a) != array_fingerprint(a.astype(np.float64))


def test_structured_hash_sees_past_numpy_repr_elision():
    """numpy's repr elides the middle of large arrays ('...'), so a
    repr-based fingerprint would collide for groupings differing only in
    the elided region — the structured hash must distinguish them."""

    from distributedkernelshap_tpu.scheduling.result_cache import (
        _update_structured,
    )
    import hashlib

    def digest(value):
        h = hashlib.sha256()
        _update_structured(h, value)
        return h.hexdigest()

    big = np.zeros(4096, dtype=np.int64)
    tweaked = big.copy()
    tweaked[2048] = 1  # repr-elided middle element
    assert repr(big) == repr(tweaked)  # the trap this guards against
    assert digest(big) != digest(tweaked)
    # containers recurse; scalars and strings still hash by value
    assert digest({"groups": [big], "k": 1}) != digest(
        {"groups": [tweaked], "k": 1})
    assert digest({"k": 1}) != digest({"k": 2})


@pytest.fixture(scope="module")
def small_model():
    rng = np.random.default_rng(0)
    D, K = 6, 2
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    bg = rng.normal(size=(12, D)).astype(np.float32)
    pred = LinearPredictor(W, b, activation="softmax")
    model = KernelShapModel(pred, bg, {"link": "logit", "seed": 0}, {})
    X = rng.normal(size=(8, D)).astype(np.float32)
    return model, bg, X, pred


def test_model_fingerprint_tracks_background_and_kwargs(small_model):
    model, bg, X, pred = small_model
    fp = model_fingerprint(model)
    assert fp == model_fingerprint(model)  # stable
    other = KernelShapModel(pred, bg + 1.0, {"link": "logit", "seed": 0}, {})
    assert fp != model_fingerprint(other)  # background change => new keys
    assert fp != model_fingerprint(model, explain_kwargs={"nsamples": 32})
    # an explicit fingerprint wins (checkpoint-hash deployments)
    model2 = KernelShapModel(pred, bg, {"link": "logit", "seed": 0}, {})
    model2.fingerprint = "pinned"
    assert model_fingerprint(model2) == "pinned"
    assert request_cache_key(X[:1], fp) != request_cache_key(X[1:2], fp)


# --------------------------------------------------------------------- #
# server integration
# --------------------------------------------------------------------- #


@pytest.fixture()
def served(small_model):
    """Server factory with scheduler knobs; stops everything at teardown."""

    model, bg, X, pred = small_model
    servers = []

    def make(**kwargs):
        kwargs.setdefault("host", "127.0.0.1")
        kwargs.setdefault("port", 0)
        kwargs.setdefault("pipeline_depth", 2)
        srv = ExplainerServer(model, **kwargs).start()
        servers.append(srv)
        return srv, f"http://127.0.0.1:{srv.port}"

    yield make, X
    for srv in servers:
        srv.stop()


def _post(url, array, headers=None, timeout=60):
    req = urllib.request.Request(
        url + "/explain",
        data=json.dumps({"array": np.asarray(array).tolist()}).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.read().decode(), dict(resp.getheaders())


def test_server_cache_hit_bit_identical(served):
    make, X = served
    srv, base = make(max_batch_size=4, cache_bytes=1 << 20)
    first = _post(base, X[:2])[1]
    second = _post(base, X[:2])[1]
    assert second == first  # bit-identical payload from cache
    # additivity still holds in the cached payload
    data = json.loads(second)["data"]
    total = (np.asarray(data["shap_values"]).sum(-1)
             + np.asarray(data["expected_value"])[:, None])
    np.testing.assert_allclose(
        total, np.asarray(data["raw"]["raw_prediction"]).T, atol=1e-4)
    text = urllib.request.urlopen(f"{base}/metrics", timeout=30).read().decode()
    assert "dks_serve_cache_hits_total 1" in text
    assert "dks_serve_cache_misses_total 1" in text


def test_server_cache_splits_batches(served):
    """Per-batch partial-hit splitting: duplicates answered from cache (or
    deduped in-batch) must not cost device rows — rows_total counts every
    answered request, but the cache hit counter proves which were free."""

    make, X = served
    srv, base = make(max_batch_size=8, cache_bytes=1 << 20,
                     batch_timeout_s=0.2)
    # seed the cache
    _post(base, X[:1])
    # fan out 6 duplicates + 2 novel rows concurrently
    rows = [X[:1]] * 6 + [X[1:2], X[2:3]]
    results = [None] * len(rows)

    def go(i):
        results[i] = _post(base, rows[i])[1]

    threads = [threading.Thread(target=go, args=(i,))
               for i in range(len(rows))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results[0] == results[5]  # duplicates identical
    metrics = urllib.request.urlopen(f"{base}/metrics",
                                     timeout=30).read().decode()
    hits = {line.split()[0]: float(line.split()[1])
            for line in metrics.splitlines()
            if line and not line.startswith("#")}
    assert hits["dks_serve_cache_hits_total"] >= 6
    assert hits["dks_serve_cache_misses_total"] == 3  # seed + 2 novel


class GateModel:
    """Sync-only model wrapper that stalls dispatch until released, so the
    queue backs up deterministically."""

    def __init__(self, model, max_rows=None, delay_s=None):
        self.model = model
        self.release = threading.Event()
        self.max_rows = max_rows
        self.delay_s = delay_s

    def explain_batch(self, instances, split_sizes=None):
        if self.delay_s is not None:
            time.sleep(self.delay_s)
        else:
            self.release.wait(30)
        return self.model.explain_batch(instances, split_sizes)


def test_server_queue_full_sheds_429(small_model):
    model, bg, X, pred = small_model
    gate = GateModel(model)
    srv = ExplainerServer(gate, host="127.0.0.1", port=0, max_batch_size=1,
                          pipeline_depth=1, max_queue_per_class=1).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        results = []

        def go():
            try:
                results.append(_post(base, X[:1], timeout=30)[0])
            except urllib.error.HTTPError as e:
                e.read()
                results.append(e.code)

        threads = [threading.Thread(target=go) for _ in range(4)]
        for t in threads:
            t.start()
            time.sleep(0.1)  # let earlier requests occupy device + queue
        got_429 = False
        deadline = time.monotonic() + 10
        while not got_429 and time.monotonic() < deadline:
            try:
                _post(base, X[:1], timeout=5)
            except urllib.error.HTTPError as e:
                body = e.read().decode()
                if e.code == 429:
                    got_429 = True
                    assert "queue_full" in body
                    assert int(e.headers["Retry-After"]) >= 1
            time.sleep(0.05)
        assert got_429, "full class queue never shed"
        text = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=30).read().decode()
        assert 'dks_serve_sheds_total{reason="queue_full"}' in text
    finally:
        gate.release.set()
        for t in threads:
            t.join(timeout=30)
        srv.stop()


def test_server_rate_limit_sheds_per_client(served):
    make, X = served
    srv, base = make(max_batch_size=1, rate_limit_per_client=(0.5, 2.0))
    ok = 0
    limited = 0
    for _ in range(4):
        try:
            status, _, _ = _post(base, X[:1],
                                 headers={"X-DKS-Client": "alice"})
            ok += 1
        except urllib.error.HTTPError as e:
            assert e.code == 429
            assert "rate_limited" in e.read().decode()
            limited += 1
    assert ok == 2 and limited == 2  # burst of 2, then shed
    # a different client key is untouched
    status, _, _ = _post(base, X[:1], headers={"X-DKS-Client": "bob"})
    assert status == 200


def test_server_priority_header_validation(served):
    make, X = served
    srv, base = make()
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(base, X[:1], headers={"X-DKS-Priority": "vip"})
    assert e.value.code == 400 and "priority" in e.value.read().decode()
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(base, X[:1], headers={"X-DKS-Deadline-Ms": "soon"})
    assert e.value.code == 400
    # valid headers serve normally
    status, payload, _ = _post(base, X[:1], headers={
        "X-DKS-Priority": "best_effort", "X-DKS-Deadline-Ms": "60000"})
    assert status == 200 and json.loads(payload)["data"]["shap_values"]


def test_server_expired_deadline_answers_504(small_model):
    model, bg, X, pred = small_model
    srv = ExplainerServer(GateModel(model, delay_s=0.6), host="127.0.0.1",
                          port=0, max_batch_size=1, pipeline_depth=1).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        # first request occupies the device; the second's 150 ms deadline
        # dies in the queue and must come back 504 without device work
        t = threading.Thread(target=lambda: _post(base, X[:1], timeout=30))
        t.start()
        time.sleep(0.15)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base, X[1:2], headers={"X-DKS-Deadline-Ms": "150"},
                  timeout=30)
        t.join(timeout=30)
        assert e.value.code == 504
        assert "deadline" in e.value.read().decode()
        text = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=30).read().decode()
        assert 'dks_serve_sheds_total{reason="deadline_expired"} 1' in text
    finally:
        srv.stop()


def test_server_metrics_queue_depth_and_histogram(served):
    """The new observability satellites: per-class queue depth gauges and a
    bounded latency histogram appear in /metrics and account answered
    requests."""

    make, X = served
    srv, base = make(max_batch_size=4)
    distribute_requests(f"{base}/explain", X[:4], max_workers=2)
    text = urllib.request.urlopen(f"{base}/metrics", timeout=30).read().decode()
    for klass in ("interactive", "batch", "best_effort"):
        assert f'dks_serve_queue_depth{{class="{klass}"}} 0' in text
    assert 'dks_serve_request_latency_seconds_bucket{le="+Inf"} 4' in text
    assert "dks_serve_request_latency_seconds_count 4" in text
    # cumulative: every finite-bucket count <= +Inf count
    counts = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
              if line.startswith("dks_serve_request_latency_seconds_bucket")]
    assert counts == sorted(counts)


def test_carry_failed_not_leaked_on_shutdown(small_model):
    """The carried-request lifecycle on shutdown: a request deferred for
    row overflow lives in the scheduler heap; stop() must fail it (the
    client gets an error, promptly) rather than leak its handler thread."""

    model, bg, X, pred = small_model
    gate = GateModel(model, max_rows=3)
    srv = ExplainerServer(gate, host="127.0.0.1", port=0, max_batch_size=8,
                          pipeline_depth=1, batch_timeout_s=0.3).start()
    base = f"http://127.0.0.1:{srv.port}"
    statuses = {}

    def go(name, rows, delay):
        time.sleep(delay)
        try:
            statuses[name] = _post(base, X[:rows], timeout=30)[0]
        except urllib.error.HTTPError as e:
            e.read()
            statuses[name] = e.code
        except Exception as e:  # noqa: BLE001 - shutdown may reset sockets
            statuses[name] = type(e).__name__

    # r1 (2 rows) + r2 (2 rows): r2 overflows max_rows=3 and is deferred
    t1 = threading.Thread(target=go, args=("r1", 2, 0.0))
    t2 = threading.Thread(target=go, args=("r2", 2, 0.05))
    t1.start()
    t2.start()
    time.sleep(0.8)  # r1 dispatched (blocked in the gate), r2 queued
    t0 = time.monotonic()
    srv.stop()  # must fail r2 immediately; r1 unblocks via the gate
    gate.release.set()
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert time.monotonic() - t0 < 20
    assert not t2.is_alive(), "carried request leaked past shutdown"
    assert statuses["r2"] != 200  # failed, not silently served


def test_carry_hot_queue_not_starved_end_to_end(small_model):
    """Satellite: with max_rows=3 and a continuous stream of small
    requests, a 3-row request that keeps overflowing shared batches must
    still be served exactly once (EDF ages it to the front)."""

    model, bg, X, pred = small_model
    model.max_rows = 3
    try:
        srv = ExplainerServer(model, host="127.0.0.1", port=0,
                              max_batch_size=8, pipeline_depth=2,
                              batch_timeout_s=0.05).start()
        base = f"http://127.0.0.1:{srv.port}"
        payloads = {}

        def small(i):
            payloads[f"s{i}"] = _post(base, X[i % 4:i % 4 + 1], timeout=60)[1]

        def big():
            payloads["big"] = _post(base, X[:3], timeout=60)[1]

        threads = [threading.Thread(target=small, args=(i,))
                   for i in range(10)]
        threads.insert(2, threading.Thread(target=big))
        for t in threads:
            t.start()
            time.sleep(0.01)
        for t in threads:
            t.join(timeout=60)
        assert len(payloads) == 11
        big_sv = np.asarray(
            json.loads(payloads["big"])["data"]["shap_values"])
        assert big_sv.shape[1] == 3  # served whole, exactly once
    finally:
        model.max_rows = None
        srv.stop()


def test_fifo_policy_knob_still_serves(small_model):
    model, bg, X, pred = small_model
    srv = ExplainerServer(model, host="127.0.0.1", port=0, max_batch_size=4,
                          pipeline_depth=2, scheduling="fifo").start()
    try:
        url = f"http://127.0.0.1:{srv.port}/explain"
        payload = explain_request(url, X[0])
        assert json.loads(payload)["data"]["shap_values"]
    finally:
        srv.stop()

    with pytest.raises(ValueError, match="policy"):
        ExplainerServer(model, scheduling="lifo")
    with pytest.raises(ValueError, match="default_class"):
        ExplainerServer(model, default_class="vip")
