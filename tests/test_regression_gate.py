"""Tests for the perf-regression gate: history recording, trailing-median
baselines keyed by config fingerprint, pass on identical runs, fail on a
synthetic +30% wall-time entry, torn-tail tolerance, CLI round trip."""

import json
import subprocess
import sys

import pytest

from benchmarks.regression_gate import (
    config_fingerprint,
    gate,
    load_history,
    record_run,
)


@pytest.fixture()
def history(tmp_path):
    return str(tmp_path / "perf_history.jsonl")


CFG = {"requests": 300, "overload": 2.0}


def test_first_run_passes_with_note(history):
    record_run(history, "scheduling", CFG, {"wall_s": 10.0})
    report = gate(history)
    assert report["ok"]
    assert "no prior run" in report["benches"][0]["note"]


def test_two_identical_runs_pass(history):
    record_run(history, "scheduling", CFG,
               {"wall_s": 10.0, "interactive_p99_s": 0.5})
    record_run(history, "scheduling", CFG,
               {"wall_s": 10.1, "interactive_p99_s": 0.52})
    report = gate(history)
    assert report["ok"]
    comp = report["benches"][0]["comparisons"]
    assert not comp["wall_s"]["regressed"]
    assert not comp["interactive_p99_s"]["regressed"]


def test_synthetic_plus_30pct_wall_fails(history):
    for _ in range(3):
        record_run(history, "scheduling", CFG, {"wall_s": 10.0})
    record_run(history, "scheduling", CFG, {"wall_s": 13.0})
    report = gate(history)
    assert not report["ok"]
    comp = report["benches"][0]["comparisons"]["wall_s"]
    assert comp["regressed"] and comp["ratio"] == pytest.approx(1.3)


def test_faster_run_never_fails(history):
    record_run(history, "scheduling", CFG, {"wall_s": 10.0})
    record_run(history, "scheduling", CFG, {"wall_s": 5.0})
    assert gate(history)["ok"]


def test_p99_threshold_is_looser_than_wall(history):
    record_run(history, "scheduling", CFG,
               {"wall_s": 10.0, "interactive_p99_s": 0.5})
    # +30% p99 passes (50% threshold), +30% wall would not (20%)
    record_run(history, "scheduling", CFG,
               {"wall_s": 10.0, "interactive_p99_s": 0.65})
    assert gate(history)["ok"]
    record_run(history, "scheduling", CFG,
               {"wall_s": 10.0, "interactive_p99_s": 0.9})
    assert not gate(history)["ok"]


def test_config_change_starts_fresh_baseline(history):
    record_run(history, "scheduling", CFG, {"wall_s": 10.0})
    record_run(history, "scheduling", {"requests": 600, "overload": 2.0},
               {"wall_s": 30.0})  # 3x slower but a DIFFERENT measurement
    report = gate(history)
    assert report["ok"]
    assert report["benches"][0]["baseline_runs"] == 0


def test_benches_gate_independently(history):
    record_run(history, "scheduling", CFG, {"wall_s": 10.0})
    record_run(history, "chaos", {"requests": 48}, {"wall_s": 20.0})
    record_run(history, "scheduling", CFG, {"wall_s": 10.0})
    record_run(history, "chaos", {"requests": 48}, {"wall_s": 40.0})
    report = gate(history)
    assert not report["ok"]
    by_bench = {r["bench"]: r for r in report["benches"]}
    assert by_bench["scheduling"]["ok"]
    assert not by_bench["chaos"]["ok"]
    # --bench filter gates one benchmark only
    assert gate(history, bench="scheduling")["ok"]


def test_baseline_is_median_of_trailing_n(history):
    # one slow outlier must not poison the baseline
    for wall in (10.0, 10.2, 30.0, 10.1, 10.0):
        record_run(history, "scheduling", CFG, {"wall_s": wall})
    record_run(history, "scheduling", CFG, {"wall_s": 11.0})
    report = gate(history)
    assert report["ok"]
    assert report["benches"][0]["comparisons"]["wall_s"][
        "baseline_median"] == pytest.approx(10.1)


def test_empty_history_and_torn_tail(history):
    report = gate(history)
    assert report["ok"] and "empty history" in report["note"]
    record_run(history, "scheduling", CFG, {"wall_s": 10.0})
    with open(history, "a") as fh:
        fh.write('{"bench": "scheduling", "met')  # torn mid-append
    assert len(load_history(history)) == 1


def test_informational_metrics_are_recorded_not_gated(history):
    record_run(history, "scheduling", CFG,
               {"wall_s": 10.0, "goodput_rps": 40.0})
    record_run(history, "scheduling", CFG,
               {"wall_s": 10.0, "goodput_rps": 10.0})  # 4x worse
    report = gate(history)
    assert report["ok"]
    assert "goodput_rps" not in report["benches"][0]["comparisons"]


def test_replica_seconds_is_gated_like_wall(history):
    """The autoscale bench's provisioning cost: its wall is a fixed
    open-loop trace, so ``replica_seconds`` is the number a scaler
    regression moves — it must gate at the wall threshold, not ride
    along as informational."""

    for _ in range(3):
        record_run(history, "autoscale", CFG,
                   {"wall_s": 80.0, "replica_seconds": 160.0})
    record_run(history, "autoscale", CFG,
               {"wall_s": 80.0, "replica_seconds": 208.0})  # +30%
    report = gate(history)
    assert not report["ok"]
    comp = report["benches"][0]["comparisons"]["replica_seconds"]
    assert comp["regressed"] and comp["ratio"] == pytest.approx(1.3)


def test_newer_different_config_run_cannot_mask_a_regression(history):
    """Gating only the single newest entry would hand a fresh config
    fingerprint a free 'new baseline' pass that buries the regressed
    run recorded just before it; every fingerprint in the recent window
    is gated on its own."""

    for _ in range(3):
        record_run(history, "scheduling", CFG, {"wall_s": 10.0})
    record_run(history, "scheduling", CFG, {"wall_s": 13.0})  # +30%
    record_run(history, "scheduling", {"requests": 50}, {"wall_s": 2.0})
    report = gate(history)
    assert not report["ok"]
    regressed = [r for r in report["benches"]
                 if r["comparisons"].get("wall_s", {}).get("regressed")]
    assert len(regressed) == 1


def test_failed_runs_never_enter_the_baseline(history):
    """A run whose own checks failed (inflated wall from timeouts) is
    recorded for history but excluded from the baseline median — it must
    not mask a later genuine regression."""

    record_run(history, "scheduling", CFG, {"wall_s": 10.0},
               extra={"checks_ok": True})
    record_run(history, "scheduling", CFG, {"wall_s": 30.0},
               extra={"checks_ok": False})  # flaky run, 3x wall
    record_run(history, "scheduling", CFG, {"wall_s": 13.0},
               extra={"checks_ok": True})
    report = gate(history)
    comp = report["benches"][0]["comparisons"]["wall_s"]
    assert comp["baseline_median"] == pytest.approx(10.0)
    assert not report["ok"]  # +30% vs the honest baseline


def test_config_fingerprint_is_order_insensitive():
    assert config_fingerprint({"a": 1, "b": 2}) == \
        config_fingerprint({"b": 2, "a": 1})
    assert config_fingerprint({"a": 1}) != config_fingerprint({"a": 2})


def test_cli_record_and_check_round_trip(history):
    argv = [sys.executable, "benchmarks/regression_gate.py",
            "--history", history]
    entry = {"bench": "cli", "config": {"n": 1}, "metrics": {"wall_s": 2.0}}
    out = subprocess.run(argv + ["--record", json.dumps(entry)],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)["bench"] == "cli"
    out = subprocess.run(argv + ["--check"], capture_output=True,
                         text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    # synthetic +30% wall over the CLI fails --check (acceptance demo)
    entry["metrics"]["wall_s"] = 2.6
    subprocess.run(argv + ["--record", json.dumps(entry)],
                   capture_output=True, text=True, timeout=60)
    out = subprocess.run(argv + ["--check"], capture_output=True,
                         text=True, timeout=60)
    assert out.returncode == 1
    report = json.loads(out.stdout)
    assert not report["ok"]
