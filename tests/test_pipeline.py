"""Tests for the shared dispatch/fetch pipeline (``parallel/pipeline.py``).

Round 2 hand-set the in-flight window at each call site (3 on the sharded
paths, 8 on the engine chunk loop); the shared resolver replaces those
constants (VERDICT.md round 2, item 7).  These tests pin: result ordering
under both execution modes, the in-flight bound, exception propagation, and
the resolution priority (explicit > env > RTT-derived, deterministic under
multi-host).
"""

import threading
import time

import numpy as np
import pytest

from distributedkernelshap_tpu.parallel import pipeline as pl


# --------------------------------------------------------------------- #
# run_pipeline
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("threaded", [False, True])
@pytest.mark.parametrize("window", [1, 2, 3, 8])
def test_run_pipeline_preserves_order(threaded, window):
    items = list(range(17))
    out = pl.run_pipeline(items, lambda i: i * 10, lambda h: h + 1,
                          window=window, threaded=threaded)
    assert out == [i * 10 + 1 for i in items]


@pytest.mark.parametrize("threaded", [False, True])
def test_run_pipeline_bounds_in_flight(threaded):
    """At most ``window`` items may be dispatched-but-unfetched."""

    window = 3
    lock = threading.Lock()
    in_flight = {"now": 0, "peak": 0}

    def dispatch(i):
        with lock:
            in_flight["now"] += 1
            in_flight["peak"] = max(in_flight["peak"], in_flight["now"])
        return i

    def fetch(h):
        time.sleep(0.002)  # let dispatch race ahead if unbounded
        with lock:
            in_flight["now"] -= 1
        return h

    out = pl.run_pipeline(list(range(20)), dispatch, fetch,
                          window=window, threaded=threaded)
    assert out == list(range(20))
    assert in_flight["peak"] <= window


@pytest.mark.parametrize("threaded", [False, True])
def test_run_pipeline_propagates_fetch_error(threaded):
    def fetch(h):
        if h == 5:
            raise RuntimeError("boom")
        return h

    with pytest.raises(RuntimeError, match="boom"):
        pl.run_pipeline(list(range(10)), lambda i: i, fetch,
                        window=3, threaded=threaded)


def test_run_pipeline_empty_and_single():
    assert pl.run_pipeline([], lambda i: i, lambda h: h, window=4) == []
    assert pl.run_pipeline([7], lambda i: i, lambda h: h * 2, window=4) == [14]


def test_run_pipeline_threaded_fetches_overlap():
    """Fetches must actually run concurrently in threaded mode (through a
    tunnelled TPU, overlapping D2H round trips is the whole point)."""

    lock = threading.Lock()
    concurrent = {"now": 0, "peak": 0}

    def fetch(h):
        with lock:
            concurrent["now"] += 1
            concurrent["peak"] = max(concurrent["peak"], concurrent["now"])
        time.sleep(0.02)  # hold the slot long enough for others to enter
        with lock:
            concurrent["now"] -= 1
        return h

    out = pl.run_pipeline(list(range(8)), lambda i: i, fetch,
                          window=8, threaded=True)
    assert out == list(range(8))
    assert concurrent["peak"] > 1  # serial mode would never exceed 1


def test_run_pipeline_threaded_stops_dispatch_after_failure():
    """A fatal fetch error must stop further dispatches (fail fast) instead
    of burning the rest of the batch's device work."""

    dispatched = []

    def fetch(h):
        if h == 0:
            raise RuntimeError("fatal")
        time.sleep(0.005)
        return h

    with pytest.raises(RuntimeError, match="fatal"):
        pl.run_pipeline(list(range(50)), lambda i: dispatched.append(i) or i,
                        fetch, window=2, threaded=True)
    # window=2: at most a couple of extra dispatches can slip through before
    # the failure flag is observed
    assert len(dispatched) < 50


# --------------------------------------------------------------------- #
# resolve_window
# --------------------------------------------------------------------- #

def test_resolve_window_explicit_wins(monkeypatch):
    monkeypatch.setenv("DKS_DISPATCH_WINDOW", "7")
    assert pl.resolve_window(5) == 5


def test_resolve_window_env_beats_probe(monkeypatch):
    monkeypatch.setenv("DKS_DISPATCH_WINDOW", "6")
    monkeypatch.setattr(pl, "device_round_trip_s",
                        lambda **kw: pytest.fail("probe must not run"))
    assert pl.resolve_window(None) == 6


def test_resolve_window_clamps_to_items_and_cap(monkeypatch):
    monkeypatch.delenv("DKS_DISPATCH_WINDOW", raising=False)
    assert pl.resolve_window(99, n_items=4) == 4
    assert pl.resolve_window(99) == pl.MAX_WINDOW
    assert pl.resolve_window(0o0, n_items=1) >= 1  # requested=0 → derived path


def test_resolve_window_latency_derived(monkeypatch):
    monkeypatch.delenv("DKS_DISPATCH_WINDOW", raising=False)
    monkeypatch.setattr(pl, "device_round_trip_s", lambda **kw: 0.070)
    assert pl.resolve_window(None) == 8  # tunnelled chip: 1 + ceil(7) = 8
    monkeypatch.setattr(pl, "device_round_trip_s", lambda **kw: 0.001)
    assert pl.resolve_window(None) == 2  # locally attached / CPU backend


def test_resolve_window_probe_failure_falls_back(monkeypatch):
    monkeypatch.delenv("DKS_DISPATCH_WINDOW", raising=False)

    def broken(**kw):
        raise RuntimeError("backend gone")

    monkeypatch.setattr(pl, "device_round_trip_s", broken)
    assert pl.resolve_window(None) == pl.DETERMINISTIC_WINDOW


def test_resolve_window_multihost_is_deterministic(monkeypatch):
    import jax

    monkeypatch.delenv("DKS_DISPATCH_WINDOW", raising=False)
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(pl, "device_round_trip_s",
                        lambda **kw: pytest.fail("probe must not run multihost"))
    assert pl.resolve_window(None) == pl.DETERMINISTIC_WINDOW


def test_resolve_window_multihost_broadcasts_lead_value(monkeypatch):
    """Under multi-host, every process must use the LEAD's resolved window:
    a per-host env/config skew becomes a broadcast-corrected warning, not a
    collective-order desync (ADVICE.md round 3, medium)."""

    import jax
    from jax.experimental import multihost_utils

    monkeypatch.delenv("DKS_DISPATCH_WINDOW", raising=False)
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(pl, "device_round_trip_s",
                        lambda **kw: pytest.fail("probe must not run multihost"))
    seen = {}

    def fake_broadcast(value, **kw):
        seen["local"] = int(value)
        return np.asarray(5)  # the lead resolved 5

    monkeypatch.setattr(multihost_utils, "broadcast_one_to_all",
                        fake_broadcast)
    # this host's env says 7 → broadcast hands back the lead's 5
    monkeypatch.setenv("DKS_DISPATCH_WINDOW", "7")
    assert pl.resolve_window(None) == 5
    assert seen["local"] == 7


def test_resolve_window_multihost_cache_key_symmetric_under_env_skew(
        monkeypatch):
    """The multihost broadcast cache must key on the resolution INPUTS
    ``(requested, DKS_DISPATCH_WINDOW, cap)``, never the locally resolved
    value: under per-host env skew, a value key can collapse two call
    sites into ONE cache entry on one host while the peer keeps TWO —
    asymmetric broadcast (collective) counts across processes, i.e. a
    permanent hang instead of the promised skew warning (ADVICE round 4).
    This simulates both peers' key sequences for the same call-site
    sequence and asserts they perform the same number of broadcasts."""

    import jax
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        pl, "device_round_trip_s",
        lambda **kw: pytest.fail("probe must not run multihost"))

    # two call sites: an unconfigured loop and an explicit request of 5.
    # With DKS_DISPATCH_WINDOW=5 both RESOLVE to 5 (the collision a
    # value-key turns into one cache entry); unset, they resolve to
    # DETERMINISTIC_WINDOW and 5 (two entries either way).
    call_sites = (None, 5)

    def simulate_host(env_value):
        monkeypatch.setattr(pl, "_window_cache", {})
        if env_value is None:
            monkeypatch.delenv("DKS_DISPATCH_WINDOW", raising=False)
        else:
            monkeypatch.setenv("DKS_DISPATCH_WINDOW", env_value)
        broadcasts = []

        def fake_broadcast(value, **kw):
            broadcasts.append(int(value))
            return int(value)

        monkeypatch.setattr(multihost_utils, "broadcast_one_to_all",
                            fake_broadcast)
        for requested in call_sites:
            pl.resolve_window(requested)
            pl.resolve_window(requested)  # repeats must hit the cache
        return len(broadcasts)

    skewed = simulate_host("5")   # env pins 5: both sites resolve to 5
    clean = simulate_host(None)
    assert skewed == len(call_sites)  # a value key would give 1 here
    assert skewed == clean  # symmetric collective counts across peers


def test_resolve_window_non_positive_request_warns_and_degrades(monkeypatch, caplog):
    """Explicit dispatch_window=0 is not 'unset': it warns and falls through
    to env/probe resolution instead of being swallowed by truthiness
    (ADVICE.md round 3, low)."""

    monkeypatch.setenv("DKS_DISPATCH_WINDOW", "4")
    import logging

    with caplog.at_level(logging.WARNING, logger=pl.logger.name):
        assert pl.resolve_window(0) == 4
    assert any("non-positive" in r.message for r in caplog.records)


def test_resolve_window_logs_clamp_of_explicit_request(monkeypatch, caplog):
    import logging

    monkeypatch.delenv("DKS_DISPATCH_WINDOW", raising=False)
    with caplog.at_level(logging.INFO, logger=pl.logger.name):
        assert pl.resolve_window(99) == pl.MAX_WINDOW
    assert any("clamping" in r.message for r in caplog.records)


def test_device_round_trip_is_cached(monkeypatch):
    pl._rtt_cache = None
    first = pl.device_round_trip_s(probes=2, refresh=True)
    assert first >= 0.0
    # a cache hit must not touch the device again: poison the probe body
    import jax.numpy as jnp

    def no_device(*a, **k):
        pytest.fail("cache hit must not re-probe the device")

    monkeypatch.setattr(jnp, "arange", no_device)
    assert pl.device_round_trip_s() == first


# --------------------------------------------------------------------- #
# integration: the engine chunk loop and the sharded slab loop both honour
# an explicit window and produce results identical to the unpipelined path
# --------------------------------------------------------------------- #

def _toy_engine(config=None):
    from distributedkernelshap_tpu.kernel_shap import KernelExplainerEngine

    rng = np.random.default_rng(0)
    bg = rng.normal(size=(12, 6)).astype(np.float32)
    X = rng.normal(size=(40, 6)).astype(np.float32)
    W = rng.normal(size=(6, 3)).astype(np.float32)

    def predict(A):
        import jax.numpy as jnp

        z = A @ W
        return jnp.exp(z) / jnp.exp(z).sum(-1, keepdims=True)

    return KernelExplainerEngine(predict, bg, link='identity', seed=0,
                                 config=config), X


def test_engine_chunked_explain_matches_unchunked():
    from distributedkernelshap_tpu.kernel_shap import EngineConfig

    base, X = _toy_engine()
    ref = base.get_explanation(X, nsamples=64, l1_reg=False)

    chunked, _ = _toy_engine(EngineConfig(instance_chunk=8, dispatch_window=2))
    got = chunked.get_explanation(X, nsamples=64, l1_reg=False)
    for a, b in zip(ref, got):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_transfer_dtype_f16_matches_f32_to_rounding():
    """Opt-in f16 result transfer (ShapConfig.transfer_dtype) halves the
    D2H payload; results must match the f32 path to f16 rounding and stay
    float32-typed on the host."""

    from distributedkernelshap_tpu.kernel_shap import EngineConfig
    from distributedkernelshap_tpu.ops.explain import ShapConfig

    base, X = _toy_engine()
    ref = base.get_explanation(X, nsamples=64, l1_reg=False)

    f16, _ = _toy_engine(EngineConfig(
        shap=ShapConfig(transfer_dtype="float16"), instance_chunk=16))
    got = f16.get_explanation(X, nsamples=64, l1_reg=False)
    for a, b in zip(ref, got):
        assert np.asarray(b).dtype == np.float32
        # f16 rounding is relative (~5e-4 of |phi|): pair rtol with atol
        np.testing.assert_allclose(a, b, atol=1e-3, rtol=2e-3)
    assert f16.last_raw_prediction.dtype == np.float32
    # only phi rides f16 — E[f]/f(x) are tiny and keep full f32 precision
    # (bit-packed alongside the f16 phi in the same single transfer), so
    # the f16 path's additivity report is not degraded by the wire format
    np.testing.assert_array_equal(f16.last_raw_prediction,
                                  base.last_raw_prediction)
    np.testing.assert_array_equal(np.asarray(f16.expected_value),
                                  np.asarray(base.expected_value))


@pytest.mark.parametrize("td", [None, "float16", "bfloat16"])
def test_pack_unpack_transfer_round_trip(td):
    """pack_transfer/unpack_transfer: the wide segment round-trips to the
    transfer dtype's precision, the narrow segment EXACTLY (it is bit-packed
    as f32 even when the wide segment is 16-bit)."""

    import jax.numpy as jnp

    from distributedkernelshap_tpu.ops.explain import (
        pack_transfer,
        unpack_transfer,
    )

    rng = np.random.default_rng(0)
    wide = rng.standard_normal(37).astype(np.float32)
    narrow = rng.standard_normal(5).astype(np.float32)
    packed = pack_transfer(jnp.asarray(wide), jnp.asarray(narrow), td)
    w, n = unpack_transfer(np.asarray(packed), wide.size, td)
    assert w.dtype == np.float32 and n.dtype == np.float32
    np.testing.assert_array_equal(n, narrow)  # exact, regardless of dtype
    if td is None:
        np.testing.assert_array_equal(w, wide)
    else:
        np.testing.assert_allclose(w, wide, rtol=1e-2, atol=1e-3)
