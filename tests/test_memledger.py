"""Device-memory ledger: computed-byte exactness of accounts and
tracked LRU caches, callback gauge rendering, pressure-driven eviction
under a soft budget, tenant label retirement, the /statusz memory
panel, and the disabled escape hatch."""

import gc

import numpy as np
import pytest

from distributedkernelshap_tpu.observability.memledger import (
    DEFAULT_MODEL_LABEL,
    MemLedger,
    approx_nbytes,
    memledger,
    resolve_mem_budget_env,
    resolve_mem_ledger_env,
)
from distributedkernelshap_tpu.observability.metrics import (
    MetricsRegistry,
    validate_exposition,
)


def _arr(n):
    return np.zeros(n, dtype=np.uint8)


# --------------------------------------------------------------------- #
# approx_nbytes
# --------------------------------------------------------------------- #


def test_approx_nbytes_sums_array_leaves_through_containers():
    v = {"a": _arr(10), "b": [_arr(3), ( _arr(4), None)], "c": "hello"}
    assert approx_nbytes(v) == 10 + 3 + 4 + 5
    assert approx_nbytes(b"12345678") == 8
    assert approx_nbytes(object()) == 0
    assert approx_nbytes(None) == 0


def test_env_resolution(monkeypatch):
    monkeypatch.delenv("DKS_MEM_LEDGER", raising=False)
    assert resolve_mem_ledger_env() is True
    monkeypatch.setenv("DKS_MEM_LEDGER", "0")
    assert resolve_mem_ledger_env() is False
    monkeypatch.setenv("DKS_MEM_BUDGET_BYTES", "1024")
    assert resolve_mem_budget_env() == 1024
    monkeypatch.setenv("DKS_MEM_BUDGET_BYTES", "garbage")
    assert resolve_mem_budget_env() == 0


# --------------------------------------------------------------------- #
# accounts: charge/release exactness
# --------------------------------------------------------------------- #


def test_account_charge_release_is_exact():
    led = MemLedger(enabled=True, budget_bytes=0)
    acct = led.account("result_cache")
    acct.charge("k1", 100)
    acct.charge("k2", 50)
    assert led.total_bytes() == 150
    # re-charging a key replaces, never double-counts
    acct.charge("k1", 70)
    assert led.total_bytes() == 120
    assert acct.release("k1") == 70
    assert acct.release("k1") == 0  # idempotent
    assert led.total_bytes() == 50
    assert acct.clear() == 50
    assert led.total_bytes() == 0
    assert led.high_water_bytes() == 150


def test_accounts_are_interned_by_labels():
    led = MemLedger(enabled=True, budget_bytes=0)
    a = led.account("staging", model="m", version=1, path="sampled")
    b = led.account("staging", model="m", version=1, path="sampled")
    assert a is b
    assert led.account("staging", model="m", version=2) is not a


# --------------------------------------------------------------------- #
# TrackedCache: every mutation path mirrors into the ledger
# --------------------------------------------------------------------- #


def test_tracked_cache_mirrors_all_mutation_paths():
    led = MemLedger(enabled=True, budget_bytes=0)
    c = led.tracked_cache("dev_cache")
    c["a"] = _arr(10)
    c["b"] = _arr(20)
    assert led.total_bytes() == 30
    c["a"] = _arr(5)             # replace releases the old charge
    assert led.total_bytes() == 25
    del c["a"]
    assert led.total_bytes() == 20
    c.pop("b")                   # pop routes through __delitem__
    assert led.total_bytes() == 0
    c.update({"x": _arr(7), "y": _arr(8)})   # update via __setitem__
    assert led.total_bytes() == 15
    c.popitem(last=False)        # LRU evict, the engine's idiom
    assert led.total_bytes() == 8
    c.clear()
    assert led.total_bytes() == 0
    assert c.ledger_bytes == 0


def test_tracked_cache_owner_for_key_routes_accounts():
    led = MemLedger(enabled=True, budget_bytes=0)
    c = led.tracked_cache(
        "plan_consts",
        owner_for_key=lambda k: "exact_consts"
        if k[0] == "exact_consts" else "plan_consts")
    c[("exact_consts", "fp")] = _arr(10)
    c[("fp", "plan", 4)] = _arr(6)
    assert led.owner_totals() == {"exact_consts": 10, "plan_consts": 6}


def test_tracked_cache_rebind_relabels_live_charges():
    led = MemLedger(enabled=True, budget_bytes=0)
    c = led.tracked_cache("dev_cache")
    c["k"] = _arr(12)
    assert led.model_totals() == {DEFAULT_MODEL_LABEL: 12}
    c.rebind(model="tenant-a", version=3, path="sampled")
    assert led.model_totals() == {"tenant-a": 12}
    assert led.total_bytes() == 12  # relabeled, not duplicated


def test_dead_cache_finalizer_releases_charges():
    led = MemLedger(enabled=True, budget_bytes=0)
    c = led.tracked_cache("dev_cache")
    c["k"] = _arr(64)
    assert led.total_bytes() == 64
    del c
    gc.collect()
    assert led.total_bytes() == 0


# --------------------------------------------------------------------- #
# metrics rendering
# --------------------------------------------------------------------- #


def test_callback_gauges_render_and_validate():
    led = MemLedger(enabled=True, budget_bytes=4096)
    cache = led.tracked_cache("dev_cache", model="alpha")
    cache["k"] = _arr(100)
    led.account("result_cache").charge("r", 50)
    reg = MetricsRegistry()
    led.attach_metrics(reg)
    text = reg.render()
    assert validate_exposition(text) == []
    gauge = reg.get("dks_device_bytes")
    assert gauge.value(owner="dev_cache", model="alpha") == 100
    assert gauge.value(owner="result_cache",
                       model=DEFAULT_MODEL_LABEL) == 50
    assert "dks_mem_budget_bytes 4096" in text
    assert "dks_mem_high_water_bytes 150" in text


# --------------------------------------------------------------------- #
# pressure: budget, eviction, MRU survival
# --------------------------------------------------------------------- #


def test_pressure_evicts_lru_but_never_mru():
    led = MemLedger(enabled=True, budget_bytes=100)
    c = led.tracked_cache("dev_cache")
    for i in range(5):
        c[i] = _arr(40)      # 200 bytes charged, budget 100
    assert led.pressure_events() > 0
    assert led.evicted_bytes() > 0
    assert led.total_bytes() <= 100
    assert 4 in c            # the most-recently-inserted entry survives
    assert len(c) >= 1


def test_pressure_callback_invoked_with_overage():
    led = MemLedger(enabled=True, budget_bytes=100)
    seen = []

    def cb(overage):
        seen.append(overage)
        return 0

    led.register_pressure_callback(cb)
    acct = led.account("staging")
    acct.charge("big", 150)
    assert seen and seen[0] == 50
    assert led.pressure_events() == 1


def test_pressure_flight_event_recorded():
    from distributedkernelshap_tpu.observability.flightrec import flightrec

    led = MemLedger(enabled=True, budget_bytes=10)
    led.account("staging").charge("x", 25)
    kinds = [e["kind"] for e in flightrec().to_payload()["events"]]
    assert "memory_pressure" in kinds


# --------------------------------------------------------------------- #
# label retirement
# --------------------------------------------------------------------- #


def test_retire_drops_model_and_version_scoped_charges():
    led = MemLedger(enabled=True, budget_bytes=0)
    led.account("dev_cache", model="a", version=1).charge("k", 10)
    led.account("dev_cache", model="a", version=2).charge("k", 20)
    led.account("dev_cache", model="b", version=1).charge("k", 40)
    assert led.retire("a", version=1) == 10
    assert led.model_totals() == {"a": 20, "b": 40}
    assert led.retire("a") == 20
    assert led.model_totals() == {"b": 40}
    assert led.total_bytes() == 40


# --------------------------------------------------------------------- #
# snapshot / statusz panel schema
# --------------------------------------------------------------------- #


def test_snapshot_schema():
    led = MemLedger(enabled=True, budget_bytes=1 << 20)
    cache = led.tracked_cache("dev_cache", model="alpha")
    cache["k"] = _arr(10)
    doc = led.snapshot()
    for key in ("enabled", "total_bytes", "high_water_bytes",
                "budget_bytes", "pressure_events", "evicted_bytes",
                "owners", "models", "reconcile"):
        assert key in doc
    assert doc["total_bytes"] == 10
    assert doc["owners"] == {"dev_cache": 10}
    assert doc["models"] == {"alpha": 10}
    assert "ledger_bytes" in doc["reconcile"]


# --------------------------------------------------------------------- #
# disabled escape hatch
# --------------------------------------------------------------------- #


def test_disabled_ledger_is_inert():
    led = MemLedger(enabled=False, budget_bytes=10)
    c = led.tracked_cache("dev_cache")
    c["k"] = _arr(100)          # caching still works...
    assert np.asarray(c["k"]).nbytes == 100
    assert led.total_bytes() == 0   # ...but nothing is charged
    assert led.pressure_events() == 0
    acct = led.account("staging")
    acct.charge("x", 50)
    assert led.total_bytes() == 0
    assert acct.release("x") == 0
    assert led.snapshot()["enabled"] is False


# --------------------------------------------------------------------- #
# serving integration: statusz panel + engine cache enrollment
# --------------------------------------------------------------------- #


def test_server_statusz_carries_memory_and_profiler_panels():
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    class _Stub:
        max_rows = None

        def explain_batch(self, instances, split_sizes=None):
            return ["{}"] * len(split_sizes or [1])

    server = ExplainerServer(_Stub(), host="127.0.0.1", port=0,
                             cache_bytes=1024, health_interval_s=0)
    detail = server._statusz_detail()
    assert "memory" in detail and "total_bytes" in detail["memory"]
    assert "profiler" in detail
    assert "sampler" in detail["profiler"]
    assert "phases" in detail["profiler"]
    text = server._render_metrics()
    assert "dks_mem_budget_bytes" in text
    assert "dks_prof_samples_total" in text


def test_engine_device_caches_are_ledger_tracked():
    from distributedkernelshap_tpu.observability.memledger import (
        TrackedCache,
    )
    from distributedkernelshap_tpu.models import LinearPredictor
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(0)
    W = rng.normal(size=(4, 2)).astype(np.float32)
    b = np.zeros(2, dtype=np.float32)
    bg = rng.normal(size=(8, 4)).astype(np.float32)
    model = BatchKernelShapModel(LinearPredictor(W, b), bg,
                                 {"link": "identity", "seed": 0}, {})
    engine = model.explainer._explainer
    assert isinstance(engine._dev_cache, TrackedCache)
    assert isinstance(engine._plan_consts_cache, TrackedCache)
    before = memledger().total_bytes()
    model.explain_batch(rng.normal(size=(1, 4)).astype(np.float32),
                        split_sizes=[1])
    assert memledger().total_bytes() >= before
