"""Contract tests for the driver benchmark entry (``bench.py``).

The driver runs ``python bench.py`` once per round and records the LAST
stdout line; the contract is that this line is always ONE parseable JSON
object carrying either a ``value`` (TPU measurement) or an ``error`` — and,
since round 3, error payloads also carry a clearly-labelled
``cpu_fallback_wall_s`` measurement whenever the remaining budget allows
(VERDICT.md round 2, "What's weak" item 8).  These tests pin the helper
behaviour without touching any device backend.
"""

import io
import json
import sys
import time
from contextlib import redirect_stdout
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import bench  # noqa: E402


def _capture(fn, *args, **kwargs):
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = fn(*args, **kwargs)
    return rc, buf.getvalue()


def _pin_bench_env(monkeypatch):
    """Pin every budget knob the tests' expectations assume, so an ambient
    DKS_BENCH_* export can't flip the assertions."""

    monkeypatch.delenv("DKS_BENCH_SKIP_PROBE", raising=False)
    monkeypatch.delenv("DKS_BENCH_PROBE_TIMEOUT", raising=False)
    monkeypatch.setenv("DKS_BENCH_BUDGET", "420")
    monkeypatch.setenv("DKS_BENCH_PROBE_RETRIES", "1")
    monkeypatch.setenv("DKS_BENCH_PROBE_RETRY_DELAY", "20")


def test_emit_error_attaches_fallback_measurement(monkeypatch):
    monkeypatch.setattr(bench, "_cpu_fallback", lambda t: (0.53, None))
    rc, out = _capture(bench._emit_error,
                       {"metric": bench._METRIC, "error": "wedged"},
                       time.monotonic(), 420.0, 100.0)
    assert rc == 1
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["error"] == "wedged"
    assert rec["cpu_fallback_wall_s"] == 0.53
    # the label must make clear this is NOT a TPU number
    assert "NOT a TPU measurement" in rec["cpu_fallback_note"]


def test_emit_error_still_parseable_when_fallback_fails(monkeypatch):
    monkeypatch.setattr(bench, "_cpu_fallback",
                        lambda t: (None, "cpu fallback exceeded 30s"))
    rc, out = _capture(bench._emit_error,
                       {"metric": bench._METRIC, "error": "wedged"},
                       time.monotonic(), 420.0, 100.0)
    assert rc == 1
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["error"] == "wedged"
    assert "cpu_fallback_wall_s" not in rec
    assert rec["cpu_fallback_error"] == "cpu fallback exceeded 30s"


def test_emit_error_caps_fallback_at_reserve(monkeypatch):
    """The fallback must be capped at DKS_BENCH_FALLBACK_RESERVE, not the
    whole remaining budget — total wedged-path wall time has to stay inside
    a ~300 s driver timeout even with DKS_BENCH_BUDGET=420."""

    granted = []
    monkeypatch.setenv("DKS_BENCH_FALLBACK_RESERVE", "100")
    monkeypatch.setattr(bench, "_cpu_fallback",
                        lambda t: granted.append(t) or (0.5, None))
    rc, out = _capture(bench._emit_error,
                       {"metric": bench._METRIC, "error": "wedged"},
                       time.monotonic(), 420.0, 100.0)
    assert rc == 1
    assert granted and granted[0] <= 100.0


def test_cpu_fallback_refuses_without_budget():
    value, err = bench._cpu_fallback(5.0)
    assert value is None
    assert "budget" in err


def test_cpu_fallback_rejects_non_dict_json(monkeypatch):
    """A last line that parses as JSON but isn't an object (a stray '100'
    progress line, say) must not crash the error-emission path."""

    class _Proc:
        returncode = 0

        def communicate(self, timeout=None):
            return b"100\n", b""

    monkeypatch.setattr(bench.subprocess, "Popen",
                        lambda *a, **k: _Proc())
    value, err = bench._cpu_fallback(120.0)
    assert value is None
    assert "without JSON" in err


def test_cpu_fallback_handles_child_without_json(monkeypatch):
    class _Proc:
        returncode = 1

        def communicate(self, timeout=None):
            return b"Traceback (most recent call last):\n  boom\n", b""

    monkeypatch.setattr(bench.subprocess, "Popen",
                        lambda *a, **k: _Proc())
    value, err = bench._cpu_fallback(120.0)
    assert value is None
    assert "without JSON" in err


def test_cpu_fallback_parses_child_json(monkeypatch):
    class _Proc:
        returncode = 0

        def communicate(self, timeout=None):
            line = json.dumps({"metric": bench._METRIC + "_cpu_fallback",
                               "value": 0.61, "unit": "s"})
            return ("some warning line\n" + line + "\n").encode(), b""

    monkeypatch.setattr(bench.subprocess, "Popen",
                        lambda *a, **k: _Proc())
    value, err = bench._cpu_fallback(120.0)
    assert err is None
    assert value == 0.61


def test_probe_retry_only_on_timeout_failures(monkeypatch):
    """The probe phase retries ONLY the transient wedged-relay signature
    (timeout); fast-failing probes are permanent errors."""

    calls = []

    def fake_probe(timeout_s):
        calls.append(timeout_s)
        if len(calls) == 1:
            return False, f"backend init did not complete within {timeout_s:.0f}s"
        return True, ""

    monkeypatch.setattr(bench, "_device_probe", fake_probe)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    # run only the probe phase: make the run phase a no-op success
    monkeypatch.setattr(bench.subprocess, "Popen", _succeeding_run_proc)
    _pin_bench_env(monkeypatch)
    rc, out = _capture(bench.main)
    assert rc == 0
    assert len(calls) == 2  # retried the timeout once (default retries=1)
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["value"] == 0.1


def _succeeding_run_proc(*a, **k):
    class _Proc:
        returncode = 0

        def communicate(self, timeout=None):
            return (json.dumps({"metric": bench._METRIC, "value": 0.1,
                                "unit": "s"}) + "\n").encode(), b""

    return _Proc()


def test_wedged_probe_retries_then_reports_fallback(monkeypatch):
    """Both attempts time out (wedged relay): the error JSON still carries
    the labelled CPU measurement and the probe was retried exactly once."""

    calls = []

    def fake_probe(timeout_s):
        calls.append(timeout_s)
        return False, f"backend init did not complete within {timeout_s:.0f}s"

    monkeypatch.setattr(bench, "_device_probe", fake_probe)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setattr(bench, "_cpu_fallback", lambda t: (0.53, None))
    _pin_bench_env(monkeypatch)
    rc, out = _capture(bench.main)
    assert rc == 1
    assert len(calls) == 2
    rec = json.loads(out.strip().splitlines()[-1])
    assert "unreachable" in rec["error"]
    assert rec["cpu_fallback_wall_s"] == 0.53


def test_run_timeout_clamped_to_deadline(monkeypatch):
    """A mid-run device hang must still produce the JSON line inside
    DKS_BENCH_DEADLINE: the run child's timeout is clamped so the kill
    escalation + CPU fallback land before the driver's ~300 s axe."""

    seen = {}

    class _HangingProc:
        returncode = 1

        def communicate(self, timeout=None):
            if timeout is not None and timeout > 20:  # the run-phase wait
                seen["timeout"] = timeout
                raise bench.subprocess.TimeoutExpired("bench", timeout)
            return b"", b""  # kill-escalation waits

        def terminate(self):
            pass

        def kill(self):
            pass

    monkeypatch.setattr(bench.subprocess, "Popen",
                        lambda *a, **k: _HangingProc())
    monkeypatch.setattr(bench, "_device_probe", lambda t: (True, ""))
    monkeypatch.setattr(bench, "_cpu_fallback", lambda t: (0.5, None))
    _pin_bench_env(monkeypatch)
    monkeypatch.setenv("DKS_BENCH_DEADLINE", "280")
    monkeypatch.setenv("DKS_BENCH_FALLBACK_RESERVE", "100")
    rc, out = _capture(bench.main)
    assert rc == 1
    # 280 deadline - 100 fallback reserve - 20 escalation margin ≈ 160
    assert seen["timeout"] <= 160.5
    rec = json.loads(out.strip().splitlines()[-1])
    assert "exceeded the remaining budget" in rec["error"]
    assert rec["cpu_fallback_wall_s"] == 0.5


def test_probe_permanent_failure_does_not_retry(monkeypatch):
    calls = []

    def fake_probe(timeout_s):
        calls.append(timeout_s)
        return False, "ImportError: no backend"

    monkeypatch.setattr(bench, "_device_probe", fake_probe)
    monkeypatch.setattr(bench, "_cpu_fallback", lambda t: (0.5, None))
    _pin_bench_env(monkeypatch)
    rc, out = _capture(bench.main)
    assert rc == 1
    assert len(calls) == 1
    rec = json.loads(out.strip().splitlines()[-1])
    assert "error" in rec and rec["cpu_fallback_wall_s"] == 0.5


def test_emit_error_attaches_cached_onchip_run(monkeypatch, tmp_path):
    """One healthy relay window anywhere in the round must be enough for
    the driver artifact to carry an on-chip number (VERDICT r3 #1): the
    wedged-path error JSON attaches the cached success, labelled with its
    age and explicitly NOT as this invocation's measurement."""

    from benchmarks import _evidence

    cache = tmp_path / "bench_last_success.json"
    cache.write_text(json.dumps({
        "metric": bench._METRIC, "value": 0.15, "unit": "s",
        "vs_baseline": 833.7, "platform": "tpu",
        "protocol": "tpu_revalidate:config:adult",
        "data_provenance": "uci", "captured_unix": time.time() - 7200}))
    monkeypatch.setattr(_evidence, "CACHE_PATH", str(cache))
    monkeypatch.setattr(bench, "_cpu_fallback", lambda t: (0.53, None))
    rc, out = _capture(bench._emit_error,
                       {"metric": bench._METRIC, "error": "wedged"},
                       time.monotonic(), 420.0, 100.0)
    assert rc == 1
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["last_onchip"]["value"] == 0.15
    assert rec["last_onchip"]["platform"] == "tpu"
    # the record says WHICH protocol captured it (any protocol may feed
    # the shared cache since round 5 — benchmarks/_evidence.py)
    assert rec["last_onchip"]["protocol"] == "tpu_revalidate:config:adult"
    assert 1.9 < rec["last_onchip"]["age_hours"] < 2.1
    assert "NOT measured" in rec["last_onchip"]["note"]
    # the cached number must never migrate into the top-level value slot
    assert "value" not in rec


def test_emit_error_ignores_corrupt_onchip_cache(monkeypatch, tmp_path):
    from benchmarks import _evidence

    cache = tmp_path / "bench_last_success.json"
    cache.write_text("not json{")
    monkeypatch.setattr(_evidence, "CACHE_PATH", str(cache))
    monkeypatch.setattr(bench, "_cpu_fallback", lambda t: (0.53, None))
    rc, out = _capture(bench._emit_error,
                       {"metric": bench._METRIC, "error": "wedged"},
                       time.monotonic(), 420.0, 100.0)
    assert rc == 1
    rec = json.loads(out.strip().splitlines()[-1])
    assert "last_onchip" not in rec
    assert rec["cpu_fallback_wall_s"] == 0.53
