"""Property-based tests (hypothesis) for the pure numerical building blocks
and the masked-evaluation equivalence invariant.

The reference ships no tests (SURVEY.md §4); the seeded unit suite pins the
documented cases, and these properties sweep the input space for the
invariants the pipeline's correctness rests on: coalition-plan structure,
the summing-matrix reduction vs a direct ``np.add.reduceat``, batching
round-trips, and permutation inversion.
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from distributedkernelshap_tpu.kernel_shap import sum_categories
from distributedkernelshap_tpu.ops.coalitions import coalition_plan
from distributedkernelshap_tpu.parallel.distributed import invert_permutation
from distributedkernelshap_tpu.utils import batch


@settings(max_examples=40, deadline=None)
@given(M=st.integers(1, 14), nsamples=st.integers(4, 600),
       seed=st.integers(0, 2**20))
def test_coalition_plan_invariants(M, nsamples, seed):
    plan = coalition_plan(M, nsamples=nsamples, seed=seed)
    mask, w = np.asarray(plan.mask), np.asarray(plan.weights)

    assert mask.shape == (plan.n_rows, M)
    assert set(np.unique(mask)) <= {0.0, 1.0}
    assert np.all(np.isfinite(w)) and np.all(w >= 0)
    assert w.sum() > 0

    if M > 1:
        sizes = mask.sum(1)
        # empty and grand coalitions are excluded (handled analytically by
        # the additivity constraint, like shap 0.35)
        live = w > 0
        assert np.all(sizes[live] >= 1) and np.all(sizes[live] <= M - 1)
        # no duplicate live coalitions: duplicates must have been merged
        live_rows = mask[live]
        assert len({r.tobytes() for r in live_rows}) == live_rows.shape[0]

    # exactness flag matches the enumerable-space condition
    if M > 1 and 2 ** M - 2 <= nsamples:
        assert plan.exact
        assert plan.n_enumerated == 2 ** M - 2
    elif M > 1:
        assert not plan.exact
        assert plan.n_rows <= nsamples


@settings(max_examples=40, deadline=None)
@given(st.data())
def test_sum_categories_matches_reduceat(data):
    """The summing-matrix implementation must equal the reference's
    ``np.add.reduceat`` formulation for arbitrary block layouts."""

    rng_seed = data.draw(st.integers(0, 2**20))
    rng = np.random.default_rng(rng_seed)
    n_blocks = data.draw(st.integers(1, 4))
    widths = [data.draw(st.integers(2, 4)) for _ in range(n_blocks)]
    gaps = [data.draw(st.integers(0, 2)) for _ in range(n_blocks + 1)]

    start_idx, pos = [], gaps[0]
    for wd, gap in zip(widths, gaps[1:]):
        start_idx.append(pos)
        pos += wd + gap
    D = pos
    values = rng.normal(size=(5, D))

    out = sum_categories(values, start_idx, widths)

    # direct reference formulation: walk columns, summing each block
    expected_cols = []
    col = 0
    blocks = dict(zip(start_idx, widths))
    while col < D:
        if col in blocks:
            expected_cols.append(values[:, col:col + blocks[col]].sum(1))
            col += blocks[col]
        else:
            expected_cols.append(values[:, col])
            col += 1
    expected = np.stack(expected_cols, 1)
    np.testing.assert_allclose(out, expected, atol=1e-12)
    assert out.shape[1] == D - sum(widths) + n_blocks


@settings(max_examples=40, deadline=None)
@given(n=st.integers(1, 64),
       d=st.integers(1, 5),
       batch_size=st.one_of(st.none(), st.integers(1, 70)),
       n_batches=st.integers(1, 8))
def test_batch_partition_roundtrip(n, d, batch_size, n_batches):
    """`utils.batch` must partition: concatenation restores the input, and
    fixed-size mode produces ceil(n/batch_size) chunks of at most that size."""

    X = np.arange(n * d, dtype=np.float32).reshape(n, d)
    chunks = batch(X, batch_size=batch_size, n_batches=n_batches)
    np.testing.assert_array_equal(np.concatenate(chunks, 0), X)
    if batch_size:
        assert len(chunks) == -(-n // batch_size)
        assert all(c.shape[0] <= batch_size for c in chunks)


@settings(max_examples=8, deadline=None)
@given(seed=st.integers(0, 2**20),
       D=st.integers(2, 10),
       K=st.integers(1, 4),
       link=st.sampled_from(["identity", "logit"]),
       grouped=st.booleans())
def test_pipeline_additivity_random_problems(seed, D, K, link, grouped):
    """Σφ + E[f] == link(f(x)) must hold for arbitrary problem shapes
    through the full jitted pipeline — the structural constraint of the
    WLS solve (SURVEY.md §2.2 oracle 1)."""

    import jax
    import jax.numpy as jnp

    from distributedkernelshap_tpu.models import LinearPredictor
    from distributedkernelshap_tpu.ops import (
        build_explainer_fn, coalition_plan, groups_to_matrix)
    from distributedkernelshap_tpu.ops.explain import ShapConfig

    rng = np.random.default_rng(seed)
    B, N = 3, 6
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    groups = None
    if grouped and D >= 4:
        half = D // 2
        groups = [list(range(half)), list(range(half, D))]
    activation = "softmax" if (link == "logit" and K > 1) else "identity"
    if link == "logit" and K == 1:
        activation = "sigmoid"
    pred = LinearPredictor(W, b, activation=activation)

    G = groups_to_matrix(groups, D)
    plan = coalition_plan(G.shape[0], nsamples=64, seed=seed)
    fn = jax.jit(build_explainer_fn(pred, ShapConfig(link=link)))
    out = fn(jnp.asarray(X), jnp.asarray(bg), jnp.ones(N, jnp.float32),
             jnp.asarray(plan.mask), jnp.asarray(plan.weights), jnp.asarray(G))
    phi = np.asarray(out["shap_values"])
    total = phi.sum(-1) + np.asarray(out["expected_value"])[None, :]
    np.testing.assert_allclose(total, np.asarray(out["raw_prediction"]),
                               atol=5e-4)


@settings(max_examples=40, deadline=None)
@given(n=st.integers(1, 100), seed=st.integers(0, 2**20))
def test_invert_permutation_property(n, seed):
    p = np.random.default_rng(seed).permutation(n)
    s = invert_permutation(list(p))
    np.testing.assert_array_equal(np.asarray(p)[s], np.arange(n))
    np.testing.assert_array_equal(s[p], np.arange(n))


@settings(max_examples=40, deadline=None)
@given(height=st.integers(1, 12), width=st.integers(1, 12),
       patch=st.integers(1, 6), channels=st.integers(1, 3))
def test_superpixel_groups_partition(height, width, patch, channels):
    """Superpixel groups must exactly partition the flattened pixel columns
    for any image geometry, including ragged edges."""

    from distributedkernelshap_tpu.ops.image import superpixel_groups

    groups, names = superpixel_groups(height, width, patch, channels=channels)
    cols = [c for g in groups for c in g]
    assert sorted(cols) == list(range(height * width * channels))
    assert len(names) == len(groups) == (-(-height // patch)) * (-(-width // patch))
    assert all(len(g) <= patch * patch * channels for g in groups)


@settings(max_examples=10, deadline=None)
@given(seed=st.integers(0, 2**20), n=st.integers(12, 40),
       d=st.integers(2, 6), k=st.integers(1, 5))
def test_kmeans_summary_properties(seed, n, d, k):
    """shap.kmeans parity invariants: every centroid coordinate is an
    actually-observed value in its column (so integer/one-hot columns stay
    valid), and the cluster weights partition the dataset."""

    from distributedkernelshap_tpu.ops.summarise import kmeans_summary

    rng = np.random.default_rng(seed)
    # mix of continuous and integer-ish columns
    data = rng.normal(size=(n, d))
    data[:, 0] = rng.integers(0, 3, size=n)

    summary = kmeans_summary(data, k, seed=0)
    centers = np.asarray(summary.data)
    weights = np.asarray(summary.weights)

    assert centers.shape == (k, d)
    for j in range(d):
        observed = set(np.round(data[:, j], 12))
        assert all(np.round(c, 12) in observed for c in centers[:, j])
    # DenseData normalises weights to sum 1; occupancy counts are recovered
    # by scaling back with n and must be whole and partition the dataset
    np.testing.assert_allclose(weights.sum(), 1.0, atol=1e-12)
    counts = weights * n
    np.testing.assert_allclose(counts, np.round(counts), atol=1e-9)
    assert np.all(counts >= 0) and counts.sum() == pytest.approx(n)


@settings(max_examples=20, deadline=None)
@given(st.data())
def test_masked_ey_equivalence_random_shapes(data_st):
    """The structure-aware masked evaluation must equal the row-materialising
    generic path for every fast-path family across random shapes — the
    invariant whose violation exposed the TPU fused tree-eval
    miscompilation (benchmarks/tpu_regression_check.py)."""

    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.neural_network import MLPClassifier
    from sklearn.svm import SVC

    from distributedkernelshap_tpu.models import as_predictor
    from distributedkernelshap_tpu.ops.coalitions import coalition_plan
    from distributedkernelshap_tpu.ops.explain import _ey_generic, groups_to_matrix

    seed = data_st.draw(st.integers(0, 2 ** 16), label="seed")
    B = data_st.draw(st.integers(1, 12), label="B")
    N = data_st.draw(st.integers(1, 24), label="N")
    S = data_st.draw(st.integers(4, 48), label="nsamples")
    D = data_st.draw(st.integers(3, 8), label="D")
    family = data_st.draw(st.sampled_from(["tree", "svm", "mlp"]), label="family")
    grouped = data_st.draw(st.booleans(), label="grouped")

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(150, D))
    y = (X[:, 0] + 0.5 * X[:, 1 % D] > 0).astype(int)
    if y.min() == y.max():
        y[0] = 1 - y[0]
    if family == "tree":
        method = GradientBoostingClassifier(
            n_estimators=4, max_depth=3, random_state=0).fit(X, y).predict_proba
    elif family == "svm":
        method = SVC(kernel="rbf", random_state=0).fit(X, y).decision_function
    else:
        method = MLPClassifier((6,), max_iter=40,
                               random_state=0).fit(X, y).predict_proba
    pred = as_predictor(method, example_dim=D)
    if not getattr(pred, "supports_masked_ey", False):
        return  # probe rejected the lift for this draw; nothing to compare

    groups = None
    if grouped and D >= 4:
        cols = list(range(D))
        groups = [cols[:2], cols[2:3], cols[3:]]
    G = groups_to_matrix(groups, D)
    plan = coalition_plan(G.shape[0], nsamples=S, seed=0)
    mask = np.asarray(plan.mask, np.float32)
    Xe = X[:B].astype(np.float32)
    bg = X[50:50 + N].astype(np.float32)
    bgw = np.full(N, 1.0 / N, np.float32)
    ey_rows = np.asarray(_ey_generic(pred, Xe, bg, bgw, mask @ G, chunk=7))
    ey_fast = np.asarray(pred.masked_ey(Xe, bg, bgw, mask, G))
    scale = max(1.0, np.abs(ey_rows).max())
    np.testing.assert_allclose(ey_fast, ey_rows, atol=3e-4 * scale)


@settings(max_examples=15, deadline=None)
@given(seed=st.integers(0, 2**20), B=st.integers(1, 13),
       S=st.integers(2, 70), N=st.integers(1, 9), M=st.integers(1, 6),
       K=st.integers(2, 4))
def test_ey_linear_fallback_matches_dense_random_shapes(seed, B, S, N, M, K):
    """The chunked XLA fallback (binary sigmoid-of-difference shortcut at
    K=2, general softmax otherwise) equals the dense synthetic-row formula
    at arbitrary shapes — guards the shortcut's padding/trim and the
    doubled-chunk logic across shape space."""

    import jax.numpy as jnp

    from distributedkernelshap_tpu.ops.explain import _ey_linear

    rng = np.random.default_rng(seed)
    D = 2 * M
    X = rng.normal(size=(B, D)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    G = np.zeros((M, D), np.float32)
    for m in range(M):
        G[m, 2 * m:2 * m + 2] = 1.0
    mask = (rng.random(size=(S, M)) < 0.5).astype(np.float32)
    bgw = rng.random(N).astype(np.float32) + 0.1
    bgw /= bgw.sum()

    zc = mask @ G
    masked = (X[:, None, None, :] * zc[None, :, None, :]
              + bg[None, None] * (1.0 - zc[None, :, None, :]))
    logits = masked @ W + b
    e = np.exp(logits - logits.max(-1, keepdims=True))
    ref = np.einsum("bsnk,n->bsk", e / e.sum(-1, keepdims=True), bgw)

    chunk = int(rng.integers(1, S + 1))
    got = np.asarray(_ey_linear(
        jnp.asarray(W), jnp.asarray(b), "softmax", jnp.asarray(X),
        jnp.asarray(bg), jnp.asarray(bgw), jnp.asarray(mask),
        jnp.asarray(G), chunk, use_pallas=False))
    np.testing.assert_allclose(got, ref, atol=2e-5)


@settings(max_examples=12, deadline=None)
@given(seed=st.integers(0, 2**20), S=st.integers(40, 150),
       p=st.integers(3, 11), T=st.integers(1, 6),
       crit=st.sampled_from(["aic", "bic"]))
def test_lars_batch_matches_sklearn_property(seed, S, p, T, crit):
    """Round-4 batched Gram-space LARS: per-target selections must equal
    sklearn's LassoLarsIC over random (possibly correlated) designs —
    fresh examples every fuzz run extend the fixed-seed oracle sweep."""

    import warnings

    from sklearn.linear_model import LassoLarsIC

    from distributedkernelshap_tpu.kernel_shap import _l1_select_batch

    rng = np.random.default_rng(seed)
    mix = np.eye(p) + 0.5 * rng.normal(size=(p, p)) / np.sqrt(p)
    Xw = rng.normal(size=(S, p)) @ mix
    C = rng.normal(size=(p, T)) * (rng.random(size=(p, T)) < 0.5)
    Yw = Xw @ C + 0.1 * rng.normal(size=(S, T))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        got = _l1_select_batch(Xw, Yw, crit)
        for t in range(T):
            want = np.nonzero(
                LassoLarsIC(criterion=crit).fit(Xw, Yw[:, t]).coef_)[0]
            np.testing.assert_array_equal(got[t], want)


@settings(max_examples=20, deadline=None)
@given(seed=st.integers(0, 2**20), n_wide=st.integers(1, 200),
       n_narrow=st.integers(1, 40),
       td=st.sampled_from([None, "float16", "bfloat16"]))
def test_pack_transfer_roundtrip_property(seed, n_wide, n_narrow, td):
    """pack/unpack_transfer: the narrow segment round-trips EXACTLY for
    every dtype and odd segment length (the bit-packing must survive
    misaligned boundaries); the wide segment to its dtype's precision."""

    import jax.numpy as jnp

    from distributedkernelshap_tpu.ops.explain import (
        pack_transfer,
        unpack_transfer,
    )

    rng = np.random.default_rng(seed)
    wide = (rng.standard_normal(n_wide) * 4).astype(np.float32)
    narrow = (rng.standard_normal(n_narrow) * 4).astype(np.float32)
    packed = pack_transfer(jnp.asarray(wide), jnp.asarray(narrow), td)
    w, n = unpack_transfer(np.asarray(packed), n_wide, td)
    np.testing.assert_array_equal(n, narrow)
    if td is None:
        np.testing.assert_array_equal(w, wide)
    else:
        np.testing.assert_allclose(w, wide, rtol=2e-2, atol=1e-2)


@settings(max_examples=6, deadline=None)
@given(seed=st.integers(0, 2**20), n_est=st.integers(1, 5),
       depth=st.integers(2, 4), N=st.integers(5, 40),
       B=st.integers(1, 9), grouped=st.booleans())
def test_exact_pallas_kernels_match_einsum_property(seed, n_est, depth, N,
                                                    B, grouped):
    """Round-4 fused exact kernels vs the einsum paths on random small
    ensembles/backgrounds — main effects AND interactions, every fuzz run
    a fresh model."""

    from sklearn.ensemble import GradientBoostingRegressor

    from distributedkernelshap_tpu.models import as_predictor
    from distributedkernelshap_tpu.ops import groups_to_matrix
    from distributedkernelshap_tpu.ops.treeshap import (
        background_reach,
        exact_interactions_from_reach,
        exact_shap_from_reach,
    )

    rng = np.random.default_rng(seed)
    D = 6
    Xtr = rng.normal(size=(120, D))
    y = Xtr[:, 0] * np.where(Xtr[:, 1] > 0, 1.0, -1.5) + 0.3 * Xtr[:, 2]
    gbt = GradientBoostingRegressor(n_estimators=n_est, max_depth=depth,
                                    random_state=seed % 1000).fit(Xtr, y)
    pred = as_predictor(gbt.predict, example_dim=D,
                        probe_data=Xtr[:8].astype(np.float32))
    from distributedkernelshap_tpu.models.trees import TreeEnsemblePredictor

    # a probe regression must fail the sweep loudly, not die as an opaque
    # AttributeError inside background_reach
    assert isinstance(pred, TreeEnsemblePredictor)
    X = Xtr[:B].astype(np.float32)
    bg = Xtr[50:50 + N].astype(np.float32)
    bgw = (rng.random(N) + 0.2).astype(np.float32)
    groups = [[0, 1], [2], [3, 4]] if grouped else None
    G = groups_to_matrix(groups, D)
    reach = background_reach(pred, bg, G)
    ref = np.asarray(exact_shap_from_reach(
        pred, X, reach, bgw, G, use_pallas=False))
    got = np.asarray(exact_shap_from_reach(
        pred, X, reach, bgw, G, use_pallas=True))
    np.testing.assert_allclose(got, ref, atol=3e-5, rtol=3e-5)
    ref_i = np.asarray(exact_interactions_from_reach(
        pred, X, reach, bgw, G, use_pallas=False))
    got_i = np.asarray(exact_interactions_from_reach(
        pred, X, reach, bgw, G, use_pallas=True))
    np.testing.assert_allclose(got_i, ref_i, atol=5e-5, rtol=5e-5)
