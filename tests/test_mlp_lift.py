"""Native lifting of sklearn MLPs (models/predictors.py:MLPPredictor).

Same contract as the linear/tree lifts: the lifted network must reproduce
sklearn's own outputs (probe-gated in ``as_predictor``), and the full
KernelShap pipeline over it must satisfy additivity.
"""

import numpy as np
import pytest

from distributedkernelshap_tpu.models import MLPPredictor, as_predictor
from distributedkernelshap_tpu.models.predictors import _lift_sklearn_mlp


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 5))
    y3 = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    yr = np.tanh(X[:, 0]) * 50.0 + X[:, 1]
    return X, y3, yr


def _check(method, X, atol=2e-5):
    lifted = _lift_sklearn_mlp(method)
    assert lifted is not None
    expected = np.asarray(method(X), dtype=np.float64)
    if expected.ndim == 1:
        expected = expected[:, None]
    got = np.asarray(lifted(X.astype(np.float32)), dtype=np.float64)
    scale = max(1.0, np.abs(expected).max())
    np.testing.assert_allclose(got, expected, atol=atol * scale)
    return lifted


@pytest.mark.parametrize("activation", ["relu", "tanh", "logistic"])
def test_mlp_classifier_binary(data, activation):
    from sklearn.neural_network import MLPClassifier

    X, y3, _ = data
    clf = MLPClassifier((8,), activation=activation, max_iter=80,
                        random_state=0).fit(X, (y3 > 0).astype(int))
    lifted = _check(clf.predict_proba, X[:64])
    assert lifted.n_outputs == 2 and lifted.out_activation == "binary_sigmoid"


def test_mlp_classifier_multiclass(data):
    from sklearn.neural_network import MLPClassifier

    X, y3, _ = data
    clf = MLPClassifier((8, 6), max_iter=80, random_state=0).fit(X, y3)
    lifted = _check(clf.predict_proba, X[:64])
    assert lifted.n_outputs == 3 and lifted.out_activation == "softmax"


def test_mlp_classifier_multilabel(data):
    """out_activation_='logistic' with several output logits (multilabel):
    lifted as elementwise sigmoids, matching sklearn's per-label proba."""

    from sklearn.neural_network import MLPClassifier

    X, y3, _ = data
    Y = np.stack([(y3 > 0).astype(int), (y3 > 1).astype(int)], axis=1)
    clf = MLPClassifier((8,), max_iter=80, random_state=0).fit(X, Y)
    assert clf.out_activation_ == "logistic"
    lifted = _check(clf.predict_proba, X[:64])
    assert lifted.out_activation == "sigmoid" and lifted.n_outputs == 2


def test_mlp_regressor(data):
    from sklearn.neural_network import MLPRegressor

    X, _, yr = data
    reg = MLPRegressor(hidden_layer_sizes=(10,), max_iter=150, random_state=0).fit(X, yr)
    lifted = _check(reg.predict, X[:64])
    assert not lifted.vector_out


def test_mlp_label_predict_not_lifted(data):
    from sklearn.neural_network import MLPClassifier

    X, y3, _ = data
    clf = MLPClassifier((4,), max_iter=30, random_state=0).fit(X, y3)
    assert _lift_sklearn_mlp(clf.predict) is None


def test_as_predictor_routes_mlp(data):
    from sklearn.neural_network import MLPClassifier

    X, y3, _ = data
    clf = MLPClassifier((6,), max_iter=60, random_state=0).fit(X, y3)
    pred = as_predictor(clf.predict_proba, example_dim=X.shape[1])
    assert isinstance(pred, MLPPredictor)


def test_masked_ey_matches_row_eval(data):
    """The first-layer-separated masked evaluation equals materialising
    every synthetic row, with and without grouping."""

    from distributedkernelshap_tpu.ops.coalitions import coalition_plan
    from distributedkernelshap_tpu.ops.explain import _ey_generic, groups_to_matrix

    from sklearn.neural_network import MLPClassifier

    X, y3, _ = data
    clf = MLPClassifier((8, 6), max_iter=80, random_state=0).fit(X, y3)
    pred = _lift_sklearn_mlp(clf.predict_proba)
    assert pred.supports_masked_ey
    for groups in (None, [[0, 1], [2], [3, 4]]):
        G = groups_to_matrix(groups, X.shape[1])
        plan = coalition_plan(G.shape[0], nsamples=30, seed=0)
        Xe = X[:9].astype(np.float32)
        bg = X[100:117].astype(np.float32)
        bgw = np.full(bg.shape[0], 1.0 / bg.shape[0], np.float32)
        mask = np.asarray(plan.mask, np.float32)
        ey_rows = np.asarray(_ey_generic(pred, Xe, bg, bgw, mask @ G, chunk=8))
        ey_fast = np.asarray(pred.masked_ey(Xe, bg, bgw, mask, G))
        np.testing.assert_allclose(ey_fast, ey_rows, atol=2e-5)


def test_masked_ey_tiny_chunks(data):
    from distributedkernelshap_tpu.ops.coalitions import coalition_plan
    from distributedkernelshap_tpu.ops.explain import groups_to_matrix

    from sklearn.neural_network import MLPClassifier

    X, y3, _ = data
    clf = MLPClassifier((7,), max_iter=60, random_state=0).fit(X, (y3 > 0).astype(int))
    pred = _lift_sklearn_mlp(clf.predict_proba)
    G = groups_to_matrix(None, X.shape[1])
    plan = coalition_plan(G.shape[0], nsamples=22, seed=0)
    Xe = X[:7].astype(np.float32)
    bg = X[100:113].astype(np.float32)
    bgw = np.full(bg.shape[0], 1.0 / bg.shape[0], np.float32)
    mask = np.asarray(plan.mask, np.float32)
    big = np.asarray(pred.masked_ey(Xe, bg, bgw, mask, G))
    tiny = np.asarray(pred.masked_ey(Xe, bg, bgw, mask, G,
                                     target_chunk_elems=1 << 9))
    np.testing.assert_allclose(tiny, big, atol=1e-5)


def test_kernel_shap_end_to_end_mlp(data):
    from sklearn.neural_network import MLPClassifier

    from distributedkernelshap_tpu import KernelShap

    X, y3, _ = data
    y = (y3 > 0).astype(int)
    clf = MLPClassifier((8,), max_iter=120, random_state=0).fit(X, y)
    ex = KernelShap(clf.predict_proba, link="logit", seed=0)
    ex.fit(X[:40])
    assert isinstance(ex._explainer.predictor, MLPPredictor)
    Xe = X[40:56]
    res = ex.explain(Xe, silent=True)
    proba = np.clip(clf.predict_proba(Xe), 1e-7, 1 - 1e-7)
    for k, phi in enumerate(res.shap_values):
        lhs = phi.sum(axis=1) + res.expected_value[k]
        rhs = np.log(proba[:, k] / (1 - proba[:, k]))
        np.testing.assert_allclose(lhs, rhs, atol=5e-3)
