"""Multi-tenant gateway serving tests: routing (header / JSON field /
binary wire field), per-model cache + metric namespaces, hot-swap
in-flight pinning, tenant quotas at the HTTP edge, the /statusz panel,
and header forwarding through the fan-in proxy."""

import http.client
import json
import threading

import numpy as np
import pytest

from distributedkernelshap_tpu.models import LinearPredictor
from distributedkernelshap_tpu.registry import ModelRegistry, TenantQuota
from distributedkernelshap_tpu.serving import wire
from distributedkernelshap_tpu.serving.server import ExplainerServer
from distributedkernelshap_tpu.serving.wrappers import BatchKernelShapModel

D = 6


def _linear_model(seed):
    rng = np.random.default_rng(seed)
    W = rng.normal(size=(D, 2)).astype(np.float32)
    b = rng.normal(size=(2,)).astype(np.float32)
    bg = np.random.default_rng(99).normal(size=(10, D)).astype(np.float32)
    return BatchKernelShapModel(LinearPredictor(W, b, activation="softmax"),
                                bg, {"link": "logit", "seed": 0}, {})


def _post(host, port, body, headers=None, path="/explain"):
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _get(host, port, path):
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path)
        return conn.getresponse().read().decode()
    finally:
        conn.close()


def _json_body(array, model=None):
    doc = {"array": np.asarray(array).tolist()}
    if model is not None:
        doc["model"] = model
    return json.dumps(doc).encode()


@pytest.fixture(scope="module")
def gateway():
    registry = ModelRegistry()
    registry.register("alpha", _linear_model(1))
    registry.register("beta", _linear_model(2))
    server = ExplainerServer(registry=registry, host="127.0.0.1", port=0,
                             max_batch_size=4, batch_timeout_s=0.003,
                             pipeline_depth=2,
                             cache_bytes=1 << 20).start()
    try:
        yield server, registry
    finally:
        server.stop()


def test_routing_header_json_wire_and_default(gateway):
    server, registry = gateway
    row = np.random.default_rng(5).normal(size=(1, D)).astype(np.float32)
    s1, p1 = _post(server.host, server.port, _json_body(row),
                   headers={"X-DKS-Model": "alpha"})
    s2, p2 = _post(server.host, server.port, _json_body(row, model="beta"))
    s3, p3 = _post(server.host, server.port, _json_body(row))  # default
    s4, p4 = _post(server.host, server.port,
                   wire.encode_request(row, model_id="beta"),
                   headers={"Content-Type": wire.CONTENT_TYPE})
    assert (s1, s2, s3, s4) == (200, 200, 200, 200)
    a1 = json.loads(p1)["data"]["shap_values"]
    a2 = json.loads(p2)["data"]["shap_values"]
    assert a1 != a2  # two tenants, two answers for the same row
    assert json.loads(p3)["data"]["shap_values"] == a1  # default = first
    assert json.loads(p4)["data"]["shap_values"] == a2  # wire field routes


def test_header_wins_over_body_field(gateway):
    server, _ = gateway
    row = np.random.default_rng(6).normal(size=(1, D)).astype(np.float32)
    _, p_beta = _post(server.host, server.port, _json_body(row, "beta"))
    s, p = _post(server.host, server.port, _json_body(row, model="beta"),
                 headers={"X-DKS-Model": "alpha"})
    assert s == 200
    _, p_alpha = _post(server.host, server.port,
                       _json_body(row, model="alpha"))
    assert json.loads(p)["data"]["shap_values"] \
        == json.loads(p_alpha)["data"]["shap_values"]
    assert json.loads(p)["data"]["shap_values"] \
        != json.loads(p_beta)["data"]["shap_values"]


def test_unknown_model_404_lists_roster(gateway):
    server, _ = gateway
    row = np.zeros((1, D), np.float32)
    s, p = _post(server.host, server.port, _json_body(row, model="nope"))
    assert s == 404
    doc = json.loads(p)
    assert "unknown model" in doc["error"]
    assert doc["models"] == ["alpha", "beta"]


def test_cache_is_scoped_per_model_fingerprint(gateway):
    server, registry = gateway
    row = np.random.default_rng(7).normal(size=(1, D)).astype(np.float32)
    before = server._cache.stats()
    s1, p1 = _post(server.host, server.port, _json_body(row, "alpha"))
    s2, p2 = _post(server.host, server.port, _json_body(row, "beta"))
    # same rows, different tenants: distinct keys, no cross-tenant hit
    assert s1 == s2 == 200 and p1 != p2
    mid = server._cache.stats()
    assert mid["entries"] >= before["entries"] + 2
    s3, p3 = _post(server.host, server.port, _json_body(row, "alpha"))
    after = server._cache.stats()
    assert s3 == 200 and p3 == p1  # duplicate: bit-identical
    assert after["hits"] == mid["hits"] + 1
    # the key namespace is the registry fingerprint (model@vN:content)
    key = server._cache_key_for(row, rm=registry.resolve("alpha"))
    assert key.startswith(registry.resolve("alpha").fingerprint)


def test_per_model_metrics_and_statusz_panel(gateway):
    server, registry = gateway
    row = np.random.default_rng(8).normal(size=(1, D)).astype(np.float32)
    _post(server.host, server.port, _json_body(row, "alpha"))
    page = _get(server.host, server.port, "/metrics")
    assert 'dks_registry_models{model="alpha",version="1",path="linear"}' \
        in page
    assert 'dks_registry_requests_total{model="alpha"}' in page
    doc = json.loads(_get(server.host, server.port,
                          "/statusz?format=json"))
    panel = doc["detail"]["registry"]
    assert panel["default_model_id"] == "alpha"
    ids = {m["model_id"]: m for m in panel["models"]}
    assert ids["alpha"]["path"] == "linear"
    assert ids["alpha"]["fingerprint"].startswith("alpha@v1:")


def test_single_model_server_ignores_model_field():
    model = _linear_model(3)
    server = ExplainerServer(model, host="127.0.0.1", port=0,
                             max_batch_size=2, pipeline_depth=1).start()
    try:
        row = np.zeros((1, D), np.float32)
        s, p = _post(server.host, server.port,
                     _json_body(row, model="whatever"),
                     headers={"X-DKS-Model": "also-ignored"})
        assert s == 200 and json.loads(p)["data"]["shap_values"]
    finally:
        server.stop()


# --------------------------------------------------------------------- #
# hot swap with a pinned in-flight request (stub models: no jax cost)
# --------------------------------------------------------------------- #


class _GatedStub:
    """Serving stub whose explain blocks until released."""

    def __init__(self, tag, gate=None):
        self.tag = tag
        self.gate = gate

    def explain_batch(self, instances, split_sizes=None):
        if self.gate is not None:
            assert self.gate.wait(timeout=30)
        sizes = split_sizes or [1] * instances.shape[0]
        return [json.dumps({"tag": self.tag}) for _ in sizes]


def test_hot_swap_pins_inflight_requests_to_admitted_version():
    gate = threading.Event()
    registry = ModelRegistry(drain_timeout_s=30.0)
    rm1 = registry.register("m", _GatedStub("v1", gate))
    server = ExplainerServer(registry=registry, host="127.0.0.1", port=0,
                             max_batch_size=2, pipeline_depth=1).start()
    try:
        results = []

        def fire():
            results.append(_post(server.host, server.port,
                                 _json_body(np.zeros((1, 3), np.float32),
                                            "m")))

        t = threading.Thread(target=fire, daemon=True)
        t.start()
        # wait until the request is pinned to v1 (admitted, in flight)
        deadline = threading.Event()
        for _ in range(200):
            if rm1.inflight >= 1:
                break
            deadline.wait(0.02)
        assert rm1.inflight >= 1

        swapped = threading.Event()

        def swap():
            registry.register("m", _GatedStub("v2"))  # drains v1
            swapped.set()

        threading.Thread(target=swap, daemon=True).start()
        # flip is immediate, drain blocks on the pinned request
        for _ in range(200):
            if registry.resolve("m").version == 2:
                break
            deadline.wait(0.02)
        assert registry.resolve("m").version == 2
        assert not swapped.wait(0.2)
        gate.set()  # let v1 finish its in-flight answer
        t.join(timeout=30)
        assert swapped.wait(30)
        # the in-flight request answered on the version that ADMITTED it
        assert results and results[0][0] == 200
        assert json.loads(results[0][1])["tag"] == "v1"
        assert rm1.state == "retired"
        # post-swap requests answer v2
        s, p = _post(server.host, server.port,
                     _json_body(np.zeros((1, 3), np.float32), "m"))
        assert s == 200 and json.loads(p)["tag"] == "v2"
        page = _get(server.host, server.port, "/metrics")
        assert 'dks_registry_swaps_total{model="m"} 2' in page
    finally:
        gate.set()
        server.stop()


def test_tenant_quota_sheds_at_the_edge():
    registry = ModelRegistry()
    registry.register("open", _GatedStub("open"))
    registry.register("capped", _GatedStub("capped"),
                      quota=TenantQuota(rate_per_s=0.001, burst=1))
    server = ExplainerServer(registry=registry, host="127.0.0.1", port=0,
                             max_batch_size=2, pipeline_depth=1).start()
    try:
        row = _json_body(np.zeros((1, 3), np.float32))
        s1, _ = _post(server.host, server.port, row,
                      headers={"X-DKS-Model": "capped"})
        s2, p2 = _post(server.host, server.port, row,
                       headers={"X-DKS-Model": "capped"})
        assert s1 == 200 and s2 == 429
        doc = json.loads(p2)
        assert doc["reason"] == "tenant_rate_limited"
        # the flooding tenant's quota never touches the other tenant
        s3, _ = _post(server.host, server.port, row,
                      headers={"X-DKS-Model": "open"})
        assert s3 == 200
        page = _get(server.host, server.port, "/metrics")
        assert ('dks_registry_sheds_total{model="capped",'
                'reason="tenant_rate_limited"} 1') in page
        assert 'dks_serve_sheds_total{reason="tenant_rate_limited"} 1' \
            in page
    finally:
        server.stop()


def test_fanin_proxy_forwards_model_header():
    from distributedkernelshap_tpu.serving.replicas import FanInProxy

    registry = ModelRegistry()
    registry.register("a", _GatedStub("a"))
    registry.register("b", _GatedStub("b"))
    server = ExplainerServer(registry=registry, host="127.0.0.1", port=0,
                             max_batch_size=2, pipeline_depth=1).start()
    proxy = FanInProxy([(server.host, server.port)],
                       host="127.0.0.1", port=0).start()
    try:
        s, p = _post(proxy.host, proxy.port,
                     _json_body(np.zeros((1, 3), np.float32)),
                     headers={"X-DKS-Model": "b"})
        assert s == 200 and json.loads(p)["tag"] == "b"
    finally:
        proxy.stop()
        server.stop()
