"""Two-process multi-host smoke test.

The TPU-native analog of the reference's only multi-node validation — running
the same code against a real cluster via ``ray.init(address='auto')``
(``benchmarks/k8s_ray_pool.py:90``): here two OS processes join one
``jax.distributed`` runtime over a local coordinator, build a global 4-device
mesh (2 local CPU devices each), and run the sharded Adult explain end to end
with collectives crossing the process boundary (gloo — the DCN stand-in).
"""

import os
import pickle
import socket
import subprocess
import sys

import pytest

from distributedkernelshap_tpu import compat

# With gloo CPU collectives enabled (compat.enable_cpu_collectives, wired
# into initialize_multihost) these tests run REAL cross-process programs:
# each one compiles a sharded explain in two fresh processes, ~4-6 min
# apiece on CI CPUs — far past the tier-1 870 s budget (ROADMAP.md), so
# they run in `make test` / `make multihost-ci`, not `make tier1`.
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# explain recipe shared by the worker template and the in-test reference run
N_INSTANCES = 32
NSAMPLES = 64
N_DEVICES = 4


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_procs(tmp_path, argv_for_pid, timeout=420):
    """Launch two collectively-coupled worker processes and wait for both.

    Logs go to files, not pipes: one process blocking on a full pipe buffer
    would stall the other inside a shared collective.  Returns the per-process
    log texts; asserts both exited 0.
    """

    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    logs = [tmp_path / f"proc{pid}.log" for pid in range(2)]
    procs = []
    try:
        for pid in range(2):
            with open(logs[pid], "wb") as log:
                procs.append(subprocess.Popen(
                    argv_for_pid(pid), cwd=str(tmp_path), env=env,
                    stdout=log, stderr=subprocess.STDOUT))
        for p in procs:
            p.wait(timeout=timeout)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    texts = [log.read_text(errors="replace") for log in logs]
    for pid, p in enumerate(procs):
        assert p.returncode == 0, f"proc {pid} failed:\n{texts[pid][-2000:]}"
    return texts


def _explain_adult(n_devices=N_DEVICES):
    """The shared recipe: fit + explain the Adult slice on a sharded mesh."""

    import numpy as np

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    clf = load_model()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    X = data["all"]["X"]["processed"]["test"].toarray()[:N_INSTANCES]
    bg = data["background"]["X"]["preprocessed"]
    ex = KernelShap(clf.predict_proba, link="logit", feature_names=gn, seed=0,
                    distributed_opts={"n_devices": n_devices})
    ex.fit(bg, group_names=gn, groups=g)
    sv = ex.explain(X, silent=True, nsamples=NSAMPLES, l1_reg=False).shap_values
    return np.stack(sv, 1)


@pytest.mark.parametrize("coalition_parallel", [
    1,
    pytest.param(2, marks=pytest.mark.skipif(
        compat.eager_concat_sums_replicas(),
        reason="multi-process coalition_parallel>1 needs jax.shard_map; "
               "this JAX mis-assembles coalition-replicated results "
               "across processes (mesh.device_mesh rejects it)")),
], ids=["data4", "data2xcoalition2"])
def test_two_process_pool_benchmark(tmp_path, coalition_parallel):
    port = _free_port()
    texts = _run_two_procs(tmp_path, lambda pid: [
        sys.executable, os.path.join(REPO, "benchmarks", "multihost_pool.py"),
        "-b", "8", "-w", str(N_DEVICES), "-n", "1", "--limit", "64",
        "--coalition_parallel", str(coalition_parallel),
        "--platform", "cpu", "--cpu_devices", "2",
        "--coordinator", f"127.0.0.1:{port}",
        "--num_processes", "2", "--process_id", str(pid)])
    for out in texts:
        assert "jax.distributed initialised: 2 processes, 4 devices" in out, out[-2000:]

    # the lead process wrote the reference-format result pickle
    with open(tmp_path / "results" / "ray_workers_4_bsize_8_actorfr_1.0.pkl", "rb") as f:
        result = pickle.load(f)
    assert len(result["t_elapsed"]) == 1 and result["t_elapsed"][0] > 0


_PHI_WORKER = """
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from distributedkernelshap_tpu.compat import force_cpu_devices
force_cpu_devices(2)
pid = int(sys.argv[1])
from distributedkernelshap_tpu.parallel.mesh import initialize_multihost
initialize_multihost("127.0.0.1:" + sys.argv[2], 2, pid)
assert jax.process_count() == 2

import numpy as np
sys.path.insert(0, {tests_dir!r})
from test_multihost import _explain_adult
np.save(sys.argv[3] + "/phi_" + str(pid) + ".npy", _explain_adult())
"""


def test_two_process_phi_matches_single_process(tmp_path):
    """Cross-process numerical equivalence: the sharded explain over a
    2-process mesh must produce exactly the same SHAP values on every
    process, and match a single-process run of the same plan (the
    sequential==distributed oracle, SURVEY.md §4, across a real process
    boundary)."""

    import numpy as np

    port = _free_port()
    worker = tmp_path / "worker.py"
    worker.write_text(_PHI_WORKER.format(
        repo=REPO, tests_dir=os.path.dirname(os.path.abspath(__file__))))
    _run_two_procs(tmp_path, lambda pid: [
        sys.executable, str(worker), str(pid), str(port), str(tmp_path)])

    phi0 = np.load(tmp_path / "phi_0.npy")
    phi1 = np.load(tmp_path / "phi_1.npy")
    np.testing.assert_array_equal(phi0, phi1)

    # single-process reference: same recipe on this process's own devices
    np.testing.assert_allclose(phi0, _explain_adult(), atol=1e-5)


def _serve_tiny(port0_file):
    """Serve leg recipe (tiny synthetic problem so the pytest leg stays
    fast): lead serves HTTP over the 2-process mesh via the broadcast
    protocol; followers join each device call.  Lead saves the served phi
    and a direct sharded explain of the same rows for comparison."""

    import json as _json

    import numpy as np

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.serving import client as cl
    from distributedkernelshap_tpu.serving.multihost import serve_multihost

    rng = np.random.default_rng(0)
    D, K, N = 6, 3, 12
    W = rng.normal(size=(D, K)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(8, D)).astype(np.float32)

    def pred(A):
        import jax.numpy as jnp

        z = A @ W
        return jnp.exp(z) / jnp.exp(z).sum(-1, keepdims=True)

    # direct sharded explain FIRST, on every process simultaneously (a
    # sharded explain is a collective program — running it on the lead
    # after the followers exited would be a peerless collective and hang)
    ex = KernelShap(pred, link="identity", seed=0,
                    distributed_opts={"n_devices": N_DEVICES})
    ex.fit(bg)
    direct = np.stack(
        ex.explain(X, silent=True, nsamples=64, l1_reg=False).shap_values, 1)

    srv = serve_multihost(pred, bg, {"link": "identity", "seed": 0},
                          {}, {"n_devices": N_DEVICES}, host="127.0.0.1",
                          port=0, max_batch_size=4, max_rows=16,
                          explain_kwargs={"nsamples": 64, "l1_reg": False})
    if srv is None:
        return None  # follower: released by the shutdown broadcast
    try:
        payloads = cl.distribute_requests(
            f"http://127.0.0.1:{srv.port}/explain", X, max_workers=4)
        phi = np.stack([
            np.asarray(_json.loads(p)["data"]["shap_values"])[:, 0]
            for p in payloads])
    finally:
        srv.stop()
        srv.model.shutdown_followers()
    np.save(port0_file, np.stack([phi, direct]))
    return None


_SERVE_WORKER = """
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from distributedkernelshap_tpu.compat import force_cpu_devices
force_cpu_devices(2)
pid = int(sys.argv[1])
from distributedkernelshap_tpu.parallel.mesh import initialize_multihost
initialize_multihost("127.0.0.1:" + sys.argv[2], 2, pid)
assert jax.process_count() == 2

sys.path.insert(0, {tests_dir!r})
import test_multihost
getattr(test_multihost, sys.argv[4])(sys.argv[3] + "/served.npy")
"""


def _serve_tiny_pipelined(out_file):
    """Pipelined broadcast-protocol serving (round 4): replicated results
    make fetches collective-free, so the lead runs several broadcast +
    explain calls in flight (pipeline_depth=3, uncoalesced single-row
    requests) while followers dispatch asynchronously.  Saves served phi +
    a direct sharded explain for comparison."""

    import json as _json

    import numpy as np

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.serving import client as cl
    from distributedkernelshap_tpu.serving.multihost import serve_multihost

    rng = np.random.default_rng(0)
    D, K, N = 6, 3, 12
    W = rng.normal(size=(D, K)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(8, D)).astype(np.float32)

    def pred(A):
        import jax.numpy as jnp

        z = A @ W
        return jnp.exp(z) / jnp.exp(z).sum(-1, keepdims=True)

    opts = {"n_devices": N_DEVICES, "replicate_results": True}
    ex = KernelShap(pred, link="identity", seed=0, distributed_opts=opts)
    ex.fit(bg)
    direct = np.stack(
        ex.explain(X, silent=True, nsamples=64, l1_reg=False).shap_values, 1)

    srv = serve_multihost(pred, bg, {"link": "identity", "seed": 0},
                          {}, opts, host="127.0.0.1",
                          port=0, max_batch_size=1, max_rows=16,
                          pipeline_depth=3,
                          explain_kwargs={"nsamples": 64, "l1_reg": False})
    if srv is None:
        return None  # follower: released by the shutdown broadcast
    try:
        from distributedkernelshap_tpu.serving.multihost import (
            PipelinedMultihostServingModel,
        )

        assert isinstance(srv.model, PipelinedMultihostServingModel)
        assert srv.pipeline_depth == 3
        payloads = cl.distribute_requests(
            f"http://127.0.0.1:{srv.port}/explain", X, max_workers=8)
        phi = np.stack([
            np.asarray(_json.loads(p)["data"]["shap_values"])[:, 0]
            for p in payloads])
    finally:
        srv.stop()
        srv.model.shutdown_followers()
    np.save(out_file, np.stack([phi, direct]))


def test_two_process_serving_matches_direct_explain(tmp_path):
    """The multi-host serving path (serving/multihost.py broadcast
    protocol): served shap values must equal a direct sharded explain of
    the same rows over the same 2-process mesh."""

    import numpy as np

    port = _free_port()
    worker = tmp_path / "serve_worker.py"
    worker.write_text(_SERVE_WORKER.format(
        repo=REPO, tests_dir=os.path.dirname(os.path.abspath(__file__))))
    _run_two_procs(tmp_path, lambda pid: [
        sys.executable, str(worker), str(pid), str(port), str(tmp_path),
        "_serve_tiny"])

    served, direct = np.load(tmp_path / "served.npy")
    np.testing.assert_allclose(served, direct, atol=1e-5)


def test_two_process_serving_pipelined_matches_direct_explain(tmp_path):
    """Round 4: the PIPELINED broadcast protocol (replicate_results=True,
    depth 3, uncoalesced single-row requests, follower async dispatch)
    must serve phi equal to a direct sharded explain — several collective
    programs in flight across a REAL process boundary."""

    import numpy as np

    port = _free_port()
    worker = tmp_path / "serve_worker.py"
    worker.write_text(_SERVE_WORKER.format(
        repo=REPO, tests_dir=os.path.dirname(os.path.abspath(__file__))))
    _run_two_procs(tmp_path, lambda pid: [
        sys.executable, str(worker), str(pid), str(port), str(tmp_path),
        "_serve_tiny_pipelined"])

    served, direct = np.load(tmp_path / "served.npy")
    np.testing.assert_allclose(served, direct, atol=1e-5)
