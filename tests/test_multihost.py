"""Two-process multi-host smoke test.

The TPU-native analog of the reference's only multi-node validation — running
the same code against a real cluster via ``ray.init(address='auto')``
(``benchmarks/k8s_ray_pool.py:90``): here two OS processes join one
``jax.distributed`` runtime over a local coordinator, build a global 4-device
mesh (2 local CPU devices each), and run the sharded Adult explain end to end
with collectives crossing the process boundary (gloo — the DCN stand-in).
"""

import os
import pickle
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("coalition_parallel", [1, 2],
                         ids=["data4", "data2xcoalition2"])
def test_two_process_pool_benchmark(tmp_path, coalition_parallel):
    port = _free_port()
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    # log to files, not pipes: the processes are collectively coupled, so one
    # blocking on a full pipe buffer would stall the other inside a collective
    logs = [tmp_path / f"proc{pid}.log" for pid in range(2)]
    procs = []
    try:
        for pid in range(2):
            with open(logs[pid], "wb") as log:
                procs.append(subprocess.Popen(
                    [sys.executable,
                     os.path.join(REPO, "benchmarks", "multihost_pool.py"),
                     "-b", "8", "-w", "4", "-n", "1", "--limit", "64",
                     "--coalition_parallel", str(coalition_parallel),
                     "--platform", "cpu", "--cpu_devices", "2",
                     "--coordinator", f"127.0.0.1:{port}",
                     "--num_processes", "2", "--process_id", str(pid)],
                    cwd=str(tmp_path), env=env, stdout=log,
                    stderr=subprocess.STDOUT))
        for p in procs:
            p.wait(timeout=420)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    for pid, p in enumerate(procs):
        out = logs[pid].read_text(errors="replace")
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-2000:]}"
        assert "jax.distributed initialised: 2 processes, 4 devices" in out, out[-2000:]

    # the lead process wrote the reference-format result pickle
    with open(tmp_path / "results" / "ray_workers_4_bsize_8_actorfr_1.0.pkl", "rb") as f:
        result = pickle.load(f)
    assert len(result["t_elapsed"]) == 1 and result["t_elapsed"][0] > 0
