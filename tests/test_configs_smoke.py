"""CPU smoke tests for the benchmark configuration suite
(``benchmarks/configs.py`` — the BASELINE.json config matrix).

Each config is run in ``--smoke`` sizes on the virtual CPU mesh and must
produce a finite wall-clock and a tight additivity error — the same oracle
``bench.py`` enforces on hardware. The MNIST config is exercised separately
by ``tests/test_image_models.py`` (CNN training is too slow for CI here).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.configs import CONFIGS


# the two heaviest smokes (covertype ~29s, model_zoo ~40s on the CI
# box) are marked slow to keep the whole tier-1 suite inside its 870s
# budget (the same call made for test_multihost in PR 1): covertype is
# a dataset-size variant of the adult config that stays, and every
# model-zoo family has its own dedicated lift/explain tests — both
# still run via `make test`.
@pytest.mark.parametrize(
    "name", ["adult", "adult_stress",
             pytest.param("covertype", marks=pytest.mark.slow)])
def test_config_smoke(name):
    result = CONFIGS[name](smoke=True)
    assert result["value"] > 0
    assert result["additivity_err"] < 1e-3, result
    assert result["n_instances"] > 0


def test_config_blackbox_smoke():
    result = CONFIGS["adult_blackbox"](smoke=True)
    assert result["value"] > 0
    assert result["additivity_err"] < 1e-3, result
    assert result["predictor"]


def test_config_trees_smoke():
    result = CONFIGS["adult_trees"](smoke=True)
    assert result["value"] > 0
    assert result["additivity_err"] < 1e-3, result
    # external oracle: Σφ + E must match the ORIGINAL sklearn model, not
    # just the engine's internal raw predictions
    assert result["model_err"] < 1e-2, result
    assert result["device_lifted"], "GBT should lift onto the device"


@pytest.mark.slow
def test_config_model_zoo_smoke():
    result = CONFIGS["model_zoo"](smoke=True)
    assert result["additivity_err"] < 1e-3, result
    assert result["model_err"] < 5e-2, result   # near-saturated logits blow up
    assert len(result["families"]) >= 5
    not_lifted = [k for k, v in result["families"].items() if not v["device_lifted"]]
    assert not not_lifted, f"families fell off the device path: {not_lifted}"
