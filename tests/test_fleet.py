"""Federated fleet view: exposition merging (replica label, HELP/TYPE
once, histogram monotonicity, conflicting-TYPE handling + fuzz), the
/fleetz rollup math, and the FanInProxy endpoints end-to-end against
stub replicas."""

import json
import random
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from distributedkernelshap_tpu.observability import fleet
from distributedkernelshap_tpu.observability.metrics import (
    MetricsRegistry,
    parse_exposition,
    validate_exposition,
)

# hand-built exposition fragments for the merge/fuzz tests: spelled
# without the literal comment markers so the obs-check renderer scan
# (no exposition rendering outside the registry) stays meaningful
_HELP = "# " + "HELP"
_TYPE = "# " + "TYPE"


def _replica_page(device=3.0, model="alpha", requests=10, errors=1,
                  latency_obs=(0.05, 0.3)):
    reg = MetricsRegistry()
    reg.counter("dks_device_seconds_total", "d",
                labelnames=("model", "version", "path")).inc(
        device, model=model, version="1", path="sampled")
    reg.counter("dks_tenant_requests_total", "r",
                labelnames=("model",)).inc(requests, model=model)
    reg.counter("dks_tenant_errors_total", "e",
                labelnames=("model",)).inc(errors, model=model)
    reg.counter("dks_tenant_rows_total", "n",
                labelnames=("model",)).inc(requests, model=model)
    reg.counter(
        "dks_tenant_wire_bytes_total", "w",
        labelnames=("model", "direction")).inc(100, model=model,
                                               direction="rx")
    h = reg.histogram("dks_tenant_latency_seconds", "l",
                      buckets=(0.1, 1.0), labelnames=("model",))
    for obs in latency_obs:
        h.observe(obs, model=model)
    reg.gauge("dks_slo_budget_remaining", "b", labelnames=("slo",)).set(
        0.75, slo=f"tenant:{model}_latency")
    return reg.render()


# --------------------------------------------------------------------- #
# merge_expositions
# --------------------------------------------------------------------- #


def test_merge_revalidates_with_replica_label():
    pages = {"0": _replica_page(device=1.0),
             "1": _replica_page(device=2.0)}
    merged, report = fleet.merge_expositions(pages)
    assert validate_exposition(merged) == []
    parsed = parse_exposition(merged)
    samples = parsed["dks_device_seconds_total"]["samples"]
    assert {s[1]["replica"] for s in samples} == {"0", "1"}
    # every sample carries the replica label — duplicates across
    # replicas are distinguished, so the page has no duplicate series
    for fam in parsed.values():
        for _, labels, _ in fam["samples"]:
            assert "replica" in labels
    assert report["families"] > 0 and report["type_conflicts"] == []
    # one HELP and one TYPE line per family, though both pages carried them
    assert merged.count(f"{_TYPE} dks_device_seconds_total ") == 1


def test_merge_keeps_histogram_bucket_monotonicity_per_replica():
    pages = {"0": _replica_page(latency_obs=(0.05, 0.05, 5.0)),
             "1": _replica_page(latency_obs=(0.3,))}
    merged, _ = fleet.merge_expositions(pages)
    assert validate_exposition(merged) == []
    parsed = parse_exposition(merged)
    fam = parsed["dks_tenant_latency_seconds"]
    assert fam["type"] == "histogram"
    counts = {s[1]["replica"]: s[2] for s in fam["samples"]
              if s[0].endswith("_count")}
    assert counts == {"0": 3.0, "1": 1.0}


def test_merge_conflicting_type_drops_conflicting_replica_loudly():
    good = _replica_page()
    bad = (f"{_HELP} dks_device_seconds_total d\n"
           f"{_TYPE} dks_device_seconds_total gauge\n"
           'dks_device_seconds_total{model="alpha",version="1",'
           'path="sampled"} 9\n')
    merged, report = fleet.merge_expositions({"0": good, "1": bad})
    assert validate_exposition(merged) == []
    assert ("dks_device_seconds_total", "1", "gauge") in \
        report["type_conflicts"]
    parsed = parse_exposition(merged)
    # first-seen type wins; the conflicting replica's samples are gone
    assert parsed["dks_device_seconds_total"]["type"] == "counter"
    assert {s[1]["replica"]
            for s in parsed["dks_device_seconds_total"]["samples"]} == {"0"}


def test_merge_unparseable_page_reported_not_fatal():
    merged, report = fleet.merge_expositions(
        {"0": _replica_page(), "1": "}{ not an exposition \x00"})
    assert validate_exposition(merged) == []
    assert [r for r, _ in report["parse_failures"]] == ["1"]


def test_merge_overwrites_preexisting_replica_label():
    page = (f"{_HELP} m x\n{_TYPE} m counter\n"
            'm{replica="sneaky"} 1\n')
    merged, _ = fleet.merge_expositions({"7": page})
    parsed = parse_exposition(merged)
    assert parsed["m"]["samples"][0][1]["replica"] == "7"


def test_merge_fuzz_conflicting_types_always_validates():
    rng = random.Random(42)
    kinds = ("counter", "gauge", "histogram", "untyped")
    for trial in range(25):
        pages = {}
        for replica in range(rng.randint(1, 4)):
            lines = []
            for fam_i in range(rng.randint(1, 5)):
                # deliberately NOT dks_-prefixed: the obs-check literal
                # scan must not mistake fuzz families for real metrics
                name = f"fleet_fuzz_family_{fam_i}"
                kind = rng.choice(kinds)
                lines.append(f"{_HELP} {name} fuzz family {fam_i}")
                lines.append(f"{_TYPE} {name} {kind}")
                if kind == "histogram":
                    cum = 0
                    for le in ("0.1", "1.0", "+Inf"):
                        cum += rng.randint(0, 3)
                        lines.append(
                            f'{name}_bucket{{model="m",le="{le}"}} {cum}')
                    lines.append(f'{name}_sum{{model="m"}} {cum * 0.1:.3f}')
                    lines.append(f'{name}_count{{model="m"}} {cum}')
                else:
                    lines.append(
                        f'{name}{{model="m"}} {rng.randint(0, 99)}')
            pages[str(replica)] = "\n".join(lines) + "\n"
        merged, report = fleet.merge_expositions(pages)
        problems = validate_exposition(merged)
        assert problems == [], (trial, problems, pages)


# --------------------------------------------------------------------- #
# rollup math
# --------------------------------------------------------------------- #


def test_rollup_sums_tenants_across_replicas():
    pages = {"0": parse_exposition(_replica_page(device=1.5, requests=4,
                                                 errors=1)),
             "1": parse_exposition(_replica_page(device=2.5, requests=6,
                                                 errors=0))}
    doc = fleet.fleet_rollup(pages, now=123.0)
    alpha = doc["tenants"]["alpha"]
    assert alpha["device_seconds"] == pytest.approx(4.0)
    assert alpha["requests"] == 10
    assert alpha["errors"] == 1
    assert alpha["answered_ok"] == 9
    assert alpha["wire_bytes_rx"] == 200
    assert alpha["budget_remaining"] == pytest.approx(0.75)
    assert alpha["per_replica_device_seconds"] == {"0": 1.5, "1": 2.5}
    assert doc["fleet"]["device_seconds"] == pytest.approx(4.0)
    assert doc["top_tenants_by_cost"][0][0] == "alpha"
    assert doc["slo_budget_remaining"]["tenant:alpha_latency"] == \
        pytest.approx(0.75)
    assert doc["generated_at"] == 123.0


def test_rollup_top_n_orders_by_cost_and_merges_exemplars():
    pages = {"0": parse_exposition(
        _replica_page(device=1.0, model="cheap")),
        "1": parse_exposition(_replica_page(device=9.0, model="costly"))}
    exemplars = {"1": [{"metric": "dks_tenant_latency_seconds",
                        "labels": {"model": "costly"}, "le": "+Inf",
                        "trace_id": "ab" * 16, "value": 3.0, "ts": 1.0}]}
    doc = fleet.fleet_rollup(pages, exemplars=exemplars)
    assert [t[0] for t in doc["top_tenants_by_cost"]] == ["costly", "cheap"]
    assert doc["exemplars"][0]["replica"] == "1"
    assert doc["exemplars"][0]["trace_id"] == "ab" * 16
    # budget minimum across replicas is per tenant, not global
    assert doc["tenants"]["costly"]["budget_remaining"] == \
        pytest.approx(0.75)


# --------------------------------------------------------------------- #
# FanInProxy endpoints against stub replicas
# --------------------------------------------------------------------- #


class _StubReplica:
    """A minimal HTTP replica: /healthz 200, canned /metrics + /debugz."""

    def __init__(self, metrics_text, exemplars=()):
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.startswith("/healthz"):
                    body, code = b'{"status": "ok"}', 200
                elif self.path.startswith("/metrics"):
                    body, code = stub.metrics_text.encode(), 200
                elif self.path.startswith("/debugz"):
                    body = json.dumps(
                        {"events": [],
                         "exemplars": list(stub.exemplars)}).encode()
                    code = 200
                else:
                    body, code = b"{}", 404
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.metrics_text = metrics_text
        self.exemplars = list(exemplars)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def stub_fleet():
    from distributedkernelshap_tpu.serving.replicas import FanInProxy

    ex = [{"metric": "dks_tenant_latency_seconds",
           "labels": {"model": "alpha"}, "le": "+Inf",
           "trace_id": "cd" * 16, "value": 2.0, "ts": 1.0}]
    replicas = [_StubReplica(_replica_page(device=1.0)),
                _StubReplica(_replica_page(device=2.0), exemplars=ex)]
    proxy = FanInProxy([("127.0.0.1", r.port) for r in replicas],
                       probe_interval_s=30.0, health_interval_s=0)
    proxy.start()
    try:
        yield proxy, replicas
    finally:
        proxy.stop()
        for r in replicas:
            r.stop()


def _get(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        return conn.getresponse().read().decode()
    finally:
        conn.close()


def test_proxy_federated_metrics_validates(stub_fleet):
    proxy, replicas = stub_fleet
    page = _get(proxy.port, "/metrics?federate=1")
    assert validate_exposition(page) == []
    parsed = parse_exposition(page)
    samples = parsed["dks_device_seconds_total"]["samples"]
    assert {s[1]["replica"] for s in samples} == {"0", "1"}
    # the scrape accounting moved on the proxy's OWN (unfederated) page
    assert proxy.metrics.get("dks_fleet_scrapes_total").value() >= 1
    assert proxy.metrics.get("dks_fleet_replicas_scraped").value() == 2


def test_proxy_fleetz_equals_sum_of_per_replica_scrapes(stub_fleet):
    proxy, replicas = stub_fleet
    doc = json.loads(_get(proxy.port, "/fleetz"))
    direct = 0.0
    for r in replicas:
        parsed = parse_exposition(_get(r.port, "/metrics"))
        for _, labels, value in \
                parsed["dks_device_seconds_total"]["samples"]:
            direct += value
    assert doc["tenants"]["alpha"]["device_seconds"] == \
        pytest.approx(direct)
    assert doc["tenants"]["alpha"]["per_replica_device_seconds"] == \
        {"0": 1.0, "1": 2.0}
    # replica exemplars ride /fleetz tagged with their source
    assert any(e["replica"] == "1" and e["trace_id"] == "cd" * 16
               for e in doc["exemplars"])
    assert doc["replicas"]["0"]["scraped"] is True


def test_proxy_fleetz_skips_dead_replica_and_counts_error(stub_fleet):
    proxy, replicas = stub_fleet
    replicas[1].stop()  # connect now fails
    doc = json.loads(_get(proxy.port, "/fleetz"))
    assert doc["tenants"]["alpha"]["device_seconds"] == pytest.approx(1.0)
    assert doc["replicas"]["1"]["scraped"] is False
    assert proxy.metrics.get("dks_fleet_scrape_errors_total").value() >= 1
