"""dks-analyze static analyzer (``distributedkernelshap_tpu/analysis/``):
every check id fires on its known-bad fixture and stays silent on the
known-good twin, the pragma + baseline suppression contract, baseline
drift, the serving-ladder rung-deletion failures (fixture tree AND the
real artifacts), and the repo-wide ``make lint`` green invariant with
its runtime budget."""

import ast
import os
import shutil
import subprocess
import sys

import pytest

from distributedkernelshap_tpu.analysis import concurrency, jax_contract, \
    ladder
from distributedkernelshap_tpu.analysis.core import (
    apply_suppressions,
    load_baseline,
    suppressed_lines,
)
from distributedkernelshap_tpu.analysis.driver import (
    lint_repo,
    package_sources,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "lint")
LADDER_GOOD = os.path.join(FIXTURES, "ladder_good")

FAMILY = {"DKS-C": concurrency.check_module,
          "DKS-J": jax_contract.check_module}


def _findings(path: str, check_id: str):
    """Findings of ONE check id from the family module that owns it (a
    fixture may legitimately trip a sibling check — e.g. the J003 twins
    both carry a ``donate_argnums`` site that J001 would flag)."""

    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    tree = ast.parse(src, filename=path)
    rel = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
    check = FAMILY[check_id[:5]]
    return [f for f in check(tree, rel) if f.check_id == check_id], src


CHECK_IDS = ["DKS-C001", "DKS-C002", "DKS-C003", "DKS-C004", "DKS-C005",
             "DKS-J001", "DKS-J002", "DKS-J003", "DKS-J004"]


@pytest.mark.parametrize("check_id", CHECK_IDS)
def test_known_bad_fixture_fires(check_id):
    stem = check_id.replace("DKS-", "").lower()
    hits, _ = _findings(os.path.join(FIXTURES, f"{stem}_bad.py"), check_id)
    assert hits, f"{check_id} did not fire on its known-bad fixture"
    for f in hits:
        assert f.line > 0
        assert f.hint, "every finding must carry a fix hint"
        rendered = f.render()
        assert check_id in rendered and f"{f.file}:{f.line}" in rendered


@pytest.mark.parametrize("check_id", CHECK_IDS)
def test_known_good_twin_stays_clean(check_id):
    stem = check_id.replace("DKS-", "").lower()
    hits, _ = _findings(os.path.join(FIXTURES, f"{stem}_good.py"),
                        check_id)
    assert hits == [], f"{check_id} false-positives on its known-good twin"


def test_j003_flags_all_three_impurity_kinds():
    """The bad fixture carries host RNG, a clock read and np-on-traced —
    each must be individually reported, not collapsed into one."""

    hits, _ = _findings(os.path.join(FIXTURES, "j003_bad.py"), "DKS-J003")
    messages = " ".join(f.message for f in hits)
    assert "np.random" in messages
    assert "time.time" in messages
    assert "numpy cannot consume tracers" in messages


# --------------------------------------------------------------------- #
# suppression: inline pragmas
# --------------------------------------------------------------------- #


def test_pragma_covers_own_line_and_line_below():
    src = ("x = 1  # dks: allow(DKS-C001)\n"
           "\n"
           "# dks: allow(DKS-C002, DKS-C004): deliberate, reviewed\n"
           "y = 2\n")
    allowed = suppressed_lines(src)
    assert allowed[1] == {"DKS-C001"}
    assert allowed[2] == {"DKS-C001"}          # line below the pragma
    assert allowed[3] == {"DKS-C002", "DKS-C004"}
    assert allowed[4] == {"DKS-C002", "DKS-C004"}
    assert 5 not in allowed


def test_pragma_suppresses_only_the_named_id(tmp_path):
    bad = os.path.join(FIXTURES, "c001_bad.py")
    with open(bad, encoding="utf-8") as fh:
        src = fh.read()
    assert "self.ticks += 1" in src
    # the WRONG id on the flagged line must not suppress C001
    wrong = src.replace("self.ticks += 1",
                        "self.ticks += 1  # dks: allow(DKS-C002)")
    right = src.replace("self.ticks += 1",
                        "self.ticks += 1  # dks: allow(DKS-C001)")
    for variant, expect_active in ((wrong, 1), (right, 0)):
        tree = ast.parse(variant)
        raw = [f for f in concurrency.check_module(tree, "pkg/mod.py")
               if f.check_id == "DKS-C001"]
        active, suppressed, stale = apply_suppressions(
            raw, {"pkg/mod.py": variant}, [])
        assert len(active) == expect_active
        assert len(suppressed) == len(raw) - expect_active
        assert stale == []


# --------------------------------------------------------------------- #
# suppression: committed baseline + drift
# --------------------------------------------------------------------- #


def _lint_tree(tmp_path, extra_module=None, baseline_text=None):
    """A scannable tree: the ladder_good fixture package (rung-complete,
    so the ladder family is quiet) plus an optional extra module and
    baseline, linted via the real ``lint_repo`` entry point."""

    root = tmp_path / "tree"
    if not root.exists():
        shutil.copytree(LADDER_GOOD, root)
    if extra_module is not None:
        (root / "distributedkernelshap_tpu" / "mod.py").write_text(
            extra_module)
    if baseline_text is not None:
        adir = root / "distributedkernelshap_tpu" / "analysis"
        adir.mkdir(exist_ok=True)
        (adir / "baseline.toml").write_text(baseline_text)
    return lint_repo(str(root))


def test_ladder_good_tree_is_clean(tmp_path):
    result = _lint_tree(tmp_path)
    assert result.ok, [f.render() for f in result.active]
    assert result.files_scanned >= 6


def test_new_finding_fails_and_baseline_suppresses(tmp_path):
    with open(os.path.join(FIXTURES, "c001_bad.py"),
              encoding="utf-8") as fh:
        bad_src = fh.read()
    result = _lint_tree(tmp_path, extra_module=bad_src)
    assert not result.ok
    assert [f.check_id for f in result.active] == ["DKS-C001"]
    finding = result.active[0]
    baseline = (
        '[[finding]]\n'
        f'id = "{finding.check_id}"\n'
        f'file = "{finding.file}"\n'
        f'symbol = "{finding.symbol}"\n'
        'justification = "pre-existing, tracked in ISSUE-99"\n')
    result = _lint_tree(tmp_path, extra_module=bad_src,
                        baseline_text=baseline)
    assert result.ok
    assert len(result.suppressed) == 1
    # an empty-symbol entry matches any symbol in the file
    result = _lint_tree(tmp_path, extra_module=bad_src, baseline_text=(
        '[[finding]]\n'
        f'id = "{finding.check_id}"\n'
        f'file = "{finding.file}"\n'))
    assert result.ok


def test_stale_baseline_entry_fails_the_lint(tmp_path):
    """Drift: once the accepted finding is fixed, its baseline entry must
    be deleted — a matching-nothing entry is itself a failure."""

    result = _lint_tree(tmp_path, baseline_text=(
        '[[finding]]\n'
        'id = "DKS-C001"\n'
        'file = "distributedkernelshap_tpu/mod.py"\n'
        'symbol = "Worker.ticks"\n'
        'justification = "the debt was paid; this entry is now stale"\n'))
    assert not result.ok
    assert len(result.stale_baseline) == 1
    assert result.stale_baseline[0].id == "DKS-C001"


def test_malformed_baseline_raises(tmp_path):
    p = tmp_path / "baseline.toml"
    p.write_text('[[finding]]\nid = "DKS-C001"\nfile = unquoted\n')
    with pytest.raises(ValueError, match="unparseable"):
        load_baseline(str(p))
    p.write_text('id = "DKS-C001"\n')
    with pytest.raises(ValueError, match="outside"):
        load_baseline(str(p))
    p.write_text('[[finding]]\nid = "DKS-C001"\nfile = "f.py"\n'
                 'severity = "high"\n')
    with pytest.raises(ValueError, match="unknown baseline key"):
        load_baseline(str(p))
    assert load_baseline(str(tmp_path / "missing.toml")) == []


# --------------------------------------------------------------------- #
# serving-ladder contract: rung deletions must fail
# --------------------------------------------------------------------- #


def _ladder_findings(root):
    return ladder.check_ladder(str(root), package_sources(str(root)))


def _mutated_tree(tmp_path, rel, old, new):
    root = tmp_path / "tree"
    shutil.copytree(LADDER_GOOD, root)
    target = root / rel
    src = target.read_text()
    assert old in src, f"mutation anchor {old!r} missing from {rel}"
    target.write_text(src.replace(old, new))
    return root


PKG = "distributedkernelshap_tpu"
RUNG_DELETIONS = [
    # (deleted artifact, expected check id, rel path, old, new)
    ("dispatch entry", "DKS-L001", f"{PKG}/kernel_shap.py",
     "def _dispatch_exact(", "def _dispatch_exact_gone("),
    ("consts builder", "DKS-L002", f"{PKG}/kernel_shap.py",
     "def _exact_consts(", "def _exact_consts_gone("),
    ("consts fingerprint key", "DKS-L002", f"{PKG}/kernel_shap.py",
     'key = ("exact_consts", self.content_fingerprint())',
     'key = ("exact_consts",)'),
    ("serve label seed", "DKS-L003", f"{PKG}/serving/wrappers.py",
     '"exact": 0.0, ', ""),
    ("explain_path selection", "DKS-L003", f"{PKG}/serving/wrappers.py",
     'self.explain_path = "exact"', "pass"),
    ("fallback counter family", "DKS-L004", f"{PKG}/ops/treeshap.py",
     '"dks_treeshap_fallback_total"', '"no_longer_registered_anywhere"'),
    ("warmup path= literal", "DKS-L005", f"{PKG}/runtime/compile_cache.py",
     ',path=', ',p='),
    ("warmup explain_path pass-through", "DKS-L005",
     f"{PKG}/serving/server.py",
     'getattr(model, "explain_path", None)', "None"),
]


@pytest.mark.parametrize(
    "artifact,check_id,rel,old,new", RUNG_DELETIONS,
    ids=[r[0].replace(" ", "-") for r in RUNG_DELETIONS])
def test_deleting_a_rung_artifact_fails(tmp_path, artifact, check_id,
                                        rel, old, new):
    root = _mutated_tree(tmp_path, rel, old, new)
    hits = [f for f in _ladder_findings(root) if f.check_id == check_id]
    assert hits, f"deleting the {artifact} did not raise {check_id}"


def test_new_engine_path_fails_until_fully_wired(tmp_path):
    """Adding a name to ENGINE_PATHS without its rung (the quadratic/GAM
    scenario, ROADMAP item 4) must fail on every missing artifact."""

    root = _mutated_tree(
        tmp_path, f"{PKG}/registry/classify.py",
        '("linear", "exact_tree", "sampled")',
        '("linear", "exact_tree", "sampled", "quadratic")')
    got = {f.check_id for f in _ladder_findings(root)
           if f.symbol == "path:quadratic"}
    assert got == {"DKS-L001", "DKS-L002", "DKS-L003", "DKS-L004"}


def test_missing_engine_paths_is_itself_a_finding(tmp_path):
    root = _mutated_tree(tmp_path, f"{PKG}/registry/classify.py",
                         "ENGINE_PATHS", "OTHER_PATHS")
    hits = _ladder_findings(root)
    assert [f.check_id for f in hits] == ["DKS-L003"]
    assert "no path universe" in hits[0].message


def test_real_tree_rung_deletion_fails(tmp_path):
    """The acceptance drill on the REAL artifacts: copy the ladder's
    artifact files out of the repo, verify the copy lints clean, then
    strip the warmup ``path=`` signature literal — DKS-L005 must fire."""

    root = tmp_path / "repo"
    for rel in (ladder.CLASSIFY, ladder.ENGINE, ladder.WRAPPERS,
                ladder.COMPILE_CACHE, ladder.SERVER):
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(os.path.join(REPO_ROOT, rel), dst)
    real_sources = package_sources(REPO_ROOT)
    clean = ladder.check_ladder(str(root), real_sources)
    assert clean == [], [f.render() for f in clean]
    cc = root / ladder.COMPILE_CACHE
    src = cc.read_text()
    assert ",path=" in src
    cc.write_text(src.replace(",path=", ",p="))
    hits = ladder.check_ladder(str(root), real_sources)
    assert any(f.check_id == "DKS-L005" and
               f.file == ladder.COMPILE_CACHE for f in hits)


# --------------------------------------------------------------------- #
# repo-wide gate
# --------------------------------------------------------------------- #


def test_repo_lint_is_green_inside_budget():
    """The tree this test ships in must lint clean — and fast enough to
    gate every ``make test`` (the driver's --check asserts the same 60 s
    budget on its own timing)."""

    result = lint_repo(REPO_ROOT)
    assert result.ok, [f.render() for f in result.active] + \
        [str(e) for e in result.stale_baseline] + result.parse_errors
    assert result.files_scanned >= 70
    assert result.elapsed_s < 60.0


def test_driver_cli_static_pass(tmp_path):
    """``scripts/dks_lint.py`` (no flags) is the static-only entry point:
    exit 0 on this tree, one JSON report line on stdout."""

    import json

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                      "dks_lint.py")],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["ok"] is True
    assert report["findings"] == 0
    assert report["stale_baseline"] == 0
    assert report["parse_errors"] == 0
