"""Streaming hot path: wire framing fuzz/roundtrip, protocol negotiation
(old clients, new servers, pre-wire servers), staging pipeline, buffer
donation gating, connection pooling."""

import http.client
import http.server
import json
import struct
import threading

import numpy as np
import pytest

from distributedkernelshap_tpu.serving import client, wire

# --------------------------------------------------------------------- #
# wire framing
# --------------------------------------------------------------------- #


def test_roundtrip_arrays_zero_copy():
    arrays = {
        "a": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b": np.array([1.5, -2.5], dtype=np.float64),
        "flags": np.array([True, False]),
        "ids": np.arange(5, dtype=np.int64),
    }
    buf = wire.encode_arrays(arrays)
    out = wire.decode_arrays(buf)
    assert set(out) == set(arrays)
    for name, arr in arrays.items():
        assert out[name].dtype == arr.dtype
        assert np.array_equal(out[name], arr)
    # zero copy: the decoded float32 payload views the message buffer
    assert out["a"].base is not None


def test_request_roundtrip_casts_to_f32():
    x = np.arange(6, dtype=np.float64).reshape(2, 3)
    arr = wire.decode_request(wire.encode_request(x))
    assert arr.dtype == np.float32 and arr.shape == (2, 3)
    assert np.array_equal(arr, x.astype(np.float32))


def test_explanation_roundtrip():
    sv = [np.random.default_rng(0).normal(size=(3, 5)).astype(np.float32)
          for _ in range(2)]
    e = np.array([0.1, 0.9], dtype=np.float32)
    fx = np.random.default_rng(1).normal(size=(3, 2)).astype(np.float32)
    out = wire.decode_explanation(wire.encode_explanation(sv, e, fx))
    assert all(np.array_equal(a, b) for a, b in zip(out["shap_values"], sv))
    assert np.array_equal(out["expected_value"], e)
    assert np.array_equal(out["raw_prediction"], fx)


def test_json_payload_extraction_matches_binary():
    """The client's downgrade path must produce the same structure the
    binary decoder does (Explanation.to_json schema)."""

    payload = json.dumps({
        "meta": {},
        "data": {"shap_values": [[[1.0, 2.0]], [[3.0, 4.0]]],
                 "expected_value": [0.5, 0.25],
                 "raw": {"raw_prediction": [[0.9, 0.1]]}}})
    out = wire.explanation_payload_from_json(payload)
    assert np.array_equal(out["shap_values"][1], [[3.0, 4.0]])
    assert out["expected_value"].shape == (2,)
    assert out["raw_prediction"].shape == (1, 2)


@pytest.mark.parametrize("mutate", [
    lambda b: b[:3],                                   # truncated header
    lambda b: b[:20],                                  # truncated array head
    lambda b: b[:-4],                                  # torn body
    lambda b: b"XXXX" + b[4:],                         # bad magic
    lambda b: b + b"\x00\x00",                         # trailing bytes
    lambda b: b[:6] + b"\xff" + b[7:],                 # garbled count/etc.
])
def test_malformed_messages_raise_wire_error_never_crash(mutate):
    buf = mutate(bytearray(wire.encode_request(np.zeros((2, 3),
                                                        np.float32))))
    with pytest.raises(wire.WireError):
        wire.decode_arrays(bytes(buf))


def test_bad_dtype_code_raises():
    buf = bytearray(wire.encode_request(np.zeros((1, 2), np.float32)))
    # array header starts right after the 8-byte message header:
    # name_len(u16) dtype(u8) ndim(u8) name(...) — poison the dtype code
    dtype_off = 8 + 2
    assert buf[dtype_off] == wire.DTYPE_CODES[np.dtype(np.float32)]
    buf[dtype_off] = 250
    with pytest.raises(wire.WireError, match="dtype"):
        wire.decode_arrays(bytes(buf))


def test_future_version_raises_version_error():
    buf = bytearray(wire.encode_request(np.zeros((1, 2), np.float32)))
    struct.pack_into("<H", buf, 4, wire.WIRE_VERSION + 1)
    with pytest.raises(wire.WireVersionError):
        wire.decode_arrays(bytes(buf))


def test_fuzz_random_bytes_never_crash():
    rng = np.random.default_rng(0)
    base = wire.encode_request(rng.normal(size=(4, 8)).astype(np.float32))
    for trial in range(200):
        buf = bytearray(base)
        for _ in range(rng.integers(1, 6)):
            buf[rng.integers(0, len(buf))] = rng.integers(0, 256)
        try:
            out = wire.decode_arrays(bytes(buf))
        except wire.WireError:
            continue  # rejected cleanly — the contract
        for arr in out.values():  # or decoded into valid arrays
            assert isinstance(arr, np.ndarray)


def test_accept_negotiation_is_explicit_only():
    assert wire.accepts_wire(wire.CONTENT_TYPE)
    assert wire.accepts_wire(f"application/json, {wire.CONTENT_TYPE};q=0.9")
    assert not wire.accepts_wire("*/*")
    assert not wire.accepts_wire("application/json")
    assert not wire.accepts_wire(None)
    assert wire.is_wire_content_type(f"{wire.CONTENT_TYPE}; charset=x")
    assert not wire.is_wire_content_type("application/json")


# --------------------------------------------------------------------- #
# end-to-end negotiation against a real server
# --------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def linear_server():
    from sklearn.linear_model import LogisticRegression

    from distributedkernelshap_tpu.serving.server import ExplainerServer
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(0)
    X = rng.normal(size=(96, 6)).astype(np.float32)
    clf = LogisticRegression(max_iter=200).fit(
        X, (X[:, 0] > 0).astype(int))
    model = BatchKernelShapModel(clf, X[:12], {"link": "logit", "seed": 0},
                                 {}, explain_kwargs={"l1_reg": False})
    srv = ExplainerServer(model, host="127.0.0.1", port=0, max_batch_size=1,
                          pipeline_depth=1, cache_bytes=1 << 20,
                          health_interval_s=0, staging=True).start()
    try:
        yield srv
    finally:
        srv.stop()


def _url(srv):
    return f"http://127.0.0.1:{srv.port}/explain"


def test_old_json_client_against_new_server(linear_server):
    """The historical contract byte-for-byte: JSON body in, Explanation
    JSON out — a pre-wire client never notices the upgrade."""

    row = np.random.default_rng(1).normal(size=(1, 6))
    payload = client.explain_request(_url(linear_server), row, timeout=60)
    doc = json.loads(payload)
    assert "shap_values" in doc["data"]


def test_binary_client_bit_identical_to_json(linear_server):
    client.reset_negotiation_cache()
    row = np.random.default_rng(2).normal(size=(1, 6))
    payload = client.explain_request(_url(linear_server), row, timeout=60)
    phi_json = np.asarray(json.loads(payload)["data"]["shap_values"],
                          dtype=np.float32)
    out = client.explain_request(_url(linear_server), row, timeout=60,
                                 wire_format="binary")
    assert np.array_equal(phi_json, np.stack(out["shap_values"]))


def test_cache_keys_are_format_scoped(linear_server):
    """A binary client must never be served a cached JSON document (and
    vice versa): same rows over both transports answer in their own
    encoding."""

    client.reset_negotiation_cache()
    row = np.random.default_rng(3).normal(size=(1, 6))
    # populate the cache through the JSON path first
    p1 = client.explain_request(_url(linear_server), row, timeout=60)
    p2 = client.explain_request(_url(linear_server), row, timeout=60)
    assert p1 == p2  # cached, bit-identical
    out = client.explain_request(_url(linear_server), row, timeout=60,
                                 wire_format="binary")
    assert np.array_equal(
        np.asarray(json.loads(p1)["data"]["shap_values"], np.float32),
        np.stack(out["shap_values"]))


def test_malformed_binary_body_is_400_not_crash(linear_server):
    conn = http.client.HTTPConnection("127.0.0.1", linear_server.port,
                                      timeout=30)
    try:
        body = wire.encode_request(np.zeros((1, 6), np.float32))[:-3]
        conn.request("POST", "/explain", body=body,
                     headers={"Content-Type": wire.CONTENT_TYPE})
        resp = conn.getresponse()
        assert resp.status == 400
        assert "bad request" in json.loads(resp.read())["error"]
        # the server survived: a clean request on a fresh connection works
    finally:
        conn.close()
    row = np.random.default_rng(4).normal(size=(1, 6))
    assert client.explain_request(_url(linear_server), row, timeout=60)


def test_future_wire_version_is_415(linear_server):
    buf = bytearray(wire.encode_request(np.zeros((1, 6), np.float32)))
    struct.pack_into("<H", buf, 4, wire.WIRE_VERSION + 7)
    conn = http.client.HTTPConnection("127.0.0.1", linear_server.port,
                                      timeout=30)
    try:
        conn.request("POST", "/explain", body=bytes(buf),
                     headers={"Content-Type": wire.CONTENT_TYPE})
        resp = conn.getresponse()
        assert resp.status == 415
        assert json.loads(resp.read())["supported_wire_versions"] == [
            wire.WIRE_VERSION]
    finally:
        conn.close()


def test_wildcard_accept_stays_json(linear_server):
    """An old client sending Accept: */* must get JSON bytes."""

    conn = http.client.HTTPConnection("127.0.0.1", linear_server.port,
                                      timeout=60)
    try:
        body = json.dumps(
            {"array": np.zeros((1, 6)).tolist()}).encode()
        conn.request("POST", "/explain", body=body,
                     headers={"Content-Type": "application/json",
                              "Accept": "*/*"})
        resp = conn.getresponse()
        payload = resp.read()
        assert resp.status == 200
        assert not wire.is_wire_content_type(
            resp.headers.get("Content-Type"))
        json.loads(payload)  # parses as the historical document
    finally:
        conn.close()


def test_staging_pipeline_served_and_metered(linear_server):
    """The module server runs staging=True: after traffic, the staging
    overlap counter exists on /metrics (the staged dispatch path ran)."""

    conn = http.client.HTTPConnection("127.0.0.1", linear_server.port,
                                      timeout=30)
    try:
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
    finally:
        conn.close()
    assert "dks_staging_overlap_seconds_total" in text
    assert 'dks_wire_bytes_total{format="binary",direction="rx"}' in text
    assert linear_server._staging_enabled


# --------------------------------------------------------------------- #
# downgrade against a pre-wire (JSON-only) server
# --------------------------------------------------------------------- #


class _ScriptedOldServer:
    """A pre-wire server: answers ``answer_binary`` (415 or 400) to binary
    bodies and a minimal Explanation JSON to JSON bodies."""

    def __init__(self, answer_binary=415):
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                if wire.is_wire_content_type(
                        self.headers.get("Content-Type")):
                    outer.binary_hits += 1
                    data = json.dumps({"error": "nope"}).encode()
                    code = answer_binary
                else:
                    outer.json_hits += 1
                    json.loads(body)
                    data = json.dumps({
                        "meta": {},
                        "data": {"shap_values": [[[0.25, 0.75]]],
                                 "expected_value": [0.5],
                                 "raw": {"raw_prediction": [[0.9]]}},
                    }).encode()
                    code = 200
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args):
                pass

        self.binary_hits = 0
        self.json_hits = 0
        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                     Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.mark.parametrize("status", [415, 400])
def test_binary_client_downgrades_cleanly(status):
    """415 (explicit) or 400 (a pre-wire server JSON-parsing the binary
    body) downgrades to JSON without consuming the retry budget, and the
    host's verdict is cached so later requests go straight to JSON."""

    srv = _ScriptedOldServer(answer_binary=status)
    client.reset_negotiation_cache()
    try:
        url = f"http://127.0.0.1:{srv.port}/explain"
        out = client.explain_request(url, np.zeros((1, 2)), timeout=30,
                                     max_retries=0, wire_format="binary")
        assert np.allclose(out["shap_values"][0], [[0.25, 0.75]])
        assert srv.binary_hits == 1 and srv.json_hits == 1
        out2 = client.explain_request(url, np.zeros((1, 2)), timeout=30,
                                      max_retries=0, wire_format="auto")
        assert np.allclose(out2["expected_value"], [0.5])
        assert srv.binary_hits == 1  # no re-probe: negotiation cached
    finally:
        srv.stop()
        client.reset_negotiation_cache()


def test_request_level_400_does_not_disable_binary(linear_server):
    """A wire-capable server answering 400 for a bad SLO header must not
    poison the host's negotiation cache: the downgrade verdict is
    withdrawn when the JSON re-send draws the same 400, so later
    well-formed requests still ride the binary transport."""

    client.reset_negotiation_cache()
    row = np.random.default_rng(7).normal(size=(1, 6))
    with pytest.raises(RuntimeError, match="HTTP 400"):
        client.explain_request(
            _url(linear_server), row, timeout=60, wire_format="binary",
            extra_headers={"X-DKS-Priority": "bogus"},
            _sleep=lambda s: None)
    # the bad request did not cache a JSON downgrade...
    from distributedkernelshap_tpu.serving.client import _negotiated
    assert not _negotiated
    # ...and a well-formed request still gets binary bytes end to end
    conn = http.client.HTTPConnection("127.0.0.1", linear_server.port,
                                      timeout=60)
    try:
        conn.request("POST", "/explain", body=wire.encode_request(row),
                     headers={"Content-Type": wire.CONTENT_TYPE,
                              "Accept": wire.CONTENT_TYPE})
        resp = conn.getresponse()
        payload = resp.read()
        assert resp.status == 200
        assert wire.is_wire_content_type(resp.headers.get("Content-Type"))
        wire.decode_explanation(payload)
    finally:
        conn.close()


def test_json_mode_400_stays_terminal():
    """The downgrade trigger must not soften genuine client errors: after
    the one binary→JSON downgrade, a 400 to the JSON body raises
    immediately (no loop, no retry-budget spend)."""

    import http.server as hs

    class AlwaysBad(hs.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            data = json.dumps({"error": "bad"}).encode()
            self.send_response(400)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, fmt, *args):
            pass

    httpd = hs.ThreadingHTTPServer(("127.0.0.1", 0), AlwaysBad)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    client.reset_negotiation_cache()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/explain"
        with pytest.raises(RuntimeError, match="HTTP 400"):
            client.explain_request(url, np.zeros((1, 2)), timeout=30,
                                   max_retries=2, wire_format="binary",
                                   _sleep=lambda s: None)
    finally:
        httpd.shutdown()
        httpd.server_close()
        client.reset_negotiation_cache()


# --------------------------------------------------------------------- #
# connection pooling (the per-attempt-reconnect satellite)
# --------------------------------------------------------------------- #


def test_client_reuses_one_connection_across_retry_loop():
    """A 429-retry loop must ride ONE TCP connection: reconnecting per
    attempt was pure handshake overhead (fresh sockets are for
    HTTPException/ConnectionError only)."""

    connections = []

    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        calls = [0]

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            Handler.calls[0] += 1
            if Handler.calls[0] < 3:
                data = json.dumps({"retry_after_s": 0.01}).encode()
                code = 429
            else:
                data = json.dumps({"data": "ok"}).encode()
                code = 200
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def setup(self):
            connections.append(self.client_address)
            super().setup()

        def log_message(self, fmt, *args):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        payload = client.explain_request(
            f"http://127.0.0.1:{httpd.server_address[1]}/explain",
            np.zeros((1, 2)), timeout=30, _sleep=lambda s: None)
        assert json.loads(payload)["data"] == "ok"
        assert Handler.calls[0] == 3
        assert len(connections) == 1  # one socket for all three attempts
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_proxy_pools_forward_connections():
    """The fan-in proxy's per-thread replica connections persist across
    forwarded requests (a fresh socket per forward was the proxy-side
    reconnect-per-attempt bug)."""

    from distributedkernelshap_tpu.serving.replicas import FanInProxy

    connections = []

    class Replica(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            data = json.dumps({"data": "ok"}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def setup(self):
            connections.append(self.client_address)
            super().setup()

        def log_message(self, fmt, *args):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Replica)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    proxy = FanInProxy([("127.0.0.1", httpd.server_address[1])],
                       health_interval_s=0, probe_interval_s=60.0)
    try:
        body = json.dumps({"array": [[0.0]]}).encode()
        for _ in range(4):
            status, payload, _ = proxy.handle_explain("POST", body)
            assert status == 200
        # handle_explain runs on this one thread → one pooled connection
        assert len(connections) == 1
    finally:
        proxy.stop()
        httpd.shutdown()
        httpd.server_close()


# --------------------------------------------------------------------- #
# buffer donation gating
# --------------------------------------------------------------------- #


def test_donation_disabled_on_cpu_and_env_overridable(monkeypatch):
    from distributedkernelshap_tpu.ops import explain as ops_explain

    monkeypatch.delenv("DKS_DONATE", raising=False)
    assert ops_explain.buffer_donation_enabled() is False  # cpu backend
    monkeypatch.setenv("DKS_DONATE", "1")
    assert ops_explain.buffer_donation_enabled() is True
    monkeypatch.setenv("DKS_DONATE", "off")
    assert ops_explain.buffer_donation_enabled() is False


def test_donated_entry_points_still_bit_identical(monkeypatch):
    """Forcing donation on (CPU ignores it with a warning at worst) must
    not change results — and repeated calls through the donating entry
    points keep serving the plan-constant cache correctly (the donated
    argnum never aliases cached buffers)."""

    from sklearn.linear_model import LogisticRegression

    from distributedkernelshap_tpu.kernel_shap import KernelExplainerEngine

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 5)).astype(np.float32)
    clf = LogisticRegression(max_iter=200).fit(X, (X[:, 0] > 0).astype(int))
    row = rng.normal(size=(2, 5)).astype(np.float32)

    monkeypatch.delenv("DKS_DONATE", raising=False)
    eng = KernelExplainerEngine(clf.predict_proba, X[:8], link="logit",
                                seed=0)
    base = np.stack(eng.get_explanation(row, l1_reg=False, silent=True))

    monkeypatch.setenv("DKS_DONATE", "1")
    eng2 = KernelExplainerEngine(clf.predict_proba, X[:8], link="logit",
                                 seed=0)
    for _ in range(3):  # repeated: cached consts must survive every call
        out = np.stack(eng2.get_explanation(row, l1_reg=False, silent=True))
        assert np.array_equal(base, out)


# --------------------------------------------------------------------- #
# StagingBuffer unit
# --------------------------------------------------------------------- #


def test_staging_buffer_handoff_and_overlap():
    from distributedkernelshap_tpu.scheduling import StagingBuffer

    buf = StagingBuffer(depth=1)
    stop = threading.Event()
    assert buf.put("a", stop=stop)
    item, ready_s = buf.get(stop=stop)
    assert item == "a" and ready_s >= 0.0
    # stop set + empty → None; staged leftovers still delivered first
    buf.put("b", stop=stop)
    stop.set()
    assert buf.get(stop=stop)[0] == "b"
    assert buf.get(stop=stop) is None
    assert not buf.put("c", stop=stop)


def test_staging_buffer_drain():
    from distributedkernelshap_tpu.scheduling import StagingBuffer

    buf = StagingBuffer(depth=2)
    buf.put("x")
    buf.put("y")
    assert buf.drain() == ["x", "y"]
    assert buf.drain() == []


# --------------------------------------------------------------------- #
# anytime round-frame streaming (ISSUE 16): framing fuzz, client
# partials, downgrade negotiation against every server generation
# --------------------------------------------------------------------- #


def _two_frame_body():
    rng = np.random.default_rng(9)
    sv = [rng.normal(size=(1, 6)).astype(np.float32) for _ in range(2)]
    ev = np.array([0.3, 0.7], np.float32)
    rp = rng.normal(size=(1, 2)).astype(np.float32)
    err0 = np.full((1, 6), 0.5, np.float32)
    err1 = np.full((1, 6), 0.1, np.float32)
    return (wire.encode_round_frame(sv, ev, rp, 0, err0)
            + wire.encode_round_frame(sv, ev, rp, 1, err1, final=True))


def test_round_frames_roundtrip_in_order():
    frames = wire.decode_round_frames(_two_frame_body())
    assert [f["round"] for f in frames] == [0, 1]
    assert [f["final"] for f in frames] == [False, True]
    assert frames[0]["est_err"].shape == (1, 6)
    assert float(frames[1]["est_err"].max()) < float(
        frames[0]["est_err"].max())
    assert len(frames[0]["shap_values"]) == 2


def test_round_frame_stream_truncations_raise_wire_error():
    body = _two_frame_body()
    hdr = wire.STREAM_HEADER_SIZE
    # cut mid-header, at the header boundary, mid-payload, and just
    # before the final byte: every torn stream rejects cleanly
    for cut in (3, hdr - 1, hdr, hdr + 17, len(body) // 2, len(body) - 1):
        with pytest.raises(wire.WireError):
            wire.decode_round_frames(body[:cut])


def test_round_frame_stream_missing_final_raises():
    body = _two_frame_body()
    # drop the second (final) frame entirely: well-formed frames, but the
    # stream never terminated — indistinguishable from truncation
    first, _ = wire.decode_round_frame(body)
    first_len = wire.STREAM_HEADER_SIZE + wire.stream_frame_length(
        body[:wire.STREAM_HEADER_SIZE])
    with pytest.raises(wire.WireError, match="final"):
        wire.decode_round_frames(body[:first_len])
    with pytest.raises(wire.WireError, match="frames"):
        wire.decode_round_frames(b"")


def test_round_frame_future_version_raises_version_error():
    body = bytearray(_two_frame_body())
    struct.pack_into("<H", body, 4, wire.STREAM_VERSION + 3)
    with pytest.raises(wire.WireVersionError):
        wire.decode_round_frames(bytes(body))
    with pytest.raises(wire.WireVersionError):
        wire.stream_frame_length(bytes(body[:wire.STREAM_HEADER_SIZE]))


def test_round_frame_fuzz_never_crashes():
    rng = np.random.default_rng(1)
    base = _two_frame_body()
    for _ in range(200):
        buf = bytearray(base)
        for _ in range(rng.integers(1, 6)):
            buf[rng.integers(0, len(buf))] = rng.integers(0, 256)
        try:
            frames = wire.decode_round_frames(bytes(buf))
        except wire.WireError:
            continue  # includes WireVersionError — rejected cleanly
        for f in frames:
            assert isinstance(f["est_err"], np.ndarray)


@pytest.fixture(scope="module")
def anytime_server():
    from distributedkernelshap_tpu.serving.server import ExplainerServer
    from distributedkernelshap_tpu.serving.wrappers import KernelShapModel

    M = 12
    rng = np.random.default_rng(21)

    class _Clf:
        coef_ = rng.normal(size=(1, M)).astype(np.float64)
        intercept_ = np.array([0.05])
        classes_ = np.array([0, 1])

        def predict_proba(self, X):
            z = X @ self.coef_.T + self.intercept_
            p = 1.0 / (1.0 + np.exp(-z))
            return np.concatenate([1.0 - p, p], axis=1)

    bg = rng.normal(size=(16, M)).astype(np.float32)
    model = KernelShapModel(
        _Clf().predict_proba, bg, {"seed": 5}, {},
        explain_kwargs={"nsamples": 256, "l1_reg": False})
    assert model.supports_anytime
    srv = ExplainerServer(model, host="127.0.0.1", port=0,
                          max_batch_size=2, cache_bytes=1 << 20,
                          health_interval_s=0).start()
    try:
        yield srv
    finally:
        srv.stop()


def test_client_stream_receives_partials_then_final(anytime_server):
    client.reset_negotiation_cache()
    row = np.random.default_rng(22).normal(size=(1, 12)).astype(np.float32)
    partials = []
    out = client.explain_request(_url(anytime_server), row, timeout=60,
                                 max_retries=0, wire_format="binary",
                                 stream=True, on_partial=partials.append)
    assert out["final"] and "est_err" in out
    assert all(not p["final"] for p in partials)
    rounds = [p["round"] for p in partials] + [out["round"]]
    assert rounds == list(range(len(rounds))) and len(rounds) >= 2
    errs = [float(np.max(p["est_err"])) for p in partials] \
        + [float(np.max(out["est_err"]))]
    assert all(b <= a + 1e-12 for a, b in zip(errs, errs[1:]))
    # every partial refines toward the final answer, same shapes
    assert np.stack(out["shap_values"]).shape == \
        np.stack(partials[0]["shap_values"]).shape


def test_client_stream_downgrades_on_non_anytime_server(linear_server):
    """A wire-capable but non-refining deployment ignores the stream
    Accept entry and answers one plain binary explanation: the client
    returns it as the same structured dict, no partials."""

    client.reset_negotiation_cache()
    row = np.random.default_rng(23).normal(size=(1, 6)).astype(np.float32)
    partials = []
    out = client.explain_request(_url(linear_server), row, timeout=60,
                                 max_retries=0, wire_format="binary",
                                 stream=True, on_partial=partials.append)
    assert partials == []
    assert "shap_values" in out and "final" not in out
    # bit-identical to the non-stream binary answer (same cache entry)
    ref = client.explain_request(_url(linear_server), row, timeout=60,
                                 wire_format="binary")
    assert np.array_equal(np.stack(out["shap_values"]),
                          np.stack(ref["shap_values"]))


@pytest.mark.parametrize("status", [415, 400])
def test_client_stream_downgrades_on_pre_wire_server(status):
    """PR 6's 415/400 tentative-downgrade rules hold unchanged when the
    client also asks to stream: binary body rejected -> JSON re-send on
    the same connection, stream Accept ignored, single JSON answer
    returned structured."""

    srv = _ScriptedOldServer(answer_binary=status)
    client.reset_negotiation_cache()
    try:
        url = f"http://{'127.0.0.1'}:{srv.port}/explain"
        partials = []
        out = client.explain_request(url, np.zeros((1, 2)), timeout=30,
                                     max_retries=0, wire_format="binary",
                                     stream=True,
                                     on_partial=partials.append)
        assert partials == []
        assert np.allclose(out["shap_values"][0], [[0.25, 0.75]])
        assert srv.binary_hits == 1 and srv.json_hits == 1
    finally:
        srv.stop()
        client.reset_negotiation_cache()


def test_mixed_clients_bit_identical_on_anytime_hot_server(anytime_server):
    """JSON and binary (non-stream) clients against an anytime-capable
    server keep the PR 6 contract: same rows, bit-identical phi over
    both transports — anytime capability changes nothing for clients
    that did not opt in."""

    client.reset_negotiation_cache()
    row = np.random.default_rng(24).normal(size=(1, 12)).astype(np.float32)
    payload = client.explain_request(_url(anytime_server), row, timeout=60)
    phi_json = np.asarray(json.loads(payload)["data"]["shap_values"],
                          dtype=np.float32)
    out = client.explain_request(_url(anytime_server), row, timeout=60,
                                 wire_format="binary")
    assert np.array_equal(phi_json, np.stack(out["shap_values"]))


def test_torn_mid_stream_never_surfaces_partial_phi():
    """A server that dies mid-frame (torn chunked stream) must surface as
    an error at the client, never as half-parsed phi."""

    body = _two_frame_body()
    torn = body[:len(body) - 9]  # valid first frame, torn final frame

    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self.send_response(200)
            self.send_header("Content-Type", wire.STREAM_CONTENT_TYPE)
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self.wfile.write(b"%x\r\n" % len(torn) + torn + b"\r\n")
            self.wfile.write(b"0\r\n\r\n")

        def log_message(self, fmt, *args):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    client.reset_negotiation_cache()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/explain"
        got = []
        with pytest.raises(RuntimeError, match="torn round-frame stream"):
            client.explain_request(url, np.zeros((1, 2)), timeout=30,
                                   max_retries=0, wire_format="json",
                                   stream=True, on_partial=got.append)
        # the well-formed first frame MAY have been delivered as a
        # partial (it is a valid refinement); the torn final never was
        assert all(not p["final"] for p in got)
    finally:
        httpd.shutdown()
        httpd.server_close()
        client.reset_negotiation_cache()
