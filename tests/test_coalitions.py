"""Tests for the static coalition plan."""

import math

import numpy as np
import pytest

from distributedkernelshap_tpu.ops.coalitions import (
    CoalitionPlan,
    coalition_plan,
    default_nsamples,
    kernel_size_masses,
)


def test_default_nsamples_matches_shap():
    assert default_nsamples(12) == 2 * 12 + 2048


def test_size_masses_normalised_and_symmetric():
    m = kernel_size_masses(10)
    assert np.isclose(m.sum(), 1.0)
    np.testing.assert_allclose(m, m[::-1])  # w(s) == w(M-s)
    assert m[0] == m.max()  # extremes carry the most kernel mass


@pytest.mark.parametrize("M", [2, 3, 5, 8])
def test_full_enumeration_when_budget_allows(M):
    plan = coalition_plan(M, nsamples=2 ** M)
    assert plan.exact
    assert plan.n_rows == 2 ** M - 2
    # every row non-trivial, all distinct
    sizes = plan.mask.sum(1)
    assert sizes.min() >= 1 and sizes.max() <= M - 1
    assert len(np.unique(plan.mask, axis=0)) == plan.n_rows
    assert np.isclose(plan.weights.sum(), 1.0)
    # per-size mass matches the Shapley kernel
    masses = kernel_size_masses(M)
    for s in range(1, M):
        w_s = plan.weights[sizes == s].sum()
        assert np.isclose(w_s, masses[s - 1], atol=1e-6)


def test_sampled_plan_structure():
    M, nsamples = 20, 256
    plan = coalition_plan(M, nsamples=nsamples, seed=0)
    assert not plan.exact
    assert plan.mask.shape == (plan.n_rows, M)
    assert plan.n_rows <= nsamples
    assert np.isclose(plan.weights.sum(), 1.0)
    # enumerated prefix covers complete small/large sizes
    sizes = plan.mask[: plan.n_enumerated].sum(1)
    assert set(np.unique(sizes)) == {1, M - 1}
    assert plan.n_enumerated == 2 * M
    # zero-weight padded rows only at the very end
    nz = plan.weights > 0
    first_zero = np.argmin(nz) if not nz.all() else len(nz)
    assert nz[:first_zero].all()


def test_sampled_plan_seed_determinism_and_fixed_shape():
    a = coalition_plan(15, nsamples=200, seed=1)
    b = coalition_plan(15, nsamples=200, seed=1)
    c = coalition_plan(15, nsamples=200, seed=2)
    np.testing.assert_array_equal(a.mask, b.mask)
    np.testing.assert_array_equal(a.weights, b.weights)
    # different seed -> same shape (no retrace), different rows
    assert c.mask.shape == a.mask.shape
    assert not np.array_equal(a.mask, c.mask)


def test_single_group_plan():
    plan = coalition_plan(1)
    assert isinstance(plan, CoalitionPlan) and plan.exact and plan.n_rows == 1


def test_pair_sampling_complements_present():
    plan = coalition_plan(16, nsamples=300, seed=0)
    sampled = plan.mask[plan.n_enumerated:]
    w = plan.weights[plan.n_enumerated:]
    sampled = sampled[w > 0]
    # for every sampled row, its complement appears too (paired sampling)
    rows = {tuple(r) for r in sampled.astype(int).tolist()}
    n_with_complement = sum(tuple(1 - np.array(r)) in rows for r in rows)
    assert n_with_complement == len(rows)


def test_enumeration_greedy_pairs():
    # M=12, budget 2072 (shap default): sizes 1..4 & 8..11 fit fully
    plan = coalition_plan(12, nsamples=default_nsamples(12), seed=0)
    expected_enum = sum(math.comb(12, s) + math.comb(12, 12 - s) for s in (1, 2, 3, 4))
    assert plan.n_enumerated == expected_enum
    assert not plan.exact
