"""Cold-start subsystem: persistent compile cache, precompile warmup
ladder, plan-constant device caching (ISSUE 5).

Covers the three contracts the warmup bench measures end-to-end, at unit
scope: warm-vs-cold bit-identity, bucket-ladder coverage of every
dispatchable padded size, and readiness gating (a replica inside warmup is
not routed to, not readmitted by the prober, and not restarted by the
supervisor), plus compile-accounting units, plan fingerprint stability and
the dev-cache rekey/bound satellite.
"""

import gc
import json
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributedkernelshap_tpu.data import DenseData
from distributedkernelshap_tpu.kernel_shap import (
    EngineConfig,
    KernelExplainerEngine,
)
from distributedkernelshap_tpu.ops.coalitions import (
    CoalitionPlan,
    plan_fingerprint,
)
from distributedkernelshap_tpu.runtime.compile_cache import (
    CompileAccounting,
    compile_events,
    enable_persistent_cache,
)


# --------------------------------------------------------------------- #
# fixtures: a tiny linear model (4 features — small plans, fast compiles)
# --------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def linear_setup():
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, 4)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    clf = LogisticRegression(max_iter=200).fit(X, y)
    bg = DenseData(X[:16], [f"f{i}" for i in range(4)], None)
    return {"clf": clf, "bg": bg, "X": X}


def _engine(setup, **cfg):
    return KernelExplainerEngine(
        setup["clf"].predict_proba, setup["bg"], link="logit", seed=0,
        config=EngineConfig(**cfg) if cfg else None)


# --------------------------------------------------------------------- #
# compile accounting
# --------------------------------------------------------------------- #


def test_compile_events_attributes_signatures():
    """A compile fired inside a signature() block lands under that shape
    signature; outside, under _unattributed."""

    import jax
    import jax.numpy as jnp

    ce = compile_events()
    before = ce.snapshot()
    salt = time.monotonic()  # a fresh constant forces a fresh compile
    with ce.signature("rows=test"):
        jax.jit(lambda x: x * salt + 1.0)(jnp.ones((3,)))
    delta = ce.delta(before, ce.snapshot())
    assert ce.fresh_for_signature(delta, "rows=test") >= 1
    sig_seconds = [s for (kind, sig), s in delta["seconds"].items()
                   if sig == "rows=test"]
    assert sig_seconds and all(s > 0 for s in sig_seconds)


def test_compile_events_signature_nesting_restores_outer():
    ce = compile_events()
    with ce.signature("outer"):
        with ce.signature("inner"):
            assert ce._local.signature == "inner"
        assert ce._local.signature == "outer"
    assert ce._local.signature is None


def test_compile_delta_only_reports_movement():
    ce = CompileAccounting()
    a = {"counts": {("fresh", "x"): 2}, "seconds": {("fresh", "x"): 1.0},
         "totals": {"fresh": 2}, "seconds_totals": {"fresh": 1.0}}
    b = {"counts": {("fresh", "x"): 2, ("cache_hit", "y"): 3},
         "seconds": {("fresh", "x"): 1.0, ("cache_hit", "y"): 0.5},
         "totals": {"fresh": 2, "cache_hit": 3},
         "seconds_totals": {"fresh": 1.0, "cache_hit": 0.5}}
    d = ce.delta(a, b)
    assert d["counts"] == {("cache_hit", "y"): 3}
    assert d["totals"]["fresh"] == 0 and d["totals"]["cache_hit"] == 3


def test_compile_metrics_registered_on_registry():
    from distributedkernelshap_tpu.observability.metrics import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    compile_events().attach_metrics(reg)
    described = {m["name"]: m for m in reg.describe()}
    assert described["dks_compile_total"]["type"] == "counter"
    assert described["dks_compile_seconds_total"]["type"] == "counter"
    assert "dks_compile_total" in reg.render()


def test_enable_persistent_cache_no_dir_is_noop(monkeypatch):
    monkeypatch.delenv("DKS_COMPILE_CACHE_DIR", raising=False)
    assert enable_persistent_cache(None) is None


# --------------------------------------------------------------------- #
# plan fingerprint + dev-cache rekey/bound (satellite)
# --------------------------------------------------------------------- #


def _plan(mask):
    mask = np.asarray(mask, dtype=np.float32)
    w = np.full(mask.shape[0], 1.0 / mask.shape[0], dtype=np.float32)
    return CoalitionPlan(mask=mask, weights=w, exact=False,
                         n_enumerated=0)


def test_plan_fingerprint_content_keyed():
    a = _plan([[1, 0], [0, 1]])
    b = _plan([[1, 0], [0, 1]])   # same content, different object
    c = _plan([[1, 1], [0, 1]])
    assert plan_fingerprint(a) == plan_fingerprint(b)
    assert plan_fingerprint(a) != plan_fingerprint(c)
    # memoised on the plan (sha paid once)
    assert a.__dict__["_content_fp"] == plan_fingerprint(a)


def test_plan_fingerprint_shape_disambiguation():
    flat = np.array([[1, 0, 0, 1]], dtype=np.float32)
    tall = flat.reshape(2, 2)
    assert (plan_fingerprint(_plan(flat))
            != plan_fingerprint(_plan(tall)))


def test_dev_cache_rekeyed_by_content_and_bounded(linear_setup):
    """A GC'd plan whose address is recycled can no longer alias a cache
    entry: content-identical plans share one entry, distinct plans get
    their own, and the LRU bound holds."""

    eng = _engine(linear_setup)
    a = _plan(np.eye(4))
    eng._device_args(a)
    key_a = plan_fingerprint(a)
    del a
    gc.collect()
    b = _plan(np.eye(4))  # same content — MUST hit the same entry
    eng._device_args(b)
    assert len(eng._dev_cache) == 1
    assert plan_fingerprint(b) == key_a
    # bound: distinct plans never grow the cache past the cap
    for i in range(eng._DEV_CACHE_MAX_ENTRIES + 4):
        mask = np.eye(4, dtype=np.float32)
        mask[0, 0] = float(i + 2)
        eng._device_args(_plan(mask))
    assert len(eng._dev_cache) <= eng._DEV_CACHE_MAX_ENTRIES


def test_distributed_dev_cache_rekeyed_and_bounded(linear_setup):
    from distributedkernelshap_tpu.parallel.distributed import (
        DistributedExplainer,
    )

    dist = DistributedExplainer(
        {"n_devices": 1, "batch_size": None, "algorithm": "kernel_shap"},
        KernelExplainerEngine,
        (linear_setup["clf"].predict_proba, linear_setup["bg"]),
        {"link": "logit", "seed": 0},
    )
    a = _plan(np.eye(4))
    dist._device_args(a)
    del a
    gc.collect()
    dist._device_args(_plan(np.eye(4)))
    assert len(dist._dev_cache) == 1
    for i in range(dist._DEV_CACHE_MAX_ENTRIES + 4):
        mask = np.eye(4, dtype=np.float32)
        mask[0, 0] = float(i + 2)
        dist._device_args(_plan(mask))
    assert len(dist._dev_cache) <= dist._DEV_CACHE_MAX_ENTRIES


# --------------------------------------------------------------------- #
# plan-constant device cache (linear fast path)
# --------------------------------------------------------------------- #


@pytest.mark.parametrize("B", [1, 3])
def test_plan_constant_cache_bit_identical_to_uncached_arm(linear_setup, B):
    """Cached and uncached arms run the SAME two-stage compiled program
    (constants served from the device cache vs recomputed per call), so
    phi must agree bit-for-bit — the contract the warmup bench asserts."""

    on = _engine(linear_setup)
    ctl = _engine(linear_setup, plan_constant_cache=False)
    X = linear_setup["X"]
    for lo in (40, 80):
        a = np.stack(on.get_explanation(X[lo:lo + B]))
        b = np.stack(ctl.get_explanation(X[lo:lo + B]))
        assert (a == b).all()
    assert on.kernel_path["ey"] == "einsum_cached"
    assert len(on._plan_consts_cache) == 1      # reused, not regrown
    assert len(ctl._plan_consts_cache) == 0     # control arm never stores


def test_plan_constant_cache_classic_path_allclose(linear_setup):
    """'off' runs the classic self-contained program — same formulas,
    different whole-program XLA graph, so equality is tolerance-based."""

    on = _engine(linear_setup)
    off = _engine(linear_setup, plan_constant_cache='off')
    X = linear_setup["X"][40:43]
    a = np.stack(on.get_explanation(X))
    c = np.stack(off.get_explanation(X))
    assert off.kernel_path["ey"] == "einsum"
    np.testing.assert_allclose(a, c, atol=2e-6)


def test_plan_constant_cache_disabled_for_nonlinear(linear_setup):
    """A black-box callable has no linear decomposition — the fast path
    must not engage."""

    clf = linear_setup["clf"]

    def opaque(x):  # numpy in/out: lifts to CallbackPredictor
        return clf.predict_proba(np.asarray(x))

    eng = KernelExplainerEngine(opaque, linear_setup["bg"], link="logit",
                                seed=0)
    assert eng.predictor.linear_decomposition is None
    assert not eng._plan_consts_enabled()


def test_plan_constant_cache_cleared_on_reset(linear_setup):
    eng = _engine(linear_setup)
    eng.get_explanation(linear_setup["X"][40:42])
    assert len(eng._plan_consts_cache) == 1
    eng.reset_device_state()
    assert len(eng._plan_consts_cache) == 0


# --------------------------------------------------------------------- #
# warm-vs-cold bit identity + ladder coverage
# --------------------------------------------------------------------- #


def test_warmed_ladder_phi_bit_identical_to_cold_engine(linear_setup):
    """Explaining through an engine pre-warmed over every bucket shape
    yields the same bits as a cold engine answering directly — warmup only
    moves WHEN programs compile, never what they compute."""

    warmed = _engine(linear_setup)
    bg = np.asarray(linear_setup["bg"].data[:1], dtype=np.float32)
    for b in (1, 2, 4):  # the bucket ladder for max_batch_size=4
        warmed.get_explanation(np.tile(bg, (b, 1)))
    cold = _engine(linear_setup)
    X = linear_setup["X"][40:43]
    a = np.stack(warmed.get_explanation(X))
    b = np.stack(cold.get_explanation(X))
    assert (a == b).all()


def test_warmup_ladder_covers_every_dispatchable_padded_size(linear_setup):
    """Every batch size 1..max_batch_size must pad to a bucket that is in
    the ladder — otherwise a first request of that size would compile."""

    from distributedkernelshap_tpu.serving.server import ExplainerServer

    eng = _engine(linear_setup)
    for top in (1, 3, 8, 10):
        stub = types.SimpleNamespace(max_batch_size=top)
        ladder = ExplainerServer._warmup_ladder(stub, eng)
        assert ladder == sorted(set(ladder))
        for n in range(1, top + 1):
            assert eng._bucket(n) in ladder, (top, n)


def test_warmup_ladder_fallback_without_engine():
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    stub = types.SimpleNamespace(max_batch_size=10)
    ladder = ExplainerServer._warmup_ladder(stub, None)
    assert ladder == [1, 2, 4, 8, 10]


# --------------------------------------------------------------------- #
# readiness gating (no jax in the fake model — fast)
# --------------------------------------------------------------------- #


class _GatedWarmupModel:
    """Fake model whose warmup blocks until released; real requests answer
    instantly (the test controls exactly when the ladder 'compiles')."""

    def __init__(self):
        self.release = threading.Event()
        engine = types.SimpleNamespace(
            background=np.ones((4, 2), dtype=np.float32))
        self.explainer = types.SimpleNamespace(_explainer=engine)

    def explain_batch(self, instances, split_sizes=None):
        if not self.release.is_set():
            # only warmup calls arrive before release; never wedge forever
            assert self.release.wait(timeout=30)
        sizes = split_sizes or [instances.shape[0]]
        out, k = [], 0
        for n in sizes:
            rows = instances[k:k + n]
            k += n
            out.append(json.dumps(
                {"data": {"sum": [float(r.sum()) for r in rows]}}))
        return out


def _healthz(port):
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture()
def warming_server():
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    model = _GatedWarmupModel()
    server = ExplainerServer(model, host="127.0.0.1", port=0,
                             max_batch_size=4, pipeline_depth=1,
                             health_interval_s=0, warmup=True).start()
    try:
        yield server, model
    finally:
        model.release.set()
        server.stop()


def _wait_for(predicate, timeout_s=10.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return False


def test_healthz_gates_readiness_during_warmup(warming_server):
    server, model = warming_server
    code, body = _healthz(server.port)
    assert code == 503 and body["status"] == "warming"
    assert body["warmup"]["state"] in ("pending", "running")
    model.release.set()
    assert _wait_for(lambda: _healthz(server.port)[0] == 200)
    assert server.warmup_status()["state"] == "done"
    assert server.warmup_status()["completed_buckets"] == [1, 2, 4]


def test_statusz_renders_warmup_progress(warming_server):
    server, model = warming_server
    payload = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/statusz?format=json",
        timeout=10).read())
    assert payload["detail"]["warmup"]["state"] in ("pending", "running")
    model.release.set()
    assert _wait_for(lambda: _healthz(server.port)[0] == 200)
    payload = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/statusz?format=json",
        timeout=10).read())
    assert payload["detail"]["warmup"]["state"] == "done"
    assert payload["detail"]["warmup"]["completed"] == 3


def test_prober_does_not_readmit_warming_replica(warming_server):
    """The fan-in prober keys readmission on /healthz 200 — a replica
    answering the warming 503 stays out of rotation until its ladder
    finishes, then returns automatically."""

    from distributedkernelshap_tpu.serving.replicas import FanInProxy

    server, model = warming_server
    proxy = FanInProxy([("127.0.0.1", server.port)], host="127.0.0.1",
                       port=0, probe_interval_s=0.05,
                       health_interval_s=0).start()
    try:
        proxy.replicas[0].alive = False
        time.sleep(0.5)  # ≥9 probe rounds against the warming replica
        assert proxy.replicas[0].alive is False
        model.release.set()
        assert _wait_for(lambda: proxy.replicas[0].alive)
    finally:
        proxy.stop()


def test_supervisor_does_not_restart_warming_replica(warming_server):
    """The supervisor restarts on process EXIT only; a warming replica's
    process is alive, so ticks must not count it as crashed."""

    from distributedkernelshap_tpu.resilience.supervisor import (
        ReplicaSupervisor,
    )

    server, model = warming_server
    assert server.warmup_status()["state"] in ("pending", "running")
    warming_proc = types.SimpleNamespace(poll=lambda: None, returncode=None)
    sup = ReplicaSupervisor([warming_proc],
                            spawn=lambda i: pytest.fail(
                                "supervisor respawned a warming replica"))
    for _ in range(5):
        sup._tick()
    assert sup.restarts_total == 0
    assert sup._respawn_at == {}


def test_manager_wait_healthy_reports_warming(warming_server):
    """ReplicaManager._wait_healthy distinguishes 'warming' (startup
    progress — keep the process) from dead (False)."""

    from distributedkernelshap_tpu.serving.replicas import ReplicaManager

    server, model = warming_server
    stub = types.SimpleNamespace(
        procs=[types.SimpleNamespace(poll=lambda: None)],
        host="127.0.0.1", ports=[server.port], _stop=threading.Event())
    assert ReplicaManager._wait_healthy(stub, 0, timeout_s=1.5) == "warming"
    model.release.set()
    assert _wait_for(lambda: _healthz(server.port)[0] == 200)
    assert ReplicaManager._wait_healthy(stub, 0, timeout_s=5.0) is True


def test_warmup_failure_serves_cold():
    """A broken warmup must never be worse than no warmup: the gate
    releases, /healthz goes ready, and the error is recorded."""

    from distributedkernelshap_tpu.serving.server import ExplainerServer

    class NoEngineModel:
        def explain_batch(self, instances, split_sizes=None):
            return [json.dumps({"data": {}})
                    for _ in (split_sizes or [1])]

    server = ExplainerServer(NoEngineModel(), host="127.0.0.1", port=0,
                             max_batch_size=2, pipeline_depth=1,
                             health_interval_s=0, warmup=True).start()
    try:
        assert _wait_for(lambda: _healthz(server.port)[0] == 200)
        status = server.warmup_status()
        assert status["state"] == "failed"
        assert "background" in status["error"]
    finally:
        server.stop()


@pytest.mark.parametrize("raw,default,expected", [
    ("", True, True), ("", False, False),
    ("1", False, True), ("yes", False, True),
    ("0", True, False), ("off", True, False),
    # unrecognised values fall back to the component default — the same
    # value must never mean ON for replica workers but OFF for servers
    ("enabled", True, True), ("enabled", False, False),
])
def test_resolve_warmup_env_one_parser(monkeypatch, raw, default, expected):
    from distributedkernelshap_tpu.serving.server import resolve_warmup_env

    if raw:
        monkeypatch.setenv("DKS_WARMUP", raw)
    else:
        monkeypatch.delenv("DKS_WARMUP", raising=False)
    assert resolve_warmup_env(default=default) is expected


def test_warmup_off_by_default():
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    class M:
        def explain_batch(self, instances, split_sizes=None):
            return [json.dumps({"data": {}})
                    for _ in (split_sizes or [1])]

    server = ExplainerServer(M(), host="127.0.0.1", port=0,
                             max_batch_size=2, pipeline_depth=1,
                             health_interval_s=0).start()
    try:
        assert server.warmup_status()["state"] == "off"
        assert _wait_for(lambda: _healthz(server.port)[0] == 200)
    finally:
        server.stop()
