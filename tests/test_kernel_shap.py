"""Tests for the public KernelShap API (reference parity per SURVEY.md §2.1)."""

import logging

import numpy as np
import pytest
from scipy import sparse

from distributedkernelshap_tpu import (
    DenseData,
    Explanation,
    KernelShap,
    rank_by_importance,
    sum_categories,
)
from distributedkernelshap_tpu.kernel_shap import KernelExplainerEngine
from distributedkernelshap_tpu.models import LinearPredictor


# --------------------------------------------------------------------------- #
# helpers


def sum_categories_oracle(values, start_idx, enc_feat_dim):
    """Independent reimplementation used as a cross-check: explicit python
    loop over output columns."""

    blocks = dict(zip(start_idx, enc_feat_dim))
    cols = []
    j = 0
    while j < values.shape[-1]:
        width = blocks.get(j, 1)
        cols.append(list(range(j, j + width)))
        j += width
    if values.ndim == 2:
        return np.stack([values[:, c].sum(1) for c in cols], axis=1)
    tmp = np.stack([values[:, :, c].sum(2) for c in cols], axis=2)
    return np.stack([tmp[:, c, :].sum(1) for c in cols], axis=1)


@pytest.fixture(scope="module")
def fitted_setup():
    rng = np.random.default_rng(0)
    D, K, N, B = 11, 2, 30, 16
    groups = [[0], [1], [2, 3, 4], [5, 6], [7, 8, 9, 10]]
    group_names = ["num0", "num1", "catA", "catB", "catC"]
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)
    pred = LinearPredictor(W, b, activation="softmax")
    return dict(groups=groups, group_names=group_names, W=W, b=b, bg=bg, X=X, pred=pred)


# --------------------------------------------------------------------------- #
# rank_by_importance / sum_categories


def test_rank_by_importance_structure():
    sv = [np.array([[1.0, -3.0, 0.5], [1.0, -3.0, 0.5]]),
          np.array([[0.1, 0.2, 4.0], [0.1, 0.2, 4.0]])]
    imp = rank_by_importance(sv, feature_names=["a", "b", "c"])
    assert set(imp) == {"0", "1", "aggregated"}
    assert imp["0"]["names"] == ["b", "a", "c"]
    np.testing.assert_allclose(imp["0"]["ranked_effect"], [3.0, 1.0, 0.5])
    assert imp["aggregated"]["names"][0] == "c"  # 0.5+4.0 largest


def test_rank_by_importance_bad_names_falls_back():
    sv = [np.ones((2, 3))]
    imp = rank_by_importance(sv, feature_names=["only_two", "names"])
    assert imp["0"]["names"][0].startswith("feature_")


@pytest.mark.parametrize("ndim", [2, 3])
def test_sum_categories_matches_oracle(ndim):
    rng = np.random.default_rng(2)
    ncols = 9
    start_idx, enc_dim = [1, 5], [3, 2]  # cols: [0][1,2,3][4][5,6][7][8]
    shape = (4, ncols) if ndim == 2 else (4, ncols, ncols)
    values = rng.normal(size=shape)
    out = sum_categories(values, start_idx, enc_dim)
    expected = sum_categories_oracle(values, start_idx, enc_dim)
    np.testing.assert_allclose(out, expected, atol=1e-12)
    assert out.shape[-1] == 6


def test_sum_categories_validation():
    v = np.zeros((2, 5))
    with pytest.raises(ValueError):
        sum_categories(v, None, [2])
    with pytest.raises(ValueError):
        sum_categories(v, [0, 1], [2])  # length mismatch
    with pytest.raises(ValueError):
        sum_categories(v, [0], [9])  # exceeds dim
    with pytest.raises(ValueError):
        sum_categories(np.zeros(5), [0], [2])  # rank 1


# --------------------------------------------------------------------------- #
# engine


def test_engine_expected_value_and_layout(fitted_setup):
    s = fitted_setup
    engine = KernelExplainerEngine(s["pred"], DenseData(
        s["bg"], s["group_names"], s["groups"]), link="logit", seed=0)
    assert engine.M == 5
    sv = engine.get_explanation(s["X"][:4], nsamples=64)
    assert isinstance(sv, list) and len(sv) == 2
    assert sv[0].shape == (4, 5)
    # (batch_idx, batch) tuple passthrough
    idx, sv2 = engine.get_explanation((7, s["X"][:4]), nsamples=64)
    assert idx == 7
    np.testing.assert_allclose(sv[0], sv2[0], atol=1e-6)


def test_engine_batch_bucketing_consistency(fitted_setup):
    s = fitted_setup
    engine = KernelExplainerEngine(s["pred"], DenseData(
        s["bg"], s["group_names"], s["groups"]), link="logit", seed=0)
    sv_all = engine.get_explanation(s["X"], nsamples=64)  # B=16 (pow2)
    sv_odd = engine.get_explanation(s["X"][:13], nsamples=64)  # padded to 16
    np.testing.assert_allclose(sv_all[1][:13], sv_odd[1], atol=1e-5)


def test_engine_instance_chunking(fitted_setup):
    from distributedkernelshap_tpu.kernel_shap import EngineConfig

    s = fitted_setup
    engine = KernelExplainerEngine(
        s["pred"], DenseData(s["bg"], s["group_names"], s["groups"]),
        link="logit", seed=0, config=EngineConfig(instance_chunk=5))
    ref = KernelExplainerEngine(
        s["pred"], DenseData(s["bg"], s["group_names"], s["groups"]),
        link="logit", seed=0)
    a = engine.get_explanation(s["X"], nsamples=64)
    b = ref.get_explanation(s["X"], nsamples=64)
    np.testing.assert_allclose(a[0], b[0], atol=1e-5)


# --------------------------------------------------------------------------- #
# KernelShap end-to-end


def test_kernel_shap_end_to_end(fitted_setup):
    s = fitted_setup
    explainer = KernelShap(s["pred"], link="logit", feature_names=s["group_names"],
                           task="classification", seed=0)
    explainer.fit(s["bg"], group_names=s["group_names"], groups=s["groups"])
    explanation = explainer.explain(s["X"], silent=True)

    assert isinstance(explanation, Explanation)
    assert explanation.meta["name"] == "KernelShap"
    sv = explanation.shap_values
    assert len(sv) == 2 and sv[0].shape == (16, 5)
    # additivity against the payload's own raw predictions
    total = np.stack(sv, 1).sum(-1) + np.asarray(explanation.expected_value)[None, :]
    np.testing.assert_allclose(total, explanation.data["raw"]["raw_prediction"], atol=1e-4)
    # importances present and prediction is argmax
    assert "aggregated" in explanation.data["raw"]["importances"]
    np.testing.assert_array_equal(
        explanation.data["raw"]["prediction"],
        np.argmax(explanation.data["raw"]["raw_prediction"], axis=1))
    # whitelisted params recorded ('grouped' is filtered by KERNEL_SHAP_PARAMS,
    # matching the reference whitelist kernel_shap.py:23-31)
    assert explainer.meta["params"]["groups"] == s["groups"]
    assert "grouped" not in explainer.meta["params"]


def test_kernel_shap_exact_linear_end_to_end(fitted_setup):
    s = fitted_setup
    pred = LinearPredictor(s["W"], s["b"], activation="identity")
    explainer = KernelShap(pred, link="identity", seed=0)
    explainer.fit(s["bg"], group_names=s["group_names"], groups=s["groups"])
    explanation = explainer.explain(s["X"], nsamples=64, l1_reg=False)
    diff = s["X"] - s["bg"].mean(0)
    for j, cols in enumerate(s["groups"]):
        expected_j = diff[:, cols] @ s["W"][cols, :]
        np.testing.assert_allclose(explanation.shap_values[0][:, j], expected_j[:, 0], atol=3e-4)


def test_unfitted_explain_raises(fitted_setup):
    explainer = KernelShap(fitted_setup["pred"])
    with pytest.raises(TypeError, match="unfitted"):
        explainer.explain(np.zeros((1, 11)))


def test_distributed_type_guard(fitted_setup):
    import pandas as pd

    s = fitted_setup
    explainer = KernelShap(s["pred"], distributed_opts={"n_cpus": 2})
    assert explainer.distribute
    explainer._fitted = True
    explainer._explainer = None
    with pytest.raises(TypeError, match="distributed context"):
        explainer.explain(pd.DataFrame(np.zeros((2, 11))))


def test_groups_degrade_on_bad_sizes(fitted_setup, caplog):
    s = fitted_setup
    explainer = KernelShap(s["pred"], link="logit", seed=0)
    bad_groups = [[0], [1, 2]]  # only covers 3 of 11 columns
    with caplog.at_level(logging.WARNING):
        explainer.fit(s["bg"], groups=bad_groups, group_names=["a", "b"])
    assert explainer.use_groups is False
    # engine falls back to singleton groups over all 11 columns
    assert explainer._explainer.M == 11


def test_group_names_only_wrong_count_degrades(fitted_setup):
    s = fitted_setup
    explainer = KernelShap(s["pred"], link="logit", seed=0)
    explainer.fit(s["bg"], group_names=["x", "y", "z"])  # no groups, wrong count
    assert explainer.use_groups is False


def test_transposed_background_detected_and_corrected(fitted_setup, caplog):
    """A background passed features-first (D, N) with grouping must be
    detected via the group-size sum (reference transposition check,
    kernel_shap.py:443-449), warned about, and transposed internally so the
    results match the correctly-oriented fit."""

    s = fitted_setup
    ex_t = KernelShap(s["pred"], link="logit", feature_names=s["group_names"], seed=0)
    with caplog.at_level(logging.WARNING):
        ex_t.fit(s["bg"].T, group_names=s["group_names"], groups=s["groups"])
    assert any("transposing" in r.message for r in caplog.records)
    got = ex_t.explain(s["X"], silent=True)

    ex = KernelShap(s["pred"], link="logit", feature_names=s["group_names"], seed=0)
    ex.fit(s["bg"], group_names=s["group_names"], groups=s["groups"])
    want = ex.explain(s["X"], silent=True)
    for g, w in zip(got.shap_values, want.shap_values):
        np.testing.assert_allclose(g, w, atol=1e-5)

    # same flip through the DataFrame dispatch path
    import pandas as pd

    ex_df = KernelShap(s["pred"], link="logit", feature_names=s["group_names"], seed=0)
    ex_df.fit(pd.DataFrame(s["bg"].T), group_names=s["group_names"], groups=s["groups"])
    got_df = ex_df.explain(s["X"], silent=True)
    for g, w in zip(got_df.shap_values, want.shap_values):
        np.testing.assert_allclose(g, w, atol=1e-5)


def test_weights_mismatch_ignored(fitted_setup):
    s = fitted_setup
    explainer = KernelShap(s["pred"], link="logit", seed=0)
    explainer.fit(s["bg"], group_names=s["group_names"], groups=s["groups"],
                  weights=np.ones(7))  # 30 rows, 7 weights
    assert explainer.ignore_weights is True


def test_dataframe_and_series_background_dispatch(fitted_setup):
    """The methdispatch background paths (reference kernel_shap.py:544-671):
    a DataFrame background must give the same values as the equivalent
    ndarray fit; a Series (single background row) must fit and explain."""

    import pandas as pd

    s = fitted_setup
    cols = [f"f{i}" for i in range(s["bg"].shape[1])]

    ex_df = KernelShap(s["pred"], link="logit", feature_names=s["group_names"], seed=0)
    ex_df.fit(pd.DataFrame(s["bg"], columns=cols),
              group_names=s["group_names"], groups=s["groups"])
    got = ex_df.explain(s["X"], silent=True)

    ex = KernelShap(s["pred"], link="logit", feature_names=s["group_names"], seed=0)
    ex.fit(s["bg"], group_names=s["group_names"], groups=s["groups"])
    want = ex.explain(s["X"], silent=True)
    for g, w in zip(got.shap_values, want.shap_values):
        np.testing.assert_allclose(g, w, atol=1e-5)

    ex_series = KernelShap(s["pred"], link="logit", seed=0)
    ex_series.fit(pd.Series(s["bg"][0], index=cols))
    exp = ex_series.explain(s["X"][:4], silent=True)
    total = (np.stack(exp.shap_values, 1).sum(-1)
             + np.asarray(exp.expected_value)[None, :])
    np.testing.assert_allclose(total, exp.data["raw"]["raw_prediction"], atol=1e-4)


def test_dataframe_keep_index_background(fitted_setup):
    """fit(..., keep_index=True) with a DataFrame background must route
    through DenseDataWithIndex (reference kernel_shap.py:637-645) and still
    explain correctly."""

    import pandas as pd

    from distributedkernelshap_tpu.data import DenseDataWithIndex

    s = fitted_setup
    df = pd.DataFrame(s["bg"], columns=[f"f{i}" for i in range(s["bg"].shape[1])],
                      index=[f"row{i}" for i in range(s["bg"].shape[0])])
    ex = KernelShap(s["pred"], link="logit", feature_names=s["group_names"], seed=0)
    ex.fit(df, group_names=s["group_names"], groups=s["groups"], keep_index=True)
    assert isinstance(ex.background_data, DenseDataWithIndex)
    exp = ex.explain(s["X"][:4], silent=True)
    total = (np.stack(exp.shap_values, 1).sum(-1)
             + np.asarray(exp.expected_value)[None, :])
    np.testing.assert_allclose(total, exp.data["raw"]["raw_prediction"], atol=1e-4)


def test_summarise_background_kmeans(fitted_setup):
    s = fitted_setup
    explainer = KernelShap(s["pred"], link="logit", seed=0)
    explainer.fit(s["bg"], summarise_background=True, n_background_samples=5)
    assert explainer.summarise_background is True
    assert isinstance(explainer.background_data, DenseData)
    assert explainer.background_data.data.shape == (5, 11)
    # centroids snapped to observed values
    assert np.isin(explainer.background_data.data[:, 0], s["bg"][:, 0]).all()


def test_summarise_background_subsample_with_groups(fitted_setup):
    s = fitted_setup
    explainer = KernelShap(s["pred"], link="logit", seed=0)
    explainer.fit(s["bg"], summarise_background="auto",
                  group_names=s["group_names"], groups=s["groups"])
    # auto caps at min(n, 300) = 30 -> no reduction, but subsample path taken
    assert explainer.summarise_background is True
    assert explainer._explainer.background.shape[0] == 30


def test_sparse_background_and_explain(fitted_setup):
    s = fitted_setup
    explainer = KernelShap(s["pred"], link="logit", seed=0)
    explainer.fit(sparse.csr_matrix(s["bg"]),
                  group_names=s["group_names"], groups=s["groups"])
    explanation = explainer.explain(sparse.csr_matrix(s["X"][:3]), nsamples=64)
    assert explanation.shap_values[0].shape == (3, 5)


def test_summarise_result(fitted_setup):
    s = fitted_setup
    pred = LinearPredictor(s["W"], s["b"], activation="softmax")
    explainer = KernelShap(pred, link="logit", seed=0)
    explainer.fit(s["bg"])  # no grouping: phi per column (11)
    explanation = explainer.explain(
        s["X"][:4], summarise_result=True,
        cat_vars_start_idx=[2, 5, 7], cat_vars_enc_dim=[3, 2, 4], nsamples=128)
    assert explainer.summarise_result is True
    assert explanation.shap_values[0].shape == (4, 5)


def test_summarise_result_with_groups_skipped(fitted_setup):
    s = fitted_setup
    explainer = KernelShap(s["pred"], link="logit", seed=0)
    explainer.fit(s["bg"], group_names=s["group_names"], groups=s["groups"])
    explanation = explainer.explain(
        s["X"][:2], summarise_result=True,
        cat_vars_start_idx=[2], cat_vars_enc_dim=[3], nsamples=64)
    assert explainer.summarise_result is False
    assert explanation.shap_values[0].shape == (2, 5)


def test_l1_reg_num_features(fitted_setup):
    s = fitted_setup
    pred = LinearPredictor(s["W"], s["b"], activation="identity")
    engine = KernelExplainerEngine(pred, DenseData(
        s["bg"], s["group_names"], s["groups"]), link="identity", seed=0)
    sv = engine.get_explanation(s["X"][:2], nsamples=20, l1_reg="num_features(3)")
    nz = (np.abs(sv[0]) > 1e-9).sum(1)
    assert (nz <= 4).all()  # 3 selected + constrained last feature
    # additivity still holds exactly by construction
    fx = engine.predict(s["X"][:2], link=True)
    ev = np.atleast_1d(engine.expected_value)
    total = np.stack(sv, 1).sum(-1) + ev[None]
    np.testing.assert_allclose(total, fx, atol=1e-4)


def test_l1_select_batch_matches_sklearn_per_fit():
    """The batched selection (shared Gram / X^T y, lars_path_gram, replicated
    LassoLarsIC criterion) must select the same feature sets as one sklearn
    fit per target — the pre-batching implementation (VERDICT r1 #8)."""

    from sklearn.linear_model import Lasso, LassoLarsIC, lars_path

    from distributedkernelshap_tpu.kernel_shap import _l1_select_batch

    rng = np.random.default_rng(3)
    S, p, T = 120, 9, 12
    Xw = rng.normal(size=(S, p))
    # sparse ground truth + noise so selections are non-trivial
    C = rng.normal(size=(p, T)) * (rng.random(size=(p, T)) < 0.4)
    Yw = Xw @ C + 0.05 * rng.normal(size=(S, T))

    for crit in ("aic", "bic"):
        got = _l1_select_batch(Xw, Yw, crit)
        for t in range(T):
            want = np.nonzero(
                LassoLarsIC(criterion=crit).fit(Xw, Yw[:, t]).coef_)[0]
            np.testing.assert_array_equal(got[t], want, err_msg=f"{crit} t={t}")

    got = _l1_select_batch(Xw, Yw, "num_features(3)")
    for t in range(T):
        _, _, coefs = lars_path(Xw, Yw[:, t], max_iter=3)
        np.testing.assert_array_equal(got[t], np.nonzero(coefs[:, -1])[0])

    got = _l1_select_batch(Xw, Yw, 0.01)
    for t in range(T):
        want = np.nonzero(Lasso(alpha=0.01).fit(Xw, Yw[:, t]).coef_)[0]
        np.testing.assert_array_equal(got[t], want)

    # l1_reg=True is classified active by _l1_active and historically ran
    # Lasso(alpha=1.0); it must keep selecting, not raise
    got_true = _l1_select_batch(Xw, Yw, True)
    for t in range(T):
        want = np.nonzero(Lasso(alpha=1.0).fit(Xw, Yw[:, t]).coef_)[0]
        np.testing.assert_array_equal(got_true[t], want)

    with pytest.raises(ValueError):
        _l1_select_batch(Xw, Yw, "bogus")


def test_fit_leaves_global_rng_alone(fitted_setup):
    """fit must not reseed numpy's global RNG (VERDICT r1 weak #7: the
    reference's np.random.seed parity call surprised library users); the
    summarisation path is seeded explicitly and stays deterministic."""

    s = fitted_setup
    np.random.seed(12345)
    before = np.random.get_state()[1].copy()
    ex = KernelShap(s["pred"], link="logit", seed=0)
    ex.fit(s["bg"], summarise_background=True, n_background_samples=5,
           group_names=s["group_names"], groups=s["groups"])
    after = np.random.get_state()[1]
    np.testing.assert_array_equal(before, after)

    # determinism still holds without the global seed: same background both times
    ex2 = KernelShap(s["pred"], link="logit", seed=0)
    ex2.fit(s["bg"], summarise_background=True, n_background_samples=5,
            group_names=s["group_names"], groups=s["groups"])
    np.testing.assert_array_equal(ex._explainer.background,
                                  ex2._explainer.background)


def test_sklearn_lift_faithfulness_guard():
    """Estimators exposing coef_ whose predict_proba is NOT softmax-of-margin
    must not be lifted (review finding: Platt-scaled SVC, ovr-LR)."""

    from sklearn.svm import SVC

    from distributedkernelshap_tpu.models import CallbackPredictor, as_predictor

    rng = np.random.default_rng(0)
    Xtr = rng.normal(size=(80, 5))
    ytr = (Xtr @ rng.normal(size=5) > 0).astype(int)
    svc = SVC(kernel="linear", probability=True, random_state=0).fit(Xtr, ytr)
    pred = as_predictor(svc.predict_proba, example_dim=5)
    assert isinstance(pred, CallbackPredictor)  # lift rejected, callback fallback


def test_engine_config_not_mutated():
    from distributedkernelshap_tpu.kernel_shap import EngineConfig

    rng = np.random.default_rng(0)
    bg = rng.normal(size=(5, 3)).astype(np.float32)
    pred = LinearPredictor(rng.normal(size=(3, 2)).astype(np.float32),
                           np.zeros(2, np.float32), activation="softmax")
    cfg = EngineConfig(link="logit")
    engine = KernelExplainerEngine(pred, bg, config=cfg)
    assert engine.config.link == "logit"  # config value kept when ctor arg absent
    KernelExplainerEngine(pred, bg, link="identity", config=cfg)
    assert cfg.link == "logit"  # caller's config untouched


def test_subsample_preserves_container_type():
    import pandas as pd

    from distributedkernelshap_tpu.ops.summarise import subsample

    df = pd.DataFrame(np.arange(20).reshape(10, 2), columns=["a", "b"])
    out = subsample(df, 4, seed=0)
    assert isinstance(out, pd.DataFrame) and list(out.columns) == ["a", "b"]
    sp = sparse.csr_matrix(np.eye(10))
    assert sparse.issparse(subsample(sp, 4, seed=0))


def test_l1_auto_activates_on_device_ey(fitted_setup, caplog):
    """M large + tiny nsamples -> auto AIC path, fed by device ey (no host
    coalition loop)."""

    rng = np.random.default_rng(1)
    D = 20
    W = rng.normal(size=(D, 2)).astype(np.float32)
    bg = rng.normal(size=(10, D)).astype(np.float32)
    X = rng.normal(size=(2, D)).astype(np.float32)
    pred = LinearPredictor(W, np.zeros(2, np.float32), activation="identity")
    engine = KernelExplainerEngine(pred, bg, link="identity", seed=0)
    with caplog.at_level(logging.WARNING):
        sv = engine.get_explanation(X, nsamples=300, l1_reg="auto")
    assert any("l1_reg='auto'" in r.message for r in caplog.records)
    # additivity preserved by the restricted solve
    fx = engine.predict(X, link=True)
    total = np.stack(sv, 1).sum(-1) + np.atleast_1d(engine.expected_value)[None]
    np.testing.assert_allclose(total, fx, atol=1e-4)


def test_explanation_json_roundtrip_end_to_end(fitted_setup):
    s = fitted_setup
    explainer = KernelShap(s["pred"], link="logit", seed=0)
    explainer.fit(s["bg"], group_names=s["group_names"], groups=s["groups"])
    explanation = explainer.explain(s["X"][:2], nsamples=64)
    rebuilt = Explanation.from_json(explanation.to_json())
    np.testing.assert_allclose(
        np.asarray(rebuilt.data["shap_values"][0]),
        explanation.shap_values[0], atol=1e-6)


def test_lars_knots_batched_matches_sklearn_on_correlated_designs():
    """The batched Gram-space LARS (round 4: one vectorized sweep replaces
    the per-target lars_path_gram loop, VERDICT r3 #5) must reproduce
    sklearn per-fit selections on correlated designs — the regime that
    exercises lasso drops and plain-LARS sign flips."""

    import warnings

    from sklearn.linear_model import LassoLarsIC, lars_path

    from distributedkernelshap_tpu.kernel_shap import _l1_select_batch

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for seed in (3, 7, 11):
            rng = np.random.default_rng(seed)
            S = int(rng.integers(60, 300))
            p = int(rng.integers(4, 14))
            mix = np.eye(p) + 0.6 * rng.normal(size=(p, p)) / np.sqrt(p)
            Xw = rng.normal(size=(S, p)) @ mix
            T = 6
            C = rng.normal(size=(p, T)) * (rng.random(size=(p, T)) < 0.5)
            Yw = Xw @ C + 0.1 * rng.normal(size=(S, T))
            for crit in ("aic", "bic"):
                got = _l1_select_batch(Xw, Yw, crit)
                for t in range(T):
                    want = np.nonzero(
                        LassoLarsIC(criterion=crit).fit(Xw, Yw[:, t]).coef_)[0]
                    np.testing.assert_array_equal(
                        got[t], want, err_msg=f"seed={seed} {crit} t={t}")
            got = _l1_select_batch(Xw, Yw, "num_features(3)")
            for t in range(T):
                _, _, coefs = lars_path(Xw, Yw[:, t], max_iter=3)
                np.testing.assert_array_equal(
                    got[t], np.nonzero(coefs[:, -1])[0],
                    err_msg=f"seed={seed} nf t={t}")


def test_l1_select_batch_survives_collinear_design():
    """Exactly collinear coalition columns (possible under tiny nsamples
    budgets) must not crash or corrupt the batch: degenerate targets are
    detected and routed through sklearn's per-target path, and every
    selection's restricted OLS fit is at least as good as sklearn's choice
    (supports are non-unique under exact duplicates, so set identity is
    not the right oracle here)."""

    import warnings

    from sklearn.linear_model import LassoLarsIC

    from distributedkernelshap_tpu.kernel_shap import _l1_select_batch

    rng = np.random.default_rng(3)
    S, p, T = 120, 6, 8
    Xw = rng.normal(size=(S, p))
    Xw[:, 3] = Xw[:, 2]  # exact duplicate
    C = rng.normal(size=(p, T)) * (rng.random(size=(p, T)) < 0.6)
    Yw = Xw @ C + 0.05 * rng.normal(size=(S, T))

    def rss_of(sel, y):
        if len(sel) == 0:
            return float(y @ y)
        coef, *_ = np.linalg.lstsq(Xw[:, sel], y, rcond=None)
        r = y - Xw[:, sel] @ coef
        return float(r @ r)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for mode in ("aic", "bic", "num_features(3)"):
            sels = _l1_select_batch(Xw, Yw, mode)  # must not raise
            assert len(sels) == T
        got = _l1_select_batch(Xw, Yw, "aic")
        for t in range(T):
            want = np.nonzero(
                LassoLarsIC(criterion="aic").fit(Xw, Yw[:, t]).coef_)[0]
            # quality parity: our support fits the target essentially as
            # well as sklearn's (identical RSS up to duplicate-column
            # ambiguity), with a comparable support size
            assert rss_of(got[t], Yw[:, t]) <= rss_of(want, Yw[:, t]) * 1.5 + 1e-9
            assert abs(len(got[t]) - len(want)) <= 2


def test_rank_features_matches_host_ranking():
    """rank_features (device-side mean-|phi| reduction; only (K, M) floats
    cross the wire) must reproduce rank_by_importance over a full explain
    on the same instances — single-device, chunked, and mesh-sharded."""

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.kernel_shap import (
        EngineConfig,
        rank_by_importance,
    )
    from distributedkernelshap_tpu.models import LinearPredictor

    rng = np.random.default_rng(0)
    D, K, N, B = 8, 3, 16, 24
    W = rng.normal(size=(D, K)).astype(np.float32)
    pred = LinearPredictor(W, np.zeros(K, np.float32), activation="softmax")
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(B, D)).astype(np.float32)
    names = [f"f{i}" for i in range(D)]

    def check(ex):
        ex.fit(bg)
        want = rank_by_importance(
            ex.explain(X, silent=True, l1_reg=False).shap_values, names)
        got = ex.rank_features(X)
        assert set(got) == set(want)
        for key in got:
            assert got[key]["names"] == want[key]["names"]
            np.testing.assert_allclose(got[key]["ranked_effect"],
                                       want[key]["ranked_effect"], atol=1e-5)

    check(KernelShap(pred, link="identity", feature_names=names, seed=0))
    check(KernelShap(pred, link="identity", feature_names=names, seed=0,
                     engine_config=EngineConfig(instance_chunk=7)))
    check(KernelShap(pred, link="identity", feature_names=names, seed=0,
                     distributed_opts={"n_devices": 4, "batch_size": 2}))


def test_rank_features_requires_fit():
    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models import LinearPredictor

    pred = LinearPredictor(np.eye(4, 2, dtype=np.float32),
                           np.zeros(2, np.float32), activation="softmax")
    with pytest.raises(TypeError, match="unfitted"):
        KernelShap(pred, link="identity").rank_features(np.zeros((2, 4)))


def test_aic_selection_perf_floor():
    """Regression guard on `_lars_knots_batched` (VERDICT r4 #8): the
    batched AIC selection pass over the headline task's 5120 targets
    (B=2560 x K=2, Adult nsamples default S=2072 rows, p=11) must stay
    well under the pre-batching implementation's ~42 s.  The bound is an
    ABSOLUTE wall-clock with >=4x headroom over the measured 4.5 s on a
    contended single-core CI host (ratio asserts flake here; this only
    catches an order-of-magnitude regression, which is exactly the class
    of bug that motivated the batching)."""

    import time

    from distributedkernelshap_tpu.kernel_shap import _l1_select_batch

    rng = np.random.default_rng(0)
    S, p, T = 2072, 11, 5120
    Xw = rng.normal(size=(S, p))
    beta = np.zeros((p, T))
    beta[:4] = rng.normal(size=(4, T))
    Yw = Xw @ beta + 0.1 * rng.normal(size=(S, T))
    t0 = time.perf_counter()
    sels = _l1_select_batch(Xw, Yw, "aic")
    wall = time.perf_counter() - t0
    # correctness sanity so the guard can't pass on a broken fast path: the
    # 4 true support features must be selected for (almost) every target
    hit = np.mean([set(range(4)) <= set(s.tolist()) for s in sels])
    assert hit > 0.99, hit
    assert wall < 20.0, (
        f"batched aic selection took {wall:.1f}s for {T} targets; the "
        f"batched path should need ~1s (4.5s on a contended core) — "
        f"pre-batching per-target sklearn needed ~42s")
