"""Registry subsystem unit tests: the ONE path classifier, content
fingerprints (+ the weak-fallback accounting), registration / hot-swap /
drain lifecycle, and per-tenant quotas."""

import threading
import time

import numpy as np
import pytest

from distributedkernelshap_tpu.models import LinearPredictor
from distributedkernelshap_tpu.registry import (
    ModelRegistry,
    TenantQuota,
    classify_path,
)

D = 5


def _linear(seed=0, activation="softmax"):
    rng = np.random.default_rng(seed)
    return LinearPredictor(rng.normal(size=(D, 2)).astype(np.float32),
                           rng.normal(size=(2,)).astype(np.float32),
                           activation=activation)


class StubServing:
    """Minimal serving model for lifecycle tests (no jax work)."""

    def __init__(self, tag):
        self.tag = tag

    def explain_batch(self, instances, split_sizes=None):
        sizes = split_sizes or [1] * instances.shape[0]
        return [f'{{"tag": "{self.tag}"}}' for _ in sizes]


# --------------------------------------------------------------------- #
# classify_path
# --------------------------------------------------------------------- #


def test_classify_linear_predictor():
    decision = classify_path(_linear())
    assert decision.path == "linear"
    assert "plan-constant" in decision.reason


def test_classify_tree_ensemble():
    from sklearn.ensemble import HistGradientBoostingRegressor

    from distributedkernelshap_tpu.models.predictors import as_predictor

    rng = np.random.default_rng(1)
    X = rng.normal(size=(120, D))
    gbr = HistGradientBoostingRegressor(max_iter=6, max_depth=3,
                                        random_state=0).fit(
        X, X[:, 0] - X[:, 1])
    pred = as_predictor(gbr.predict, example_dim=D)
    assert classify_path(pred).path == "exact_tree"
    # a non-identity link changes the target quantity: stays sampled
    assert classify_path(pred, link="logit").path == "sampled"


def test_classify_tensor_train():
    from distributedkernelshap_tpu.models.tensor_net import (
        TensorTrainPredictor,
    )

    rng = np.random.default_rng(2)
    ranks = [1, 2, 2, 2, 2, 1]
    cores = [(rng.normal(size=(ranks[i], ranks[i + 1])).astype(np.float32),
              rng.normal(size=(ranks[i], ranks[i + 1])).astype(np.float32))
             for i in range(D)]
    decision = classify_path(TensorTrainPredictor(cores))
    assert decision.path == "exact_tn"


def test_classify_generic_callable_is_sampled():
    from distributedkernelshap_tpu.models.predictors import (
        CallbackPredictor,
    )

    pred = CallbackPredictor(lambda x: np.ones((x.shape[0], 1)),
                             n_outputs=1)
    assert classify_path(pred).path == "sampled"


def test_classify_never_raises():
    class Hostile:
        @property
        def linear_decomposition(self):
            raise RuntimeError("boom")

    decision = classify_path(Hostile())
    assert decision.path == "sampled"
    assert "probe failed" in decision.reason


# --------------------------------------------------------------------- #
# content fingerprints + the weak fallback
# --------------------------------------------------------------------- #


def test_predictor_fingerprint_is_content_stable():
    from distributedkernelshap_tpu.scheduling.result_cache import (
        predictor_fingerprint,
    )

    a, weak_a = predictor_fingerprint(_linear(seed=3))
    b, weak_b = predictor_fingerprint(_linear(seed=3))
    c, _ = predictor_fingerprint(_linear(seed=4))
    assert not weak_a and not weak_b
    assert a == b  # distinct objects, identical parameters
    assert a != c  # different weights


def test_predictor_fingerprint_hashes_scalar_config():
    from distributedkernelshap_tpu.scheduling.result_cache import (
        predictor_fingerprint,
    )

    rng = np.random.default_rng(5)
    W = rng.normal(size=(D, 1)).astype(np.float32)
    b = rng.normal(size=(1,)).astype(np.float32)
    # same parameter arrays, different scalar config: MUST NOT collide
    # (a collision here serves one model's cached phi for the other)
    ident, w_i = predictor_fingerprint(
        LinearPredictor(W, b, activation="identity"))
    sig, w_s = predictor_fingerprint(
        LinearPredictor(W, b, activation="sigmoid"))
    assert not w_i and not w_s
    assert ident != sig


def test_weak_fingerprint_counts_and_warns_once():
    from distributedkernelshap_tpu.models.predictors import (
        CallbackPredictor,
    )
    from distributedkernelshap_tpu.scheduling.result_cache import (
        predictor_fingerprint,
        record_weak_fingerprint,
        weak_fingerprint_total,
    )

    pred = CallbackPredictor(lambda x: np.ones((x.shape[0], 1)),
                             n_outputs=1)
    digest, weak = predictor_fingerprint(pred)
    assert weak and str(id(pred)) in digest
    before = weak_fingerprint_total()
    record_weak_fingerprint(pred)
    assert weak_fingerprint_total() == before + 1


def test_model_fingerprint_counts_weak_for_stub_models():
    from distributedkernelshap_tpu.scheduling.result_cache import (
        model_fingerprint,
        weak_fingerprint_total,
    )

    before = weak_fingerprint_total()
    model_fingerprint(StubServing("x"))
    assert weak_fingerprint_total() == before + 1
    # the registry's ingest path namespaces instead of counting
    model_fingerprint(StubServing("x"), count_weak=False)
    assert weak_fingerprint_total() == before + 1


# --------------------------------------------------------------------- #
# registration / versions / hot swap / drain
# --------------------------------------------------------------------- #


def test_register_versions_and_resolve():
    reg = ModelRegistry()
    m1 = StubServing("v1")
    rm1 = reg.register("m", m1)
    assert rm1.version == 1 and reg.resolve("m") is rm1
    assert reg.resolve() is rm1  # first id is the default
    assert m1.fingerprint.startswith("m@v1:")
    rm2 = reg.register("m", StubServing("v2"))
    assert rm2.version == 2 and reg.resolve("m") is rm2
    assert rm1.state == "retired"  # nothing in flight: drained instantly
    assert reg.resolve("nope") is None
    with pytest.raises(ValueError):
        reg.register("m", StubServing("v2dup"), version=2)
    with pytest.raises(ValueError):
        reg.register("bad=id", StubServing("x"))
    with pytest.raises(ValueError):
        reg.register("m", object())  # no explain_batch


def test_swap_records_flight_event_and_counts():
    from distributedkernelshap_tpu.observability.flightrec import flightrec

    reg = ModelRegistry()
    reg.register("swapper", StubServing("v1"))
    reg.register("swapper", StubServing("v2"))
    events = [e for e in flightrec().to_payload()["events"]
              if e["kind"] == "model_swap" and e.get("model") == "swapper"]
    assert len(events) >= 2
    assert events[-1]["from_version"] == 1
    assert events[-1]["to_version"] == 2
    assert reg.metric_swaps() == {("swapper",): 2.0}
    assert reg.metric_models() == {("swapper", "2", "sampled"): 1.0}


def test_per_model_counters_survive_hot_swap():
    reg = ModelRegistry()
    rm1 = reg.register("agg", StubServing("v1"))
    rm1.record_answer(0.5, False)
    rm1.record_answer(0.5, False)
    assert reg.metric_requests() == {("agg",): 2.0}
    reg.register("agg", StubServing("v2"))
    # a hot swap must NOT reset the per-model counter (Prometheus would
    # read it as a counter reset and lose v1's tallies from rates)
    assert reg.metric_requests() == {("agg",): 2.0}
    assert reg.metric_seconds() == {("agg",): 1.0}
    reg.resolve("agg").record_answer(0.25, False)
    assert reg.metric_requests() == {("agg",): 3.0}
    assert reg.metric_seconds() == {("agg",): 1.25}


def test_concurrent_registrations_allocate_distinct_versions():
    reg = ModelRegistry()
    errors = []

    def one(i):
        try:
            reg.register("race", StubServing(f"m{i}"))
        except Exception as e:  # pragma: no cover - failure detail
            errors.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    versions = reg._models["race"]["versions"]
    assert sorted(versions) == [1, 2, 3, 4, 5, 6]  # nothing overwritten


def test_registry_path_reflects_pinned_deployment():
    from sklearn.ensemble import HistGradientBoostingRegressor

    from distributedkernelshap_tpu.serving.wrappers import KernelShapModel

    rng = np.random.default_rng(6)
    X = rng.normal(size=(120, D))
    gbr = HistGradientBoostingRegressor(max_iter=5, max_depth=3,
                                        random_state=0).fit(
        X, X[:, 0] - X[:, 1])
    bg = X[:8].astype(np.float32)
    auto = KernelShapModel(gbr.predict, bg, {"seed": 0}, {})
    pinned = KernelShapModel(gbr.predict, bg, {"seed": 0}, {},
                             explain_kwargs={"nsamples": 64})
    reg = ModelRegistry()
    assert reg.register("auto_tree", auto).path == "exact_tree"
    rm = reg.register("pinned_tree", pinned)
    # the deployment SERVES sampled (pinned nsamples): the registry must
    # not advertise an exact path it does not run
    assert rm.path == "sampled"
    assert "structurally available" in rm.path_reason


def test_drain_waits_for_pinned_requests():
    reg = ModelRegistry(drain_timeout_s=5.0)
    rm1 = reg.register("d", StubServing("v1"))
    rm1.acquire()  # a request in flight on v1
    done = threading.Event()

    def swap():
        reg.register("d", StubServing("v2"))
        done.set()

    t = threading.Thread(target=swap, daemon=True)
    t.start()
    # the swap FLIPS immediately (new requests already land on v2)...
    deadline = time.monotonic() + 5
    while reg.resolve("d").version != 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert reg.resolve("d").version == 2
    # ...but the register call itself blocks in the drain until the
    # pinned request releases
    assert not done.wait(0.2)
    assert rm1.state == "draining"
    rm1.release()
    assert done.wait(5)
    assert rm1.state == "retired"


def test_drain_timeout_leaves_version_draining():
    reg = ModelRegistry(drain_timeout_s=0.1)
    rm1 = reg.register("t", StubServing("v1"))
    rm1.acquire()
    reg.register("t", StubServing("v2"))  # drain times out
    assert rm1.state == "draining"
    rm1.release()


# --------------------------------------------------------------------- #
# per-tenant quotas
# --------------------------------------------------------------------- #


def test_quota_inflight_bound():
    quota = TenantQuota(max_inflight=2)
    assert quota.admit(0)[0] and quota.admit(1)[0]
    ok, reason, retry = quota.admit(2)
    assert not ok and reason == "tenant_queue_full" and retry > 0


def test_quota_rate_bucket():
    quota = TenantQuota(rate_per_s=1000.0, burst=2)
    assert quota.admit(0)[0] and quota.admit(0)[0]
    ok, reason, retry = quota.admit(0)
    assert not ok and reason == "tenant_rate_limited" and retry > 0


def test_default_quota_is_cloned_per_tenant():
    reg = ModelRegistry(default_quota=TenantQuota(rate_per_s=1000.0,
                                                  burst=1))
    rm_a = reg.register("a", StubServing("a"))
    rm_b = reg.register("b", StubServing("b"))
    assert rm_a.quota is not rm_b.quota
    # draining tenant a's bucket must not shed tenant b
    assert reg.admit(rm_a)[0]
    ok_a2, reason_a, _ = reg.admit(rm_a)
    assert not ok_a2 and reason_a == "tenant_rate_limited"
    assert reg.admit(rm_b)[0]


def test_hot_swap_preserves_tenant_quota():
    reg = ModelRegistry()
    quota = TenantQuota(max_inflight=7)
    reg.register("keep", StubServing("v1"), quota=quota)
    rm2 = reg.register("keep", StubServing("v2"))  # routine model update
    # the tenant's policy survives the swap (same object: bucket state
    # carries across the flip); an explicit quota= still overrides
    assert rm2.quota is quota
    rm3 = reg.register("keep", StubServing("v3"),
                       quota=TenantQuota(max_inflight=1))
    assert rm3.quota is not quota and rm3.quota.max_inflight == 1


def test_retired_version_releases_its_model():
    reg = ModelRegistry()
    rm1 = reg.register("leak", StubServing("v1"))
    reg.register("leak", StubServing("v2"))
    assert rm1.state == "retired"
    # the engine is released (one model per nightly swap must not
    # accumulate); the scalar tallies stay for the per-id metric sums
    assert rm1.model is None
    assert reg.metric_requests() == {("leak",): 0.0}


def test_resolve_pin_is_atomic_with_lookup():
    reg = ModelRegistry()
    reg.register("pin", StubServing("v1"))
    rm = reg.resolve("pin", pin=True)
    assert rm.inflight == 1
    rm.release()
    assert rm.inflight == 0
    assert reg.resolve("pin").inflight == 0  # plain resolve never pins
    # admit() with exclude_self ignores the caller's own pin
    reg2 = ModelRegistry()
    rm2 = reg2.register("q", StubServing("v1"),
                        quota=TenantQuota(max_inflight=1))
    rm2.acquire()
    assert reg2.admit(rm2, exclude_self=True)[0]
    assert not reg2.admit(rm2)[0]
    rm2.release()


def test_registry_admit_counts_sheds_per_model():
    reg = ModelRegistry()
    rm = reg.register("q", StubServing("v1"),
                      quota=TenantQuota(max_inflight=0))
    ok, reason, _ = reg.admit(rm)
    assert not ok and reason == "tenant_queue_full"
    assert reg.metric_sheds() == {("q", "tenant_queue_full"): 1.0}
    # a quota-less tenant never sheds
    rm2 = reg.register("free", StubServing("v1"))
    assert reg.admit(rm2) == (True, "", 0.0)
