"""Tests for the SLO layer (availability / latency / staleness burn
rates, multi-window breach logic, priority-class sync) and the alert
rules engine (pending→firing→resolved state machine, for/keep-firing
durations, dedup, silences, sinks) plus the committed health-check
replay fixture."""

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from distributedkernelshap_tpu.observability.alerts import (
    AlertManager,
    AlertRule,
    CollectSink,
    FlightRecorderSink,
    WebhookSink,
    slo_burn_rule,
)
from distributedkernelshap_tpu.observability.flightrec import FlightRecorder
from distributedkernelshap_tpu.observability.metrics import MetricsRegistry
from distributedkernelshap_tpu.observability.slo import (
    AvailabilitySLO,
    BurnRateWindow,
    LatencySLO,
    PRIORITY_CLASSES,
    SLO,
    StalenessSLO,
    default_proxy_slos,
    default_server_slos,
)
from distributedkernelshap_tpu.observability.timeseries import TimeSeriesStore


def _counter_ramp(store, name, per_s, until=120, start=0, t0=0):
    value = 0.0
    for t in range(t0, until + 1):
        if t >= start:
            value += per_s
        store.add(name, t, value, kind="counter")


# --------------------------------------------------------------------- #
# SLO units
# --------------------------------------------------------------------- #


def test_priority_classes_stay_in_sync_with_scheduler():
    from distributedkernelshap_tpu.scheduling import (
        PRIORITY_CLASSES as SCHED_CLASSES,
    )

    assert tuple(SCHED_CLASSES) == PRIORITY_CLASSES


def test_availability_slo_burn_rate_and_windows():
    store = TimeSeriesStore()
    _counter_ramp(store, "total", 10.0)
    _counter_ramp(store, "bad", 5.0, start=30)
    slo = AvailabilitySLO("avail", total="total", bad="bad", target=0.99,
                          windows=(BurnRateWindow(20, 5, 2.0),))
    # before the error burst: burn 0, full budget, not breached
    status = slo.evaluate(store, now=20)
    assert status["burn_rates"]["20s"] == pytest.approx(0.0)
    assert status["budget_remaining"] == pytest.approx(1.0)
    assert not status["breached"]
    # mid-burst: 50% bad / 1% budget = 50x burn in both windows
    status = slo.evaluate(store, now=50)
    assert status["burn_rates"]["5s"] == pytest.approx(50.0)
    assert status["breached"]
    assert status["budget_remaining"] < 0
    # idle store: no verdict, no breach
    assert not AvailabilitySLO(
        "a2", total="nope", bad="bad", target=0.99).evaluate(
        store, now=50)["breached"]


def test_breach_requires_both_windows():
    """The long window proves sustained burn; the short window clears
    promptly.  Burn in only ONE window must not breach."""

    store = TimeSeriesStore()
    _counter_ramp(store, "total", 10.0)
    # errors stop at t=60: the 5s window is clean by t=70 while the 60s
    # window still carries the burst
    _counter_ramp(store, "bad", 5.0, start=30, until=60)
    for t in range(61, 121):
        store.add("bad", t, store.latest("bad"), kind="counter")
    slo = AvailabilitySLO("avail", total="total", bad="bad", target=0.9,
                          windows=(BurnRateWindow(60, 5, 2.0),))
    status = slo.evaluate(store, now=70)
    assert status["burn_rates"]["60s"] > 2.0
    assert status["burn_rates"]["5s"] == pytest.approx(0.0)
    assert not status["breached"]


def test_latency_slo_over_histogram_labels():
    store = TimeSeriesStore()
    buckets = (0.1, 0.5, 1.0)
    store.add_histogram("lat", 0, buckets, (0, 0, 0, 0), 0.0, 0,
                        labels={"class": "interactive"})
    # 8 fast, 2 slow: 20% bad vs 10% budget = burn 2
    store.add_histogram("lat", 10, buckets, (0, 8, 0, 2), 6.0, 10,
                        labels={"class": "interactive"})
    slo = LatencySLO("ilat", histogram="lat", threshold_s=0.5, target=0.9,
                     labels={"class": "interactive"},
                     windows=(BurnRateWindow(30, 30, 2.0),))
    status = slo.evaluate(store, now=10)
    assert status["burn_rates"]["30s"] == pytest.approx(2.0)
    assert status["breached"]


def test_staleness_slo_fraction_of_bad_samples():
    store = TimeSeriesStore()
    for t in range(10):
        store.add("age", t, 60.0 if t >= 5 else 1.0)
    slo = StalenessSLO("stale", gauge="age", max_staleness_s=30.0,
                       target=0.9, windows=(BurnRateWindow(10, 10, 2.0),))
    status = slo.evaluate(store, now=9)
    assert status["burn_rates"]["10s"] == pytest.approx(5.0)
    assert status["breached"]


def test_slo_target_validation_and_defaults():
    with pytest.raises(ValueError):
        SLO("bad", target=1.0)
    with pytest.raises(ValueError):
        SLO("bad", target=0.9, windows=())
    server_slos = default_server_slos()
    names = {s.name for s in server_slos}
    assert {"availability", "interactive_latency", "batch_latency",
            "best_effort_latency", "inflight_progress",
            "anytime_error", "answer_quality"} == names
    assert {s.name for s in default_proxy_slos()} == {"proxy_availability"}


# --------------------------------------------------------------------- #
# alert state machine
# --------------------------------------------------------------------- #


def _flag_rule(flag, **kw):
    return AlertRule("flag", lambda store, now: flag["v"], **kw)


def test_alert_for_duration_gates_firing():
    flag = {"v": False}
    sink = CollectSink()
    mgr = AlertManager(None, [_flag_rule(flag, for_s=5, keep_firing_s=3)],
                       sinks=[sink])
    assert mgr.evaluate(now=0) == []
    flag["v"] = True
    mgr.evaluate(now=1)
    assert mgr.states()["flag"] == "pending"
    mgr.evaluate(now=3)
    assert mgr.states()["flag"] == "pending"  # for_s not yet served
    mgr.evaluate(now=6)
    assert mgr.states()["flag"] == "firing"
    # steady firing does not re-notify (dedup)
    mgr.evaluate(now=7)
    mgr.evaluate(now=8)
    assert [e["state"] for e in sink.events] == ["pending", "firing"]
    # condition clears: firing persists until keep_firing_s elapses
    flag["v"] = False
    mgr.evaluate(now=9)
    assert mgr.states()["flag"] == "firing"
    mgr.evaluate(now=11.5)
    assert mgr.states()["flag"] == "inactive"
    assert [e["state"] for e in sink.events] == [
        "pending", "firing", "resolved"]


def test_pending_flap_notifies_once_per_renotify_window():
    """A condition blinking just under for_s moves the state machine
    every episode but must not spam sinks (and the bounded flight ring)
    with one pending notification per blink."""

    flag = {"v": False}
    sink = CollectSink()
    mgr = AlertManager(None, [_flag_rule(flag, for_s=10)], sinks=[sink],
                       pending_renotify_s=60)
    for t in range(0, 40, 2):
        flag["v"] = (t % 4 == 0)  # true/false every other tick
        mgr.evaluate(now=t)
    assert [e["state"] for e in sink.events] == ["pending"]
    # after the renotify window a fresh episode notifies again
    flag["v"] = True
    mgr.evaluate(now=100)
    assert [e["state"] for e in sink.events] == ["pending", "pending"]


def test_alert_pending_blink_never_fires():
    flag = {"v": True}
    sink = CollectSink()
    mgr = AlertManager(None, [_flag_rule(flag, for_s=10)], sinks=[sink])
    mgr.evaluate(now=0)
    flag["v"] = False
    mgr.evaluate(now=2)
    assert mgr.states()["flag"] == "inactive"
    assert [e["state"] for e in sink.events] == ["pending"]  # no resolved


def test_alert_zero_for_fires_immediately_and_refires_after_resolve():
    flag = {"v": True}
    sink = CollectSink()
    mgr = AlertManager(None, [_flag_rule(flag, for_s=0, keep_firing_s=0)],
                       sinks=[sink])
    mgr.evaluate(now=0)
    assert mgr.firing() == ["flag"]
    flag["v"] = False
    mgr.evaluate(now=1)
    flag["v"] = True
    mgr.evaluate(now=2)
    assert [e["state"] for e in sink.events] == [
        "firing", "resolved", "firing"]


def test_silence_suppresses_sinks_but_not_state():
    flag = {"v": True}
    sink = CollectSink()
    mgr = AlertManager(None, [_flag_rule(flag, for_s=0)], sinks=[sink])
    mgr.silence("fl*", duration_s=100, now=0)
    events = mgr.evaluate(now=1)
    assert mgr.firing() == ["flag"]  # state machine ran
    assert sink.events == []  # sink suppressed
    assert events and events[0].get("silenced")
    # lapsed silence notifies again
    flag["v"] = False
    mgr.evaluate(now=200)
    assert [e["state"] for e in sink.events] == ["resolved"]


def test_duplicate_rule_names_rejected():
    rule = AlertRule("dup", lambda s, n: False)
    with pytest.raises(ValueError):
        AlertManager(None, [rule, AlertRule("dup", lambda s, n: False)])


def test_broken_condition_and_sink_do_not_kill_evaluator():
    def boom(store, now):
        raise RuntimeError("boom")

    class BadSink:
        def notify(self, event):
            raise RuntimeError("sink boom")

    flag = {"v": True}
    good = CollectSink()
    mgr = AlertManager(None, [AlertRule("broken", boom),
                              _flag_rule(flag, for_s=0)],
                       sinks=[BadSink(), good])
    mgr.evaluate(now=0)
    assert mgr.firing() == ["flag"]
    assert [e["state"] for e in good.events] == ["firing"]


def test_firing_gauge_attaches_to_registry():
    flag = {"v": True}
    mgr = AlertManager(None, [_flag_rule(flag, for_s=0)])
    reg = MetricsRegistry()
    mgr.attach_metrics(reg)
    assert 'dks_alerts_firing{rule="flag"} 0' in reg.render()
    mgr.evaluate(now=0)
    assert 'dks_alerts_firing{rule="flag"} 1' in reg.render()


def test_flightrec_sink_records_transitions():
    flight = FlightRecorder()
    flag = {"v": True}
    mgr = AlertManager(None, [_flag_rule(flag, for_s=0)],
                       sinks=[FlightRecorderSink(flight)], component="test")
    mgr.evaluate(now=0)
    events = flight.snapshot("alert")
    assert len(events) == 1
    assert events[0]["rule"] == "flag" and events[0]["state"] == "firing"


def test_webhook_sink_posts_and_survives_dead_receiver():
    received = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            received.append(json.loads(
                self.rfile.read(int(self.headers["Content-Length"]))))
            self.send_response(204)
            self.end_headers()

        def log_message(self, *a):
            pass

    httpd = HTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        sink = WebhookSink(f"http://127.0.0.1:{httpd.server_address[1]}/")
        sink.notify({"rule": "r", "state": "firing", "severity": "page"})
        sink.wait()  # POSTs run on a daemon thread off the evaluator
        assert received and received[0]["rule"] == "r"
    finally:
        httpd.shutdown()
        httpd.server_close()
    # dead receiver: logged, never raised (and never blocks notify)
    dead = WebhookSink("http://127.0.0.1:1/", timeout_s=0.2)
    dead.notify({"rule": "r", "state": "resolved"})
    dead.wait()


def test_slo_burn_rule_carries_status_info():
    store = TimeSeriesStore()
    _counter_ramp(store, "total", 10.0, until=60)
    _counter_ramp(store, "bad", 10.0, until=60)
    slo = AvailabilitySLO("avail", total="total", bad="bad", target=0.9,
                          windows=(BurnRateWindow(20, 5, 2.0),))
    sink = CollectSink()
    mgr = AlertManager(store, [slo_burn_rule(slo, for_s=0)], sinks=[sink])
    mgr.evaluate(now=30)
    assert mgr.firing() == ["slo_burn:avail"]
    info = sink.events[0]["info"]
    assert info["slo"] == "avail"
    assert info["burn_rates"]["5s"] == pytest.approx(10.0)


# --------------------------------------------------------------------- #
# the committed replay fixture (the make health-check golden path)
# --------------------------------------------------------------------- #


def test_health_check_replay_fixture_golden_transitions():
    import scripts.health_check as hc

    report = hc.run_check()
    assert report["ok"], report
    assert [t["state"] for t in report["transitions"]] == [
        "pending", "firing", "resolved"]
