"""Exact tensor-network Shapley (ops/tensor_shap.py + models/tensor_net.py
and their engine / mesh / serving integration).

Oracles: the size-indexed DP contraction is pinned against a float64
brute-force enumeration of ALL 2^M coalitions at small M (tighter than
the f32 phi it produces); the rank-1/linear lift is pinned against the
closed-form linear Shapley values W_j (x_j - E z_j) and the linear fast
path; the mesh-sharded run is pinned BIT-IDENTICAL to the single-device
run (its engineered property — per-row phi all-gathered, the one final
weighted-row-sum einsum replayed replicated); and the full-enumeration
parity regime pins the sampled estimator against both exact paths
end to end (``coalition_plan`` with ``total <= nsamples`` silently
enumerates every coalition, so the WLS solve is exact by construction —
nothing asserted that until now).
"""

import json
import time
from itertools import combinations
from math import factorial

import numpy as np
import pytest

from distributedkernelshap_tpu.kernel_shap import (
    EngineConfig,
    KernelExplainerEngine,
    KernelShap,
    StagedRows,
)
from distributedkernelshap_tpu.models.tensor_net import (
    TensorTrainPredictor,
    fit_tt_surrogate,
)
from distributedkernelshap_tpu.ops import tensor_shap as tns


def _make_tt(M, r, seed=0, K=1, b_scale=0.3):
    """A well-conditioned random TT predictor (per-site scale ~ r^-1/2
    keeps the chained products O(1) over M sites)."""

    rng = np.random.default_rng(seed)
    dims = [1] + [r] * (M - 1) + [K]
    scale = 1.0 / np.sqrt(r)
    cores = [(rng.normal(scale=scale,
                         size=(dims[i], dims[i + 1])).astype(np.float32),
              rng.normal(scale=b_scale * scale,
                         size=(dims[i], dims[i + 1])).astype(np.float32))
             for i in range(M)]
    return TensorTrainPredictor(cores)


@pytest.fixture(scope="module")
def small_tn():
    rng = np.random.default_rng(3)
    M = 6
    pred = _make_tt(M, 3, seed=0, b_scale=0.5)
    return dict(pred=pred, M=M,
                bg=rng.normal(size=(5, M)).astype(np.float32),
                X=rng.normal(size=(3, M)).astype(np.float32))


@pytest.fixture(scope="module")
def mid_tn():
    rng = np.random.default_rng(7)
    M = 8
    pred = _make_tt(M, 4, seed=1)
    return dict(pred=pred, M=M,
                bg=rng.normal(size=(16, M)).astype(np.float32),
                X=rng.normal(size=(5, M)).astype(np.float32))


# --------------------------------------------------------------------- #
# the DP contraction vs brute-force 2^M enumeration
# --------------------------------------------------------------------- #


def _brute_force_phi(pred, X, bg):
    """float64 Shapley values by enumerating ALL coalitions: the masked-EY
    value function v(S) = E_z f(x_S; z) evaluated through the HOST cores
    in float64, marginals weighted by s!(M-1-s)!/M! — a higher-precision
    oracle than the f32 DP under test."""

    M = X.shape[1]
    bg64 = np.asarray(bg, np.float64)

    def f64(rows):
        v = np.ones((rows.shape[0], 1))
        for i, (A, B) in enumerate(pred._host_cores):
            C = (A[None].astype(np.float64)
                 + rows[:, i][:, None, None] * B[None].astype(np.float64))
            v = np.einsum('br,brs->bs', v, C)
        return v                                           # (n, K)

    def value(S, x):
        comp = np.tile(x, (bg64.shape[0], 1)).astype(np.float64)
        keep = np.ones(M, bool)
        keep[list(S)] = False
        comp[:, keep] = bg64[:, keep]
        return f64(comp).mean(0)

    K = pred.n_outputs
    phi = np.zeros((X.shape[0], K, M))
    for bi, x in enumerate(X):
        for j in range(M):
            others = [i for i in range(M) if i != j]
            for s in range(M):
                w = factorial(s) * factorial(M - 1 - s) / factorial(M)
                for S in combinations(others, s):
                    phi[bi, :, j] += w * (value(set(S) | {j}, x)
                                          - value(S, x))
    return phi


def test_dp_matches_brute_force_enumeration(small_tn):
    """The size-indexed DP over all coalitions == the 2^M enumeration, to
    f32 rounding of the DP itself (the float64 oracle carries ~1e-16
    error; everything beyond ~1e-6 here would be a DP derivation bug,
    not float noise)."""

    s = small_tn
    engine = KernelExplainerEngine(s["pred"], s["bg"], link="identity",
                                   seed=0)
    phi = np.asarray(engine.get_explanation(s["X"], nsamples="exact"))
    assert engine.kernel_path.get("exact_phi") == "tn_dp"
    want = _brute_force_phi(s["pred"], s["X"], s["bg"])
    got = phi[0] if phi.ndim == 3 and want.shape[1] == 1 else phi
    np.testing.assert_allclose(np.squeeze(got), np.squeeze(want),
                               atol=1e-6)
    # additivity: phi sums to f(x) - E f(z) (the Shapley efficiency axiom)
    fx = np.asarray(s["pred"](s["X"]))
    efz = np.asarray(s["pred"](s["bg"])).mean(0)
    np.testing.assert_allclose(np.squeeze(got).sum(-1),
                               np.squeeze(fx - efz[None]), atol=1e-5)


def test_weight_table_exact_values():
    w = tns.shapley_size_weights(5)
    want = [factorial(s) * factorial(4 - s) / factorial(5) for s in range(5)]
    np.testing.assert_allclose(w, np.asarray(want, np.float32), rtol=0)
    Wt = tns.weight_toeplitz(4)
    assert Wt.shape == (4, 4)
    # Wt[a, b] = w_{a+b}, zero once a+b spills past M-1
    w4 = tns.shapley_size_weights(4)
    for a in range(4):
        for b in range(4):
            assert Wt[a, b] == (w4[a + b] if a + b < 4 else 0.0)


# --------------------------------------------------------------------- #
# rank-1 / linear lift == the linear fast path
# --------------------------------------------------------------------- #


def test_rank1_linear_lift_matches_linear_fast_path():
    """A linear model lifted to TT form serves the SAME phi as the linear
    fast path: both are exact, so they must agree to f32 rounding — and
    both must match the closed form W_j (x_j - E z_j)."""

    from distributedkernelshap_tpu.models.predictors import LinearPredictor

    rng = np.random.default_rng(11)
    D, K = 7, 2
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    bg = rng.normal(size=(9, D)).astype(np.float32)
    X = rng.normal(size=(4, D)).astype(np.float32)

    tt = TensorTrainPredictor.from_linear(W, b)
    # the lift reproduces the linear predictions exactly-to-rounding
    np.testing.assert_allclose(np.asarray(tt(X)), X @ W + b, atol=1e-5)

    closed = np.einsum('dk,bd->bkd', W, X - bg.mean(0, keepdims=True))

    eng_tt = KernelExplainerEngine(tt, bg, link="identity", seed=0)
    phi_tt = np.stack([np.asarray(v) for v in
                       eng_tt.get_explanation(X, nsamples="exact")], 1)
    np.testing.assert_allclose(phi_tt, closed, atol=2e-5)

    lin = LinearPredictor(W, b, activation="identity")
    eng_lin = KernelExplainerEngine(lin, bg, link="identity", seed=0)
    full = 2 ** D - 2
    phi_lin = np.stack([np.asarray(v) for v in
                        eng_lin.get_explanation(X, nsamples=full,
                                                l1_reg=False)], 1)
    np.testing.assert_allclose(phi_tt, phi_lin, atol=2e-5)

    # from_linear_predictor round-trips the fitted decomposition
    tt2 = TensorTrainPredictor.from_linear_predictor(lin)
    assert tt2.fingerprint_bytes() == tt.fingerprint_bytes()


def test_cp_lift_predictions_exact():
    rng = np.random.default_rng(13)
    M, R, K = 5, 3, 2
    a = rng.normal(size=(M, R)).astype(np.float32)
    b = rng.normal(scale=0.4, size=(M, R)).astype(np.float32)
    head = rng.normal(size=(R, K)).astype(np.float32)
    X = rng.normal(size=(6, M)).astype(np.float32)
    tt = TensorTrainPredictor.from_cp(a, b, head)
    want = np.einsum('rk,br->bk',
                     head.astype(np.float64),
                     np.prod(a.T[None].astype(np.float64)
                             + X[:, None, :] * b.T[None], axis=2))
    np.testing.assert_allclose(np.asarray(tt(X)), want, atol=1e-4)


def test_fit_tt_surrogate_recovers_tt_model(small_tn):
    """ALS on samples of an actual TT model recovers a near-zero-MSE
    surrogate (the A/B-constructor contract the accuracy bench leans on)."""

    s = small_tn
    rng = np.random.default_rng(17)
    Xfit = rng.normal(size=(200, s["M"])).astype(np.float32)
    sur = fit_tt_surrogate(lambda X: np.asarray(s["pred"](X)), Xfit,
                           rank=3, n_sweeps=3, seed=0)
    y = np.asarray(s["pred"](Xfit), np.float64)
    var = float(np.var(y))
    assert sur.fit_mse_ < 0.05 * var
    assert tns.supports_exact_tn(sur)


# --------------------------------------------------------------------- #
# full-enumeration parity: sampled estimator == exact paths end to end
# --------------------------------------------------------------------- #


def test_sampled_full_enumeration_matches_exact_tn(mid_tn):
    """``coalition_plan`` with ``total <= nsamples`` silently enumerates
    all coalitions — the WLS solve is then exact by construction, so the
    SAMPLED estimator must agree with exact-TN phi end to end (to the
    f32 rounding of two different exact formulations, far below any
    sampling error)."""

    from distributedkernelshap_tpu.ops.coalitions import coalition_plan

    s = mid_tn
    full = 2 ** s["M"] - 2
    plan = coalition_plan(s["M"], nsamples=full)
    assert plan.exact and plan.n_enumerated == full

    engine = KernelExplainerEngine(s["pred"], s["bg"], link="identity",
                                   seed=0)
    exact = np.asarray(engine.get_explanation(s["X"], nsamples="exact"))
    scale = float(np.abs(exact).max())
    for budget in (full, full + 100):   # at and past the space: both enumerate
        sampled = np.asarray(engine.get_explanation(s["X"], nsamples=budget,
                                                    l1_reg=False))
        np.testing.assert_allclose(sampled, exact,
                                   atol=max(1e-5, 2e-5 * scale))


def test_sampled_full_enumeration_matches_exact_tree():
    """Same parity pin for the tree family: full enumeration == exact
    interventional TreeSHAP."""

    sklearn = pytest.importorskip("sklearn")
    from sklearn.ensemble import HistGradientBoostingRegressor

    rng = np.random.default_rng(5)
    M = 6
    Xtr = rng.normal(size=(200, M))
    y = Xtr[:, 0] - np.where(Xtr[:, 2] > 0, 1.0, -1.0) * Xtr[:, 3]
    gbr = HistGradientBoostingRegressor(max_iter=8, random_state=0).fit(
        Xtr, y)
    bg = Xtr[:12].astype(np.float32)
    X = Xtr[100:105].astype(np.float32)

    engine = KernelExplainerEngine(gbr.predict, bg, link="identity", seed=0)
    exact = np.asarray(engine.get_explanation(X, nsamples="exact"))
    scale = float(np.abs(exact).max())
    sampled = np.asarray(engine.get_explanation(X, nsamples=2 ** M - 2,
                                                l1_reg=False))
    np.testing.assert_allclose(sampled, exact, atol=max(1e-5, 2e-5 * scale))


# --------------------------------------------------------------------- #
# mesh sharding: bit-identical to single-device
# --------------------------------------------------------------------- #


def test_sharded_matches_single_device_bit_identical(mid_tn):
    """Background rows sharded over the coalition axis, per-row phi
    all-gathered, the final weighted-row-sum einsum replayed replicated:
    the sharded run must be BIT-identical to the single-device one."""

    from distributedkernelshap_tpu.parallel.distributed import (
        DistributedExplainer,
    )

    s = mid_tn
    seq = KernelExplainerEngine(s["pred"], s["bg"], link="identity", seed=0)
    want = seq.get_explanation(s["X"], nsamples="exact")

    for cp in (2, 4):
        dist = DistributedExplainer(
            {"n_devices": 8, "coalition_parallel": cp,
             "algorithm": "kernel_shap"},
            KernelExplainerEngine, (s["pred"], s["bg"]),
            {"link": "identity", "seed": 0})
        got = dist.get_explanation(s["X"], nsamples="exact")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        np.testing.assert_allclose(
            np.asarray(dist.last_raw_prediction),
            np.asarray(seq.last_raw_prediction), atol=1e-6)
        # staging declines for sharded explainers (mesh padding differs
        # from the single-engine bucketing)
        assert dist.stage_rows(s["X"], nsamples="exact") is None


def test_sharded_pads_ragged_background(mid_tn):
    """A background size not divisible by the coalition-parallel degree
    pads with zero-WEIGHT rows — an exact +0.0 in the final einsum, so
    the answer stays bit-identical to single-device."""

    from distributedkernelshap_tpu.parallel.distributed import (
        DistributedExplainer,
    )

    s = mid_tn
    bg = s["bg"][:13]                   # 13 rows over cp=4: pad 3
    seq = KernelExplainerEngine(s["pred"], bg, link="identity", seed=0)
    want = seq.get_explanation(s["X"], nsamples="exact")
    dist = DistributedExplainer(
        {"n_devices": 8, "coalition_parallel": 4,
         "algorithm": "kernel_shap"},
        KernelExplainerEngine, (s["pred"], bg),
        {"link": "identity", "seed": 0})
    got = dist.get_explanation(s["X"], nsamples="exact")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# --------------------------------------------------------------------- #
# engine: staged async == sync, device cache rekey/reset/bound
# --------------------------------------------------------------------- #


def test_engine_staged_async_matches_sync(mid_tn):
    s = mid_tn
    engine = KernelExplainerEngine(s["pred"], s["bg"], link="identity",
                                   seed=0)
    want = engine.get_explanation(s["X"], nsamples="exact")
    staged = engine.stage_rows(s["X"], nsamples="exact")
    assert isinstance(staged, StagedRows)
    values, info = engine.get_explanation_async(staged, nsamples="exact")()
    np.testing.assert_array_equal(np.asarray(values), np.asarray(want))
    np.testing.assert_array_equal(info["raw_prediction"],
                                  np.asarray(engine.last_raw_prediction))
    # unstaged async (staging-off deployments) pads/buckets identically
    values2, _ = engine.get_explanation_async(s["X"], nsamples="exact")()
    np.testing.assert_array_equal(np.asarray(values2), np.asarray(want))
    # interactions have no TN closed form: sync raises, staging declines
    assert engine.stage_rows(s["X"], nsamples="exact",
                             interactions=True) is None
    with pytest.raises(ValueError, match="interactions"):
        engine.get_explanation(s["X"], nsamples="exact", interactions=True)


def test_device_cache_rekey_reset_and_bound(mid_tn):
    s = mid_tn
    engine = KernelExplainerEngine(s["pred"], s["bg"], link="identity",
                                   seed=0)
    c1 = engine._exact_tn_consts()
    assert engine._exact_tn_consts() is c1          # cache hit
    key = ('exact_tn_consts', engine.content_fingerprint())
    assert key in engine._plan_consts_cache

    # reset clears device state; the rebuild is a fresh dict
    engine.reset_device_state()
    assert key not in engine._plan_consts_cache
    assert engine._exact_tn_consts() is not c1

    # LRU bound: flooding the shared consts cache keeps it bounded (the
    # trim runs on insert, so drop the live key first to force one)
    for i in range(engine._DEV_CACHE_MAX_ENTRIES + 3):
        engine._plan_consts_cache[("dummy", i)] = None
    engine._plan_consts_cache.pop(key, None)
    engine._exact_tn_consts()
    assert (len(engine._plan_consts_cache)
            <= engine._DEV_CACHE_MAX_ENTRIES)

    # content rekey: equal core bytes ARE the same constants; any byte
    # change is a different fingerprint (no id()-aliasing staleness)
    clone = TensorTrainPredictor(
        [(A.copy(), B.copy()) for A, B in s["pred"]._host_cores])
    eng_clone = KernelExplainerEngine(clone, s["bg"], link="identity",
                                      seed=0)
    assert eng_clone.content_fingerprint() == engine.content_fingerprint()
    bent = [(A.copy(), B.copy()) for A, B in s["pred"]._host_cores]
    bent[0][0][0, 0] += 1.0
    eng_bent = KernelExplainerEngine(TensorTrainPredictor(bent), s["bg"],
                                     link="identity", seed=0)
    assert eng_bent.content_fingerprint() != engine.content_fingerprint()

    # plan_constant_cache=False bypasses the cache (recompute arm)
    eng_off = KernelExplainerEngine(
        s["pred"], s["bg"], link="identity", seed=0,
        config=EngineConfig(plan_constant_cache=False))
    eng_off._exact_tn_consts()
    assert not eng_off._plan_consts_cache
    got = eng_off.get_explanation(s["X"], nsamples="exact")
    want = engine.get_explanation(s["X"], nsamples="exact")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# --------------------------------------------------------------------- #
# readiness gates + fallback accounting
# --------------------------------------------------------------------- #


def test_readiness_gates_and_validation(mid_tn):
    s = mid_tn
    pred, M = s["pred"], s["M"]
    G = np.eye(M, dtype=np.float32)
    assert tns.tn_exact_ready(pred, "identity", G) is None
    assert tns.tn_exact_ready(object(), "identity", G) == "structure"
    assert tns.tn_exact_ready(pred, "logit", G) == "link"
    grouped = np.zeros((M, M - 1), np.float32)
    grouped[:M - 1] = np.eye(M - 1)
    grouped[-1, -1] = 1.0
    assert tns.tn_exact_ready(pred, "identity", grouped) == "grouping"
    big = _make_tt(3, tns.TN_MAX_RANK + 1, seed=2)
    assert tns.tn_exact_ready(big, "identity",
                              np.eye(3, dtype=np.float32)) == "rank"
    assert tns.tn_exact_ready(pred, "identity", G,
                              target_chunk_elems=256) == "footprint"
    with pytest.raises(ValueError, match="link='identity'"):
        tns.validate_exact_tn(pred, "logit", G)
    before = dict(tns.tn_fallback_counts())
    tns.record_tn_fallback("rank")
    after = tns.tn_fallback_counts()
    assert after[("rank",)] == before.get(("rank",), 0.0) + 1.0


# --------------------------------------------------------------------- #
# serving: auto-selection, opt-outs, payload parity, path metric, warmup
# --------------------------------------------------------------------- #


def test_serving_auto_selects_exact_tn(mid_tn):
    from distributedkernelshap_tpu.serving.wrappers import KernelShapModel

    s = mid_tn
    model = KernelShapModel(s["pred"], s["bg"], {"seed": 0}, {})
    assert model.explain_path == "exact_tn"
    assert model.explain_path_reason == "auto"
    assert model.explain_kwargs == {"nsamples": "exact"}
    # responses match a direct exact explain bit-for-bit
    payloads = model.explain_batch(s["X"][:4], split_sizes=[2, 2])
    direct = KernelShap(s["pred"], seed=0)
    direct.fit(s["bg"])
    want = np.asarray(direct.explain(s["X"][:4], silent=True,
                                     nsamples="exact").shap_values)
    want = want[0] if want.ndim == 3 else want
    got = np.asarray(json.loads(payloads[0])["data"]["shap_values"])
    np.testing.assert_array_equal(np.squeeze(got), want[:2])


def test_serving_auto_select_opt_outs(mid_tn, monkeypatch):
    from distributedkernelshap_tpu.serving.wrappers import KernelShapModel

    s = mid_tn
    pinned = KernelShapModel(s["pred"], s["bg"], {"seed": 0}, {},
                             explain_kwargs={"nsamples": 100})
    assert pinned.explain_path == "sampled"
    assert pinned.explain_path_reason == "pinned"
    opted = KernelShapModel(s["pred"], s["bg"], {"seed": 0}, {},
                            explain_kwargs={"nsamples": None})
    assert opted.explain_path == "sampled"
    monkeypatch.setenv("DKS_EXACT_AUTO", "0")
    off = KernelShapModel(s["pred"], s["bg"], {"seed": 0}, {})
    assert off.explain_path == "sampled"
    assert off.explain_path_reason == "auto_disabled"
    assert "nsamples" not in off.explain_kwargs
    monkeypatch.delenv("DKS_EXACT_AUTO")
    # a failed readiness gate keeps the sampled path AND counts a reason
    before = tns.tn_fallback_counts().get(("rank",), 0.0)
    big = _make_tt(3, tns.TN_MAX_RANK + 1, seed=2)
    bg3 = np.zeros((4, 3), np.float32)
    gated = KernelShapModel(big, bg3, {"seed": 0}, {})
    assert gated.explain_path == "sampled"
    assert gated.explain_path_reason == "default"
    assert tns.tn_fallback_counts()[("rank",)] == before + 1.0


def test_serving_staged_async_matches_sync_payloads(mid_tn):
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    s = mid_tn
    model = BatchKernelShapModel(s["pred"], s["bg"], {"seed": 0}, {})
    assert model.explain_path == "exact_tn"
    staged = model.stage_rows(s["X"][:4])
    assert isinstance(staged, StagedRows)
    sync = model.explain_batch(s["X"][:4], split_sizes=[2, 2])
    got = model.explain_batch_async(staged, split_sizes=[2, 2])()
    assert got == sync
    # binary wire slots work on the exact-TN path too
    staged2 = model.stage_rows(s["X"][:4])
    binary = model.explain_batch_async(
        staged2, split_sizes=[2, 2], formats=["binary", "json"])()
    assert isinstance(binary[0], (bytes, bytearray))
    assert binary[1] == sync[1]


def test_explain_path_metric_counts_exact_tn(mid_tn):
    from distributedkernelshap_tpu.serving import wrappers

    s = mid_tn
    model = wrappers.BatchKernelShapModel(s["pred"], s["bg"], {"seed": 0},
                                          {})
    before = wrappers.explain_path_counts().get(("exact_tn",), 0.0)
    model.explain_batch(s["X"][:4], split_sizes=[2, 2])
    after = wrappers.explain_path_counts()[("exact_tn",)]
    assert after == before + 2          # one per request slot, not per row


def test_warmup_ladder_covers_exact_tn_path(mid_tn):
    """A warmup-enabled server over an auto-exact_tn deployment compiles
    the TN entry per bucket (signatures carry the path), serves warm, and
    renders the path/fallback metrics."""

    from distributedkernelshap_tpu.runtime.compile_cache import (
        compile_events,
    )
    from distributedkernelshap_tpu.serving.server import ExplainerServer
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    s = mid_tn
    model = BatchKernelShapModel(s["pred"], s["bg"], {"seed": 0}, {})
    assert model.explain_path == "exact_tn"
    ce = compile_events()
    before = ce.snapshot()
    srv = ExplainerServer(model, host="127.0.0.1", port=0,
                          max_batch_size=4, warmup=True,
                          health_interval_s=0).start()
    try:
        deadline = time.monotonic() + 60
        while srv.warmup_status()["state"] in ("pending", "running"):
            assert time.monotonic() < deadline, "warmup never finished"
            time.sleep(0.05)
        st = srv.warmup_status()
        assert st["state"] == "done"
        assert st["completed_buckets"] == st["buckets"] != []
        delta = ce.delta(before, ce.snapshot())
        sigs = {sig for (_, sig) in delta["counts"]}
        assert any(sig.endswith(",path=exact_tn") for sig in sigs), sigs
        page = srv.metrics.render()
        assert 'dks_serve_explain_path_total{path="exact_tn"}' in page
        assert "dks_tensor_shap_fallback_total" in page
    finally:
        srv.stop()
