"""Tests for the profiling subsystem and explainer checkpointing."""

import numpy as np
import pytest

from distributedkernelshap_tpu import KernelShap
from distributedkernelshap_tpu.models import LinearPredictor
from distributedkernelshap_tpu.profiling import Profiler, profiler


@pytest.fixture()
def fitted(tmp_path):
    rng = np.random.default_rng(0)
    D = 7
    groups = [[0], [1, 2], [3, 4], [5, 6]]
    names = ["a", "b", "c", "d"]
    W = rng.normal(size=(D, 2)).astype(np.float32)
    bg = rng.normal(size=(10, D)).astype(np.float32)
    X = rng.normal(size=(4, D)).astype(np.float32)
    pred = LinearPredictor(W, np.zeros(2, np.float32), activation="softmax")
    ex = KernelShap(pred, link="logit", feature_names=names, seed=0)
    ex.fit(bg, group_names=names, groups=groups, data_provenance="synthetic")
    return ex, X, tmp_path


def test_profiler_phases():
    p = Profiler(enabled=True)
    with p.phase("solve"):
        pass
    with p.phase("solve"):
        pass
    with p.phase("eval", sync=True):
        pass
    s = p.summary()
    assert s["solve"]["count"] == 2 and "mean_s" in s["solve"]
    assert "eval" in s
    assert "solve" in p.report()
    p.reset()
    assert p.summary() == {}


def test_profiler_disabled_is_noop():
    p = Profiler(enabled=False)
    with p.phase("x"):
        pass
    assert p.summary() == {}


def test_default_profiler_collects_engine_phases(fitted):
    ex, X, _ = fitted
    prof = profiler()
    prof.enable()
    prof.reset()
    try:
        ex.explain(X, nsamples=32, silent=True)
        s = prof.summary()
        assert "explain" in s and "device_explain" in s and "coalition_plan" in s
    finally:
        prof.disable()
        prof.reset()


def test_save_load_roundtrip(fitted):
    ex, X, tmp_path = fitted
    before = ex.explain(X, nsamples=32, silent=True)
    path = str(tmp_path / "ckpt" / "explainer.pkl")
    ex.save(path)

    loaded = KernelShap.load(path)
    after = loaded.explain(X, nsamples=32, silent=True)
    np.testing.assert_allclose(before.shap_values[0], after.shap_values[0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(before.expected_value),
                               np.asarray(loaded.expected_value), atol=1e-6)
    assert loaded.feature_names == ex.feature_names
    # provenance survives the checkpoint round trip (meta is saved whole)
    assert loaded.meta["data_provenance"] == "synthetic"
    assert after.meta["data_provenance"] == "synthetic"


def test_save_load_preserves_engine_config(fitted, tmp_path):
    """`engine_config` must survive the checkpoint round trip — a serving
    replica restored from disk has to behave like the writer process."""

    from distributedkernelshap_tpu.kernel_shap import EngineConfig

    ex, X, _ = fitted
    cfg = EngineConfig(host_eval=True, host_eval_workers=3)
    ex2 = KernelShap(ex.predictor, link=ex.link, seed=0, engine_config=cfg)
    ex2.fit(np.asarray(ex.background_data.data))
    path = str(tmp_path / "cfg" / "explainer.pkl")
    ex2.save(path)

    loaded = KernelShap.load(path)
    assert loaded.engine_config == cfg
    assert loaded._explainer.config.host_eval is True
    assert loaded._explainer.config.host_eval_workers == 3


def test_save_unfitted_raises():
    ex = KernelShap(LinearPredictor(np.zeros((3, 2), np.float32),
                                    np.zeros(2, np.float32)))
    with pytest.raises(ValueError, match="unfitted"):
        ex.save("/tmp/nope.pkl")


def test_save_load_exact_interactions(tmp_path):
    """A restored explainer must run the exact path with interactions:
    the lazily-built fn caches rebuild after load, and the tensors match
    the writer process's."""

    from sklearn.ensemble import GradientBoostingRegressor

    rng = np.random.default_rng(11)
    X = rng.normal(size=(150, 4))
    y = X[:, 0] * np.where(X[:, 1] > 0, 1.0, -1.0)
    gbt = GradientBoostingRegressor(n_estimators=5, max_depth=3,
                                    random_state=0).fit(X, y)
    ex = KernelShap(gbt.predict, seed=0)
    ex.fit(X[:12].astype(np.float32))
    Xq = X[:6].astype(np.float32)
    before = ex.explain(Xq, silent=True, nsamples="exact", interactions=True)

    path = str(tmp_path / "exact" / "explainer.pkl")
    ex.save(path)
    loaded = KernelShap.load(path)
    after = loaded.explain(Xq, silent=True, nsamples="exact",
                           interactions=True)
    np.testing.assert_allclose(
        before.data["raw"]["interaction_values"][0],
        after.data["raw"]["interaction_values"][0], atol=1e-6)
    np.testing.assert_allclose(before.shap_values[0], after.shap_values[0],
                               atol=1e-6)
