"""Tests for the in-process time-series store and the registry sampler:
ring bounds, counter/gauge/histogram queries (rate, delta, avg_over,
windowed quantile with interpolation), reset handling, JSONL
export/replay, and sampler snapshots of a live registry."""

import threading

import pytest

from distributedkernelshap_tpu.observability.metrics import MetricsRegistry
from distributedkernelshap_tpu.observability.timeseries import (
    RegistrySampler,
    TimeSeriesStore,
    load_jsonl,
    sparkline,
)


def test_ring_is_bounded_per_series():
    store = TimeSeriesStore(capacity=10)
    for t in range(100):
        store.add("g", t, float(t))
    pts = store.points("g")
    assert len(pts) == 10
    assert pts[0] == (90.0, 90.0) and pts[-1] == (99.0, 99.0)
    assert store.samples_total == 100


def test_counter_rate_and_delta():
    store = TimeSeriesStore()
    for t in range(0, 11):
        store.add("c", t, 5.0 * t, kind="counter")
    assert store.delta("c", 10, now=10) == pytest.approx(50.0)
    assert store.rate("c", 10, now=10) == pytest.approx(5.0)
    # window restricts which samples count
    assert store.delta("c", 3, now=10) == pytest.approx(15.0)
    # counter reset: the negative step is dropped, not summed — the
    # 3s window [9,12] holds values 45,50,2,4, so the honest increase
    # is (50-45) + (4-2) = 7
    store.add("c", 11, 2.0, kind="counter")
    store.add("c", 12, 4.0, kind="counter")
    assert store.delta("c", 3, now=12) == pytest.approx(7.0)


def test_rate_needs_two_samples_and_distinct_times():
    store = TimeSeriesStore()
    assert store.rate("missing", 10, now=0) is None
    store.add("c", 5, 1.0, kind="counter")
    assert store.rate("c", 10, now=5) is None


def test_avg_over_and_frac_over_gauges():
    store = TimeSeriesStore()
    for t, v in enumerate([0.0, 10.0, 20.0, 30.0]):
        store.add("g", t, v)
    assert store.avg_over("g", 10, now=3) == pytest.approx(15.0)
    assert store.frac_over("g", 10, 15.0, now=3) == pytest.approx(0.5)
    assert store.avg_over("g", 0.5, now=100) is None  # empty window


def test_labels_isolate_series():
    store = TimeSeriesStore()
    store.add("q", 0, 1.0, labels={"class": "interactive"})
    store.add("q", 0, 9.0, labels={"class": "batch"})
    assert store.latest("q", {"class": "interactive"}) == 1.0
    assert store.latest("q", {"class": "batch"}) == 9.0
    assert store.latest("q") is None  # the unlabeled series was never fed
    assert sorted(d["class"] for d in store.labelsets("q")) == [
        "batch", "interactive"]


def test_histogram_window_quantile_interpolates():
    store = TimeSeriesStore()
    buckets = (0.1, 0.5, 1.0)
    # cumulative snapshots: 0 obs, then 100 in (0.1, 0.5] + 10 in +Inf
    store.add_histogram("h", 0, buckets, (0, 0, 0, 0), 0.0, 0)
    store.add_histogram("h", 10, buckets, (0, 100, 0, 10), 50.0, 110)
    # 55th of 110 lands in the (0.1, 0.5] bucket: linear interpolation
    assert store.quantile("h", 0.5, 60, now=10) == pytest.approx(0.32)
    # the +Inf tail answers with the highest finite bound
    assert store.quantile("h", 0.999, 60, now=10) == pytest.approx(1.0)
    assert store.frac_le("h", 0.5, 60, now=10) == pytest.approx(100 / 110)
    # threshold between bounds interpolates inside the bucket
    assert store.frac_le("h", 0.3, 60, now=10) == pytest.approx(
        (100 * 0.5) / 110)
    assert store.quantile("h", 0.5, 60, now=5) is None  # one snapshot


def test_histogram_reset_mid_window_returns_none():
    store = TimeSeriesStore()
    buckets = (1.0,)
    store.add_histogram("h", 0, buckets, (5, 0), 2.0, 5)
    store.add_histogram("h", 1, buckets, (2, 0), 1.0, 2)  # restart
    assert store.histogram_window("h", 10, now=1) is None


def test_jsonl_export_replay_round_trip(tmp_path):
    store = TimeSeriesStore()
    for t in range(5):
        store.add("c", t, 2.0 * t, kind="counter",
                  labels={"class": "batch"})
    store.add_histogram("h", 4, (0.5,), (3, 1), 1.5, 4)
    path = str(tmp_path / "series.jsonl")
    n = store.export_jsonl(path)
    assert n == 6
    replayed = load_jsonl(path)
    assert replayed.delta("c", 10, {"class": "batch"},
                          now=4) == pytest.approx(8.0)
    assert replayed.kind("h") == "histogram"
    # torn tail is skipped, not fatal
    with open(path, "a") as fh:
        fh.write('{"name": "c", "t"')
    assert load_jsonl(path).delta("c", 10, {"class": "batch"},
                                  now=4) == pytest.approx(8.0)


def test_sampler_snapshots_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "C.", labelnames=("reason",))
    g = reg.gauge("g", "G.")
    h = reg.histogram("h_seconds", "H.", buckets=(0.1, 1.0))
    store = TimeSeriesStore()
    sampler = RegistrySampler(store, [reg], interval_s=0)
    c.inc(0, reason="x")  # labeled series exist only once touched
    sampler.sample_once(now=0)
    c.inc(4, reason="x")
    g.set(7.0)
    h.observe(0.05)
    h.observe(5.0)
    sampler.sample_once(now=10)
    assert store.delta("c_total", 60, {"reason": "x"},
                       now=10) == pytest.approx(4.0)
    assert store.latest("g") == 7.0
    assert store.kind("c_total", {"reason": "x"}) == "counter"
    win = store.histogram_window("h_seconds", 60, now=10)
    assert win is not None and win[3] == 2
    assert sampler.samples_taken == 2


def test_sampler_thread_start_stop_and_on_tick():
    reg = MetricsRegistry()
    reg.gauge("g", "G.").set(1.0)
    store = TimeSeriesStore()
    ticks = []
    sampler = RegistrySampler(store, [reg], interval_s=0.02)
    sampler.start(on_tick=lambda: ticks.append(1))
    deadline = threading.Event()
    deadline.wait(0.2)
    sampler.stop()
    assert sampler.samples_taken >= 2
    assert len(ticks) >= 2
    taken = sampler.samples_taken
    deadline.wait(0.1)
    assert sampler.samples_taken == taken  # actually stopped
    # interval 0 never starts a thread
    s2 = RegistrySampler(store, [reg], interval_s=0)
    assert s2.start()._thread is None


def test_concurrent_writes_and_windowed_reads():
    """Scrape-time gauge callbacks and /statusz handlers query the store
    while the sampler thread appends; a read iterating the live deque
    mid-append would raise 'deque mutated during iteration'."""

    store = TimeSeriesStore(capacity=64)
    stop = threading.Event()
    errors = []

    def writer():
        t = 0
        while not stop.is_set():
            store.add("c", t, float(t), kind="counter")
            store.add_histogram("h", t, (0.5,), (t, 0), 0.1 * t, t)
            t += 1

    def reader():
        while not stop.is_set():
            try:
                store.delta("c", 1e9, now=1e9)
                store.rate("c", 1e9, now=1e9)
                store.avg_over("c", 1e9, now=1e9)
                store.histogram_window("h", 1e9, now=1e9)
                store.points("c")
                store.latest("c")
            except Exception as e:  # pragma: no cover - the regression
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=writer, daemon=True)] + \
        [threading.Thread(target=reader, daemon=True) for _ in range(3)]
    for t in threads:
        t.start()
    stop.wait(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert errors == []


def test_sparkline_shapes():
    assert sparkline([]) == ""
    assert sparkline([1.0, 1.0]) == "▁▁"
    line = sparkline([0, 1, 2, 3])
    assert len(line) == 4 and line[0] == "▁" and line[-1] == "█"
