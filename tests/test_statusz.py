"""Tests for the ``/statusz`` endpoint on both serving components:
stable JSON schema under load, firing-alert rendering on the human page,
cold-start rendering with an empty store, and the health engine's
registry back-channel (satellite task)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from distributedkernelshap_tpu.observability.alerts import AlertRule
from distributedkernelshap_tpu.observability.statusz import (
    HealthEngine,
    render_statusz_html,
)
from distributedkernelshap_tpu.observability.metrics import MetricsRegistry

#: the stable machine schema — adding a key is a conscious doc +
#: test update, never an accident (dashboards consume this)
TOP_LEVEL_KEYS = {"component", "generated_at", "uptime_s", "healthy",
                  "sampler", "slos", "alerts", "silences", "series",
                  "flightrec", "detail"}

SLO_KEYS = {"name", "kind", "target", "description", "windows",
            "burn_rates", "budget_remaining", "breached"}

ALERT_KEYS = {"rule", "state", "severity", "since_s", "transitions_total",
              "info"}


class FakeModel:
    def explain_batch(self, instances, split_sizes=None):
        sizes = split_sizes or [instances.shape[0]]
        out, k = [], 0
        for n in sizes:
            rows = instances[k:k + n]
            k += n
            out.append(json.dumps(
                {"data": {"sum": [float(r.sum()) for r in rows]}}))
        return out


@pytest.fixture()
def stack():
    from distributedkernelshap_tpu.serving.replicas import FanInProxy
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    server = ExplainerServer(FakeModel(), host="127.0.0.1", port=0,
                             max_batch_size=4, pipeline_depth=1,
                             cache_bytes=1 << 20,
                             health_interval_s=0.05).start()
    proxy = FanInProxy([("127.0.0.1", server.port)], host="127.0.0.1",
                       port=0, health_interval_s=0.05).start()
    try:
        yield server, proxy
    finally:
        proxy.stop()
        server.stop()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.headers.get("Content-Type", ""), r.read().decode()


def test_statusz_json_schema_under_load(stack):
    """Scrape both components' /statusz while real requests flow
    in-process; the JSON schema must be exactly the documented one."""

    from distributedkernelshap_tpu.serving.client import explain_request

    server, proxy = stack
    stop = threading.Event()

    def load():
        i = 0
        while not stop.is_set():
            explain_request(
                f"http://127.0.0.1:{proxy.port}/explain",
                np.full((1, 3), float(i % 7), dtype=np.float32),
                timeout=30)
            i += 1

    loader = threading.Thread(target=load, daemon=True)
    loader.start()
    try:
        time.sleep(0.3)  # let the samplers tick under traffic
        for port, component in ((server.port, "server"),
                                (proxy.port, "proxy")):
            ctype, body = _get(port, "/statusz?format=json")
            assert ctype.startswith("application/json")
            doc = json.loads(body)
            assert set(doc) == TOP_LEVEL_KEYS
            assert doc["component"] == component
            assert doc["healthy"] is True
            for slo in doc["slos"]:
                assert set(slo) == SLO_KEYS
            for alert in doc["alerts"]:
                assert set(alert) == ALERT_KEYS
                assert alert["state"] in ("inactive", "pending", "firing")
            assert doc["sampler"]["enabled"]
            assert doc["sampler"]["samples_taken"] > 0
            assert doc["series"], "sparkline series missing under load"
    finally:
        stop.set()
        loader.join(timeout=10)
    # component-specific detail blocks
    _, body = _get(server.port, "/statusz?format=json")
    detail = json.loads(body)["detail"]
    assert {"wedged", "queue_depths", "cache",
            "in_flight_batches"} <= set(detail)
    _, body = _get(proxy.port, "/statusz?format=json")
    detail = json.loads(body)["detail"]
    assert detail["live_replicas"] == 1
    assert detail["replicas"][0]["alive"] is True
    assert detail["supervisor"] is None  # no ReplicaManager here


def test_statusz_html_renders_under_load(stack):
    server, proxy = stack
    for port in (server.port, proxy.port):
        ctype, page = _get(port, "/statusz")
        assert ctype.startswith("text/html")
        assert "/statusz" in page and "SLOs" in page and "Alerts" in page


def test_statusz_cold_start_renders_empty_store():
    """A server whose sampler never ticked (health_interval_s=0, no
    traffic) must still serve both /statusz forms (satellite: cold
    start)."""

    from distributedkernelshap_tpu.serving.server import ExplainerServer

    server = ExplainerServer(FakeModel(), host="127.0.0.1", port=0,
                             health_interval_s=0).start()
    try:
        ctype, body = _get(server.port, "/statusz?format=json")
        doc = json.loads(body)
        assert set(doc) == TOP_LEVEL_KEYS
        assert doc["sampler"]["enabled"] is False
        assert doc["sampler"]["samples_taken"] == 0
        assert doc["series"] == {}
        assert doc["healthy"] is True  # silence is not an outage
        _, page = _get(server.port, "/statusz")
        assert "no samples yet" in page
    finally:
        server.stop()


def test_statusz_renders_firing_alert():
    """A firing rule must show on the JSON payload, the human page and
    the healthy flag (satellite: firing-alert rendering)."""

    from distributedkernelshap_tpu.serving.server import ExplainerServer

    always = AlertRule("always_on", lambda store, now: True, for_s=0,
                       severity="page")
    server = ExplainerServer(FakeModel(), host="127.0.0.1", port=0,
                             health_interval_s=0.05, slos=[],
                             alert_rules=[always]).start()
    try:
        deadline = time.monotonic() + 5
        while True:
            _, body = _get(server.port, "/statusz?format=json")
            doc = json.loads(body)
            if doc["alerts"] and doc["alerts"][0]["state"] == "firing":
                break
            assert time.monotonic() < deadline, doc["alerts"]
            time.sleep(0.05)
        assert doc["healthy"] is False
        _, page = _get(server.port, "/statusz")
        assert "always_on" in page and "firing" in page
        assert "UNHEALTHY" in page
        # and the registry back-channel agrees
        _, metrics = _get(server.port, "/metrics")
        assert 'dks_alerts_firing{rule="always_on"} 1' in metrics
    finally:
        server.stop()


def test_deterministic_tick_evaluates_gauges_at_logical_time():
    """A replayed tick(now=...) must evaluate the dks_slo_* gauge
    callbacks at the LOGICAL timestamp, not wall time — otherwise a
    replay over logically-stamped samples records full-budget gauges
    during the very burn it is replaying."""

    from distributedkernelshap_tpu.observability.slo import (
        AvailabilitySLO,
        BurnRateWindow,
    )

    reg = MetricsRegistry()
    total = reg.counter("dks_serve_requests_total", "R.")
    bad = reg.counter("dks_serve_errors_total", "E.")
    slo = AvailabilitySLO("avail", total="dks_serve_requests_total",
                          bad="dks_serve_errors_total", target=0.9,
                          windows=(BurnRateWindow(20, 5, 2.0),))
    engine = HealthEngine(reg, component="unit", interval_s=0, slos=[slo])
    for t in range(0, 31):
        total.inc(10)
        bad.inc(10)  # 100% errors: the budget is deeply overspent
        engine.tick(now=float(t))
    recorded = engine.store.latest("dks_slo_budget_remaining",
                                   {"slo": "avail"})
    assert recorded is not None and recorded < 0
    # outside a tick, callbacks fall back to wall time (live scrapes)
    assert engine._eval_now is None


def test_health_engine_standalone_tick_and_payload():
    """The engine works without a serving component: explicit ticks move
    the store, and the payload builds from any registry."""

    reg = MetricsRegistry()
    c = reg.counter("dks_serve_requests_total", "R.")
    engine = HealthEngine(reg, component="unit", interval_s=0,
                          spark_names=("dks_serve_requests_total",))
    engine.tick(now=0.0)
    c.inc(5)
    engine.tick(now=1.0)
    payload = engine.statusz_payload(detail={"k": "v"})
    assert payload["detail"] == {"k": "v"}
    series = payload["series"]["dks_serve_requests_total"]
    assert series["kind"] == "rate"
    assert series["latest"] == pytest.approx(5.0)
    assert series["sparkline"]
    html = render_statusz_html(payload)
    assert "unit /statusz" in html
