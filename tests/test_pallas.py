"""Pallas fused-kernel numerics (interpret mode on CPU).

The fused kernel must agree with the straightforward dense formula — the
masked-evaluation contract of shap 0.35's synthetic-data loop (SURVEY.md
§2.2) — for every activation and for non-aligned, multi-block shapes.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from distributedkernelshap_tpu.ops.pallas_kernels import fused_linear_ey


def _dense_reference(X, bg, W, b, G, mask, bgw, activation):
    zc = mask @ G
    masked = (X[:, None, None, :] * zc[None, :, None, :]
              + bg[None, None] * (1.0 - zc[None, :, None, :]))
    logits = masked @ W + b
    if activation == "softmax":
        e = np.exp(logits - logits.max(-1, keepdims=True))
        out = e / e.sum(-1, keepdims=True)
    elif activation == "sigmoid":
        out = 1.0 / (1.0 + np.exp(-logits))
    else:
        out = logits
    return np.einsum("bsnk,n->bsk", out, bgw)


def _problem(B, S, N, M, K, seed=0):
    rng = np.random.default_rng(seed)
    D = 2 * M
    X = rng.normal(size=(B, D)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    G = np.zeros((M, D), np.float32)
    for m in range(M):
        G[m, 2 * m:2 * m + 2] = 1.0
    mask = (rng.random(size=(S, M)) < 0.5).astype(np.float32)
    bgw = rng.random(N).astype(np.float32)
    bgw /= bgw.sum()
    GW = G[:, :, None] * W[None]
    XWg = np.einsum("bd,mdk->bmk", X, GW)
    bgWg = np.einsum("nd,mdk->nmk", bg, GW)
    bgW = bg @ W + b
    return X, bg, W, b, G, mask, bgw, XWg, bgWg, bgW


@pytest.mark.parametrize("K,activation", [(2, "softmax"), (3, "softmax"),
                                          (1, "sigmoid"), (2, "sigmoid")])
def test_fused_linear_ey_matches_dense(K, activation):
    B, S, N, M = 12, 150, 9, 6
    X, bg, W, b, G, mask, bgw, XWg, bgWg, bgW = _problem(B, S, N, M, K)
    ref = _dense_reference(X, bg, W, b, G, mask, bgw, activation)
    got = np.asarray(fused_linear_ey(
        jnp.asarray(XWg), jnp.asarray(bgWg), jnp.asarray(bgW),
        jnp.asarray(bgw), jnp.asarray(mask), activation, interpret=True))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_fused_linear_ey_multiblock_edges():
    """Non-aligned B and S exercise edge blocks of the (tb, ts) grid."""

    B, S, N, M, K = 33, 700, 9, 7, 2
    X, bg, W, b, G, mask, bgw, XWg, bgWg, bgW = _problem(B, S, N, M, K, seed=1)
    ref = _dense_reference(X, bg, W, b, G, mask, bgw, "softmax")
    got = np.asarray(fused_linear_ey(
        jnp.asarray(XWg), jnp.asarray(bgWg), jnp.asarray(bgW),
        jnp.asarray(bgw), jnp.asarray(mask), "softmax",
        tb=16, ts=256, interpret=True))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_fused_linear_ey_many_classes_covertype_shape():
    """K=7 at Covertype-like dims: the auto-picked tiles must fit the scoped
    VMEM budget (Mosaic rejected the default 256x512 tiles at 20.5 MB) and
    the numbers must still match."""

    from distributedkernelshap_tpu.ops.pallas_kernels import (
        _TB, _TS, _VMEM_BUDGET, _tile_sizes)

    B, S, N, M, K = 40, 300, 20, 12, 7
    tb, ts = _tile_sizes(B, S, N, M, K, _TB, _TS)
    # round-3 footprint model: (4K+4) live tile sets (recompute-based
    # multi-pass softmax) + the dT2 scratch
    assert (4 * K + 4) * tb * ts * 4 + 2 * K * N * ts * 4 <= _VMEM_BUDGET
    assert tb >= 8 and ts >= 128

    X, bg, W, b, G, mask, bgw, XWg, bgWg, bgW = _problem(B, S, N, M, K, seed=3)
    ref = _dense_reference(X, bg, W, b, G, mask, bgw, "softmax")
    got = np.asarray(fused_linear_ey(
        jnp.asarray(XWg), jnp.asarray(bgWg), jnp.asarray(bgW),
        jnp.asarray(bgw), jnp.asarray(mask), "softmax", interpret=True))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_tile_sizes_respect_hardware_floors_under_pressure():
    """Even when no tile size fits the budget (huge N·K scratch), the
    halving must stop at the 8-sublane / 128-lane floors rather than
    emitting shapes Mosaic rejects."""

    from distributedkernelshap_tpu.ops.pallas_kernels import _TB, _TS, _tile_sizes

    # B=40 starts tb at 40 (not a power of two): 40 -> 20 -> 10 -> floor 8
    tb, ts = _tile_sizes(B=40, S=4096, N=1000, M=54, K=7, tb=_TB, ts=_TS)
    assert tb >= 8 and ts >= 128


def test_tile_sizes_defaults_unchanged_for_small_k():
    """The headline Adult config (K=2) must keep the full-size tiles —
    shrinking them there would regress the benchmark for no reason."""

    from distributedkernelshap_tpu.ops.pallas_kernels import _TB, _TS, _tile_sizes

    assert _tile_sizes(B=2560, S=2072, N=100, M=12, K=2, tb=_TB, ts=_TS) == (_TB, _TS)


def test_tile_search_is_tb_major():
    """Under VMEM pressure the search must sacrifice ts before tb: the
    dominant re-staging cost (per-tile-row dT2 rebuild) scales with B/tb
    only, so (256, 256) beats the round-2 shrink order's (64, 512) at
    equal VMEM (Covertype K=7 sat at 13% of its roofline partly on this)."""

    from distributedkernelshap_tpu.ops.pallas_kernels import _TB, _TS, _tile_sizes

    tb, ts = _tile_sizes(B=65536, S=2072, N=100, M=12, K=7, tb=_TB, ts=_TS)
    assert tb == _TB          # full-size batch tile kept
    assert ts < _TS           # the lane tile absorbed the shrink
    # the stress shape (bg=1000 scratch pressure) must also keep tb large
    tb2, _ = _tile_sizes(B=512, S=2048, N=1000, M=12, K=2, tb=_TB, ts=_TS)
    assert tb2 == _TB


def test_ey_linear_pallas_vs_xla_path():
    """`_ey_linear(use_pallas=True)` must equal the chunked XLA fallback."""

    from distributedkernelshap_tpu.ops.explain import _ey_linear

    B, S, N, M, K = 10, 90, 8, 5, 2
    X, bg, W, b, G, mask, bgw, *_ = _problem(B, S, N, M, K, seed=2)
    args = (jnp.asarray(W), jnp.asarray(b), "softmax", jnp.asarray(X),
            jnp.asarray(bg), jnp.asarray(bgw), jnp.asarray(mask),
            jnp.asarray(G), 17)
    xla = np.asarray(_ey_linear(*args, use_pallas=False))
    pallas = np.asarray(_ey_linear(*args, use_pallas=True))
    np.testing.assert_allclose(pallas, xla, atol=1e-5)


@pytest.mark.parametrize("K", [2, 3])
def test_ey_linear_xla_fallback_matches_dense(K):
    """The XLA fallback path (binary sigmoid-of-difference shortcut at K=2,
    general softmax otherwise) must equal the dense synthetic-row formula."""

    from distributedkernelshap_tpu.ops.explain import _ey_linear

    B, S, N, M = 11, 77, 7, 5
    X, bg, W, b, G, mask, bgw, *_ = _problem(B, S, N, M, K, seed=4)
    ref = _dense_reference(X, bg, W, b, G, mask, bgw, "softmax")
    got = np.asarray(_ey_linear(
        jnp.asarray(W), jnp.asarray(b), "softmax", jnp.asarray(X),
        jnp.asarray(bg), jnp.asarray(bgw), jnp.asarray(mask),
        jnp.asarray(G), 13, use_pallas=False))
    np.testing.assert_allclose(got, ref, atol=1e-5)
