"""Tests for the benchmark analysis/reporting layer (``benchmarks/analysis.py``),
the script equivalent of the reference's ``Analysis.ipynb`` helpers
(`read_runtimes`/`compare_timing`, cells 2 and 25-54)."""

import os
import pickle
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.analysis import compare_timing, plot_rows, read_runtimes
from distributedkernelshap_tpu.utils import get_filename


def _write(path, times):
    with open(path, "wb") as f:
        pickle.dump({"t_elapsed": times}, f)


@pytest.fixture()
def results_dir(tmp_path):
    d = tmp_path / "results"
    d.mkdir()
    _write(d / "ray_workers_1_bsize_10_actorfr_1.0.pkl", [10.0, 12.0])
    _write(d / "ray_workers_8_bsize_10_actorfr_1.0.pkl", [2.0, 2.0, 2.0])
    _write(d / "ray_workers_8_bsize_None_actorfr_1.0.pkl", [1.5])
    _write(d / "ray_replicas_4_maxbatch_5_actorfr_1.0.pkl", [7.0])
    _write(d / "ray_replicas_4_maxbatch_5_actorfr_1.0_mode_default.pkl", [8.0])
    (d / "not_a_result.pkl").write_bytes(b"junk")
    return str(d)


def test_read_runtimes_pool(results_dir):
    rt = read_runtimes(results_dir)
    assert rt[(1, "10")] == [10.0, 12.0]
    assert rt[(8, "10")] == [2.0, 2.0, 2.0]
    assert rt[(8, "None")] == [1.5]
    # serve pickles and junk are excluded from the pool view
    assert all(k[0] in (1, 8) for k in rt)


def test_read_runtimes_serve_and_mode_suffix(results_dir):
    rt = read_runtimes(results_dir, serve=True)
    assert rt[(4, "5")] == [7.0]
    assert rt[(4, "5/default")] == [8.0]


def test_compare_timing_aggregates_and_sorts(results_dir):
    rows = compare_timing(read_runtimes(results_dir))
    assert [r["workers"] for r in rows] == [1, 8, 8]
    one = rows[0]
    assert one["mean_s"] == pytest.approx(11.0)
    assert one["std_s"] == pytest.approx(np.std([10.0, 12.0]))
    assert one["n_runs"] == 2
    assert one["vs_ray_pool_best"] == pytest.approx(125.05 / 11.0)
    # numeric batches sort before non-numeric ('None') at equal workers
    assert [r["batch"] for r in rows[1:]] == ["10", "None"]


def test_filename_convention_roundtrip(tmp_path):
    """`utils.get_filename` output must parse back through `read_runtimes`
    for both the pool and serve conventions (reference `utils.py:67-86`)."""

    d = tmp_path / "results"
    d.mkdir()
    pool_name = get_filename(workers=3, batch_size=7, serve=False)
    serve_name = get_filename(workers=2, batch_size=1, serve=True)
    _write(tmp_path / pool_name, [1.0])
    _write(tmp_path / serve_name, [2.0])
    assert read_runtimes(str(d))[(3, "7")] == [1.0]
    assert read_runtimes(str(d), serve=True)[(2, "1")] == [2.0]


def test_plot_rows_writes_png(results_dir, tmp_path):
    rows = compare_timing(read_runtimes(results_dir))
    out = str(tmp_path / "plot.png")
    plot_rows(rows, out, baseline=125.05)
    assert os.path.getsize(out) > 1000


def test_roofline_model_rows():
    """The analytic roofline emits one sane row per config: positive work
    terms, floors consistent with the stated peaks, and the documented
    boundedness readings (adult latency-bound with a ~1 ms floor; the
    masked tree path VPU-bound; exact transcendental- or MXU-bound)."""

    import json
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks", "roofline.py")
    out = subprocess.run(
        [sys.executable, script, "--json"],
        capture_output=True, text=True, check=True).stdout
    rows = {r["config"]: r
            for r in (json.loads(line) for line in out.splitlines() if line)}
    assert {"adult", "adult_stress", "covertype_full", "adult_trees",
            "adult_trees_exact", "adult_trees_exact_inter"} <= set(rows)
    for r in rows.values():
        for key in ("mxu_flops", "vpu_ops", "transcendentals", "hbm_bytes"):
            assert r[key] > 0, (r["config"], key)
        assert r["roofline_floor_s"] == max(
            r["mxu_s"], r["vpu_s"], r["transcendental_s"], r["hbm_s"])
    assert rows["adult"]["roofline_floor_s"] < 2e-3          # latency-bound
    assert rows["adult_trees"]["bound"] == "vpu_s"
    assert rows["adult_trees_exact"]["bound"] == "transcendental_s"
    # interactions cost ~M x the exact pass's contraction stage
    assert (rows["adult_trees_exact_inter"]["mxu_flops"]
            > 5 * rows["adult_trees_exact"]["mxu_flops"])


def test_summarise_jsonl_latest_success_wins(tmp_path):
    """Per step: the latest row wins, except a failed re-run never shadows
    an earlier success (the wedge-interrupted model_zoo case)."""

    import json

    from benchmarks.analysis import summarise_jsonl

    p = tmp_path / "sweep.jsonl"
    rows = [
        {"step": "backend", "ok": True, "result": {}},
        {"step": "config:adult", "ok": True, "result": {"value": 0.15}},
        {"step": "config:adult", "ok": True, "result": {"value": 0.09}},
        {"step": "config:model_zoo", "ok": True, "result": {"value": 0.7}},
        {"step": "config:model_zoo", "ok": False, "error": "wedge"},
        {"step": "done", "ok": True},
    ]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    latest = dict(summarise_jsonl(str(p)))
    assert latest["config:adult"]["result"]["value"] == 0.09
    assert latest["config:model_zoo"]["ok"] is True  # failure didn't shadow
    assert "done" not in latest
