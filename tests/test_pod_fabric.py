"""Tier-1 pod-fabric protocol tests (serving/multihost.py) over an
in-process fake transport — no gloo mesh, no subprocesses, no jax
collectives.  The gloo end-to-end coverage stays in test_multihost.py
(slow) and benchmarks/pod_serve_bench.py; these tests pin the WIRE
CONTRACT: header/payload framing under the broadcast lock, bucket
selection, over-slot rejection, shutdown idempotence + the
post-shutdown dispatch ordering (a popped batch must error, never
hang), follower catch-and-continue, and the warmup-rung and drain
commands."""

import queue
import threading

import numpy as np
import pytest

from distributedkernelshap_tpu.serving import multihost
from distributedkernelshap_tpu.serving.multihost import (
    _CMD_EXPLAIN,
    _CMD_SHUTDOWN,
    _CMD_WARMUP,
    _HEADER_LEN,
    KVStoreTransport,
    MultihostServingModel,
    PipelinedMultihostServingModel,
    _chunk_elems,
    _payload_chunks,
    broadcast_buckets,
    follower_loop,
    pod_bcast_byte_counts,
    pod_bcast_seconds_total,
)

N_FEATURES = 4
#: the wire's fixed MTU for this feature width — every op on the fake
#: wire must be exactly this shape (shape-uniform ops are the transport
#: correctness contract, see multihost._chunk_elems)
CHUNK = _chunk_elems(N_FEATURES)


# -- fakes -------------------------------------------------------------- #


class _FakeWire:
    """Shared broadcast medium: the lead appends frames, each follower
    pops them in order — the collective's source-to-all semantics
    without any collective."""

    def __init__(self, n_followers: int = 1):
        self.queues = [queue.Queue() for _ in range(n_followers)]
        self.sent = []  # every frame the lead broadcast, in order


class _LeadTransport:
    is_lead = True
    process_index = 0

    def __init__(self, wire: _FakeWire):
        self.wire = wire
        self.process_count = len(wire.queues) + 1

    def broadcast(self, value, is_source):
        assert is_source, "lead must broadcast as source"
        arr = np.array(value, copy=True)
        self.wire.sent.append(arr)
        for q in self.wire.queues:
            q.put(arr)
        return arr


class _FollowerTransport:
    is_lead = False

    def __init__(self, wire: _FakeWire, rank: int = 1):
        self._q = wire.queues[rank - 1]
        self.process_index = rank
        self.process_count = len(wire.queues) + 1

    def broadcast(self, value, is_source):
        assert not is_source, "follower must broadcast as receiver"
        got = self._q.get(timeout=10)
        # the framing contract the whole protocol rests on: the receive
        # buffer the follower allocated from the previous header must
        # match the frame the lead actually sent — any desync in
        # header/payload pairing or bucket sizing fails loudly here
        assert got.shape == np.shape(value), \
            f"framing desync: lead sent {got.shape}, " \
            f"follower expected {np.shape(value)}"
        assert got.dtype == np.asarray(value).dtype
        return got


class _KVLeadTransport(_LeadTransport):
    """A host-side wire fake: frames carried as-is, no MTU chunking."""

    needs_uniform_ops = False


class _KVFollowerTransport(_FollowerTransport):
    needs_uniform_ops = False


class _FakeInner:
    """The DistributedExplainer stand-in behind model.explainer."""

    def __init__(self, replicate=False):
        self.background = np.zeros((8, N_FEATURES), np.float32)
        self.replicate_results = replicate
        self.async_calls = []

    def get_explanation_async(self, X, **kw):
        self.async_calls.append(np.array(X, copy=True))
        return lambda: None


class _FakeExplainer:
    def __init__(self, inner, fail=None):
        self._explainer = inner
        self.calls = []
        self._fail = fail  # callable(X) -> bool, raise on match

    def explain(self, X, silent=True, **kw):
        X = np.asarray(X)
        if self._fail is not None and self._fail(X):
            raise RuntimeError("injected explain failure")
        self.calls.append(np.array(X, copy=True))
        return "explanation"


class _FakeModel:
    """KernelShapModel-shaped serving model the pod wrapper wraps."""

    supports_wire_formats = True

    def __init__(self, replicate=False, fail=None):
        self.explainer = _FakeExplainer(_FakeInner(replicate), fail=fail)
        self.explain_kwargs = {"nsamples": 8}
        self.batch_calls = []

    def explain_batch(self, stacked, split_sizes=None, formats=None):
        self.batch_calls.append((np.array(stacked, copy=True),
                                 split_sizes, formats))
        return ["ok"] * (len(split_sizes) if split_sizes else 1)

    def explain_batch_async(self, stacked, split_sizes=None, formats=None):
        arr = np.array(stacked, copy=True)

        def finalize():
            self.batch_calls.append((arr, split_sizes, formats))
            return ["ok"]

        return finalize


def _lead(model=None, wire=None, max_rows=8, buckets=(1, 2, 4, 8),
          cls=MultihostServingModel):
    wire = wire or _FakeWire()
    model = model or _FakeModel(replicate=cls
                                is PipelinedMultihostServingModel)
    pod = cls(model, max_rows=max_rows, buckets=list(buckets),
              transport=_LeadTransport(wire))
    return pod, model, wire


# -- lead-side framing -------------------------------------------------- #


def test_bucket_selection_smallest_fitting_rung():
    pod, _, _ = _lead()
    assert [pod._bucket_for(r) for r in (1, 2, 3, 4, 5, 8)] \
        == [1, 2, 4, 4, 8, 8]


def test_frame_is_shape_uniform_chunks_padded_to_bucket():
    pod, model, wire = _lead()
    stacked = np.arange(3 * N_FEATURES, dtype=np.float32).reshape(3, -1)
    pod.explain_batch(stacked, split_sizes=[2, 1])
    # every op on the wire is ONE MTU shape: header chunk + payload
    # chunks covering the BUCKET (4), not the slot (8)
    n_chunks = _payload_chunks(4, N_FEATURES)
    assert len(wire.sent) == 1 + n_chunks
    for op in wire.sent:
        assert op.shape == (CHUNK,) and op.dtype == np.float32
    header = wire.sent[0]
    assert list(header[:_HEADER_LEN]) == [_CMD_EXPLAIN, 3, 4]
    np.testing.assert_array_equal(header[_HEADER_LEN:], 0)
    body = np.concatenate(wire.sent[1:])[:4 * N_FEATURES]
    payload = body.reshape(4, N_FEATURES)
    np.testing.assert_array_equal(payload[:3], stacked)
    np.testing.assert_array_equal(payload[3:], 0)
    # the lead's own explain sees the unpadded batch
    (got, split, formats), = model.batch_calls
    np.testing.assert_array_equal(got, stacked)
    assert split == [2, 1] and formats is None


def test_formats_passthrough_and_capability():
    pod, model, _ = _lead()
    assert pod.supports_wire_formats is True
    pod.explain_batch(np.ones((1, N_FEATURES), np.float32),
                      split_sizes=[1], formats=["binary"])
    assert model.batch_calls[-1][2] == ["binary"]


def test_over_slot_batch_rejected_before_any_broadcast():
    pod, _, wire = _lead(max_rows=8)
    with pytest.raises(ValueError, match="broadcast slot"):
        pod.explain_batch(np.zeros((9, N_FEATURES), np.float32))
    assert wire.sent == []  # nothing hit the wire — followers stay paired


def test_buckets_must_end_at_max_rows():
    with pytest.raises(ValueError, match="end at max_rows"):
        _lead(max_rows=8, buckets=(1, 2, 4))


def test_lead_only_construction():
    with pytest.raises(RuntimeError, match="lead process"):
        MultihostServingModel(_FakeModel(), max_rows=8, buckets=[8],
                              transport=_FollowerTransport(_FakeWire()))


def test_pipelined_requires_replicated_results():
    with pytest.raises(ValueError, match="replicate_results"):
        PipelinedMultihostServingModel(
            _FakeModel(replicate=False), max_rows=8, buckets=[8],
            transport=_LeadTransport(_FakeWire()))


# -- shutdown ordering -------------------------------------------------- #


def test_shutdown_idempotent_single_frame():
    pod, _, wire = _lead()
    pod.shutdown_followers()
    pod.shutdown_followers()
    assert len(wire.sent) == 1  # header-only frame: bucket 0 -> no payload
    assert wire.sent[0].shape == (CHUNK,)
    assert list(wire.sent[0][:_HEADER_LEN]) == [_CMD_SHUTDOWN, 0, 0]


def test_post_shutdown_dispatch_errors_never_hangs():
    """The shutdown-vs-in-flight ordering pin: a batch the dispatcher
    popped before stop() but dispatched after the shutdown broadcast
    must fail as a per-request error (the server answers 500) — a
    broadcast into a peerless mesh would hang forever."""

    pod, _, wire = _lead(cls=PipelinedMultihostServingModel)
    pod.shutdown_followers()
    n_frames = len(wire.sent)
    with pytest.raises(RuntimeError, match="shut down"):
        pod.explain_batch(np.zeros((1, N_FEATURES), np.float32))
    with pytest.raises(RuntimeError, match="shut down"):
        pod.explain_batch_async(np.zeros((1, N_FEATURES), np.float32))
    with pytest.raises(RuntimeError, match="shut down"):
        pod.warmup_batch(np.zeros((1, N_FEATURES), np.float32))
    assert len(wire.sent) == n_frames  # nothing broadcast after shutdown


# -- drain -------------------------------------------------------------- #


def test_drain_waits_for_pipelined_finalizes():
    pod, _, _ = _lead(cls=PipelinedMultihostServingModel)
    fin = pod.explain_batch_async(np.zeros((2, N_FEATURES), np.float32),
                                  split_sizes=[2])
    assert pod.drain(timeout_s=0.05) is False  # finalize outstanding
    done = threading.Event()

    def _drainer():
        assert pod.drain(timeout_s=10) is True
        done.set()

    t = threading.Thread(target=_drainer, daemon=True)
    t.start()
    assert fin() == ["ok"]
    t.join(timeout=10)
    assert done.is_set()


def test_drain_and_shutdown_flushes_then_broadcasts():
    pod, _, wire = _lead(cls=PipelinedMultihostServingModel)
    fin = pod.explain_batch_async(np.zeros((1, N_FEATURES), np.float32))
    fin()
    assert pod.drain_and_shutdown(server=None, grace_s=5) is True
    assert list(wire.sent[-1][:_HEADER_LEN]) == [_CMD_SHUTDOWN, 0, 0]
    # grace expiry still broadcasts shutdown (liveness probe is the
    # backstop for a truly wedged collective) but reports unclean
    pod2, _, wire2 = _lead(cls=PipelinedMultihostServingModel)
    pod2.explain_batch_async(np.zeros((1, N_FEATURES), np.float32))
    assert pod2.drain_and_shutdown(server=None, grace_s=0.05) is False
    assert list(wire2.sent[-1][:_HEADER_LEN]) == [_CMD_SHUTDOWN, 0, 0]


# -- follower loop ------------------------------------------------------ #


def _run_follower(model, wire, rank=1, max_rows=8):
    t = threading.Thread(
        target=follower_loop, args=(model,),
        kwargs={"max_rows": max_rows,
                "transport": _FollowerTransport(wire, rank=rank)},
        daemon=True)
    t.start()
    return t


def test_follower_mirrors_lead_end_to_end():
    wire = _FakeWire()
    pod, lead_model, _ = _lead(wire=wire)
    follower_model = _FakeModel()
    t = _run_follower(follower_model, wire)
    pod.warmup_batch(np.zeros((2, N_FEATURES), np.float32))
    b1 = np.full((1, N_FEATURES), 7.0, np.float32)
    b2 = np.full((3, N_FEATURES), 9.0, np.float32)
    pod.explain_batch(b1, split_sizes=[1])
    pod.explain_batch(b2, split_sizes=[3])
    pod.shutdown_followers()
    t.join(timeout=10)
    assert not t.is_alive()
    # the follower entered the identical unpadded batches, in order
    calls = follower_model.explainer.calls
    assert [c.shape[0] for c in calls] == [2, 1, 3]
    np.testing.assert_array_equal(calls[1], b1)
    np.testing.assert_array_equal(calls[2], b2)
    assert len(lead_model.batch_calls) == 3  # warmup + 2 explains


def test_follower_catch_and_continue():
    wire = _FakeWire()
    pod, _, _ = _lead(wire=wire)
    # first batch poisons the follower's explain; the loop must stay up
    # and serve the next broadcast (the lead answered its 500 already)
    follower_model = _FakeModel(fail=lambda X: bool(np.any(X == 13.0)))
    t = _run_follower(follower_model, wire)
    pod.explain_batch(np.full((1, N_FEATURES), 13.0, np.float32))
    good = np.full((2, N_FEATURES), 1.0, np.float32)
    pod.explain_batch(good)
    pod.shutdown_followers()
    t.join(timeout=10)
    assert not t.is_alive()
    calls = follower_model.explainer.calls
    assert len(calls) == 1
    np.testing.assert_array_equal(calls[0], good)


def test_pipelined_follower_async_dispatch_sync_warmup():
    wire = _FakeWire()
    pod, _, _ = _lead(wire=wire, cls=PipelinedMultihostServingModel)
    follower_model = _FakeModel(replicate=True)
    t = _run_follower(follower_model, wire)
    # warmup rungs compile SYNCHRONOUSLY even on the pipelined protocol
    pod.warmup_batch(np.zeros((4, N_FEATURES), np.float32))
    fin = pod.explain_batch_async(np.ones((2, N_FEATURES), np.float32))
    fin()
    pod.shutdown_followers()
    t.join(timeout=10)
    assert not t.is_alive()
    inner = follower_model.explainer._explainer
    assert [c.shape[0] for c in follower_model.explainer.calls] == [4]
    assert [c.shape[0] for c in inner.async_calls] == [2]


def test_follower_refuses_lead_transport():
    with pytest.raises(RuntimeError, match="lead process"):
        follower_loop(_FakeModel(), max_rows=8,
                      transport=_LeadTransport(_FakeWire()))


# -- warmup command framing --------------------------------------------- #


def test_warmup_broadcasts_warmup_command():
    pod, model, wire = _lead()
    pod.warmup_batch(np.zeros((4, N_FEATURES), np.float32),
                     split_sizes=[4])
    header = wire.sent[0]
    assert list(header[:_HEADER_LEN]) == [_CMD_WARMUP, 4, 4]
    assert len(model.batch_calls) == 1  # lead compiles the rung too


# -- ladder + metering --------------------------------------------------- #


def test_broadcast_buckets_pow2_fallback():
    # a model without engine compile buckets gets the pow2 ladder
    assert broadcast_buckets(_FakeModel(), 8) == [1, 2, 4, 8]
    assert broadcast_buckets(_FakeModel(), 6) == [1, 2, 4, 6]


def test_broadcast_buckets_follows_engine_rungs():
    model = _FakeModel()
    inner = model.explainer._explainer
    inner._bucket = lambda n: 1 << max(0, int(n) - 1).bit_length()
    inner.config = type("C", (), {"bucket_batches": True})()
    # engine rungs capped at max_rows, max_rows always present
    assert broadcast_buckets(model, 6) == [1, 2, 4, 6]


def test_pod_bcast_metering_counts_frames():
    bytes_before = pod_bcast_byte_counts()
    seconds_before = pod_bcast_seconds_total()
    pod, _, _ = _lead()
    pod.explain_batch(np.zeros((3, N_FEATURES), np.float32))
    delta = (pod_bcast_byte_counts().get(("4",), 0.0)
             - bytes_before.get(("4",), 0.0))
    # (header chunk + bucket-4 payload chunks) x MTU x 4 bytes
    assert delta == (1 + _payload_chunks(4, N_FEATURES)) * CHUNK * 4
    assert pod_bcast_seconds_total() >= seconds_before


def test_pod_bcast_metering_host_wire_bytes():
    # a non-uniform (host-side) wire meters exact frame bytes: header +
    # bucket-padded payload, no MTU chunk padding
    bytes_before = pod_bcast_byte_counts()
    pod = MultihostServingModel(
        _FakeModel(), max_rows=8, buckets=[1, 2, 4, 8],
        transport=_KVLeadTransport(_FakeWire()))
    pod.explain_batch(np.zeros((3, N_FEATURES), np.float32))
    delta = (pod_bcast_byte_counts().get(("4",), 0.0)
             - bytes_before.get(("4",), 0.0))
    assert delta == (_HEADER_LEN + 4 * N_FEATURES) * 4


def test_attach_pod_metrics_renders_bucket_series():
    from distributedkernelshap_tpu.observability.metrics import (
        MetricsRegistry,
    )

    pod, _, _ = _lead()
    pod.explain_batch(np.zeros((1, N_FEATURES), np.float32))
    reg = MetricsRegistry()
    multihost.attach_pod_metrics(reg)
    text = reg.render()
    assert 'dks_pod_bcast_bytes_total{bucket="1"}' in text
    assert "dks_pod_bcast_seconds_total" in text


# -- host-side (KV) wire ------------------------------------------------- #


def test_host_wire_frames_are_unchunked():
    # transports that don't need shape-uniform ops get exact frames: one
    # [cmd, rows, bucket] header op + one bucket-padded payload op
    wire = _FakeWire()
    pod = MultihostServingModel(
        _FakeModel(), max_rows=8, buckets=[1, 2, 4, 8],
        transport=_KVLeadTransport(wire))
    stacked = np.arange(3 * N_FEATURES, dtype=np.float32).reshape(3, -1)
    pod.explain_batch(stacked)
    assert len(wire.sent) == 2
    header, payload = wire.sent
    assert header.shape == (_HEADER_LEN,)
    assert list(header) == [_CMD_EXPLAIN, 3, 4]
    assert payload.shape == (4, N_FEATURES) and payload.dtype == np.float32
    np.testing.assert_array_equal(payload[:3], stacked)
    np.testing.assert_array_equal(payload[3:], 0)
    pod.shutdown_followers()
    assert wire.sent[-1].shape == (_HEADER_LEN,)
    assert list(wire.sent[-1]) == [_CMD_SHUTDOWN, 0, 0]


def test_host_wire_follower_mirrors_lead():
    wire = _FakeWire()
    pod = MultihostServingModel(
        _FakeModel(), max_rows=8, buckets=[1, 2, 4, 8],
        transport=_KVLeadTransport(wire))
    follower_model = _FakeModel()
    t = threading.Thread(
        target=follower_loop, args=(follower_model,),
        kwargs={"max_rows": 8, "transport": _KVFollowerTransport(wire)},
        daemon=True)
    t.start()
    b = np.full((3, N_FEATURES), 5.0, np.float32)
    pod.explain_batch(b)
    pod.shutdown_followers()
    t.join(timeout=10)
    assert not t.is_alive()
    calls = follower_model.explainer.calls
    assert len(calls) == 1
    np.testing.assert_array_equal(calls[0], b)


class _FakeKVClient:
    """Dict-backed stand-in for the jax coordination-service KV client."""

    def __init__(self):
        self.store = {}

    def key_value_set_bytes(self, key, value):
        self.store[key] = bytes(value)

    def blocking_key_value_get_bytes(self, key, timeout_ms):
        if key not in self.store:
            raise RuntimeError("DEADLINE_EXCEEDED (fake)")
        return self.store[key]

    def key_value_delete(self, key):
        self.store.pop(key, None)


def _kv_pair():
    client = _FakeKVClient()
    pair = []
    for _ in range(2):
        t = object.__new__(KVStoreTransport)
        t._client = client
        t._session = "dks/pod/wire/test"
        t._seq = 0
        pair.append(t)
    return pair[0], pair[1], client


def test_kv_transport_orders_and_round_trips():
    lead, follower, _ = _kv_pair()
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    b = np.array([9.0, 8.0, 7.0], np.float32)
    lead.broadcast(a, is_source=True)
    lead.broadcast(b, is_source=True)
    # the follower consumes in sequence order, recovering dtype and
    # shape from its receive template
    got_a = follower.broadcast(np.zeros_like(a), is_source=False)
    got_b = follower.broadcast(np.zeros_like(b), is_source=False)
    np.testing.assert_array_equal(got_a, a)
    np.testing.assert_array_equal(got_b, b)
    assert got_a.dtype == a.dtype and got_a.shape == a.shape


def test_kv_transport_gc_window_bounds_store():
    lead, _, client = _kv_pair()
    n = KVStoreTransport._GC_WINDOW + 10
    x = np.zeros(1, np.float32)
    for _ in range(n):
        lead.broadcast(x, is_source=True)
    # keys trail the head by at most the GC window; the oldest are gone
    assert len(client.store) == KVStoreTransport._GC_WINDOW
    assert "dks/pod/wire/test/0" not in client.store
    assert f"dks/pod/wire/test/{n - 1}" in client.store
