"""Structural validation of the k8s deployment manifests (L7).

The reference's manifests were exercised against a live cluster
(``/root/reference/README.md:57-62``); no cluster exists in CI, so this is
the next-best thing: parse ``cluster/*.yaml`` and assert the cross-file
invariants a deploy would trip over — commands point at files the image
actually ships, ports line up between Service/container/server code, the
Job's ``subdomain`` is backed by a headless Service, namespaces agree with
the Makefiles, and TPU resource requests equal limits (GKE rejects
fractional/mismatched TPU requests).
"""

import os
import re

import pytest

yaml = pytest.importorskip("yaml")  # pyyaml is not a package dependency

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLUSTER = os.path.join(REPO, "cluster")


def _load(name):
    with open(os.path.join(CLUSTER, name)) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def _by_kind(docs):
    out = {}
    for d in docs:
        out.setdefault(d["kind"], []).append(d)
    return out


def test_pool_manifest_structure():
    docs = _by_kind(_load("tpu_pool_cluster.yaml"))
    assert set(docs) == {"Namespace", "Service", "Job"}

    job = docs["Job"][0]
    spec = job["spec"]
    # indexed completion: every host runs exactly one worker process
    assert spec["completionMode"] == "Indexed"
    assert spec["completions"] == spec["parallelism"]

    pod = spec["template"]["spec"]
    assert pod["restartPolicy"] == "Never"

    # the subdomain must be backed by a headless Service of the same name
    # selecting these pods, or per-pod DNS records are never created
    svc = docs["Service"][0]
    assert pod["subdomain"] == svc["metadata"]["name"]
    assert svc["spec"]["clusterIP"] in (None, "None")  # k8s spells it "None"
    labels = spec["template"]["metadata"]["labels"]
    assert svc["spec"]["selector"].items() <= labels.items()

    (container,) = pod["containers"]
    # the command must point at a file the Dockerfile ships (it COPYes
    # benchmarks/ into /app and sets workingDir /app)
    assert container["command"][0] == "python"
    target = container["command"][1]
    assert os.path.exists(os.path.join(REPO, target)), target
    # GKE requires TPU requests == limits
    res = container["resources"]
    assert res["requests"]["google.com/tpu"] == res["limits"]["google.com/tpu"]


def test_serve_manifest_structure():
    docs = _by_kind(_load("tpu_serve_cluster.yaml"))
    assert set(docs) == {"Service", "Deployment"}

    svc = docs["Service"][0]
    (port,) = svc["spec"]["ports"]
    dep = docs["Deployment"][0]
    pod = dep["spec"]["template"]["spec"]
    (container,) = pod["containers"]

    # Service target port == container port == the --port the server binds
    assert port["targetPort"] == container["ports"][0]["containerPort"]
    args = container["args"]
    assert str(port["targetPort"]) == args[args.index("--port") + 1]

    # the Service must select the Deployment's pods
    labels = dep["spec"]["template"]["metadata"]["labels"]
    assert svc["spec"]["selector"].items() <= labels.items()

    # the command module must exist in the shipped package
    assert container["command"][:2] == ["python", "-m"]
    module = container["command"][2]
    assert os.path.exists(os.path.join(REPO, *module.split(".")) + ".py")

    # readiness probe must hit a route the server actually serves
    probe_path = container["readinessProbe"]["httpGet"]["path"]
    with open(os.path.join(REPO, "distributedkernelshap_tpu", "serving",
                           "server.py")) as f:
        assert f'"{probe_path}"' in f.read()

    res = container["resources"]
    assert res["requests"]["google.com/tpu"] == res["limits"]["google.com/tpu"]


def test_namespaces_and_images_consistent():
    pool = _load("tpu_pool_cluster.yaml")
    serve = _load("tpu_serve_cluster.yaml")
    namespaces = {d["metadata"].get("namespace")
                  for d in pool + serve if d["kind"] != "Namespace"}
    assert namespaces == {"dks-tpu"}

    # the Makefiles' default NAMESPACE must match the manifests
    for mk in ("Makefile.pool", "Makefile.serve"):
        with open(os.path.join(CLUSTER, mk)) as f:
            m = re.search(r"NAMESPACE \?= (\S+)", f.read())
        assert m and m.group(1) == "dks-tpu", mk

    # one image name across both manifests, matching dockerfiles/Makefile
    images = {c["image"]
              for d in pool + serve if d["kind"] in ("Job", "Deployment")
              for c in d["spec"]["template"]["spec"]["containers"]}
    assert len(images) == 1
    with open(os.path.join(REPO, "dockerfiles", "Makefile")) as f:
        m = re.search(r"IMAGE_NAME \?= (\S+)", f.read())
    assert m and next(iter(images)).startswith(m.group(1) + ":")


def test_pool_makefile_script_paths_exist():
    """Makefile.pool copies/executes scripts by path — they must exist."""

    with open(os.path.join(CLUSTER, "Makefile.pool")) as f:
        text = f.read()
    for rel in re.findall(r"\.\./(benchmarks/\S+\.py)", text):
        assert os.path.exists(os.path.join(REPO, rel)), rel
