"""LightGBM dump_model lifting (models/lgbm.py).

lightgbm is not installed in CI, so the parser is validated against
hand-constructed ``dump_model()`` dicts (per the documented nested-tree
structure) and an independent pure-Python walker — mirroring
``tests/test_xgb_lift.py``.  On machines with lightgbm installed, lifts are
additionally probe-verified in ``as_predictor``.
"""

import numpy as np
import pytest

from distributedkernelshap_tpu.models import predictor_from_lightgbm_dump


def _leaf(v):
    return {"leaf_value": v}


def _split(feat, thr, left, right, default_left=True, decision_type="<="):
    return {"split_feature": feat, "threshold": thr, "decision_type": decision_type,
            "default_left": default_left, "left_child": left, "right_child": right}


def _dump(roots, objective, num_class=1, average_output=False):
    return {"objective": objective, "num_class": num_class,
            "average_output": average_output,
            "tree_info": [{"tree_structure": r} for r in roots]}


def _walk(node, x):
    while "leaf_value" not in node:
        v = x[node["split_feature"]]
        if np.isnan(v):
            go_left = node["default_left"]
        else:
            go_left = v <= node["threshold"]
        node = node["left_child"] if go_left else node["right_child"]
    return node["leaf_value"]


@pytest.fixture
def binary_roots():
    r0 = _split(0, 0.5,
                _split(1, -1.0, _leaf(0.3), _leaf(-0.7), default_left=False),
                _split(2, 2.0, _leaf(1.1), _leaf(-0.2)))
    r1 = _split(2, 1.5, _leaf(0.25), _leaf(-0.4))
    return [r0, r1]


def test_binary(binary_roots):
    pred = predictor_from_lightgbm_dump(_dump(binary_roots, "binary sigmoid:1"))
    assert pred is not None and pred.n_outputs == 2
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 3)).astype(np.float32)
    margin = np.array([sum(_walk(r, x) for r in binary_roots) for x in X])
    np.testing.assert_allclose(np.asarray(pred(X))[:, 1],
                               1 / (1 + np.exp(-margin)), atol=1e-5)


def test_nonunit_sigmoid_scale_declines(binary_roots):
    """``binary sigmoid:2`` means p = 1/(1+exp(-2f)); the lift only reproduces
    scale 1, so any other scale must decline on the dump path too (ADVICE r1:
    previously only the as_predictor probe caught it)."""

    assert predictor_from_lightgbm_dump(
        _dump(binary_roots, "binary sigmoid:2")) is None
    assert predictor_from_lightgbm_dump(
        _dump(binary_roots, "binary sigmoid:0.5")) is None
    assert predictor_from_lightgbm_dump(
        _dump(binary_roots, "binary sigmoid:bogus")) is None
    assert predictor_from_lightgbm_dump(
        _dump(binary_roots, "binary sigmoid:1")) is not None
    # bare "binary" (no scale token) keeps the default scale of 1
    assert predictor_from_lightgbm_dump(_dump(binary_roots, "binary")) is not None


def test_boundary_goes_left(binary_roots):
    """LightGBM routes x <= t left (inclusive) — exactly our comparator."""

    pred = predictor_from_lightgbm_dump(_dump(binary_roots, "binary"))
    x = np.array([[0.5, -1.0, 1.5]], np.float32)    # every value AT a threshold
    margin = sum(_walk(r, x[0]) for r in binary_roots)
    np.testing.assert_allclose(np.asarray(pred(x))[0, 1],
                               1 / (1 + np.exp(-margin)), atol=1e-5)


def test_missing_routing(binary_roots):
    pred = predictor_from_lightgbm_dump(_dump(binary_roots, "binary"))
    X = np.array([[np.nan, 0.0, 0.0], [1.0, np.nan, np.nan]], np.float32)
    margin = np.array([sum(_walk(r, x) for r in binary_roots) for x in X])
    np.testing.assert_allclose(np.asarray(pred(X))[:, 1],
                               1 / (1 + np.exp(-margin)), atol=1e-5)


def test_multiclass_iteration_major():
    """num_class=3: tree i feeds class i % 3 (iteration-major dump order)."""

    roots = [_split(0, 0.0, _leaf(0.1 * (i + 1)), _leaf(-0.2 * (i + 1)))
             for i in range(6)]                      # 2 rounds x 3 classes
    pred = predictor_from_lightgbm_dump(_dump(roots, "multiclass num_class:3",
                                              num_class=3))
    assert pred.n_outputs == 3
    rng = np.random.default_rng(1)
    X = rng.normal(size=(32, 1)).astype(np.float32)
    margins = np.stack([[sum(_walk(roots[r * 3 + k], x) for r in range(2))
                         for k in range(3)] for x in X])
    e = np.exp(margins - margins.max(1, keepdims=True))
    np.testing.assert_allclose(np.asarray(pred(X)), e / e.sum(1, keepdims=True),
                               atol=1e-5)


def test_regression_identity_and_rf_average():
    roots = [_split(0, 0.0, _leaf(2.0), _leaf(4.0)),
             _split(0, 1.0, _leaf(-1.0), _leaf(3.0))]
    summed = predictor_from_lightgbm_dump(_dump(roots, "regression"))
    averaged = predictor_from_lightgbm_dump(_dump(roots, "regression",
                                                  average_output=True))
    x = np.array([[0.5]], np.float32)
    np.testing.assert_allclose(np.asarray(summed(x))[0, 0], 4.0 - 1.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(averaged(x))[0, 0], (4.0 - 1.0) / 2,
                               atol=1e-6)
    assert not summed.vector_out


def test_threshold_rounds_down_not_nearest():
    """A double threshold half-an-ulp below an f32 value must not round up
    onto it: x == 1.0 with t = 1 - 1e-12 goes RIGHT in LightGBM's double
    compare and must go right on the device too."""

    t = 1.0 - 1e-12
    assert np.float32(t) == np.float32(1.0)          # nearest-cast overshoots
    roots = [_split(0, t, _leaf(10.0), _leaf(-10.0))]
    pred = predictor_from_lightgbm_dump(_dump(roots, "regression"))
    got = np.asarray(pred(np.array([[1.0], [0.999999]], np.float32)))
    np.testing.assert_allclose(got[:, 0], [-10.0, 10.0], atol=1e-6)


def test_linear_tree_declines():
    leaf = {"leaf_value": 0.5, "leaf_coeff": [0.1], "leaf_const": 0.2,
            "leaf_features": [0]}
    roots = [_split(0, 0.0, leaf, _leaf(-0.5))]
    assert predictor_from_lightgbm_dump(_dump(roots, "regression")) is None


def test_multiclass_rf_average_declines():
    roots = [_split(0, 0.0, _leaf(0.1), _leaf(-0.1)) for _ in range(6)]
    assert predictor_from_lightgbm_dump(
        _dump(roots, "multiclass", num_class=3, average_output=True)) is None


def test_binary_as_scalar_matches_raw_booster_layout(binary_roots):
    """Raw Booster.predict returns one probability column for binary
    objectives; binary_as_scalar reproduces that layout."""

    pred = predictor_from_lightgbm_dump(_dump(binary_roots, "binary"),
                                        binary_as_scalar=True)
    assert pred.n_outputs == 1 and not pred.vector_out
    rng = np.random.default_rng(3)
    X = rng.normal(size=(16, 3)).astype(np.float32)
    margin = np.array([sum(_walk(r, x) for r in binary_roots) for x in X])
    np.testing.assert_allclose(np.asarray(pred(X))[:, 0],
                               1 / (1 + np.exp(-margin)), atol=1e-5)


def test_categorical_split_declines(binary_roots):
    roots = [_split(0, 0.5, _leaf(1.0), _leaf(-1.0), decision_type="==")]
    assert predictor_from_lightgbm_dump(_dump(roots, "binary")) is None


def test_link_objectives_decline():
    roots = [_leaf(0.5)]
    for obj in ("poisson", "gamma", "tweedie", "cross_entropy", "multiclassova"):
        assert predictor_from_lightgbm_dump(_dump(roots, obj)) is None


def test_single_leaf_tree():
    pred = predictor_from_lightgbm_dump(_dump([_leaf(1.25)], "regression"))
    np.testing.assert_allclose(np.asarray(pred(np.zeros((2, 1), np.float32)))[:, 0],
                               [1.25, 1.25], atol=1e-6)


def test_malformed_dump_declines():
    assert predictor_from_lightgbm_dump({}) is None
    assert predictor_from_lightgbm_dump({"objective": "binary"}) is None
    assert predictor_from_lightgbm_dump(
        {"objective": "binary", "tree_info": [{"tree_structure": {"bogus": 1}}]}) is None


def test_explain_end_to_end_from_dump(binary_roots):
    from distributedkernelshap_tpu import KernelShap

    pred = predictor_from_lightgbm_dump(_dump(binary_roots, "binary"))
    rng = np.random.default_rng(2)
    bg = rng.normal(size=(30, 3)).astype(np.float32)
    Xe = rng.normal(size=(12, 3)).astype(np.float32)
    ex = KernelShap(pred, link="logit", seed=0)
    ex.fit(bg)
    res = ex.explain(Xe, silent=True)
    proba = np.clip(np.asarray(pred(Xe)), 1e-7, 1 - 1e-7)
    for k, phi in enumerate(res.shap_values):
        lhs = phi.sum(axis=1) + res.expected_value[k]
        rhs = np.log(proba[:, k] / (1 - proba[:, k]))
        np.testing.assert_allclose(lhs, rhs, atol=5e-3)
