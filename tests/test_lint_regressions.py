"""Regression pins for the genuine defects the dks-analyze static pass
surfaced on the tree it landed in (ISSUE 15 satellite: each fix cites
its finding id).  The fixes live in ``resilience/supervisor.py``,
``serving/autoscaler.py``, ``serving/replicas.py`` and
``serving/server.py``; these tests fail against the pre-fix code —
probabilistically for the data races (the hammers reliably trip
"changed size during iteration" / torn counters within their budgets on
unlocked code), deterministically for the dead-thread guards."""

import threading
import time

import pytest

from distributedkernelshap_tpu.resilience.supervisor import (
    ReplicaSupervisor,
    RestartPolicy,
)
from distributedkernelshap_tpu.serving.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
)
from distributedkernelshap_tpu.serving.replicas import FanInProxy
from distributedkernelshap_tpu.serving.server import ExplainerServer


class _FakeProc:
    def __init__(self, returncode=None):
        self.returncode = returncode

    def poll(self):
        return self.returncode


# --------------------------------------------------------------------- #
# DKS-C001/C002 @ resilience/supervisor.py — crash bookkeeping raced the
# autoscaler's track/retire and the statusz stats() reader
# --------------------------------------------------------------------- #


def test_supervisor_bookkeeping_survives_concurrent_scaler_traffic():
    """Finding DKS-C001 [ReplicaSupervisor._retired / _respawn_at /
    _consecutive]: ``_tick`` mutated the crash books while the
    autoscaler thread called ``track``/``retire`` and statusz handlers
    called ``stats``/``is_retired`` — all unlocked."""

    procs = [_FakeProc(returncode=0) for _ in range(8)]
    sup = ReplicaSupervisor(
        procs, lambda i: _FakeProc(),
        policy=RestartPolicy(base_backoff_s=0.001, max_backoff_s=0.001,
                             jitter_frac=0.0, seed=0),
        poll_interval_s=3600)
    stop = time.monotonic() + 1.0
    errors = []

    def scaler():
        i = 0
        while time.monotonic() < stop:
            try:
                sup.retire(i % 8)
                sup.track(i % 8)
                sup.is_retired((i + 3) % 8)
            except Exception as e:      # pragma: no cover - the defect
                errors.append(e)
                return
            i += 1

    def panel():
        while time.monotonic() < stop:
            try:
                s = sup.stats()
                assert set(s) == {"restarts_total",
                                  "crash_loops_backing_off", "retired"}
            except Exception as e:      # pragma: no cover - the defect
                errors.append(e)
                return

    threads = [threading.Thread(target=scaler),
               threading.Thread(target=scaler),
               threading.Thread(target=panel)]
    for t in threads:
        t.start()
    while time.monotonic() < stop:
        sup._tick()
    for t in threads:
        t.join(10)
    assert errors == []


def test_supervisor_book_calls_never_deadlock_against_the_owner_lock():
    """The fix deliberately gave the books their OWN lock: the owner
    (``ReplicaManager.spawn_replica``) calls ``is_retired()`` while
    holding the procs lock it passed as ``lock=`` — bookkeeping guarded
    by that same lock would self-deadlock."""

    owner_lock = threading.Lock()
    sup = ReplicaSupervisor([_FakeProc()], lambda i: _FakeProc(),
                            poll_interval_s=3600, lock=owner_lock)
    done = threading.Event()

    def owner_path():
        with owner_lock:                # the spawn_replica pattern
            sup.is_retired(0)
            sup.stats()
            sup.retire(0)
            sup.track(0)
        done.set()

    t = threading.Thread(target=owner_path, daemon=True)
    t.start()
    assert done.wait(5), \
        "supervisor bookkeeping deadlocked against the owner's procs lock"


# --------------------------------------------------------------------- #
# DKS-C001/C002 @ serving/autoscaler.py — the statusz panel read streaks,
# cooldown stamps, tick counts and the draining book without the lock
# --------------------------------------------------------------------- #


class _IdleFleet:
    def spawn_replica(self, standby=False):      # pragma: no cover
        return 0

    def retire_replica(self, index):             # pragma: no cover
        pass


def _proxy():
    return FanInProxy([("127.0.0.1", 1)], probe_interval_s=3600,
                      health_interval_s=0)


def test_autoscaler_panel_survives_concurrent_tick_state():
    """Finding DKS-C001/C002 [Autoscaler._draining / _up_streak /
    ticks_total]: ``statusz_panel`` (proxy handler threads) iterated the
    draining book and read the decision state while the scaler thread
    mutated them."""

    scaler = Autoscaler(_IdleFleet(), _proxy(),
                        config=AutoscalerConfig(max_replicas=4))
    stop = time.monotonic() + 1.0
    errors = []

    def mutator():
        i = 0
        while time.monotonic() < stop:
            with scaler._lock:           # the tick path's write pattern
                scaler._draining[i % 5] = {"since": time.monotonic()}
                scaler._draining.pop((i + 2) % 5, None)
                scaler._up_streak += 1
                scaler.ticks_total += 1
                scaler._last_decision = {"action": "none",
                                         "reason": "test",
                                         "t": time.monotonic()}
            i += 1

    def reader():
        while time.monotonic() < stop:
            try:
                panel = scaler.statusz_panel()
                assert isinstance(panel["ticks_total"], int)
                assert isinstance(panel["draining_age_s"], dict)
            except Exception as e:      # pragma: no cover - the defect
                errors.append(e)
                return

    threads = [threading.Thread(target=mutator)] + \
        [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert errors == []


# --------------------------------------------------------------------- #
# DKS-C005 @ serving/replicas.py — an unexpected raise inside the probe
# sweep silently killed the process's ONE dead-replica recovery thread
# --------------------------------------------------------------------- #


def test_prober_thread_survives_a_raising_sweep(monkeypatch):
    """Finding DKS-C005 [_probe_loop]: per-probe OSError handling did
    not cover e.g. a roster mutated mid-sweep; the first stray raise
    ended the loop and dead replicas stayed dead forever."""

    proxy = FanInProxy([("127.0.0.1", 1)], probe_interval_s=0.01,
                       health_interval_s=0)
    calls = []
    survived = threading.Event()

    def sweep():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("roster mutated mid-sweep")
        survived.set()

    monkeypatch.setattr(proxy, "_probe_sweep", sweep)
    t = threading.Thread(target=proxy._probe_loop, daemon=True)
    t.start()
    try:
        assert survived.wait(10), \
            "the prober thread died on the first sweep exception"
    finally:
        proxy._stop.set()
        t.join(10)
    assert len(calls) >= 2


# --------------------------------------------------------------------- #
# DKS-C005 @ serving/server.py — same class of defect in the watchdog:
# a transient raise in the stall evaluation killed the wedge detector
# --------------------------------------------------------------------- #


def test_watchdog_thread_survives_a_raising_tick(monkeypatch):
    """Finding DKS-C005 [_watchdog_loop]: a raise in the tick (a dying
    registry mid-swap, a torn model reset) silently disabled wedge
    detection — the next device hang became an every-socket-hangs
    outage instead of a failed health check."""

    class _Stub:
        pass

    srv = ExplainerServer(_Stub(), health_interval_s=0,
                          watchdog_timeout_s=0.05)  # tick every ~12 ms
    calls = []
    survived = threading.Event()

    def tick():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("registry swap raced the tick")
        survived.set()

    monkeypatch.setattr(srv, "_watchdog_tick", tick)
    t = threading.Thread(target=srv._watchdog_loop, daemon=True)
    t.start()
    try:
        assert survived.wait(10), \
            "the watchdog thread died on the first tick exception"
    finally:
        srv._stop.set()
        t.join(10)
    assert len(calls) >= 2


# --------------------------------------------------------------------- #
# DKS-C001 @ serving/server.py — progress markers (_last_progress,
# _ever_completed) were written by finalizer threads and read by
# health/statusz handlers without a common guard
# --------------------------------------------------------------------- #


def test_progress_markers_are_consistent_under_concurrent_completion():
    """Finding DKS-C001 [ExplainerServer._last_progress /
    _ever_completed]: the stall-age gauge could pair a stale
    ``_last_progress`` with a fresh ``_active`` view (and vice versa),
    yielding phantom stall ages; both markers now move under
    ``_active_lock`` together with the active-batch book."""

    import numpy as np

    from distributedkernelshap_tpu.serving.server import _Pending

    class _Stub:
        pass

    srv = ExplainerServer(_Stub(), health_interval_s=0)
    stop = time.monotonic() + 1.0
    errors = []

    def completer():
        while time.monotonic() < stop:
            p = _Pending(np.ones((1, 2), dtype=np.float32))
            p.done = True
            batch = [p]
            with srv._active_lock:
                srv._active[id(batch)] = batch
            srv._complete(batch, payloads=["{}"])

    def health_reader():
        while time.monotonic() < stop:
            try:
                with srv._active_lock:
                    busy = bool(srv._active)
                    last = srv._last_progress
                age = (time.monotonic() - last) if busy else 0.0
                # a marker paired under the lock can never be from the
                # future, and an idle server never reports a stall
                assert age >= 0.0
                assert srv._ever_completed in (True, False)
            except Exception as e:      # pragma: no cover - the defect
                errors.append(e)
                return

    threads = [threading.Thread(target=completer),
               threading.Thread(target=health_reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert errors == []
    assert srv._ever_completed          # completions really flowed
