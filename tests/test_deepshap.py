"""DeepSHAP attribution engine (ISSUE 12): layer rules vs brute-force
Shapley enumeration, completeness, readiness gates and fallback
accounting, engine/serving promotion (auto-select, device cache, staged
async, warmup-ladder coverage, path metrics) and the CNN graph export.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributedkernelshap_tpu.attribution.deepshap import (
    brute_force_shapley,
    build_deepshap_fn,
    deepshap_fallback_counts,
    deepshap_ready,
    supports_deepshap,
)
from distributedkernelshap_tpu.ops.explain import groups_to_matrix
from distributedkernelshap_tpu.ops.image import superpixel_groups
from distributedkernelshap_tpu.registry.onnx_lift import (
    GraphSpec,
    NodeSpec,
    run_graph_reference,
)


def _run_phi(spec, K, X, bg, bgw=None, G=None):
    D = spec.input_dim
    bgw = (np.ones(bg.shape[0], np.float32) / bg.shape[0]
           if bgw is None else bgw)
    G = np.eye(D, dtype=np.float32) if G is None else G
    fn = jax.jit(build_deepshap_fn(spec, K))
    params = {k: jnp.asarray(v) for k, v in spec.initializers.items()
              if np.asarray(v).dtype.kind == "f"}
    return np.asarray(fn(jnp.asarray(X), params, jnp.asarray(bg),
                         jnp.asarray(bgw), jnp.asarray(G)))


def _additive_relu_spec(seed=0, D=6, H=8, K=2):
    """Each hidden unit reads ONE input feature — the model is additive
    across features, where the rescale rule IS the Shapley marginal."""

    rng = np.random.default_rng(seed)
    W1 = np.zeros((D, H), np.float32)
    for j in range(H):
        W1[j % D, j] = rng.normal()
    spec = GraphSpec(
        [NodeSpec("Gemm", ("x", "W1", "b1"), ("h",), {}),
         NodeSpec("Relu", ("h",), ("a",), {}),
         NodeSpec("Gemm", ("a", "W2", "b2"), ("y",), {})],
        {"W1": W1, "b1": rng.normal(size=H).astype(np.float32),
         "W2": rng.normal(size=(H, K)).astype(np.float32),
         "b2": rng.normal(size=K).astype(np.float32)},
        "x", "y", D)
    return spec, K


def _conv_spec(seed=1, side=6, K=3, nonneg=False, maxpool=False,
               batchnorm=False, pool_strides=(2, 2)):
    rng = np.random.default_rng(seed)
    D = side * side

    def maybe(a):
        return np.abs(a) if nonneg else a

    Wc = maybe(rng.normal(scale=0.4, size=(4, 1, 3, 3))).astype(np.float32)
    bc = maybe(rng.normal(scale=0.1, size=4)).astype(np.float32)
    nodes = [
        NodeSpec("Reshape", ("x", "shape_img"), ("img",), {}),
        NodeSpec("Transpose", ("img",), ("nchw",), {"perm": [0, 3, 1, 2]}),
        NodeSpec("Conv", ("nchw", "Wc", "bc"), ("c1",),
                 {"strides": [1, 1], "pads": [1, 1, 1, 1]}, "conv1"),
    ]
    inits = {"shape_img": np.asarray([0, side, side, 1], np.int64),
             "Wc": Wc, "bc": bc}
    tensor = "c1"
    if batchnorm:
        inits.update(scale=rng.uniform(0.5, 1.5, 4).astype(np.float32),
                     bias=rng.normal(scale=0.1, size=4).astype(np.float32),
                     mean=rng.normal(scale=0.1, size=4).astype(np.float32),
                     var=rng.uniform(0.5, 1.5, 4).astype(np.float32))
        nodes.append(NodeSpec(
            "BatchNormalization", (tensor, "scale", "bias", "mean", "var"),
            ("bn",), {"epsilon": 1e-5}))
        tensor = "bn"
    nodes.append(NodeSpec("Relu", (tensor,), ("r1",), {}))
    tensor, feat_side = "r1", side
    if maxpool:
        nodes.append(NodeSpec("MaxPool", (tensor,), ("p1",),
                              {"kernel_shape": [2, 2],
                               "strides": list(pool_strides)}))
        tensor, feat_side = "p1", side // 2
    nodes.append(NodeSpec("Flatten", (tensor,), ("fl",), {"axis": 1}))
    Wd = rng.normal(scale=0.3,
                    size=(4 * feat_side * feat_side, K)).astype(np.float32)
    nodes.append(NodeSpec("Gemm", ("fl", "Wd", "bd"), ("y",), {}))
    inits.update(Wd=Wd,
                 bd=rng.normal(scale=0.1, size=K).astype(np.float32))
    return GraphSpec(nodes, inits, "x", "y", D), K


class _GraphPred:
    def __init__(self, spec):
        self._spec = spec

    def graph_spec(self):
        return self._spec


# --------------------------------------------------------------------- #
# rule engine vs brute-force Shapley enumeration


def test_additive_relu_net_matches_brute_force():
    spec, K = _additive_relu_spec()
    rng = np.random.default_rng(10)
    X = rng.normal(size=(3, spec.input_dim)).astype(np.float32)
    bg = rng.normal(size=(4, spec.input_dim)).astype(np.float32)
    phi = _run_phi(spec, K, X, bg)
    for i in range(X.shape[0]):
        ref = brute_force_shapley(
            lambda r: run_graph_reference(spec, r), X[i], bg)
        np.testing.assert_allclose(phi[i], ref, atol=2e-6)


def test_stable_conv_relu_net_matches_brute_force_grouped():
    """Non-negative Conv/Relu stack over non-negative pixels: every
    pre-activation stays non-negative over the whole coalition cube, so
    the piecewise-linear net is coalition-stable and DeepSHAP equals
    exact Shapley — on superpixel groups too."""

    spec, K = _conv_spec(nonneg=True)
    rng = np.random.default_rng(11)
    X = rng.uniform(0, 1, size=(2, spec.input_dim)).astype(np.float32)
    bg = rng.uniform(0, 1, size=(3, spec.input_dim)).astype(np.float32)
    groups, _ = superpixel_groups(6, 6, patch=2)
    G = groups_to_matrix(groups, spec.input_dim)
    phi = _run_phi(spec, K, X, bg, G=G)
    for i in range(X.shape[0]):
        ref = brute_force_shapley(
            lambda r: run_graph_reference(spec, r), X[i], bg, G=G)
        np.testing.assert_allclose(phi[i], ref, atol=2e-6)


def test_completeness_on_general_cnn_with_bn_and_maxpool():
    """Arbitrary-sign Conv+BN+Relu+MaxPool net: phi is the DeepLIFT
    approximation there, but completeness (sum phi = f(x) - E[f]) holds
    exactly — including through the maxpool rule's argmax routing."""

    spec, K = _conv_spec(seed=2, maxpool=True, batchnorm=True)
    rng = np.random.default_rng(12)
    X = rng.uniform(0, 1, size=(4, spec.input_dim)).astype(np.float32)
    bg = rng.uniform(0, 1, size=(3, spec.input_dim)).astype(np.float32)
    phi = _run_phi(spec, K, X, bg)
    fx = run_graph_reference(spec, X)
    ef = run_graph_reference(spec, bg).mean(0)
    np.testing.assert_allclose(phi.sum(2), fx - ef, atol=5e-6)


def test_rescale_zero_delta_is_finite_and_complete():
    """Features equal between x and background: the rescale rule's
    difference quotient degenerates there; the midpoint-derivative limit
    must keep phi finite (and zero for untouched features)."""

    spec, K = _additive_relu_spec(seed=3)
    rng = np.random.default_rng(13)
    bg = rng.normal(size=(2, spec.input_dim)).astype(np.float32)
    X = bg[:1].copy()
    X[0, 0] += 1.0  # only feature 0 differs from background row 0
    phi = _run_phi(spec, K, X, bg)
    assert np.isfinite(phi).all()
    fx = run_graph_reference(spec, X)
    ef = run_graph_reference(spec, bg).mean(0)
    np.testing.assert_allclose(phi.sum(2), fx - ef, atol=5e-6)


def test_brute_force_refuses_oracle_scale():
    with pytest.raises(ValueError, match="2\\^M"):
        brute_force_shapley(lambda r: r, np.zeros(17), np.zeros((1, 17)))


# --------------------------------------------------------------------- #
# readiness gates


def test_readiness_gates_and_reasons():
    spec, _ = _conv_spec(seed=4)
    pred = _GraphPred(spec)
    assert supports_deepshap(pred)
    assert deepshap_ready(pred, "identity") is None
    assert deepshap_ready(pred, "logit") == "link"
    assert deepshap_ready(pred, "identity",
                          target_chunk_elems=1024) == "footprint"
    assert deepshap_ready(object(), "identity") == "structure"

    softmax = GraphSpec(
        spec.nodes + [NodeSpec("Softmax", ("y",), ("p",), {})],
        spec.initializers, "x", "p", spec.input_dim)
    assert deepshap_ready(_GraphPred(softmax), "identity") == "rule"
    assert not supports_deepshap(_GraphPred(softmax))

    bilinear = GraphSpec(
        [NodeSpec("MatMul", ("x", "h"), ("y",), {}),
         NodeSpec("Gemm", ("x", "W"), ("h",), {})][::-1],
        {"W": np.eye(4, dtype=np.float32)}, "x", "y", 4)
    assert deepshap_ready(_GraphPred(bilinear), "identity") == "bilinear"

    # BatchNormalization is affine ONLY for constant scale/mean/var: a
    # graph-produced parameter input makes it a product — the linear
    # rule would silently break even completeness, so it must gate
    dyn_bn = GraphSpec(
        [NodeSpec("Gemm", ("x", "W"), ("s",), {}),
         NodeSpec("BatchNormalization", ("x", "s", "o", "m", "v"),
                  ("y",), {})],
        {"W": np.eye(4, dtype=np.float32),
         "o": np.zeros(4, np.float32), "m": np.zeros(4, np.float32),
         "v": np.ones(4, np.float32)}, "x", "y", 4)
    assert deepshap_ready(_GraphPred(dyn_bn), "identity") == "bilinear"


def test_overlapping_maxpool_fails_readiness():
    spec, _ = _conv_spec(seed=5, maxpool=True, pool_strides=(1, 1))
    assert deepshap_ready(_GraphPred(spec), "identity") == "pool_overlap"


def test_classify_path_deepshap_and_fallback():
    from distributedkernelshap_tpu.registry.classify import classify_path

    spec, _ = _conv_spec(seed=6)
    decision = classify_path(_GraphPred(spec))
    assert decision.path == "deepshap"
    assert "neural graph" in decision.reason

    softmax = GraphSpec(
        spec.nodes + [NodeSpec("Softmax", ("y",), ("p",), {})],
        spec.initializers, "x", "p", spec.input_dim)
    fallback = classify_path(_GraphPred(softmax))
    assert fallback.path == "sampled"
    assert fallback.deepshap_fallback == "rule"


# --------------------------------------------------------------------- #
# fingerprints (satellite: JaxPredictor-family content identity)


def test_jaxpredictor_fingerprint_bytes():
    from distributedkernelshap_tpu.models.predictors import JaxPredictor
    from distributedkernelshap_tpu.scheduling.result_cache import (
        predictor_fingerprint,
    )

    def identity_fn(x):
        return x

    params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    a = JaxPredictor(identity_fn, n_outputs=3, params=params)
    b = JaxPredictor(identity_fn, n_outputs=3,
                     params={"w": params["w"].copy()})
    assert a.fingerprint_bytes() == b.fingerprint_bytes()
    da, weak_a = predictor_fingerprint(a)
    db, weak_b = predictor_fingerprint(b)
    assert (da, weak_a) == (db, False) and not weak_b
    other = JaxPredictor(identity_fn, n_outputs=3,
                         params={"w": params["w"] + 1.0})
    assert predictor_fingerprint(other)[0] != da
    # a DIFFERENT function over the same params is a different model —
    # the fn's code identity is part of the content, so two such
    # tenants can never coalesce via the share key or the result cache
    different_fn = JaxPredictor(lambda x: x + 1.0, n_outputs=3,
                                params={"w": params["w"].copy()})
    assert different_fn.fingerprint_bytes() != a.fingerprint_bytes()
    # no params, or a callable OBJECT whose behaviour code hashing
    # cannot see: no content identity — the loud weak fallback stays
    bare = JaxPredictor(identity_fn, n_outputs=3)
    assert bare.fingerprint_bytes() is None
    assert predictor_fingerprint(bare)[1] is True

    class _Callable:
        def __call__(self, x):
            return x

    opaque = JaxPredictor(_Callable(), n_outputs=3, params=params)
    assert opaque.fingerprint_bytes() is None


def test_cnn_predictor_is_share_eligible_content():
    from distributedkernelshap_tpu.scheduling.result_cache import (
        predictor_fingerprint,
    )

    pred = _tiny_cnn(seed=0)
    digest, weak = predictor_fingerprint(pred)
    assert not weak
    assert predictor_fingerprint(_tiny_cnn(seed=0)) == (digest, False)
    assert predictor_fingerprint(_tiny_cnn(seed=1))[0] != digest
    # same params, different head: different FUNCTIONS — the scalar
    # config is part of the content identity, so these must never
    # collide in the result cache or the cross-tenant share key
    assert predictor_fingerprint(_tiny_cnn(seed=0,
                                           output="probs"))[0] != digest


# --------------------------------------------------------------------- #
# CNN graph export + engine/serving integration


def _tiny_cnn(seed=0, output="logits", side=12):
    pytest.importorskip("flax")

    from distributedkernelshap_tpu.models.cnn import _CNN, CNNPredictor

    module = _CNN(n_classes=4)
    params = module.init(
        jax.random.PRNGKey(seed),
        jnp.zeros((1, side, side, 1), jnp.float32))["params"]
    return CNNPredictor(params, (side, side, 1), n_classes=4,
                        output=output)


@pytest.fixture(scope="module")
def cnn_setup():
    rng = np.random.default_rng(21)
    pred = _tiny_cnn()
    bg = rng.uniform(0, 1, size=(2, 144)).astype(np.float32)
    Xe = rng.uniform(0, 1, size=(4, 144)).astype(np.float32)
    groups, names = superpixel_groups(12, 12, patch=4)  # 9 superpixels
    return dict(pred=pred, bg=bg, Xe=Xe, groups=groups, names=names)


def test_cnn_graph_spec_matches_flax_eval(cnn_setup):
    s = cnn_setup
    spec = s["pred"].graph_spec()
    ref = run_graph_reference(spec, s["Xe"])
    got = np.asarray(s["pred"](jnp.asarray(s["Xe"])))
    np.testing.assert_allclose(ref, got, atol=2e-5)
    # probs head exports with a Softmax tail — correct eval, off-path
    probs = _tiny_cnn(output="probs")
    ref_p = run_graph_reference(probs.graph_spec(), s["Xe"])
    np.testing.assert_allclose(ref_p, np.asarray(probs(jnp.asarray(s["Xe"]))),
                               atol=2e-5)
    assert deepshap_ready(probs, "identity") == "rule"


def _fit_model(s, **kw):
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    return BatchKernelShapModel(
        s["pred"], s["bg"], {"seed": 0},
        {"groups": s["groups"], "group_names": s["names"]}, **kw)


def test_auto_selects_deepshap_for_cnn_tenant(cnn_setup):
    s = cnn_setup
    model = _fit_model(s)
    assert model.explain_path == "deepshap"
    assert model.explain_path_reason == "auto"
    assert model.explain_kwargs == {"nsamples": "exact"}
    payloads = model.explain_batch(s["Xe"], split_sizes=[2, 2])
    doc = json.loads(payloads[0])
    phi = np.asarray(doc["data"]["shap_values"])  # (K, 2, M)
    assert phi.shape == (4, 2, 9)
    # additivity over the wire: group phi sums to f(x) - E[f]
    total = phi.sum(-1).T + np.asarray(doc["data"]["expected_value"])[None]
    np.testing.assert_allclose(
        total, np.asarray(doc["data"]["raw"]["raw_prediction"]), atol=1e-4)


def test_deepshap_opt_outs(cnn_setup, monkeypatch):
    from distributedkernelshap_tpu.serving.wrappers import KernelShapModel

    s = cnn_setup
    pinned = KernelShapModel(s["pred"], s["bg"], {"seed": 0}, {},
                             explain_kwargs={"nsamples": 64})
    assert pinned.explain_path == "sampled"
    assert pinned.explain_path_reason == "pinned"
    # pinned 'exact' resolves to the deepshap flavor for attribution
    exact = KernelShapModel(s["pred"], s["bg"], {"seed": 0}, {},
                            explain_kwargs={"nsamples": "exact"})
    assert exact.explain_path == "deepshap"
    assert exact.explain_path_reason == "pinned"
    # its own kill switch, counted in the fallback accounting
    before = deepshap_fallback_counts().get(("auto_disabled",), 0.0)
    monkeypatch.setenv("DKS_DEEPSHAP_AUTO", "0")
    off = KernelShapModel(s["pred"], s["bg"], {"seed": 0}, {})
    assert off.explain_path == "sampled"
    assert off.explain_path_reason == "auto_disabled"
    assert deepshap_fallback_counts()[("auto_disabled",)] == before + 1
    monkeypatch.delenv("DKS_DEEPSHAP_AUTO")
    # the global exact-path switch applies too
    monkeypatch.setenv("DKS_EXACT_AUTO", "0")
    off2 = KernelShapModel(s["pred"], s["bg"], {"seed": 0}, {})
    assert off2.explain_path == "sampled"
    assert off2.explain_path_reason == "auto_disabled"


def test_engine_deepshap_device_cache_and_reset(cnn_setup):
    s = cnn_setup
    model = _fit_model(s)
    engine = model.explainer._explainer
    model.explain_batch(s["Xe"])
    key = ("deepshap_consts", engine.content_fingerprint())
    assert key in engine._plan_consts_cache
    consts = engine._plan_consts_cache[key]
    model.explain_batch(s["Xe"])
    assert engine._plan_consts_cache[key] is consts  # served from cache
    engine.reset_device_state()
    assert not engine._plan_consts_cache
    # bit-identical across the rebuild (same program, same constants)
    a = model.explain_batch(s["Xe"])
    b = model.explain_batch(s["Xe"])
    assert a == b


def test_deepshap_staged_async_matches_sync(cnn_setup):
    from distributedkernelshap_tpu.kernel_shap import StagedRows

    s = cnn_setup
    model = _fit_model(s)
    staged = model.stage_rows(s["Xe"])
    assert isinstance(staged, StagedRows)
    sync = model.explain_batch(s["Xe"], split_sizes=[2, 2])
    got = model.explain_batch_async(staged, split_sizes=[2, 2])()
    assert got == sync
    staged2 = model.stage_rows(s["Xe"])
    binary = model.explain_batch_async(
        staged2, split_sizes=[2, 2], formats=["binary", "json"])()
    assert isinstance(binary[0], (bytes, bytearray))
    assert binary[1] == sync[1]


def test_group_phi_is_summed_feature_phi(cnn_setup):
    """Superpixel phi is the sum of member-pixel phi — the image-SHAP
    grouping convention, implemented as one einsum against G."""

    from distributedkernelshap_tpu import KernelShap

    s = cnn_setup
    grouped = KernelShap(s["pred"], seed=0)
    grouped.fit(s["bg"], groups=s["groups"], group_names=s["names"])
    flat = KernelShap(s["pred"], seed=0)
    flat.fit(s["bg"])
    X = s["Xe"][:2]
    phi_g = np.stack(grouped.explain(X, nsamples="exact",
                                     silent=True).shap_values, 1)
    phi_f = np.stack(flat.explain(X, nsamples="exact",
                                  silent=True).shap_values, 1)
    G = groups_to_matrix(s["groups"], 144)
    np.testing.assert_allclose(phi_g, phi_f @ G.T, atol=1e-5)


def test_path_metric_counts_deepshap(cnn_setup):
    from distributedkernelshap_tpu.serving import wrappers

    s = cnn_setup
    model = _fit_model(s)
    before = wrappers.explain_path_counts().get(("deepshap",), 0.0)
    model.explain_batch(s["Xe"], split_sizes=[2, 2])
    assert wrappers.explain_path_counts()[("deepshap",)] == before + 2


def test_warmup_ladder_covers_deepshap_path(cnn_setup):
    import time

    from distributedkernelshap_tpu.runtime.compile_cache import (
        compile_events,
    )
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    s = cnn_setup
    model = _fit_model(s)
    assert model.explain_path == "deepshap"
    ce = compile_events()
    before = ce.snapshot()
    srv = ExplainerServer(model, host="127.0.0.1", port=0,
                          max_batch_size=4, warmup=True,
                          health_interval_s=0).start()
    try:
        deadline = time.monotonic() + 60
        while srv.warmup_status()["state"] in ("pending", "running"):
            assert time.monotonic() < deadline, "warmup never finished"
            time.sleep(0.05)
        st = srv.warmup_status()
        assert st["state"] == "done"
        assert st["completed_buckets"] == st["buckets"] != []
        delta = ce.delta(before, ce.snapshot())
        sigs = {sig for (_, sig) in delta["counts"]}
        assert any(sig.endswith(",path=deepshap") for sig in sigs), sigs
        page = srv.metrics.render()
        assert 'dks_serve_explain_path_total{path="deepshap"}' in page
        assert "dks_deepshap_fallback_total" in page
    finally:
        srv.stop()
