"""Runtime lock-order witness (``analysis/lockwitness.py``): factory
gating by the env knob, acquisition-order edge recording, cycle
detection across threads, hold-time budgets, condition-wait accounting,
and the tier-1 smoke — a live server start/probe/stop records a
cycle-free graph over the named control-plane locks.  Full witness
sweeps (every chaos scenario under ``DKS_LOCK_WITNESS=1``) stay behind
``make chaos-bench``."""

import threading
import time
import urllib.request

import pytest

from distributedkernelshap_tpu.analysis import lockwitness


@pytest.fixture()
def witness(monkeypatch):
    """Witness ON with clean process-wide state, reset afterwards so no
    edges leak into other tests (or the conftest session teardown)."""

    monkeypatch.setenv(lockwitness.ENV_KNOB, "1")
    lockwitness.reset()
    yield lockwitness
    lockwitness.reset()


def test_disabled_by_default_returns_plain_primitives(monkeypatch):
    monkeypatch.delenv(lockwitness.ENV_KNOB, raising=False)
    assert not lockwitness.enabled()
    lock = lockwitness.make_lock("plain")
    assert not isinstance(lock, lockwitness.WitnessedLock)
    cond = lockwitness.make_condition("plain.cond")
    assert isinstance(cond, threading.Condition)
    assert not isinstance(cond._lock, lockwitness.WitnessedLock)
    # "0"/"false"/"off" also mean off
    for off in ("0", "false", "off"):
        monkeypatch.setenv(lockwitness.ENV_KNOB, off)
        assert not lockwitness.enabled()


def test_consistent_order_records_edges_and_stays_clean(witness):
    a = witness.make_lock("t.a")
    b = witness.make_lock("t.b")
    assert isinstance(a, witness.WitnessedLock)
    for _ in range(3):
        with a:
            with b:
                pass
    snap = witness.assert_clean()          # acyclic: a -> b only
    assert snap["edges"] == {("t.a", "t.b"): 3}
    assert snap["acquisitions"] == {"t.a": 3, "t.b": 3}
    assert witness.problems() == []


def test_order_inversion_across_threads_is_a_cycle(witness):
    """The TSan-lite property: the deadlock needs the threads to
    interleave, but the witness flags the ORDER inversion even on a run
    that got lucky and never hung."""

    a = witness.make_lock("t.a")
    b = witness.make_lock("t.b")
    with a:
        with b:
            pass

    def inverted():
        with b:
            with a:
                pass

    t = threading.Thread(target=inverted)
    t.start()
    t.join(5)
    issues = witness.problems()
    assert len(issues) == 1 and "cycle" in issues[0]
    assert "t.a" in issues[0] and "t.b" in issues[0]
    with pytest.raises(AssertionError, match="cycle"):
        witness.assert_clean()


def test_same_thread_inversion_is_also_caught(witness):
    a = witness.make_lock("s.a")
    b = witness.make_lock("s.b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert witness.find_cycle_in_edges(
        witness.snapshot()["edges"]) is not None


def test_hold_time_budget(witness):
    a = witness.make_lock("slow.lock")
    with a:
        time.sleep(0.05)
    assert witness.problems(max_hold_s=1.0) == []
    issues = witness.problems(max_hold_s=0.01)
    assert len(issues) == 1 and "slow.lock" in issues[0]
    assert "must not bracket blocking work" in issues[0]


def test_same_name_instances_never_fabricate_a_cycle(witness):
    """Two DISTINCT locks sharing one factory name (two models'
    ``registry.model`` conditions) must not produce a self-edge (an
    instant false cycle); the nesting is counted in the snapshot
    instead (documented limitation: their relative order is not
    verifiable through the name-keyed graph)."""

    a = witness.make_lock("model.cond")
    b = witness.make_lock("model.cond")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    snap = witness.snapshot()
    assert snap["edges"] == {}
    assert snap["same_name_nestings"] == {"model.cond": 2}
    assert witness.problems() == []
    # and each instance's release matched ITS OWN acquisition
    assert snap["acquisitions"]["model.cond"] == 4


def test_rlock_nesting(witness):
    r = witness.make_rlock("re.lock")
    with r:
        with r:
            pass
    snap = witness.snapshot()
    assert snap["acquisitions"]["re.lock"] == 2
    # re-acquiring the SAME lock is not an ordering edge
    assert snap["edges"] == {}
    assert witness.problems() == []


def test_condition_wait_releases_the_hold_clock(witness):
    """``Condition.wait`` releases through the wrapper, so a long wait
    must NOT count as a long hold (waiters hold nothing)."""

    cond = witness.make_condition("w.cond")
    with cond:
        cond.wait(0.3)
    snap = witness.snapshot()
    # two short holds (pre-wait, post-wakeup), not one 0.3 s hold
    assert snap["acquisitions"]["w.cond"] == 2
    assert snap["max_hold_s"]["w.cond"] < 0.2
    assert witness.problems(max_hold_s=0.2) == []


def test_reset_clears_all_state(witness):
    a = witness.make_lock("r.a")
    with a:
        pass
    assert witness.snapshot()["acquisitions"]
    witness.reset()
    snap = witness.snapshot()
    assert snap["edges"] == {} and snap["acquisitions"] == {}
    assert snap["overhead_s"] == 0.0


def test_overhead_is_metered(witness):
    a = witness.make_lock("o.a")
    for _ in range(100):
        with a:
            pass
    snap = witness.snapshot()
    assert 0.0 < snap["overhead_s"] < 0.5


# --------------------------------------------------------------------- #
# tier-1 smoke: live server start/probe/stop under the witness
# --------------------------------------------------------------------- #


def test_live_server_lock_graph_is_acyclic(witness):
    """The acceptance smoke: a real ``ExplainerServer`` start → health
    probe → metrics scrape → statusz render → stop cycle, with every
    named control-plane lock witnessed, must record an acyclic
    acquisition graph and respect the hold budget (30 s here: the probe
    compiles a trivial device op on first use)."""

    from distributedkernelshap_tpu.serving.server import ExplainerServer

    class _Stub:                      # /healthz probes the DEVICE, not
        pass                          # the model: a stub serves fine

    srv = ExplainerServer(_Stub(), host="127.0.0.1", port=0,
                          max_batch_size=1, health_interval_s=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        for route in ("/healthz", "/metrics", "/statusz?format=json"):
            with urllib.request.urlopen(base + route, timeout=30) as resp:
                assert resp.status == 200
    finally:
        srv.stop()
    snap = lockwitness.assert_clean(max_hold_s=30.0)
    assert snap["acquisitions"], \
        "the witness observed no named locks — the server's control " \
        "plane is no longer wired through lockwitness.make_lock"
    observed = set(snap["acquisitions"])
    assert any(name.startswith("server.") for name in observed)
    assert "scheduler.cond" in observed
