"""Tenant cost-attribution plane: CostMeter proration/caps, histogram
exemplars, label retirement, trace-sink rotation, per-tenant SLO
templating + dynamic refresh, and the serving integration (device-time
attribution, tenant counters, /debugz exemplars, unregister)."""

import json
import http.client
import threading

import numpy as np
import pytest

import distributedkernelshap_tpu.observability.tracing as tracing
from distributedkernelshap_tpu.observability.costmeter import (
    OVERFLOW_LABEL,
    CostMeter,
    dispatch_shares,
)
from distributedkernelshap_tpu.observability.metrics import (
    MetricsRegistry,
    validate_exposition,
)
from distributedkernelshap_tpu.observability.slo import (
    MAX_TENANT_SLOS,
    default_server_slos,
    tenant_slos,
)
from distributedkernelshap_tpu.observability.statusz import HealthEngine

D = 4


# --------------------------------------------------------------------- #
# CostMeter units
# --------------------------------------------------------------------- #


def _meter(**kwargs):
    reg = MetricsRegistry()
    meter = CostMeter(**kwargs)
    meter.attach_metrics(reg)
    return meter, reg


def test_settle_prorates_by_row_share_and_sums_to_total():
    meter, reg = _meter()
    tx = (100.0, 0.0)  # t0, compile seconds at dispatch
    shares = [("a", 1, "sampled", 3), ("b", 2, "exact", 1)]
    elapsed = meter.settle(tx, shares, t_end=102.0, compile_end=0.0)
    assert elapsed == pytest.approx(2.0)
    dev = reg.get("dks_device_seconds_total")
    a = dev.value(model="a", version="1", path="sampled")
    b = dev.value(model="b", version="2", path="exact")
    assert a == pytest.approx(1.5)
    assert b == pytest.approx(0.5)
    assert a + b == pytest.approx(elapsed)


def test_settle_excludes_compile_seconds():
    meter, reg = _meter()
    # 5s wall, of which 4.2s was backend compile: only 0.8s is billed
    elapsed = meter.settle((0.0, 10.0), [("a", 1, "sampled", 2)],
                           t_end=5.0, compile_end=14.2)
    assert elapsed == pytest.approx(0.8)
    assert reg.get("dks_device_seconds_total").value(
        model="a", version="1", path="sampled") == pytest.approx(0.8)


def test_settle_clamps_negative_and_handles_zero_rows():
    meter, reg = _meter()
    # compile delta larger than the wall (clock skew paranoia): clamp to 0
    assert meter.settle((0.0, 0.0), [("a", 1, "p", 1)],
                        t_end=1.0, compile_end=2.0) == 0.0
    # zero-row shares never divide by zero
    assert meter.settle((0.0, 0.0), [("a", 1, "p", 0)], t_end=1.0,
                        compile_end=0.0) == 0.0


def test_disabled_meter_is_inert():
    meter, reg = _meter(enabled=False)
    assert meter.begin() is None
    meter.settle(None, [("a", 1, "p", 1)], t_end=1.0, compile_end=0.0)
    meter.record_answer("a", 1, 0.1, False, False)
    meter.record_shed("a", "queue_full")
    meter.record_wire("a", "rx", 100)
    page = reg.render()
    assert 'model="a"' not in page
    assert validate_exposition(page) == []


def test_tenant_label_cap_overflows_explicitly():
    meter, reg = _meter(max_tenants=2)
    assert meter.label("t1") == "t1"
    assert meter.label("t2") == "t2"
    assert meter.label("t3") == OVERFLOW_LABEL  # cap reached
    assert meter.label("t1") == "t1"            # known ids still pass
    meter.record_answer("t9", 1, 0.1, False, False)
    assert reg.get("dks_tenant_requests_total").value(
        model=OVERFLOW_LABEL) == 1
    assert reg.get("dks_tenant_label_overflow_total").value() >= 2


def test_retire_tenant_frees_cap_slot_and_series():
    meter, reg = _meter(max_tenants=2)
    meter.record_answer("t1", 1, 0.1, False, False)
    meter.record_answer("t2", 2, 0.1, False, False)
    meter.settle((0.0, 0.0), [("t1", 1, "p", 1)], t_end=1.0,
                 compile_end=0.0)
    removed = meter.retire_tenant("t1")
    assert removed >= 3  # requests, rows, latency, device series at least
    assert 'model="t1"' not in reg.render()
    # the freed slot admits a new tenant instead of overflowing
    assert meter.label("t3") == "t3"


def test_retire_tenant_version_scoped_drops_only_that_version():
    meter, reg = _meter()
    meter.settle((0.0, 0.0), [("a", 1, "p", 1)], t_end=1.0, compile_end=0.0)
    meter.settle((0.0, 0.0), [("a", 2, "p", 1)], t_end=1.0, compile_end=0.0)
    meter.record_answer("a", 1, 0.1, False, False)
    assert meter.retire_tenant("a", version=1) == 1
    dev = reg.get("dks_device_seconds_total")
    assert dev.value(model="a", version="1", path="p") == 0.0
    assert dev.value(model="a", version="2", path="p") == pytest.approx(1.0)
    # version-scoped retirement keeps the tenant's scalar tallies
    assert reg.get("dks_tenant_requests_total").value(model="a") == 1


def test_dispatch_shares_aggregates_by_pinned_version():
    class RM:
        def __init__(self, mid, version, path):
            self.model_id, self.version = mid, version
            self.model = type("M", (), {"explain_path": path})()

    class P:
        def __init__(self, rows, rm=None):
            self.rows, self.model = rows, rm

    rm_a = RM("a", 1, "sampled")
    rm_b = RM("b", 3, "exact")
    shares = dispatch_shares([P(2, rm_a), P(1, rm_b), P(3, rm_a)])
    assert shares == [("a", 1, "sampled", 5), ("b", 3, "exact", 1)]
    # single-model leaders fold into the default tenant with the
    # dispatching model's path
    assert dispatch_shares([P(2), P(1)], default_path="deepshap") == \
        [(None, 0, "deepshap", 3)]


# --------------------------------------------------------------------- #
# histogram exemplars
# --------------------------------------------------------------------- #


def test_histogram_exemplars_bounded_per_bucket_and_retireable():
    reg = MetricsRegistry()
    h = reg.histogram("dks_tenant_latency_seconds", "t",
                      buckets=(0.1, 1.0), labelnames=("model",),
                      exemplar_slots=2)
    for i in range(5):
        h.observe(0.05, exemplar=f"trace{i}", model="a")
    h.observe(5.0, exemplar="slow", model="a")
    h.observe(0.5, model="a")  # no exemplar: nothing stored
    ex = h.exemplars()
    fast = [e for e in ex if e["le"] == "0.1"]
    assert len(fast) == 2  # last-K bound
    assert {e["trace_id"] for e in fast} == {"trace3", "trace4"}
    slow = [e for e in ex if e["le"] == "+Inf"]
    assert len(slow) == 1 and slow[0]["trace_id"] == "slow"
    assert all(e["labels"] == {"model": "a"} for e in ex)
    # registry-level collection sees them; retirement drops them
    assert len(reg.exemplars()) == 3
    assert reg.retire_labels("dks_tenant_latency_seconds",
                             {"model": "a"}) == 1
    assert reg.exemplars() == []
    # the text exposition never renders exemplars (format 0.0.4)
    assert validate_exposition(reg.render()) == []


def test_retire_labels_counter_gauge_and_subset_match():
    reg = MetricsRegistry()
    c = reg.counter("dks_tenant_sheds_total", "t",
                    labelnames=("model", "reason"))
    c.inc(model="a", reason="x")
    c.inc(model="a", reason="y")
    c.inc(model="b", reason="x")
    assert reg.retire_labels("dks_tenant_sheds_total", {"model": "a"}) == 2
    assert c.value(model="b", reason="x") == 1
    # unknown metric / unknown label name: 0, never an error
    assert reg.retire_labels("nope", {"model": "a"}) == 0
    assert reg.retire_labels("dks_tenant_sheds_total", {"zz": "a"}) == 0
    g = reg.gauge("dks_registry_inflight", "t", labelnames=("model",))
    g.set(3, model="a")
    assert reg.retire_labels("dks_registry_inflight", {"model": "a"}) == 1


def test_declare_retirement_and_bound_surface_in_describe():
    reg = MetricsRegistry()
    c = reg.counter("m_capped", "t", labelnames=("model",))
    c.bound_cardinality(8)
    reg.counter("m_retired", "t", labelnames=("model",))
    reg.declare_retirement("m_retired")
    by_name = {d["name"]: d for d in reg.describe()}
    assert by_name["m_capped"]["cardinality"] == "capped(8)"
    assert by_name["m_retired"]["cardinality"] == "retire-hook"
    with pytest.raises(ValueError):
        reg.declare_retirement("missing")


# --------------------------------------------------------------------- #
# trace-sink rotation
# --------------------------------------------------------------------- #


def test_trace_sink_rotates_by_size_and_counts_drops(tmp_path):
    tr = tracing.Tracer(enabled=True, sink_dir=str(tmp_path),
                        sink_max_bytes=2000, sink_max_age_s=0)
    with tr.span("padding", note="x" * 120):
        pass
    line = len(json.dumps(tr.spans()[0].to_dict())) + 1
    per_file = max(1, 2000 // line)
    for _ in range(4 * per_file):
        with tr.span("padding", note="x" * 120):
            pass
    import os

    current = tmp_path / f"spans-{os.getpid()}.jsonl"
    rotated = tmp_path / f"spans-{os.getpid()}.jsonl.1"
    assert rotated.exists() and current.exists()
    assert tr.sink_rotations_total >= 2
    # >=2 rotations displaced at least one kept generation: its spans
    # are the dropped ones
    assert tr.sink_dropped_total > 0
    # flush-per-span preserved: both files parse line-by-line
    for path in (current, rotated):
        spans = tracing.read_jsonl(str(path))
        assert spans and all(s.name == "padding" for s in spans)
    # conservation: recorded = still-on-disk + dropped
    on_disk = sum(len(tracing.read_jsonl(str(p)))
                  for p in (current, rotated))
    assert on_disk + tr.sink_dropped_total == tr.recorded_total


def test_trace_sink_rotation_disabled_by_default_bounds(tmp_path):
    tr = tracing.Tracer(enabled=True, sink_dir=str(tmp_path),
                        sink_max_bytes=0, sink_max_age_s=0)
    for _ in range(50):
        with tr.span("s"):
            pass
    assert tr.sink_rotations_total == 0
    assert tr.sink_dropped_total == 0


def test_trace_sink_rotates_by_age(tmp_path, monkeypatch):
    tr = tracing.Tracer(enabled=True, sink_dir=str(tmp_path),
                        sink_max_bytes=0, sink_max_age_s=10.0)
    with tr.span("s"):
        pass
    assert tr.sink_rotations_total == 0
    tr._sink_opened_mono -= 11.0  # age the open file past the bound
    with tr.span("s"):
        pass
    assert tr.sink_rotations_total == 1


# --------------------------------------------------------------------- #
# per-tenant SLO templating + dynamic refresh
# --------------------------------------------------------------------- #


def test_tenant_slos_template_latency_and_availability():
    slos = tenant_slos(["a", ("b", 3)])
    names = [s.name for s in slos]
    assert names == ["tenant:a_latency", "tenant:a_availability",
                     "tenant:b_latency", "tenant:b_availability"]
    lat = slos[0]
    assert lat.histogram == "dks_tenant_latency_seconds"
    assert lat.labels == {"model": "a"}
    avail = slos[3]
    assert avail.total == "dks_tenant_requests_total"
    assert avail.bad_labels == {"model": "b"}
    assert "b@v3" in avail.description


def test_tenant_slos_bounded_cardinality_guard():
    many = [f"t{i}" for i in range(MAX_TENANT_SLOS + 10)]
    slos = tenant_slos(many)
    assert len(slos) == 2 * MAX_TENANT_SLOS
    # duplicates collapse instead of burning cap slots
    assert len(tenant_slos(["a", "a", "a"])) == 2


def test_default_server_slos_tenants_extend_base_set():
    base = default_server_slos()
    with_tenants = default_server_slos(tenants=["a"])
    assert [s.name for s in with_tenants][:len(base)] == \
        [s.name for s in base]
    assert [s.name for s in with_tenants][len(base):] == \
        ["tenant:a_latency", "tenant:a_availability"]


def test_health_engine_set_slos_rebuilds_derived_rules_keeps_state():
    reg = MetricsRegistry()
    engine = HealthEngine(reg, component="server",
                          slos=default_server_slos(), interval_s=0)
    old_rules = set(engine.alerts.states())
    assert "slo_burn:availability" in old_rules
    inst = engine.alerts._alerts["slo_burn:availability"]
    inst.state = "firing"  # pretend: must survive the refresh
    engine.set_slos(default_server_slos(tenants=["a"]))
    states = engine.alerts.states()
    assert "slo_burn:tenant:a_latency" in states
    assert states["slo_burn:availability"] == "firing"
    assert {s["name"] for s in engine.slo_statuses()} >= {
        "tenant:a_latency", "tenant:a_availability"}
    # removal drops the rule with its state
    engine.set_slos(default_server_slos())
    assert "slo_burn:tenant:a_latency" not in engine.alerts.states()


def test_health_engine_explicit_rules_survive_set_slos():
    from distributedkernelshap_tpu.observability.alerts import AlertRule

    reg = MetricsRegistry()
    rule = AlertRule("custom", lambda store, now: (False, {}))
    engine = HealthEngine(reg, component="server", slos=[],
                          rules=[rule], interval_s=0)
    engine.set_slos(default_server_slos(tenants=["a"]))
    assert set(engine.alerts.states()) == {"custom"}


# --------------------------------------------------------------------- #
# serving integration
# --------------------------------------------------------------------- #


def _linear_model(seed):
    from distributedkernelshap_tpu.models import LinearPredictor
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(seed)
    W = rng.normal(size=(D, 2)).astype(np.float32)
    b = rng.normal(size=(2,)).astype(np.float32)
    bg = np.random.default_rng(99).normal(size=(8, D)).astype(np.float32)
    return BatchKernelShapModel(LinearPredictor(W, b, activation="softmax"),
                                bg, {"link": "logit", "seed": 0}, {})


def _post(host, port, body, headers=None):
    conn = http.client.HTTPConnection(host, port, timeout=60)
    try:
        conn.request("POST", "/explain", body=body,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _get(host, port, path):
    conn = http.client.HTTPConnection(host, port, timeout=60)
    try:
        conn.request("GET", path)
        return conn.getresponse().read().decode()
    finally:
        conn.close()


@pytest.fixture(scope="module")
def metered_gateway():
    from distributedkernelshap_tpu.registry import ModelRegistry
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    was_enabled = tracing.tracer().enabled
    tracing.tracer().enable()
    registry = ModelRegistry()
    registry.register("alpha", _linear_model(1))
    registry.register("beta", _linear_model(2))
    server = ExplainerServer(registry=registry, host="127.0.0.1", port=0,
                             max_batch_size=4, batch_timeout_s=0.003,
                             pipeline_depth=2,
                             cache_bytes=1 << 20).start()
    rng = np.random.default_rng(5)
    rows = {}
    for mid in ("alpha", "beta"):
        rows[mid] = rng.normal(size=(1, D)).astype(np.float32)
        for _ in range(2):
            status, _ = _post(server.host, server.port,
                              json.dumps({"array":
                                          rows[mid].tolist()}).encode(),
                              headers={"X-DKS-Model": mid})
            assert status == 200
    try:
        yield server, registry, rows
    finally:
        server.stop()
        if not was_enabled:
            tracing.tracer().disable()


def test_device_seconds_attributed_per_tenant(metered_gateway):
    server, registry, rows = metered_gateway
    dev = server.metrics.get("dks_device_seconds_total")
    a = dev.value(model="alpha", version="1", path="sampled")
    b = dev.value(model="beta", version="1", path="sampled")
    assert a > 0 and b > 0
    reqs = server.metrics.get("dks_tenant_requests_total")
    assert reqs.value(model="alpha") == 2
    assert reqs.value(model="beta") == 2
    rows_m = server.metrics.get("dks_tenant_rows_total")
    assert rows_m.value(model="alpha") == 2
    # duplicate requests hit the fingerprint-scoped cache: counted per
    # tenant, and no additional device seconds accrue
    hits = server.metrics.get("dks_tenant_cache_hits_total")
    assert hits.value(model="alpha") >= 1
    page = _get(server.host, server.port, "/metrics")
    assert validate_exposition(page) == []


def test_tenant_wire_bytes_and_debugz_exemplars(metered_gateway):
    server, registry, rows = metered_gateway
    wire = server.metrics.get("dks_tenant_wire_bytes_total")
    assert wire.value(model="alpha", direction="rx") > 0
    assert wire.value(model="alpha", direction="tx") > 0
    doc = json.loads(_get(server.host, server.port, "/debugz"))
    assert isinstance(doc["exemplars"], list) and doc["exemplars"]
    tenant_ex = [e for e in doc["exemplars"]
                 if e["metric"] == "dks_tenant_latency_seconds"]
    assert tenant_ex and all(len(e["trace_id"]) == 32 for e in tenant_ex)
    # the exemplar's trace id is followable: the in-process ring holds
    # server.request spans under the same id
    ring_ids = {s.trace_id for s in tracing.tracer().spans()}
    assert any(e["trace_id"] in ring_ids for e in tenant_ex)


def test_tenant_shed_attribution(metered_gateway):
    server, registry, rows = metered_gateway
    from distributedkernelshap_tpu.registry import TenantQuota

    gamma = _linear_model(3)
    registry.register("gamma", gamma,
                      quota=TenantQuota(max_inflight=0), warm=False)
    status, payload = _post(server.host, server.port,
                            json.dumps({"array":
                                        rows["alpha"].tolist()}).encode(),
                            headers={"X-DKS-Model": "gamma"})
    assert status == 429
    sheds = server.metrics.get("dks_tenant_sheds_total")
    assert sheds.value(model="gamma", reason="tenant_queue_full") == 1
    # other tenants' shed series untouched
    assert sheds.value(model="alpha", reason="tenant_queue_full") == 0


def test_unregister_retires_labels_and_tenant_slos(metered_gateway):
    server, registry, rows = metered_gateway
    from distributedkernelshap_tpu.registry import TenantQuota  # noqa: F401

    delta = _linear_model(4)
    registry.register("delta", delta, warm=False)
    status, _ = _post(server.host, server.port,
                      json.dumps({"array": rows["alpha"].tolist()}).encode(),
                      headers={"X-DKS-Model": "delta"})
    assert status == 200
    assert server.metrics.get("dks_tenant_requests_total").value(
        model="delta") == 1
    assert any(s.name == "tenant:delta_latency"
               for s in server.health.slos)
    registry.unregister("delta")
    page = _get(server.host, server.port, "/metrics")
    assert 'model="delta"' not in page
    assert not any(s.name.startswith("tenant:delta")
                   for s in server.health.slos)
    # routing now 404s with the remaining roster
    status, payload = _post(server.host, server.port,
                            json.dumps({"array":
                                        rows["alpha"].tolist()}).encode(),
                            headers={"X-DKS-Model": "delta"})
    assert status == 404
    assert "delta" not in json.loads(payload)["models"]


def test_hot_swap_retires_old_version_device_series(metered_gateway):
    server, registry, rows = metered_gateway
    dev = server.metrics.get("dks_device_seconds_total")
    assert dev.value(model="beta", version="1", path="sampled") > 0
    registry.register("beta", _linear_model(20), warm=False)
    # v1 drained+retired at the swap: its version-labeled series is gone
    assert dev.value(model="beta", version="1", path="sampled") == 0.0
    status, _ = _post(server.host, server.port,
                      json.dumps({"array": rows["beta"].tolist()}).encode(),
                      headers={"X-DKS-Model": "beta"})
    assert status == 200
    assert dev.value(model="beta", version="2", path="sampled") > 0
    # version-free tallies survive the swap (no counter reset)
    assert server.metrics.get("dks_tenant_requests_total").value(
        model="beta") >= 3


def test_single_model_server_attributes_to_default_and_freeze_knob():
    """One server spin covers both single-model behaviours: default-
    tenant attribution with the meter on, and the frozen write path
    with it off (the live ``enabled`` flip is exactly what the bench's
    overhead arm toggles)."""

    from distributedkernelshap_tpu.serving.server import ExplainerServer

    model = _linear_model(9)
    server = ExplainerServer(model, host="127.0.0.1", port=0,
                             max_batch_size=2, batch_timeout_s=0.003,
                             pipeline_depth=1).start()
    try:
        rng = np.random.default_rng(10)
        status, _ = _post(server.host, server.port,
                          json.dumps({"array": rng.normal(
                              size=(1, D)).astype(np.float32).tolist()}
                              ).encode())
        assert status == 200
        dev = server.metrics.get("dks_device_seconds_total")
        assert dev.value(model="default", version="0", path="sampled") > 0
        reqs = server.metrics.get("dks_tenant_requests_total")
        assert reqs.value(model="default") == 1
        page = _get(server.host, server.port, "/metrics")
        assert validate_exposition(page) == []
        # freeze: with the meter off, another request moves NOTHING in
        # the cost families (dks_serve_* accounting is untouched)
        server._costmeter.enabled = False
        before = (dev.value(model="default", version="0", path="sampled"),
                  reqs.value(model="default"))
        status, _ = _post(server.host, server.port,
                          json.dumps({"array": rng.normal(
                              size=(1, D)).astype(np.float32).tolist()}
                              ).encode())
        assert status == 200
        assert (dev.value(model="default", version="0", path="sampled"),
                reqs.value(model="default")) == before
        assert server.metrics.get("dks_serve_requests_total").value() == 2
    finally:
        server.stop()


def test_cost_metering_ctor_knob_registers_frozen_families():
    """``cost_metering=False`` (the ``DKS_COST_METER=0`` resolution)
    still registers every family — the catalog is mode-independent —
    with the meter's write path disabled.  Registration happens in
    ``__init__``, so no server start (and no engine compile) needed."""

    from distributedkernelshap_tpu.serving.server import (
        ExplainerServer,
        resolve_cost_meter_env,
    )

    server = ExplainerServer(_linear_model(7), host="127.0.0.1", port=0,
                             cost_metering=False)
    assert server._costmeter.enabled is False
    page = server.metrics.render()
    assert "dks_device_seconds_total" in page  # family registers...
    assert "dks_device_seconds_total{" not in page  # ...no series exist
    assert validate_exposition(page) == []
    assert resolve_cost_meter_env(default=True) is True  # env unset
