"""Tests for the Explainer/Explanation API (reference interface.py semantics)."""

import json

import numpy as np
import pytest

from distributedkernelshap_tpu.interface import (
    DEFAULT_DATA_KERNEL_SHAP,
    DEFAULT_META_KERNEL_SHAP,
    Explainer,
    Explanation,
    FitMixin,
    NumpyEncoder,
)


def test_default_schemas():
    assert set(DEFAULT_META_KERNEL_SHAP) == {"name", "type", "task", "explanations", "params"}
    assert DEFAULT_META_KERNEL_SHAP["type"] == ["blackbox"]
    assert set(DEFAULT_DATA_KERNEL_SHAP) == {
        "shap_values", "expected_value", "link", "categorical_names", "feature_names", "raw",
    }
    assert set(DEFAULT_DATA_KERNEL_SHAP["raw"]) == {
        "raw_prediction", "prediction", "instances", "importances",
    }


def test_explainer_meta_name_and_attrs():
    class Dummy(Explainer, FitMixin):
        def fit(self, X):
            return self

        def explain(self, X):
            return Explanation(meta=self.meta, data={"shap_values": []})

    d = Dummy()
    assert d.meta["name"] == "Dummy"
    # meta keys exposed as attributes
    assert d.params == {}


def test_explanation_attribute_access_and_json_roundtrip():
    meta = {"name": "KernelShap", "params": {"link": "logit"}}
    data = {
        "shap_values": [np.arange(6, dtype=np.float32).reshape(2, 3)],
        "expected_value": np.array([0.5]),
        "raw": {"instances": np.ones((2, 3))},
    }
    exp = Explanation(meta=meta, data=data)
    assert exp.name == "KernelShap"
    assert np.allclose(exp.shap_values[0], data["shap_values"][0])

    s = exp.to_json()
    decoded = json.loads(s)
    assert decoded["meta"]["name"] == "KernelShap"
    exp2 = Explanation.from_json(s)
    assert exp2.meta["name"] == "KernelShap"
    assert np.allclose(np.array(exp2.data["shap_values"][0]), data["shap_values"][0])


def test_from_json_invalid_payload_raises():
    with pytest.raises(ValueError, match="Invalid explanation representation"):
        Explanation.from_json('{"foo": 1}')


def test_explainer_does_not_mutate_passed_meta():
    class Dummy(Explainer):
        def __init__(self):
            super().__init__(meta=DEFAULT_META_KERNEL_SHAP)

        def explain(self, X):
            pass

    Dummy()
    assert DEFAULT_META_KERNEL_SHAP["name"] is None


def test_explanation_getitem_deprecated():
    exp = Explanation(meta={"name": "x"}, data={"shap_values": [1]})
    with pytest.warns(DeprecationWarning):
        assert exp["name"] == "x"


def test_numpy_encoder_scalars():
    payload = {
        "i": np.int64(3),
        "f": np.float32(0.5),
        "b": np.bool_(True),
        "a": np.zeros((2, 2)),
    }
    out = json.loads(json.dumps(payload, cls=NumpyEncoder))
    assert out["i"] == 3 and abs(out["f"] - 0.5) < 1e-9 and out["b"] is True
    assert out["a"] == [[0.0, 0.0], [0.0, 0.0]]


def test_numpy_encoder_jax_array():
    import jax.numpy as jnp

    out = json.loads(json.dumps({"x": jnp.ones((2,))}, cls=NumpyEncoder))
    assert out["x"] == [1.0, 1.0]
