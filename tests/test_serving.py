"""Tests for the serving layer: wrappers, HTTP server, micro-batching,
client fan-out (reference wrappers.py + serve_explanations.py semantics)."""

import json

import numpy as np
import pytest

from distributedkernelshap_tpu.interface import Explanation
from distributedkernelshap_tpu.models import LinearPredictor
from distributedkernelshap_tpu.serving import (
    BatchKernelShapModel,
    ExplainerServer,
    KernelShapModel,
    distribute_requests,
    explain_request,
    serve_explainer,
)


@pytest.fixture(scope="module")
def model_setup():
    rng = np.random.default_rng(0)
    D, K, N = 8, 2, 16
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(6, D)).astype(np.float32)
    pred = LinearPredictor(W, b, activation="softmax")
    kwargs = dict(constructor_kwargs={"link": "logit", "seed": 0},
                  fit_kwargs={})
    return dict(pred=pred, bg=bg, X=X, **kwargs)


class FakeRequest:
    """Flask-style request stand-in (the reference handlers read
    ``flask_request.json['array']``, wrappers.py:56)."""

    def __init__(self, array):
        self.json = {"array": np.asarray(array).tolist()}


def test_kernel_shap_model_single(model_setup):
    s = model_setup
    model = KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"])
    payload = model(FakeRequest(s["X"][0]))
    exp = Explanation.from_json(payload)
    sv = np.asarray(exp.data["shap_values"][0])
    assert sv.shape == (1, 8)
    total = (np.asarray(exp.data["shap_values"]).sum(-1)
             + np.asarray(exp.data["expected_value"])[:, None])
    np.testing.assert_allclose(total[:, 0],
                               np.asarray(exp.data["raw"]["raw_prediction"])[0],
                               atol=1e-4)


def test_sklearn_predictor_detection(model_setup):
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(1)
    Xtr = rng.normal(size=(100, 8))
    ytr = (Xtr.sum(1) > 0).astype(int)
    clf = LogisticRegression(max_iter=200).fit(Xtr, ytr)
    model = KernelShapModel(clf, model_setup["bg"],
                            model_setup["constructor_kwargs"], {})
    payload = model(FakeRequest(Xtr[0]))
    assert json.loads(payload)["data"]["shap_values"]


def test_batch_model_matches_singles(model_setup):
    s = model_setup
    batched = BatchKernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"])
    requests = [FakeRequest(x) for x in s["X"]]
    payloads = batched(requests)
    assert len(payloads) == len(requests)

    single = KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"])
    for i, payload in enumerate(payloads):
        got = np.asarray(json.loads(payload)["data"]["shap_values"])
        want = np.asarray(json.loads(single(requests[i]))["data"]["shap_values"])
        np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.fixture(scope="module")
def server(model_setup):
    s = model_setup
    srv = serve_explainer(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"],
                          host="127.0.0.1", port=0, max_batch_size=4)
    yield srv
    srv.stop()


def test_http_explain_roundtrip(server, model_setup):
    url = f"http://127.0.0.1:{server.port}/explain"
    payload = explain_request(url, model_setup["X"][0])
    exp = Explanation.from_json(payload)
    assert np.asarray(exp.data["shap_values"][0]).shape == (1, 8)


def test_http_fanout_batched(server, model_setup):
    url = f"http://127.0.0.1:{server.port}/explain"
    payloads = distribute_requests(url, model_setup["X"], batch_mode="ray")
    assert len(payloads) == 6
    # responses line up with their requests (micro-batching must not shuffle)
    single = KernelShapModel(model_setup["pred"], model_setup["bg"],
                             model_setup["constructor_kwargs"], model_setup["fit_kwargs"])
    for i, payload in enumerate(payloads):
        got = np.asarray(json.loads(payload)["data"]["shap_values"])
        want = np.asarray(json.loads(single(FakeRequest(model_setup["X"][i])))["data"]["shap_values"])
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_http_minibatch_mode(server, model_setup):
    url = f"http://127.0.0.1:{server.port}/explain"
    X = model_setup["X"]
    payloads = distribute_requests(url, X, batch_mode="default",
                                   minibatches=[X[:4], X[4:]])
    shapes = [np.asarray(json.loads(p)["data"]["shap_values"]).shape for p in payloads]
    assert shapes == [(2, 4, 8), (2, 2, 8)]


def test_http_error_paths(server):
    import urllib.error
    import urllib.request

    url = f"http://127.0.0.1:{server.port}"
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/nope", data=b"{}")
    assert e.value.code == 404

    req = urllib.request.Request(url + "/explain", data=b'{"wrong": 1}',
                                 headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req)
    assert e.value.code == 400
