"""Tests for the serving layer: wrappers, HTTP server, micro-batching,
client fan-out (reference wrappers.py + serve_explanations.py semantics)."""

import json

import numpy as np
import pytest

from distributedkernelshap_tpu.interface import Explanation
from distributedkernelshap_tpu.models import LinearPredictor
from distributedkernelshap_tpu.serving import (
    BatchKernelShapModel,
    ExplainerServer,
    KernelShapModel,
    distribute_requests,
    explain_request,
    serve_explainer,
)


@pytest.fixture(scope="module")
def model_setup():
    rng = np.random.default_rng(0)
    D, K, N = 8, 2, 16
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    X = rng.normal(size=(6, D)).astype(np.float32)
    pred = LinearPredictor(W, b, activation="softmax")
    kwargs = dict(constructor_kwargs={"link": "logit", "seed": 0},
                  fit_kwargs={})
    return dict(pred=pred, bg=bg, X=X, **kwargs)


class FakeRequest:
    """Flask-style request stand-in (the reference handlers read
    ``flask_request.json['array']``, wrappers.py:56)."""

    def __init__(self, array):
        self.json = {"array": np.asarray(array).tolist()}


def test_kernel_shap_model_single(model_setup):
    s = model_setup
    model = KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"])
    payload = model(FakeRequest(s["X"][0]))
    exp = Explanation.from_json(payload)
    sv = np.asarray(exp.data["shap_values"][0])
    assert sv.shape == (1, 8)
    total = (np.asarray(exp.data["shap_values"]).sum(-1)
             + np.asarray(exp.data["expected_value"])[:, None])
    np.testing.assert_allclose(total[:, 0],
                               np.asarray(exp.data["raw"]["raw_prediction"])[0],
                               atol=1e-4)


def test_sklearn_predictor_detection(model_setup):
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(1)
    Xtr = rng.normal(size=(100, 8))
    ytr = (Xtr.sum(1) > 0).astype(int)
    clf = LogisticRegression(max_iter=200).fit(Xtr, ytr)
    model = KernelShapModel(clf, model_setup["bg"],
                            model_setup["constructor_kwargs"], {})
    payload = model(FakeRequest(Xtr[0]))
    assert json.loads(payload)["data"]["shap_values"]


def test_batch_model_matches_singles(model_setup):
    s = model_setup
    batched = BatchKernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"])
    requests = [FakeRequest(x) for x in s["X"]]
    payloads = batched(requests)
    assert len(payloads) == len(requests)

    single = KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"])
    for i, payload in enumerate(payloads):
        got = np.asarray(json.loads(payload)["data"]["shap_values"])
        want = np.asarray(json.loads(single(requests[i]))["data"]["shap_values"])
        np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.fixture(scope="module")
def server(model_setup):
    s = model_setup
    srv = serve_explainer(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"],
                          host="127.0.0.1", port=0, max_batch_size=4)
    yield srv
    srv.stop()


def test_http_explain_roundtrip(server, model_setup):
    url = f"http://127.0.0.1:{server.port}/explain"
    payload = explain_request(url, model_setup["X"][0])
    exp = Explanation.from_json(payload)
    assert np.asarray(exp.data["shap_values"][0]).shape == (1, 8)


def test_http_fanout_batched(server, model_setup):
    url = f"http://127.0.0.1:{server.port}/explain"
    payloads = distribute_requests(url, model_setup["X"], batch_mode="ray")
    assert len(payloads) == 6
    # responses line up with their requests (micro-batching must not shuffle)
    single = KernelShapModel(model_setup["pred"], model_setup["bg"],
                             model_setup["constructor_kwargs"], model_setup["fit_kwargs"])
    for i, payload in enumerate(payloads):
        got = np.asarray(json.loads(payload)["data"]["shap_values"])
        want = np.asarray(json.loads(single(FakeRequest(model_setup["X"][i])))["data"]["shap_values"])
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_http_randomized_concurrent_stress(server, model_setup):
    """Seeded stress: many concurrent clients with mixed-size payloads must
    each get back exactly their own explanation — the micro-batcher coalesces
    across requests of different row counts without shuffling or mixing."""

    rng = np.random.default_rng(42)
    D = model_setup["X"].shape[1]
    requests_ = [rng.normal(size=(int(rng.integers(1, 5)), D)).astype(np.float32)
                 for _ in range(24)]
    url = f"http://127.0.0.1:{server.port}/explain"

    payloads = distribute_requests(url, np.zeros((0, D), np.float32),
                                   batch_mode="default", minibatches=requests_,
                                   max_workers=12)

    single = KernelShapModel(model_setup["pred"], model_setup["bg"],
                             model_setup["constructor_kwargs"], model_setup["fit_kwargs"])
    for x, payload in zip(requests_, payloads):
        got = np.asarray(json.loads(payload)["data"]["shap_values"])
        want = np.asarray(json.loads(single(FakeRequest(x)))["data"]["shap_values"])
        assert got.shape == (2, x.shape[0], D)
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_http_minibatch_mode(server, model_setup):
    url = f"http://127.0.0.1:{server.port}/explain"
    X = model_setup["X"]
    payloads = distribute_requests(url, X, batch_mode="default",
                                   minibatches=[X[:4], X[4:]])
    shapes = [np.asarray(json.loads(p)["data"]["shap_values"]).shape for p in payloads]
    assert shapes == [(2, 4, 8), (2, 2, 8)]


def test_explain_batch_async_matches_sync(model_setup):
    s = model_setup
    model = BatchKernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"])
    stacked = s["X"]
    sizes = [1, 2, 3]
    want = model.explain_batch(stacked, split_sizes=sizes)
    finalize = model.explain_batch_async(stacked, split_sizes=sizes)
    got = finalize()
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(json.loads(g)["data"]["shap_values"]),
            np.asarray(json.loads(w)["data"]["shap_values"]), atol=1e-6)


def test_get_explanation_async_matches_sync(model_setup):
    from distributedkernelshap_tpu.kernel_shap import KernelShap

    s = model_setup
    ex = KernelShap(s["pred"], link="logit", seed=0)
    ex.fit(s["bg"])
    engine = ex._explainer
    want = engine.get_explanation(s["X"], silent=True)
    values, info = engine.get_explanation_async(s["X"])()
    for g, w in zip(values, want):
        np.testing.assert_allclose(g, w, atol=1e-6)
    assert info["raw_prediction"].shape == (s["X"].shape[0], 2)
    assert info["expected_value"].shape == (2,)


def test_pipelined_singles_order_and_depth(model_setup):
    """max_batch_size=1 exercises the dispatch/finalize pipeline: many
    concurrent single-row requests must come back 200 and aligned with
    their instances."""

    s = model_setup
    rng = np.random.default_rng(3)
    X = rng.normal(size=(24, 8)).astype(np.float32)
    srv = serve_explainer(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"],
                          host="127.0.0.1", port=0, max_batch_size=1,
                          pipeline_depth=4)
    try:
        url = f"http://127.0.0.1:{srv.port}/explain"
        payloads = distribute_requests(url, X, max_workers=8)
        assert len(payloads) == 24
        single = KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"])
        for i in (0, 7, 23):
            got = np.asarray(json.loads(payloads[i])["data"]["shap_values"])
            want = np.asarray(json.loads(single(FakeRequest(X[i])))["data"]["shap_values"])
            np.testing.assert_allclose(got, want, atol=1e-5)
    finally:
        srv.stop()


def test_finalize_error_surfaces_500(model_setup):
    """A failure inside the async finalize must come back as a per-request
    HTTP 500, not a hung connection."""

    s = model_setup

    class BrokenAsyncModel(KernelShapModel):
        def explain_batch_async(self, instances, split_sizes=None):
            def finalize():
                raise RuntimeError("boom in finalize")
            return finalize

    model = BrokenAsyncModel(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"])
    srv = ExplainerServer(model, host="127.0.0.1", port=0, max_batch_size=1).start()
    try:
        url = f"http://127.0.0.1:{srv.port}/explain"
        with pytest.raises(RuntimeError, match="HTTP 500"):
            explain_request(url, s["X"][0])
    finally:
        srv.stop()


def test_pipeline_depth_self_calibration(model_setup):
    """pipeline_depth=None (the default) must self-calibrate at start() to
    one of the candidate depths and still serve correct answers (VERDICT r1
    #9: hand-set depths spanned a 3.7x wall-clock spread)."""

    from distributedkernelshap_tpu.serving.server import calibrate_pipeline_depth

    s = model_setup
    model = KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"],
                            s["fit_kwargs"])
    depth = calibrate_pipeline_depth(model, probes=8)
    assert depth in (2, 4, 8, 16, 24)

    # a model without the async protocol degenerates to depth 1
    class SyncOnly:
        pass

    assert calibrate_pipeline_depth(SyncOnly()) == 1

    # a hung device must not block startup: the budget expires and the
    # fallback depth is returned (the calibration thread is a daemon)
    class HungModel(KernelShapModel):
        def explain_batch_async(self, instances, split_sizes=None):
            import threading as _t

            def finalize():
                _t.Event().wait()  # never returns

            return finalize

    hung = HungModel(s["pred"], s["bg"], s["constructor_kwargs"], s["fit_kwargs"])
    assert calibrate_pipeline_depth(hung, example_array=s["bg"][:1],
                                    budget_s=1.0) == 8

    srv = ExplainerServer(model, host="127.0.0.1", port=0).start()
    try:
        assert srv.pipeline_depth in (2, 4, 8, 16, 24)
        url = f"http://127.0.0.1:{srv.port}/explain"
        payload = explain_request(url, s["X"][0])
        got = np.asarray(json.loads(payload)["data"]["shap_values"])[:, 0, :]
        want = model.explainer.explain(s["X"][:1], silent=True).shap_values
        np.testing.assert_allclose(got, np.stack([v[0] for v in want]), atol=1e-5)
    finally:
        srv.stop()


def test_serve_checkpointed_explainer(model_setup, tmp_path):
    """The serving.main --checkpoint path: save a fitted explainer, rebuild
    a serving model from it without refitting, serve, and get aligned
    answers (the reference has no explainer checkpointing at all)."""

    from distributedkernelshap_tpu.kernel_shap import KernelShap

    s = model_setup
    ex = KernelShap(s["pred"], link="logit", seed=0)
    ex.fit(s["bg"])
    want = ex.explain(s["X"], silent=True)
    path = str(tmp_path / "ckpt" / "explainer.pkl")
    ex.save(path)

    restored = KernelShap.load(path)
    model = BatchKernelShapModel.from_explainer(restored)
    srv = ExplainerServer(model, host="127.0.0.1", port=0,
                          max_batch_size=4, pipeline_depth=4).start()
    try:
        url = f"http://127.0.0.1:{srv.port}/explain"
        payloads = distribute_requests(url, s["X"])
        for i in (0, 5):
            got = np.asarray(json.loads(payloads[i])["data"]["shap_values"])[:, 0, :]
            np.testing.assert_allclose(
                got, np.stack([v[i] for v in want.shap_values]), atol=1e-5)
    finally:
        srv.stop()


def test_serving_lifted_tree_model():
    """The HTTP service works with a device-lifted GBT predictor end to end:
    responses match a direct explain and the lift actually engaged."""

    from sklearn.ensemble import GradientBoostingClassifier

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models import TreeEnsemblePredictor

    rng = np.random.default_rng(5)
    Xtr = rng.normal(size=(300, 6))
    ytr = (Xtr[:, 0] + Xtr[:, 1] > 0).astype(int)
    clf = GradientBoostingClassifier(n_estimators=10, max_depth=3,
                                     random_state=0).fit(Xtr, ytr)
    bg = Xtr[:20].astype(np.float32)
    X = Xtr[20:26].astype(np.float32)

    srv = serve_explainer(clf.predict_proba, bg, {"link": "logit", "seed": 0},
                          {}, host="127.0.0.1", port=0, max_batch_size=3)
    try:
        assert isinstance(srv.model.explainer._explainer.predictor,
                          TreeEnsemblePredictor)
        url = f"http://127.0.0.1:{srv.port}/explain"
        payloads = distribute_requests(url, X, max_workers=3)
        direct = KernelShap(clf.predict_proba, link="logit", seed=0)
        direct.fit(bg)
        want = direct.explain(X, silent=True)
        for i, payload in enumerate(payloads):
            exp = Explanation.from_json(payload)
            got = np.asarray(exp.data["shap_values"][0])[0]
            np.testing.assert_allclose(got, want.shap_values[0][i], atol=1e-4)
    finally:
        srv.stop()


def test_http_error_paths(server):
    import urllib.error
    import urllib.request

    url = f"http://127.0.0.1:{server.port}"
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/nope", data=b"{}")
    assert e.value.code == 404

    req = urllib.request.Request(url + "/explain", data=b'{"wrong": 1}',
                                 headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req)
    assert e.value.code == 400


def test_serving_exact_tree_mode():
    """A served tree regressor can run exact mode for every request via
    explain_kwargs={'nsamples': 'exact'}; responses match a direct exact
    explain."""

    from sklearn.ensemble import HistGradientBoostingRegressor

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.serving.server import serve_explainer

    rng = np.random.default_rng(8)
    X = rng.normal(size=(200, 5)).astype(np.float64)
    y = X[:, 0] - np.where(X[:, 2] > 0, 1.0, -1.0) * X[:, 3]
    gbr = HistGradientBoostingRegressor(max_iter=8, random_state=0).fit(X, y)
    bg = X[:15].astype(np.float32)
    srv = serve_explainer(gbr.predict, bg, {"seed": 0}, {}, port=0,
                          max_batch_size=4, pipeline_depth=2,
                          explain_kwargs={"nsamples": "exact"})
    try:
        url = f"http://127.0.0.1:{srv.port}/explain"
        Xe = X[100:106].astype(np.float32)
        payloads = distribute_requests(url, Xe)
        direct = KernelShap(gbr.predict, seed=0)
        direct.fit(bg)
        want = np.asarray(direct.explain(Xe, silent=True,
                                         nsamples="exact").shap_values)
        for i in range(Xe.shape[0]):
            got = np.asarray(json.loads(payloads[i])["data"]["shap_values"])
            np.testing.assert_allclose(got[:, 0, :], want[:, i, :]
                                       if want.ndim == 3 else want[i][None],
                                       atol=1e-5)
    finally:
        srv.stop()


def test_explain_kwargs_validated_at_construction(model_setup):
    s = model_setup
    with pytest.raises(ValueError, match="explain_kwargs"):
        KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"],
                        s["fit_kwargs"], explain_kwargs={"silent": False})


def test_serving_exact_interactions():
    """explain_kwargs={'nsamples': 'exact', 'interactions': True}: every
    response carries its slice of the interaction matrices, matching a
    direct explain (batched responses must re-split the tensors)."""

    from sklearn.ensemble import HistGradientBoostingRegressor

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.serving.server import serve_explainer

    rng = np.random.default_rng(9)
    X = rng.normal(size=(160, 5)).astype(np.float64)
    y = X[:, 0] - np.where(X[:, 2] > 0, 1.0, -1.0) * X[:, 3]
    gbr = HistGradientBoostingRegressor(max_iter=8, random_state=0).fit(X, y)
    bg = X[:12].astype(np.float32)
    srv = serve_explainer(
        gbr.predict, bg, {"seed": 0}, {}, port=0, max_batch_size=4,
        pipeline_depth=2,
        explain_kwargs={"nsamples": "exact", "interactions": True})
    try:
        url = f"http://127.0.0.1:{srv.port}/explain"
        Xe = X[100:106].astype(np.float32)
        payloads = distribute_requests(url, Xe)
        direct = KernelShap(gbr.predict, seed=0)
        direct.fit(bg)
        res = direct.explain(Xe, silent=True, nsamples="exact",
                             interactions=True)
        want = np.asarray(res.data["raw"]["interaction_values"][0])
        for i in range(Xe.shape[0]):
            data = json.loads(payloads[i])["data"]
            iv = data["raw"]["interaction_values"]
            assert isinstance(iv, list) and len(iv) == 1   # list of K tensors
            got = np.asarray(iv[0])
            assert got.shape == (1, 5, 5), got.shape
            np.testing.assert_allclose(got[0], want[i], atol=1e-5)
    finally:
        srv.stop()


def test_serving_interactions_require_exact_at_construction(model_setup):
    s = model_setup
    with pytest.raises(ValueError, match="exact"):
        KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"],
                        s["fit_kwargs"], explain_kwargs={"interactions": True})


def test_serving_main_flag_guards(monkeypatch, capsys):
    """serving.main must refuse incompatible flag combinations at parse
    time instead of silently misrouting (follower flags without a
    coordinator would start a stray single-host server; the single-host
    replica-fleet mode cannot honour multihost flags).  --checkpoint /
    --exact / --factory under --coordinator are deliberately ABSENT
    here: any deployment tuple serves from a pod."""

    import pytest as _pytest

    from distributedkernelshap_tpu.serving import main as serving_main

    def run(argv):
        monkeypatch.setattr("sys.argv", ["main.py"] + argv)
        with _pytest.raises(SystemExit) as exc:
            serving_main.main()
        assert exc.value.code == 2  # argparse parser.error
        return capsys.readouterr().err

    err = run(["--num_processes", "2", "--process_id", "1"])
    assert "require --coordinator" in err
    err = run(["--factory", "mod:fn", "--checkpoint", "x.pkl"])
    assert "pick one" in err
    err = run(["--replicate_results", "--lockstep"])
    assert "opposites" in err
    err = run(["--replica_procs", "2", "--coordinator", "127.0.0.1:1"])
    assert "single-host replica" in err
    err = run(["--pod_procs", "2"])
    assert "--replica_procs fleet" in err


def test_metrics_endpoint(model_setup):
    """/metrics exposes Prometheus-format serving counters (beyond the
    reference, which exports no metrics: SURVEY.md §5.5)."""

    import urllib.request

    from distributedkernelshap_tpu.serving.server import ExplainerServer
    from distributedkernelshap_tpu.serving.wrappers import BatchKernelShapModel

    model = BatchKernelShapModel(model_setup["pred"], model_setup["bg"],
                                 model_setup["constructor_kwargs"],
                                 model_setup["fit_kwargs"])
    server = ExplainerServer(model, host="127.0.0.1", port=0,
                             max_batch_size=4, pipeline_depth=2).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        distribute_requests(f"{base}/explain", model_setup["X"][:6],
                            max_workers=3)
        text = urllib.request.urlopen(f"{base}/metrics", timeout=30).read().decode()
    finally:
        server.stop()
    metrics = {line.split()[0]: float(line.split()[1])
               for line in text.splitlines() if line and not line.startswith("#")}
    assert metrics["dks_serve_requests_total"] == 6
    assert metrics["dks_serve_rows_total"] == 6
    assert metrics["dks_serve_errors_total"] == 0
    assert 1 <= metrics["dks_serve_batches_total"] <= 6
    assert metrics["dks_serve_request_seconds_sum"] > 0
    assert metrics["dks_serve_pipeline_depth"] == 2


def test_max_rows_slot_rejection_and_coalescing_cap(model_setup):
    """A model declaring max_rows (the multihost broadcast slot): single
    over-slot requests get 413 at enqueue; coalescing stops before the
    stacked batch would overflow the slot (the overflowing item is carried
    to the next batch instead of failing innocent neighbours)."""

    import json as _json
    import urllib.error
    import urllib.request

    from distributedkernelshap_tpu.serving.server import ExplainerServer
    from distributedkernelshap_tpu.serving.wrappers import BatchKernelShapModel

    model = BatchKernelShapModel(model_setup["pred"], model_setup["bg"],
                                 model_setup["constructor_kwargs"],
                                 model_setup["fit_kwargs"])
    model.max_rows = 4  # declare a tiny slot
    server = ExplainerServer(model, host="127.0.0.1", port=0,
                             max_batch_size=8, pipeline_depth=1).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        # single request larger than the slot -> 413, others unaffected
        big = _json.dumps(
            {"array": model_setup["X"][:6].tolist()}).encode()
        req = urllib.request.Request(f"{base}/explain", data=big,
                                     method="POST")
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected HTTP 413")
        except urllib.error.HTTPError as e:
            assert e.code == 413
            assert "max_rows" in e.read().decode()
        # six 1-row requests with an 8-request coalescer and a 4-row slot:
        # every request must still succeed (batches capped at 4 rows)
        payloads = distribute_requests(f"{base}/explain",
                                       model_setup["X"][:6], max_workers=6)
        assert len(payloads) == 6
        for p in payloads:
            assert _json.loads(p)["data"]["shap_values"]
    finally:
        server.stop()


def test_multihost_model_single_process_semantics(model_setup):
    """MultihostServingModel unit behaviour without a second process
    (broadcast_one_to_all is the identity at process_count()==1): payloads
    match the wrapped model, over-slot batches raise, shutdown is
    idempotent, and post-shutdown explains fail loudly instead of
    broadcasting into a dead mesh."""

    import pytest as _pytest

    from distributedkernelshap_tpu.serving.multihost import MultihostServingModel
    from distributedkernelshap_tpu.serving.wrappers import BatchKernelShapModel

    base = BatchKernelShapModel(model_setup["pred"], model_setup["bg"],
                                model_setup["constructor_kwargs"],
                                model_setup["fit_kwargs"])
    wrapped = MultihostServingModel(base, max_rows=4)
    X = model_setup["X"][:3]
    assert wrapped.explain_batch(X, split_sizes=[3]) == \
        base.explain_batch(X, split_sizes=[3])
    with _pytest.raises(ValueError, match="max_rows"):
        wrapped.explain_batch(model_setup["X"][:6], split_sizes=[6])

    wrapped.shutdown_followers()
    wrapped.shutdown_followers()  # idempotent: second call is a no-op
    with _pytest.raises(RuntimeError, match="shut down"):
        wrapped.explain_batch(X, split_sizes=[3])


# --------------------------------------------------------------------- #
# fault isolation (VERDICT r3 #4): dispatch watchdog, device-probing
# /healthz, wedge -> fast errors -> recovery
# --------------------------------------------------------------------- #

def test_healthz_round_trips_device(server):
    """/healthz must prove the device answers (a static 200 would stay
    green through a wedged relay — the motivating 19 h failure)."""

    import urllib.request

    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/healthz", timeout=10) as r:
        assert r.status == 200
        assert json.loads(r.read())["status"] == "ok"


def test_watchdog_wedge_fast_errors_and_recovery(model_setup):
    """Wedge a dispatch mid-flight: the watchdog must (a) fail the held
    request with a watchdog error instead of a hung socket, (b) flip
    /healthz to 503 and fast-503 new explains, and (c) recover — clearing
    the wedge — once device work completes again."""

    import threading
    import urllib.error
    import urllib.request

    s = model_setup

    class WedgeOnceModel(KernelShapModel):
        """First async dispatch returns a finalize that blocks until
        released (a dead-relay RPC in miniature); later calls delegate to
        the real pipeline."""

        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self.release = threading.Event()
            self.wedged_once = False

        def explain_batch_async(self, instances, split_sizes=None):
            if not self.wedged_once:
                self.wedged_once = True
                real = super().explain_batch_async(instances, split_sizes)

                def finalize():
                    self.release.wait(120)
                    return real()

                return finalize
            return super().explain_batch_async(instances, split_sizes)

    model = WedgeOnceModel(s["pred"], s["bg"], s["constructor_kwargs"],
                           s["fit_kwargs"])
    srv = ExplainerServer(model, host="127.0.0.1", port=0, max_batch_size=1,
                          # 5s: short enough to catch the deliberate wedge
                          # promptly, long enough that post-recovery explains
                          # on a loaded 1-core CI host don't re-trip it
                          pipeline_depth=2, watchdog_timeout_s=5.0,
                          first_batch_grace_s=5.0,
                          device_probe_timeout_s=30.0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        # (a) the wedged request comes back as a fast watchdog error
        with pytest.raises(RuntimeError, match="watchdog"):
            explain_request(f"{base}/explain", s["X"][0], timeout=60)
        assert srv._wedged.is_set()
        # (b) health reports the wedge; new requests fail fast with 503
        try:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["status"] == "wedged"
        with pytest.raises(RuntimeError, match="HTTP 503"):
            explain_request(f"{base}/explain", s["X"][0], timeout=10)
        # (c) release the blocked RPC: its completion is the recovery
        # signal; serving resumes and health goes green again
        model.release.set()
        # generous: the release triggers the REAL first compile of the
        # serving model, which on a loaded single-core host takes a while
        deadline = __import__("time").monotonic() + 90
        while srv._wedged.is_set():
            assert __import__("time").monotonic() < deadline, "no recovery"
            __import__("time").sleep(0.05)
        payload = explain_request(f"{base}/explain", s["X"][0], timeout=60)
        assert json.loads(payload)["data"]["shap_values"]
        with urllib.request.urlopen(f"{base}/healthz", timeout=45) as r:
            assert r.status == 200
    finally:
        srv.stop()


def test_watchdog_reset_drops_device_state(model_setup):
    """The wedge path calls model.reset(): device-resident caches must be
    dropped (dead buffer handles on a restarted backend) and the next
    explain must still be correct."""

    s = model_setup
    model = KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"],
                            s["fit_kwargs"])
    want = model.explainer.explain(s["X"][:2], silent=True).shap_values
    eng = model.explainer._explainer
    assert eng._fn_cache and eng._dev_cache  # populated by the explain
    model.reset()
    assert not eng._fn_cache and not eng._dev_cache
    got = model.explainer.explain(s["X"][:2], silent=True).shap_values
    for a, b in zip(want, got):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_handler_side_wedge_claim_counts_in_metrics():
    """A request claimed by the HANDLER-side wedge check (one the
    watchdog's scheduler drain cannot see) must still count in
    requests_total/errors_total via the shared completion-accounting
    helper — error counters must not go dark exactly during wedge
    incidents (ADVICE round 4)."""

    import threading
    import time

    class BlockingModel:
        max_rows = None

        def __init__(self):
            self.release = threading.Event()

        def explain_batch(self, instances, split_sizes=None):
            self.release.wait(30)
            sizes = split_sizes or [1] * instances.shape[0]
            return [json.dumps({"data": {"i": i}})
                    for i in range(len(sizes))]

    model = BlockingModel()
    # watchdog effectively off: the wedge is declared MANUALLY below, so
    # the only path that can fail the queued request is the handler claim
    srv = ExplainerServer(model, host="127.0.0.1", port=0,
                          max_batch_size=1, watchdog_timeout_s=3600.0,
                          first_batch_grace_s=3600.0,
                          health_interval_s=0).start()
    try:
        X = np.ones((1, 4), dtype=np.float32)
        results = {}

        def fire(key):
            try:
                results[key] = ("ok", explain_request(
                    f"http://127.0.0.1:{srv.port}/explain", X, timeout=60,
                    max_retries=0))
            except Exception as e:
                results[key] = ("err", str(e))

        t1 = threading.Thread(target=fire, args=("first",), daemon=True)
        t1.start()
        # wait until the first request is inside the blocking device call
        deadline = time.monotonic() + 10
        while not srv._active:
            assert time.monotonic() < deadline, "dispatch never started"
            time.sleep(0.01)
        t2 = threading.Thread(target=fire, args=("second",), daemon=True)
        t2.start()
        deadline = time.monotonic() + 10
        while srv._sched.qsize() == 0:
            assert time.monotonic() < deadline, "second request not queued"
            time.sleep(0.01)
        # declare the wedge: BOTH handlers (the queued request and the
        # one whose batch is held by the blocked device call) claim their
        # requests (503) and run the shared counter accounting
        srv._wedged.set()
        t2.join(timeout=15)
        t1.join(timeout=15)
        for key in ("first", "second"):
            assert results[key][0] == "err"
            assert "503" in results[key][1]
        assert srv._m_requests.value() == 2
        assert srv._m_errors.value() == 2
        assert srv._m_rows.value() == 2
        # release the device: the late completion hits _complete's
        # already-claimed recovery branch — it must clear the wedge and
        # NOT recount the claimed request (totals stay at 2/2)
        model.release.set()
        deadline = time.monotonic() + 15
        while srv._wedged.is_set():
            assert time.monotonic() < deadline, "wedge never recovered"
            time.sleep(0.02)
        assert srv._ever_completed
        assert srv._m_requests.value() == 2
        assert srv._m_errors.value() == 2
    finally:
        model.release.set()
        srv.stop()


def test_recovered_wedge_batch_sets_ever_completed():
    """A watchdog-failed FIRST batch whose device work later completes
    must set ``_ever_completed``: the next stall is judged against
    ``watchdog_timeout_s``, not the generous ``first_batch_grace_s``
    (ADVICE round 4).  An errored late completion must NOT graduate."""

    from distributedkernelshap_tpu.serving.server import _Pending

    class _Stub:
        pass

    srv = ExplainerServer(_Stub(), health_interval_s=0)  # never started
    p = _Pending(np.ones((1, 2), dtype=np.float32))
    p.done = True  # the watchdog already failed it
    batch = [p]
    srv._active[id(batch)] = batch
    srv._wedged.set()
    assert not srv._ever_completed
    srv._complete(batch, payloads=["{}"])  # late success: recovery signal
    assert srv._ever_completed
    assert not srv._wedged.is_set()
    assert id(batch) not in srv._active

    srv2 = ExplainerServer(_Stub(), health_interval_s=0)
    p2 = _Pending(np.ones((1, 2), dtype=np.float32))
    p2.done = True
    batch2 = [p2]
    srv2._active[id(batch2)] = batch2
    srv2._complete(batch2, error="device still broken")
    assert not srv2._ever_completed


def test_follower_health_listener():
    """Follower pods answer /healthz (process liveness only) so a kubelet
    liveness probe does not kill a healthy follower that correctly serves
    no explain API."""

    import urllib.request

    from distributedkernelshap_tpu.serving.multihost import (
        follower_health_server,
    )

    httpd = follower_health_server(0)
    try:
        port = httpd.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
            assert r.status == 200
            assert json.loads(r.read())["role"] == "follower"
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_watchdog_first_compile_grace(model_setup):
    """A server that has never completed a batch gets first_batch_grace_s
    (the first jit compile is ~40-140 s through a tunnel), not the
    steady-state watchdog timeout — a slow first compile must not be
    declared a wedge."""

    import threading
    import time as _time

    s = model_setup

    class SlowFirstModel(KernelShapModel):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self.first = True

        def explain_batch_async(self, instances, split_sizes=None):
            real = super().explain_batch_async(instances, split_sizes)
            if self.first:
                self.first = False

                def finalize():
                    _time.sleep(2.5)  # "compile" longer than the watchdog
                    return real()

                return finalize
            return real

    model = SlowFirstModel(s["pred"], s["bg"], s["constructor_kwargs"],
                           s["fit_kwargs"])
    srv = ExplainerServer(model, host="127.0.0.1", port=0, max_batch_size=1,
                          pipeline_depth=2, watchdog_timeout_s=1.0,
                          first_batch_grace_s=30.0).start()
    try:
        payload = explain_request(
            f"http://127.0.0.1:{srv.port}/explain", s["X"][0], timeout=30)
        assert json.loads(payload)["data"]["shap_values"]
        assert not srv._wedged.is_set()
    finally:
        srv.stop()


def test_healthz_skips_probe_while_busy(model_setup):
    """Busy is not wedged: with in-flight work progressing, /healthz must
    answer 200 without queueing a probe op behind the load."""

    s = model_setup
    model = KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"],
                            s["fit_kwargs"])
    srv = ExplainerServer(model, host="127.0.0.1", port=0,
                          pipeline_depth=2).start()
    try:
        # simulate in-flight work + recent progress, and a probe that would
        # hang if consulted
        srv._active[123] = [object()]
        srv._last_progress = __import__("time").monotonic()
        srv._device_probe_ok = lambda: (_ for _ in ()).throw(
            AssertionError("probe must be skipped while busy+progressing"))
        code, payload = srv._health()
        assert code == 200 and payload["status"] == "ok"
    finally:
        srv._active.clear()
        srv.stop()


def test_metrics_expose_wedge_counters(model_setup):
    """/metrics must carry the fault-isolation observables: a wedge
    increments dks_serve_wedges_total and flips the dks_serve_wedged gauge;
    recovery clears the gauge but not the counter."""

    import urllib.request

    s = model_setup
    model = KernelShapModel(s["pred"], s["bg"], s["constructor_kwargs"],
                            s["fit_kwargs"])
    srv = ExplainerServer(model, host="127.0.0.1", port=0,
                          pipeline_depth=2).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def scrape():
            with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
                return r.read().decode()

        text = scrape()
        assert "dks_serve_wedges_total 0" in text
        assert "dks_serve_wedged 0" in text
        # simulate the watchdog's declaration + a later recovery
        srv._wedged.set()
        srv._m_wedges.inc()
        text = scrape()
        assert "dks_serve_wedges_total 1" in text
        assert "dks_serve_wedged 1" in text
        srv._wedged.clear()
        text = scrape()
        assert "dks_serve_wedges_total 1" in text
        assert "dks_serve_wedged 0" in text
    finally:
        srv.stop()


def test_serve_multihost_pipelined_selection(model_setup):
    """serve_multihost (single-process semantics here) must select the
    PIPELINED broadcast model only when the deployment's explain options
    actually take the async fast path; otherwise it degrades loudly to
    lock-step rather than paying the in-program all-gather for nothing."""

    from distributedkernelshap_tpu.serving.multihost import (
        MultihostServingModel,
        PipelinedMultihostServingModel,
        serve_multihost,
    )

    s = model_setup
    opts = {"n_devices": 4, "replicate_results": True}

    srv = serve_multihost(s["pred"], s["bg"], {"link": "logit", "seed": 0},
                          {}, opts, host="127.0.0.1", port=0, max_rows=16,
                          pipeline_depth=3,
                          explain_kwargs={"nsamples": 64, "l1_reg": False})
    try:
        assert type(srv.model) is PipelinedMultihostServingModel
        assert srv.pipeline_depth == 3
    finally:
        srv.stop()
        srv.model.shutdown_followers()

    # exact-mode options route every request through the sync fallback:
    # lock-step protocol, depth 1, no pipelined model
    srv2 = serve_multihost(s["pred"], s["bg"], {"link": "logit", "seed": 0},
                           {}, opts, host="127.0.0.1", port=0, max_rows=16,
                           pipeline_depth=3,
                           explain_kwargs={"nsamples": "exact"})
    try:
        assert type(srv2.model) is MultihostServingModel
        assert srv2.pipeline_depth == 1
    finally:
        srv2.stop()
        srv2.model.shutdown_followers()
