"""The relay-recovery watcher state machine (``benchmarks/tpu_watch.py``)
against fake backends — the committed, tested replacement for the untracked
shell watchers (VERDICT r4 #1b / weak #6)."""

import json

import pytest

from benchmarks._evidence import (
    load_last_onchip,
    record_onchip_success,
)
from benchmarks.tpu_watch import Step, Watcher, default_steps, main


def make_watcher(probe_results, runner_log, log_records, steps=None,
                 clock=None, **kw):
    """A Watcher whose probe pops from ``probe_results``, whose runner
    appends to ``runner_log`` and always succeeds, and whose sleeps are
    instant."""

    probes = list(probe_results)

    def probe(timeout_s):
        return probes.pop(0) if probes else False

    def runner(step):
        runner_log.append(step.name)
        return {"step": step.name, "rc": 0, "timed_out": False,
                "elapsed_s": 0.1}

    return Watcher(
        steps=[Step("a", ["true"], 1), Step("b", ["true"], 1)]
        if steps is None else steps,
        probe=probe, runner=runner, sleep=lambda s: None,
        clock=clock or (lambda: 0.0), log=log_records.append, **kw)


def test_recovery_then_sweep_in_order():
    ran, logged = [], []
    w = make_watcher([False, False, True], ran, logged)
    assert w.run() == 0
    assert ran == ["a", "b"]
    states = [r["state"] for r in logged]
    # two wedged probes, then recovery, then the sweep
    assert states.count("wedged") == 2
    assert "recovered" in states
    assert states.index("recovered") < states.index("step_start")
    assert states[-1] == "sweep_done"


def test_gives_up_after_patience_budget():
    ran, logged = [], []
    t = [0.0]

    def clock():
        t[0] += 3600.0  # every probe costs an hour
        return t[0]

    w = make_watcher([False] * 100, ran, logged, clock=clock, max_hours=3.0)
    assert w.run() == 1
    assert ran == []  # the sweep never starts
    assert logged[-1]["state"] == "gave_up"


def test_sweep_continues_past_failing_step():
    logged = []
    outcomes = {"a": 1, "b": 0}

    def runner(step):
        return {"step": step.name, "rc": outcomes[step.name],
                "timed_out": False, "elapsed_s": 0.1}

    w = Watcher(steps=[Step("a", ["x"], 1), Step("b", ["x"], 1)],
                probe=lambda t: True, runner=runner, sleep=lambda s: None,
                log=logged.append)
    assert w.run() == 0  # one step succeeded
    done = [r for r in logged if r.get("state") == "step_done"]
    assert [d["step"] for d in done] == ["a", "b"]
    assert [d["rc"] for d in done] == [1, 0]


def test_sweep_only_skips_probing():
    ran, logged = [], []
    w = make_watcher([], ran, logged)  # probe would fail if consulted
    assert w.run(sweep_only=True) == 0
    assert ran == ["a", "b"]
    assert all(r.get("state") != "probing" for r in logged)


def test_all_steps_failing_exits_nonzero():
    logged = []
    w = Watcher(steps=[Step("a", ["x"], 1)], probe=lambda t: True,
                runner=lambda s: {"step": s.name, "rc": 2,
                                  "timed_out": False, "elapsed_s": 0.1},
                sleep=lambda s: None, log=logged.append)
    assert w.run() == 1


def test_default_steps_value_per_minute_order():
    names = [s.name for s in default_steps()]
    # evidence-bearing fast steps strictly before the ~80-min zoo leg
    assert names.index("fast_configs") == 0
    assert names.index("bench_contract") < names.index("model_zoo")
    assert names.index("exact_ab") < names.index("model_zoo")
    # every step is a bounded subprocess
    assert all(s.timeout_s > 0 for s in default_steps())


def test_dry_run_prints_plan(capsys):
    assert main(["--dry-run"]) == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert [l["step"] for l in lines] == [s.name for s in default_steps()]


# --------------------------------------------------------------------- #
# the shared evidence cache (benchmarks/_evidence.py)


def test_evidence_cache_roundtrip(tmp_path):
    path = str(tmp_path / "cache.json")
    rec = {"metric": "adult_2560_bg100_wall_s", "value": 0.123, "unit": "s",
           "platform": "tpu"}
    assert record_onchip_success(rec, protocol="unit-test", cache_path=path)
    loaded = load_last_onchip(cache_path=path)
    assert loaded["value"] == 0.123
    assert loaded["protocol"] == "unit-test"
    assert loaded["age_hours"] >= 0
    assert "NOT measured" in loaded["note"]


def test_evidence_cache_refuses_cpu_and_valueless(tmp_path):
    path = str(tmp_path / "cache.json")
    assert not record_onchip_success(
        {"value": 1.0, "platform": "cpu"}, protocol="x", cache_path=path)
    assert not record_onchip_success(
        {"platform": "tpu", "error": "boom"}, protocol="x", cache_path=path)
    assert load_last_onchip(cache_path=path) is None


def test_evidence_cache_corrupt_file_is_no_evidence(tmp_path):
    path = str(tmp_path / "cache.json")
    with open(path, "w") as f:
        f.write("{not json")
    assert load_last_onchip(cache_path=path) is None


@pytest.mark.parametrize("missing", ["captured_unix"])
def test_evidence_cache_missing_stamp_is_no_evidence(tmp_path, missing):
    path = str(tmp_path / "cache.json")
    with open(path, "w") as f:
        json.dump({"value": 1.0}, f)  # no captured_unix
    assert load_last_onchip(cache_path=path) is None


def test_default_steps_use_only_spelling_and_validate():
    """Steps select work with tpu_revalidate's --only (positive spelling):
    a config added later can never silently run in several sweep steps the
    way complement-of-skip strings allowed."""

    import subprocess
    import sys

    from benchmarks.tpu_revalidate import STEP_NAMES

    for s in default_steps():
        argv = list(s.argv)
        if "--only" in argv:
            names = argv[argv.index("--only") + 1].split(",")
            assert all(n in STEP_NAMES for n in names), (s.name, names)
    # the evidence-bearing serve_and_pool step precedes the ~80-min zoo leg
    names = [s.name for s in default_steps()]
    assert names.index("serve_and_pool") < names.index("model_zoo")
    # unknown names fail fast (before any backend import)
    proc = subprocess.run(
        [sys.executable,
         str(__import__('pathlib').Path(__file__).parent.parent
             / "benchmarks" / "tpu_revalidate.py"),
         "--only", "bogus_step"],
        capture_output=True, timeout=60)
    assert proc.returncode == 2
    assert b"unknown step names" in proc.stderr
