"""Streaming hot path benchmark: binary wire + staging vs JSON on the
REAL linear engine at B=1 (standalone, CPU backend, exits nonzero on
``--check`` fail).

PR 1's scheduling bench had to use a deliberately-slow *synthetic* device
to be device-bound — per-request Python/HTTP plumbing dominated the real
engine.  This bench is the proof that the streaming hot path (ISSUE 6:
binary wire protocol ``serving/wire.py``, persistent connections, buffer
donation, double-buffered host→device staging) killed that overhead: the
device model here is the REAL plan-constant-cached linear engine, no
synthetic slowdown anywhere.

Three arms, same fitted model, same request rows, open-loop B=1
interactive traffic fired above saturation (arrivals on a fixed schedule;
under overload measured goodput converges to each arm's capacity):

1. ``json``          — historical JSON clients, staging off (the pre-wire
                       baseline);
2. ``binary``        — binary wire clients, staging off (isolates the
                       protocol);
3. ``binary_staging``— binary wire + the double-buffered staging pipeline
                       (the full hot path).

``--check`` asserts, measured:

* phi **bit-identical** across all three arms (and for the JSON clients
  served mid-flight by the binary+staging server — negotiation keeps old
  clients first-class);
* ``binary_staging`` goodput ≥ 2× the ``json`` arm's (single process,
  same engine);
* the staged arm recorded nonzero ``dks_staging_overlap_seconds_total``
  and binary ``dks_wire_bytes_total`` moved;
* the engine-busy fraction of the ``binary_staging`` arm is reported and
  must own the majority (≥0.6) of the arm's wall clock: with plumbing
  gone, wall time belongs to the engine, not the HTTP stack.

Every measured run self-records into ``results/perf_history.jsonl``
(``--no-record`` opts out) with the full-hot-path arm's wall clock as
``wall_s``, so ``make perf-gate`` fails a commit that regresses streaming
goodput.

    JAX_PLATFORMS=cpu python benchmarks/streaming_bench.py --check
"""

import argparse
import http.client
import json
import sys
import threading
import time

import numpy as np

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)

N_FEATURES = 448
N_BACKGROUND = 24
SEED = 0


# --------------------------------------------------------------------- #
# timed model shim: measures engine-busy intervals without touching the
# serving path (dispatch→finalize-return per batch, union'd over overlap)
# --------------------------------------------------------------------- #


class TimedModel:
    """Delegates to a real serving model, recording one
    ``(t_dispatch, t_finalized)`` interval per device batch.  The union of
    the intervals over an arm's wall clock is the engine-busy fraction —
    the honest "is the device or the plumbing the bottleneck" number."""

    supports_wire_formats = True

    def __init__(self, inner):
        self.inner = inner
        self.intervals = []
        self._lock = threading.Lock()

    # capability surface the server probes
    def stage_rows(self, instances):
        return self.inner.stage_rows(instances)

    def explain_batch(self, instances, split_sizes=None, formats=None):
        t0 = time.monotonic()
        try:
            return self.inner.explain_batch(instances,
                                            split_sizes=split_sizes,
                                            formats=formats)
        finally:
            with self._lock:
                self.intervals.append((t0, time.monotonic()))

    def explain_batch_async(self, instances, split_sizes=None, formats=None):
        t0 = time.monotonic()
        fin = self.inner.explain_batch_async(instances,
                                             split_sizes=split_sizes,
                                             formats=formats)

        def finalize():
            try:
                return fin()
            finally:
                with self._lock:
                    self.intervals.append((t0, time.monotonic()))

        return finalize

    def reset_intervals(self):
        with self._lock:
            self.intervals = []

    def busy_seconds(self):
        """Union length of the recorded intervals (overlapping pipelined
        batches are not double-counted)."""

        with self._lock:
            spans = sorted(self.intervals)
        total, cur_start, cur_end = 0.0, None, None
        for s, e in spans:
            if cur_start is None or s > cur_end:
                if cur_start is not None:
                    total += cur_end - cur_start
                cur_start, cur_end = s, e
            else:
                cur_end = max(cur_end, e)
        if cur_start is not None:
            total += cur_end - cur_start
        return total


def build_model():
    """One fitted REAL linear model (logistic regression → the engine's
    plan-constant-cached linear fast path), shared by every arm so jit
    caches stay warm and the A/B isolates the serving plumbing."""

    from sklearn.linear_model import LogisticRegression

    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(SEED)
    X = rng.normal(size=(512, N_FEATURES)).astype(np.float32)
    y = (X[:, :4].sum(axis=1) > 0).astype(int)
    clf = LogisticRegression(max_iter=300).fit(X, y)
    # interactive-serving deployment shape: l1_reg pinned OFF (the
    # default 'auto' would route every request through the per-instance
    # host-side AIC selection — a sync-fallback path that cannot stage
    # and buries the wire A/B under host regression fits) and a
    # latency-oriented nsamples (the knob real interactive deployments
    # turn; the estimator stays the real seeded sampled KernelSHAP)
    inner = BatchKernelShapModel(clf, X[:N_BACKGROUND],
                                 {"link": "logit", "seed": SEED}, {},
                                 explain_kwargs={"l1_reg": False,
                                                 "nsamples": 512})
    return TimedModel(inner)


def make_rows(n):
    rng = np.random.default_rng(SEED + 1)
    return rng.normal(size=(n, N_FEATURES)).astype(np.float32)


def scrape_metric(port, needle, labels=None):
    """Sum the samples of one metric, optionally restricted to a label
    subset — dks_wire_bytes_total carries {format, direction}, and e.g.
    the binary-rx check must not be satisfied by json/tx bytes under the
    same family name."""

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
    finally:
        conn.close()
    from distributedkernelshap_tpu.observability.metrics import (
        parse_exposition,
    )

    total = 0.0
    for family in parse_exposition(text).values():
        for name, sample_labels, value in family["samples"]:
            if name == needle and all(
                    sample_labels.get(k) == v
                    for k, v in (labels or {}).items()):
                total += value
    return total


# --------------------------------------------------------------------- #
# open-loop traffic
# --------------------------------------------------------------------- #


def run_arm(model, rows, wire_format, staging, rate_rps, max_workers=8):
    """Serve ``rows`` as open-loop B=1 requests (arrival schedule at
    ``rate_rps``, fired regardless of completions up to the worker bound)
    and return the arm's measurement dict.  ``phi`` per request index so
    arms can be compared bit-for-bit."""

    from concurrent.futures import ThreadPoolExecutor

    from distributedkernelshap_tpu.serving import client
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    model.reset_intervals()
    # max_batch_size=1: the workload IS B=1 interactive, and identical
    # compile shapes per request across arms are what makes phi
    # bit-identity assertable (coalescing would make batch composition,
    # hence chunking, timing-dependent)
    server = ExplainerServer(
        model, host="127.0.0.1", port=0, max_batch_size=1,
        pipeline_depth=2, admission_control=False,
        health_interval_s=0, staging=staging).start()
    url = f"http://127.0.0.1:{server.port}/explain"
    client.reset_negotiation_cache()
    n = rows.shape[0]
    phi = [None] * n
    errors = []

    def one(i):
        try:
            if wire_format == "json":
                payload = client.explain_request(url, rows[i:i + 1],
                                                 timeout=120)
                doc = json.loads(payload)
                phi[i] = np.asarray(doc["data"]["shap_values"],
                                    dtype=np.float32)
            else:
                out = client.explain_request(url, rows[i:i + 1], timeout=120,
                                             wire_format="binary")
                phi[i] = np.stack(out["shap_values"])
        except Exception as e:  # counted, surfaced in --check
            errors.append(f"req {i}: {e}")

    try:
        # warmup outside the timed window: first-trace compiles + the
        # plan-constant populate must not ride either arm's clock
        for i in range(min(4, n)):
            one(i)
        # collect the previous pass's garbage outside the timed window
        # (the JSON arms allocate ~50 KB documents per request)
        import gc

        gc.collect()
        t0 = time.monotonic()
        with ThreadPoolExecutor(max_workers=max_workers) as pool:
            futures = []
            for i in range(n):
                target = t0 + i / rate_rps
                delay = target - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                futures.append(pool.submit(one, i))
            for f in futures:
                f.result()
        wall = time.monotonic() - t0
        busy = model.busy_seconds()
        result = {
            "wire_format": wire_format,
            "staging": bool(staging),
            "requests": n,
            "errors": len(errors),
            "error_sample": errors[:3],
            "wall_s": round(wall, 3),
            "goodput_rows_per_s": round((n - len(errors)) / wall, 2),
            "engine_busy_frac": round(min(1.0, busy / wall), 3),
            "wire_rx_binary_bytes": scrape_metric(
                server.port, "dks_wire_bytes_total",
                labels={"format": "binary", "direction": "rx"})
            if wire_format == "binary" else None,
            "staging_overlap_s": round(scrape_metric(
                server.port, "dks_staging_overlap_seconds_total"), 4),
        }
        # negotiation regression inside the hot arm: a historical JSON
        # client against the binary+staging server must be served the
        # same bits
        if wire_format == "binary" and staging:
            json_phi = []
            for i in range(min(4, n)):
                payload = client.explain_request(url, rows[i:i + 1],
                                                 timeout=120)
                json_phi.append(np.asarray(
                    json.loads(payload)["data"]["shap_values"],
                    dtype=np.float32))
            result["json_clients_served"] = all(
                np.array_equal(json_phi[i], phi[i])
                for i in range(len(json_phi)))
        return result, phi
    finally:
        server.stop()


def probe_rate(model, rows):
    """Closed-loop burst against a staging-off JSON server to size the
    open-loop arrival rate: every arm is then driven at ~2.5× the JSON
    arm's capacity, comfortably above saturation for the baseline and the
    hot path alike."""

    from distributedkernelshap_tpu.serving import client
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    server = ExplainerServer(
        model, host="127.0.0.1", port=0, max_batch_size=1,
        pipeline_depth=2, admission_control=False,
        health_interval_s=0).start()
    url = f"http://127.0.0.1:{server.port}/explain"
    try:
        for i in range(3):  # compile + plan-consts warmup
            client.explain_request(url, rows[i:i + 1], timeout=120)
        t0 = time.monotonic()
        n = 12
        for i in range(n):
            client.explain_request(url, rows[i % rows.shape[0]:
                                             i % rows.shape[0] + 1],
                                   timeout=120)
        return n / (time.monotonic() - t0)
    finally:
        server.stop()


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true",
                        help="exit nonzero unless every criterion holds")
    parser.add_argument("--requests", type=int, default=96,
                        help="open-loop requests per arm")
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history self-record "
                             "(results/perf_history.jsonl)")
    args = parser.parse_args()

    t_start = time.monotonic()
    # ~14 threads (client pool + server handlers + dispatcher/batcher/
    # finalizers) share 2 cores here; the default 5 ms GIL switch interval
    # produces convoy effects that dominated run-to-run variance.  1 ms is
    # the standard tune for mixed IO/compute threaded serving.
    sys.setswitchinterval(0.001)
    model = build_model()
    rows = make_rows(args.requests)
    json_serial_rps = probe_rate(model, rows)
    # 4x the serial JSON capacity: comfortably past saturation for the
    # baseline AND (with pipelining) usually past the hot path's too, so
    # measured goodput converges to each arm's capacity
    rate = 4.0 * json_serial_rps

    # the arms interleave round-robin in short passes and aggregate:
    # this box's speed drifts on a minutes timescale (shared host), so a
    # sequential one-pass-per-arm layout hands whichever arm runs in a
    # fast window a phantom win — fine-grained interleaving makes the
    # drift land on every arm nearly equally.  phi bit-identity is
    # asserted for EVERY pass of every arm.
    specs = {"json": ("json", False), "binary": ("binary", False),
             "binary_staging": ("binary", True)}
    rounds = 3
    arms = {}
    phis = {}
    totals = {name: {"wall": 0.0, "ok": 0} for name in specs}
    for r in range(rounds):
        for name, (fmt, staging) in specs.items():
            result, phi = run_arm(model, rows, fmt, staging, rate)
            totals[name]["wall"] += result["wall_s"]
            totals[name]["ok"] += result["requests"] - result["errors"]
            prev = phis.get(name)
            if prev is not None and not all(
                    a is not None and b is not None and np.array_equal(a, b)
                    for a, b in zip(prev, phi)):
                result["errors"] += 1
                result["error_sample"].append(
                    "phi differed between this arm's passes")
            if name not in arms or result["errors"] > arms[name]["errors"]:
                arms[name] = result
            phis[name] = phi
    for name, agg in totals.items():
        arms[name]["passes"] = rounds
        arms[name]["wall_s"] = round(agg["wall"], 3)
        arms[name]["goodput_rows_per_s"] = round(
            agg["ok"] / max(agg["wall"], 1e-9), 2)

    # bit-identity across every arm, per request row
    bit_identical = all(
        phis["json"][i] is not None
        and np.array_equal(phis["json"][i], phis["binary"][i])
        and np.array_equal(phis["json"][i], phis["binary_staging"][i])
        for i in range(args.requests))
    # additivity on one arm (the payloads carry link-space predictions)
    goodput_ratio = (arms["binary_staging"]["goodput_rows_per_s"]
                     / max(arms["json"]["goodput_rows_per_s"], 1e-9))
    staging_ratio = (arms["binary_staging"]["goodput_rows_per_s"]
                     / max(arms["binary"]["goodput_rows_per_s"], 1e-9))

    checks = {
        "phi_bit_identical_across_arms": bit_identical,
        "no_errors": all(a["errors"] == 0 for a in arms.values()),
        "goodput_binary_staging_ge_2x_json": goodput_ratio >= 2.0,
        "json_clients_served_by_hot_server":
            bool(arms["binary_staging"].get("json_clients_served")),
        "staging_overlap_recorded":
            arms["binary_staging"]["staging_overlap_s"] > 0.0,
        "binary_wire_bytes_recorded":
            (arms["binary_staging"]["wire_rx_binary_bytes"] or 0) > 0,
        # the engine (not the HTTP stack) owns the MAJORITY of the hot
        # arm's wall clock.  Not compared against the JSON arm: on a
        # shared-core CPU box GIL contention stretches the JSON arm's
        # engine intervals too, so its fraction is inflated, not
        # meaningful.
        "engine_is_bottleneck_in_hot_arm":
            arms["binary_staging"]["engine_busy_frac"] >= 0.6,
    }

    report = {
        "bench": "streaming_bench",
        "open_loop_rate_rps": round(rate, 1),
        "json_serial_rps": round(json_serial_rps, 1),
        "goodput_ratio_binary_staging_vs_json": round(goodput_ratio, 2),
        "goodput_ratio_staging_vs_unstaged_binary": round(staging_ratio, 2),
        "arms": arms,
        "checks": checks,
        "elapsed_s": round(time.monotonic() - t_start, 1),
    }

    if not args.no_record:
        from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

        entry = record_run(
            DEFAULT_HISTORY, "streaming_bench",
            config={"requests": args.requests, "features": N_FEATURES,
                    "background": N_BACKGROUND, "max_batch_size": 1,
                    "arms": ["json", "binary", "binary_staging"]},
            metrics={"wall_s": arms["binary_staging"]["wall_s"]},
            extra={"goodput_rows_per_s":
                   arms["binary_staging"]["goodput_rows_per_s"],
                   "goodput_ratio_vs_json": round(goodput_ratio, 2),
                   # "checks_ok" is the key regression_gate filters
                   # failed runs out of the baseline median by
                   "checks_ok": all(checks.values())})
        report["perf_history"] = {"git_sha": entry["git_sha"],
                                  "config_fp": entry["config_fp"]}

    print(json.dumps(report))
    if args.check and not all(checks.values()):
        print(json.dumps({"failed_checks":
                          [k for k, v in checks.items() if not v]}),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
