"""On-chip A/B of the fused exact-TreeSHAP Pallas kernels vs the XLA
einsum path (VERDICT r4 #3: "make exact ≤ sampled on chip").

For the Adult-GBT headline shape (B=256 instances, bg=100, M=12 groups,
HistGradientBoostingRegressor(max_iter=50)) this measures, in ONE session:

* ``nsamples='exact'`` phi with ``use_pallas=True`` and ``False``;
* exact interaction matrices under both settings;
* the sampled KernelSHAP baseline on the same model/instances —
  the number exact has to beat for the round-3 directive.

Every row carries ``kernel_path`` (recorded at trace time,
``ops/explain.capture_kernel_paths``) and the engine's ``pallas_degrades``
counter, so a Mosaic rejection that silently degrades the staged kernel to
einsum is visible in the artifact instead of masquerading as a kernel
measurement (VERDICT r4 #2/weak #6 — the round-4 shell A/B could not tell).

Appends JSON lines to ``results/exact_ab.jsonl`` and prints them.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks._evidence import REPO_ROOT, code_version  # noqa: E402

OUT = os.path.join(REPO_ROOT, "results", "exact_ab.jsonl")


def _emit(record):
    record = dict(record, ts=time.strftime("%Y-%m-%dT%H:%M:%S"),
                  code_version=code_version())
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(record) + "\n")
    print(json.dumps(record), flush=True)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true",
                        help="tiny shapes + 1 timed run: validates the "
                             "script end-to-end (e.g. on CPU) without "
                             "burning a recovery window on a bug")
    args = parser.parse_args(argv)
    smoke = args.smoke

    import jax
    import scipy.sparse as sp
    from sklearn.ensemble import HistGradientBoostingRegressor

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.kernel_shap import EngineConfig
    from distributedkernelshap_tpu.models import TreeEnsemblePredictor
    from distributedkernelshap_tpu.ops.explain import ShapConfig
    from distributedkernelshap_tpu.utils import load_data

    def emit(record):
        # EVERY row carries the smoke marker: a tiny-shape CPU validation
        # row must never be mistakable for a full B=256 on-chip measurement
        _emit(dict(record, smoke=smoke))

    emit({"step": "backend", "backend": jax.default_backend(),
          "devices": [str(d) for d in jax.devices()]})

    data = load_data()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    Xtr = data["all"]["X"]["processed"]["train"].toarray()
    ytr = data["all"]["y"]["train"].astype(np.float64)
    if smoke:
        Xtr, ytr = Xtr[:4000], ytr[:4000]
    gbr = HistGradientBoostingRegressor(max_iter=10 if smoke else 50,
                                        random_state=0).fit(Xtr, ytr)
    X = data["all"]["X"]["processed"]["test"].toarray().astype(np.float32)
    X = X[:8] if smoke else X[:256]
    bgd = data["background"]["X"]["preprocessed"]
    bg = bgd.toarray() if sp.issparse(bgd) else np.asarray(bgd)
    nruns = 1 if smoke else 3

    for pallas in (True, False):
        ex = KernelShap(gbr.predict, seed=0,
                        engine_config=EngineConfig(
                            shap=ShapConfig(use_pallas=pallas)))
        ex.fit(bg, group_names=gn, groups=g)
        assert isinstance(ex._explainer.predictor, TreeEnsemblePredictor)

        # --- exact phi -------------------------------------------------- #
        ex.explain(X, silent=True, nsamples="exact")  # warm/compile
        ts = []
        for _ in range(nruns):
            t0 = time.perf_counter()
            r = ex.explain(X, silent=True, nsamples="exact")
            ts.append(time.perf_counter() - t0)
        total = (np.asarray(r.shap_values).sum(-1).ravel()
                 + np.ravel(r.expected_value)[0])
        err = float(np.abs(total - gbr.predict(X.astype(np.float64))).max())
        emit({"step": f"exact_phi_pallas_{pallas}",
               "wall_s": round(float(np.median(ts)), 4), "model_err": err,
               "kernel_path": ex.kernel_path})

        # --- exact interactions ----------------------------------------- #
        ex.explain(X, silent=True, nsamples="exact", interactions=True)
        t0 = time.perf_counter()
        ri = ex.explain(X, silent=True, nsamples="exact", interactions=True)
        ti = time.perf_counter() - t0
        iv = ri.data["raw"]["interaction_values"][0]
        ierr = float(np.abs(iv.sum(-1) - np.asarray(ri.shap_values[0])).max())
        emit({"step": f"exact_inter_pallas_{pallas}",
               "wall_s": round(ti, 4), "rowsum_err": ierr,
               "kernel_path": ex.kernel_path})

        # --- sampled baseline (the bar exact must beat on chip) ---------- #
        if pallas:  # one measurement is enough; it shares the model
            ex.explain(X, silent=True, l1_reg=False)  # warm
            ts = []
            for _ in range(nruns):
                t0 = time.perf_counter()
                ex.explain(X, silent=True, l1_reg=False)
                ts.append(time.perf_counter() - t0)
            emit({"step": "sampled_baseline",
                   "wall_s": round(float(np.median(ts)), 4),
                   "kernel_path": ex.kernel_path})
    return 0


if __name__ == "__main__":
    sys.exit(main())
