"""Exact-TreeSHAP A/B benchmark: fused kernels, packed work scheduling,
and the serving hot path.

Three arms (``--arm adult,large,serving`` — default ``adult``, the
historical on-chip A/B):

* **adult** — the original Adult-GBT A/B of the fused exact kernels vs
  the XLA einsum path plus the sampled baseline (VERDICT r4 #3), rows
  appended to ``results/exact_ab.jsonl`` exactly as before.
* **large** — the production-ensemble arm (ISSUE 7): a synthetic
  unbalanced ensemble (default >=1000 trees, depth >= 10, mixed leaf
  counts) where the path-packed schedule (``ops/treeshap_pack.py``) is
  A/B'd against the dense einsum exact path and the sampled KernelSHAP
  estimator.  ``--check`` asserts the packed path is faster than BOTH
  and that packed phi is **bit-identical** to the dense einsum reference
  (`np.array_equal`, the engineered property of the packed einsum route).
* **serving** — exact requests on the serving hot path: a deployment
  over a lifted tree regressor must AUTO-select the exact path, stage
  rows (H2D overlapped), ride the donated batch entry, and answer with
  phi matching a direct exact explain; the engine-busy fraction is
  reported like ``streaming_bench``.

Every measured arm self-records into ``results/perf_history.jsonl`` with
``checks_ok`` (PR 6 convention) so ``make perf-gate`` covers exact-path
regressions; ``make exact-bench`` runs the large+serving arms on CPU.

Every row carries ``kernel_path`` (recorded at trace time) and the
engine's ``pallas_degrades`` counter, so a Mosaic rejection that silently
degrades the staged kernel is visible in the artifact instead of
masquerading as a kernel measurement (VERDICT r4 #2).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks._evidence import REPO_ROOT, code_version  # noqa: E402

OUT = os.path.join(REPO_ROOT, "results", "exact_ab.jsonl")


def _emit(record):
    record = dict(record, ts=time.strftime("%Y-%m-%dT%H:%M:%S"),
                  code_version=code_version())
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(record) + "\n")
    print(json.dumps(record), flush=True)


# --------------------------------------------------------------------- #
# synthetic unbalanced ensembles (the large arm's model)
# --------------------------------------------------------------------- #


def _caterpillar_table(depth, D, rng):
    """Chain tree: depth-``depth`` root path with one leaf per level —
    long paths, few leaves (the shape that used to raise the global dmax
    for every tile)."""

    n = 2 * depth + 1
    feature = np.zeros(n, np.int32)
    thr = np.full(n, np.inf, np.float32)
    left = np.arange(n, dtype=np.int32)
    right = np.arange(n, dtype=np.int32)
    value = rng.normal(size=(n, 1)).astype(np.float32)
    feats = rng.permutation(D)[:depth] if depth <= D \
        else rng.integers(D, size=depth)
    for d in range(depth):
        i = 2 * d
        feature[i] = feats[d]
        thr[i] = rng.normal()
        left[i] = i + 1
        right[i] = i + 2
    return dict(feature=feature, threshold=thr, left=left, right=right,
                value=value)


def _bushy_table(depth, D, rng):
    """Complete binary tree of ``depth``: many leaves, short paths."""

    n = 2 ** (depth + 1) - 1
    feature = np.zeros(n, np.int32)
    thr = np.full(n, np.inf, np.float32)
    left = np.arange(n, dtype=np.int32)
    right = np.arange(n, dtype=np.int32)
    value = rng.normal(size=(n, 1)).astype(np.float32)
    for i in range(2 ** depth - 1):
        feature[i] = rng.integers(D)
        thr[i] = rng.normal()
        left[i] = 2 * i + 1
        right[i] = 2 * i + 2
    return dict(feature=feature, threshold=thr, left=left, right=right,
                value=value)


def build_unbalanced_ensemble(n_bushy, bushy_depth, n_deep, deep_depth, D,
                              seed=0):
    """A ``TreeEnsemblePredictor`` with mostly-shallow bushy trees plus a
    deep caterpillar minority — the production-GBT shape where the dense
    ``(T, L_max)`` layout pads every tree to the bushiest leaf count and
    the global dmax to the deepest path."""

    from distributedkernelshap_tpu.models.trees import (
        TreeEnsemblePredictor,
        _pack_tables,
        _tree_depth,
    )

    rng = np.random.default_rng(seed)
    tables = [_bushy_table(bushy_depth, D, rng) for _ in range(n_bushy)]
    tables += [_caterpillar_table(deep_depth, D, rng) for _ in range(n_deep)]
    packed = _pack_tables(tables)
    depth = max(_tree_depth(packed["left"][i], packed["right"][i])
                for i in range(len(tables)))
    return TreeEnsemblePredictor(
        packed["feature"], packed["threshold"], packed["left"],
        packed["right"], packed["value"], depth=depth,
        max_path_flops_per_row=1 << 28)


# --------------------------------------------------------------------- #
# arms
# --------------------------------------------------------------------- #


def run_adult_arm(emit, smoke: bool) -> bool:
    """The historical Adult-GBT fused-kernel A/B (unchanged contract)."""

    import jax
    import scipy.sparse as sp
    from sklearn.ensemble import HistGradientBoostingRegressor

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.kernel_shap import EngineConfig
    from distributedkernelshap_tpu.models import TreeEnsemblePredictor
    from distributedkernelshap_tpu.ops.explain import ShapConfig
    from distributedkernelshap_tpu.utils import load_data

    del jax
    data = load_data()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    Xtr = data["all"]["X"]["processed"]["train"].toarray()
    ytr = data["all"]["y"]["train"].astype(np.float64)
    if smoke:
        Xtr, ytr = Xtr[:4000], ytr[:4000]
    gbr = HistGradientBoostingRegressor(max_iter=10 if smoke else 50,
                                        random_state=0).fit(Xtr, ytr)
    X = data["all"]["X"]["processed"]["test"].toarray().astype(np.float32)
    X = X[:8] if smoke else X[:256]
    bgd = data["background"]["X"]["preprocessed"]
    bg = bgd.toarray() if sp.issparse(bgd) else np.asarray(bgd)
    nruns = 1 if smoke else 3

    for pallas in (True, False):
        ex = KernelShap(gbr.predict, seed=0,
                        engine_config=EngineConfig(
                            shap=ShapConfig(use_pallas=pallas)))
        ex.fit(bg, group_names=gn, groups=g)
        assert isinstance(ex._explainer.predictor, TreeEnsemblePredictor)

        # --- exact phi -------------------------------------------------- #
        ex.explain(X, silent=True, nsamples="exact")  # warm/compile
        ts = []
        for _ in range(nruns):
            t0 = time.perf_counter()
            r = ex.explain(X, silent=True, nsamples="exact")
            ts.append(time.perf_counter() - t0)
        total = (np.asarray(r.shap_values).sum(-1).ravel()
                 + np.ravel(r.expected_value)[0])
        err = float(np.abs(total - gbr.predict(X.astype(np.float64))).max())
        emit({"step": f"exact_phi_pallas_{pallas}",
              "wall_s": round(float(np.median(ts)), 4), "model_err": err,
              "kernel_path": ex.kernel_path})

        # --- exact interactions ----------------------------------------- #
        ex.explain(X, silent=True, nsamples="exact", interactions=True)
        t0 = time.perf_counter()
        ri = ex.explain(X, silent=True, nsamples="exact", interactions=True)
        ti = time.perf_counter() - t0
        iv = ri.data["raw"]["interaction_values"][0]
        ierr = float(np.abs(iv.sum(-1) - np.asarray(ri.shap_values[0])).max())
        emit({"step": f"exact_inter_pallas_{pallas}",
              "wall_s": round(ti, 4), "rowsum_err": ierr,
              "kernel_path": ex.kernel_path})

        # --- sampled baseline (the bar exact must beat on chip) ---------- #
        if pallas:  # one measurement is enough; it shares the model
            ex.explain(X, silent=True, l1_reg=False)  # warm
            ts = []
            for _ in range(nruns):
                t0 = time.perf_counter()
                ex.explain(X, silent=True, l1_reg=False)
                ts.append(time.perf_counter() - t0)
            emit({"step": "sampled_baseline",
                  "wall_s": round(float(np.median(ts)), 4),
                  "kernel_path": ex.kernel_path})
    return True


def run_large_arm(emit, smoke: bool) -> bool:
    """Production-ensemble arm: packed path-parallel schedule vs the dense
    einsum exact path vs sampled KernelSHAP, on an unbalanced synthetic
    ensemble (>=1000 trees, depth >= 10 unless --smoke)."""

    import jax
    import jax.numpy as jnp

    from distributedkernelshap_tpu.kernel_shap import KernelExplainerEngine
    from distributedkernelshap_tpu.ops import treeshap as ts_ops
    from distributedkernelshap_tpu.ops.explain import capture_kernel_paths
    from distributedkernelshap_tpu.ops import groups_to_matrix

    if smoke:
        n_bushy, bushy_depth, n_deep, deep_depth = 56, 2, 8, 11
        D, N, B, B_sampled, nsamples, nruns = 16, 8, 4, 2, 32, 1
    else:
        n_bushy, bushy_depth, n_deep, deep_depth = 960, 5, 64, 12
        D, N, B, B_sampled, nsamples, nruns = 32, 24, 16, 2, 128, 3

    rng = np.random.default_rng(7)
    pred = build_unbalanced_ensemble(n_bushy, bushy_depth, n_deep,
                                     deep_depth, D, seed=7)
    T, L = pred.path_sign.shape[:2]
    G = groups_to_matrix(None, D)
    X = rng.normal(size=(B, D)).astype(np.float32)
    bg = rng.normal(size=(N, D)).astype(np.float32)
    bgw = np.ones(N, np.float32)
    budget = 1 << 25

    emit({"step": "large_model", "backend": jax.default_backend(),
          "n_trees": T, "max_leaves": L, "depth": int(pred.depth),
          "dense_paths": T * L})

    plan = ts_ops.build_packed_plan(pred, G)
    emit({"step": "large_plan", "n_live": plan.n_live,
          "n_packed": plan.n_packed, "n_dense": plan.n_dense,
          "gain": round(plan.gain, 3), "buckets": list(plan.buckets),
          "shard_balance": round(plan.shard_balance, 3)})

    reach = jax.jit(lambda b, g: ts_ops.background_reach(
        pred, b, g, target_chunk_elems=budget))(jnp.asarray(bg),
                                                jnp.asarray(G))
    packed = ts_ops.pack_reach(pred, reach, plan)

    f_dense = jax.jit(lambda Xc: ts_ops.exact_shap_from_reach(
        pred, Xc, reach, jnp.asarray(bgw), jnp.asarray(G),
        target_chunk_elems=budget, use_pallas=False))
    f_packed = jax.jit(lambda Xc: ts_ops.exact_shap_packed(
        pred, Xc, reach["onpath_g"], packed, jnp.asarray(bgw),
        jnp.asarray(G), plan.buckets, target_chunk_elems=budget))

    def timed(fn, tag):
        with capture_kernel_paths() as kp:
            ref = np.asarray(fn(X))             # warm/compile + reference
        walls = []
        for _ in range(nruns):
            t0 = time.perf_counter()
            np.asarray(fn(X))
            walls.append(time.perf_counter() - t0)
        return ref, float(np.median(walls)), dict(kp)

    phi_dense, dense_wall, kp_dense = timed(f_dense, "dense")
    phi_packed, packed_wall, kp_packed = timed(f_packed, "packed")
    bit_identical = bool(np.array_equal(phi_packed, phi_dense))
    emit({"step": "large_exact_dense_einsum", "wall_s": round(dense_wall, 4),
          "kernel_path": kp_dense})
    emit({"step": "large_exact_packed", "wall_s": round(packed_wall, 4),
          "kernel_path": kp_packed, "bit_identical": bit_identical,
          "max_abs_diff": float(np.abs(phi_packed - phi_dense).max()),
          "speedup_vs_dense": round(dense_wall / max(packed_wall, 1e-9), 3)})

    # sampled KernelSHAP on the same model — already below exact's
    # accuracy at this nsamples, and the wall-clock bar exact must beat.
    # Measured per instance at a reduced batch: the sampled estimator at
    # production-ensemble scale is exactly the cost this PR exists to
    # avoid paying per request.
    engine = KernelExplainerEngine(pred, bg, link="identity", seed=0)
    Xs = X[:B_sampled]
    engine.get_explanation(Xs, nsamples=nsamples, l1_reg=False)  # warm
    t0 = time.perf_counter()
    sampled = engine.get_explanation(Xs, nsamples=nsamples, l1_reg=False)
    sampled_wall = time.perf_counter() - t0
    sampled_phi = np.asarray(sampled)
    exact_slice = np.moveaxis(phi_packed[:B_sampled], 1, 0)  # (K, Bs, M)
    sampled_err = float(np.abs(sampled_phi - exact_slice).max())
    emit({"step": "large_sampled_baseline", "nsamples": nsamples,
          "batch": B_sampled, "wall_s": round(sampled_wall, 4),
          "per_instance_s": round(sampled_wall / B_sampled, 4),
          "err_vs_exact": sampled_err,
          "kernel_path": engine.kernel_path})

    checks = {
        # wall-clock checks gate the full-scale run only: a --smoke run's
        # ~10 ms walls are noise (and its rows are marked smoke=true)
        "packed_faster_than_dense": smoke or packed_wall < dense_wall,
        "packed_faster_than_sampled_per_instance":
            smoke or packed_wall / B < sampled_wall / B_sampled,
        "bit_identical_to_einsum": bit_identical,
        "plan_gain_gt_1": plan.gain > 1.0,
        "scale_floor": smoke or (T >= 1000 and pred.depth >= 10),
    }
    emit({"step": "large_checks", "checks": checks,
          "ok": all(checks.values())})

    from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

    entry = record_run(
        DEFAULT_HISTORY, "exact_ab_large",
        config={"n_trees": T, "max_leaves": L, "depth": int(pred.depth),
                "D": D, "N": N, "B": B, "smoke": smoke,
                "backend": __import__("jax").default_backend()},
        metrics={"wall_s": packed_wall, "dense_wall_s": dense_wall,
                 "sampled_per_instance_s": sampled_wall / B_sampled},
        extra={"checks_ok": all(checks.values()), "checks": checks,
               "plan_gain": round(plan.gain, 3),
               "kernel_path": kp_packed})
    emit({"step": "large_perf_history", "git_sha": entry["git_sha"],
          "config_fp": entry["config_fp"]})
    return all(checks.values())


def run_serving_arm(emit, smoke: bool) -> bool:
    """Exact tree requests on the serving hot path: auto-selected,
    staged, donated — not the sync fallback."""

    from sklearn.ensemble import HistGradientBoostingRegressor

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.kernel_shap import StagedRows
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )
    from benchmarks.streaming_bench import TimedModel, run_arm

    rng = np.random.default_rng(11)
    n_train = 400 if smoke else 4000
    D = 8
    Xtr = rng.normal(size=(n_train, D)).astype(np.float64)
    ytr = Xtr[:, 0] - np.where(Xtr[:, 2] > 0, 1.0, -1.0) * Xtr[:, 3]
    gbr = HistGradientBoostingRegressor(
        max_iter=8 if smoke else 50, random_state=0).fit(Xtr, ytr)
    bg = Xtr[:20].astype(np.float32)

    inner = BatchKernelShapModel(gbr.predict, bg, {"seed": 0}, {})
    auto_exact = (inner.explain_path == "exact"
                  and inner.explain_path_reason == "auto")
    rows = rng.normal(size=(24 if smoke else 96, D)).astype(np.float32)
    staged = inner.stage_rows(rows[:4])
    staged_ok = isinstance(staged, StagedRows)
    # consume the staged handle through the pipelined entry (donated
    # buffer, single packed D2H) and compare against the sync path
    async_payloads = inner.explain_batch_async(staged,
                                               split_sizes=[4])()
    sync_payloads = inner.explain_batch(rows[:4], split_sizes=[4])
    staged_bits_ok = async_payloads == sync_payloads
    emit({"step": "serving_path_selection", "auto_exact": auto_exact,
          "reason": inner.explain_path_reason, "staged": staged_ok,
          "staged_matches_sync": staged_bits_ok,
          "kernel_path": inner.explainer._explainer.kernel_path})

    # open-loop B=1 traffic against the real server with staging ON —
    # engine-busy fraction reported like streaming_bench
    model = TimedModel(inner)
    model.explain_path = inner.explain_path  # server reads it for spans
    rate = 50.0 if smoke else 100.0
    result, phi = run_arm(model, rows, "binary", staging=True,
                          rate_rps=rate)
    emit(dict({"step": "serving_exact_hot_path"}, **result))

    direct = KernelShap(gbr.predict, seed=0)
    direct.fit(bg)
    want = np.asarray(direct.explain(rows, silent=True,
                                     nsamples="exact").shap_values)
    want = want[0] if want.ndim == 3 else want
    got = np.stack([np.squeeze(np.asarray(p)) for p in phi])
    phi_ok = bool(np.allclose(got, want, atol=1e-5))

    checks = {
        "auto_exact": auto_exact,
        "stage_rows_accepts_exact": staged_ok,
        "staged_matches_sync": staged_bits_ok,
        "no_errors": result["errors"] == 0,
        "phi_matches_direct_exact": phi_ok,
        "no_pallas_degrades":
            inner.explainer._explainer.pallas_degrades == 0,
    }
    emit({"step": "serving_checks", "checks": checks,
          "ok": all(checks.values())})

    from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

    entry = record_run(
        DEFAULT_HISTORY, "exact_ab_serving",
        config={"requests": int(rows.shape[0]), "D": D, "smoke": smoke,
                "backend": __import__("jax").default_backend()},
        metrics={"wall_s": result["wall_s"],
                 "goodput_rows_per_s": result["goodput_rows_per_s"]},
        extra={"checks_ok": all(checks.values()), "checks": checks,
               "engine_busy_frac": result["engine_busy_frac"],
               "staging_overlap_s": result["staging_overlap_s"]})
    emit({"step": "serving_perf_history", "git_sha": entry["git_sha"],
          "config_fp": entry["config_fp"]})
    return all(checks.values())


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true",
                        help="tiny shapes + 1 timed run: validates the "
                             "script end-to-end (e.g. on CPU) without "
                             "burning a recovery window on a bug")
    parser.add_argument("--arm", default="adult",
                        help="comma-separated arms: adult, large, serving")
    parser.add_argument("--check", action="store_true",
                        help="exit 1 when any arm's acceptance checks fail")
    args = parser.parse_args(argv)
    smoke = args.smoke
    arms = [a.strip() for a in args.arm.split(",") if a.strip()]
    bad = sorted(set(arms) - {"adult", "large", "serving"})
    if bad:
        parser.error(f"unknown arm(s): {bad}")

    import jax

    def emit(record):
        # EVERY row carries the smoke marker: a tiny-shape CPU validation
        # row must never be mistakable for a full-scale measurement
        _emit(dict(record, smoke=smoke))

    emit({"step": "backend", "backend": jax.default_backend(),
          "devices": [str(d) for d in jax.devices()], "arms": arms})

    ok = True
    for arm in arms:
        runner = {"adult": run_adult_arm, "large": run_large_arm,
                  "serving": run_serving_arm}[arm]
        ok = runner(emit, smoke) and ok
    if args.check and not ok:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
