#!/usr/bin/env bash
# Sweep the pool benchmark across device counts.
#
# Local mode (default) runs benchmarks/pool.py on this host's devices.
# Cluster mode (MODE=cluster) mirrors the reference's
# benchmarks/k8s_benchmark_pool.sh: loop worker counts, driving the
# cluster/Makefile.pool deploy / upload-script / run-experiment /
# pull-results / destroy cycle per configuration.
#
# Usage: bash tpu_benchmark_pool.sh START END
#        MODE=cluster bash tpu_benchmark_pool.sh START END
set -euo pipefail
START=${1:?usage: [MODE=cluster] tpu_benchmark_pool.sh START END}
END=${2:?usage: [MODE=cluster] tpu_benchmark_pool.sh START END}
MODE=${MODE:-local}
MAKEFILE_DIR=$(dirname "$0")/../cluster

for workers in $(seq "$START" "$END"); do
    echo "=== workers=$workers ==="
    if [ "$MODE" = cluster ]; then
        make -C "$MAKEFILE_DIR" -f Makefile.pool deploy
        make -C "$MAKEFILE_DIR" -f Makefile.pool upload-script
        make -C "$MAKEFILE_DIR" -f Makefile.pool run-experiment WORKERS="$workers"
        make -C "$MAKEFILE_DIR" -f Makefile.pool pull-results
        make -C "$MAKEFILE_DIR" -f Makefile.pool destroy
    else
        python benchmarks/pool.py -b 1 5 10 -w "$workers" -n 5
    fi
done
