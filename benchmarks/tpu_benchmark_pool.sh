#!/usr/bin/env bash
# Sweep the pool benchmark across device counts (reference
# benchmarks/k8s_benchmark_pool.sh swept Ray worker counts with a full
# cluster redeploy per configuration; a mesh needs no redeploy).
# Usage: bash tpu_benchmark_pool.sh START END
set -euo pipefail
START=${1:?usage: tpu_benchmark_pool.sh START END}
END=${2:?usage: tpu_benchmark_pool.sh START END}
for workers in $(seq "$START" "$END"); do
    echo "=== workers=$workers ==="
    python benchmarks/pool.py -b 1 5 10 -w "$workers" -n 5
done
