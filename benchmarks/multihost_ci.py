"""CI-style multi-host validation driver (standalone, exits nonzero on fail).

Drives ``benchmarks/multihost_pool.py`` with real OS processes joining one
``jax.distributed`` runtime (2 virtual CPU devices each, collectives
crossing the process boundary over gloo — the DCN stand-in), the way the
reference's k8s Makefiles drove ``k8s_ray_pool.py`` against a live cluster
(``cluster/Makefile.pool``, ``k8s_ray_pool.py:90``).  Checks:

1. both processes exit 0 and report a 2-process / 4-device runtime;
2. the lead process wrote the reference-format result pickle;
3. the multi-process SHAP values byte-match across processes and agree with
   a single-process run of the same plan (the sequential == distributed
   oracle of SURVEY.md §4, across a real process boundary);
4. exact TreeSHAP interaction matrices byte-match across processes and
   agree with a single-process run (the psum-of-local-matrices
   decomposition, across the same boundary);
5. FOUR processes x 2 devices on a 2-D ``data(4) x coalition(2)`` mesh —
   the data axis spans processes while coalition partners are
   process-local — run the pool benchmark end-to-end (VERDICT r2 item 9);
6. the multi-host SERVING path: lead process serves HTTP over the
   2-process mesh via the broadcast protocol
   (``serving/multihost.py``), and the served shap values match a
   single-process direct explain;
7. 16-device envelope (VERDICT r3 #7 — the v5e-64 Covertype projection
   must rest on exercised shapes): ``data(4) x coalition(4)`` and
   ``data(8) x coalition(2)`` on 4 processes x 4 devices;
8. a multi-slice-shaped mesh: 2 processes x 8 devices with
   ``coalition_parallel=8`` — every coalition collective (the psum'd
   normal equations) stays process-local (the ICI analog) while the data
   axis is PURE cross-process traffic (the DCN analog), the axis layout
   of a real multi-slice deployment.

Prints ONE JSON line and exits 0/1 — suitable for cron/CI.

    python benchmarks/multihost_ci.py [--timeout 420]
"""

import argparse
import json
import os
import pickle
import socket
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_INSTANCES = 64
NSAMPLES = 64
N_DEVICES = 4

# one worker template for every in-process recipe leg (phi, interactions,
# serve): argv = (pid, coordinator_port, outdir, repo, recipe_name).  A
# recipe returning an array gets it saved per-process for byte-equality
# checks; a recipe returning None (the serve leg writes its own artifact)
# just runs.
_RECIPE_WORKER = """
import sys
sys.path.insert(0, sys.argv[4])
import jax
jax.config.update("jax_platforms", "cpu")
from distributedkernelshap_tpu.compat import force_cpu_devices
force_cpu_devices(2)
pid = int(sys.argv[1])
from distributedkernelshap_tpu.parallel.mesh import initialize_multihost
initialize_multihost("127.0.0.1:" + sys.argv[2], 2, pid)
assert jax.process_count() == 2
import numpy as np
import benchmarks.multihost_ci as ci
out = getattr(ci, sys.argv[5])()
if out is not None:
    np.save(sys.argv[3] + "/" + sys.argv[5] + "_" + str(pid) + ".npy", out)
"""


def explain_adult_slice(n_devices: int = N_DEVICES) -> np.ndarray:
    """Shared recipe: fit + explain the Adult slice on an n-device mesh."""

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    clf = load_model()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    X = data["all"]["X"]["processed"]["test"].toarray()[:N_INSTANCES]
    bg = data["background"]["X"]["preprocessed"]
    ex = KernelShap(clf.predict_proba, link="logit", feature_names=gn, seed=0,
                    distributed_opts={"n_devices": n_devices})
    ex.fit(bg, group_names=gn, groups=g)
    sv = ex.explain(X, silent=True, nsamples=NSAMPLES, l1_reg=False).shap_values
    return np.stack(sv, 1)


def rank_adult_slice(n_devices: int = N_DEVICES) -> np.ndarray:
    """Shared recipe: the device-side global-importance reduction behind
    ``KernelShap.rank_features`` over the mesh — the jitted masked slab
    reduce must hold across a REAL process boundary (round 4; only K·M
    floats reach each host).  Returns the raw ``(K, M)`` mean-|phi| matrix
    in FEATURE order (order-insensitive: comparing the ranked serialisation
    instead would flake whenever two near-tied features sort differently
    across mesh layouts; the ranking structure itself is unit-test
    territory, ``tests/test_kernel_shap.py::test_rank_features_*``)."""

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    clf = load_model()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    X = data["all"]["X"]["processed"]["test"].toarray()[:N_INSTANCES]
    bg = data["background"]["X"]["preprocessed"]
    ex = KernelShap(clf.predict_proba, link="logit", feature_names=gn, seed=0,
                    distributed_opts={"n_devices": n_devices,
                                      "batch_size": 8})
    ex.fit(bg, group_names=gn, groups=g)
    return np.asarray(ex._explainer.get_importance(
        np.asarray(X, np.float32), nsamples=NSAMPLES), np.float64)


def explain_exact_interactions_slice(n_devices: int = N_DEVICES) -> np.ndarray:
    """Shared recipe: exact TreeSHAP interaction matrices for a small GBT,
    sharded over the mesh (deterministic synthetic fit, so every process
    trains the identical model)."""

    from sklearn.ensemble import GradientBoostingRegressor

    from distributedkernelshap_tpu import KernelShap

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 5))
    y = X[:, 0] * np.where(X[:, 1] > 0, 1.0, -2.0) + 0.5 * X[:, 3]
    gbt = GradientBoostingRegressor(n_estimators=6, max_depth=3,
                                    random_state=0).fit(X, y)
    ex = KernelShap(gbt.predict, seed=0,
                    distributed_opts={"n_devices": n_devices})
    ex.fit(X[:16].astype(np.float32))
    res = ex.explain(X[:24].astype(np.float32), silent=True,
                     nsamples="exact", interactions=True)
    return np.stack(res.data["raw"]["interaction_values"], 1)


SERVE_ROWS = 12


def serve_leg(n_devices: int = N_DEVICES) -> None:
    """Per-process body of the multi-host serving leg: the lead serves HTTP
    over the mesh (``serving/multihost.py`` broadcast protocol), fans
    ``SERVE_ROWS`` single-row requests at itself, and saves the served phi
    to the working directory; followers participate via the broadcast loop
    until shutdown.  Returns None (the recipe worker skips the per-process
    save)."""

    from distributedkernelshap_tpu.serving.multihost import serve_multihost
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    clf = load_model()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    bg = data["background"]["X"]["preprocessed"]
    srv = serve_multihost(
        clf, bg, {"link": "logit", "feature_names": gn, "seed": 0},
        {"group_names": gn, "groups": g}, {"n_devices": n_devices},
        host="127.0.0.1", port=0, max_batch_size=4, max_rows=64)
    if srv is None:
        return  # follower: returns once the lead broadcasts shutdown

    import json as _json

    from distributedkernelshap_tpu.serving import client as cl

    X = data["all"]["X"]["processed"]["test"].toarray()[:SERVE_ROWS].astype(
        np.float32)
    try:
        payloads = cl.distribute_requests(
            f"http://127.0.0.1:{srv.port}/explain", X, max_workers=8)
        phi = np.stack([
            np.asarray(_json.loads(p)["data"]["shap_values"])[:, 0]
            for p in payloads])                      # (rows, K, M)
    finally:
        srv.stop()
        srv.model.shutdown_followers()
    np.save(os.path.join(os.getcwd(), "served_phi.npy"), phi)


def explain_adult_serving_defaults(rows: int = SERVE_ROWS,
                                   n_devices: int = N_DEVICES) -> np.ndarray:
    """Single-process reference for the serving leg: same rows, the serving
    path's default explain options (auto nsamples, l1_reg='auto')."""

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    clf = load_model()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    X = data["all"]["X"]["processed"]["test"].toarray()[:rows]
    ex = KernelShap(clf.predict_proba, link="logit", feature_names=gn, seed=0,
                    distributed_opts={"n_devices": n_devices})
    ex.fit(data["background"]["X"]["preprocessed"], group_names=gn, groups=g)
    sv = ex.explain(X, silent=True).shap_values
    return np.stack(sv, 1)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_procs(argv_for_pid, workdir: str, timeout: float, n_procs: int = 2,
               log_prefix: str = "proc"):
    """``n_procs`` collectively-coupled processes; logs to files (a process
    blocking on a full pipe would stall its peers inside a shared
    collective)."""

    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    logs = [os.path.join(workdir, f"{log_prefix}{pid}.log")
            for pid in range(n_procs)]
    procs = []
    try:
        for pid in range(n_procs):
            with open(logs[pid], "wb") as log:
                procs.append(subprocess.Popen(
                    argv_for_pid(pid), cwd=workdir, env=env,
                    stdout=log, stderr=subprocess.STDOUT))
        for p in procs:
            p.wait(timeout=timeout)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    # unreapable (uninterruptible syscall): keep cleaning up
                    # the peer rather than masking the original failure
                    pass
    texts = [open(log, errors="replace").read() for log in logs]
    for pid, p in enumerate(procs):
        if p.returncode != 0:
            raise RuntimeError(
                f"process {pid} exited {p.returncode}:\n{texts[pid][-2000:]}")
    return texts


def _run_two(argv_for_pid, workdir: str, timeout: float):
    return _run_procs(argv_for_pid, workdir, timeout, n_procs=2)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--timeout", default=420.0, type=float)
    args = parser.parse_args()

    checks = {}
    try:
        with tempfile.TemporaryDirectory() as tmp:

            def run_pool_leg(name: str, n_procs: int, dev_per_proc: int,
                             coalition_parallel: int = 1) -> None:
                """One pool-benchmark leg: ``n_procs`` coupled processes on
                a ``data x coalition`` mesh of ``n_procs * dev_per_proc``
                devices; asserts the runtime spanned all processes and the
                lead wrote THIS leg's reference-format result pickle."""

                workers = n_procs * dev_per_proc
                pkl = os.path.join(
                    tmp, "results",
                    f"ray_workers_{workers}_bsize_8_actorfr_1.0.pkl")
                # several legs share a worker count: a leftover pickle from
                # an earlier leg must not satisfy this leg's check
                if os.path.exists(pkl):
                    os.remove(pkl)
                port = _free_port()
                texts = _run_procs(lambda pid: [
                    sys.executable, os.path.join(REPO, "benchmarks",
                                                 "multihost_pool.py"),
                    "-b", "8", "-w", str(workers), "-n", "1", "--limit", "64",
                    "--coalition_parallel", str(coalition_parallel),
                    "--platform", "cpu", "--cpu_devices", str(dev_per_proc),
                    "--coordinator", f"127.0.0.1:{port}",
                    "--num_processes", str(n_procs),
                    "--process_id", str(pid)],
                    tmp, args.timeout, n_procs=n_procs,
                    log_prefix=f"{name}_")
                want = (f"jax.distributed initialised: {n_procs} processes, "
                        f"{workers} devices")
                for out in texts:
                    if want not in out:
                        raise RuntimeError(
                            f"{name}: runtime did not span {n_procs} "
                            f"processes:\n" + out[-1500:])
                with open(pkl, "rb") as f:
                    result = pickle.load(f)
                assert result["t_elapsed"] and result["t_elapsed"][0] > 0
                checks[name] = "ok"

            # --- leg 1: the pool benchmark across two processes ----------
            run_pool_leg("pool_benchmark_2proc", n_procs=2, dev_per_proc=2)

            # --- leg 2: cross-process phi equivalence --------------------
            worker = os.path.join(tmp, "worker.py")
            with open(worker, "w") as f:
                f.write(_RECIPE_WORKER)

            def run_recipe(name: str) -> np.ndarray:
                """Two coupled processes run recipe ``name``; byte-equality
                of their outputs asserted, the shared value returned."""

                rp = _free_port()
                _run_two(lambda pid: [
                    sys.executable, worker, str(pid), str(rp), tmp, REPO,
                    name], tmp, args.timeout)
                out0 = np.load(os.path.join(tmp, f"{name}_0.npy"))
                out1 = np.load(os.path.join(tmp, f"{name}_1.npy"))
                np.testing.assert_array_equal(out0, out1)
                return out0

            phi0 = run_recipe("explain_adult_slice")
            checks["phi_identical_across_processes"] = "ok"

            # --- leg 2b: device-side ranking across processes ------------
            rank0 = run_recipe("rank_adult_slice")
            checks["ranking_identical_across_processes"] = "ok"

            # --- leg 3: exact TreeSHAP interactions across processes -----
            iv0 = run_recipe("explain_exact_interactions_slice")
            checks["interactions_identical_across_processes"] = "ok"

            # --- leg 4: FOUR processes on a data(4) x coalition(2) mesh --
            run_pool_leg("pool_benchmark_4proc_2x2_mesh", n_procs=4,
                         dev_per_proc=2, coalition_parallel=2)

            # --- legs 4b-4d: the 16-device envelope ----------------------
            # (VERDICT r3 #7) dp4 x cp4 and dp8 x cp2 on 4 procs x 4 dev,
            # plus the multi-slice axis layout: 2 procs x 8 dev with all
            # coalition collectives process-local ("ICI") and the data
            # axis purely cross-process ("DCN").
            run_pool_leg("pool_16dev_dp4xcp4", n_procs=4, dev_per_proc=4,
                         coalition_parallel=4)
            run_pool_leg("pool_16dev_dp8xcp2", n_procs=4, dev_per_proc=4,
                         coalition_parallel=2)
            run_pool_leg("pool_16dev_multislice_dp2xcp8", n_procs=2,
                         dev_per_proc=8, coalition_parallel=8)

            # --- leg 5: multi-host SERVING over the broadcast protocol ---
            sp = _free_port()
            _run_procs(lambda pid: [
                sys.executable, worker, str(pid), str(sp), tmp, REPO,
                "serve_leg"], tmp, args.timeout, n_procs=2,
                log_prefix="serve_")
            served_phi = np.load(os.path.join(tmp, "served_phi.npy"))
            checks["serve_2proc_mesh"] = "ok"

            # single-process reference on this process's own devices
            import jax

            jax.config.update("jax_platforms", "cpu")
            from distributedkernelshap_tpu.compat import force_cpu_devices
            force_cpu_devices(N_DEVICES)
            np.testing.assert_allclose(phi0, explain_adult_slice(), atol=1e-5)
            checks["phi_matches_single_process"] = "ok"
            np.testing.assert_allclose(rank0, rank_adult_slice(), atol=1e-5)
            checks["ranking_matches_single_process"] = "ok"
            np.testing.assert_allclose(iv0, explain_exact_interactions_slice(),
                                       atol=1e-5)
            checks["interactions_match_single_process"] = "ok"
            np.testing.assert_allclose(
                served_phi, explain_adult_serving_defaults(), atol=1e-5)
            checks["served_phi_matches_single_process"] = "ok"
    except Exception as e:  # noqa: BLE001 - CI driver reports, never raises
        checks["error"] = f"{type(e).__name__}: {e}"
        print(json.dumps({"multihost_ci": "fail", **checks}))
        return 1

    print(json.dumps({"multihost_ci": "ok", **checks}))
    return 0


if __name__ == "__main__":
    main_rc = main()
    sys.exit(main_rc)
