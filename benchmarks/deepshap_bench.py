"""DeepSHAP attribution bench + acceptance gate (``make deepshap-bench``).

Three phases over the deep-model attribution engine (ISSUE 12,
``attribution/deepshap.py``), each riding the REAL engine/serving paths:

1. **Exactness** — DeepSHAP phi through the fitted engine
   (``nsamples='exact'``) vs brute-force ``2^M`` Shapley enumeration (an
   independent numpy oracle) on piecewise-linear nets at small M: a
   non-negative Conv/Relu/Dense CNN over superpixel groups
   (coalition-stable ⇒ exact) and a feature-wise Relu MLP with
   mixed-sign weights (additive ⇒ exact, Relus genuinely clip); plus
   exact completeness on a general mixed-sign Conv+BN+MaxPool net where
   DeepSHAP is the documented approximation.

2. **Matched-error speedup** — on a coalition-stable 28×28 CNN of the
   MNIST architecture (two conv layers, M=16 superpixels) the sampled
   estimator is swept across budgets with ground truth from the
   full-enumeration plan (``plan.exact``: WLS over all 2^M-2 coalitions
   — exact Shapley, PR 9's parity regime).  DeepSHAP must sit at the
   f32-rounding floor against that truth, and the ≥10× criterion
   follows PR 9's matched-error convention: DeepSHAP's error is
   deterministic, while a sampled estimate's error is a random variable
   the estimator can only CERTIFY down to analytic level by enumerating
   — so the certified matched-error arm is the enumeration plan, and
   its per-instance wall is what matching DeepSHAP's certainty actually
   costs.  Sub-enumeration budgets on this (secretly linear-in-mask)
   game also floor out — a property of the degenerate game, not
   something the estimator can know without the very enumeration it
   skipped — and the bench reports that uncertified floor-match ratio
   alongside (measured ≈11× at n=128), so nothing hides behind the
   convention.

3. **Serving** — the dormant vision scenario opened end to end: a
   trained MNIST-scale CNN tenant (logits head) with superpixel
   grouping, registered through ``ModelRegistry``, warmed through the
   ladder (compile signatures ``model=mnist_cnn@v1,rows=<b>,
   path=deepshap``), explained over the BINARY wire protocol at
   interactive SLO; group phi sums to ``f(x) - E[f]`` on the wire,
   repeats are bit-identical via the content-fingerprint result cache,
   and ``dks_serve_explain_path_total{path="deepshap"}`` attributes the
   traffic.

``--check`` exits nonzero unless every criterion holds; every measured
run self-records into ``results/perf_history.jsonl`` with ``checks_ok``
so ``make perf-gate`` covers attribution-path regressions.

    JAX_PLATFORMS=cpu python benchmarks/deepshap_bench.py --check
"""

import argparse
import http.client
import json
import sys
import threading
import time

import numpy as np

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)

from benchmarks.regression_gate import (  # noqa: E402
    DEFAULT_HISTORY,
    record_run,
)
from benchmarks.scheduling_bench import (  # noqa: E402
    percentile,
    scrape_metrics,
)

#: interactive SLO bound on the serving phase's warm p95 (seconds) —
#: matches the repo's interactive latency SLO threshold
SERVING_P95_SLO_S = 0.5
#: exactness tolerance, relative to the phi scale
EXACT_RTOL = 1e-4
#: required per-instance speedup of DeepSHAP over the matched-error
#: sampled arm (the acceptance criterion's floor)
MIN_SPEEDUP = 10.0


# --------------------------------------------------------------------- #
# model builders (deterministic; graphs via registry/onnx_lift so the
# bench exercises the exact structures ONNX ingest produces)
# --------------------------------------------------------------------- #


def _superpixel_G(side, patch, channels=1):
    from distributedkernelshap_tpu.ops.explain import groups_to_matrix
    from distributedkernelshap_tpu.ops.image import superpixel_groups

    groups, names = superpixel_groups(side, side, patch=patch,
                                      channels=channels)
    return groups, names, groups_to_matrix(groups,
                                           side * side * channels)


def build_stable_cnn_spec(side, seed=0, K=3, channels_out=(4,),
                          nonneg=True, batchnorm=False, maxpool=False):
    """Conv/Relu(+BN/MaxPool)/Dense graph over ``side×side`` pixels.
    ``nonneg=True`` keeps conv weights/biases non-negative: over
    non-negative pixels every pre-activation stays non-negative across
    the WHOLE coalition cube, the Relus never switch, and DeepSHAP is
    exactly Shapley (the coalition-stable regime)."""

    from distributedkernelshap_tpu.registry.onnx_lift import (
        GraphSpec,
        NodeSpec,
    )

    rng = np.random.default_rng(seed)

    def maybe(a):
        return np.abs(a) if nonneg else a

    D = side * side
    inits = {"shape_img": np.asarray([0, side, side, 1], np.int64)}
    nodes = [
        NodeSpec("Reshape", ("x", "shape_img"), ("img",), {}),
        NodeSpec("Transpose", ("img",), ("t0",), {"perm": [0, 3, 1, 2]}),
    ]
    tensor, c_in, feat = "t0", 1, side
    for i, c_out in enumerate(channels_out):
        inits[f"W{i}"] = maybe(rng.normal(
            scale=0.4, size=(c_out, c_in, 3, 3))).astype(np.float32)
        inits[f"b{i}"] = maybe(rng.normal(
            scale=0.1, size=c_out)).astype(np.float32)
        nodes.append(NodeSpec("Conv", (tensor, f"W{i}", f"b{i}"),
                              (f"c{i}",),
                              {"strides": [2, 2], "pads": [1, 1, 1, 1]},
                              f"conv{i}"))
        tensor, c_in, feat = f"c{i}", c_out, -(-feat // 2)
        if batchnorm:
            inits.update({
                f"s{i}": rng.uniform(0.5, 1.5, c_out).astype(np.float32),
                f"o{i}": rng.normal(scale=0.1,
                                    size=c_out).astype(np.float32),
                f"m{i}": rng.normal(scale=0.1,
                                    size=c_out).astype(np.float32),
                f"v{i}": rng.uniform(0.5, 1.5, c_out).astype(np.float32)})
            nodes.append(NodeSpec(
                "BatchNormalization",
                (tensor, f"s{i}", f"o{i}", f"m{i}", f"v{i}"),
                (f"n{i}",), {"epsilon": 1e-5}))
            tensor = f"n{i}"
        nodes.append(NodeSpec("Relu", (tensor,), (f"r{i}",), {}))
        tensor = f"r{i}"
    if maxpool:
        nodes.append(NodeSpec("MaxPool", (tensor,), ("mp",),
                              {"kernel_shape": [2, 2], "strides": [2, 2]}))
        tensor, feat = "mp", feat // 2
    nodes.append(NodeSpec("Flatten", (tensor,), ("fl",), {"axis": 1}))
    inits["Wd"] = rng.normal(scale=0.3, size=(c_in * feat * feat,
                                              K)).astype(np.float32)
    inits["bd"] = rng.normal(scale=0.1, size=K).astype(np.float32)
    nodes.append(NodeSpec("Gemm", ("fl", "Wd", "bd"), ("y",), {}))
    return GraphSpec(nodes, inits, "x", "y", D)


def build_additive_mlp_spec(seed=0, M=12, H=24, K=2):
    """Feature-wise Relu MLP (each hidden unit reads ONE feature),
    mixed-sign: additive across features, so DeepSHAP is exact while the
    Relus genuinely clip (a nonlinearity the stable CNN never exercises)."""

    from distributedkernelshap_tpu.registry.onnx_lift import (
        GraphSpec,
        NodeSpec,
    )

    rng = np.random.default_rng(seed)
    W1 = np.zeros((M, H), np.float32)
    for j in range(H):
        W1[j % M, j] = rng.normal()
    return GraphSpec(
        [NodeSpec("Gemm", ("x", "W1", "b1"), ("h",), {}),
         NodeSpec("Relu", ("h",), ("a",), {}),
         NodeSpec("Gemm", ("a", "W2", "b2"), ("y",), {})],
        {"W1": W1, "b1": rng.normal(size=H).astype(np.float32),
         "W2": rng.normal(scale=0.5, size=(H, K)).astype(np.float32),
         "b2": rng.normal(size=K).astype(np.float32)},
        "x", "y", M)


def _fit_engine(spec, bg, seed=0, groups=None, names=None):
    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.registry.onnx_lift import lift_graph

    ex = KernelShap(lift_graph(spec), seed=seed)
    ex.fit(bg, groups=groups, group_names=names)
    return ex


def _phi_matrix(values):
    vals = values if isinstance(values, list) else [values]
    return np.stack([np.asarray(v) for v in vals], 1)  # (B, K, M)


# --------------------------------------------------------------------- #
# phase 1: exactness vs the independent brute-force oracle
# --------------------------------------------------------------------- #


def run_exactness_phase():
    from distributedkernelshap_tpu.attribution.deepshap import (
        brute_force_shapley,
    )
    from distributedkernelshap_tpu.registry.onnx_lift import (
        run_graph_reference,
    )

    rng = np.random.default_rng(42)
    out = {}

    # (a) coalition-stable Conv/Relu/Dense CNN over superpixel groups
    spec = build_stable_cnn_spec(side=6, seed=1, nonneg=True)
    groups, names, G = _superpixel_G(6, patch=2)     # M = 9 -> 2^9 oracle
    bg = rng.uniform(0, 1, size=(3, 36)).astype(np.float32)
    X = rng.uniform(0, 1, size=(2, 36)).astype(np.float32)
    ex = _fit_engine(spec, bg, groups=groups, names=names)
    phi = _phi_matrix(ex.explain(X, nsamples="exact", silent=True)
                      .shap_values)
    errs = []
    for i in range(X.shape[0]):
        ref = brute_force_shapley(
            lambda r: run_graph_reference(spec, r), X[i], bg, G=G)
        errs.append(float(np.abs(phi[i] - ref).max()
                          / max(np.abs(ref).max(), 1e-9)))
    out["stable_cnn_rel_err"] = max(errs)
    out["stable_cnn_path"] = ex.kernel_path.get("exact_phi")

    # (b) additive mixed-sign Relu MLP (the Relus actively clip)
    spec_mlp = build_additive_mlp_spec(seed=2)
    bg2 = rng.normal(size=(4, 12)).astype(np.float32)
    X2 = rng.normal(size=(2, 12)).astype(np.float32)
    ex2 = _fit_engine(spec_mlp, bg2)
    phi2 = _phi_matrix(ex2.explain(X2, nsamples="exact", silent=True)
                       .shap_values)
    errs2 = []
    for i in range(X2.shape[0]):
        ref = brute_force_shapley(
            lambda r: run_graph_reference(spec_mlp, r), X2[i], bg2)
        errs2.append(float(np.abs(phi2[i] - ref).max()
                           / max(np.abs(ref).max(), 1e-9)))
    out["additive_mlp_rel_err"] = max(errs2)

    # (c) general mixed-sign net with BN + MaxPool: approximation regime,
    # but completeness (sum phi = f(x) - E[f]) must hold exactly
    spec_gen = build_stable_cnn_spec(side=6, seed=3, nonneg=False,
                                     batchnorm=True, maxpool=False,
                                     channels_out=(4,))
    # maxpool via a second variant (stride==kernel, disjoint windows)
    spec_mp = build_stable_cnn_spec(side=8, seed=4, nonneg=False,
                                    maxpool=True, channels_out=(4,))
    comp_errs = []
    for s, d in ((spec_gen, 36), (spec_mp, 64)):
        bgc = rng.uniform(0, 1, size=(3, d)).astype(np.float32)
        Xc = rng.uniform(0, 1, size=(3, d)).astype(np.float32)
        exc = _fit_engine(s, bgc)
        phic = _phi_matrix(exc.explain(Xc, nsamples="exact", silent=True)
                           .shap_values)
        fx = run_graph_reference(s, Xc)
        ef = run_graph_reference(s, bgc).mean(0)
        comp_errs.append(float(np.abs(phic.sum(2) - (fx - ef)).max()
                               / max(np.abs(fx).max(), 1e-9)))
    out["completeness_rel_err"] = max(comp_errs)
    return out


# --------------------------------------------------------------------- #
# phase 2: matched-error speedup vs the sampled estimator
# --------------------------------------------------------------------- #


def _timed(fn, reps):
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        walls.append(time.perf_counter() - t0)
    return float(np.median(walls))


def _timed_interleaved(arms: dict, reps: int) -> dict:
    """Min-of-``reps`` wall per arm, arms interleaved every pass so
    box-load drift hits all of them symmetrically (the 1-core bench
    host's jitter exceeds the small batches' walls; min is the
    least-noise estimator and the same for every arm)."""

    walls = {name: [] for name in arms}
    for _ in range(reps):
        for name, fn in arms.items():
            t0 = time.perf_counter()
            fn()
            walls[name].append(time.perf_counter() - t0)
    return {name: float(min(w)) for name, w in walls.items()}


def run_speedup_phase(budgets=(128, 512), n_instances=8, reps=3,
                      seed=0):
    """MNIST-architecture CNN (two conv layers, 16+32 channels) in the
    coalition-stable regime over M=16 superpixels.  The certified
    matched-error arm is the enumeration plan (see module docstring);
    the swept budgets' uncertified (error, wall) pairs are reported
    alongside, including their own floor-match ratio."""

    side = 28
    spec = build_stable_cnn_spec(side=side, seed=seed, nonneg=True,
                                 channels_out=(16, 32), K=3)
    groups, names, _ = _superpixel_G(side, patch=7)  # M = 16
    M = len(groups)
    rng = np.random.default_rng(seed + 5)
    bg = rng.uniform(0, 1, size=(1, side * side)).astype(np.float32)
    X = rng.uniform(0, 1, size=(n_instances,
                                side * side)).astype(np.float32)

    ex = _fit_engine(spec, bg, groups=groups, names=names)
    ex.explain(X, nsamples="exact", silent=True)      # compile
    for b in budgets:                                 # compile
        ex.explain(X, nsamples=b, l1_reg=False, silent=True)
    arms = {"deepshap": lambda: ex.explain(X, nsamples="exact",
                                           silent=True)}
    for b in budgets:
        arms[str(b)] = (lambda n: lambda: ex.explain(
            X, nsamples=n, l1_reg=False, silent=True))(b)
    timed = _timed_interleaved(arms, max(reps, 3))
    ds_wall = timed["deepshap"]
    phi_ds = _phi_matrix(ex.explain(X, nsamples="exact",
                                    silent=True).shap_values)

    # ground truth AND the certified matched-error arm: the
    # full-enumeration plan (nsamples >= 2^M-2 -> plan.exact; WLS over
    # every coalition IS exact Shapley — PR 9's pinned parity regime).
    # 2^16 composites through the real CNN is expensive, so truth (and
    # the enumeration wall) is established on a 2-instance slice.
    n_truth = 2
    n_enum = (1 << M)
    ex.explain(X[:n_truth], nsamples=n_enum, l1_reg=False,
               silent=True)  # compile
    t0 = time.perf_counter()
    truth = ex.explain(X[:n_truth], nsamples=n_enum, l1_reg=False,
                       silent=True)
    enum_wall_per_inst = (time.perf_counter() - t0) / n_truth
    phi_exact = _phi_matrix(truth.shap_values)
    scale = float(np.abs(phi_exact).max())
    ds_err = float(np.abs(phi_ds[:n_truth] - phi_exact).max())

    errors, walls = {}, {}
    for b in budgets:
        walls[b] = timed[str(b)]
        phi_b = _phi_matrix(ex.explain(X, nsamples=b, l1_reg=False,
                                       silent=True).shap_values)
        errors[b] = float(np.abs(phi_b[:n_truth] - phi_exact).max())

    # uncertified floor match: the cheapest swept budget whose realised
    # error reached DeepSHAP's floor on this degenerate game — reported
    # for transparency, never the gated arm (see module docstring)
    floor = [b for b in sorted(budgets)
             if errors[b] <= max(ds_err, EXACT_RTOL * scale)]
    B = n_instances
    ds_per_inst = ds_wall / B
    return {
        "M": M,
        "deepshap_per_instance_s": ds_per_inst,
        "deepshap_err_vs_exact": ds_err,
        "phi_scale": scale,
        "sampled_errors": {str(b): errors[b] for b in budgets},
        "sampled_per_instance_s": {str(b): walls[b] / B for b in budgets},
        "matched_arm": f"enumeration(n={n_enum})",
        "matched_per_instance_s": enum_wall_per_inst,
        "speedup_x": enum_wall_per_inst / ds_per_inst,
        "uncertified_floor_match": {
            "arm": str(floor[0]) if floor else None,
            "speedup_x": ((walls[floor[0]] / B) / ds_per_inst
                          if floor else None)},
        "kernel_path": ex.kernel_path,
    }


# --------------------------------------------------------------------- #
# phase 3: the CNN image tenant, served over the binary wire protocol
# --------------------------------------------------------------------- #


def _post_binary(host, port, body, headers, timeout=60.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/explain", body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def run_serving_phase(n_requests=24, rate_rps=20.0, seed=0):
    from distributedkernelshap_tpu.models.cnn import train_mnist_cnn
    from distributedkernelshap_tpu.registry import ModelRegistry
    from distributedkernelshap_tpu.serving import wire
    from distributedkernelshap_tpu.serving.server import ExplainerServer
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )
    from distributedkernelshap_tpu.ops.image import image_background
    from scripts.process_mnist_data import (
        _class_templates,
        _synthetic_digits,
    )

    rng = np.random.default_rng(seed)
    templates = _class_templates(rng)
    images, labels = _synthetic_digits(800, rng, templates)
    pred = train_mnist_cnn(images, labels, epochs=1, batch_size=128,
                           output="logits")
    groups, names, _ = _superpixel_G(28, patch=7)
    bg = image_background(images, mode="mean")
    model = BatchKernelShapModel(
        pred, bg, {"seed": 0},
        {"groups": groups, "group_names": names})
    registry = ModelRegistry()
    rm = registry.register("mnist_cnn", model)
    server = ExplainerServer(registry=registry, host="127.0.0.1", port=0,
                             max_batch_size=4, batch_timeout_s=0.004,
                             warmup=True, cache_bytes=1 << 22).start()
    try:
        deadline = time.monotonic() + 180
        while server.warmup_status()["state"] in ("pending", "running") \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        warm_state = server.warmup_status()["state"]

        test = _synthetic_digits(n_requests, rng, templates)[0]
        rows = test.reshape(n_requests, -1).astype(np.float32)
        headers = {"Content-Type": wire.CONTENT_TYPE,
                   "Accept": wire.CONTENT_TYPE,
                   "X-DKS-Priority": "interactive"}
        results = [None] * n_requests
        t0 = time.monotonic()

        def fire(i):
            delay = t0 + i / rate_rps - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            body = wire.encode_request(rows[i:i + 1],
                                       model_id="mnist_cnn")
            sent = time.monotonic()
            status, payload = _post_binary(server.host, server.port,
                                           body, headers)
            results[i] = (status, time.monotonic() - sent, payload)

        threads = [threading.Thread(target=fire, args=(i,), daemon=True)
                   for i in range(n_requests)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)

        # duplicate of request 0: content-fingerprint result cache must
        # answer bit-identically
        body0 = wire.encode_request(rows[:1], model_id="mnist_cnn")
        s_a, p_a = _post_binary(server.host, server.port, body0, headers)
        s_b, p_b = _post_binary(server.host, server.port, body0, headers)

        metrics = scrape_metrics(server)
        ds_requests = sum(
            v for k, v in metrics.items()
            if k.startswith("dks_serve_explain_path_total")
            and 'path="deepshap"' in k)
        signed = [k for k in metrics
                  if k.startswith("dks_compile_total")
                  and "model=mnist_cnn@v1" in k and "path=deepshap" in k]
        cache_hits = metrics.get("dks_serve_cache_hits_total", 0)
    finally:
        server.stop()

    done = [r for r in results if r is not None]
    statuses = [s for s, _, _ in done]
    lat = [w for s, w, _ in done if s == 200]
    # completeness over the wire, on the decoded binary payload
    additive = False
    ok_payloads = [p for s, _, p in done if s == 200]
    if ok_payloads:
        doc = wire.decode_explanation(ok_payloads[0])
        total = (np.stack(doc["shap_values"], 1).sum(-1)
                 + doc["expected_value"][None, :])
        additive = bool(np.allclose(total, doc["raw_prediction"],
                                    atol=1e-3))
    return {
        "classified_path": rm.path,
        "warmup_state": warm_state,
        "answered": sum(1 for s in statuses if s == 200),
        "n_requests": n_requests,
        "p50_s": percentile(lat, 50),
        "p95_s": percentile(lat, 95),
        "deepshap_request_slots": ds_requests,
        "ladder_signed_compiles": signed[:3],
        "cache_hits_after_dup": int(cache_hits),
        "dup_bit_identical": (s_a == s_b == 200 and p_a == p_b),
        "wire_additivity_ok": additive,
        "fingerprint": rm.fingerprint,
    }


# --------------------------------------------------------------------- #


def run_checks(exact, speed, serving) -> dict:
    return {
        "stable_cnn_matches_brute_force":
            exact["stable_cnn_rel_err"] <= EXACT_RTOL,
        "additive_mlp_matches_brute_force":
            exact["additive_mlp_rel_err"] <= EXACT_RTOL,
        "completeness_exact":
            exact["completeness_rel_err"] <= EXACT_RTOL,
        "deepshap_path_engaged":
            exact["stable_cnn_path"] == "deepshap"
            and speed["kernel_path"].get("exact_phi") == "deepshap",
        "deepshap_matches_enumerated_exact":
            speed["deepshap_err_vs_exact"]
            <= EXACT_RTOL * speed["phi_scale"],
        "certified_matched_error_speedup_10x":
            speed["speedup_x"] >= MIN_SPEEDUP,
        "tenant_classified_deepshap":
            serving["classified_path"] == "deepshap",
        "tenant_warmed": serving["warmup_state"] == "done",
        "ladder_rungs_signed_deepshap":
            len(serving["ladder_signed_compiles"]) > 0,
        "all_answered":
            serving["answered"] == serving["n_requests"],
        "interactive_p95_slo":
            serving["p95_s"] is not None
            and serving["p95_s"] <= SERVING_P95_SLO_S,
        "path_metric_attributes_traffic":
            serving["deepshap_request_slots"] >= serving["n_requests"],
        "dup_bit_identical_via_cache":
            serving["dup_bit_identical"]
            and serving["cache_hits_after_dup"] >= 1,
        "wire_additivity": serving["wire_additivity_ok"],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true",
                        help="exit 1 unless every criterion holds")
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--requests", type=int, default=24)
    parser.add_argument("--no-record", action="store_true",
                        help="measure without appending perf history")
    args = parser.parse_args(argv)

    exact = run_exactness_phase()
    speed = run_speedup_phase(reps=args.reps, seed=args.seed)
    serving = run_serving_phase(n_requests=args.requests, seed=args.seed)
    checks = run_checks(exact, speed, serving)
    checks_ok = all(checks.values())

    if not args.no_record:
        record_run(
            DEFAULT_HISTORY, "deepshap",
            {"M": speed["M"], "side": 28, "seed": args.seed,
             "requests": args.requests,
             "slo_s": SERVING_P95_SLO_S},
            {"wall_s": speed["deepshap_per_instance_s"],
             "serving_p95_s": serving["p95_s"] or 0.0,
             "speedup_x": speed["speedup_x"]},
            extra={"checks_ok": checks_ok,
                   "matched_arm": speed["matched_arm"],
                   "deepshap_err_vs_exact":
                       speed["deepshap_err_vs_exact"]})

    result = {
        "bench": "deepshap",
        "exactness": exact,
        "speedup": {k: v for k, v in speed.items()
                    if k != "kernel_path"},
        "serving": serving,
        "checks": checks,
        "checks_ok": checks_ok,
    }
    print(json.dumps(result))
    return 0 if (checks_ok or not args.check) else 1


if __name__ == "__main__":
    sys.exit(main())
