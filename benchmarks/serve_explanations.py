"""Serving benchmark — translation of ``benchmarks/serve_explanations.py``.

Same CLI flags (``--replicas``, ``-batch``, ``-benchmark``, ``--nruns``,
``--host``, ``--port``) and the same result pickle format/naming
(``utils.get_filename(serve=True)``) as the reference (:199-244).  The
client fans out one request per instance (reference ``distribute_request``
Ray tasks, :96-139 — here a thread pool); the server coalesces them into
device batches of ``max_batch_size``.

``--replicas`` has no hardware meaning on a single device (the reference
spawned that many replica processes); it is kept for sweep/filename parity
and sets the HTTP thread-pool width.
"""

import argparse
import logging
import os
import pickle
import sys
from timeit import default_timer as timer

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedkernelshap_tpu.serving import distribute_requests  # noqa: E402
from benchmarks._common import add_platform_flag, apply_platform  # noqa: E402
from distributedkernelshap_tpu.utils import get_filename, load_data, load_model  # noqa: E402

logging.basicConfig(level=logging.INFO)


def prepare_explainer_args(data: dict):
    """Constructor/fit args for the served explainer
    (reference serve_explanations.py:70-93 call shape)."""

    from distributedkernelshap_tpu.utils import data_provenance

    group_names, groups = data['all']['group_names'], data['all']['groups']
    background = data['background']['X']['preprocessed']
    constructor_kwargs = {'link': 'logit', 'feature_names': group_names, 'seed': 0}
    fit_kwargs = {'group_names': group_names, 'groups': groups,
                  'data_provenance': data_provenance(data)}
    return background, constructor_kwargs, fit_kwargs


def build_model(predictor, data):
    """One fitted serving model for the whole sweep: re-fitting per config
    would recreate the jitted functions and pay the 15-40s TPU bucket
    compiles for every (replicas, batch) point."""

    from distributedkernelshap_tpu.serving.wrappers import BatchKernelShapModel

    background, ctor_kwargs, fit_kwargs = prepare_explainer_args(data)
    return BatchKernelShapModel(predictor, background, ctor_kwargs, fit_kwargs)


def run_config(predictor, data, X_explain, replicas: int, max_batch_size: int,
               host: str, port: int, nruns: int, batch_mode: str = "ray",
               model=None):
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    if model is None:
        model = build_model(predictor, data)
    # replicas → pipeline depth: the reference's N replica processes become N
    # in-flight device batches whose D2H round trips overlap; 0 = let the
    # server self-calibrate the depth at startup
    server = ExplainerServer(model, host=host, port=port,
                             max_batch_size=max_batch_size,
                             pipeline_depth=replicas or None).start()
    if not replicas:
        logging.info("auto-calibrated pipeline_depth=%d", server.pipeline_depth)
    url = f"http://{'127.0.0.1' if host == '0.0.0.0' else host}:{server.port}/explain"
    # the reference client fans out every instance as its own Ray task
    # (serve_explanations.py:131-134); a colocated single-core client gets the
    # same queue pressure from a bounded keep-alive pool
    fanout = 32
    try:
        # warmup: compile every device bucket the coalescer can form,
        # deterministically (HTTP warmup alone can't guarantee which sizes
        # arrive together, and a 15-40s TPU compile inside the timed region
        # would corrupt run 0).  'ray' coalesces 1..max_batch_size rows,
        # 'default' up to max_batch_size requests of max_batch_size rows —
        # every stacked size pads onto the power-of-two bucket ladder, so
        # warming the ladder covers partial coalesces too.  The jit cache
        # lives on the shared model, so the sweep pays each bucket once.
        full_rows = min(X_explain.shape[0],
                        max_batch_size if batch_mode == "ray"
                        else max_batch_size * max_batch_size)
        bucket = server.model.explainer._explainer._bucket
        ladder = sorted({bucket(rows) for rows in range(1, full_rows + 1)})
        for rows in ladder:
            rows = min(rows, X_explain.shape[0])
            server.model.explain_batch(X_explain[:rows], split_sizes=[rows])
        distribute_requests(url, X_explain[:4 * max_batch_size],
                            max_workers=fanout)
        if not os.path.exists('./results'):
            os.mkdir('./results')
        # batch_mode mirrors the reference's k8s driver
        # (k8s_serve_explanations.py:181-184): 'ray' = one single-row request
        # per instance with server-side coalescing; 'default' = client-side
        # minibatches of max_batch_size rows each
        minibatches = None
        if batch_mode == "default":
            from distributedkernelshap_tpu.utils import batch as make_batches

            minibatches = make_batches(X_explain, batch_size=max_batch_size)
        result = {'t_elapsed': [],
                  'data_provenance': server.model.explainer.meta.get(
                      'data_provenance', 'unspecified')}
        for run in range(nruns):
            logging.info("run: %d", run)
            t_start = timer()
            responses = distribute_requests(url, X_explain, batch_mode=batch_mode,
                                            minibatches=minibatches,
                                            max_workers=fanout)
            t_elapsed = timer() - t_start
            expected = (len(minibatches) if minibatches is not None
                        else X_explain.shape[0])
            assert len(responses) == expected
            logging.info("Time elapsed: %s", t_elapsed)
            result['t_elapsed'].append(t_elapsed)
            # re-read per run (like pool.py): a Pallas degrade DURING a
            # timed run must reach the pickle, not a pre-degrade snapshot
            result['kernel_path'] = server.model.explainer.kernel_path
            fname = get_filename(replicas, max_batch_size, serve=True)
            if batch_mode != "ray":  # keep 'ray' on the reference naming
                fname = fname.replace(".pkl", f"_mode_{batch_mode}.pkl")
            with open(fname, 'wb') as f:
                pickle.dump(result, f)
    finally:
        server.stop()


def emit_trace(trace_out: str) -> None:
    """Export the tracer ring: JSONL at ``trace_out``, a Perfetto
    ``trace_event`` conversion next to it, and a per-phase breakdown on
    stdout (one JSON line) — the "where did the time go" artifact the
    sweep produces when tracing is on."""

    import json

    from distributedkernelshap_tpu.observability import tracing

    spans = tracing.tracer().spans()
    tracing.tracer().export_jsonl(trace_out)
    perfetto = trace_out + ".perfetto.json"
    tracing.write_chrome_trace(spans, perfetto)
    print(json.dumps({"trace": {
        "spans": len(spans),
        "dropped": tracing.tracer().dropped_total,
        "jsonl": trace_out,
        "perfetto": perfetto,
        "phases": tracing.phase_breakdown(spans),
    }}))


def main():
    nruns = args.nruns if args.benchmark else 1
    batch_sizes = [int(elem) for elem in args.batch]

    data = load_data()
    predictor = load_model()
    X_explain = data['all']['X']['processed']['test'].toarray()
    assert X_explain.shape[0] == 2560
    assert data['background']['X']['preprocessed'].shape[0] == 100

    model = build_model(predictor, data)
    replicas_range = (range(1, args.replicas + 1) if args.benchmark == 1
                      else range(args.replicas, args.replicas + 1))
    for replicas in replicas_range:
        for max_batch_size in batch_sizes:
            logging.info("Experiment: pipeline depth %d, max_batch_size %d, "
                         "batch_mode %s", replicas, max_batch_size,
                         args.batch_mode)
            run_config(predictor, data, X_explain, replicas, max_batch_size,
                       args.host, args.port, nruns, batch_mode=args.batch_mode,
                       model=model)
    if args.trace_out:
        emit_trace(args.trace_out)


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "-r", "--replicas", default=1, type=int,
        help="Server pipeline depth (the reference's replica count: N "
             "in-flight device batches with overlapped D2H, instead of N "
             "model-copy processes). 0 = self-calibrate at server startup. "
             "Client fan-out is fixed at 32.")
    parser.add_argument(
        "-b", "--batch", nargs='+', required=True,
        help="max_batch_size values to sweep for server-side request coalescing.")
    parser.add_argument("-benchmark", default=0, type=int,
                        help="Set to 1 to sweep replicas in range(1, replicas+1).")
    parser.add_argument("-n", "--nruns", default=5, type=int)
    parser.add_argument(
        "-batch_mode", default="ray", choices=("ray", "default"),
        help="'ray': one single-row request per instance, server-side "
             "coalescing; 'default': client-side minibatches (the reference "
             "k8s driver's modes, k8s_serve_explanations.py:181-184).")
    parser.add_argument("--host", default="0.0.0.0", type=str)
    parser.add_argument("--port", default=8000, type=int)
    parser.add_argument(
        "--trace-out", default="", type=str,
        help="Enable end-to-end tracing and write the span ring here as "
             "JSONL (plus <path>.perfetto.json for chrome://tracing / "
             "Perfetto) with a per-phase breakdown on stdout.  Client, "
             "server and engine-phase spans share trace ids, so one "
             "request is followable end to end.")
    add_platform_flag(parser)
    args = parser.parse_args()
    apply_platform(args)
    if args.trace_out:
        from distributedkernelshap_tpu.observability import tracing

        tracing.tracer().enable()
    main()
