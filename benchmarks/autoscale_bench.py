"""Autoscaling benchmark: diurnal open-loop replay, elastic fleet vs
static fleets (standalone, CPU backend, exits nonzero on ``--check``
fail).

One diurnal trace (trough → ramp → peak → fall → trough; arrivals fired
on schedule regardless of completions — the honest way to load a fleet)
is replayed against three arms IN THE SAME RUN:

* **static-2** — a fixed fleet one replica short of peak capacity: must
  measurably BLOW the interactive latency SLO at peak (so the smallest
  static fleet that holds the SLO is the next size up);
* **static-3** — the smallest static fleet that holds the SLO: the
  replica-seconds baseline the autoscaler must beat;
* **autoscaled** — ``min=1, max=3`` with the burn-rate + queue-signal +
  rate-trend scaler (``serving/autoscaler.py``): must hold the SLO (no
  firing burn-rate alert at steady state), spend >= 30% fewer
  replica-seconds than the smallest holding static fleet, make every
  scale-up replica serve its first answer <= 5 s after spawn (pre-warm
  through the real ``DKS_WARMUP`` ladder, observed via the proxy's
  ``warming`` state), and scale DOWN by draining — zero lost and zero
  duplicated answers, verified per request like ``chaos_bench.py``.

The fleet is in-process (real :class:`ExplainerServer` instances with
the real warmup ladder, scheduler, admission estimator and ``/statusz``
behind a real :class:`FanInProxy`) so a 1-core box can replay a
3-replica diurnal trace with sub-second control timing; the subprocess
fleet path (``ReplicaManager.spawn_replica`` / supervisor retirement)
is exercised by ``tests/test_autoscaler.py`` and the chaos bench.  The
device model is synthetic (deterministic seconds per batch, like
``scheduling_bench.py``) so capacity margins are designed, not guessed;
every response echoes its request's rows so answers verify against
their own request.

    JAX_PLATFORMS=cpu python benchmarks/autoscale_bench.py --check
"""

import argparse
import http.client
import json
import sys
import threading
import time
from types import SimpleNamespace

import numpy as np

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)

DIM = 6

#: interactive latency SLO the replay is judged against (bench-fast
#: threshold sized to the synthetic device's service quantum — a full
#: batch is ~0.94 s, and the holding static fleet runs ~79% utilization
#: at peak, so its queueing p99 sits ~1.5-1.9 s run to run on a noisy
#: 1-core box: the threshold must leave that REAL headroom while the
#: under-provisioned arm still blows it by >2x (measured p99 4.4-5.9 s);
#: the production thresholds live in observability/slo.py)
SLO_THRESHOLD_S = 2.5
SLO_TARGET = 0.9

#: diurnal trace (seconds, requests/s) — peak sits between the 2-replica
#: and 3-replica full-batch capacities (~16 / ~24 rps), troughs well
#: under one replica's (~8 rps)
TROUGH_RPS = 2.5
PEAK_RPS = 19.0
T_TROUGH_A = 15.0
T_RAMP = 10.0
T_PEAK = 25.0
T_FALL = 5.0
T_TROUGH_B = 25.0


# --------------------------------------------------------------------- #
# synthetic served model: deterministic device time + warmup-ladder
# compatibility + request echo for per-request verification
# --------------------------------------------------------------------- #


class SyntheticServedModel:
    """Deterministic device cost per batch (``base_s + per_row_s *
    rows``) with two additions over ``scheduling_bench.SyntheticModel``:

    * a minimal engine facade (``explainer._explainer.background``) so
      the REAL warmup ladder engages — a freshly spawned replica pays
      the ladder (simulated compiles) in the ``warming`` readiness state
      before the prober admits it, exactly like a production worker;
    * every response echoes its request's rows, so the parent can verify
      each answer against ITS OWN request (the chaos bench's zero-lost /
      zero-duplicated discipline, applied to drains).
    """

    max_rows = None

    def __init__(self, base_s=0.02, per_row_s=0.115):
        # per-ROW dominated on purpose: a replica's observed service rate
        # (the admission EWMA the scaler aggregates into fleet capacity)
        # then reads ~the same at batch size 1 as at 8, so the scaler's
        # utilization signal doesn't under-estimate capacity at the
        # trough (which would block the final drain and re-trigger
        # spurious scale-ups — measured before this was pinned down)
        self.base_s = base_s
        self.per_row_s = per_row_s
        self.explainer = SimpleNamespace(_explainer=SimpleNamespace(
            background=np.zeros((4, DIM), np.float32)))

    def explain_batch(self, instances, split_sizes=None):
        time.sleep(self.base_s + self.per_row_s * instances.shape[0])
        sizes = split_sizes or [1] * instances.shape[0]
        out, offset = [], 0
        for size in sizes:
            rows = instances[offset:offset + size]
            out.append(json.dumps({"data": {
                "echo": np.asarray(rows, np.float32).tolist(),
                "rows": int(size)}}))
            offset += size
        return out

    def full_batch_rps(self, max_batch: int = 8) -> float:
        return max_batch / (self.base_s + self.per_row_s * max_batch)


# --------------------------------------------------------------------- #
# in-process elastic fleet
# --------------------------------------------------------------------- #


class LocalFleet:
    """An elastic fleet of in-process :class:`ExplainerServer` replicas
    behind a :class:`FanInProxy` — the same ``spawn_replica`` /
    ``retire_replica`` hooks :class:`ReplicaManager` exposes, with the
    worker subprocess replaced by a server thread stack (1-core boxes
    cannot replay a multi-replica diurnal trace against N jax worker
    processes)."""

    def __init__(self, model_factory, max_batch_size=8,
                 batch_timeout_s=0.02, warmup=True,
                 proxy_kwargs=None):
        self.model_factory = model_factory
        self.max_batch_size = max_batch_size
        self.batch_timeout_s = batch_timeout_s
        self.warmup = warmup
        self.proxy_kwargs = dict(proxy_kwargs or {})
        self.servers = {}      # index -> ExplainerServer
        self.spawn_walls = {}  # index -> monotonic spawn time
        self.proxy = None
        self._lock = threading.Lock()

    def _new_server(self):
        from distributedkernelshap_tpu.serving.server import ExplainerServer

        return ExplainerServer(
            self.model_factory(), host="127.0.0.1", port=0,
            max_batch_size=self.max_batch_size,
            batch_timeout_s=self.batch_timeout_s,
            pipeline_depth=1, scheduling="slo",
            health_interval_s=0.0, warmup=self.warmup).start()

    def start(self, n_initial: int) -> "LocalFleet":
        from distributedkernelshap_tpu.serving.replicas import FanInProxy

        targets = []
        for i in range(n_initial):
            t0 = time.monotonic()
            server = self._new_server()
            self.servers[i] = server
            self.spawn_walls[i] = t0
            targets.append((server.host, server.port))
        self.proxy = FanInProxy(targets, probe_interval_s=0.2,
                                **self.proxy_kwargs).start()
        return self

    # -- the autoscaler's elastic hooks --------------------------------- #

    def spawn_replica(self, standby: bool = False):
        with self._lock:
            t0 = time.monotonic()
            server = self._new_server()
            index = self.proxy.add_target(server.host, server.port,
                                          standby=standby)
            self.servers[index] = server
            self.spawn_walls[index] = t0
            return index

    def retire_replica(self, index: int) -> None:
        self.servers[index].stop()
        self.proxy.finish_drain(index)

    # ------------------------------------------------------------------- #

    def wait_ready(self, timeout_s: float = 60.0) -> bool:
        """Block until every non-retired replica finished its warmup
        ladder and is routable (arms must start from a warm fleet so the
        replay measures scaling, not cold start)."""

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(s.warmup_status()["state"] in ("done", "off")
                   for s in self.servers.values()) and \
                    any(r.routable() for r in self.proxy.replicas):
                return True
            time.sleep(0.05)
        return False

    def stop(self) -> None:
        if self.proxy is not None:
            self.proxy.stop()
        for server in self.servers.values():
            try:
                server.stop()
            except Exception:
                pass


# --------------------------------------------------------------------- #
# diurnal open-loop load
# --------------------------------------------------------------------- #


def diurnal_rate(t: float) -> float:
    """Requests/s at trace offset ``t`` (piecewise linear diurnal)."""

    if t < T_TROUGH_A:
        return TROUGH_RPS
    t -= T_TROUGH_A
    if t < T_RAMP:
        return TROUGH_RPS + (PEAK_RPS - TROUGH_RPS) * t / T_RAMP
    t -= T_RAMP
    if t < T_PEAK:
        return PEAK_RPS
    t -= T_PEAK
    if t < T_FALL:
        return PEAK_RPS - (PEAK_RPS - TROUGH_RPS) * t / T_FALL
    return TROUGH_RPS


def trace_total_s() -> float:
    return T_TROUGH_A + T_RAMP + T_PEAK + T_FALL + T_TROUGH_B


def build_diurnal_plan(seed: int = 0):
    """``[(offset_s, array, headers), ...]`` — deterministic arrivals
    integrated from the rate profile, every request one unique
    interactive row (uniqueness is what makes per-request verification
    able to catch a duplicated or mixed-up answer)."""

    rng = np.random.default_rng(seed)
    plan, t = [], 0.0
    total = trace_total_s()
    while t < total:
        array = rng.normal(size=(1, DIM)).astype(np.float32)
        plan.append((t, array, {"X-DKS-Priority": "interactive"}))
        t += 1.0 / diurnal_rate(t)
    return plan


def _post_with_retry(host, port, array, headers, timeout=60.0,
                     max_retries=4):
    """One /explain request with bounded retries on retriable failures
    (502/503/connection loss — a drained replica's final pre-dispatch
    503s re-route exactly like the chaos bench's kills; explains are
    deterministic, so a retry is idempotent)."""

    body = json.dumps({"array": array.tolist()}).encode()
    last = (None, "")
    for attempt in range(max_retries + 1):
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("POST", "/explain", body=body,
                         headers={"Content-Type": "application/json",
                                  **headers})
            resp = conn.getresponse()
            status, payload = resp.status, resp.read().decode()
        except OSError:
            status, payload = -1, ""
        finally:
            conn.close()
        if status not in (-1, 502, 503):
            return status, payload, attempt
        last = (status, payload)
        time.sleep(0.1 * (attempt + 1))
    return last[0], last[1], max_retries


def open_loop(proxy, plan, timeout=60.0):
    """Fire ``plan`` on schedule through the fan-in proxy (rolling
    spawner: thread per request, created at its offset — a diurnal trace
    is too long to pre-spawn every client thread).  Returns
    ``[(status, latency_s, payload, retries)]`` in plan order."""

    results = [None] * len(plan)
    threads = []
    t0 = time.monotonic()

    def fire(i, array, headers):
        sent = time.monotonic()
        status, payload, retries = _post_with_retry(
            proxy.host, proxy.port, array, headers, timeout=timeout)
        results[i] = (status, time.monotonic() - sent, payload, retries)

    for i, (offset, array, headers) in enumerate(plan):
        delay = t0 + offset - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=fire, args=(i, array, headers),
                             daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout * 2)
    return [r for r in results if r is not None], time.monotonic() - t0


def percentile(values, q):
    return float(np.percentile(np.asarray(values), q)) if values else None


# --------------------------------------------------------------------- #
# one arm
# --------------------------------------------------------------------- #


def _bench_slo_and_rule():
    from distributedkernelshap_tpu.observability.alerts import slo_burn_rule
    from distributedkernelshap_tpu.observability.slo import (
        BurnRateWindow,
        LatencySLO,
    )

    slo = LatencySLO(
        "interactive_latency_autoscale",
        histogram="dks_fanin_class_latency_seconds",
        labels={"class": "interactive"},
        threshold_s=SLO_THRESHOLD_S, target=SLO_TARGET,
        windows=(BurnRateWindow(long_s=8.0, short_s=2.0, factor=3.0),),
        description="bench-fast interactive latency SLO at the fan-in")
    return slo, slo_burn_rule(slo, for_s=0.5, keep_firing_s=1.0)


def run_arm(mode: str, plan, seed: int = 0):
    """One replay.  ``mode`` is ``"static-N"`` or ``"auto"``."""

    from distributedkernelshap_tpu.serving.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
    )

    slo, rule = _bench_slo_and_rule()
    fleet = LocalFleet(
        SyntheticServedModel,
        proxy_kwargs=dict(health_interval_s=0.25, slos=[slo],
                          alert_rules=[rule]))
    scaler = None
    config = None
    if mode == "auto":
        config = AutoscalerConfig(
            min_replicas=1, max_replicas=3, warm_standby=0,
            interval_s=0.25, up_ticks=2, down_ticks=5,
            up_cooldown_s=2.5, down_cooldown_s=4.0,
            queue_wait_up_s=0.35, replica_wait_up_s=0.5,
            trend_factor=1.4, trend_window_short_s=2.0,
            trend_window_long_s=10.0, trend_min_utilization=0.45,
            down_utilization=0.6, drain_timeout_s=20.0,
            drain_settle_polls=2)
        fleet.start(1)
    else:
        fleet.start(int(mode.split("-")[1]))

    # per-replica observation: lifecycle states seen, first-answer
    # times, and the replica-count integral (the arm's replica-seconds)
    samples = []          # (t, provisioned_count)
    states_seen = {}      # index -> set of states
    first_answer = {}     # index -> monotonic time of first HTTP answer
    alert_states = []     # (t, state)
    stop_poll = threading.Event()

    def poll():
        while not stop_poll.is_set():
            now = time.monotonic()
            counts = fleet.proxy.replica_state_counts()
            provisioned = sum(counts.get(s, 0) for s in
                              ("ready", "warming", "draining", "standby"))
            samples.append((now, provisioned))
            for r in fleet.proxy.replicas:
                states_seen.setdefault(r.index, set()).add(r.state())
            for index, server in list(fleet.servers.items()):
                if index not in first_answer and \
                        server._m_requests.value() > 0:
                    first_answer[index] = now
            try:
                state = fleet.proxy.health.alerts.payload()["alerts"][0][
                    "state"]
                alert_states.append((now - t_start, state))
            except (IndexError, KeyError):
                pass
            stop_poll.wait(0.1)

    try:
        if not fleet.wait_ready():
            return {"error": f"{mode}: fleet never became ready"}
        if scaler is None and mode == "auto":
            scaler = Autoscaler(fleet, fleet.proxy, config=config).start()
        t_start = time.monotonic()
        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        results, wall = open_loop(fleet.proxy, plan)
        # let a trailing drain finish so its replica-seconds and the
        # drain_complete event land inside this arm's measurement
        if scaler is not None:
            settle_deadline = time.monotonic() + 10.0
            while time.monotonic() < settle_deadline and \
                    (scaler._draining or
                     fleet.proxy.replica_state_counts().get("draining")):
                time.sleep(0.2)
        stop_poll.set()
        poller.join(timeout=5)

        # per-request verification (chaos-bench discipline): every
        # answer must echo ITS OWN request's rows
        lost, mismatched, latencies, retried = [], [], [], 0
        for i, r in enumerate(results):
            status, latency, payload, retries = r
            retried += int(retries > 0)
            if status != 200:
                lost.append(i)
                continue
            latencies.append(latency)
            try:
                echo = np.asarray(json.loads(payload)["data"]["echo"],
                                  np.float32)
            except (ValueError, KeyError):
                mismatched.append(i)
                continue
            if not np.array_equal(echo, plan[i][1]):
                mismatched.append(i)

        # replica-seconds: trapezoid-free integral of the provisioned
        # count over the replay (samples every ~0.1 s)
        replay_samples = [(t, c) for t, c in samples
                          if t_start <= t <= t_start + wall]
        replica_seconds = 0.0
        for (ta, ca), (tb, _) in zip(replay_samples, replay_samples[1:]):
            replica_seconds += ca * (tb - ta)
        max_provisioned = max((c for _, c in replay_samples), default=0)
        final_ready = fleet.proxy.replica_state_counts().get("ready", 0)

        report = {
            "mode": mode,
            "n": len(plan),
            "answered": len(results),
            "wall_s": round(wall, 2),
            "lost": len(lost),
            "mismatched": len(mismatched),
            "retried_requests": retried,
            "p50_s": (round(percentile(latencies, 50), 3)
                      if latencies else None),
            "p99_s": (round(percentile(latencies, 99), 3)
                      if latencies else None),
            "replica_seconds": round(replica_seconds, 1),
            "max_provisioned": int(max_provisioned),
            "final_ready": int(final_ready),
            "alert_states_seen": sorted({s for _, s in alert_states}),
            "alert_firing_spans": [
                round(t, 1) for t, s in alert_states if s == "firing"],
        }
        if scaler is not None:
            from distributedkernelshap_tpu.observability.flightrec import (
                flightrec,
            )

            scaleups = []
            for index, t_spawn in sorted(fleet.spawn_walls.items()):
                if index == 0:
                    continue  # the initial replica is not a scale-up
                served = first_answer.get(index)
                warm_state = fleet.servers[index].warmup_status()["state"]
                scaleups.append({
                    "replica": index,
                    "spawn_to_first_answer_s": (
                        round(served - t_spawn, 2)
                        if served is not None else None),
                    "warming_observed": "warming" in states_seen.get(
                        index, set()),
                    "warmup_state": warm_state,
                })
            drains = [e for e in flightrec().snapshot()
                      if e["kind"] == "drain_complete"
                      and e.get("replica") in fleet.servers]
            metrics_rs = {}
            for line in fleet.proxy.metrics.render().splitlines():
                if line.startswith("dks_autoscale_replica_seconds_total"):
                    name, value = line.rsplit(" ", 1)
                    metrics_rs[name] = round(float(value), 1)
            report.update({
                "scaleups": scaleups,
                "drains_completed": len(drains),
                "drains_forced": sum(1 for e in drains if e.get("forced")),
                "scaler_decisions": {
                    "up_streak": scaler._up_streak,
                    "ticks": scaler.ticks_total},
                "dks_autoscale_replica_seconds_total": metrics_rs,
                "statusz_panel": fleet.proxy._statusz_detail()[
                    "autoscaler"],
            })
        return report
    finally:
        stop_poll.set()
        if scaler is not None:
            scaler.stop()
        fleet.stop()


def steady_state_firing(arm: dict) -> bool:
    """Whether the burn-rate alert fired OUTSIDE scaling transients —
    the trace's steady segments: trough A, the peak after a settling
    grace, trough B after the drain window."""

    ramp_end = T_TROUGH_A + T_RAMP
    peak_end = ramp_end + T_PEAK
    fall_end = peak_end + T_FALL
    windows = [(1.0, T_TROUGH_A),
               (ramp_end + 6.0, peak_end),
               (fall_end + 10.0, trace_total_s())]
    for t in arm.get("alert_firing_spans", []):
        if any(lo <= t <= hi for lo, hi in windows):
            return True
    return False


# --------------------------------------------------------------------- #


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true",
                        help="exit 1 unless the acceptance criteria hold")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--history", default=None,
                        help="perf-history JSONL this run appends to "
                             "(default: results/perf_history.jsonl)")
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history self-record")
    args = parser.parse_args()

    model = SyntheticServedModel()
    plan = build_diurnal_plan(seed=args.seed)

    # throwaway warm pass: the first server in a process runs slow
    # (thread/socket warmup) — scheduling_bench's discipline
    warm = LocalFleet(SyntheticServedModel).start(1)
    try:
        warm.wait_ready()
        _post_with_retry(warm.proxy.host, warm.proxy.port,
                         np.zeros((1, DIM), np.float32), {})
    finally:
        warm.stop()

    static2 = run_arm("static-2", plan, seed=args.seed)
    static3 = run_arm("static-3", plan, seed=args.seed)
    auto = run_arm("auto", plan, seed=args.seed)

    report = {
        "bench": "autoscale",
        "trace": {"trough_rps": TROUGH_RPS, "peak_rps": PEAK_RPS,
                  "total_s": trace_total_s(), "requests": len(plan)},
        "per_replica_full_batch_rps": round(model.full_batch_rps(), 1),
        "slo_threshold_s": SLO_THRESHOLD_S,
        "static2": static2, "static3": static3, "auto": auto,
    }
    if any("error" in a for a in (static2, static3, auto)):
        report["ok"] = False
        print(json.dumps(report))
        return 1

    # the smallest static fleet that holds the SLO (measured IN THIS
    # run): static-2 is designed to blow it, so normally static-3
    holding = [a for a in (static2, static3)
               if a["p99_s"] is not None and a["p99_s"] <= SLO_THRESHOLD_S
               and a["lost"] == 0]
    smallest_holding = (min(holding, key=lambda a: a["replica_seconds"])
                        if holding else None)
    saving = (1.0 - auto["replica_seconds"]
              / smallest_holding["replica_seconds"]
              if smallest_holding else None)
    scaleups = auto.get("scaleups", [])
    checks = {
        # (a) the autoscaled fleet holds the interactive p99 SLO and no
        # burn-rate alert fires at steady state
        "auto_holds_p99_slo": (auto["p99_s"] is not None
                               and auto["p99_s"] <= SLO_THRESHOLD_S),
        "auto_no_firing_alert_steady_state": not steady_state_firing(auto),
        # the under-provisioned static arm must fail (otherwise the
        # baseline fleet was not the smallest holding one)
        "static2_blows_slo": (static2["p99_s"] is None
                              or static2["p99_s"] > SLO_THRESHOLD_S),
        # (b) >= 30% fewer replica-seconds than the smallest static
        # fleet that also holds the SLO, both measured in this run
        "replica_seconds_saving_ge_30pct": (saving is not None
                                            and saving >= 0.30),
        # (c) every scale-up replica served its first answer <= 5 s
        # after spawn, pre-warmed through the ladder in warming state
        "scaleup_first_answer_le_5s": bool(scaleups) and all(
            s["spawn_to_first_answer_s"] is not None
            and s["spawn_to_first_answer_s"] <= 5.0 for s in scaleups),
        "scaleup_warming_observed": bool(scaleups) and all(
            s["warming_observed"] and s["warmup_state"] == "done"
            for s in scaleups),
        # (d) scale-down drained with zero lost / zero duplicated
        "drains_completed": auto.get("drains_completed", 0) >= 1,
        "drain_zero_lost": auto["lost"] == 0,
        "drain_zero_duplicated_or_mixed": auto["mismatched"] == 0,
        # the fleet actually breathed: up to the bound, back to the floor
        "scaled_to_max": auto["max_provisioned"] >= 3,
        "scaled_back_down": auto["final_ready"] == 1,
    }
    report["smallest_holding_static"] = (smallest_holding["mode"]
                                         if smallest_holding else None)
    report["replica_seconds_saving"] = (round(saving, 3)
                                        if saving is not None else None)
    report["checks"] = checks
    report["ok"] = all(checks.values())

    if not args.no_record:
        from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

        entry = record_run(
            args.history or DEFAULT_HISTORY, bench="autoscale",
            # the fleet bounds ARE part of the measurement's identity: a
            # different min/max (or standby pool) is a different
            # replica-seconds baseline
            config={"min_replicas": 1, "max_replicas": 3,
                    "warm_standby": 0,
                    "trace": {"trough_rps": TROUGH_RPS,
                              "peak_rps": PEAK_RPS,
                              "total_s": trace_total_s()},
                    "model": {"base_s": model.base_s,
                              "per_row_s": model.per_row_s},
                    "slo_threshold_s": SLO_THRESHOLD_S},
            metrics={"wall_s": auto["wall_s"],
                     "interactive_p99_s": auto["p99_s"],
                     "replica_seconds": auto["replica_seconds"]},
            extra={"checks_ok": report["ok"],
                   "replica_seconds_saving": report[
                       "replica_seconds_saving"]})
        report["perf_history"] = {"git_sha": entry["git_sha"],
                                  "config_fp": entry["config_fp"]}
    print(json.dumps(report))
    if args.check and not report["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
