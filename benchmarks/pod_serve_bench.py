"""Pod-serving fabric benchmark (standalone; ``--check`` exits nonzero on
criteria fail): the bucketed-broadcast + pipelined multi-host hot path on
a REAL 2-process gloo CPU mesh (``serving/multihost.py``).

Two OS processes join one ``jax.distributed`` runtime (2 virtual CPU
devices each — 4 global devices) and serve the tiny synthetic deployment
through BOTH protocols, lock-step then pipelined, on the same mesh:

1. **parity** — served phi (B=1 requests over HTTP) matches a direct
   sharded explain of the same rows on the same mesh, for both
   protocols, and the mesh's phi matches a single-process run;
2. **bucketed frames** — the measured broadcast bytes per B=1 request
   (``dks_pod_bcast_bytes_total``) are at most half the full-slot
   frame the pre-bucketed protocol would have broadcast every batch;
3. **pipelined goodput** — a B=1 frame backlog driven through the pod
   models exactly as the server's dispatcher runs them (real wire, real
   collectives), both protocols.  On a host with CPU parallelism the
   backlog must retire >= 1.3x faster pipelined than lock-step.  On a
   single-CPU host both processes timeshare one core, so overlap cannot
   buy throughput BY CONSTRUCTION (total work per row is the floor, and
   the follower recomputes every frame either way) — there the bench
   gates the *mechanism* instead: per-frame dispatcher occupancy.
   Lock-step occupancy is wall time (the dispatcher is blocked
   end-to-end by protocol: broadcast + full device call + result
   fetch); pipelined occupancy is the dispatch thread's CPU time
   (``time.thread_time`` — broadcast + async enqueue), because on one
   core the frame's own XLA compute threads starve the dispatcher
   mid-dispatch and inflate its *wall* to ~frame time even though it
   never blocks (measured: ~3ms CPU inside ~22ms wall at any pipeline
   depth).  Thread CPU is the starvation-free occupancy — it equals
   the wall a >=2-core host would observe for a never-blocking
   dispatcher, so the ratio is exactly what converts into goodput the
   moment device work and dispatch run on distinct silicon — the
   TPU-pod deployment this fabric exists for.  The gate: occupancy
   ratio >= 1.3, AND pipelining must not cost goodput (pipelined wall
   <= 1.15x lock-step);
4. **drain** — a rollout-style ``drain_and_shutdown`` under live
   traffic loses nothing and duplicates nothing: every request either
   returns the correct phi for ITS row or is cleanly rejected, no
   client hangs, and the drain completes inside its grace window;
5. **pod chargeback** — the lead's ``dks_device_seconds_total`` accrual
   over a sequential request stream is within 5% of the independent
   per-process clock sum (2 x the lead's own dispatch-to-fetch wall —
   the SPMD program occupies both processes' devices for the same
   interval).

Self-records into ``results/perf_history.jsonl`` with ``checks_ok``
(``bcast_bytes_per_row_b1`` and ``pipelined_row_s`` are recorded
higher-is-worse so ``make perf-gate`` gates them like wall time).

    python benchmarks/pod_serve_bench.py --check        # = make pod-bench
"""

import argparse
import http.client
import json
import os
import queue
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_DEVICES = 4
D, K = 6, 3
N_BG = int(os.environ.get("DKS_POD_BENCH_NBG", "4"))
NSAMPLES = int(os.environ.get("DKS_POD_BENCH_NSAMPLES", "16"))
MAX_ROWS = 64
PARITY_ROWS = 8
GOODPUT_ROWS = 48
METER_ROWS = 12
DRAIN_ROWS = 16
EXPLAIN_KWARGS = {"nsamples": NSAMPLES, "l1_reg": False}

_WORKER = """
import sys
sys.path.insert(0, sys.argv[4])
import jax
jax.config.update("jax_platforms", "cpu")
from distributedkernelshap_tpu.compat import force_cpu_devices
force_cpu_devices(2)
pid = int(sys.argv[1])
from distributedkernelshap_tpu.parallel.mesh import initialize_multihost
initialize_multihost("127.0.0.1:" + sys.argv[2], 2, pid)
assert jax.process_count() == 2
import benchmarks.pod_serve_bench as bench
bench.pod_leg(sys.argv[3])
"""


def _tiny_problem():
    """The tiny deterministic synthetic deployment every leg shares
    (tests/test_multihost.py's recipe): a softmax-linear predictor the
    jitted explain evaluates on-device — fast to fit, no dataset."""

    rng = np.random.default_rng(0)
    W = rng.normal(size=(D, K)).astype(np.float32)
    bg = rng.normal(size=(N_BG, D)).astype(np.float32)
    X = rng.normal(size=(PARITY_ROWS, D)).astype(np.float32)

    def pred(A):
        import jax.numpy as jnp

        z = A @ W
        return jnp.exp(z) / jnp.exp(z).sum(-1, keepdims=True)

    return pred, bg, X


def _direct_phi(pred, bg, X, opts):
    from distributedkernelshap_tpu import KernelShap

    ex = KernelShap(pred, link="identity", seed=0, distributed_opts=opts)
    ex.fit(bg)
    sv = ex.explain(X, silent=True, **EXPLAIN_KWARGS).shap_values
    return np.stack(sv, 1)


def _wait_ready(port: int, timeout_s: float = 120.0) -> None:
    """Block until the lead's /healthz answers 200 — the warmup ladder
    (broadcast ``_CMD_WARMUP`` rungs) must finish before any snapshot,
    or warmup frames pollute the per-request byte accounting."""

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            conn.request("GET", "/healthz")
            status = conn.getresponse().status
            conn.close()
            if status == 200:
                return
        except OSError:
            pass
        time.sleep(0.2)
    raise RuntimeError(f"server on :{port} not ready in {timeout_s:.0f}s")


def _served_phi(port: int, X: np.ndarray, max_workers: int):
    from distributedkernelshap_tpu.serving import client as cl

    payloads = cl.distribute_requests(
        f"http://127.0.0.1:{port}/explain", X, max_workers=max_workers)
    return np.stack([
        np.asarray(json.loads(p)["data"]["shap_values"])[:, 0]
        for p in payloads])


def _device_seconds(server) -> float:
    total = 0.0
    for line in server.metrics.render().splitlines():
        if line.startswith("dks_device_seconds_total{"):
            total += float(line.rsplit(" ", 1)[1])
    return total


def _pod_bytes_total() -> float:
    from distributedkernelshap_tpu.serving.multihost import (
        pod_bcast_byte_counts,
    )

    return sum(pod_bcast_byte_counts().values())


def _raw_explain(port: int, row: np.ndarray, timeout_s: float = 120.0):
    """One retry-free /explain POST: ``(status, phi | None)``.  Status -1
    = connection-level rejection (server already stopped accepting) —
    clean for the drain criterion; only a HANG counts as lost."""

    body = json.dumps({"array": np.asarray(row)[None].tolist()}).encode()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=timeout_s)
        conn.request("POST", "/explain", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        status, payload = resp.status, resp.read()
        conn.close()
    except OSError:
        return -1, None
    if status != 200:
        return status, None
    phi = np.asarray(
        json.loads(payload)["data"]["shap_values"])[:, 0]
    return status, phi


def _serve_round(pred, bg, opts, pipeline_depth=None):
    from distributedkernelshap_tpu.serving.multihost import serve_multihost

    # staging=False on BOTH rounds: at max_batch_size=1 the staging
    # batcher is pure added latency, and leaving it on only for the
    # pipelined round (its production default) would conflate the
    # batcher with the protocol this bench isolates
    return serve_multihost(
        pred, bg, {"link": "identity", "seed": 0}, {}, opts,
        host="127.0.0.1", port=0, max_batch_size=1, max_rows=MAX_ROWS,
        explain_kwargs=dict(EXPLAIN_KWARGS),
        pipeline_depth=pipeline_depth, staging=False)


def pod_leg(outdir: str) -> None:
    """Per-process body: direct sharded explain (reference), then the
    lock-step serve round, then the pipelined serve round with the drain
    arm.  Followers participate via the broadcast loop each round; the
    lead measures and saves the artifact."""

    import jax

    def mark(msg):
        print(f"[pod_leg p{jax.process_index()}] {msg}", flush=True)

    pred, bg, X = _tiny_problem()
    is_lead = jax.process_index() == 0

    # direct sharded explain FIRST, on every process simultaneously (a
    # sharded explain is a collective program)
    direct = _direct_phi(pred, bg, X, {"n_devices": N_DEVICES})
    mark("direct explain done")

    out = {}

    # ---- round A: lock-step protocol --------------------------------- #
    srv = _serve_round(pred, bg, {"n_devices": N_DEVICES,
                                  "replicate_results": False})
    mark("round A serve returned")
    if srv is not None:
        try:
            _wait_ready(srv.port)
            mark("round A ready")
            from distributedkernelshap_tpu.serving.multihost import (
                MultihostServingModel,
                PipelinedMultihostServingModel,
            )

            assert isinstance(srv.model, MultihostServingModel)
            assert not isinstance(srv.model, PipelinedMultihostServingModel)
            # parity stream doubles as the B=1 frame-size measurement
            bytes0 = _pod_bytes_total()
            phi_lock = _served_phi(srv.port, X, max_workers=4)
            out["bcast_bytes_per_row_b1"] = \
                (_pod_bytes_total() - bytes0) / PARITY_ROWS
            # what every frame would cost if padded to the full slot,
            # under the SAME wire the round actually used (the KV wire
            # carries frames as-is; the collective wire MTU-chunks them)
            from distributedkernelshap_tpu.serving.multihost import (
                _HEADER_LEN,
                _chunk_elems,
                _payload_chunks,
            )

            if srv.model._uniform_wire:
                out["full_slot_frame_bytes"] = \
                    (1 + _payload_chunks(MAX_ROWS, D)) * _chunk_elems(D) * 4
            else:
                out["full_slot_frame_bytes"] = \
                    (_HEADER_LEN + MAX_ROWS * D) * 4

            # pod chargeback: meter accrual vs 2x the lead's own
            # dispatch-to-fetch clock over a sequential stream (shim the
            # pod model's explain_batch — the exact span the costmeter
            # brackets; everything is compiled by now, so the meter's
            # compile exclusion subtracts nothing)
            pod, shim = srv.model, {"s": 0.0}
            orig = pod.explain_batch

            def timed(stacked, split_sizes=None, formats=None):
                t0 = time.monotonic()
                try:
                    return orig(stacked, split_sizes=split_sizes,
                                formats=formats)
                finally:
                    shim["s"] += time.monotonic() - t0

            pod.explain_batch = timed
            meter0 = _device_seconds(srv)
            _served_phi(srv.port, np.tile(X, (METER_ROWS // PARITY_ROWS
                                              + 1, 1))[:METER_ROWS],
                        max_workers=1)
            out["meter_device_s"] = _device_seconds(srv) - meter0
            out["clock_sum_device_s"] = 2.0 * shim["s"]
            pod.explain_batch = orig

            # lock-step goodput: the dispatcher hot path itself — a
            # B=1 frame backlog through the pod model exactly as the
            # server's dispatch loop runs it (broadcast, sync device
            # call, cross-process result allgather), measured without
            # the HTTP client sharing this process's interpreter
            t0 = time.monotonic()
            occ = 0.0
            for i in range(GOODPUT_ROWS):
                t1 = time.monotonic()
                srv.model.explain_batch(X[i % PARITY_ROWS][None],
                                        split_sizes=[1])
                occ += time.monotonic() - t1
            out["lockstep_wall_s"] = time.monotonic() - t0
            out["lockstep_dispatch_occupancy_s"] = occ / GOODPUT_ROWS
            np.save(os.path.join(outdir, "phi_lock.npy"), phi_lock)
        finally:
            srv.model.drain_and_shutdown(srv)
    mark("round A done")

    # ---- round B: pipelined protocol (the production default) -------- #
    srv = _serve_round(pred, bg, {"n_devices": N_DEVICES},
                       pipeline_depth=4)
    mark("round B serve returned")
    if srv is None:
        return  # follower: released by round B's shutdown broadcast
    try:
        _wait_ready(srv.port)
        mark("round B ready")
        from distributedkernelshap_tpu.serving.multihost import (
            PipelinedMultihostServingModel,
        )

        assert isinstance(srv.model, PipelinedMultihostServingModel)
        phi_pipe = _served_phi(srv.port, X, max_workers=4)
        # pipelined goodput: the same backlog through the pipelined
        # dispatch — broadcast + async device dispatch up to depth in
        # flight, finalizes (now local fetches) retired in dispatch
        # order off the dispatcher's thread
        depth = 4
        sem = threading.Semaphore(depth)
        fin_q = queue.Queue()

        def _finisher():
            while True:
                fin = fin_q.get()
                if fin is None:
                    return
                fin()
                sem.release()

        fth = threading.Thread(target=_finisher, daemon=True)
        fth.start()
        t0 = time.monotonic()
        occ = 0.0
        cpu = 0.0
        for i in range(GOODPUT_ROWS):
            sem.acquire()
            t1 = time.monotonic()
            c1 = time.thread_time()
            fin = srv.model.explain_batch_async(
                X[i % PARITY_ROWS][None], split_sizes=[1])
            cpu += time.thread_time() - c1
            occ += time.monotonic() - t1
            fin_q.put(fin)
        fin_q.put(None)
        fth.join()
        out["pipelined_wall_s"] = time.monotonic() - t0
        out["pipelined_dispatch_occupancy_s"] = occ / GOODPUT_ROWS
        # the starvation-free occupancy for the single-core gate (see
        # module docstring check 3): the dispatch thread's own CPU time,
        # which is the wall a multi-core host would observe for a
        # dispatcher that never blocks
        out["pipelined_dispatch_cpu_s"] = cpu / GOODPUT_ROWS
        out["cpu_parallelism"] = len(os.sched_getaffinity(0))
        mark("round B goodput done")

        # ---- drain arm: rollout under live traffic ------------------- #
        results = []  # (row_idx, status, phi | None)
        res_lock = threading.Lock()

        def _client(rows):
            for i in rows:
                status, phi = _raw_explain(srv.port, X[i % PARITY_ROWS])
                with res_lock:
                    results.append((i % PARITY_ROWS, status, phi))

        threads = [threading.Thread(target=_client,
                                    args=([2 * t, 2 * t + 1],),
                                    daemon=True)
                   for t in range(DRAIN_ROWS // 2)]
        for t in threads:
            t.start()
        time.sleep(0.25)  # let some requests get in flight
        out["drain_clean"] = bool(srv.model.drain_and_shutdown(
            srv, grace_s=60.0))
        for t in threads:
            t.join(timeout=180)
        out["drain_lost"] = sum(t.is_alive() for t in threads)
        ok, rejected, wrong = 0, 0, 0
        for row, status, phi in results:
            if status == 200:
                ok += 1
                if not np.allclose(phi, direct[row], atol=1e-5):
                    wrong += 1
            else:
                rejected += 1
        out["drain_ok"] = ok
        out["drain_rejected"] = rejected
        out["drain_wrong_phi"] = wrong
        out["drain_responses"] = len(results)
    finally:
        if not srv.model._shut:
            srv.model.drain_and_shutdown(srv)

    if is_lead:
        np.save(os.path.join(outdir, "direct.npy"), direct)
        np.save(os.path.join(outdir, "phi_pipe.npy"), phi_pipe)
        with open(os.path.join(outdir, "pod_lead.json"), "w") as f:
            json.dump(out, f)


# ---------------------------------------------------------------------- #
# driver


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two(workdir: str, timeout: float):
    port = _free_port()
    worker = os.path.join(workdir, "worker.py")
    with open(worker, "w") as f:
        f.write(_WORKER)
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    logs = [os.path.join(workdir, f"pod{pid}.log") for pid in range(2)]
    procs = []
    try:
        for pid in range(2):
            with open(logs[pid], "wb") as log:
                procs.append(subprocess.Popen(
                    [sys.executable, worker, str(pid), str(port),
                     workdir, REPO],
                    cwd=workdir, env=env, stdout=log,
                    stderr=subprocess.STDOUT))
        for p in procs:
            p.wait(timeout=timeout)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
    texts = [open(log, errors="replace").read() for log in logs]
    for pid, p in enumerate(procs):
        if p.returncode != 0:
            raise RuntimeError(
                f"pod process {pid} exited {p.returncode}:\n"
                + texts[pid][-1500:]
                + f"\n---- peer log (p{1 - pid}) ----\n"
                + texts[1 - pid][-1500:])


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--check", action="store_true",
                        help="exit nonzero unless every criterion holds")
    parser.add_argument("--timeout", default=540.0, type=float)
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history append")
    args = parser.parse_args()

    t_start = time.monotonic()
    checks, report = {}, {}
    try:
        with tempfile.TemporaryDirectory() as tmp:
            _run_two(tmp, args.timeout)
            direct = np.load(os.path.join(tmp, "direct.npy"))
            phi_lock = np.load(os.path.join(tmp, "phi_lock.npy"))
            phi_pipe = np.load(os.path.join(tmp, "phi_pipe.npy"))
            with open(os.path.join(tmp, "pod_lead.json")) as f:
                lead = json.load(f)

        # 1. parity: both protocols vs the same-mesh direct explain, and
        # the mesh vs a single-process run of the same plan
        report["parity_max_err_lockstep"] = float(
            np.max(np.abs(phi_lock - direct)))
        report["parity_max_err_pipelined"] = float(
            np.max(np.abs(phi_pipe - direct)))
        checks["phi_lockstep_matches_direct"] = bool(
            np.allclose(phi_lock, direct, atol=1e-5))
        checks["phi_pipelined_matches_direct"] = bool(
            np.allclose(phi_pipe, direct, atol=1e-5))
        import jax

        jax.config.update("jax_platforms", "cpu")
        from distributedkernelshap_tpu.compat import force_cpu_devices

        force_cpu_devices(N_DEVICES)
        pred, bg, X = _tiny_problem()
        single = _direct_phi(pred, bg, X, {"n_devices": N_DEVICES})
        checks["phi_matches_single_process"] = bool(
            np.allclose(direct, single, atol=1e-5))

        # 2. bucketed frames beat the full slot on the B=1 stream
        per_row = lead["bcast_bytes_per_row_b1"]
        full_slot = lead["full_slot_frame_bytes"]
        report["bcast_bytes_per_row_b1"] = round(per_row, 1)
        report["full_slot_frame_bytes"] = full_slot
        checks["bucketed_frames_beat_full_slot"] = \
            per_row <= 0.5 * full_slot

        # 3. pipelined goodput (see module docstring: on a single-CPU
        # host overlap cannot buy throughput, so the gate moves to the
        # dispatcher-occupancy mechanism + a no-overhead bound)
        lock_rows_s = GOODPUT_ROWS / lead["lockstep_wall_s"]
        pipe_rows_s = GOODPUT_ROWS / lead["pipelined_wall_s"]
        ratio = pipe_rows_s / lock_rows_s
        # lock-step occupancy is wall (blocked end-to-end by protocol);
        # pipelined occupancy is the dispatch thread's CPU time (its
        # wall is starvation-inflated on a single core — docstring #3)
        occ_ratio = (lead["lockstep_dispatch_occupancy_s"]
                     / max(lead["pipelined_dispatch_cpu_s"], 1e-9))
        report["lockstep_rows_per_s"] = round(lock_rows_s, 1)
        report["pipelined_rows_per_s"] = round(pipe_rows_s, 1)
        report["pipelined_goodput_ratio"] = round(ratio, 2)
        report["pipelined_dispatch_ms"] = round(
            lead["pipelined_dispatch_cpu_s"] * 1e3, 2)
        report["dispatch_occupancy_ratio"] = round(occ_ratio, 2)
        report["cpu_parallelism"] = lead["cpu_parallelism"]
        if lead["cpu_parallelism"] > 1:
            checks["pipelined_goodput_ge_1_3x"] = ratio >= 1.3
        else:
            checks["pipelined_dispatch_occupancy_ge_1_3x"] = \
                occ_ratio >= 1.3
            checks["pipelining_costs_no_goodput"] = ratio >= 1 / 1.15

        # 4. drain: nothing lost, nothing duplicated/cross-wired
        report["drain"] = {k: lead[k] for k in
                           ("drain_clean", "drain_lost", "drain_ok",
                            "drain_rejected", "drain_wrong_phi",
                            "drain_responses")}
        checks["drain_zero_lost"] = (
            lead["drain_lost"] == 0
            and lead["drain_responses"] == DRAIN_ROWS)
        checks["drain_zero_dup_or_mixup"] = lead["drain_wrong_phi"] == 0
        checks["drain_served_some"] = lead["drain_ok"] >= 1
        checks["drain_completed_in_grace"] = bool(lead["drain_clean"])

        # 5. pod chargeback within 5% of the per-process clock sum
        meter, clock = lead["meter_device_s"], lead["clock_sum_device_s"]
        report["meter_device_s"] = round(meter, 4)
        report["clock_sum_device_s"] = round(clock, 4)
        checks["device_seconds_within_5pct"] = (
            clock > 0 and abs(meter - clock) / clock <= 0.05)
    except Exception as e:  # noqa: BLE001 - bench reports, never raises
        checks["error"] = f"{type(e).__name__}: {e}"
        print(json.dumps({"pod_serve_bench": "fail", "checks": checks,
                          **report}))
        return 1

    report["checks"] = checks
    report["elapsed_s"] = round(time.monotonic() - t_start, 1)

    if not args.no_record:
        from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

        entry = record_run(
            DEFAULT_HISTORY, "pod_serve_bench",
            config={"processes": 2, "devices": N_DEVICES,
                    "features": D, "max_rows": MAX_ROWS,
                    "goodput_rows": GOODPUT_ROWS, "max_batch_size": 1},
            metrics={
                # the production (pipelined) arm's goodput wall
                "wall_s": lead["pipelined_wall_s"],
                # recorded higher-is-worse so perf-gate gates them
                "pipelined_row_s": lead["pipelined_wall_s"]
                / GOODPUT_ROWS,
                "bcast_bytes_per_row_b1": per_row,
            },
            extra={"pod_processes": 2,
                   "pipelined_goodput_ratio": round(ratio, 2),
                   "dispatch_occupancy_ratio": round(occ_ratio, 2),
                   "cpu_parallelism": lead["cpu_parallelism"],
                   "lockstep_rows_per_s": round(lock_rows_s, 1),
                   "pipelined_rows_per_s": round(pipe_rows_s, 1),
                   # the key regression_gate filters failed runs out of
                   # the baseline median by
                   "checks_ok": all(checks.values())})
        report["perf_history"] = {"git_sha": entry["git_sha"],
                                  "config_fp": entry["config_fp"]}

    print(json.dumps({"pod_serve_bench":
                      "ok" if all(checks.values()) else "fail",
                      **report}))
    if args.check and not all(checks.values()):
        print(json.dumps({"failed_checks":
                          [k for k, v in checks.items() if not v]}),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
