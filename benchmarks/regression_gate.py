"""Perf-regression gate over an append-only benchmark history.

The paper's contribution IS a wall-clock number (2560-instance Adult:
1736.89 s sequential → 125.05 s on 32 workers), and every serving PR
ships with a measured benchmark — but until now nothing compared one
run against the last: a commit could quietly regress `scheduling_bench`
by 25% and every later run would just re-print the new, slower number.
This module closes the loop:

* **history** — every measured run appends one JSON line to
  ``results/perf_history.jsonl``: benchmark name, git SHA, a
  **config fingerprint** (sha256 over the canonical JSON of the knobs
  that shape the measurement — request counts, overload factor, batch
  sizes), and the run's headline metrics (wall seconds, p99s, goodput).
  ``scheduling_bench`` and ``chaos_bench`` self-record on every measured
  run, so the history accretes without anyone remembering to write it.
* **gate** — ``python benchmarks/regression_gate.py --check`` compares
  the newest run of each benchmark against a **trailing baseline**: the
  median of the last N prior runs with the SAME benchmark AND config
  fingerprint (a config change starts a fresh baseline instead of
  producing a false regression).  The gate fails when the newest run's
  wall time (or the autoscale bench's ``replica_seconds`` provisioning
  cost — its wall is a fixed open-loop trace) exceeds the baseline
  median by more than ``--max-wall-regression`` (default 20%) or any
  ``*p99_s`` metric by ``--max-p99-regression`` (default 50% — a p99
  over a few dozen requests is one order statistic and noisy).
  Lower-is-better only: a run that got FASTER never fails, it just
  tightens the next baseline.

First runs (no baseline yet) pass with a note — a gate that fails on an
empty history would block the first measurement forever.

    python benchmarks/regression_gate.py --check
    python benchmarks/regression_gate.py --record '{"bench": ...}'
    make perf-gate
"""

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_HISTORY = os.path.join(REPO_ROOT, "results", "perf_history.jsonl")

#: default regression thresholds (fractions over the baseline median);
#: wall time is tight, p99 deliberately loose — a p99 over a few dozen
#: open-loop requests is a single order statistic (measured run-to-run
#: spread ~±30%), so a tight p99 gate would page on noise
MAX_WALL_REGRESSION = 0.20
MAX_P99_REGRESSION = 0.50

#: trailing runs folded into the baseline median
BASELINE_N = 5


def config_fingerprint(config: Dict) -> str:
    """Stable hash of the measurement-shaping knobs: runs are only
    comparable when these match."""

    canonical = json.dumps(config, sort_keys=True, separators=(",", ":"),
                           default=repr)
    return hashlib.sha256(canonical.encode()).hexdigest()[:16]


def git_sha() -> str:
    env = os.environ.get("DKS_GIT_SHA")
    if env:
        return env
    try:
        out = subprocess.run(["git", "rev-parse", "--short=12", "HEAD"],
                             cwd=REPO_ROOT, capture_output=True, text=True,
                             timeout=10)
        if out.returncode == 0:
            return out.stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        pass
    return "unknown"


def record_run(history_path: str, bench: str, config: Dict,
               metrics: Dict[str, float],
               extra: Optional[Dict] = None,
               model_id: Optional[str] = None,
               model_version: Optional[int] = None) -> Dict:
    """Append one run to the history (fsync'd, one JSON line) and return
    the entry.  ``metrics`` should carry ``wall_s`` plus any ``*p99_s``
    series the gate should watch.  ``model_id``/``model_version``
    attribute the run to one registered model (multi-tenant fleets) —
    they fold into ``config`` BEFORE fingerprinting, so runs against
    different models (or versions) get distinct baselines instead of
    polluting each other's medians."""

    if model_id is not None:
        config = dict(config, model_id=model_id)
        if model_version is not None:
            config["model_version"] = model_version
    entry = {
        "ts": time.time(),
        "bench": bench,
        "git_sha": git_sha(),
        "config": config,
        "config_fp": config_fingerprint(config),
        "metrics": {k: float(v) for k, v in metrics.items()
                    if v is not None},
    }
    if extra:
        entry["extra"] = extra
    os.makedirs(os.path.dirname(os.path.abspath(history_path)),
                exist_ok=True)
    with open(history_path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(entry) + "\n")
        fh.flush()
        os.fsync(fh.fileno())
    return entry


def load_history(history_path: str) -> List[Dict]:
    """All parseable entries, file order (== chronological for an
    append-only file).  A torn trailing line — a run killed mid-append —
    is skipped, like the shard journal's torn-tail rule."""

    if not os.path.exists(history_path):
        return []
    entries = []
    with open(history_path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and "bench" in doc \
                    and "metrics" in doc:
                entries.append(doc)
    return entries


def _median(values: List[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    return (ordered[mid] if n % 2
            else (ordered[mid - 1] + ordered[mid]) / 2.0)


def _threshold_for(metric: str, max_wall: float,
                   max_p99: float) -> Optional[float]:
    if metric == "wall_s":
        return max_wall
    if metric == "replica_seconds":
        # the autoscale bench's provisioning cost: its wall is a FIXED
        # open-loop trace, so replica-seconds is the number a scaler
        # regression would move — gated as tightly as wall time
        return max_wall
    if metric == "metered_median_s":
        # the cost-attribution bench's metering-overhead sentinel: the
        # metered arm's median request latency (a meter that got
        # expensive moves it); a median is far more stable than a p99,
        # so gate it like wall time
        return max_wall
    if metric == "prof_overhead_factor":
        # the profiling bench's sampler-overhead sentinel: median
        # request latency with the sampler on over median with it off
        # (so pinned near 1.0 by construction).  A sampler that got
        # expensive moves it directly; medians are stable, gate it like
        # wall time
        return max_wall
    if metric == "audit_overhead_factor":
        # the quality bench's invariant-auditor sentinel, same shape as
        # prof_overhead_factor: median latency audit-on over audit-off
        # under per-request alternation.  Audit work leaking back onto
        # the serving latency path moves it off 1.0
        return max_wall
    if metric == "err_at_deadline":
        # the anytime bench's degradation depth: mean reported error of
        # the answers the deadline actually bought under overload.  An
        # estimator, calibration or scheduler regression all surface as
        # MORE residual error at the same deadline — gated like wall time
        return max_wall
    if metric == "bcast_bytes_per_row_b1":
        # the pod bench's broadcast-frame size on a B=1 stream, in bytes
        # per row (HIGHER is worse — frames crept back toward the old
        # full-slot padding).  Deterministic by construction (header +
        # smallest-bucket payload), so gate it as tightly as wall time
        return max_wall
    if metric == "pipelined_row_s":
        # the pod bench's pipelined goodput, recorded INVERTED (seconds
        # per row, so higher is worse like every gated metric): the
        # pipelined hot path losing overlap shows up here directly
        return max_wall
    if metric == "rounds_per_request_p50":
        # the complementary stop-rule sentinel: at a fixed schedule and
        # deadline, rounds per request CLIMBING means requests keep
        # buying rounds they should have stopped at (budget-met or
        # deadline-imminent detection firing late) — device time other
        # requests needed; rounds DROPPING shows up as err_at_deadline
        # rising, which the branch above gates.  Scheduling-noisy, so
        # use the p99 budget
        return max_p99
    if metric.endswith("p99_s"):
        return max_p99
    return None  # informational metric: recorded, never gated


def gate_bench(entries: List[Dict], newest: Optional[Dict] = None,
               max_wall: float = MAX_WALL_REGRESSION,
               max_p99: float = MAX_P99_REGRESSION,
               baseline_n: int = BASELINE_N) -> Dict:
    """Gate one run (default: the benchmark's newest entry) against the
    median of the last ``baseline_n`` PRIOR runs sharing its config
    fingerprint.  ``entries`` are one benchmark's runs, chronological."""

    if newest is None:
        newest = entries[-1]
    prior = entries[:entries.index(newest)]
    # a run whose OWN acceptance checks failed (timeouts, lost requests)
    # carries an inflated wall — folding it into the median would shift
    # the baseline up and mask a later genuine regression, so failed
    # runs are recorded (history stays honest) but never baseline
    baseline_pool = [
        e for e in prior
        if e.get("config_fp") == newest.get("config_fp")
        and e.get("extra", {}).get("checks_ok") is not False]
    baseline = baseline_pool[-baseline_n:]
    result = {
        "bench": newest["bench"],
        "git_sha": newest.get("git_sha"),
        "config_fp": newest.get("config_fp"),
        "baseline_runs": len(baseline),
        "comparisons": {},
        "ok": True,
    }
    if not baseline:
        result["note"] = ("no prior run with this config fingerprint — "
                          "recorded as the new baseline")
        return result
    for metric, value in sorted(newest["metrics"].items()):
        threshold = _threshold_for(metric, max_wall, max_p99)
        if threshold is None:
            continue
        base_values = [e["metrics"][metric] for e in baseline
                       if metric in e["metrics"]]
        if not base_values:
            continue
        base = _median(base_values)
        if base <= 0:
            continue
        ratio = value / base
        regressed = ratio > 1.0 + threshold
        result["comparisons"][metric] = {
            "value": round(value, 4), "baseline_median": round(base, 4),
            "ratio": round(ratio, 4), "threshold": 1.0 + threshold,
            "regressed": regressed,
        }
        if regressed:
            result["ok"] = False
    return result


def gate(history_path: str, bench: Optional[str] = None,
         max_wall: float = MAX_WALL_REGRESSION,
         max_p99: float = MAX_P99_REGRESSION,
         baseline_n: int = BASELINE_N, recent_n: int = 10) -> Dict:
    """Gate every benchmark in the history (or just ``bench``): for each
    benchmark, the newest run of EVERY config fingerprint appearing in
    its trailing ``recent_n`` entries is gated — gating only the single
    newest entry would let one differently-configured run (a fresh
    fingerprint, hence a free pass) bury a recorded regression in the
    run just before it.  ``ok`` is the AND across all gated runs; an
    empty history passes with a note (nothing measured yet = nothing
    regressed)."""

    entries = load_history(history_path)
    if bench is not None:
        entries = [e for e in entries if e["bench"] == bench]
    by_bench: Dict[str, List[Dict]] = {}
    for e in entries:
        by_bench.setdefault(e["bench"], []).append(e)
    results = []
    for _, runs in sorted(by_bench.items()):
        newest_per_fp: Dict[str, Dict] = {}
        for e in runs[-recent_n:]:
            newest_per_fp[e.get("config_fp")] = e
        for e in sorted(newest_per_fp.values(), key=runs.index):
            results.append(gate_bench(runs, newest=e, max_wall=max_wall,
                                      max_p99=max_p99,
                                      baseline_n=baseline_n))
    report = {
        "history": history_path,
        "entries": len(entries),
        "benches": results,
        "ok": all(r["ok"] for r in results),
    }
    if not entries:
        report["note"] = "empty history: nothing to gate"
    return report


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--history", default=DEFAULT_HISTORY,
                        help="perf-history JSONL path")
    parser.add_argument("--bench", default=None,
                        help="gate only this benchmark name")
    parser.add_argument("--check", action="store_true",
                        help="exit 1 on any regression")
    parser.add_argument("--record", default=None, metavar="JSON",
                        help="append one entry: a JSON object with "
                             "bench/config/metrics keys (synthetic "
                             "entries for testing the gate)")
    parser.add_argument("--max-wall-regression", type=float,
                        default=MAX_WALL_REGRESSION,
                        help="allowed wall_s increase over baseline "
                             "median (fraction)")
    parser.add_argument("--max-p99-regression", type=float,
                        default=MAX_P99_REGRESSION,
                        help="allowed *p99_s increase over baseline "
                             "median (fraction)")
    parser.add_argument("--baseline-n", type=int, default=BASELINE_N,
                        help="trailing runs in the baseline median")
    args = parser.parse_args()

    if args.record is not None:
        doc = json.loads(args.record)
        entry = record_run(args.history, doc["bench"],
                           doc.get("config", {}), doc["metrics"],
                           extra=doc.get("extra"))
        print(json.dumps(entry))
        return 0

    report = gate(args.history, bench=args.bench,
                  max_wall=args.max_wall_regression,
                  max_p99=args.max_p99_regression,
                  baseline_n=args.baseline_n)
    print(json.dumps(report))
    if args.check and not report["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
