"""Chaos benchmark: fault-tolerant serving + resumable batch runs
(standalone, CPU backend, exits nonzero on ``--check`` fail).

Two scenarios, one JSON line:

1. **Serve chaos** — a 3-replica fleet of REAL worker processes
   (``serving/replica_worker.py``, synthetic factory) behind the fan-in
   proxy with hedging enabled, replica 2 scripted slow via the fault
   harness (``DKS_FAULTS=slow:site=server.explain,...,replica=2``).
   Mid-run, replica 0 is SIGKILLed; the supervisor restarts it with
   backoff and the prober returns it to rotation.  Every request carries
   a unique instance row, and the parent reconstructs the (seeded,
   deterministic) model to verify each answer against ITS OWN request.
   Criteria: every request answered exactly once (zero lost, zero
   duplicated/mixed-up), additivity intact on every payload, the killed
   replica restarted, and at least one hedge win against the slow
   replica.  Client-side retries of 502/503 are part of the scenario —
   explanations are idempotent (deterministic + content-addressed), so a
   retry can change WHERE the answer computes, never WHAT it is.

3. **Scaler chaos** — the autoscaler's control loop is crashed
   (thread-scoped) and wedged (hang) at the ``scaler.tick`` fault site:
   either way the fleet must stay at its CURRENT size and keep serving
   (a dead control plane degrades to a static fleet, never drains the
   data plane).

2. **Pool resume** — a sharded batch explain run in a subprocess with
   shard journaling on (``distributed_opts['checkpoint_dir']``), killed
   deterministically by ``DKS_FAULTS=crash:site=pool.shard,after=K``
   (the crash lands after the K-th shard's fetch but BEFORE its journal
   record — the worst case).  A second invocation resumes.  Criteria:
   the journal survived with exactly K-1 shards, the resume restored
   them and recomputed only the rest (total recomputed overlap <= 1
   shard), and the resumed phi is BIT-IDENTICAL to an uninterrupted
   reference run.

    JAX_PLATFORMS=cpu python benchmarks/chaos_bench.py --check
"""

import argparse
import http.client
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

#: worker/subprocess env: import the repo without installation, CPU-only
BASE_ENV = {"PYTHONPATH": REPO_ROOT, "JAX_PLATFORMS": "cpu"}

FACTORY = ("distributedkernelshap_tpu.serving."
           "replica_worker:synthetic_factory")


# --------------------------------------------------------------------- #
# scenario 1: serve chaos (kill one replica + one slow replica)
# --------------------------------------------------------------------- #


def _synthetic_reference():
    """The same deterministic model ``synthetic_factory`` builds inside
    each worker — recomputed here so every answer can be verified against
    its own request."""

    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 8)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    return LogisticRegression(max_iter=200).fit(X, y)


def _scrape(host, port, path="/metrics"):
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path)
        return conn.getresponse().read().decode()
    finally:
        conn.close()


def _metric(text, name):
    """Sum of one family's samples, via the registry's own exposition
    parser (one parser for the whole tree; handles labeled series too)."""

    from distributedkernelshap_tpu.observability.metrics import (
        parse_exposition,
    )

    family = parse_exposition(text).get(name)
    if not family:
        return 0.0
    return sum(v for sample_name, _, v in family["samples"]
               if sample_name == name)


def run_serve_chaos(n_requests=48, n_replicas=3, slow_delay_s=0.5,
                    kill_after_s=1.5, client_threads=6, trace_dir=None):
    from distributedkernelshap_tpu.resilience.hedging import HedgePolicy
    from distributedkernelshap_tpu.resilience.supervisor import RestartPolicy
    from distributedkernelshap_tpu.serving.client import explain_request
    from distributedkernelshap_tpu.serving.replicas import ReplicaManager

    # replica n-1 answers every /explain slow_delay_s late — a straggler,
    # not a corpse: only hedging can cut the tail it creates
    faults = (f"slow:site=server.explain,delay={slow_delay_s},"
              f"replica={n_replicas - 1}")
    env_extra = {**BASE_ENV, "DKS_FAULTS": faults}
    if trace_dir:
        # workers sink every finished span to <trace_dir>/spans-<pid>.jsonl
        # (flushed per span, so the SIGKILLed replica's spans survive);
        # the parent merges them with its own client+proxy spans
        env_extra.update({"DKS_TRACE": "1", "DKS_TRACE_DIR": trace_dir})
    manager = ReplicaManager(
        n_replicas, factory=FACTORY, pin_devices=False, restart=True,
        env_extra=env_extra,
        max_batch_size=4, pipeline_depth=2, startup_timeout_s=300,
        restart_policy=RestartPolicy(base_backoff_s=0.25, max_backoff_s=2.0,
                                     jitter_frac=0.25, seed=0),
        # aggressive hedge (median) so EVERY slow-replica request hedges:
        # the bench demonstrates the tail cut, production would run p95
        hedge_policy=HedgePolicy(quantile=0.5, min_delay_s=0.05,
                                 initial_delay_s=2.0, min_samples=8))
    rng = np.random.default_rng(7)
    instances = rng.normal(size=(n_requests, 1, 8)).astype(np.float32)
    answers = [None] * n_requests
    report = {}
    with manager:
        proxy = manager.proxy
        url = f"http://{proxy.host}:{proxy.port}/explain"

        # warmup: compile every replica and seed the hedge latency tracker
        for i in range(4 * n_replicas):
            explain_request(url, instances[0], timeout=120, max_retries=6)

        def fire(i):
            # bounded retries; 502/503 retried because explains are
            # idempotent — this is the "zero lost" mechanism under a kill
            answers[i] = explain_request(url, instances[i], timeout=120,
                                         max_retries=8)

        t0 = time.monotonic()
        killed = {}

        def killer():
            time.sleep(kill_after_s)
            victim = manager.procs[0]
            killed["pid"] = victim.pid
            os.kill(victim.pid, signal.SIGKILL)

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        with ThreadPoolExecutor(max_workers=client_threads) as pool:
            errors = []
            futs = [pool.submit(fire, i) for i in range(n_requests)]
            for i, f in enumerate(futs):
                try:
                    f.result()
                except Exception as e:  # lost request: recorded, not fatal
                    errors.append((i, str(e)))
        kt.join()
        wall = time.monotonic() - t0

        # the supervisor must resurrect the victim and the prober must
        # return it to rotation.  /healthz alone is not enough to wait
        # on: a fast client run can finish at the kill instant, and the
        # corpse's `alive` flag stays stale-True until the supervisor's
        # next tick — so "3 live" must be REACHED THROUGH a restart, not
        # observed before anyone noticed the death
        all_live = False
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            health = json.loads(_scrape(proxy.host, proxy.port, "/healthz"))
            if manager.supervisor.stats()["restarts_total"] >= 1 and \
                    len(health.get("live", [])) == n_replicas:
                all_live = True
                break
            time.sleep(1.0)
        metrics = _scrape(proxy.host, proxy.port)
        restarts = manager.supervisor.stats()["restarts_total"]

    # verify every answer against ITS OWN request: additivity inside the
    # payload, and the raw prediction against the reconstructed model —
    # a swapped/duplicated payload fails its request's check
    clf = _synthetic_reference()
    lost, mismatched, additivity_bad = [], [], []
    for i, payload in enumerate(answers):
        if payload is None:
            lost.append(i)
            continue
        try:
            data = json.loads(payload)["data"]
        except (ValueError, KeyError):
            mismatched.append(i)
            continue
        sv = np.asarray(data["shap_values"])          # (K, 1, M)
        e_val = np.asarray(data["expected_value"])    # (K,)
        raw = np.asarray(data["raw"]["raw_prediction"])  # (1, K)
        total = sv.sum(-1) + e_val[:, None]
        if not np.allclose(total, raw.T, atol=1e-3):
            additivity_bad.append(i)
        p = clf.predict_proba(instances[i])[0]
        expected_raw = np.log(p / (1.0 - p))  # logit link space
        if not np.allclose(raw[0], expected_raw, atol=1e-2):
            mismatched.append(i)

    # a retries-exhausted request appears in BOTH errors (the raised
    # exception) and lost (its answers slot stayed None) — count the slot
    return {
        "n": n_requests,
        "wall_s": round(wall, 2),
        "lost": len(lost),
        "mismatched": len(mismatched),
        "additivity_bad": len(additivity_bad),
        "client_gave_up": [e for _, e in errors][:3],
        "killed_pid": killed.get("pid"),
        "supervisor_restarts": int(restarts),
        "all_replicas_recovered": bool(all_live),
        "hedges": int(_metric(metrics, "dks_fanin_hedges_total")),
        "hedge_wins": int(_metric(metrics, "dks_fanin_hedge_wins_total")),
        "proxy_502s": int(_metric(metrics, "dks_fanin_replica_errors_total")),
    }


# --------------------------------------------------------------------- #
# scenario 3: wedged/killed autoscaler degrades to the current fleet size
# --------------------------------------------------------------------- #


def run_scaler_chaos():
    """Fault-inject the autoscaler's control loop (site ``scaler.tick``,
    ``resilience/faults.py``): a CRASHED scaler (thread-scoped — the
    control thread dies, the serving process lives) and a WEDGED one
    (hang) must both leave the fleet at its CURRENT size and serving —
    a dead control plane degrades to a static fleet, it never drains the
    data plane to zero.

    Runs against the in-process elastic fleet (real ``ExplainerServer``
    replicas + ``FanInProxy`` + the real ``Autoscaler``) so both fault
    kinds finish in seconds; the subprocess spawn/retire path is scenario
    1's fleet plus ``tests/test_autoscaler.py``."""

    from benchmarks.autoscale_bench import (
        DIM,
        LocalFleet,
        SyntheticServedModel,
        _post_with_retry,
    )
    from distributedkernelshap_tpu.resilience.faults import (
        FaultInjector,
        parse_faults,
    )
    from distributedkernelshap_tpu.serving.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
    )

    out = {}
    for kind in ("crash", "hang"):
        fleet = LocalFleet(SyntheticServedModel).start(2)
        scaler = None
        try:
            fleet.wait_ready()
            injector = FaultInjector(parse_faults(
                "crash:site=scaler.tick,after=3" if kind == "crash"
                else "hang:site=scaler.tick,after=3,delay=3600"))
            # down knobs deliberately inert (down_ticks huge): the ONLY
            # thing that may change the fleet before or after the fault
            # is the fault's effect itself
            scaler = Autoscaler(
                fleet, fleet.proxy,
                config=AutoscalerConfig(
                    min_replicas=1, max_replicas=3, interval_s=0.1,
                    down_ticks=10_000),
                fault_injector=injector).start()
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and \
                    injector.hits("scaler.tick") < 4:
                time.sleep(0.05)
            ticks_at_fault = scaler.ticks_total
            time.sleep(1.5)  # the window a dying scaler could misuse
            counts = fleet.proxy.replica_state_counts()
            status, _, _ = _post_with_retry(
                fleet.proxy.host, fleet.proxy.port,
                np.zeros((1, DIM), np.float32), {})
            out[kind] = {
                "fault_fired": injector.hits("scaler.tick") >= 4,
                "ready_after": counts.get("ready", 0),
                "draining_after": counts.get("draining", 0),
                "serving_after": status == 200,
                # crash: the loop thread must be DEAD; hang: alive but
                # frozen (no tick since the fault)
                "scaler_thread_alive": scaler._thread.is_alive(),
                "ticks_frozen": scaler.ticks_total == ticks_at_fault,
            }
        finally:
            if scaler is not None:
                scaler.stop()
            fleet.stop()
    return out


# --------------------------------------------------------------------- #
# scenario 2: killed-then-resumed pool run
# --------------------------------------------------------------------- #

POOL_INSTANCES = 64
POOL_BATCH = 8       # x 1 device -> 8 shards of 8 rows
POOL_NSAMPLES = 64
CRASH_AFTER = 4      # skip 4 shard completions; crash on the 5th shard's
                     # fetch, before its journal record — so the killed
                     # run computed CRASH_AFTER + 1 shards and durably
                     # recorded CRASH_AFTER


def pool_run(checkpoint_dir: str, out_path: str) -> dict:
    """One (possibly resumed) journaled pool explain — the subprocess
    body.  Deterministic end to end: seeded data, fixed shard layout,
    l1_reg off."""

    from distributedkernelshap_tpu import DenseData
    from distributedkernelshap_tpu.kernel_shap import KernelExplainerEngine
    from distributedkernelshap_tpu.models import LinearPredictor
    from distributedkernelshap_tpu.parallel.distributed import (
        DistributedExplainer,
    )

    rng = np.random.default_rng(3)
    D, K = 11, 2
    groups = [[0], [1], [2, 3, 4], [5, 6], [7, 8, 9, 10]]
    names = ["a", "b", "c", "d", "e"]
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    bg = rng.normal(size=(20, D)).astype(np.float32)
    X = rng.normal(size=(POOL_INSTANCES, D)).astype(np.float32)
    dist = DistributedExplainer(
        {"n_devices": 1, "batch_size": POOL_BATCH,
         "checkpoint_dir": checkpoint_dir},
        KernelExplainerEngine,
        (LinearPredictor(W, b, activation="softmax"),
         DenseData(bg, names, groups)),
        {"link": "logit", "seed": 0})
    sv = dist.get_explanation(X, nsamples=POOL_NSAMPLES, l1_reg=False)
    np.save(out_path, np.stack(sv if isinstance(sv, list) else [sv]))
    return dist.last_journal_stats


def _spawn_pool_run(checkpoint_dir: str, out_path: str, faults: str = "",
                    flightrec_dir: str = ""):
    env = {**os.environ, **BASE_ENV, "DKS_DISPATCH_WINDOW": "1"}
    env.pop("DKS_FAULTS", None)
    env.pop("DKS_FLIGHTREC_DIR", None)
    if faults:
        env["DKS_FAULTS"] = faults
    if flightrec_dir:
        # the injected crash dumps the flight recorder here before
        # os._exit — the black box the --check assertions read back
        env["DKS_FLIGHTREC_DIR"] = flightrec_dir
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--pool-run",
         "--checkpoint-dir", checkpoint_dir, "--out", out_path],
        env=env, capture_output=True, text=True, timeout=900)
    stats = None
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("{"):
            stats = json.loads(line)
            break
    return proc.returncode, stats, proc.stderr[-2000:]


def _journal_records(checkpoint_dir: str) -> int:
    names = [n for n in os.listdir(checkpoint_dir)
             if n.endswith(".journal")]
    if len(names) != 1:
        return -1
    with open(os.path.join(checkpoint_dir, names[0])) as fh:
        return max(0, len(fh.read().splitlines()) - 1)  # minus header


def run_pool_resume():
    from distributedkernelshap_tpu.resilience.faults import CRASH_EXIT_CODE

    n_shards = POOL_INSTANCES // POOL_BATCH
    with tempfile.TemporaryDirectory() as tmp:
        ref_dir = os.path.join(tmp, "ref")
        res_dir = os.path.join(tmp, "resume")
        ref_phi = os.path.join(tmp, "ref.npy")
        res_phi = os.path.join(tmp, "resume.npy")

        rc_ref, ref_stats, err = _spawn_pool_run(ref_dir, ref_phi)
        if rc_ref != 0:
            return {"error": f"reference run failed rc={rc_ref}: {err}"}

        flightrec_dir = os.path.join(tmp, "flightrec")
        rc_kill, _, _ = _spawn_pool_run(
            res_dir, res_phi,
            faults=f"crash:site=pool.shard,after={CRASH_AFTER}",
            flightrec_dir=flightrec_dir)
        survived = _journal_records(res_dir)
        # the injected crash must leave its black box: a flight-recorder
        # dump whose timeline includes the fired fault
        dump_events = None
        dumps = (sorted(os.listdir(flightrec_dir))
                 if os.path.isdir(flightrec_dir) else [])
        if dumps:
            with open(os.path.join(flightrec_dir, dumps[0])) as fh:
                dump = json.load(fh)
            dump_events = [e["kind"] for e in dump.get("events", [])]

        rc_res, res_stats, err = _spawn_pool_run(res_dir, res_phi)
        if rc_res != 0:
            return {"error": f"resume run failed rc={rc_res}: {err}"}

        phi_ref = np.load(ref_phi)
        phi_res = np.load(res_phi)
        # shards the killed run computed (CRASH_AFTER + 1: the fault fires
        # on the following hit) plus shards the resume computed, minus
        # the total = work done twice — the in-flight shard, at most
        recomputed_overlap = (CRASH_AFTER + 1 + res_stats["computed"]
                              - n_shards)
        return {
            "n_shards": n_shards,
            "crash_rc": rc_kill,
            "crash_exit_code_expected": CRASH_EXIT_CODE,
            "flightrec_dumps": len(dumps),
            "flightrec_dump_kinds": dump_events,
            "journal_shards_after_kill": survived,
            "resume_restored": res_stats["restored"],
            "resume_computed": res_stats["computed"],
            "recomputed_overlap_shards": int(recomputed_overlap),
            "bit_identical_phi": bool(np.array_equal(phi_ref, phi_res)),
            "reference_computed": ref_stats["computed"],
        }


# --------------------------------------------------------------------- #
# trace merging (--trace-out)
# --------------------------------------------------------------------- #


def merge_trace(trace_dir: str, trace_out: str) -> dict:
    """Merge the parent's client+proxy spans with every worker's sink file
    into one JSONL + a Perfetto conversion, and check the end-to-end
    criterion: at least one trace id must carry the full client → proxy
    (incl. a pass span) → replica admission/queue/schedule/device/finalize
    chain, with the Perfetto conversion round-tripping losslessly."""

    from distributedkernelshap_tpu.observability import tracing

    spans = tracing.tracer().spans()
    for name in sorted(os.listdir(trace_dir)) if os.path.isdir(trace_dir) \
            else []:
        if name.startswith("spans-") and name.endswith(".jsonl"):
            spans.extend(tracing.read_jsonl(os.path.join(trace_dir, name)))
    os.makedirs(os.path.dirname(os.path.abspath(trace_out)), exist_ok=True)
    with open(trace_out, "w", encoding="utf-8") as fh:
        for s in spans:
            fh.write(json.dumps(s.to_dict()) + "\n")
    perfetto = trace_out + ".perfetto.json"
    tracing.write_chrome_trace(spans, perfetto)
    back = tracing.read_chrome_trace(perfetto)
    round_trips = (
        len(back) == len(spans)
        and {(s.name, s.trace_id, s.span_id, s.parent_id) for s in back}
        == {(s.name, s.trace_id, s.span_id, s.parent_id) for s in spans})

    # end-to-end followability: group span names by trace id
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, set()).add(s.name)
    required = {"client.request", "proxy.request", "proxy.pass",
                "server.request", "server.admission", "server.queue_wait",
                "server.schedule", "server.device_explain",
                "server.finalize"}
    complete = [t for t, names in by_trace.items() if required <= names]
    hedged_traces = [t for t, names in by_trace.items()
                     if "proxy.pass" in names
                     and any(s.trace_id == t and s.name == "proxy.pass"
                             and s.attrs.get("slot") == "hedge"
                             for s in spans)]
    return {
        "spans": len(spans),
        "jsonl": trace_out,
        "perfetto": perfetto,
        "perfetto_round_trips": bool(round_trips),
        "traces": len(by_trace),
        "end_to_end_traces": len(complete),
        "hedged_pass_traces": len(hedged_traces),
        "phases": tracing.phase_breakdown(spans),
    }


# --------------------------------------------------------------------- #


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true",
                        help="exit 1 unless the acceptance criteria hold")
    parser.add_argument("--serve-only", action="store_true")
    parser.add_argument("--pool-only", action="store_true")
    parser.add_argument("--requests", type=int, default=48)
    parser.add_argument(
        "--trace-out", default="",
        help="enable end-to-end tracing for the serve scenario and write "
             "the merged client+proxy+replica span trace here as JSONL "
             "(plus <path>.perfetto.json); with --check, also asserts one "
             "request is followable end to end by shared trace id")
    parser.add_argument("--history", default=None,
                        help="perf-history JSONL this run appends to "
                             "(default: results/perf_history.jsonl)")
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history self-record")
    # subprocess mode (internal): one journaled pool run
    parser.add_argument("--pool-run", action="store_true",
                        help=argparse.SUPPRESS)
    parser.add_argument("--checkpoint-dir", help=argparse.SUPPRESS)
    parser.add_argument("--out", help=argparse.SUPPRESS)
    args = parser.parse_args()

    if args.pool_run:
        stats = pool_run(args.checkpoint_dir, args.out)
        print(json.dumps(stats))
        return 0

    # the chaos scenarios double as lock-order witness workloads: every
    # parent-side named control-plane lock (proxy rotation, scheduler
    # condition, registries, autoscaler state) records its acquisition
    # order; at the end the graph must be cycle-free and the witness's
    # own bookkeeping must cost <= 2% of the bench wall.  --check only:
    # a plain timing run must not wrap the parent hot-path locks (the
    # proxy rotation lock is taken per routed request) in witness
    # bookkeeping whose serialization would contaminate the latencies
    # self-recorded into perf_history.  In-process force_enable, NOT the
    # env knob: the env would be inherited by the spawned replica
    # workers, taxing THEIR hot-path locks too — and --check REQUIRES
    # the witness checks, so an inherited DKS_LOCK_WITNESS=0 must not
    # silently fail the gate with an empty graph either
    from distributedkernelshap_tpu.analysis import lockwitness

    if args.check:
        lockwitness.force_enable()
        lockwitness.reset()
    t_witness0 = time.monotonic()

    report = {"bench": "chaos"}
    checks = {}
    trace_dir = None
    if args.trace_out:
        from distributedkernelshap_tpu.observability import tracing

        tracing.tracer().enable()
        trace_dir = tempfile.mkdtemp(prefix="dks-trace-")
    if not args.pool_only:
        serve = run_serve_chaos(n_requests=args.requests,
                                trace_dir=trace_dir)
        report["serve"] = serve
        checks.update({
            "zero_lost": serve["lost"] == 0,
            "zero_duplicated_or_mixed": serve["mismatched"] == 0,
            "additivity_ok": serve["additivity_bad"] == 0,
            "killed_replica_restarted": serve["supervisor_restarts"] >= 1,
            "all_replicas_recovered": serve["all_replicas_recovered"],
            "hedge_beat_slow_replica": serve["hedge_wins"] >= 1,
        })
        if args.trace_out:
            trace = merge_trace(trace_dir, args.trace_out)
            report["trace"] = trace
            checks.update({
                # one client request followable end to end by trace id,
                # hedged passes visible as distinct pass spans, and the
                # Perfetto conversion round-tripping the JSONL
                "trace_end_to_end": trace["end_to_end_traces"] >= 1,
                "trace_hedged_pass_tagged":
                    trace["hedged_pass_traces"] >= 1,
                "perfetto_round_trips": trace["perfetto_round_trips"],
            })
    if not args.pool_only:
        scaler = run_scaler_chaos()
        report["scaler"] = scaler
        checks.update({
            # a dead/wedged control plane degrades to the CURRENT fleet
            # size — it never drains the data plane (to zero or at all)
            "scaler_crash_fleet_intact":
                scaler["crash"]["fault_fired"]
                and scaler["crash"]["ready_after"] == 2
                and scaler["crash"]["draining_after"] == 0
                and scaler["crash"]["serving_after"],
            "scaler_crash_thread_dead":
                not scaler["crash"]["scaler_thread_alive"],
            "scaler_hang_fleet_intact":
                scaler["hang"]["fault_fired"]
                and scaler["hang"]["ready_after"] == 2
                and scaler["hang"]["draining_after"] == 0
                and scaler["hang"]["serving_after"],
            "scaler_hang_ticks_frozen": scaler["hang"]["ticks_frozen"],
        })
    if not args.serve_only:
        pool = run_pool_resume()
        report["pool"] = pool
        checks.update({
            "crash_was_injected": pool.get("crash_rc")
            == pool.get("crash_exit_code_expected"),
            # the injected crash left its flight-recorder black box, with
            # the fired fault on the timeline
            "flightrec_dump_on_crash": pool.get("flightrec_dumps", 0) >= 1
            and "fault_injected" in (pool.get("flightrec_dump_kinds") or []),
            "journal_survived_kill": pool.get("journal_shards_after_kill")
            == CRASH_AFTER,
            "resume_recomputes_le_1_shard":
                0 <= pool.get("recomputed_overlap_shards", 99) <= 1,
            "bit_identical_phi": pool.get("bit_identical_phi", False),
        })
    if args.check:
        witness_wall_s = max(1e-9, time.monotonic() - t_witness0)
        snap = lockwitness.snapshot()
        cycle = lockwitness.find_cycle_in_edges(snap["edges"])
        overhead_frac = snap["overhead_s"] / witness_wall_s
        report["lockwitness"] = {
            "locks": sorted(snap["acquisitions"]),
            "acquisitions_total": int(sum(snap["acquisitions"].values())),
            "edges": [f"{a}->{b}" for a, b in sorted(snap["edges"])],
            "cycle": cycle,
            "max_hold_s": {k: round(v, 4)
                           for k, v in sorted(snap["max_hold_s"].items())},
            "overhead_s": round(snap["overhead_s"], 4),
            "overhead_frac_of_wall": round(overhead_frac, 5),
        }
        if not args.pool_only:
            # pool-only runs do all their work in subprocesses, so the
            # parent-side witness legitimately sees nothing there
            checks.update({
                # the witness must have actually observed the control
                # plane...
                "lockwitness_observed": bool(snap["acquisitions"]),
                # ...recorded a cycle-free acquisition order...
                "lockwitness_acyclic": cycle is None,
                # ...and cost a negligible slice of the bench wall
                "lockwitness_overhead_le_2pct": overhead_frac <= 0.02,
            })
    report["checks"] = checks
    report["ok"] = bool(checks) and all(checks.values())
    if not args.no_record and "serve" in report:
        # perf-history self-record (benchmarks/regression_gate.py): the
        # serve scenario's wall clock is this bench's headline number
        from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

        entry = record_run(
            args.history or DEFAULT_HISTORY, bench="chaos",
            # traced runs pay span-recording + JSONL-flush overhead in
            # their wall clock — a different measurement, so a
            # different fingerprint (and baseline)
            config={"requests": args.requests, "scenario": "serve_chaos",
                    "traced": bool(args.trace_out)},
            metrics={"wall_s": report["serve"]["wall_s"]},
            extra={"checks_ok": report["ok"]})
        report["perf_history"] = {"git_sha": entry["git_sha"],
                                  "config_fp": entry["config_fp"]}
    print(json.dumps(report))
    if args.check and not report["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
