"""Benchmark analysis & reporting.

Script equivalent of the reference's ``Analysis.ipynb`` (cells 2, 25-54):
reads the ``{'t_elapsed': [...]}`` result pickles produced by the benchmark
drivers (same filename convention, ``utils.get_filename``), computes
mean/std runtimes per configuration, renders the bar charts with the
sequential-baseline overlay, and prints a comparison table against the
reference's published numbers (BASELINE.md).

Usage::

    python benchmarks/analysis.py --results results/ --serve 0 --plot out.png
"""

import argparse
import glob
import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

# reference headline numbers for the comparison table (BASELINE.md)
REFERENCE_BASELINES = {
    "sequential_1cpu": 1736.89,
    "ray_pool_32cpu_best": 125.05,
    "ray_serve_32cpu_best": 115.13,
    "ray_pool_k8s_56cpu_best": 57.0,
    "ray_serve_k8s_56cpu_best": 60.5,
}

_POOL_RE = re.compile(
    r"ray_workers_(-?\d+)_bsize_(\w+?)_actorfr_([\d.]+?)(_mode_(\w+))?\.pkl")
_SERVE_RE = re.compile(
    r"ray_replicas_(-?\d+)_maxbatch_(\w+?)_actorfr_([\d.]+?)(_mode_(\w+))?\.pkl")


def read_runtimes(results_dir: str, serve: bool = False) -> Dict[Tuple[int, str], List[float]]:
    """Load all result pickles into ``{(workers, batch): [t, ...]}``
    (the reference notebook's ``read_runtimes`` helper)."""

    import pickle

    pattern = _SERVE_RE if serve else _POOL_RE
    out: Dict[Tuple[int, str], List[float]] = {}
    for path in sorted(glob.glob(os.path.join(results_dir, "*.pkl"))):
        m = pattern.match(os.path.basename(path))
        if not m:
            continue
        workers, batch = int(m.group(1)), m.group(2)
        if m.group(5):  # non-default batch_mode suffix, e.g. 'default'
            batch = f"{batch}/{m.group(5)}"
        with open(path, "rb") as f:
            out[(workers, batch)] = pickle.load(f)["t_elapsed"]
    return out


def compare_timing(runtimes: Dict[Tuple[int, str], List[float]]):
    """Mean/std per configuration, sorted by workers then batch
    (the notebook's ``compare_timing``)."""

    def batch_key(batch: str):
        return (0, int(batch)) if batch.lstrip("-").isdigit() else (1, batch)

    rows = []
    for (workers, batch), times in sorted(
            runtimes.items(), key=lambda kv: (kv[0][0], batch_key(kv[0][1]))):
        rows.append({
            "workers": workers,
            "batch": batch,
            "mean_s": float(np.mean(times)),
            "std_s": float(np.std(times)),
            "n_runs": len(times),
            "vs_ray_pool_best": REFERENCE_BASELINES["ray_pool_32cpu_best"] / float(np.mean(times)),
        })
    return rows


def print_table(rows) -> None:
    hdr = f"{'workers':>8}{'batch':>12}{'mean_s':>10}{'std_s':>9}{'runs':>6}{'vs ref best':>13}"
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        print(f"{r['workers']:>8}{r['batch']:>12}{r['mean_s']:>10.3f}{r['std_s']:>9.3f}"
              f"{r['n_runs']:>6}{r['vs_ray_pool_best']:>12.1f}x")


def plot_rows(rows, out_path: str, baseline: float = None) -> None:
    """Bar chart with the sequential baseline overlay (the notebook's red
    dashed line, images/pool_1_node.PNG style)."""

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    labels = [f"w{r['workers']}/b{r['batch']}" for r in rows]
    means = [r["mean_s"] for r in rows]
    stds = [r["std_s"] for r in rows]

    fig, ax = plt.subplots(figsize=(max(6, len(rows)), 4))
    bars = ax.bar(labels, means, yerr=stds, capsize=3)
    for bar, mean in zip(bars, means):
        ax.annotate(f"{mean:.2f}", (bar.get_x() + bar.get_width() / 2, mean),
                    ha="center", va="bottom", fontsize=8)
    if baseline:
        ax.axhline(baseline, color="red", linestyle="--",
                   label=f"reference best ({baseline:.1f}s)")
        ax.legend()
    ax.set_ylabel("wall-clock (s)")
    ax.set_title("KernelSHAP explanation runtime (2560 Adult instances)")
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    print(f"plot written to {out_path}")


def comparison_figure(out_path: str,
                      jsonl: Optional[str] = None,
                      results_dir: str = "results") -> str:
    """The reference notebook's at-a-glance punchline, reproduced for this
    framework (VERDICT r3 #8): wall-clock for the SAME 2560-instance Adult
    task across the reference's published systems (BASELINE.md) and this
    repo's committed TPU rows, with the sequential baseline as the dashed
    overlay — the visual convention of ``/root/reference/Analysis.ipynb``
    cells 21-27 / ``images/pool_1_node.PNG``.

    Our rows come from committed artifacts, not hardcoded numbers: the
    latest successful ``config:adult`` record in the hardware sweep jsonl
    (direct sharded explain on one chip) and the serve sweep's coalesced
    auto-depth pickle (``ray_replicas_0_maxbatch_10``).  Missing artifacts
    drop their bar rather than fail the figure.
    """

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if jsonl is None:
        jsonl = os.path.join(results_dir, "tpu_revalidate.jsonl")

    bars = [
        ("sequential\n1 vCPU", REFERENCE_BASELINES["sequential_1cpu"], "ref"),
        ("pool best\n32 vCPU", REFERENCE_BASELINES["ray_pool_32cpu_best"], "ref"),
        ("serve best\n32 vCPU", REFERENCE_BASELINES["ray_serve_32cpu_best"], "ref"),
        ("pool best\nk8s 56 vCPU", REFERENCE_BASELINES["ray_pool_k8s_56cpu_best"], "ref"),
    ]
    # serve, coalesced b=10, auto depth — one TPU chip.  Malformed artifacts
    # (truncated pickle/jsonl from a killed sweep) drop their bar like
    # missing ones — this figure must never abort the rest of the analysis.
    serve_pkl = os.path.join(results_dir,
                             "ray_replicas_0_maxbatch_10_actorfr_1.0.pkl")
    try:
        import pickle as _pickle

        with open(serve_pkl, "rb") as f:
            t = _pickle.load(f)["t_elapsed"]
        bars.append(("serve b=10\n1 TPU chip", float(np.mean(t)), "ours"))
    except (OSError, KeyError, ValueError, _pickle.UnpicklingError, EOFError):
        pass
    # direct sharded explain — one TPU chip (latest successful sweep row,
    # through the same scan the RESULTS.md summary table uses)
    try:
        rec = dict(summarise_jsonl(jsonl)).get("config:adult")
        if rec and rec.get("ok") and isinstance(rec.get("result"), dict):
            adult = rec["result"].get("value")
            if adult:
                bars.append(("direct explain\n1 TPU chip", float(adult),
                             "ours"))
    except (OSError, ValueError):
        pass

    seq = REFERENCE_BASELINES["sequential_1cpu"]
    colors = {"ref": "#9aa0a6", "ours": "#3b76d6"}
    fig, ax = plt.subplots(figsize=(9.5, 5.2))
    xs = np.arange(len(bars))
    for i, (label, value, group) in enumerate(bars):
        ax.bar(i, value, width=0.62, color=colors[group], zorder=3)
        speed = seq / value
        value_s = f"{value:,.0f}s" if value >= 10 else f"{value:.3g}s"
        note = value_s + (f"\n{speed:,.0f}×" if group == "ours"
                          else f"\n{speed:.1f}×")
        ax.text(i, value * 1.25, note, ha="center", va="bottom", fontsize=9,
                color="#333333")
    ax.axhline(seq, color="red", linestyle="--", linewidth=1.2,
               label=f"sequential baseline ({seq:.0f}s)", zorder=2)
    ax.set_yscale("log")
    ax.set_ylim(top=seq * 40)
    ax.set_xticks(xs)
    ax.set_xticklabels([b[0] for b in bars], fontsize=9)
    ax.set_ylabel("wall-clock (s, log scale)")
    ax.set_title("Explain 2560 Adult instances (bg=100): "
                 "reference (gray) vs this framework (blue)")
    ax.grid(axis="y", alpha=0.25, zorder=0)
    ax.spines[["top", "right"]].set_visible(False)
    import matplotlib.patches as mpatches

    ax.legend(handles=[
        mpatches.Patch(color=colors["ref"], label="reference (Ray, CPU)"),
        mpatches.Patch(color=colors["ours"], label="this framework (TPU)"),
        ax.lines[0]], loc="upper right", fontsize=9, frameon=False)
    fig.tight_layout()
    fig.savefig(out_path, dpi=130)
    plt.close(fig)
    print(f"wrote {out_path}")
    return out_path


def summarise_jsonl(path: str):
    """Latest successful row per step of a ``tpu_revalidate.jsonl`` file
    (the one-session hardware sweep appends per-step records; re-runs
    supersede in time order).  Returns ``[(step, record)]`` sorted by step
    — the table RESULTS.md's numbers are folded from."""

    import json

    latest = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            step = rec.get("step")
            if step in (None, "done"):
                continue
            # a failed re-run must not shadow an earlier success
            if rec.get("ok") or step not in latest:
                latest[step] = rec
    return sorted(latest.items())


def print_jsonl_summary(path: str) -> None:
    rows = summarise_jsonl(path)
    hdr = f"{'step':<26} {'ok':>3} {'value':>10} {'extra'}"
    print(hdr)
    print("-" * 78)
    for step, rec in rows:
        result = rec.get("result") or {}
        value = result.get("value")
        extras = {k: v for k, v in result.items()
                  if k in ("additivity_err", "model_err", "inst_per_s",
                           "data_provenance", "vs_baseline", "platform",
                           "sampled_wall_s", "speedup_vs_sampled")}
        extra = (" ".join(f"{k}={v}" for k, v in extras.items())
                 if rec.get("ok") else rec.get("error", "")[:48])
        value_s = f"{value:.4f}" if isinstance(value, (int, float)) else "-"
        print(f"{step:<26} {'y' if rec.get('ok') else 'N':>3} "
              f"{value_s:>10} {extra}")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--results", default="results")
    parser.add_argument("--serve", default=0, type=int)
    parser.add_argument("--plot", default=None, type=str)
    parser.add_argument("--jsonl", default=None, type=str,
                        help="Summarise a tpu_revalidate.jsonl sweep "
                             "(latest row per step) instead of pickles.")
    parser.add_argument("--compare", default=None, type=str,
                        help="Render the reference-vs-TPU comparison figure "
                             "to this path (committed artifacts only).")
    args = parser.parse_args()

    if args.compare:
        comparison_figure(args.compare, jsonl=args.jsonl,
                          results_dir=args.results)
    if args.jsonl:
        print_jsonl_summary(args.jsonl)
        return
    if args.compare and not args.plot:
        return

    runtimes = read_runtimes(args.results, serve=bool(args.serve))
    if not runtimes:
        print(f"no result pickles found in {args.results}")
        return
    rows = compare_timing(runtimes)
    print_table(rows)
    if args.plot:
        plot_rows(rows, args.plot,
                  baseline=REFERENCE_BASELINES["ray_pool_32cpu_best"])


if __name__ == "__main__":
    main()
