"""The full benchmark-configuration suite from BASELINE.json.

Each config prints one JSON line; ``bench.py`` remains the headline driver.

  * ``adult``            — 2560 instances, bg=100, LR (the reference task)
  * ``adult_stress``     — bg=1000, nsamples=2048 (stresses the WLS/eval
                           size; uses coalition-axis sharding on >1 device)
  * ``adult_blackbox``   — gradient-boosted predictor as an opaque host
                           callable (XGBoost when installed, sklearn
                           HistGradientBoosting otherwise) via the host-eval
                           path
  * ``adult_trees``      — a gradient-boosted predictor lifted onto the
                           device (``models/trees.py`` path-matmul eval);
                           measures the native-tree path against
                           ``adult_blackbox``'s host path
  * ``model_zoo``        — one timing per lifted model family (linear, GBT,
                           RBF SVM, sklearn MLP, torch net, pipeline) on the
                           same Adult batch
  * ``mnist``            — CNN + superpixel image KernelSHAP
  * ``covertype``        — 581k-instance dataset, instance-sharded across
                           every visible device

Run: ``python benchmarks/configs.py --config adult_stress [--smoke]``.
``--smoke`` shrinks sizes for CI-style validation on CPU.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks._common import add_platform_flag, apply_platform  # noqa: E402


_NRUNS_OVERRIDE = None  # set by --nruns (e.g. 1 for slow CPU-mesh validation)


def _timed_explain(explainer, X, nruns=3, **kwargs):
    nruns = _NRUNS_OVERRIDE or nruns
    explainer.explain(X, silent=True, **kwargs)  # warmup/compile
    times = []
    for _ in range(nruns):
        t0 = time.perf_counter()
        explanation = explainer.explain(X, silent=True, **kwargs)
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), explanation


def _phi_total(explanation):
    """``Σφ + E`` as an ``(n, K)`` array for list (multi-output) or plain
    (scalar-output) shap_values layouts."""

    sv = explanation.shap_values
    if isinstance(sv, list):
        return np.stack(sv, 1).sum(-1) + np.asarray(explanation.expected_value)[None, :]
    total = np.asarray(sv).sum(-1) + np.ravel(explanation.expected_value)[0]
    return total[:, None]


def _additivity(explanation):
    total = _phi_total(explanation)
    raw = np.asarray(explanation.data["raw"]["raw_prediction"]).reshape(total.shape)
    return float(np.abs(total - raw).max())


def _model_err(explanation, model_out, link="logit"):
    """Additivity against the ORIGINAL model's outputs — the external
    faithfulness oracle.  The internal `_additivity` holds by WLS
    construction even if the lifted predictor mis-evaluates (observed once
    on the TPU backend via a miscompiling fusion, models/trees.py
    `_split_conditions`), so lifted-model configs must also check this."""

    total = _phi_total(explanation)
    out = np.asarray(model_out, np.float64)
    if out.ndim == 1:
        out = out[:, None]
    if link == "logit":
        p = np.clip(out, 1e-7, 1 - 1e-7)
        out = np.log(p / (1.0 - p))
    return float(np.abs(total - out.reshape(total.shape)).max())



def _prov(data):
    """Provenance tag for a load_data() dict (see utils.data_provenance)."""

    from distributedkernelshap_tpu.utils import data_provenance

    return data_provenance(data)


def config_adult(smoke=False):
    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    clf = load_model()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    X = data["all"]["X"]["processed"]["test"].toarray()
    if smoke:
        X = X[:64]
    ex = KernelShap(clf.predict_proba, link="logit", feature_names=gn, seed=0)
    ex.fit(data["background"]["X"]["preprocessed"], group_names=gn, groups=g)
    t, explanation = _timed_explain(ex, X)
    return {"metric": "adult_2560_bg100_wall_s", "value": round(t, 4), "unit": "s",
            "n_instances": X.shape[0], "additivity_err": _additivity(explanation),
            "data_provenance": _prov(data), "kernel_path": ex.kernel_path}


def config_adult_stress(smoke=False):
    """bg=1000 / nsamples=2048 (SURVEY.md §5.7 stress shape)."""

    import jax

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    clf = load_model()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    X = data["all"]["X"]["processed"]["test"].toarray()
    bg = data["all"]["X"]["processed"]["train"][:1000]
    n_x = 64 if smoke else 512
    X = X[:n_x]

    n_dev = len(jax.devices())
    opts = None
    if n_dev > 1:
        # devices co-operate on the coalition axis: partial normal equations
        # psum'd over ICI (parallel/coalition_sharding.py)
        cp = 2 if n_dev % 2 == 0 else 1
        opts = {"n_devices": n_dev, "coalition_parallel": cp}
    ex = KernelShap(clf.predict_proba, link="logit", feature_names=gn, seed=0,
                    distributed_opts=opts)
    ex.fit(bg, group_names=gn, groups=g)
    t, explanation = _timed_explain(ex, X, nsamples=2048)
    return {"metric": "adult_bg1000_ns2048_wall_s", "value": round(t, 4), "unit": "s",
            "n_instances": n_x, "additivity_err": _additivity(explanation),
            "data_provenance": _prov(data), "kernel_path": ex.kernel_path}


def config_adult_blackbox(smoke=False):
    """Opaque host predictor through the host-eval path (the reference's
    'any pickled callable' capability, wrappers.py:33-37)."""

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.kernel_shap import EngineConfig
    from distributedkernelshap_tpu.utils import load_data

    from distributedkernelshap_tpu.models import CallbackPredictor

    data = load_data()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    Xtr = data["all"]["X"]["processed"]["train"].toarray()
    ytr = data["all"]["y"]["train"]
    if smoke:
        Xtr, ytr = Xtr[:4000], ytr[:4000]
    try:  # xgboost when available; sklearn boosted trees otherwise
        from xgboost import XGBClassifier

        clf = XGBClassifier(n_estimators=15 if smoke else 50, max_depth=4).fit(Xtr, ytr)
    except ImportError:
        from sklearn.ensemble import HistGradientBoostingClassifier

        clf = HistGradientBoostingClassifier(max_iter=15 if smoke else 50,
                                             random_state=0).fit(Xtr, ytr)

    X = data["all"]["X"]["processed"]["test"].toarray()
    X = X[:16] if smoke else X[:256]
    # host_eval=True: force the host path even on backends that support
    # callbacks, so this config always measures the fan-out it advertises.
    # host_eval_workers stays at its DEFAULT (auto: host core count) — the
    # config proves the fan-out engages without configuration (VERDICT r4
    # #7); the resolved worker count is reported below.
    # The explicit CallbackPredictor wrap keeps the model opaque — without it
    # as_predictor would lift the sklearn ensemble onto the device
    # (models/trees.py), which is what config_adult_trees measures instead
    cfg = EngineConfig(host_eval=True)
    pred = CallbackPredictor(clf.predict_proba, example_dim=Xtr.shape[1])
    ex = KernelShap(pred, link="logit", feature_names=gn, seed=0,
                    engine_config=cfg)
    ex.fit(data["background"]["X"]["preprocessed"], group_names=gn, groups=g)
    t, explanation = _timed_explain(ex, X, nruns=1)
    return {"metric": "adult_blackbox_wall_s", "value": round(t, 4), "unit": "s",
            "n_instances": X.shape[0], "additivity_err": _additivity(explanation),
            "data_provenance": _prov(data), "kernel_path": ex.kernel_path,
            "host_eval_workers": ex.hosteval_workers,
            "predictor": type(clf).__name__}


def config_adult_trees(smoke=False):
    """A gradient-boosted model lifted onto the device (``models/trees.py``):
    the whole ``B×S×N`` synthetic tensor is evaluated on-chip as MXU
    path-matmuls, no host callback.  Same task size as ``adult_blackbox``;
    the two lines are directly comparable when xgboost is not installed
    (both then use HistGradientBoostingClassifier(max_iter=50) — the case for
    the numbers in RESULTS.md).  With xgboost installed, ``adult_blackbox``
    measures XGBClassifier instead, a different per-row eval cost."""

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models import TreeEnsemblePredictor
    from distributedkernelshap_tpu.utils import load_data

    data = load_data()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    Xtr = data["all"]["X"]["processed"]["train"].toarray()
    ytr = data["all"]["y"]["train"]
    if smoke:
        Xtr, ytr = Xtr[:4000], ytr[:4000]
    from sklearn.ensemble import HistGradientBoostingClassifier

    clf = HistGradientBoostingClassifier(max_iter=10 if smoke else 50,
                                         random_state=0).fit(Xtr, ytr)

    # f32 evaluation points for BOTH sides of the model_err oracle: the
    # device evaluates in f32, and HistGBT routes threshold-adjacent rows
    # differently for x vs float32(x) (measured 1.946 max logit diff on this
    # batch from the cast alone, sklearn-vs-sklearn) — comparing an f32
    # engine against the f64-input predictions would report that cast
    # sensitivity as engine error
    X = data["all"]["X"]["processed"]["test"].toarray().astype(np.float32)
    X = X[:8] if smoke else X[:256]
    ex = KernelShap(clf.predict_proba, link="logit", feature_names=gn, seed=0)
    ex.fit(data["background"]["X"]["preprocessed"], group_names=gn, groups=g)
    lifted = isinstance(ex._explainer.predictor, TreeEnsemblePredictor)
    t, explanation = _timed_explain(ex, X, nruns=1 if smoke else 3)
    return {"metric": "adult_trees_wall_s", "value": round(t, 4), "unit": "s",
            "n_instances": X.shape[0], "additivity_err": _additivity(explanation),
            "data_provenance": _prov(data),
            "model_err": _model_err(explanation, clf.predict_proba(X)),
            "predictor": type(clf).__name__, "device_lifted": lifted,
            "kernel_path": ex.kernel_path}


def config_adult_trees_exact(smoke=False):
    """Sampling-free interventional TreeSHAP (``nsamples='exact'``,
    ``ops/treeshap.py``) on a lifted GBT regressor — closed-form Shapley
    values of the raw margin, no coalition sampling, no WLS.  Reported next
    to the sampled path on the same model/instances for the speed and the
    zero-sampling-error comparison."""

    import scipy.sparse as sp
    from sklearn.ensemble import HistGradientBoostingRegressor

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models import TreeEnsemblePredictor
    from distributedkernelshap_tpu.utils import load_data

    data = load_data()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    Xtr = data["all"]["X"]["processed"]["train"].toarray()
    ytr = data["all"]["y"]["train"].astype(np.float64)
    if smoke:
        Xtr, ytr = Xtr[:4000], ytr[:4000]
    gbr = HistGradientBoostingRegressor(max_iter=10 if smoke else 50,
                                        random_state=0).fit(Xtr, ytr)
    X = data["all"]["X"]["processed"]["test"].toarray().astype(np.float32)
    X = X[:8] if smoke else X[:256]
    bgd = data["background"]["X"]["preprocessed"]
    bg = bgd.toarray() if sp.issparse(bgd) else np.asarray(bgd)

    ex = KernelShap(gbr.predict, seed=0)  # identity link: raw margins
    ex.fit(bg, group_names=gn, groups=g)
    assert isinstance(ex._explainer.predictor, TreeEnsemblePredictor)
    t_exact, expl = _timed_explain(ex, X, nruns=1 if smoke else 3,
                                   nsamples="exact")
    t_sampled, _ = _timed_explain(ex, X, nruns=1 if smoke else 3,
                                  l1_reg=False)
    t_inter, expl_i = _timed_explain(ex, X, nruns=1 if smoke else 3,
                                     nsamples="exact", interactions=True)
    total = np.asarray(expl.shap_values).sum(-1).ravel() \
        + np.ravel(expl.expected_value)[0]
    err = float(np.abs(total - gbr.predict(X.astype(np.float64))).max())
    inter = expl_i.data["raw"]["interaction_values"][0]
    inter_err = float(np.abs(inter.sum(-1)
                             - np.asarray(expl_i.shap_values[0])).max())
    return {"metric": "adult_trees_exact_wall_s", "value": round(t_exact, 4),
            "unit": "s", "n_instances": X.shape[0],
            "data_provenance": _prov(data),
            "sampled_wall_s": round(t_sampled, 4),
            "speedup_vs_sampled": round(t_sampled / t_exact, 2),
            "model_err": err,
            "interactions_wall_s": round(t_inter, 4),
            "interactions_rowsum_err": inter_err,
            "kernel_path": ex.kernel_path}


def config_model_zoo(smoke=False):
    """One line per lifted model family on the Adult task: every predictor
    class the lift matrix covers (linear, GBT path-matmul, RBF SVM Gram
    matmul, sklearn MLP, torch net, scaler pipeline) explained on-device
    with the same 256-instance batch.  Evidence that 'switch your model,
    keep your speed' holds across the families the reference could only run
    as opaque CPU callables."""

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models import (
        LinearPredictor,
        MLPPredictor,
        PipelinePredictor,
        SVMPredictor,
        TorchMLPPredictor,
        TreeEnsemblePredictor,
    )
    from distributedkernelshap_tpu.utils import load_data, load_model

    data = load_data()
    gn, g = data["all"]["group_names"], data["all"]["groups"]
    Xtr = data["all"]["X"]["processed"]["train"].toarray()
    ytr = data["all"]["y"]["train"]
    if smoke:
        Xtr, ytr = Xtr[:3000], ytr[:3000]
    # f32 points for both explain and the model_err oracle (see
    # config_adult_trees: the cast itself flips HistGBT threshold routing)
    X = data["all"]["X"]["processed"]["test"].toarray().astype(np.float32)
    X = X[:16] if smoke else X[:256]
    bg = data["background"]["X"]["preprocessed"]

    def zoo():
        from sklearn.ensemble import HistGradientBoostingClassifier
        from sklearn.neural_network import MLPClassifier
        from sklearn.pipeline import Pipeline
        from sklearn.preprocessing import StandardScaler
        from sklearn.svm import SVC

        yield "linear_lr", load_model().predict_proba, LinearPredictor
        yield ("hist_gbt",
               HistGradientBoostingClassifier(
                   max_iter=10 if smoke else 50, random_state=0)
               .fit(Xtr, ytr).predict_proba, TreeEnsemblePredictor)
        svc_n = 2000 if smoke else 5000   # SVC fit is quadratic-ish in rows
        yield ("svc_rbf",
               SVC(kernel="rbf", random_state=0)
               .fit(Xtr[:svc_n], ytr[:svc_n]).decision_function, SVMPredictor)
        yield ("sklearn_mlp",
               MLPClassifier((32,), max_iter=30 if smoke else 120,
                             random_state=0).fit(Xtr, ytr).predict_proba,
               MLPPredictor)
        try:
            import torch
            from torch import nn

            torch.manual_seed(0)
            D = Xtr.shape[1]
            net = nn.Sequential(nn.Linear(D, 32), nn.ReLU(), nn.Linear(32, 2),
                                nn.Softmax(dim=-1)).eval()
            yield "torch_mlp", net, TorchMLPPredictor
        except ImportError:
            pass
        yield ("scaler_pipeline",
               Pipeline([("sc", StandardScaler()),
                         ("gb", HistGradientBoostingClassifier(
                             max_iter=10 if smoke else 50, random_state=0))])
               .fit(Xtr, ytr).predict_proba, PipelinePredictor)
        from sklearn.ensemble import AdaBoostClassifier
        from sklearn.linear_model import LogisticRegression
        from sklearn.model_selection import GridSearchCV

        from distributedkernelshap_tpu.models.compose import AdaBoostPredictor

        yield ("adaboost",
               AdaBoostClassifier(n_estimators=10 if smoke else 50,
                                  random_state=0)
               .fit(Xtr, ytr).predict_proba, AdaBoostPredictor)
        yield ("grid_search_lr",
               GridSearchCV(LogisticRegression(max_iter=500),
                            {"C": [0.5, 1.0]}, cv=3)
               .fit(Xtr, ytr).predict_proba, LinearPredictor)
        from sklearn.ensemble import IsolationForest

        yield ("isolation_forest",
               IsolationForest(n_estimators=20 if smoke else 100,
                               random_state=0).fit(Xtr).score_samples,
               TreeEnsemblePredictor)

    from distributedkernelshap_tpu.models.torch_lift import is_torch_module, torch_callback

    families = {}
    for fam_name, predictor, expected_cls in zoo():
        link = ("identity" if fam_name in ("svc_rbf", "isolation_forest")
                else "logit")
        ex = KernelShap(predictor, link=link, feature_names=gn, seed=0)
        ex.fit(bg, group_names=gn, groups=g)
        lifted = isinstance(ex._explainer.predictor, expected_cls)
        t, explanation = _timed_explain(ex, X, nruns=1 if smoke else 3)
        host = torch_callback(predictor) if is_torch_module(predictor) else predictor
        families[fam_name] = {"wall_s": round(t, 4), "device_lifted": lifted,
                              "additivity_err": _additivity(explanation),
                              "model_err": _model_err(explanation, host(X), link),
                              "kernel_path": ex.kernel_path}
    worst = max(v["wall_s"] for v in families.values())
    return {"metric": "model_zoo_worst_wall_s", "value": worst, "unit": "s",
            "n_instances": X.shape[0], "families": families,
            "data_provenance": _prov(data),
            "additivity_err": max(v["additivity_err"] for v in families.values()),
            "model_err": max(v["model_err"] for v in families.values())}


def config_mnist(smoke=False):
    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.models.cnn import train_mnist_cnn
    from distributedkernelshap_tpu.ops.image import image_background, superpixel_groups
    from scripts.process_mnist_data import load_mnist

    data = load_mnist()
    tr_images, tr_labels = data["train"]
    te_images, te_labels = data["test"]
    if smoke:
        tr_images, tr_labels = tr_images[:4000], tr_labels[:4000]

    pred = train_mnist_cnn(tr_images, tr_labels, epochs=1 if smoke else 2)
    acc = float((np.asarray(pred(te_images[:1000].reshape(1000, -1))).argmax(1)
                 == te_labels[:1000]).mean())

    groups, names = superpixel_groups(28, 28, patch=4)  # 49 superpixels
    bg = image_background(tr_images, mode="mean")
    X = te_images.reshape(te_images.shape[0], -1)
    X = X[:16] if smoke else X[:10000]

    from distributedkernelshap_tpu.kernel_shap import EngineConfig

    # instance_chunk: run the 10k-image batch as five ~2k-image dispatches
    # through the shared sliding window (parallel/pipeline.py) instead of
    # ONE giant call — H2D/compute/D2H of successive chunks overlap, so the
    # config stops paying the session's full transfer latency serially
    # (12.25 s vs 5.02 s across 07-30/07-31 sessions was pure exposure to
    # per-session tunnel throughput; VERDICT r2 item 5).  f16 result
    # transfer halves the remaining exposure — the 10k x 10 x 49 phi tensor
    # (~19.6 MB f32) is the dominant D2H payload, and ~5e-4 absolute phi
    # rounding stays far under the 1e-2 faithfulness bar (VERDICT r4 #5:
    # kill the session-latency sensitivity in the design)
    from distributedkernelshap_tpu.ops.explain import ShapConfig as _SC

    ex = KernelShap(pred, link="logit", feature_names=names, seed=0,
                    engine_config=None if smoke else EngineConfig(
                        instance_chunk=2048,
                        shap=_SC(transfer_dtype="float16")))
    ex.fit(bg, group_names=names, groups=groups)
    # l1_reg=False: with M=49 superpixels shap's 'auto' default would switch
    # to host-side AIC selection (sampled fraction << 0.2); keep the bench on
    # the fully on-device pipeline
    t, explanation = _timed_explain(ex, X, nruns=1 if smoke else 3, l1_reg=False)
    return {"metric": "mnist_cnn_superpixel_wall_s", "value": round(t, 4), "unit": "s",
            "data_provenance": data.get("provenance", "synthetic"),
            "n_instances": X.shape[0], "cnn_test_acc": round(acc, 3),
            "n_superpixels": len(groups), "additivity_err": _additivity(explanation),
            "kernel_path": ex.kernel_path}


def config_covertype(smoke=False):
    import jax

    from distributedkernelshap_tpu import KernelShap
    from distributedkernelshap_tpu.kernel_shap import EngineConfig
    from scripts.process_covertype_data import covertype_groups, load_covertype

    data = load_covertype(n_rows=20000 if smoke else 581012)
    X, y = data["X"], data["y"]
    n_train = min(100000, X.shape[0] // 2)
    from sklearn.linear_model import LogisticRegression

    clf = LogisticRegression(max_iter=200).fit(X[:n_train], y[:n_train])
    groups, names = covertype_groups()

    # the task is the FULL dataset (581,012 rows; BASELINE.json config 5):
    # every row is explained, sharded over all visible devices.  65,536-row
    # sub-batches bound per-call device memory — one call's synthetic-eval
    # working set stays chunk-budgeted — while the 512-multiple bucketing
    # keeps padding of the last sub-batch negligible.
    X_explain = X[:512] if smoke else X
    sub = 65536
    n_dev = len(jax.devices())
    # f16 result transfer: the full-dataset phi tensor (581k x 7 x 12 ≈
    # 195 MB f32) dominates the D2H wire through a session-throughput-
    # limited tunnel; halving it costs ~5e-4 absolute phi rounding
    # (reported additivity_err rises to ~1e-3 — still far under the 1e-2
    # faithfulness bar; VERDICT r2 item 4)
    from distributedkernelshap_tpu.ops.explain import ShapConfig

    shap_cfg = ShapConfig(transfer_dtype=None if smoke else "float16")
    opts, cfg = None, EngineConfig(shap=shap_cfg)
    if n_dev > 1:
        opts = {"n_devices": n_dev, "batch_size": max(1, sub // n_dev)}
    else:
        cfg = EngineConfig(instance_chunk=sub, shap=shap_cfg)
    ex = KernelShap(clf.predict_proba, link="logit", feature_names=names, seed=0,
                    distributed_opts=opts, engine_config=cfg)
    ex.fit(X[:100], group_names=names, groups=groups)
    t, explanation = _timed_explain(ex, X_explain, nruns=1 if smoke else 3)
    # the global-explanation path: mean-|phi| ranking reduced ON device, so
    # only (K, M) floats cross the wire instead of the ~195 MB phi tensor
    # (round 4; the wall-clock difference vs `value` is the D2H share)
    t0 = time.perf_counter()
    ranking = ex.rank_features(X_explain)
    t_rank = time.perf_counter() - t0
    return {"metric": "covertype_sharded_wall_s", "value": round(t, 4), "unit": "s",
            "data_provenance": data.get("provenance", "synthetic"),
            "n_instances": X_explain.shape[0], "n_devices": n_dev,
            "inst_per_s": round(X_explain.shape[0] / t, 1),
            "ranking_wall_s": round(t_rank, 4),
            "top_feature": ranking["aggregated"]["names"][0],
            "additivity_err": _additivity(explanation),
            "kernel_path": ex.kernel_path}


CONFIGS = {
    "adult": config_adult,
    "adult_stress": config_adult_stress,
    "adult_blackbox": config_adult_blackbox,
    "adult_trees": config_adult_trees,
    "adult_trees_exact": config_adult_trees_exact,
    "model_zoo": config_model_zoo,
    "mnist": config_mnist,
    "covertype": config_covertype,
}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="adult", choices=sorted(CONFIGS) + ["all"])
    parser.add_argument("--smoke", action="store_true",
                        help="Shrunk sizes for CI-style validation.")
    parser.add_argument("--nruns", default=None, type=int,
                        help="Override each config's timed-run count "
                             "(e.g. 1 for slow CPU-mesh validation runs).")
    add_platform_flag(parser)
    args = parser.parse_args()
    apply_platform(args)
    if args.nruns:
        global _NRUNS_OVERRIDE
        _NRUNS_OVERRIDE = args.nruns

    names = sorted(CONFIGS) if args.config == "all" else [args.config]
    for name in names:
        result = CONFIGS[name](smoke=args.smoke)
        print(json.dumps(result))


if __name__ == "__main__":
    main()
