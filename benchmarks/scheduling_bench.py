"""Scheduling subsystem benchmark: tail latency + goodput under overload,
SLO scheduler vs. the FIFO baseline, result-cache effectiveness, the SLO
health engine's burn-rate alert under a real flood, and the sampler's
serve-path overhead (standalone, CPU backend, exits nonzero on
``--check`` fail).

Five measurements, one JSON line:

1. **Overload A/B** — an open-loop arrival stream (requests fired on a
   fixed schedule regardless of completions, the honest way to measure an
   overloaded server: closed-loop clients self-throttle and hide the
   queueing) at ~2x measured capacity, 30% ``interactive`` requests with a
   real deadline + 70% ``batch``, against (a) the FIFO baseline
   (``scheduling="fifo"``, admission off — the round-4 server) and (b) the
   SLO scheduler with admission control.  The device model is synthetic
   (deterministic service time per batch) so the comparison isolates the
   scheduling layer; criteria: interactive p99 strictly better under SLO,
   nonzero 429 sheds, goodput within 10% of the FIFO arm's throughput.
2. **Cache** — a ≥90%-duplicate workload against a REAL (small) KernelShap
   model with the content-addressed cache enabled: ≥80% hit rate,
   bit-identical payloads for duplicate rows, additivity intact.
3. **SLO alert lifecycle** — the same flood against a FIFO server with a
   fast-window interactive-latency SLO: the burn-rate alert must go
   pending → firing during the flood and resolve after it, visible on
   ``/statusz``, on the flight-recorder timeline, and as
   ``dks_alerts_firing`` on ``/metrics``.
4. **Sampler overhead** — identical closed-loop serial runs with the
   health sampler off vs on (drift-symmetric off/on/on/off order,
   best-of-two per arm); the sampler must cost ≤1% wall time on the
   serve path.
5. Every measured run **self-records** into the perf history
   (``benchmarks/regression_gate.py``; disable with ``--no-record``),
   so ``make perf-gate`` can fail a commit that regresses this bench.

    JAX_PLATFORMS=cpu python benchmarks/scheduling_bench.py --check
"""

import argparse
import http.client
import json
import sys
import threading
import time

import numpy as np

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)


# --------------------------------------------------------------------- #
# synthetic device model: deterministic service time, trivial payloads
# --------------------------------------------------------------------- #


class SyntheticModel:
    """Sync-only model with a deterministic cost per device batch:
    ``base_s + per_row_s * rows`` — the scheduling layer sees exactly the
    contention profile of a real accelerator without compile noise.

    The defaults are deliberately slow (~38 rows/s at full batching): the
    device must dominate the stdlib HTTP stack's per-request overhead
    (~1 ms thread spawn + connection each), otherwise the A/B measures
    Python accept-loop contention — in the device-bound regime a shed
    frees device capacity for the backlog, so goodput tracks capacity in
    both arms, which is the production behaviour being modelled."""

    max_rows = None

    def __init__(self, base_s=0.05, per_row_s=0.02):
        self.base_s = base_s
        self.per_row_s = per_row_s

    def explain_batch(self, instances, split_sizes=None):
        time.sleep(self.base_s + self.per_row_s * instances.shape[0])
        sizes = split_sizes or [1] * instances.shape[0]
        out, offset = [], 0
        for size in sizes:
            out.append(json.dumps({"data": {"rows": size, "offset": offset}}))
            offset += size
        return out


# --------------------------------------------------------------------- #
# open-loop load generator
# --------------------------------------------------------------------- #


def _post(host, port, array, headers, timeout):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/explain",
                     body=json.dumps({"array": array.tolist()}).encode(),
                     headers={"Content-Type": "application/json", **headers})
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def open_loop(server, plan, timeout=120.0):
    """Fire ``plan`` — ``[(t_offset_s, array, headers, tag), ...]`` — on
    schedule, one thread per request (open loop: arrivals never wait for
    completions).  Returns ``[(tag, status, latency_s, payload)]``."""

    results = [None] * len(plan)
    t0 = time.monotonic()

    def fire(i, offset, array, headers, tag):
        delay = t0 + offset - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        sent = time.monotonic()
        try:
            status, payload = _post(server.host, server.port, array,
                                    headers, timeout)
        except OSError:
            status, payload = -1, ""
        results[i] = (tag, status, time.monotonic() - sent, payload)

    threads = [threading.Thread(target=fire, args=(i, *spec), daemon=True)
               for i, spec in enumerate(plan)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout * 2)
    return [r for r in results if r is not None]


def percentile(values, q):
    return float(np.percentile(np.asarray(values), q)) if values else None


def scrape_metrics(server):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=30)
    try:
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
    finally:
        conn.close()
    out = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            name, value = line.rsplit(" ", 1)
            out[name] = float(value)
    return out


# --------------------------------------------------------------------- #
# phase 1: overload A/B
# --------------------------------------------------------------------- #


def run_overload_arm(policy, plan, n_requests, rng_seed=0):
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    kwargs = dict(host="127.0.0.1", port=0, max_batch_size=8,
                  batch_timeout_s=0.004, scheduling=policy)
    if policy == "fifo":
        # the round-4 baseline: accept everything, serve in arrival order
        kwargs["admission_control"] = False
    else:
        kwargs["max_queue_per_class"] = 120
    server = ExplainerServer(SyntheticModel(), **kwargs).start()
    try:
        t0 = time.monotonic()
        results = open_loop(server, plan)
        wall = time.monotonic() - t0
        metrics = scrape_metrics(server)
    finally:
        server.stop()

    by_tag = {}
    for tag, status, latency, _ in results:
        by_tag.setdefault(tag, []).append((status, latency))
    summary = {"wall_s": round(wall, 3)}
    total_ok = 0
    for tag, rs in sorted(by_tag.items()):
        ok = [lat for status, lat in rs if status == 200]
        total_ok += len(ok)
        summary[tag] = {
            "n": len(rs),
            "ok": len(ok),
            "shed_429": sum(1 for s, _ in rs if s == 429),
            "expired_504": sum(1 for s, _ in rs if s == 504),
            "p50_s": round(percentile(ok, 50), 4) if ok else None,
            "p99_s": round(percentile(ok, 99), 4) if ok else None,
        }
    summary["goodput_rps"] = round(total_ok / wall, 2)
    summary["sheds_total"] = int(sum(
        v for k, v in metrics.items()
        if k.startswith("dks_serve_sheds_total")))
    return summary


def build_overload_plan(n_requests, rate_rps, interactive_frac,
                        interactive_deadline_ms, dim, seed=0):
    rng = np.random.default_rng(seed)
    plan = []
    for i in range(n_requests):
        offset = i / rate_rps
        array = rng.normal(size=(1, dim)).astype(np.float32)
        if rng.random() < interactive_frac:
            headers = {"X-DKS-Priority": "interactive",
                       "X-DKS-Deadline-Ms": str(interactive_deadline_ms)}
            tag = "interactive"
        else:
            headers = {"X-DKS-Priority": "batch"}
            tag = "batch"
        plan.append((offset, array, headers, tag))
    return plan


# --------------------------------------------------------------------- #
# phase 3: SLO burn-rate alert lifecycle under a real flood
# --------------------------------------------------------------------- #


def _get(server, path):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=30)
    try:
        conn.request("GET", path)
        return conn.getresponse().read().decode()
    finally:
        conn.close()


def run_slo_alert_phase(n_requests=220, overload=2.0, poll_s=0.15,
                        resolve_timeout_s=30.0):
    """Flood a FIFO server carrying a fast-window interactive-latency SLO
    and watch the burn-rate alert's full lifecycle from the outside:
    ``/statusz?format=json`` polls, ``/metrics`` gauge polls, and the
    flight-recorder timeline at ``/debugz``.

    The windows are deliberately short (8 s long / 2 s short, for 0.4 s,
    keep-firing 1 s) so the lifecycle fits a benchmark run; production
    defaults live in ``observability/slo.py``."""

    from distributedkernelshap_tpu.observability.alerts import slo_burn_rule
    from distributedkernelshap_tpu.observability.slo import (
        BurnRateWindow,
        LatencySLO,
    )
    from distributedkernelshap_tpu.serving.server import ExplainerServer

    model = SyntheticModel()
    capacity_rps = 8 / (model.base_s + 8 * model.per_row_s)
    slo = LatencySLO(
        "interactive_latency_fast",
        histogram="dks_serve_class_latency_seconds",
        labels={"class": "interactive"}, threshold_s=0.5, target=0.9,
        windows=(BurnRateWindow(long_s=8.0, short_s=2.0, factor=2.0),),
        description="bench-fast interactive latency SLO")
    rule = slo_burn_rule(slo, for_s=0.4, keep_firing_s=1.0)
    server = ExplainerServer(
        model, host="127.0.0.1", port=0, max_batch_size=8,
        batch_timeout_s=0.004, scheduling="fifo", admission_control=False,
        health_interval_s=0.2, slos=[slo], alert_rules=[rule]).start()

    statusz_states, gauge_values = [], []
    stop_poll = threading.Event()
    gauge_name = f'dks_alerts_firing{{rule="{rule.name}"}}'

    def poll():
        while not stop_poll.is_set():
            try:
                doc = json.loads(_get(server, "/statusz?format=json"))
                statusz_states.append(doc["alerts"][0]["state"])
                gauge_values.append(
                    scrape_metrics(server).get(gauge_name, 0.0))
            except (OSError, http.client.HTTPException, ValueError,
                    KeyError, IndexError):
                # a torn response under the deliberate flood must not
                # kill the poller (and with it the lifecycle checks)
                pass
            time.sleep(poll_s)

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    try:
        plan = build_overload_plan(n_requests, capacity_rps * overload,
                                   0.4, 800, 6, seed=1)
        t0 = time.monotonic()
        open_loop(server, plan)
        flood_wall = time.monotonic() - t0
        # the alert must now resolve: the short window drains, the
        # condition goes false, keep_firing elapses
        deadline = time.monotonic() + resolve_timeout_s
        resolved = False
        while time.monotonic() < deadline:
            try:
                doc = json.loads(_get(server, "/statusz?format=json"))
                state = doc["alerts"][0]["state"]
            except (OSError, http.client.HTTPException, ValueError,
                    KeyError, IndexError):
                # a torn response while the flood drains must fail the
                # resolve CHECK at worst, never crash the bench
                state = None
            if state == "inactive":
                resolved = True
                break
            time.sleep(poll_s)
        stop_poll.set()
        poller.join(timeout=5)
        debug = json.loads(_get(server, "/debugz"))
        statusz_json = json.loads(_get(server, "/statusz?format=json"))
        # the gauge AFTER resolution (the poller's last sample predates it)
        gauge_final = scrape_metrics(server).get(gauge_name, 0.0)
    finally:
        stop_poll.set()
        server.stop()

    flight_states = [e["state"] for e in debug["events"]
                     if e["kind"] == "alert" and e.get("rule") == rule.name]
    return {
        "flood_wall_s": round(flood_wall, 2),
        "statusz_states_seen": sorted(set(statusz_states)),
        "flightrec_transitions": flight_states,
        "gauge_max": max(gauge_values, default=0.0),
        "gauge_final": gauge_final,
        "resolved_after_flood": resolved,
        "final_budget_remaining": statusz_json["slos"][0][
            "budget_remaining"],
    }


# --------------------------------------------------------------------- #
# phase 4: health-sampler overhead on the serve path
# --------------------------------------------------------------------- #


def run_sampler_overhead(n_requests=36, rows_per_request=10, warmup=6):
    """Identical closed-loop serial runs (deterministic device time:
    ``n_requests`` batches of ``base + rows*per_row`` seconds) with the
    sampler off vs on; the sampler must cost ≤1% wall time on the serve
    path.  The sampler is one thread copying ~20 metric dicts per tick,
    so its true cost is microseconds — the measurement discipline exists
    to keep host noise from swamping that: the compared statistic is the
    MEDIAN per-request latency (a run's wall clock is dominated by a few
    scheduler-hiccup outliers unrelated to the sampler), each arm runs
    twice in drift-symmetric order (off,on,on,off) taking the better
    median, a throwaway run warms the process first, and per-run warmup
    requests warm each server."""

    import statistics

    from distributedkernelshap_tpu.serving.server import ExplainerServer

    def one_run(interval: float):
        # max_batch_size=1: serial closed-loop traffic never coalesces,
        # and a batch size of 1 skips the fill wait entirely — the run is
        # sleep-dominated (device model) instead of timer-jitter-dominated
        server = ExplainerServer(
            SyntheticModel(), host="127.0.0.1", port=0,
            max_batch_size=1, scheduling="slo", admission_control=False,
            health_interval_s=interval).start()
        try:
            rng = np.random.default_rng(7)
            arrays = rng.normal(
                size=(warmup + n_requests, rows_per_request, 6)).astype(
                np.float32)
            latencies = []
            for i in range(warmup + n_requests):
                t0 = time.monotonic()
                status, _ = _post(server.host, server.port, arrays[i],
                                  {}, timeout=60)
                assert status == 200, status
                if i >= warmup:
                    latencies.append(time.monotonic() - t0)
            return statistics.median(latencies), sum(latencies)
        finally:
            server.stop()

    one_run(0.0)  # throwaway: the first server in a process runs slow
    meds = {"off": [], "on": []}
    walls = {"off": [], "on": []}
    for label, interval in (("off", 0.0), ("on", 0.5),
                            ("on", 0.5), ("off", 0.0)):
        med, wall = one_run(interval)
        meds[label].append(med)
        walls[label].append(wall)
    med_off, med_on = min(meds["off"]), min(meds["on"])
    overhead = max(0.0, (med_on - med_off) / med_off)
    return {
        "wall_off_s": round(min(walls["off"]), 3),
        "wall_on_s": round(min(walls["on"]), 3),
        "median_request_off_s": round(med_off, 5),
        "median_request_on_s": round(med_on, 5),
        "overhead_frac": round(overhead, 4),
    }


# --------------------------------------------------------------------- #
# phase 2: cache effectiveness on a real model
# --------------------------------------------------------------------- #


def run_cache_phase(n_requests=120, duplicate_frac=0.92, pool_size=5,
                    seed=0):
    from distributedkernelshap_tpu.models import LinearPredictor
    from distributedkernelshap_tpu.serving.server import ExplainerServer
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
    )

    rng = np.random.default_rng(seed)
    D, K = 6, 2
    W = rng.normal(size=(D, K)).astype(np.float32)
    b = rng.normal(size=(K,)).astype(np.float32)
    bg = rng.normal(size=(12, D)).astype(np.float32)
    pool = rng.normal(size=(pool_size, 1, D)).astype(np.float32)
    model = BatchKernelShapModel(LinearPredictor(W, b, activation="softmax"),
                                 bg, {"link": "logit", "seed": 0}, {})
    server = ExplainerServer(model, host="127.0.0.1", port=0,
                             max_batch_size=8, batch_timeout_s=0.005,
                             pipeline_depth=2,
                             cache_bytes=4 << 20).start()
    payloads_by_row = {}
    identical = True
    additivity_ok = True
    try:
        plan = []
        duplicates = 0
        for i in range(n_requests):
            if rng.random() < duplicate_frac:
                row_id = int(rng.integers(pool_size))
                duplicates += 1
            else:
                row_id = -(i + 1)  # novel request (-0 would alias pool row 0)
            array = (pool[row_id] if row_id >= 0
                     else rng.normal(size=(1, D)).astype(np.float32))
            plan.append((i * 0.003, array, {}, row_id))
        results = open_loop(server, plan)
        for tag, status, _, payload in results:
            if status != 200:
                identical = False
                continue
            if tag >= 0:
                if tag in payloads_by_row:
                    identical &= (payload == payloads_by_row[tag])
                else:
                    payloads_by_row[tag] = payload
            data = json.loads(payload)["data"]
            total = (np.asarray(data["shap_values"]).sum(-1)
                     + np.asarray(data["expected_value"])[:, None])
            additivity_ok &= bool(np.allclose(
                total, np.asarray(data["raw"]["raw_prediction"]).T,
                atol=1e-3))
        metrics = scrape_metrics(server)
    finally:
        server.stop()
    hits = metrics.get("dks_serve_cache_hits_total", 0)
    misses = metrics.get("dks_serve_cache_misses_total", 0)
    return {
        "n": n_requests,
        "duplicate_frac": round(duplicates / n_requests, 3),
        "hits": int(hits),
        "misses": int(misses),
        "hit_rate": round(hits / max(1, hits + misses), 3),
        "bit_identical": bool(identical),
        "additivity_ok": bool(additivity_ok),
        "cache_bytes": int(metrics.get("dks_serve_cache_bytes", 0)),
    }


# --------------------------------------------------------------------- #


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--requests", type=int, default=300,
                        help="open-loop requests per overload arm")
    parser.add_argument("--overload", type=float, default=2.0,
                        help="arrival rate as a multiple of capacity")
    parser.add_argument("--interactive_frac", type=float, default=0.3)
    # roughly four full-batch service times: tight enough that FIFO's
    # backlog blows through it (the A/B contrast), loose enough that an
    # EDF-prioritised request clears it even when admitted mid-batch
    parser.add_argument("--interactive_deadline_ms", type=float, default=800)
    parser.add_argument("--check", action="store_true",
                        help="exit 1 unless the acceptance criteria hold")
    parser.add_argument("--history", default=None,
                        help="perf-history JSONL this run appends to "
                             "(default: benchmarks/regression_gate.py's "
                             "results/perf_history.jsonl)")
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history self-record")
    args = parser.parse_args()

    # measured capacity of the synthetic model at full batching:
    # 8 rows per (base + 8*per_row) seconds
    model = SyntheticModel()
    capacity_rps = 8 / (model.base_s + 8 * model.per_row_s)
    rate = capacity_rps * args.overload
    dim = 6

    plan = build_overload_plan(args.requests, rate, args.interactive_frac,
                               args.interactive_deadline_ms, dim)
    fifo = run_overload_arm("fifo", plan, args.requests)
    slo = run_overload_arm("slo", plan, args.requests)
    cache = run_cache_phase()
    alert = run_slo_alert_phase()
    sampler = run_sampler_overhead()

    fifo_p99 = (fifo.get("interactive") or {}).get("p99_s")
    slo_p99 = (slo.get("interactive") or {}).get("p99_s")
    goodput_ratio = (slo["goodput_rps"] / fifo["goodput_rps"]
                     if fifo["goodput_rps"] else None)
    flight = alert["flightrec_transitions"]
    checks = {
        "interactive_p99_better": (fifo_p99 is not None
                                   and slo_p99 is not None
                                   and slo_p99 < fifo_p99),
        "nonzero_sheds_429": slo["sheds_total"] > 0 and (
            slo["interactive"]["shed_429"] + slo["batch"]["shed_429"] > 0),
        "goodput_within_10pct": (goodput_ratio is not None
                                 and goodput_ratio >= 0.9),
        "cache_hit_rate_ge_80pct": cache["hit_rate"] >= 0.8,
        "cache_bit_identical": cache["bit_identical"],
        "cache_additivity_ok": cache["additivity_ok"],
        # SLO alert lifecycle (phase 3): full pending→firing→resolved
        # on the flight-recorder timeline, firing visible to a /statusz
        # poller, the dks_alerts_firing gauge raised during the flood
        # and cleared after resolution
        "alert_pending_firing_resolved": flight == ["pending", "firing",
                                                    "resolved"],
        "alert_firing_on_statusz": "firing" in alert["statusz_states_seen"],
        "alert_gauge_fired": alert["gauge_max"] == 1.0,
        "alert_resolved_after_flood": (alert["resolved_after_flood"]
                                       and alert["gauge_final"] == 0.0),
        # sampler overhead (phase 4)
        "sampler_overhead_le_1pct": sampler["overhead_frac"] <= 0.01,
    }
    report = {
        "bench": "scheduling",
        "capacity_rps": round(capacity_rps, 1),
        "offered_rps": round(rate, 1),
        "fifo": fifo,
        "slo": slo,
        "goodput_ratio": round(goodput_ratio, 3) if goodput_ratio else None,
        "cache": cache,
        "slo_alert": alert,
        "sampler_overhead": sampler,
        "checks": checks,
        "ok": all(checks.values()),
    }
    if not args.no_record:
        # perf-history self-record: make perf-gate compares this run
        # against the trailing baseline for the same config fingerprint
        from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

        entry = record_run(
            args.history or DEFAULT_HISTORY, bench="scheduling",
            config={"requests": args.requests, "overload": args.overload,
                    "interactive_frac": args.interactive_frac,
                    "interactive_deadline_ms": args.interactive_deadline_ms,
                    "model": {"base_s": model.base_s,
                              "per_row_s": model.per_row_s}},
            metrics={"wall_s": slo["wall_s"],
                     "interactive_p99_s": slo_p99,
                     "goodput_rps": slo["goodput_rps"]},
            extra={"checks_ok": report["ok"]})
        report["perf_history"] = {"git_sha": entry["git_sha"],
                                  "config_fp": entry["config_fp"]}
    print(json.dumps(report))
    if args.check and not report["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
