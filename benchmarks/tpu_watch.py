"""Relay-recovery watcher: probe the TPU backend, then flush the round's
staged on-chip work the moment it answers.

Replaces the untracked ``.tpu_watch3*.sh`` dotfiles (VERDICT r4 weak #6):
the entire hardware-evidence pipeline used to hang on gitignored,
untestable shell scripts chained by log-grepping.  This is the same
discipline as a committed, unit-tested state machine:

    PROBING --(probe ok)--> SWEEPING --(steps done)--> DONE
        \\--(probe fails)--> sleep, re-probe (bounded by --max-hours)

Operational rules encoded here (learned rounds 2-4, catalogued in
``.claude/skills/verify/SKILL.md``):

* **One prober, full patience.**  A killed TPU client mid-init can re-wedge
  the relay; the probe child gets ``--probe-timeout`` (default 590 s — the
  relay's observed worst healthy init is ~500 s) before the watcher gives
  up on it, and probes are spaced ``--probe-interval`` apart.
* **Value-per-minute sweep order.**  The short configs that anchor the
  round's claims run first; the ~80-minute model-zoo leg runs LAST so a
  short relay window still captures the headline evidence.  Every step is
  its own subprocess appending to its own artifacts; a later hang cannot
  lose earlier numbers.
* **Evidence first.**  THREE steps feed ``results/bench_last_success.json``
  (benchmarks/_evidence.py): fast configs (the ``config:adult`` row),
  ``bench.py``, and ``serve_and_pool`` (the pool w=1/b=2560 point) — all
  ordered before the ~80-minute zoo leg, so a recovery window as short as
  ~10 minutes already puts an on-chip headline number where the driver's
  end-of-round ``bench.py`` will attach it.
* **Steps continue on failure** and their rc/duration land in
  ``results/tpu_watch.jsonl`` — the sweep's own state is an artifact.

Run:  ``python benchmarks/tpu_watch.py``          (probe loop + sweep)
      ``python benchmarks/tpu_watch.py --sweep-only``   (relay known healthy)
      ``python benchmarks/tpu_watch.py --dry-run``      (print the plan)
"""

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks._evidence import REPO_ROOT, code_version  # noqa: E402

LOG_PATH = os.path.join(REPO_ROOT, "results", "tpu_watch.jsonl")


@dataclasses.dataclass(frozen=True)
class Step:
    """One sweep step: a bounded subprocess with its own artifacts."""

    name: str
    argv: Sequence[str]
    timeout_s: float
    env: Optional[dict] = None  # overrides merged onto os.environ
    why: str = ""


def default_steps() -> List[Step]:
    """The round-5 staged-backlog sweep, value-per-minute ordered."""

    py = sys.executable
    reval = os.path.join(REPO_ROOT, "benchmarks", "tpu_revalidate.py")
    return [
        Step("fast_configs",
             [py, reval, "--only", "adult,adult_stress,adult_trees,"
                                   "adult_trees_exact,mnist,covertype"],
             timeout_s=5400,
             why="headline adult (feeds the evidence cache), stress, trees, "
                 "the exact A/B vs sampled, mnist (dispatch-window chunks), "
                 "covertype (pipeline+f16+retile+ranking) — every result "
                 "now carries kernel_path"),
        Step("bench_contract",
             [py, os.path.join(REPO_ROOT, "bench.py")],
             timeout_s=600, env={"DKS_BENCH_SKIP_PROBE": "1",
                                 "DKS_BENCH_BUDGET": "420"},
             why="the driver's exact contract; caches its own success"),
        Step("exact_ab",
             [py, os.path.join(REPO_ROOT, "benchmarks", "exact_ab.py"),
              "--arm", "adult,large"],
             timeout_s=2700,
             why="fused exact kernels vs einsum on real Mosaic — the "
                 "kernel_path field proves which path engaged (a Mosaic "
                 "auto-degrade can no longer masquerade as a measurement); "
                 "the large arm exercises the packed pallas route "
                 "(per-bucket dmax) at >=1000 trees x depth>=10"),
        Step("serve_and_pool",
             [py, reval, "--only", "serve,pool"],
             timeout_s=3600,
             why="serve auto/hand depth rows + the pool points — the "
                 "w=1/b=2560 point is the pool-protocol evidence-cache "
                 "feed, and both pickles now record kernel_path"),
        Step("blackbox_and_regression",
             [py, reval, "--only", "adult_blackbox,regression"],
             timeout_s=3600,
             why="host-eval fan-out now defaults to the core count; the "
                 "fused-tree-eval regression sweep"),
        Step("model_zoo",
             [py, reval, "--only", "model_zoo"],
             timeout_s=7200,
             why="the f32-oracle zoo refresh (~80 min of host model "
                 "training) runs LAST — it must not starve the short, "
                 "evidence-bearing steps"),
    ]


def _log(record: dict, log_path: str = LOG_PATH) -> None:
    record = dict(record, ts=time.strftime("%Y-%m-%dT%H:%M:%S"),
                  code_version=code_version())  # lru-cached in _evidence
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, "a") as f:
        f.write(json.dumps(record) + "\n")
    print(json.dumps(record), flush=True)


def probe_device(timeout_s: float) -> bool:
    """One backend-init probe via the shared child-probe ladder
    (``benchmarks/_evidence.device_probe``).  The child gets the FULL
    timeout before being terminated — killing a TPU client during a
    slow-but-progressing init is the known re-wedge hazard, so the timeout
    must exceed the worst healthy init, and the watcher never probes
    concurrently."""

    from benchmarks._evidence import device_probe

    ok, _ = device_probe(timeout_s)
    return ok


def run_step(step: Step) -> dict:
    """Execute one sweep step; returns its outcome record (never raises).

    The timeout path uses the same SIGTERM→bounded-wait→SIGKILL→bounded-
    wait ladder as ``_evidence.device_probe`` — ``subprocess.run`` would
    SIGKILL then ``wait()`` UNBOUNDEDLY, so a child stuck in the
    uninterruptible wedged device call (the exact failure mode this
    watcher exists to survive) would hang the sweep forever with no
    step_done record and stop the evidence-cache feeders from ever
    running."""

    env = dict(os.environ, **(step.env or {}))
    t0 = time.monotonic()
    try:
        proc = subprocess.Popen(list(step.argv), cwd=REPO_ROOT, env=env)
    except OSError as e:
        return {"step": step.name, "rc": -1, "error": str(e),
                "elapsed_s": round(time.monotonic() - t0, 1)}
    rc: Optional[int] = None
    timed_out = False
    try:
        rc = proc.wait(timeout=step.timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.terminate()  # SIGTERM first: give the client a chance to exit
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass  # unkillable (D-state) child: abandon, keep sweeping
    return {"step": step.name, "rc": rc, "timed_out": timed_out,
            "elapsed_s": round(time.monotonic() - t0, 1)}


class Watcher:
    """The probe→sweep state machine, with every effect injectable so the
    whole flow is unit-testable against fakes (``tests/test_tpu_watch.py``)."""

    def __init__(self,
                 steps: Optional[List[Step]] = None,
                 probe: Callable[[float], bool] = probe_device,
                 runner: Callable[[Step], dict] = run_step,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 log: Callable[[dict], None] = _log,
                 probe_timeout_s: float = 590.0,
                 probe_interval_s: float = 300.0,
                 max_hours: float = 24.0):
        self.steps = default_steps() if steps is None else steps
        self._probe = probe
        self._runner = runner
        self._sleep = sleep
        self._clock = clock
        self._log = log
        self.probe_timeout_s = probe_timeout_s
        self.probe_interval_s = probe_interval_s
        self.max_hours = max_hours

    def wait_for_recovery(self) -> bool:
        """Probe until the backend answers or the patience budget runs out.
        Returns whether the relay recovered."""

        deadline = self._clock() + self.max_hours * 3600.0
        attempt = 0
        while True:
            attempt += 1
            self._log({"state": "probing", "attempt": attempt})
            if self._probe(self.probe_timeout_s):
                self._log({"state": "recovered", "attempt": attempt})
                return True
            if self._clock() >= deadline:
                self._log({"state": "gave_up", "attempt": attempt,
                           "max_hours": self.max_hours})
                return False
            self._log({"state": "wedged", "attempt": attempt})
            self._sleep(self.probe_interval_s)

    def sweep(self) -> List[dict]:
        """Run every step in order, continuing past failures; single-shot."""

        results = []
        for step in self.steps:
            self._log({"state": "step_start", "step": step.name,
                       "why": step.why})
            outcome = self._runner(step)
            self._log(dict(outcome, state="step_done"))
            results.append(outcome)
        self._log({"state": "sweep_done",
                   "ok_steps": sum(1 for r in results if r.get("rc") == 0),
                   "n_steps": len(results)})
        return results

    def run(self, sweep_only: bool = False) -> int:
        """Full flow; returns a process exit code."""

        if not sweep_only:
            if not self.wait_for_recovery():
                return 1
            # settle: a client blocked mid-RPC through the recovering relay
            # may need a moment to resume before new sessions pile on (a
            # sweep-only caller declared the relay already healthy)
            self._sleep(30.0)
        results = self.sweep()
        return 0 if any(r.get("rc") == 0 for r in results) else 1


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--sweep-only", action="store_true",
                        help="skip probing (relay known healthy)")
    parser.add_argument("--dry-run", action="store_true",
                        help="print the sweep plan and exit")
    parser.add_argument("--probe-timeout", type=float, default=590.0)
    parser.add_argument("--probe-interval", type=float, default=300.0)
    parser.add_argument("--max-hours", type=float, default=24.0)
    args = parser.parse_args(argv)

    watcher = Watcher(probe_timeout_s=args.probe_timeout,
                      probe_interval_s=args.probe_interval,
                      max_hours=args.max_hours)
    if args.dry_run:
        for step in watcher.steps:
            print(json.dumps({"step": step.name, "argv": list(step.argv),
                              "timeout_s": step.timeout_s, "why": step.why}))
        return 0
    return watcher.run(sweep_only=args.sweep_only)


if __name__ == "__main__":
    sys.exit(main())
