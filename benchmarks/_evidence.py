"""On-chip evidence cache shared by every benchmark protocol.

The TPU relay's uptime windows rarely align with the driver's end-of-round
``bench.py`` run (rounds 1-4: rc=124, 1, 1, 1 — while committed on-chip
sessions existed in ``results/tpu_revalidate.jsonl`` each round).  Round 4
cached ``bench.py``'s own successes only, which left the cache empty when
the round's on-chip sessions ran other protocols (VERDICT r4 missing #1).

This module closes that hole: EVERY protocol that measures the headline
task on a non-CPU backend (``bench.py``, ``tpu_revalidate.py``'s
``config:adult`` step, the pool benchmark's w=1/b=2560 point, the recovery
watcher) records its success here, labelled with the protocol, capture
time and code version — so ONE healthy relay window anywhere in the round,
under ANY protocol, puts an on-chip number into the driver artifact.

The cache is a single JSON file (``results/bench_last_success.json``),
written atomically; readers treat a missing/corrupt file as "no evidence".
"""

import functools
import json
import os
import subprocess
import sys
import time
from typing import Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the one cache file every protocol feeds and ``bench.py`` attaches
CACHE_PATH = os.path.join(REPO_ROOT, "results", "bench_last_success.json")


@functools.lru_cache(maxsize=1)
def code_version() -> str:
    """Short commit hash of the code that produced a measurement (ties a
    cached record to what was benchmarked; 'unknown' outside a checkout).
    Cached — constant for the process lifetime, and callers emit it once
    per record (a 24 h watch emits hundreds)."""

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            cwd=REPO_ROOT, timeout=10)
        if out.returncode == 0:
            return out.stdout.decode().strip() or "unknown"
    except (OSError, subprocess.TimeoutExpired):
        pass
    return "unknown"


def device_probe(timeout_s: float) -> Tuple[bool, str]:
    """Probe backend init in a throwaway child; ``(ok, detail)``.

    The ONE copy of the kill-a-TPU-client-safely ladder, shared by
    ``bench.py`` and ``tpu_watch.py``: a killed TPU client can wedge the
    tunnel relay so that backend init blocks forever (uninterruptibly, in
    C) for every later process — probing in a child lets callers fail fast
    with a bounded wait, and the SIGTERM→wait→SIGKILL→wait escalation
    mirrors how a shell ``timeout`` would end it.  NB: killing a client
    during a slow-but-progressing first init (the recovery window after a
    wedge) can RE-wedge the relay, so callers must give ``timeout_s`` the
    full worst-healthy-init patience (~590 s for the watcher) and never
    probe concurrently.
    """

    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    try:
        _, err = proc.communicate(timeout=timeout_s)
        if proc.returncode == 0:
            return True, ""
        return False, err.decode(errors="replace").strip()[-400:]
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.communicate(timeout=5)
            except subprocess.TimeoutExpired:
                pass  # unkillable child: leave it behind rather than hang
        return False, f"backend init did not complete within {timeout_s:.0f}s"


def record_onchip_success(record: dict, protocol: str,
                          cache_path: str = None) -> bool:
    """Persist an on-chip headline measurement for the wedged-path artifact.

    ``record`` must carry a numeric ``value`` (seconds for the headline
    2560-instance Adult explain) and SHOULD carry ``platform`` — records
    whose platform is ``'cpu'`` are refused (the cache exists precisely so
    CPU fallbacks never impersonate chip evidence).  Returns True when the
    cache was written.  Best-effort: IO errors never propagate into the
    measuring process (the printed/logged line remains the contract there).
    """

    path = cache_path or CACHE_PATH
    # a MISSING platform is refused too: a protocol that forgets to stamp
    # it while running on the CPU backend would otherwise cache a CPU
    # number as chip evidence — the exact impersonation this gate prevents
    if record.get("platform") in (None, "cpu"):
        return False
    if not isinstance(record.get("value"), (int, float)):
        return False
    try:
        stamped = dict(record, captured_unix=time.time(),
                       code_version=code_version(), protocol=protocol)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # atomic replace: a concurrently-wedging driver invocation must
        # never read a half-written cache (that race window is exactly what
        # this cache exists to cover)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(stamped, f)
        os.replace(tmp, path)
        return True
    except OSError:
        return False


def load_last_onchip(cache_path: str = None) -> Optional[dict]:
    """The most recent on-chip success (any protocol) with its age, or
    ``None``.  The returned dict carries ``age_hours`` plus a note making
    clear it is cached evidence, not the current invocation's measurement."""

    path = cache_path or CACHE_PATH
    try:
        with open(path) as f:
            last = json.load(f)
        age_h = (time.time() - float(last.pop("captured_unix"))) / 3600.0
        return dict(
            last, age_hours=round(age_h, 2),
            note="cached on-chip run from an earlier session this round; "
                 "NOT measured by this invocation — protocol says which "
                 "benchmark captured it, age_hours how stale, code_version "
                 "what was benchmarked")
    except (OSError, ValueError, KeyError, TypeError):
        return None
