"""Cold-start benchmark: persistent compile cache + warmup ladder +
plan-constant device caching (standalone, CPU backend, exits nonzero on
``--check`` fail).

Three measurements, one JSON line:

1. **Cold-start A/B** — four cold *process* starts of a real
   ``ExplainerServer`` (synthetic logistic deployment, warmup ladder ON),
   two without the persistent compile cache and two sharing a fresh cache
   directory, **bracketed** (uncached → cached-populate → uncached →
   cached-measure) so the latency comparison is between drift-adjacent
   starts on this load-drifting 1-core box.  Each child reports the
   warming→ready ``/healthz`` transition, the warmup-ladder compile
   accounting (per shape signature), ``/statusz`` warmup visibility, the
   cold-process→first-answer latency, and the first answer's phi.
   Criteria: the second cached start records **zero fresh compiles** for
   every ladder shape (all served by the persistent cache) and a
   cold→first-answer latency reduction vs the adjacent uncached start;
   every child observed ``/healthz`` not-ready (``"warming"``) before
   ready and ``/statusz`` shows the ladder done; phi **bit-identical**
   across all four starts (the cache changes where executables come
   from, never what they compute).
2. **Plan-constant A/B** — small-B interactive requests against two
   engines running the *same* two-stage linear fast path, constants
   served from the device cache vs recomputed every call
   (``plan_constant_cache=False``, the honest control arm — identical
   compiled program, so phi is bit-identical by construction and the
   timing difference is exactly what the cache saves).  Criteria:
   cached median per-request time strictly below uncached, phi
   bit-identical on every request, and both arms allclose to the classic
   self-contained program (``plan_constant_cache='off'``; XLA fuses that
   graph differently, so equality there is tolerance-based — see
   ``ops/explain.build_linear_cached_fn``).
3. Every measured run **self-records** into the perf history
   (``benchmarks/regression_gate.py``; disable with ``--no-record``)
   with the warmed cold-start latency as ``wall_s``, so ``make
   perf-gate`` covers cold-start regressions.

    JAX_PLATFORMS=cpu python benchmarks/warmup_bench.py --check
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)

CHILD_TIMEOUT_S = 300.0


# --------------------------------------------------------------------- #
# child: one cold process start
# --------------------------------------------------------------------- #


def _child(port: int, request_b: int) -> int:
    """One cold server start: build + warm + answer one request, print a
    JSON report.  The parent scripts the persistent cache via
    ``DKS_COMPILE_CACHE_DIR`` in the child env; ``t0`` is process start
    (well, interpreter main — the honest cold-start clock)."""

    t0 = time.monotonic()
    import numpy as np

    from distributedkernelshap_tpu.runtime.compile_cache import (
        compile_events,
    )
    from distributedkernelshap_tpu.serving.replica_worker import (
        synthetic_factory,
    )
    from distributedkernelshap_tpu.serving.server import serve_explainer

    ce = compile_events()
    before = ce.snapshot()

    predictor, background, ctor_kwargs, fit_kwargs = synthetic_factory()
    # max_batch_size=16 → a 5-rung ladder: enough compile work that the
    # persistent-cache saving stays visible over this box's load noise
    server = serve_explainer(
        predictor.predict_proba, background, ctor_kwargs, fit_kwargs,
        host="127.0.0.1", port=port, max_batch_size=16, pipeline_depth=1,
        warmup=True)

    url = f"http://127.0.0.1:{port}"
    saw_warming = False
    ready_s = None
    deadline = time.monotonic() + CHILD_TIMEOUT_S
    while time.monotonic() < deadline:
        try:
            resp = urllib.request.urlopen(url + "/healthz", timeout=5)
            code, body = resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            code, body = e.code, json.loads(e.read())
        except OSError:
            time.sleep(0.02)
            continue
        if body.get("status") == "warming":
            saw_warming = True
        if code == 200:
            ready_s = time.monotonic() - t0
            break
        time.sleep(0.02)

    # the synthetic factory's deterministic rows — every child asks the
    # same question, so phi must agree bit-for-bit across all starts
    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 8)).astype(np.float32)
    payload = json.dumps({"array": X[40:40 + request_b].tolist()}).encode()
    req = urllib.request.Request(
        url + "/explain", data=payload,
        headers={"Content-Type": "application/json"})
    answer = json.loads(urllib.request.urlopen(req, timeout=60).read())
    first_answer_s = time.monotonic() - t0

    statusz = json.loads(urllib.request.urlopen(
        url + "/statusz?format=json", timeout=10).read())
    warmup = server.warmup_status()
    delta = ce.delta(before, ce.snapshot())
    server.stop()

    print(json.dumps({
        "ready_s": round(ready_s, 4) if ready_s is not None else None,
        "first_answer_s": round(first_answer_s, 4),
        "saw_warming": saw_warming,
        "warmup": {k: warmup[k] for k in
                   ("state", "buckets", "completed_buckets", "compile",
                    "elapsed_s")},
        "statusz_warmup_state": statusz["detail"]["warmup"]["state"],
        "statusz_warmup_completed": statusz["detail"]["warmup"]["completed"],
        # per-signature compile accounting: {"kind|sig": count}
        "compile_by_signature": {
            f"{kind}|{sig}": int(n)
            for (kind, sig), n in delta["counts"].items()},
        "compile_totals": delta["totals"],
        "compile_seconds_totals": {
            k: round(v, 4) for k, v in delta["seconds_totals"].items()},
        "shap_values": answer["data"]["shap_values"],
    }))
    return 0


def _spawn_child(port: int, request_b: int, cache_dir=None) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DKS_COMPILE_CACHE_DIR", None)
    if cache_dir:
        env["DKS_COMPILE_CACHE_DIR"] = cache_dir
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--port", str(port), "--request-b", str(request_b)],
        env=env, capture_output=True, text=True, timeout=CHILD_TIMEOUT_S,
        cwd=REPO_ROOT)
    if proc.returncode != 0:
        raise RuntimeError(
            f"cold-start child failed (rc={proc.returncode}):\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


# --------------------------------------------------------------------- #
# phase 1: cold-start A/B across process starts
# --------------------------------------------------------------------- #


def run_cold_start_ab(base_port: int, request_b: int) -> dict:
    """Two cold starts without the persistent cache, two sharing a fresh
    cache dir, BRACKETED (uncached, cached-populate, uncached, cached-
    measure): this 1-core box drifts under load, so the latency check
    compares the drift-adjacent pair (the last two starts) rather than
    arms run minutes apart.  The second cached start must compile NOTHING
    fresh for ladder shapes, and answer cold-to-first-answer faster than
    the adjacent uncached start."""

    with tempfile.TemporaryDirectory(prefix="dks-compile-cache-") as cache:
        u1 = _spawn_child(base_port, request_b)
        c1 = _spawn_child(base_port + 1, request_b, cache_dir=cache)
        u2 = _spawn_child(base_port + 2, request_b)
        c2 = _spawn_child(base_port + 3, request_b, cache_dir=cache)
        cache_files = len(os.listdir(cache))

    uncached, cached = [u1, u2], [c1, c2]
    runs = [u1, c1, u2, c2]
    warm2 = c2
    ladder = warm2["warmup"]["buckets"]
    ladder_fresh = {
        f"rows={b}": warm2["compile_by_signature"].get(f"fresh|rows={b}", 0)
        for b in ladder}
    ladder_hits = sum(
        warm2["compile_by_signature"].get(f"cache_hit|rows={b}", 0)
        for b in ladder)
    # drift-adjacent comparison: u2 ran immediately before c2
    uncached_first = u2["first_answer_s"]
    phi0 = runs[0]["shap_values"]
    return {
        "uncached_first_answer_s": [r["first_answer_s"] for r in uncached],
        "cached_first_answer_s": [r["first_answer_s"] for r in cached],
        "uncached_ready_s": [r["ready_s"] for r in uncached],
        "cached_ready_s": [r["ready_s"] for r in cached],
        "ladder": ladder,
        "warm_start_ladder_fresh": ladder_fresh,
        "warm_start_ladder_cache_hits": ladder_hits,
        "warm_start_compile_totals": warm2["compile_totals"],
        "warm_start_compile_seconds": warm2["compile_seconds_totals"],
        "cache_files": cache_files,
        "checks": {
            # readiness gating observed on every start: /healthz answered
            # the distinct "warming" 503 before going ready, and /statusz
            # rendered the finished ladder
            "healthz_gates_warmup": all(
                r["saw_warming"] and r["ready_s"] is not None
                for r in runs),
            "statusz_shows_warmup": all(
                r["statusz_warmup_state"] == "done"
                and r["statusz_warmup_completed"] == len(r["warmup"]["buckets"])
                for r in runs),
            "ladder_completed_everywhere": all(
                r["warmup"]["state"] == "done"
                and r["warmup"]["completed_buckets"] == r["warmup"]["buckets"]
                for r in runs),
            # the tentpole: a second cold process start pays ZERO fresh
            # compiles for warmed shapes — the persistent cache served
            # every ladder rung
            "warm_start_zero_fresh_ladder_compiles": (
                sum(ladder_fresh.values()) == 0 and ladder_hits > 0),
            "warm_start_faster_first_answer": (
                warm2["first_answer_s"] < uncached_first),
            # warm-vs-cold bit-identity: same request, same phi, every arm
            "phi_bit_identical_across_starts": all(
                r["shap_values"] == phi0 for r in runs[1:]),
        },
    }


# --------------------------------------------------------------------- #
# phase 2: plan-constant device cache A/B (in-process)
# --------------------------------------------------------------------- #


def run_plan_constant_ab(request_b: int, requests: int) -> dict:
    """Small-B per-request device time with the plan-constant cache vs the
    recompute-every-call control arm (same compiled program → phi
    bit-identical by construction), plus an allclose sanity arm against
    the classic self-contained program."""

    import numpy as np

    from distributedkernelshap_tpu.data import DenseData
    from distributedkernelshap_tpu.kernel_shap import (
        EngineConfig,
        KernelExplainerEngine,
    )

    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 8)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    clf = LogisticRegression(max_iter=200).fit(X, y)
    bg = DenseData(X[:32], [f"f{i}" for i in range(8)], None)

    def build(mode):
        return KernelExplainerEngine(
            clf.predict_proba, bg, link="logit", seed=0,
            config=EngineConfig(plan_constant_cache=mode))

    cached, control, classic = build(True), build(False), build('off')
    queries = [X[64 + i * request_b:64 + (i + 1) * request_b]
               for i in range(requests)]

    # compile + first-dispatch warm for every arm (the cold-start story is
    # phase 1's; this phase isolates steady-state per-request time)
    for eng in (cached, control, classic):
        eng.get_explanation(queries[0])

    def timed(eng):
        times, outs = [], []
        for Xq in queries:
            t0 = time.perf_counter()
            outs.append(np.stack(eng.get_explanation(Xq)))
            times.append(time.perf_counter() - t0)
        return times, outs

    cached_t, cached_phi = timed(cached)
    control_t, control_phi = timed(control)
    _, classic_phi = timed(classic)

    bit_identical = all(
        (a == b).all() for a, b in zip(cached_phi, control_phi))
    classic_close = all(
        np.allclose(a, c, atol=2e-6)
        for a, c in zip(cached_phi, classic_phi))
    cached_med = statistics.median(cached_t)
    control_med = statistics.median(control_t)
    return {
        "request_b": request_b,
        "requests": requests,
        "cached_request_s": round(cached_med, 6),
        "uncached_request_s": round(control_med, 6),
        "speedup": round(control_med / cached_med, 2) if cached_med else None,
        "kernel_path": cached.kernel_path,
        "checks": {
            "planconst_fast_path_engaged": (
                cached.kernel_path.get("ey") == "einsum_cached"),
            "planconst_cached_faster": cached_med < control_med,
            "planconst_phi_bit_identical": bit_identical,
            "planconst_classic_allclose": classic_close,
        },
    }


# --------------------------------------------------------------------- #


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true",
                        help="exit nonzero unless every criterion holds")
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--port", default=19840, type=int)
    parser.add_argument("--request-b", default=3, type=int,
                        help="rows per small-B request")
    parser.add_argument("--requests", default=30, type=int,
                        help="timed requests per plan-constant arm")
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history self-record")
    parser.add_argument("--history", default=None,
                        help="perf-history path (default: results/"
                             "perf_history.jsonl)")
    args = parser.parse_args()

    if args.child:
        return _child(args.port, args.request_b)

    t0 = time.monotonic()
    cold = run_cold_start_ab(args.port, args.request_b)
    planconst = run_plan_constant_ab(args.request_b, args.requests)

    checks = {**cold["checks"], **planconst["checks"]}
    report = {
        "bench": "warmup",
        "wall_s": round(time.monotonic() - t0, 2),
        "cold_start": {k: v for k, v in cold.items() if k != "checks"},
        "plan_constant": {k: v for k, v in planconst.items()
                          if k != "checks"},
        "checks": checks,
        "ok": all(checks.values()),
    }
    if not args.no_record:
        # perf-history self-record: wall_s is the WARMED cold-process→
        # first-answer latency — the number this subsystem exists to keep
        # small — so make perf-gate fails a commit that regresses it
        from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

        entry = record_run(
            args.history or DEFAULT_HISTORY, bench="warmup",
            config={"request_b": args.request_b,
                    "requests": args.requests,
                    "max_batch_size": 16},
            metrics={"wall_s": cold["cached_first_answer_s"][1],
                     "planconst_request_s":
                         planconst["cached_request_s"]},
            extra={"checks_ok": report["ok"],
                   "uncached_first_answer_s":
                       min(cold["uncached_first_answer_s"])})
        report["perf_history"] = {"git_sha": entry["git_sha"],
                                  "config_fp": entry["config_fp"]}
    print(json.dumps(report))
    if args.check and not report["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
