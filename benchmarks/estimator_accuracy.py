"""Estimator-accuracy benchmark + CI gate (``make accuracy-gate``).

The sampled KernelSHAP estimator pays for accuracy with ``nsamples`` —
and until now nothing measured that trade against ground truth, so an
estimator regression (a weighting bug, a broken sampler, a degraded
solve) would ship as silently as a perf regression did before
``make perf-gate``.  The exact paths close the loop: exact-TN
(``ops/tensor_shap.py``) provides sampling-free ground truth at feature
counts whose coalition spaces (``2^M``) no enumeration-based A/B —
``results/exact_ab.jsonl`` included — could ever cover, and exact-tree
(``ops/treeshap.py``) anchors a second model family.

What one run does:

* sweeps the sampled estimator across ``nsamples`` budgets on a
  mid-size tensor-train model (M=24: 16.7M coalitions), a lifted GBT,
  and — since the deep-model attribution engine landed — a
  piecewise-linear neural graph whose DeepSHAP phi is provably exact
  (``--families``, default all three plus the anytime arm), recording
  the max-abs phi error against the analytic path per budget into
  ``results/accuracy_history.jsonl`` (same entry schema as the perf
  history: git SHA + config fingerprint + metrics);
* the ``anytime`` arm replaces the budget sweep with progressive
  refinement (``anytime/``): one run per batch steps every round,
  pairing the engine's calibrated REPORTED error with the TRUE error
  against exact-enumeration ground truth — per-round true errors gate
  as ``err_n{cumulative}`` like any family, and ``--check`` fails when
  the reported error stops bounding the true error within
  x``ANYTIME_ERR_BOUND`` at >= ``ANYTIME_COVERAGE`` of observed rounds
  (the honest-error-bar contract streaming clients budget against);
* gates the newest run of each (bench, config) against the median of
  its trailing same-config baselines with the ``regression_gate``
  machinery — an error metric rising >50% over baseline (above a small
  absolute floor) fails, exactly how ``wall_s`` fails the perf gate;
* ``--check`` additionally asserts the structural criteria: error
  decreases monotonically-ish with budget, the exact-TN path beats the
  sampled path's per-instance wall-clock at matched phi error (the
  sampled arm's most accurate budget still carries MORE error than the
  exact path's zero, so beating its wall means exact dominates both
  axes — self-recorded with ``checks_ok`` into
  ``results/perf_history.jsonl`` so ``make perf-gate`` covers it), and
  a synthetic degraded-estimator entry demonstrably fails the gate
  (drilled against a throwaway copy of the history).
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from typing import Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.regression_gate import (  # noqa: E402
    DEFAULT_HISTORY,
    _median,
    config_fingerprint,
    load_history,
    record_run,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ACCURACY_HISTORY = os.path.join(REPO_ROOT, "results",
                                "accuracy_history.jsonl")

#: allowed per-budget error increase over the trailing baseline median
#: (fraction) — accuracy analog of regression_gate's wall threshold
MAX_ERR_REGRESSION = 0.50
#: absolute error floor below which ratios are noise (f32 phi on unit-
#: scale models: a 2e-7 -> 4e-7 wobble is not a regression)
ERR_ABS_FLOOR = 1e-6
#: trailing runs folded into the baseline median
BASELINE_N = 5

#: default nsamples sweep (well under the TN model's 2^24 coalition
#: space, so every budget genuinely samples)
DEFAULT_BUDGETS = (128, 512, 2048)

#: adjacent-budget tolerance for the monotonicity criterion: sampling
#: error is stochastic in the seed, so "monotonically-ish" allows one
#: budget step to backslide by up to this factor
MONO_SLACK = 1.25

#: total refinement budget for the anytime arm (M=14: 16382 proper
#: coalitions, so every round of the 4-round geometric schedule
#: genuinely samples while exact enumeration stays tractable as truth)
ANYTIME_NSAMPLES = 1024
#: the honesty contract the serving error budget rides on: reported
#: error must bound true error within this factor ...
ANYTIME_ERR_BOUND = 2.0
#: ... at at least this fraction of observed (batch, round) pairs
ANYTIME_COVERAGE = 0.90


# --------------------------------------------------------------------- #
# models


def build_tn_model(seed: int = 0):
    """Mid-size tensor-train model + background/explain rows: M=24
    features (2^24 coalitions — beyond any enumeration A/B), rank 4,
    deterministic from the seed.  Cores are scaled so products stay
    O(1) over 24 sites (the per-site scale ~ r^-1/2 keeps the chained
    matmuls from exploding, mirroring how fitted surrogates come out)."""

    from distributedkernelshap_tpu.models.tensor_net import (
        TensorTrainPredictor,
    )

    rng = np.random.default_rng(seed)
    M, r = 24, 4
    dims = [1] + [r] * (M - 1) + [1]
    scale = 1.0 / np.sqrt(r)
    cores = []
    for i in range(M):
        A = rng.normal(scale=scale, size=(dims[i], dims[i + 1]))
        B = rng.normal(scale=0.3 * scale, size=(dims[i], dims[i + 1]))
        cores.append((A.astype(np.float32), B.astype(np.float32)))
    pred = TensorTrainPredictor(cores)
    bg = rng.normal(size=(32, M)).astype(np.float32)
    X = rng.normal(size=(8, M)).astype(np.float32)
    return pred, bg, X, {"family": "tn", "M": M, "rank": r,
                         "n_bg": 32, "n_x": 8, "seed": seed}


def build_deepshap_model(seed: int = 0):
    """Piecewise-linear neural graph in a provably-exact DeepSHAP regime
    (feature-wise Relu units: the model is additive across features, so
    the rescale rule IS the Shapley marginal — pinned against brute-force
    enumeration in tests/test_deepshap.py and deepshap_bench).  M=12
    (4094 proper coalitions), mixed-sign weights so the Relus genuinely
    clip; the DeepSHAP phi is the sampled estimator's ground truth."""

    from distributedkernelshap_tpu.registry.onnx_lift import lift_graph

    from benchmarks.deepshap_bench import build_additive_mlp_spec

    rng = np.random.default_rng(seed)
    M, H = 12, 24
    # the ONE additive-net construction, shared with deepshap_bench's
    # exactness phase — the regime both benches' claims rest on must be
    # a single definition, not two hand-maintained copies
    spec = build_additive_mlp_spec(seed=seed, M=M, H=H, K=2)
    pred = lift_graph(spec)
    bg = rng.normal(size=(16, M)).astype(np.float32)
    X = rng.normal(size=(8, M)).astype(np.float32)
    # "builder" marks the shared-construction revision in the config
    # fingerprint: the builder defines the measured data stream, so a
    # builder change must start a fresh gate baseline, not look like an
    # estimator regression against the old stream's floor
    return pred, bg, X, {"family": "deepshap", "M": M, "hidden": H,
                         "n_bg": 16, "n_x": 8, "seed": seed,
                         "builder": "shared_additive_v1",
                         "budgets_override": (128, 512, 2048)}


def build_tree_model(seed: int = 0):
    """Small lifted GBT (exact-tree ground truth anchor): M=8 features,
    sampled budgets below 2^8-2=254 genuinely sample."""

    from sklearn.ensemble import HistGradientBoostingRegressor

    rng = np.random.default_rng(seed)
    M = 8
    Xtr = rng.normal(size=(300, M))
    y = (Xtr[:, 0] - np.where(Xtr[:, 2] > 0, 1.0, -1.0) * Xtr[:, 3]
         + 0.5 * Xtr[:, 5])
    gbr = HistGradientBoostingRegressor(max_iter=12,
                                        random_state=seed).fit(Xtr, y)
    bg = Xtr[:16].astype(np.float32)
    X = Xtr[100:108].astype(np.float32)
    # the 2^8-2=254 coalition space caps useful budgets well below the
    # TN sweep's; this family brings its own so every point samples
    return gbr.predict, bg, X, {"family": "tree", "M": M, "n_bg": 16,
                                "n_x": 8, "seed": seed,
                                "budgets_override": (32, 64, 128)}


def build_anytime_model(seed: int = 0):
    """Tensor-train model for the anytime arm: the exact-TN DP
    contraction is the only sampling-free ground truth that scales past
    enumeration, and at M=14 the 2^14-2 coalition space sits far above
    ``ANYTIME_NSAMPLES`` so every refinement round genuinely samples.
    Same core construction (and O(1) product scaling) as the TN family,
    at the anytime serving sweet spot's feature count."""

    from distributedkernelshap_tpu.models.tensor_net import (
        TensorTrainPredictor,
    )

    rng = np.random.default_rng(seed)
    M, r = 14, 4
    dims = [1] + [r] * (M - 1) + [1]
    scale = 1.0 / np.sqrt(r)
    cores = []
    for i in range(M):
        A = rng.normal(scale=scale, size=(dims[i], dims[i + 1]))
        B = rng.normal(scale=0.3 * scale, size=(dims[i], dims[i + 1]))
        cores.append((A.astype(np.float32), B.astype(np.float32)))
    pred = TensorTrainPredictor(cores)
    bg = rng.normal(size=(16, M)).astype(np.float32)
    X = rng.normal(size=(8, M)).astype(np.float32)
    return pred, bg, X, {"family": "anytime", "M": M, "rank": r,
                         "n_bg": 16, "n_x": 8, "seed": seed,
                         "nsamples": ANYTIME_NSAMPLES}


# --------------------------------------------------------------------- #
# sweep


def _phi_matrix(values) -> np.ndarray:
    vals = values if isinstance(values, list) else [values]
    return np.stack([np.asarray(v) for v in vals], 1)  # (B, K, M)


def _timed_explain(explainer, X, reps: int = 3, **kw) -> float:
    """Median wall seconds of ``explain`` (after the caller warmed it)."""

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        explainer.explain(X, silent=True, **kw)
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def sweep(builder, budgets, seed: int = 0, reps: int = 3) -> Dict:
    """One model family's sweep: exact ground truth once, sampled phi +
    wall per budget; returns errors, per-instance walls and the config
    that fingerprints the measurement."""

    from distributedkernelshap_tpu import KernelShap

    pred, bg, X, config = builder(seed)
    # budgets above the full coalition space silently enumerate (the
    # parity regime tests pin); families whose space is small bring
    # their own sweep so every point genuinely samples
    budgets = config.pop("budgets_override", budgets)
    config["budgets"] = list(map(int, budgets))

    explainer = KernelShap(pred, seed=seed)
    explainer.fit(bg)

    explainer.explain(X, silent=True, nsamples="exact")  # compile
    exact_wall = _timed_explain(explainer, X, reps=reps, nsamples="exact")
    phi_exact = _phi_matrix(explainer.explain(
        X, silent=True, nsamples="exact").shap_values)
    scale = float(np.abs(phi_exact).max())

    errors: Dict[int, float] = {}
    walls: Dict[int, float] = {}
    for b in budgets:
        explainer.explain(X, silent=True, nsamples=b, l1_reg=False)
        walls[b] = _timed_explain(explainer, X, reps=reps, nsamples=b,
                                  l1_reg=False)
        phi_b = _phi_matrix(explainer.explain(
            X, silent=True, nsamples=b, l1_reg=False).shap_values)
        errors[b] = float(np.abs(phi_b - phi_exact).max())

    B = X.shape[0]
    return {
        "config": config,
        "errors": errors,
        "phi_scale": scale,
        "exact_per_instance_s": exact_wall / B,
        "sampled_per_instance_s": {b: w / B for b, w in walls.items()},
        "kernel_path": explainer.kernel_path,
    }


def sweep_anytime(seed: int = 0, reps: int = 3) -> Dict:
    """The anytime arm's sweep: instead of independent budgets, one
    progressive-refinement run per batch steps every round of the
    schedule, recording at each round both the TRUE max-abs phi error
    against exact-enumeration ground truth and the engine's calibrated
    REPORTED error.  Returns the classic sweep's shape — ``errors``
    keyed by cumulative nsamples, so the recorded ``err_n*`` metrics
    gate against trailing medians exactly like any family — plus the
    per-round (reported, true) pairs and their coverage under the
    x``ANYTIME_ERR_BOUND`` honesty bound."""

    from distributedkernelshap_tpu import KernelShap

    pred, bg, X, config = build_anytime_model(seed)
    # reps shapes the measured pair set (not just timing noise), so it
    # must fingerprint: a reps change starts a fresh gate baseline
    config["reps"] = int(reps)

    explainer = KernelShap(pred, seed=seed)
    explainer.fit(bg)
    engine = explainer._explainer

    explainer.explain(X, silent=True, nsamples="exact")  # compile
    exact_wall = _timed_explain(explainer, X, reps=reps, nsamples="exact")
    phi_exact = _phi_matrix(explainer.explain(
        X, silent=True, nsamples="exact").shap_values)
    scale = float(np.abs(phi_exact).max())

    # batch 0 re-walks the builder's rows; later reps draw fresh rows
    # from the same distribution so the honesty bound is judged across
    # several realisations of the draw noise, not one lucky batch
    rng = np.random.default_rng(seed + 7919)
    batches = [X] + [rng.normal(size=X.shape).astype(np.float32)
                     for _ in range(max(0, reps - 1))]

    B = X.shape[0]
    rounds: Dict[int, Dict[str, float]] = {}
    pairs: List[Dict[str, float]] = []
    walls: Dict[int, float] = {}
    for rep, Xb in enumerate(batches):
        phi_ref = phi_exact if rep == 0 else _phi_matrix(
            explainer.explain(Xb, silent=True,
                              nsamples="exact").shap_values)
        run = engine.anytime_begin(Xb, nsamples=ANYTIME_NSAMPLES)
        if run is None:
            raise RuntimeError(
                "anytime refinement did not engage "
                f"(M={config['M']}, nsamples={ANYTIME_NSAMPLES})")
        while not run.done:
            res = run.step()
            true_err = float(np.abs(res.phi - phi_ref).max())
            n = int(res.cumulative_nsamples)
            pairs.append({"round": res.round_index, "nsamples": n,
                          "reported": res.max_err, "true": true_err})
            agg = rounds.setdefault(n, {"true": 0.0, "reported": 0.0})
            agg["true"] = max(agg["true"], true_err)
            agg["reported"] = max(agg["reported"], res.max_err)
            # last rep's walls land in the record: rep 0 pays each
            # round's trace, later reps replay the cached entries
            walls[n] = run.last_round_s / B
    covered = sum(1 for p in pairs
                  if p["true"] <= ANYTIME_ERR_BOUND * p["reported"])
    return {
        "config": config,
        "errors": {n: v["true"] for n, v in sorted(rounds.items())},
        "reported": {n: v["reported"]
                     for n, v in sorted(rounds.items())},
        "coverage": covered / len(pairs),
        "n_pairs": len(pairs),
        "phi_scale": scale,
        "exact_per_instance_s": exact_wall / B,
        "sampled_per_instance_s": walls,
        "kernel_path": explainer.kernel_path,
    }


# --------------------------------------------------------------------- #
# gate


def gate_accuracy(history_path: str = ACCURACY_HISTORY,
                  max_err_regression: float = MAX_ERR_REGRESSION,
                  abs_floor: float = ERR_ABS_FLOOR,
                  baseline_n: int = BASELINE_N,
                  recent_n: int = 10) -> Dict:
    """Accuracy analog of ``regression_gate.gate``: for each benchmark
    in the accuracy history, the newest run of every config fingerprint
    in its trailing window is compared metric-by-metric (``err_n*``)
    against the median of its last ``baseline_n`` same-config prior
    runs.  Higher error than baseline by more than
    ``max_err_regression`` (and above ``abs_floor``) fails; improving
    never fails; first runs pass with a note."""

    entries = load_history(history_path)
    by_bench: Dict[str, List[Dict]] = {}
    for e in entries:
        by_bench.setdefault(e["bench"], []).append(e)
    results = []
    for _, runs in sorted(by_bench.items()):
        newest_per_fp: Dict[str, Dict] = {}
        for e in runs[-recent_n:]:
            newest_per_fp[e.get("config_fp")] = e
        for newest in sorted(newest_per_fp.values(), key=runs.index):
            prior = runs[:runs.index(newest)]
            baseline = [
                e for e in prior
                if e.get("config_fp") == newest.get("config_fp")
                and e.get("extra", {}).get("checks_ok") is not False
            ][-baseline_n:]
            res = {"bench": newest["bench"],
                   "config_fp": newest.get("config_fp"),
                   "baseline_runs": len(baseline),
                   "comparisons": {}, "ok": True}
            if not baseline:
                res["note"] = ("no prior run with this config "
                               "fingerprint — recorded as the new "
                               "baseline")
                results.append(res)
                continue
            for metric, value in sorted(newest["metrics"].items()):
                if not metric.startswith("err_"):
                    continue
                base_values = [e["metrics"][metric] for e in baseline
                               if metric in e["metrics"]]
                if not base_values:
                    continue
                base = _median(base_values)
                regressed = (value > abs_floor
                             and value > base * (1.0 + max_err_regression)
                             and value - base > abs_floor)
                res["comparisons"][metric] = {
                    "value": value, "baseline_median": base,
                    "regressed": regressed,
                }
                if regressed:
                    res["ok"] = False
            results.append(res)
    report = {"history": history_path, "entries": len(entries),
              "benches": results, "ok": all(r["ok"] for r in results)}
    if not entries:
        report["note"] = "empty history: nothing to gate"
    return report


def _record_sweep(history_path: str, bench: str, result: Dict,
                  checks_ok: Optional[bool] = None) -> Dict:
    metrics = {f"err_n{b}": e for b, e in result["errors"].items()}
    metrics["exact_per_instance_s"] = result["exact_per_instance_s"]
    extra = {"phi_scale": result["phi_scale"],
             "sampled_per_instance_s": {
                 str(b): w
                 for b, w in result["sampled_per_instance_s"].items()},
             "kernel_path": result["kernel_path"]}
    if "coverage" in result:
        # the anytime arm's honesty record: the reported error curve and
        # its coverage travel with the gated true-error metrics so a
        # calibration drift is diagnosable from the history alone
        extra["coverage"] = result["coverage"]
        extra["n_pairs"] = result["n_pairs"]
        extra["reported_err"] = {str(n): e
                                 for n, e in result["reported"].items()}
    if checks_ok is not None:
        extra["checks_ok"] = checks_ok
    return record_run(history_path, bench, result["config"], metrics,
                      extra=extra)


def _monotonic_ish(errors: Dict[int, float]) -> bool:
    """Error must fall from the smallest to the largest budget overall,
    with at most MONO_SLACK backsliding on any adjacent step (sampling
    error is stochastic; strict monotonicity would flake)."""

    budgets = sorted(errors)
    if len(budgets) < 2:
        return True
    if not errors[budgets[-1]] < errors[budgets[0]]:
        return False
    return all(errors[budgets[i + 1]] <= errors[budgets[i]] * MONO_SLACK
               for i in range(len(budgets) - 1))


def _degraded_gate_drill(history_path: str) -> bool:
    """Append a synthetic degraded-estimator entry (every error 3x the
    newest real run's) to a THROWAWAY copy of the history and assert
    the gate fails it — proof the gate would catch a real regression,
    without poisoning the real baseline."""

    entries = load_history(history_path)
    if not entries:
        return False
    newest = entries[-1]
    degraded_metrics = {
        k: (v * 3.0 + 10 * ERR_ABS_FLOOR if k.startswith("err_") else v)
        for k, v in newest["metrics"].items()}
    tmpdir = tempfile.mkdtemp(prefix="dks_accuracy_drill_")
    try:
        tmp = os.path.join(tmpdir, "accuracy_history.jsonl")
        shutil.copy(history_path, tmp)
        record_run(tmp, newest["bench"], newest.get("config", {}),
                   degraded_metrics, extra={"synthetic_drill": True})
        report = gate_accuracy(tmp)
        return report["ok"] is False
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


# --------------------------------------------------------------------- #


#: model-family builders: exact ground truth per family is exact-TN DP
#: contraction, exact TreeSHAP, DeepSHAP backprop on a provably-exact
#: (feature-wise piecewise-linear) net, and full coalition enumeration
#: respectively (the anytime family swaps the budget sweep for
#: per-round refinement — ``sweep_anytime``)
FAMILIES = {"tn": build_tn_model, "tree": build_tree_model,
            "deepshap": build_deepshap_model,
            "anytime": build_anytime_model}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--budgets", default=",".join(
        map(str, DEFAULT_BUDGETS)),
        help="comma-separated nsamples sweep")
    parser.add_argument("--families", "--family",
                        default="tn,tree,deepshap,anytime",
                        help="comma-separated model families to sweep "
                             f"(of {sorted(FAMILIES)})")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--reps", type=int, default=3,
                        help="timing repetitions per arm")
    parser.add_argument("--history", default=ACCURACY_HISTORY,
                        help="accuracy-history JSONL path")
    parser.add_argument("--no-record", action="store_true",
                        help="measure + gate without appending history")
    parser.add_argument("--gate-only", action="store_true",
                        help="gate the existing history, no new sweep")
    parser.add_argument("--check", action="store_true",
                        help="exit 1 unless every criterion holds")
    args = parser.parse_args(argv)

    if args.gate_only:
        report = gate_accuracy(args.history)
        print(json.dumps(report))
        return 0 if (report["ok"] or not args.check) else 1

    budgets = [int(b) for b in args.budgets.split(",") if b.strip()]
    families = [f.strip() for f in args.families.split(",") if f.strip()]
    unknown = sorted(set(families) - set(FAMILIES))
    if unknown:
        parser.error(f"unknown families {unknown}; pick from "
                     f"{sorted(FAMILIES)}")
    results = {f: (sweep_anytime(seed=args.seed, reps=args.reps)
                   if f == "anytime"
                   else sweep(FAMILIES[f], budgets, seed=args.seed,
                              reps=args.reps))
               for f in families}

    # wall-clock criterion: at matched phi error the analytic path must
    # beat the sampled path per instance.  The sampled arm's most
    # accurate (largest) budget still carries more error than the
    # analytic arm's (zero for exact-TN; f32 rounding for DeepSHAP on
    # the provably-exact net), so its wall is the FLOOR of what matching
    # that accuracy would cost — beating it means the analytic path
    # dominates both axes.
    checks = {}
    for f in families:
        if f == "anytime":
            # the honest-error-bar contract serving budgets against: the
            # calibrated reported error must bound the true error within
            # xANYTIME_ERR_BOUND at >= ANYTIME_COVERAGE of the observed
            # (batch, round) pairs.  Coverage is measured fresh every
            # run, so calibration drift fails HERE immediately, while a
            # slow estimator drift also trips the recorded err_n*
            # trailing-median gate
            r = results[f]
            checks["anytime_error_monotonic_ish"] = _monotonic_ish(
                r["errors"])
            checks["anytime_reported_err_bounds_true"] = (
                r["coverage"] >= ANYTIME_COVERAGE)
            continue
        if f == "deepshap":
            # the provably-exact DeepSHAP regimes (additive /
            # coalition-stable nets) are exactly the games the sampled
            # WLS recovers from any budget, so the error sits at the f32
            # floor at EVERY budget and monotonic decay is meaningless —
            # the meaningful invariant is that floor agreement itself: a
            # regression in either the estimator or the attribution
            # engine breaks it by orders of magnitude (and the recorded
            # err_n* entries gate against their trailing medians too)
            r = results[f]
            floor = 1e-3 * max(r["phi_scale"], 1e-6)
            checks["deepshap_sampled_agreement_at_floor"] = (
                max(r["errors"].values()) <= floor)
            continue
        checks[f"{f}_error_monotonic_ish"] = _monotonic_ish(
            results[f]["errors"])
    expected_kernel = {"tn": "tn_dp", "deepshap": "deepshap"}
    for f in ("tn", "deepshap"):
        if f not in results:
            continue
        r = results[f]
        matched = r["sampled_per_instance_s"][
            max(r["sampled_per_instance_s"])]
        checks[f"{f}_exact_beats_sampled_wall"] = (
            r["exact_per_instance_s"] < matched)
        checks[f"{f}_exact_path_engaged"] = (
            r["kernel_path"].get("exact_phi") == expected_kernel[f])

    # each family's history entries carry its OWN verdict: a flake in
    # one family must not evict the other families' healthy runs from
    # their gate baselines (checks_ok=False entries never baseline —
    # the cross-arm contamination rule the multitenant bench pins)
    family_ok = {f: all(v for k, v in checks.items()
                        if k.startswith(f"{f}_"))
                 for f in families}
    if not args.no_record:
        for f in families:
            _record_sweep(args.history, f"estimator_accuracy_{f}",
                          results[f], checks_ok=family_ok[f])

    gate_report = gate_accuracy(args.history)
    checks["accuracy_gate_ok"] = bool(gate_report["ok"])
    if not args.no_record and os.path.exists(args.history):
        checks["degraded_entry_fails_gate"] = _degraded_gate_drill(
            args.history)

    if not args.no_record:
        # perf-gate coverage of the wall criteria (PR 6 convention):
        # wall_s is the analytic path's per-instance cost the criterion
        # bounds, one same-config-fingerprinted entry per family
        for f in ("tn", "deepshap"):
            if f not in results:
                continue
            r = results[f]
            best_budget = max(r["sampled_per_instance_s"])
            record_run(
                DEFAULT_HISTORY, "estimator_accuracy",
                dict(r["config"], criterion="exact_vs_sampled_wall"),
                {"wall_s": r["exact_per_instance_s"],
                 "sampled_matched_per_instance_s":
                     r["sampled_per_instance_s"][best_budget]},
                extra={"checks_ok": family_ok[f],
                       "matched_budget": int(best_budget)})

    result = {
        "bench": "estimator_accuracy",
        "config_fp": config_fingerprint(
            results[families[0]]["config"]),
        "checks": checks,
        "checks_ok": all(checks.values()),
        "gate": gate_report,
    }
    for f in families:
        r = results[f]
        result[f] = {
            "errors": {str(b): e for b, e in r["errors"].items()},
            "phi_scale": r["phi_scale"],
            "exact_per_instance_s": round(r["exact_per_instance_s"], 6),
            "sampled_per_instance_s": {
                str(b): round(w, 6)
                for b, w in r["sampled_per_instance_s"].items()},
            "kernel_path": r["kernel_path"]}
        if "coverage" in r:
            result[f]["coverage"] = round(r["coverage"], 4)
            result[f]["reported"] = {str(n): e
                                     for n, e in r["reported"].items()}
    print(json.dumps(result))
    if args.check and not result["checks_ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
