"""Device-pool benchmark — translation of ``benchmarks/ray_pool.py``.

Same CLI flags (``-b/--batch``, ``-w/--workers``, ``-benchmark``,
``-n/--nruns``), the same ``{'t_elapsed': [...]}`` incremental pickle format
and the same result filename convention (``utils.get_filename``,- reference
``utils.py:67-86``) so the reference's Analysis notebook ingests the results
unchanged.  ``--workers`` maps to mesh devices instead of Ray actors:
``-1`` runs the single-device sequential path (reference ``ray_pool.py:95-99``),
otherwise a ``workers``-wide data-parallel mesh explains the batch
(``ray.shutdown()`` between configurations has no analog — meshes are
stateless).
"""

import argparse
import logging
import os
import pickle
import sys
from timeit import default_timer as timer
from typing import Any, Dict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedkernelshap_tpu import KernelShap  # noqa: E402
from benchmarks._common import add_platform_flag, apply_platform  # noqa: E402
from distributedkernelshap_tpu.utils import get_filename, load_data, load_model  # noqa: E402

logging.basicConfig(level=logging.INFO)


def fit_kernel_shap_explainer(clf, data: dict, distributed_opts: Dict[str, Any] = None):
    """Fitted KernelShap explainer for ``clf`` with grouping from ``data``
    (reference ray_pool.py:18-38 call shape)."""

    from distributedkernelshap_tpu.utils import data_provenance

    pred_fcn = clf.predict_proba
    group_names, groups = data['all']['group_names'], data['all']['groups']
    explainer = KernelShap(pred_fcn, link='logit', feature_names=group_names,
                           distributed_opts=distributed_opts, seed=0)
    explainer.fit(data['background']['X']['preprocessed'],
                  group_names=group_names, groups=groups,
                  data_provenance=data_provenance(data))
    return explainer


def run_explainer(explainer, X_explain: np.ndarray, distributed_opts: dict, nruns: int):
    """Timed explain runs with incremental result pickles
    (reference ray_pool.py:41-79)."""

    if not os.path.exists('./results'):
        os.mkdir('./results')
    batch_size = distributed_opts['batch_size']
    workers = distributed_opts.get('n_devices') or distributed_opts.get('n_cpus')
    result = {'t_elapsed': [],
              'data_provenance': explainer.meta.get('data_provenance',
                                                    'unspecified')}
    for run in range(nruns):
        logging.info("run: %d", run)
        t_start = timer()
        explainer.explain(X_explain, silent=True)
        t_elapsed = timer() - t_start
        logging.info("Time elapsed: %s", t_elapsed)
        result['t_elapsed'].append(t_elapsed)
        # recorded at trace time during the first run; a Pallas degrade
        # mid-sweep shows up here instead of being silently absorbed
        result['kernel_path'] = explainer.kernel_path
        with open(get_filename(workers if workers else -1, batch_size, serve=False), 'wb') as f:
            pickle.dump(result, f)


def main():
    nruns = args.nruns if args.benchmark else 1
    batch_sizes = [int(elem) for elem in args.batch]

    data = load_data()
    predictor = load_model()
    y_test = data['all']['y']['test']
    X_test_proc = data['all']['X']['processed']['test']
    from sklearn.metrics import accuracy_score
    logging.info("Test accuracy: %s", accuracy_score(y_test, predictor.predict(X_test_proc)))
    X_explain = X_test_proc.toarray()

    if args.workers == -1:  # single-device sequential path
        logging.info("Running sequential benchmark on a single device ...")
        distributed_opts = {'batch_size': None, 'n_devices': None}
        explainer = fit_kernel_shap_explainer(predictor, data, distributed_opts)
        # warmup compile at the timed shape, then timed runs (the
        # reference's 1-worker runs pay no compile cost; keep comparable)
        explainer.explain(X_explain, silent=True)
        run_explainer(explainer, X_explain, distributed_opts, nruns)
        return

    workers_range = (range(1, args.workers + 1) if args.benchmark == 1
                     else range(args.workers, args.workers + 1))
    for workers in workers_range:
        for batch_size in batch_sizes:
            logging.info("Running experiment on %d device(s), batch size %d",
                         workers, batch_size)
            distributed_opts = {'batch_size': int(batch_size), 'n_devices': workers}
            explainer = fit_kernel_shap_explainer(predictor, data, distributed_opts)
            # warmup at the timed shape so no 15-40s TPU compile lands inside
            # run 0: one slab (batch_size*workers rows) hits the same
            # compiled bucket every timed slab uses; when the whole dataset
            # fits one slab that's the full array anyway
            slab = int(batch_size) * workers
            explainer.explain(X_explain[:min(len(X_explain), slab)], silent=True)
            run_explainer(explainer, X_explain, distributed_opts, nruns)


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "-b", "--batch", nargs='+', required=True,
        help="Maximum per-device batch sizes to sweep.")
    parser.add_argument(
        "-w", "--workers", default=-1, type=int,
        help="Number of devices to shard explanations over; -1 runs the "
             "sequential single-device path.")
    parser.add_argument(
        "-benchmark", default=0, type=int,
        help="Set to 1 to sweep devices in range(1, workers+1).")
    parser.add_argument(
        "-n", "--nruns", default=5, type=int,
        help="Timed repetitions per configuration (benchmark mode).")
    add_platform_flag(parser)
    args = parser.parse_args()
    apply_platform(args)
    main()
