"""Analytic roofline model for the explain pipeline (VERDICT r1 #5).

Computes, per benchmark configuration, the work the jitted pipeline performs
— MXU einsum FLOPs, VPU elementwise ops, transcendentals, and minimum HBM
traffic — from the *actual* coalition-plan shapes, then reports the floor
wall-clock implied by each hardware bound next to the measured number from
RESULTS.md.  Pure host arithmetic: no device needed, reproducible anywhere.

Cost model (linear fast path, ``ops/explain._ey_linear`` / the fused Pallas
kernel ``ops/pallas_kernels.fused_linear_ey``):

* MXU: the group-space contractions ``XWg``/``bgWg`` (once per call), the
  per-tile ``p1``/``t2`` mask matmuls, and the WLS normal equations;
* VPU: assembling ``logits = p1 + bgW - t2`` over the ``(B, S, N, K)``
  synthetic tensor, the softmax/sigmoid, and the background-weighted
  average — ~8 arithmetic ops per element plus one transcendental per
  ``(B, S, N)`` (binary sigmoid path) or per element (general softmax);
* HBM: inputs/outputs plus the Pallas grid's block reloads; the logits
  tensor itself never leaves VMEM (that is the kernel's point — the XLA
  fallback keeps it fused too, spilling only the chunked ``ey``).

Peaks are explicit, overridable constants (public TPU v5e-1 specs where
published; the VPU/transcendental rates are stated order-of-magnitude
assumptions since Google does not publish them — conclusions below are
robust to 2x error in them).
"""

import argparse
import json
import math
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ---------------------------------------------------------------------------
# hardware peaks (TPU v5e, one chip)
PEAK = {
    "mxu_bf16_flops": 197e12,   # published v5e peak (bf16)
    "mxu_f32_flops": 49e12,     # f32 passes run ~1/4 of bf16 on the MXU
    "vpu_f32_ops": 4e12,        # assumption: order of magnitude for 8x128-lane VPU
    "transcendental_ops": 1e12,  # assumption: exp/sigmoid ~1/4 of VPU rate
    "hbm_bytes": 819e9,         # published v5e HBM bandwidth (819 GB/s)
    "tunnel_rpc_s": 0.07,       # measured: every device sync through the axon
                                # tunnel costs ~70 ms regardless of payload
}


def linear_path_cost(B, S, N, K, D, M, tb=256, ts=512):
    """Work/traffic of one explain call on the linear fast path."""

    f32 = 4
    mxu = (2 * B * M * D * K        # XWg
           + 2 * N * M * D * K      # bgWg
           + 2 * N * D * K          # bgW
           + 2 * B * S * M * K      # p1 (per tile, total over grid)
           + 2 * S * N * M * K      # t2
           + 2 * S * (M - 1) ** 2   # normal-equation Gram
           + 2 * B * S * (M - 1) * K  # normal-equation rhs
           + 2 * B * D * K)         # fx
    E = B * S * N * K
    binary = K == 2
    vpu = 8 * (B * S * N if binary else E)
    transcendental = B * S * N if binary else E
    grid_b, grid_s = math.ceil(B / tb), math.ceil(S / ts)
    hbm = f32 * (
        B * D + N * D + S * M + S + M * D          # inputs
        + B * M * K + N * M * K                    # staged XWg / bgWg
        + K * N * M * grid_b                       # bgWg reloaded per B-tile row
        + K * B * M * grid_s                       # XWg reloaded per S-tile col
        + 2 * B * S * K                            # ey written + read by solve
        + B * K * M                                # phi out
    )
    return {"mxu_flops": mxu, "vpu_ops": vpu,
            "transcendentals": transcendental, "hbm_bytes": hbm}


def tree_masked_cost(B, S, N, K, M, T, L, Nn):
    """Work of one explain call on the separable masked tree path
    (``models/trees.masked_ey``): per-side hit contractions (Q/R), the
    mask contractions (hx/hb), and the ``S*B*N*L`` bulk per tree
    (add + compare on the VPU, leaf einsum on the MXU), plus the output
    transform + weighted background average."""

    f32 = 4
    bulk = S * B * N * L * T
    mxu = (2 * B * T * L * Nn * M      # Q (per-instance hits)
           + 2 * N * T * L * Nn * M    # R (background hits)
           + 2 * S * B * T * L * M     # hx
           + 2 * S * N * T * L * M     # hb
           + 2 * bulk * K              # eq x leaf_value einsum
           + 2 * S * B * N * K)        # background-weighted average
    vpu = 3 * bulk                     # hb broadcast add + compare + cast
    transcendental = S * B * N * max(1, K - 1)   # _finish sigmoid/softmax
    hbm = f32 * (B * Nn + N * Nn + S * M         # inputs
                 + (N + B) * T * L * M           # persistent R / Q tensors
                 + S * B * K + B * K * M)        # ey + phi out
    return {"mxu_flops": mxu, "vpu_ops": vpu,
            "transcendentals": transcendental, "hbm_bytes": hbm}


def tree_exact_cost(B, N, K, M, T, L, Nn, interactions=False):
    """Work of one exact interventional TreeSHAP call
    (``ops/treeshap.exact_shap_from_reach``): the (b, n) pairwise counts
    (u/v/dead), on-device Beta weights via lgamma (5 lgamma + 2 exp per
    pair-leaf), and the phi contractions; ``interactions`` multiplies the
    pairwise contraction stage by ~M (one main-effect-shaped einsum set
    per group, ``exact_interactions_from_reach``)."""

    f32 = 4
    pairs = B * N * T * L
    contraction_sets = (3 + 4 * M) if interactions else (3 + 2)
    mxu = (2 * pairs * M * contraction_sets      # u/v/dead + phi passes
           + 2 * (B + N) * T * L * Nn * M)       # x_ok / z_ok reach einsums
    weight_sets = 2 if interactions else 1       # main + pairwise weights
    transcendental = 7 * pairs * weight_sets
    vpu = 6 * pairs * (M if interactions else 1)  # masks/products per pass
    hbm = f32 * (B * Nn + N * Nn
                 + N * T * L * M                 # persistent z_ok reach
                 + B * T * L * M                 # x_ok
                 + B * K * M * (M if interactions else 1))
    return {"mxu_flops": mxu, "vpu_ops": vpu,
            "transcendentals": transcendental, "hbm_bytes": hbm}


def cnn_masked_cost(B, S, N, K, D, M, flops_per_eval=1.16e6):
    """Work of one image explain call (``ops/image`` superpixel masking +
    the generic synthetic-row path): every (coalition, instance, background)
    triple synthesises one masked image and evaluates the CNN on it.

    ``flops_per_eval`` for the benchmark CNN (``models/cnn.py``:
    Conv16(3x3,s2) 2*14*14*16*9 = 56k, Conv32(3x3,s2) 2*7*7*32*9*16 = 903k,
    Dense64 2*1568*64 = 201k, Dense10 2*64*10 = 1.3k ≈ 1.16 MFLOP/image).
    Unlike the tabular paths the synthetic rows DO hit HBM: the generic
    path materialises each ``lax.map`` coalition chunk before the predictor
    consumes it (one write + one read)."""

    f32 = 4
    rows = B * S * N
    mxu = rows * flops_per_eval + 2 * B * S * (M - 1) * K + 2 * S * (M - 1) ** 2
    vpu = 3 * rows * D            # per-pixel select/lerp synthesis
    transcendental = rows * K     # softmax over the logits
    hbm = f32 * (2 * rows * D     # synthetic chunk written + read
                 + B * D + N * D + S * M            # inputs
                 + 2 * B * S * K                    # ey written + read
                 + B * K * M)                       # phi out
    return {"mxu_flops": mxu, "vpu_ops": vpu,
            "transcendentals": transcendental, "hbm_bytes": hbm}


def floors(cost):
    return {
        "mxu_s": cost["mxu_flops"] / PEAK["mxu_f32_flops"],
        "vpu_s": cost["vpu_ops"] / PEAK["vpu_f32_ops"],
        "transcendental_s": cost["transcendentals"] / PEAK["transcendental_ops"],
        "hbm_s": cost["hbm_bytes"] / PEAK["hbm_bytes"],
    }


# measured single-chip wall-clocks (RESULTS.md, axon tunnel; each includes at
# least one ~70 ms tunnel round trip that is NOT device work)
MEASURED = {
    "adult": 0.086,         # 2026-07-29 bench.py (0.09-0.15 on 07-31)
    "adult_stress": 0.073,  # 2026-07-30 (0.125 on 07-31)
    "covertype_65536": 2.13,  # 2026-07-30, 65,536-row sub-run
    "covertype_full": 13.08,  # 2026-07-31, full 581k rows, one chip
    "adult_trees": 0.2671,    # 2026-07-31 (separable masked tree path)
    "adult_trees_exact": 0.8835,  # 2026-07-31, PRE-lgamma (gather weights)
    "mnist": 5.02,            # 2026-07-30 session (12.25 on the slower
                              # 07-31 session — pre-instance_chunk, so the
                              # whole 10k-image batch ran as ONE dispatch)
}

CONFIGS = {
    # B, S, N, K, D, M  (S from coalition_plan: 2M + 2^11 capped by 2^M - 2)
    "adult": dict(B=2560, S=2072, N=100, K=2, D=48, M=12),
    "adult_stress": dict(B=512, S=2048, N=1000, K=2, D=48, M=12),
    "covertype_65536": dict(B=65536, S=2072, N=100, K=7, D=54, M=12),
    "covertype_full": dict(B=581012, S=2072, N=100, K=7, D=54, M=12),
}

# tree-path configs (Adult HistGBT max_iter=50: T=50 trees, L=31 leaves,
# Nn=61 node slots; introspected from the fitted lift)
TREE_CONFIGS = {
    "adult_trees": (tree_masked_cost,
                    dict(B=256, S=2072, N=100, K=2, M=12, T=50, L=31, Nn=61)),
    "adult_trees_exact": (tree_exact_cost,
                          dict(B=256, N=100, K=1, M=12, T=50, L=31, Nn=61)),
    "adult_trees_exact_inter": (tree_exact_cost,
                                dict(B=256, N=100, K=1, M=12, T=50, L=31,
                                     Nn=61, interactions=True)),
}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args()

    rows = []
    all_costs = [(name, linear_path_cost(**dims), dims)
                 for name, dims in CONFIGS.items()]
    all_costs += [(name, fn(**dims), dims)
                  for name, (fn, dims) in TREE_CONFIGS.items()]
    # image config: B=10240 (10k bucketed), S = 2*49 + 2048, mean background
    # (N=1), K=10 digits, D=28*28 pixels, M=49 superpixels
    mnist_dims = dict(B=10240, S=2146, N=1, K=10, D=784, M=49)
    all_costs.append(("mnist", cnn_masked_cost(**mnist_dims), mnist_dims))
    for name, cost, dims in all_costs:
        fl = floors(cost)
        floor = max(fl.values())
        bound = max(fl, key=fl.get)
        measured = MEASURED.get(name)
        rows.append({
            "config": name, **dims, **cost, **fl,
            "roofline_floor_s": floor, "bound": bound,
            "measured_s": measured,
            "roofline_frac": (floor / measured) if measured else None,
            "device_frac_excl_rpc": (
                floor / max(measured - PEAK["tunnel_rpc_s"], 1e-9)
                if measured else None),
        })

    if args.json:
        for r in rows:
            print(json.dumps(r))
        return

    hdr = (f"{'config':<18} {'MXU GF':>8} {'VPU Gop':>8} {'exp Gop':>8} "
           f"{'HBM MB':>8} {'floor ms':>9} {'bound':>16} {'meas ms':>8} "
           f"{'% roofline':>10} {'% excl RPC':>10}")
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        meas = f"{1e3 * r['measured_s']:8.1f}" if r["measured_s"] else "       -"
        frac = (f"{100 * r['roofline_frac']:9.1f}%" if r["roofline_frac"]
                else "         -")
        fracx = (f"{100 * r['device_frac_excl_rpc']:9.1f}%"
                 if r["device_frac_excl_rpc"] else "         -")
        print(f"{r['config']:<18} {r['mxu_flops'] / 1e9:8.1f} "
              f"{r['vpu_ops'] / 1e9:8.1f} {r['transcendentals'] / 1e9:8.1f} "
              f"{r['hbm_bytes'] / 1e6:8.1f} {1e3 * r['roofline_floor_s']:9.2f} "
              f"{r['bound']:>16} {meas} {frac} {fracx}")
    print()
    print("Peaks assumed:", json.dumps(PEAK))


if __name__ == "__main__":
    main()
