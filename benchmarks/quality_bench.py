"""Continuous-correctness bench: the in-band auditor must catch real
numeric corruption, stay silent on healthy traffic, cost nothing, and
the shadow oracle must respect its budget (standalone, CPU backend,
exits nonzero on ``--check`` fail).

Five measured arms, one JSON line (ISSUE 19):

1. **Detection (true-positive)** — a live fleet with the ``engine.phi``
   chaos site armed (``corrupt``, seeded): the injected numeric phi
   corruption must be flagged by the invariant auditor on EVERY fired
   hit — counted in ``dks_quality_violations_total``, landed on the
   flight recorder as ``quality_violation`` events and captured into
   the ``/qualityz`` repro ring — within the K-request run.
2. **Clean (false-positive)** — the same serving setup with no faults:
   zero violations over the whole run.  The screen's path-specific
   tolerances must clear healthy solver noise with margin.
3. **Audit overhead** — one live server, the auditor toggled PER
   REQUEST (strict on/off alternation, the drift-robust methodology the
   cost/profiling benches settled on): the audited pool's median
   latency must sit within 1% of the unaudited pool's.  Records as
   ``audit_overhead_factor`` for ``make perf-gate``.
4. **Shadow budget** — sampler at fraction 1.0 under a deliberately
   tiny ``DKS_QUALITY_BUDGET_S``-style budget: the oracle must run at
   least once, then trip the cap — verified against the cost meter's
   ``_quality`` tenant (device-seconds within budget + one run's cost,
   the pre-gated cap's contract: a run cannot be preempted mid-explain).
5. **Canary drift** — hot swaps on a live registry: an identical
   re-register must replay ~zero drift (verdict ``ok``), a deliberately
   perturbed version must report nonzero drift (verdict ``drift`` +
   ``swap_drift`` flight event) BEFORE traffic moves, with the verdict
   riding the ``model_swap`` event.

Self-records into ``results/perf_history.jsonl`` with ``checks_ok``.

    JAX_PLATFORMS=cpu python benchmarks/quality_bench.py --check
"""

import argparse
import json
import statistics
import sys
import time

import numpy as np

REPO_ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO_ROOT)

from benchmarks.cost_attribution_bench import (  # noqa: E402
    http_get,
    post_explain,
    serve_fleet,
)
from benchmarks.multitenant_bench import build_linear  # noqa: E402

D = 6  # the multitenant builders' feature width


def _flight_events(kind):
    from distributedkernelshap_tpu.observability.flightrec import flightrec

    return [e for e in flightrec().to_payload()["events"]
            if e.get("kind") == kind]


def _qualityz(server):
    return json.loads(http_get(server.host, server.port, "/qualityz"))


# --------------------------------------------------------------------- #
# arm 1: detection (true-positive) under injected engine.phi corruption
# --------------------------------------------------------------------- #


def run_detect_arm(requests=12, corruptions=3, seed=7):
    """K requests against a fleet whose ``engine.phi`` site corrupts
    ``corruptions`` answers (seeded, deterministic): every fired hit
    must be flagged — no more (that would be a false positive on the
    clean majority), no fewer (a miss is the whole failure mode this
    subsystem exists to kill)."""

    from distributedkernelshap_tpu.resilience.faults import (
        FaultInjector,
        parse_faults,
    )

    inj = FaultInjector(parse_faults(
        f"corrupt:site=engine.phi,after=2,times={corruptions},seed={seed}"))
    events_before = len(_flight_events("quality_violation"))
    server, _registry = serve_fleet([("tenant-det", build_linear(seed=1))],
                                    fault_injector=inj)
    rng = np.random.default_rng(0)
    try:
        statuses = []
        for _ in range(requests):
            s, _ = post_explain(server.host, server.port,
                                rng.normal(size=(1, D)).astype(np.float32),
                                model="tenant-det")
            statuses.append(s)
        server._quality.flush(timeout_s=10.0)  # let the deferred screen land
        page = _qualityz(server)
        fired = inj.hits("engine.phi")
    finally:
        server.stop()
    events = len(_flight_events("quality_violation")) - events_before
    audit = page["audit"]
    return {
        "requests": requests,
        "all_ok": all(s == 200 for s in statuses),
        "corruptions_armed": corruptions,
        "site_hits": fired,
        "violations": audit["violation_answers_total"],
        "audited": audit["audited_total"],
        "ring_entries": len(audit["ring"]),
        "ring_checks": sorted({c for e in audit["ring"]
                               for c in e["checks"]}),
        "flight_events": events,
    }


# --------------------------------------------------------------------- #
# arm 2: clean traffic (false-positive)
# --------------------------------------------------------------------- #


def run_clean_arm(requests=40):
    """No faults, mixed batch sizes: the auditor must stay silent over
    the whole run — the tolerances are calibrated to clear healthy
    solver noise, and a single false positive would train operators to
    ignore the alert."""

    server, _registry = serve_fleet([("tenant-cln", build_linear(seed=2))])
    rng = np.random.default_rng(1)
    try:
        statuses = []
        for i in range(requests):
            rows = 1 + (i % 3)
            s, _ = post_explain(server.host, server.port,
                                rng.normal(size=(rows, D)).astype(
                                    np.float32),
                                model="tenant-cln")
            statuses.append(s)
        server._quality.flush(timeout_s=10.0)
        page = _qualityz(server)
    finally:
        server.stop()
    audit = page["audit"]
    return {
        "requests": requests,
        "all_ok": all(s == 200 for s in statuses),
        "audited": audit["audited_total"],
        "violations": audit["violation_answers_total"],
    }


# --------------------------------------------------------------------- #
# arm 3: audit overhead (the gated sentinel)
# --------------------------------------------------------------------- #


def run_overhead_arm(requests=300, seed=13):
    """Auditor cost on ONE live server, toggling the screen PER REQUEST
    (strict alternation: any latency drift hits both pools identically;
    the only difference between the pooled medians is the decode+screen
    the audited pool runs at finalize).  The on/off median ratio records
    as ``audit_overhead_factor`` for the perf gate."""

    server, _registry = serve_fleet([("tenant-ovh", build_linear(seed=1))])
    auditor = server._quality.auditor
    lat = {"on": [], "off": []}
    rng = np.random.default_rng(seed)
    try:
        for _ in range(10):  # untimed warm pass
            post_explain(server.host, server.port,
                         rng.normal(size=(1, D)).astype(np.float32),
                         model="tenant-ovh")
        for i in range(2 * requests):
            arm = "on" if i % 2 == 0 else "off"
            auditor.enabled = (arm == "on")
            row = rng.normal(size=(1, D)).astype(np.float32)
            t0 = time.monotonic()
            status, _ = post_explain(server.host, server.port, row,
                                     model="tenant-ovh")
            assert status == 200
            lat[arm].append(time.monotonic() - t0)
        server._quality.flush(timeout_s=10.0)
        audited = auditor.snapshot()["audited_total"]
    finally:
        auditor.enabled = True
        server.stop()
    med_on = statistics.median(lat["on"])
    med_off = statistics.median(lat["off"])
    return {"median_on_s": round(med_on, 6),
            "median_off_s": round(med_off, 6),
            "overhead_frac": round(med_on / med_off - 1.0, 4),
            "audit_overhead_factor": round(med_on / med_off, 4),
            "audited_in_on_pool": audited,
            "requests_per_arm": requests}


# --------------------------------------------------------------------- #
# arm 4: shadow-oracle budget enforcement vs the cost meter
# --------------------------------------------------------------------- #


def _quality_tenant_seconds(server):
    """The ``_quality`` system tenant's device-seconds, read back from
    the cost meter's rendered series — the bench verifies the budget
    against the METER, not the sampler's self-report."""

    total = 0.0
    for line in server.metrics.render().splitlines():
        if line.startswith("dks_device_seconds_total{") \
                and 'model="_quality"' in line:
            total += float(line.rsplit(" ", 1)[1])
    return total


def run_budget_arm(budget_s=0.05, max_requests=400, timeout_s=120.0):
    """Sampler at fraction 1.0 under a tiny budget: the oracle must get
    real runs in, then trip the cap with the meter's ``_quality``
    device-seconds inside budget + one run's cost (pre-gated cap).
    Traffic is fed in rounds until the budget trips (oracle run cost is
    machine-dependent; a fixed request count would be flaky)."""

    server, _registry = serve_fleet([("tenant-bud", build_linear(seed=3))])
    monitor = server._quality
    sampler = monitor.sampler
    sampler.fraction = 1.0
    sampler.budget_s = float(budget_s)
    monitor.stop()
    monitor.start(tick_s=0.01)  # drain fast: the arm measures budget, not pacing
    rng = np.random.default_rng(5)
    sent = 0
    try:
        deadline = time.monotonic() + timeout_s
        shadow = _qualityz(server)["shadow"]
        while not shadow["exhausted"] and sent < max_requests \
                and time.monotonic() < deadline:
            for _ in range(20):
                s, _ = post_explain(server.host, server.port,
                                    rng.normal(size=(1, D)).astype(
                                        np.float32),
                                    model="tenant-bud")
                assert s == 200
                sent += 1
            # let the audit + oracle drains catch up before sending more
            monitor.flush(timeout_s=10.0)
            while time.monotonic() < deadline:
                shadow = _qualityz(server)["shadow"]
                if shadow["exhausted"] or shadow["queued"] == 0:
                    break
                time.sleep(0.05)
        meter_s = _quality_tenant_seconds(server)
        shadow = _qualityz(server)["shadow"]
    finally:
        server.stop()
    runs = sum(t["runs"] for t in shadow["tenants"].values())
    return {
        "requests_sent": sent,
        "budget_s": budget_s,
        "spent_s": round(shadow["spent_s"], 4),
        "max_run_s": round(shadow["max_run_s"], 4),
        "meter_quality_seconds": round(meter_s, 4),
        "exhausted": shadow["exhausted"],
        "oracle_runs": runs,
        "sampled": shadow["sampled"],
        "worst_err": max((t["last_err"] or 0.0
                          for t in shadow["tenants"].values()),
                         default=None),
    }


# --------------------------------------------------------------------- #
# arm 5: canary drift across gated hot swaps
# --------------------------------------------------------------------- #


def run_canary_arm():
    """Three swaps on one live registry: v2 adopts the baseline, an
    identical v3 must replay ~zero drift (verdict ``ok``), a perturbed
    v4 must report nonzero drift (verdict ``drift``) before traffic
    moves — quantified on the ``model_swap`` event, alarmed via
    ``swap_drift``."""

    from distributedkernelshap_tpu.observability.quality import (
        DRIFT_TOLERANCE,
    )

    drift_before = len(_flight_events("swap_drift"))
    server, registry = serve_fleet([("tenant-can", build_linear(seed=1))])
    try:
        registry.register("tenant-can", build_linear(seed=1))  # v2: adopt
        registry.register("tenant-can", build_linear(seed=1))  # v3: same
        swaps = [e for e in _flight_events("model_swap")
                 if e.get("model") == "tenant-can"]
        identical = next(e for e in reversed(swaps)
                         if e.get("to_version") == 3)
        registry.register("tenant-can", build_linear(seed=9))  # v4: drifted
        swaps = [e for e in _flight_events("model_swap")
                 if e.get("model") == "tenant-can"]
        perturbed = next(e for e in reversed(swaps)
                         if e.get("to_version") == 4)
        page = _qualityz(server)["canary"]
    finally:
        server.stop()
    drift_events = len(_flight_events("swap_drift")) - drift_before
    return {
        "threshold": DRIFT_TOLERANCE,
        "identical_drift": identical.get("canary_drift"),
        "identical_verdict": identical.get("canary_verdict"),
        "perturbed_drift": perturbed.get("canary_drift"),
        "perturbed_verdict": perturbed.get("canary_verdict"),
        "swap_drift_events": drift_events,
        "qualityz_verdict": page["tenants"].get("tenant-can", {}),
    }


# --------------------------------------------------------------------- #
# checks / record / main
# --------------------------------------------------------------------- #


def run_checks(result):
    det = result["detect"]
    cln = result["clean"]
    ovh = result["overhead"]
    bud = result["budget"]
    can = result["canary"]
    return {
        # every fired corruption flagged, nothing else flagged, offenders
        # on the ring AND the flight recorder — within the K-request run
        "corruption_detected_within_k": (
            det["all_ok"]
            and det["violations"] == det["corruptions_armed"]
            and det["ring_entries"] == det["corruptions_armed"]
            and det["flight_events"] == det["corruptions_armed"]
            and det["ring_checks"] == ["additivity"]),
        "zero_false_positives": (
            cln["all_ok"] and cln["violations"] == 0
            and cln["audited"] >= cln["requests"]),
        "audit_overhead_le_1pct": (
            ovh["audited_in_on_pool"] > 0
            and ovh["overhead_frac"] <= 0.01),
        # the cap is pre-gated (a run cannot be preempted mid-explain):
        # device-seconds must land within budget + one run's cost
        "shadow_within_budget": (
            bud["oracle_runs"] >= 1 and bud["exhausted"]
            and bud["meter_quality_seconds"]
            <= bud["budget_s"] + bud["max_run_s"]),
        "canary_drift_verdicts": (
            can["identical_verdict"] == "ok"
            and (can["identical_drift"] or 0.0) <= can["threshold"]
            and can["perturbed_verdict"] == "drift"
            and (can["perturbed_drift"] or 0.0) > can["threshold"]
            and can["swap_drift_events"] >= 1),
    }


def record(result, checks_ok, no_record=False):
    if no_record:
        return
    from benchmarks.regression_gate import DEFAULT_HISTORY, record_run

    record_run(
        DEFAULT_HISTORY, "quality",
        config={"detect_requests": result["config"]["detect_requests"],
                "overhead_requests": result["config"]["overhead_requests"],
                "budget_s": result["config"]["budget_s"]},
        metrics={"wall_s": result["wall_s"],
                 # the auditor-overhead sentinel perf-gate watches: the
                 # on/off median latency ratio (a screen that got
                 # expensive moves it off 1.0)
                 "audit_overhead_factor":
                     result["overhead"]["audit_overhead_factor"]},
        extra={"checks_ok": checks_ok,
               "overhead_frac": result["overhead"]["overhead_frac"],
               "oracle_runs": result["budget"]["oracle_runs"],
               "perturbed_drift": result["canary"]["perturbed_drift"]})


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true",
                        help="exit nonzero unless every criterion holds")
    parser.add_argument("--detect-requests", type=int, default=12)
    parser.add_argument("--overhead-requests", type=int, default=300,
                        help="requests per overhead arm (per-request "
                             "auditor on/off alternation on one server)")
    parser.add_argument("--budget-s", type=float, default=0.05,
                        help="shadow-oracle budget for the enforcement arm")
    parser.add_argument("--no-record", action="store_true",
                        help="skip the perf-history self-record")
    args = parser.parse_args()

    t0 = time.monotonic()
    result = {"config": {"detect_requests": args.detect_requests,
                         "overhead_requests": args.overhead_requests,
                         "budget_s": args.budget_s}}
    result["detect"] = run_detect_arm(requests=args.detect_requests)
    result["clean"] = run_clean_arm()
    result["overhead"] = run_overhead_arm(requests=args.overhead_requests)
    result["budget"] = run_budget_arm(budget_s=args.budget_s)
    result["canary"] = run_canary_arm()
    result["wall_s"] = round(time.monotonic() - t0, 2)
    checks = run_checks(result)
    result["checks"] = checks
    checks_ok = all(checks.values())
    result["checks_ok"] = checks_ok
    record(result, checks_ok, no_record=args.no_record)
    print(json.dumps(result))
    if args.check and not checks_ok:
        failed = [k for k, v in checks.items() if not v]
        print(f"quality_bench: FAILED {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
